// Metrics tests, mirroring reference bvar coverage (test/bvar_reducer_
// unittest.cpp, bvar_percentile_unittest.cpp, bvar_recorder_unittest.cpp).
#include <thread>
#include <vector>

#include "tbase/time.h"
#include "tvar/latency_recorder.h"
#include "tvar/percentile.h"
#include "tvar/reducer.h"
#include "tvar/variable.h"
#include "tvar/window.h"
#include "ttest/ttest.h"

using namespace tpurpc;

TEST(Reducer, AdderBasics) {
    Adder<int64_t> a;
    a << 1 << 2 << 3;
    EXPECT_EQ(a.get_value(), 6);
    a << -6;
    EXPECT_EQ(a.get_value(), 0);
}

TEST(Reducer, AdderMultithreaded) {
    Adder<int64_t> a;
    std::vector<std::thread> threads;
    for (int t = 0; t < 8; ++t) {
        threads.emplace_back([&a] {
            for (int i = 0; i < 10000; ++i) a << 1;
        });
    }
    for (auto& t : threads) t.join();
    EXPECT_EQ(a.get_value(), 80000);
}

TEST(Reducer, ThreadExitFoldsIntoResidual) {
    Adder<int64_t> a;
    std::thread([&a] { a << 42; }).join();
    EXPECT_EQ(a.get_value(), 42);  // agent folded at thread exit
}

TEST(Reducer, MaxerMiner) {
    Maxer<int64_t> mx;
    Miner<int64_t> mn;
    mx << 3 << 9 << 1;
    mn << 3 << 9 << 1;
    EXPECT_EQ(mx.get_value(), 9);
    EXPECT_EQ(mn.get_value(), 1);
}

TEST(Reducer, ResetReturnsAndClears) {
    Adder<int64_t> a;
    a << 5 << 6;
    EXPECT_EQ(a.reset(), 11);
    EXPECT_EQ(a.get_value(), 0);
}

TEST(Variable, ExposeListDescribe) {
    Adder<int64_t> a;
    a << 123;
    a.expose("test_exposed_counter");
    std::string desc;
    EXPECT_TRUE(Variable::describe_exposed("test_exposed_counter", &desc));
    EXPECT_EQ(desc, "123");
    auto names = Variable::list_exposed();
    bool found = false;
    for (auto& n : names) {
        if (n == "test_exposed_counter") found = true;
    }
    EXPECT_TRUE(found);
    a.hide();
    EXPECT_FALSE(Variable::describe_exposed("test_exposed_counter", &desc));
}

TEST(Percentile, HistogramQuantiles) {
    PercentileHistogram h;
    // 1000 samples uniform 1..1000us.
    for (int i = 1; i <= 1000; ++i) h.add(i);
    HistogramSnapshot s;
    s.add_from(h);
    EXPECT_EQ(s.total(), 1000u);
    const int64_t p50 = s.quantile(0.5);
    const int64_t p99 = s.quantile(0.99);
    // Log-histogram error bound: within ~15% of true values.
    EXPECT_GT(p50, 350);
    EXPECT_LT(p50, 700);
    EXPECT_GT(p99, 800);
    EXPECT_LE(p99, 1200);
    EXPECT_GE(p99, p50);
}

TEST(Percentile, BucketMonotonic) {
    int last = -1;
    const int64_t vals[] = {0, 1, 5, 8, 100, 1000, 50000, 1000000,
                            (int64_t)1 << 40};
    for (int64_t v : vals) {
        int b = PercentileHistogram::bucket_of(v);
        EXPECT_GE(b, last);
        last = b;
    }
}

TEST(LatencyRecorder, RecordsAndDescribes) {
    LatencyRecorder rec(10);
    for (int i = 0; i < 1000; ++i) rec << (i % 2 ? 100 : 200);
    EXPECT_EQ(rec.count(), 1000);
    // Pre-window (no sampler ticks yet): falls back to live totals.
    const int64_t avg = rec.latency();
    EXPECT_GT(avg, 120);
    EXPECT_LT(avg, 180);
    const int64_t p99 = rec.latency_percentile(0.99);
    EXPECT_GT(p99, 150);
    EXPECT_LT(p99, 260);
    EXPECT_GE(rec.max_latency(), 200);
    std::string d = rec.get_description();
    EXPECT_TRUE(d.find("\"qps\"") != std::string::npos);
}

TEST(Window, DeltaOverSamples) {
    // Drive the window by calling the sampler callback path indirectly:
    // register, write, and wait two ticks (2s+) — kept short by relying on
    // the warm-up fallback for the first read.
    Adder<int64_t> a;
    WindowBase<Adder<int64_t>, int64_t> w(&a, 5);
    a << 10;
    EXPECT_EQ(w.get_value(), 0);  // no samples yet
}

// ---------------- process variables ----------------
// Reference: src/bvar/default_variables.cpp — process_* gauges at /vars.

#include "tvar/default_variables.h"

TEST(ProcessVars, ExposeAndRead) {
    ExposeProcessVariables();
    std::string v;
    ASSERT_TRUE(Variable::describe_exposed("process_memory_resident_bytes",
                                           &v));
    EXPECT_GT(atoll(v.c_str()), 1024 * 1024);  // > 1MB resident
    ASSERT_TRUE(Variable::describe_exposed("process_thread_count", &v));
    EXPECT_GE(atoll(v.c_str()), 1);  // >=1: no hidden dep on worker startup
    ASSERT_TRUE(Variable::describe_exposed("process_fd_count", &v));
    EXPECT_GT(atoll(v.c_str()), 2);
    ASSERT_TRUE(Variable::describe_exposed("process_uptime_seconds", &v));
    EXPECT_GE(atoll(v.c_str()), 0);
    ASSERT_TRUE(Variable::describe_exposed("process_cpu_user_ms", &v));
    EXPECT_GE(atoll(v.c_str()), 0);
}

// ---------------- labelled metrics ----------------
// Reference: src/bvar/multi_dimension* — label-tuple-keyed series with
// prometheus exposition.

#include "tvar/multi_dimension.h"

TEST(MultiDimension, SeriesAndPrometheusText) {
    LabelledMetric<Adder<int64_t>> requests("test_requests_total",
                                            {"method", "status"});
    *requests.get_stats({"Echo", "ok"}) << 3;
    *requests.get_stats({"Echo", "ok"}) << 2;
    *requests.get_stats({"Echo", "error"}) << 1;
    *requests.get_stats({"Stats", "ok"}) << 7;
    EXPECT_EQ(requests.count_stats(), 3u);

    const std::string text =
        requests.prometheus_text("test_requests_total");
    EXPECT_TRUE(text.find("test_requests_total{method=\"Echo\","
                          "status=\"ok\"} 5") != std::string::npos)
        << text;
    EXPECT_TRUE(text.find("test_requests_total{method=\"Echo\","
                          "status=\"error\"} 1") != std::string::npos);
    EXPECT_TRUE(text.find("test_requests_total{method=\"Stats\","
                          "status=\"ok\"} 7") != std::string::npos);

    // Registered: the global /metrics dump includes the series.
    const std::string all = DumpLabelledMetrics();
    EXPECT_TRUE(all.find("test_requests_total{method=\"Stats\"") !=
                std::string::npos);

    // Series removal.
    requests.delete_stats({"Stats", "ok"});
    EXPECT_EQ(requests.count_stats(), 2u);

    // /vars description lists series.
    const std::string desc = requests.get_description();
    EXPECT_TRUE(desc.find("2 series") != std::string::npos);
}

// ---------------- sampler off-lock execution ----------------

TEST(Sampler, SlowSamplerDoesNotBlockRegistry) {
    auto* sc = SamplerCollector::singleton();
    std::atomic<bool> slow_started{false};
    std::atomic<bool> release_slow{false};
    const uint64_t slow_id = sc->add([&] {
        slow_started.store(true);
        while (!release_slow.load()) {
            std::this_thread::sleep_for(std::chrono::milliseconds(5));
        }
    });
    // Wait for the 1Hz collector to enter the slow sampler.
    for (int i = 0; i < 400 && !slow_started.load(); ++i) {
        std::this_thread::sleep_for(std::chrono::milliseconds(10));
    }
    ASSERT_TRUE(slow_started.load());
    // While it spins OFF-lock, add+remove of other samplers return
    // immediately (used to block on the global registry mutex).
    const int64_t t0 = monotonic_time_us();
    const uint64_t other = sc->add([] {});
    sc->remove(other);
    const int64_t elapsed_us = monotonic_time_us() - t0;
    EXPECT_LT(elapsed_us, 500 * 1000);
    // remove() of the RUNNING sampler must block until it finishes.
    std::atomic<bool> removed{false};
    std::thread remover([&] {
        sc->remove(slow_id);
        removed.store(true);
    });
    std::this_thread::sleep_for(std::chrono::milliseconds(100));
    EXPECT_FALSE(removed.load());
    release_slow.store(true);
    remover.join();
    EXPECT_TRUE(removed.load());
}

// ---------------- percentile accuracy + collector gate ----------------
// VERDICT depth: quantile error bounds for the log-histogram, and the
// Collector's global sampling rate gate.

#include "tvar/collector.h"

TEST(Percentile, QuantileAccuracyBounds) {
    // Uniform 1..100000us through a LatencyRecorder: the log-histogram's
    // bucket resolution bounds relative error; assert every headline
    // quantile lands within 15% of the true value.
    LatencyRecorder lat;
    for (int i = 1; i <= 100000; ++i) lat << i;
    struct Case {
        double q;
        int64_t truth;
    } cases[] = {{0.5, 50000}, {0.9, 90000}, {0.99, 99000},
                 {0.999, 99900}};
    for (const Case& c : cases) {
        const int64_t got = lat.latency_percentile(c.q);
        const double rel =
            (double)(got > c.truth ? got - c.truth : c.truth - got) /
            (double)c.truth;
        EXPECT_LT(rel, 0.15);
    }
    // Monotone: higher quantiles never report lower values.
    EXPECT_LE(lat.latency_percentile(0.5), lat.latency_percentile(0.9));
    EXPECT_LE(lat.latency_percentile(0.9), lat.latency_percentile(0.99));
    EXPECT_LE(lat.latency_percentile(0.99),
              lat.latency_percentile(0.999));
}

TEST(Collector, RateGateCapsSamples) {
    // Hammer the gate: within one second it must admit at most
    // max_samples_per_second (+ a small burst slack), however many
    // threads ask.
    auto* c = Collector::singleton();
    const int64_t cap = c->max_samples_per_second();
    ASSERT_GT(cap, 0);
    std::atomic<int64_t> admitted{0};
    std::vector<std::thread> threads;
    std::atomic<bool> stop{false};
    for (int t = 0; t < 3; ++t) {
        threads.emplace_back([&] {
            while (!stop.load()) {
                if (c->sample()) admitted.fetch_add(1);
            }
        });
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(500));
    stop.store(true);
    for (auto& t : threads) t.join();
    // Half a second of hammering: no more than ~one second's budget
    // (generous slack for window boundaries).
    EXPECT_LE(admitted.load(), cap + cap / 2);
    EXPECT_GT(admitted.load(), 0);
}
