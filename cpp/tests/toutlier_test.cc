// Outlier-detection tier (trpc/outlier.h): tracker state machine
// (eject -> probe -> ramp -> healthy), median-relative latency detector
// (uniform slowness ejects nobody), ejection-budget vetoes, revive
// routing, the hedge-delay starvation refresh (trpc/hedge_model.h) and
// the grey-failure chaos kinds (slow_node / error_rate at the kHandler
// seam). Pb-free: everything here drives the detectors directly, no
// channels or sockets — it also links into the toolchain-less
// standalone runner (see .claude/skills/verify/SKILL.md, Round 23).
#include <unistd.h>

#include <string>

#include "tbase/endpoint.h"
#include "tbase/errno.h"
#include "tbase/flags.h"
#include "tbase/time.h"
#include "tnet/fault_injection.h"
#include "trpc/hedge_model.h"
#include "trpc/outlier.h"
#include "ttest/ttest.h"

using namespace tpurpc;

DECLARE_bool(outlier_detection_enabled);
DECLARE_int32(outlier_consecutive_errors);
DECLARE_int32(outlier_check_interval_ms);
DECLARE_int32(outlier_latency_ratio_pct);
DECLARE_int32(outlier_latency_mad_k);
DECLARE_int32(outlier_min_delta_us);
DECLARE_int32(outlier_min_samples);
DECLARE_int32(outlier_max_ejection_pct);
DECLARE_int32(outlier_ejection_ms);
DECLARE_int32(outlier_max_ejection_window_ms);
DECLARE_int32(outlier_probe_interval_ms);
DECLARE_int32(outlier_probe_passes);
DECLARE_int32(outlier_rampup_ms);
DECLARE_bool(chaos_enabled);
DECLARE_int64(chaos_seed);
DECLARE_string(chaos_plan);
DECLARE_string(chaos_peers);

namespace {

// Suites share the runner binary: every test leaves the outlier flags
// at their compiled defaults and the process chaos-free.
struct FlagsReset {
    ~FlagsReset() {
        FLAGS_outlier_detection_enabled.set(true);
        FLAGS_outlier_consecutive_errors.set(5);
        FLAGS_outlier_check_interval_ms.set(250);
        FLAGS_outlier_latency_ratio_pct.set(300);
        FLAGS_outlier_latency_mad_k.set(4);
        FLAGS_outlier_min_delta_us.set(5000);
        FLAGS_outlier_min_samples.set(8);
        FLAGS_outlier_max_ejection_pct.set(40);
        FLAGS_outlier_ejection_ms.set(2000);
        FLAGS_outlier_max_ejection_window_ms.set(60000);
        FLAGS_outlier_probe_interval_ms.set(200);
        FLAGS_outlier_probe_passes.set(3);
        FLAGS_outlier_rampup_ms.set(3000);
        FLAGS_chaos_plan.set("");
        FLAGS_chaos_peers.set("");
        FLAGS_chaos_seed.set(1);
        FLAGS_chaos_enabled.set(false);
    }
};

ServerNode MakeNode(SocketId id, int port) {
    ServerNode n;
    n.id = id;
    char buf[32];
    snprintf(buf, sizeof(buf), "10.0.0.%d:%d", (int)id + 1, port);
    str2endpoint(buf, &n.ep);
    return n;
}

// Drive one backend's EWMA to ~target: the first sample seeds it
// exactly, repeats keep it there while accumulating `samples`.
void FeedLatency(outlier::OutlierTracker* t, SocketId id, int64_t us,
                 int n) {
    for (int i = 0; i < n; ++i) t->Feed(id, us, 0);
}

int HardError() { return ECONNRESET; }

}  // namespace

TEST(Outlier, ConsecutiveErrorsEject) {
    FlagsReset reset;
    FLAGS_outlier_consecutive_errors.set(3);
    outlier::OutlierTracker t("ut-consecutive");
    for (SocketId id = 0; id < 3; ++id) t.AddServer(MakeNode(id, 8000));
    ASSERT_EQ(t.size(), 3u);
    EXPECT_TRUE(t.all_healthy());

    const int64_t ejections0 = outlier::ejections();
    // Two hard errors arm the trigger; a success disarms it.
    t.Feed(1, 1000, HardError());
    t.Feed(1, 1000, HardError());
    t.Feed(1, 1000, 0);
    EXPECT_EQ(t.StateOf(1), outlier::State::kHealthy);
    // Three in a row eject.
    t.Feed(1, 1000, HardError());
    t.Feed(1, 1000, HardError());
    t.Feed(1, 1000, HardError());
    EXPECT_EQ(t.StateOf(1), outlier::State::kEjected);
    EXPECT_TRUE(t.IsEjected(1));
    EXPECT_FALSE(t.all_healthy());
    EXPECT_EQ(t.ejected_now(), 1u);
    EXPECT_EQ(outlier::ejections(), ejections0 + 1);

    // The pick gate skips it and hands back the span-annotation note.
    std::string note;
    EXPECT_EQ(t.OnPick(1, &note), outlier::OutlierTracker::Verdict::kSkip);
    EXPECT_NE(note.find("consecutive errors"), std::string::npos);
    EXPECT_EQ(t.OnPick(0, &note), outlier::OutlierTracker::Verdict::kAllow);

    outlier::BackendSnapshot snap;
    ASSERT_TRUE(t.Snapshot(1, &snap));
    EXPECT_EQ(snap.reason, outlier::Reason::kConsecutiveErrors);
    EXPECT_EQ(snap.eject_count, 1);
    EXPECT_GT(snap.ejected_for_ms, 0);
}

TEST(Outlier, OverloadPushbackNeverEjects) {
    FlagsReset reset;
    FLAGS_outlier_consecutive_errors.set(3);
    outlier::OutlierTracker t("ut-overload");
    for (SocketId id = 0; id < 3; ++id) t.AddServer(MakeNode(id, 8010));
    // TERR_OVERLOAD is admission pushback, not grey failure: feeding it
    // forever must not trip the consecutive-error detector.
    for (int i = 0; i < 50; ++i) t.Feed(1, 1000, TERR_OVERLOAD);
    EXPECT_EQ(t.StateOf(1), outlier::State::kHealthy);
    EXPECT_TRUE(t.all_healthy());
}

TEST(Outlier, UniformSlownessEjectsNobody) {
    FlagsReset reset;
    FLAGS_outlier_check_interval_ms.set(0);  // sweep on every feed
    outlier::OutlierTracker t("ut-uniform");
    for (SocketId id = 0; id < 5; ++id) t.AddServer(MakeNode(id, 8020));
    // The whole mesh is slow the same way: the median moves with it,
    // k*MAD finds no outlier, nobody is ejected.
    for (SocketId id = 0; id < 5; ++id) {
        FeedLatency(&t, id, 50000 + (int64_t)id * 200, 12);
    }
    EXPECT_TRUE(t.all_healthy());
    EXPECT_EQ(t.ejected_now(), 0u);

    // One backend drifts to many multiples of the live median: only IT
    // is ejected, with the ratio recorded for the span annotation.
    FeedLatency(&t, 2, 400000, 12);
    EXPECT_EQ(t.StateOf(2), outlier::State::kEjected);
    EXPECT_EQ(t.ejected_now(), 1u);
    outlier::BackendSnapshot snap;
    ASSERT_TRUE(t.Snapshot(2, &snap));
    EXPECT_EQ(snap.reason, outlier::Reason::kLatencyOutlier);
    EXPECT_GE(snap.ratio_x100, FLAGS_outlier_latency_ratio_pct.get());
    std::string note;
    EXPECT_EQ(t.OnPick(2, &note), outlier::OutlierTracker::Verdict::kSkip);
    EXPECT_NE(note.find("latency outlier"), std::string::npos);
    for (SocketId id = 0; id < 5; ++id) {
        if (id != 2) EXPECT_EQ(t.StateOf(id), outlier::State::kHealthy);
    }
}

TEST(Outlier, EjectionBudgetVetoes) {
    FlagsReset reset;
    FLAGS_outlier_consecutive_errors.set(3);
    FLAGS_outlier_max_ejection_pct.set(40);
    outlier::OutlierTracker t("ut-budget");
    for (SocketId id = 0; id < 3; ++id) t.AddServer(MakeNode(id, 8030));
    // 40% of 3 backends floors at one ejection. The first goes out...
    for (int i = 0; i < 3; ++i) t.Feed(0, 1000, HardError());
    ASSERT_EQ(t.StateOf(0), outlier::State::kEjected);
    // ...the second is vetoed no matter how sick it looks, and the veto
    // re-arms the trigger instead of re-proposing every feedback.
    const int64_t ejections0 = outlier::ejections();
    for (int i = 0; i < 9; ++i) t.Feed(1, 1000, HardError());
    EXPECT_EQ(t.StateOf(1), outlier::State::kHealthy);
    EXPECT_EQ(t.ejected_now(), 1u);
    EXPECT_EQ(outlier::ejections(), ejections0);
    outlier::BackendSnapshot snap;
    ASSERT_TRUE(t.Snapshot(1, &snap));
    EXPECT_LT(snap.consecutive_errors, 3);  // trigger was reset
}

TEST(Outlier, SubsetFloorVetoesFirstEjection) {
    FlagsReset reset;
    FLAGS_outlier_consecutive_errors.set(3);
    FLAGS_outlier_max_ejection_pct.set(100);
    outlier::OutlierTracker t("ut-floor");
    for (SocketId id = 0; id < 3; ++id) t.AddServer(MakeNode(id, 8040));
    // The naming layer's subset floor: never leave fewer than 3 backends
    // un-ejected -> with exactly 3 members even the FIRST eject is
    // vetoed.
    t.set_min_unejected(3);
    for (int i = 0; i < 6; ++i) t.Feed(2, 1000, HardError());
    EXPECT_EQ(t.StateOf(2), outlier::State::kHealthy);
    EXPECT_EQ(t.ejected_now(), 0u);
    EXPECT_TRUE(t.all_healthy());
}

TEST(Outlier, ProbeRampReinstatement) {
    FlagsReset reset;
    FLAGS_outlier_consecutive_errors.set(3);
    FLAGS_outlier_ejection_ms.set(30);
    FLAGS_outlier_probe_interval_ms.set(1);
    FLAGS_outlier_probe_passes.set(2);
    FLAGS_outlier_rampup_ms.set(40);
    outlier::OutlierTracker t("ut-probe");
    for (SocketId id = 0; id < 3; ++id) t.AddServer(MakeNode(id, 8050));
    for (int i = 0; i < 3; ++i) t.Feed(1, 1000, HardError());
    ASSERT_EQ(t.StateOf(1), outlier::State::kEjected);

    // Inside the window: no probe is due.
    EXPECT_EQ(t.ProbeCandidate(monotonic_time_us()), INVALID_VREF_ID);
    usleep(40 * 1000);  // window expires
    // Window expiry moves it to PROBING and nominates it for ONE
    // diverted real RPC...
    ASSERT_EQ(t.ProbeCandidate(monotonic_time_us()), (SocketId)1);
    EXPECT_EQ(t.StateOf(1), outlier::State::kProbing);
    // ...but normal picks still skip it.
    EXPECT_EQ(t.OnPick(1, nullptr),
              outlier::OutlierTracker::Verdict::kSkip);
    // The probe interval gates the next nomination.
    EXPECT_EQ(t.ProbeCandidate(monotonic_time_us()), INVALID_VREF_ID);

    const int64_t reinstatements0 = outlier::reinstatements();
    t.Feed(1, 500, 0);  // probe 1 passes
    EXPECT_EQ(t.StateOf(1), outlier::State::kProbing);
    usleep(2 * 1000);
    ASSERT_EQ(t.ProbeCandidate(monotonic_time_us()), (SocketId)1);
    t.Feed(1, 500, 0);  // probe 2 passes -> reinstated, ramping
    EXPECT_EQ(t.StateOf(1), outlier::State::kRamping);
    EXPECT_EQ(outlier::reinstatements(), reinstatements0 + 1);
    EXPECT_EQ(t.ejected_now(), 0u);  // ramping takes normal traffic

    // Slow start: early in the ramp some picks are skipped; once the
    // window elapses a pick graduates it to HEALTHY.
    int allowed = 0, skipped = 0;
    for (int i = 0; i < 200; ++i) {
        if (t.OnPick(1, nullptr) ==
            outlier::OutlierTracker::Verdict::kAllow) {
            ++allowed;
        } else {
            ++skipped;
        }
    }
    EXPECT_GT(allowed, 0);  // admission is floored at 10%
    usleep(45 * 1000);  // past the ramp window
    EXPECT_EQ(t.OnPick(1, nullptr),
              outlier::OutlierTracker::Verdict::kAllow);
    EXPECT_EQ(t.StateOf(1), outlier::State::kHealthy);
    EXPECT_TRUE(t.all_healthy());
}

TEST(Outlier, ReinstatementForgetsGreyHistory) {
    FlagsReset reset;
    FLAGS_outlier_check_interval_ms.set(0);  // sweep on every feed
    FLAGS_outlier_ejection_ms.set(30);
    FLAGS_outlier_probe_interval_ms.set(1);
    FLAGS_outlier_probe_passes.set(2);
    FLAGS_outlier_rampup_ms.set(40);
    outlier::OutlierTracker t("ut-fresh");
    for (SocketId id = 0; id < 4; ++id) t.AddServer(MakeNode(id, 8070));
    for (SocketId id = 0; id < 3; ++id) FeedLatency(&t, id, 1000, 12);
    // The grey phase poisons the EWMA far above the live median.
    FeedLatency(&t, 3, 80000, 12);
    ASSERT_EQ(t.StateOf(3), outlier::State::kEjected);
    const int64_t ejections0 = outlier::ejections();

    usleep(40 * 1000);  // window expires -> probing
    ASSERT_EQ(t.ProbeCandidate(monotonic_time_us()), (SocketId)3);
    t.Feed(3, 900, 0);  // probe 1 passes (the node healed)
    usleep(2 * 1000);
    ASSERT_EQ(t.ProbeCandidate(monotonic_time_us()), (SocketId)3);
    t.Feed(3, 900, 0);  // probe 2 passes -> reinstated, ramping
    ASSERT_EQ(t.StateOf(3), outlier::State::kRamping);

    // Fresh healthy samples re-earn min_samples. The grey-era EWMA is
    // forgotten at reinstatement, so the sweep judges ~900us — not an
    // alpha-1/8 decay tail of 80ms that would re-eject the healed node
    // onto a DOUBLED relapse window it sits out for most of a run.
    FeedLatency(&t, 3, 900, 12);
    EXPECT_NE(t.StateOf(3), outlier::State::kEjected);
    EXPECT_EQ(outlier::ejections(), ejections0);
    usleep(45 * 1000);  // past the ramp window
    EXPECT_EQ(t.OnPick(3, nullptr),
              outlier::OutlierTracker::Verdict::kAllow);
    EXPECT_EQ(t.StateOf(3), outlier::State::kHealthy);
}

TEST(Outlier, ProbeFailRelapseDoublesWindow) {
    FlagsReset reset;
    FLAGS_outlier_consecutive_errors.set(3);
    FLAGS_outlier_ejection_ms.set(30);
    FLAGS_outlier_probe_interval_ms.set(1);
    outlier::OutlierTracker t("ut-relapse");
    for (SocketId id = 0; id < 3; ++id) t.AddServer(MakeNode(id, 8060));
    for (int i = 0; i < 3; ++i) t.Feed(1, 1000, HardError());
    ASSERT_EQ(t.StateOf(1), outlier::State::kEjected);
    usleep(40 * 1000);
    ASSERT_EQ(t.ProbeCandidate(monotonic_time_us()), (SocketId)1);
    const int64_t probe_fails0 = outlier::probe_fails();
    t.Feed(1, 1000, HardError());  // probe fails -> relapse
    EXPECT_EQ(t.StateOf(1), outlier::State::kEjected);
    EXPECT_EQ(outlier::probe_fails(), probe_fails0 + 1);
    outlier::BackendSnapshot snap;
    ASSERT_TRUE(t.Snapshot(1, &snap));
    EXPECT_EQ(snap.eject_count, 2);
    // The relapse window doubled (base 30ms -> 60ms).
    EXPECT_GT(snap.ejected_for_ms, 35);
}

TEST(Outlier, ReviveRoutesThroughProbeRamp) {
    FlagsReset reset;
    FLAGS_outlier_consecutive_errors.set(3);
    FLAGS_outlier_ejection_ms.set(60000);  // window would hold for ages
    FLAGS_outlier_probe_interval_ms.set(1);
    outlier::OutlierTracker t("ut-revive");
    for (SocketId id = 0; id < 3; ++id) t.AddServer(MakeNode(id, 8070));
    for (int i = 0; i < 3; ++i) t.Feed(1, 1000, HardError());
    ASSERT_EQ(t.StateOf(1), outlier::State::kEjected);
    // The health-check revive (satellite fix): the transport came back,
    // so skip the remaining window — but re-enter through PROBING, not
    // at full weight.
    t.OnRevive(1);
    EXPECT_EQ(t.StateOf(1), outlier::State::kProbing);
    EXPECT_EQ(t.OnPick(1, nullptr),
              outlier::OutlierTracker::Verdict::kSkip);
    EXPECT_EQ(t.ProbeCandidate(monotonic_time_us()), (SocketId)1);
}

TEST(Outlier, DisabledFlagIsNoop) {
    FlagsReset reset;
    FLAGS_outlier_detection_enabled.set(false);
    outlier::OutlierTracker t("ut-disabled");
    for (SocketId id = 0; id < 3; ++id) t.AddServer(MakeNode(id, 8080));
    for (int i = 0; i < 50; ++i) t.Feed(1, 1000, HardError());
    EXPECT_EQ(t.StateOf(1), outlier::State::kHealthy);
    EXPECT_EQ(t.OnPick(1, nullptr),
              outlier::OutlierTracker::Verdict::kAllow);
    EXPECT_EQ(t.ProbeCandidate(monotonic_time_us()), INVALID_VREF_ID);
}

// ---- hedge-delay starvation refresh (tools/tpu_router.cc bugfix) ----

TEST(HedgeModel, CleanFeedOwnsTheEstimate) {
    HedgeDelayModel m;
    int64_t now = 1000000;
    m.FeedClean(8000, now);
    EXPECT_EQ(m.ewma_p99_us(), 8000);
    // EWMA alpha 1/8.
    m.FeedClean(16000, now + 1000);
    EXPECT_EQ(m.ewma_p99_us(), 9000);
    // A hedged completion right after a clean sample teaches NOTHING —
    // hedge-truncated latencies must not feed back into the delay.
    EXPECT_FALSE(m.FeedHedged(500000, now + 2000));
    EXPECT_EQ(m.ewma_p99_us(), 9000);
    EXPECT_EQ(m.starved_refreshes(), 0);
}

TEST(HedgeModel, StarvedRaiseOnlyRefresh) {
    HedgeDelayModel m;
    int64_t now = 1000000;
    m.FeedClean(8000, now);
    // THE regression: backend slows past the delay, every forward gets
    // hedged, no clean sample arrives for >= kStarvedRefreshUs. Before
    // the fix the estimate froze at 8ms and the router hedged 100% of
    // traffic forever. Now a hedged completion may RAISE the estimate.
    now += HedgeDelayModel::kStarvedRefreshUs + 1;
    // Raise-only: a hedged elapsed below the estimate still teaches
    // nothing even when starved.
    EXPECT_FALSE(m.FeedHedged(4000, now));
    EXPECT_EQ(m.ewma_p99_us(), 8000);
    EXPECT_TRUE(m.FeedHedged(80000, now));
    EXPECT_GT(m.ewma_p99_us(), 8000);
    EXPECT_EQ(m.starved_refreshes(), 1);
    // Clean completions resume ownership and reset the starvation clock:
    // the very next hedged completion is ignored again.
    m.FeedClean(10000, now + 1000);
    EXPECT_FALSE(m.FeedHedged(500000, now + 2000));
}

TEST(HedgeModel, DelayFlooredForColdCallers) {
    HedgeDelayModel m;
    // No samples: the floor alone drives (a cold caller hedges only
    // calls already slower than the floor).
    EXPECT_EQ(m.DelayMs(150, 30), 30);
    m.FeedClean(100000, 1);  // 100ms p99
    EXPECT_EQ(m.DelayMs(150, 30), 150);
    EXPECT_EQ(m.DelayMs(150, 200), 200);
}

// ---- grey-failure chaos kinds (kHandler seam) ----

TEST(GreyChaos, HandlerPlanValidates) {
    EXPECT_TRUE(FaultInjection::ValidatePlan("slow_node=1:80"));
    EXPECT_TRUE(
        FaultInjection::ValidatePlan("slow_node=1:80,error_rate=0.05"));
    EXPECT_TRUE(FaultInjection::ValidatePlan("error_rate=0.25"));
    EXPECT_FALSE(FaultInjection::ValidatePlan("error_rate=1.5"));
    EXPECT_FALSE(FaultInjection::ValidatePlan("slow_node=0.5:junk"));
    // error_rate carries no parameter.
    EXPECT_FALSE(FaultInjection::ValidatePlan("error_rate=0.5:123"));
}

TEST(GreyChaos, SlowNodeAndErrorRateAtHandlerSeam) {
    FlagsReset reset;
    EndPoint peer;
    str2endpoint("127.0.0.1:7001", &peer);
    FLAGS_chaos_plan.set("slow_node=1:80,error_rate=0.25");
    // The handler seam is NOT peer-filtered: the plan runs ON the grey
    // server, whose peers are its clients, not chaos_peers targets. A
    // filter naming someone else must not shield the seam.
    FLAGS_chaos_peers.set("10.9.9.9:9999");
    FLAGS_chaos_seed.set(20260807);
    FLAGS_chaos_enabled.set(true);
    ASSERT_TRUE(fault_injection_enabled());

    int fails = 0, delays = 0, none = 0;
    for (int i = 0; i < 2000; ++i) {
        const FaultAction a =
            FaultInjection::Decide(FaultOp::kHandler, peer, 128);
        if (a.kind == FaultAction::kFail) {
            ++fails;
        } else if (a.kind == FaultAction::kDelay) {
            EXPECT_EQ(a.delay_us, 80 * 1000);
            ++delays;
        } else {
            ++none;
        }
    }
    // error_rate draws FIRST (a grey node errors instead of answering
    // slowly), so even with slow_node=1.0 the failures still land at
    // ~25%; everything else is delayed.
    EXPECT_GT(fails, 2000 / 4 / 2);
    EXPECT_LT(fails, 2000 / 2);
    EXPECT_EQ(none, 0);
    EXPECT_EQ(delays, 2000 - fails);

    // Deterministic replay: re-applying the seed restarts the sequence.
    FLAGS_chaos_seed.set(20260807);
    int fails2 = 0;
    for (int i = 0; i < 2000; ++i) {
        if (FaultInjection::Decide(FaultOp::kHandler, peer, 128).kind ==
            FaultAction::kFail) {
            ++fails2;
        }
    }
    EXPECT_EQ(fails, fails2);
}
