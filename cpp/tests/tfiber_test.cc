// Fiber runtime tests, mirroring the reference's bthread suite coverage
// (test/bthread_unittest.cpp, butex, mutex, cond, execution_queue,
// work_stealing_queue, ping-pong).
#include <unistd.h>

#include <atomic>
#include <cerrno>
#include <thread>
#include <vector>

#include "tbase/time.h"
#include "tfiber/butex.h"
#include "tfiber/execution_queue.h"
#include "tfiber/fiber.h"
#include "tfiber/fiber_sync.h"
#include "tfiber/work_stealing_queue.h"
#include "ttest/ttest.h"

using namespace tpurpc;

TEST(Fiber, StartJoin) {
    std::atomic<int> x{0};
    fiber_t tid;
    ASSERT_EQ(fiber_start_background(
                  &tid, nullptr,
                  [](void* arg) -> void* {
                      ((std::atomic<int>*)arg)->store(42);
                      return nullptr;
                  },
                  &x),
              0);
    ASSERT_EQ(fiber_join(tid, nullptr), 0);
    EXPECT_EQ(x.load(), 42);
    // Joining a finished fiber returns immediately.
    EXPECT_EQ(fiber_join(tid, nullptr), 0);
    EXPECT_FALSE(fiber_exists(tid));
}

TEST(Fiber, ManyFibers) {
    std::atomic<int> count{0};
    std::vector<fiber_t> tids(500);
    for (auto& tid : tids) {
        ASSERT_EQ(fiber_start_background(
                      &tid, nullptr,
                      [](void* arg) -> void* {
                          ((std::atomic<int>*)arg)->fetch_add(1);
                          fiber_yield();
                          return nullptr;
                      },
                      &count),
                  0);
    }
    for (auto tid : tids) fiber_join(tid, nullptr);
    EXPECT_EQ(count.load(), 500);
}

TEST(Fiber, SelfInsideWorker) {
    fiber_t tid;
    std::atomic<uint64_t> observed{0};
    fiber_start_background(
        &tid, nullptr,
        [](void* arg) -> void* {
            ((std::atomic<uint64_t>*)arg)->store(fiber_self());
            return nullptr;
        },
        &observed);
    fiber_join(tid, nullptr);
    EXPECT_EQ(observed.load(), tid);
    EXPECT_EQ(fiber_self(), INVALID_FIBER);  // not on a worker here
}

TEST(Fiber, Usleep) {
    fiber_t tid;
    std::atomic<int64_t> elapsed{0};
    fiber_start_background(
        &tid, nullptr,
        [](void* arg) -> void* {
            const int64_t t0 = monotonic_time_us();
            fiber_usleep(30000);
            ((std::atomic<int64_t>*)arg)->store(monotonic_time_us() - t0);
            return nullptr;
        },
        &elapsed);
    fiber_join(tid, nullptr);
    EXPECT_GE(elapsed.load(), 25000);
    EXPECT_LT(elapsed.load(), 500000);
}

TEST(Butex, WakeFromPthread) {
    void* b = butex_create();
    butex_word(b)->store(7);
    std::atomic<int> woke{0};
    fiber_t tid;
    struct Ctx {
        void* b;
        std::atomic<int>* woke;
    } ctx{b, &woke};
    fiber_start_background(
        &tid, nullptr,
        [](void* arg) -> void* {
            Ctx* c = (Ctx*)arg;
            while (butex_word(c->b)->load() == 7) {
                butex_wait(c->b, 7, nullptr);
            }
            c->woke->store(1);
            return nullptr;
        },
        &ctx);
    usleep(20000);  // give the fiber time to park
    EXPECT_EQ(woke.load(), 0);
    butex_word(b)->store(8);
    butex_wake(b);
    fiber_join(tid, nullptr);
    EXPECT_EQ(woke.load(), 1);
    butex_destroy(b);
}

TEST(Butex, TimedWaitTimesOut) {
    void* b = butex_create();
    butex_word(b)->store(3);
    fiber_t tid;
    std::atomic<int> rc{-2};
    struct Ctx {
        void* b;
        std::atomic<int>* rc;
    } ctx{b, &rc};
    fiber_start_background(
        &tid, nullptr,
        [](void* arg) -> void* {
            Ctx* c = (Ctx*)arg;
            const int64_t abst = monotonic_time_us() + 20000;
            int r = butex_wait(c->b, 3, &abst);
            c->rc->store(r == ETIMEDOUT ? 1 : 0);
            return nullptr;
        },
        &ctx);
    fiber_join(tid, nullptr);
    EXPECT_EQ(rc.load(), 1);
    butex_destroy(b);
}

TEST(Butex, ValueMismatchReturnsWouldblock) {
    void* b = butex_create();
    butex_word(b)->store(5);
    EXPECT_EQ(butex_wait(b, 99, nullptr), EWOULDBLOCK);
    butex_destroy(b);
}

TEST(Butex, PthreadWaiter) {
    // Wait from a NON-worker pthread; wake from a fiber.
    void* b = butex_create();
    butex_word(b)->store(1);
    std::thread waiter([&] {
        while (butex_word(b)->load() == 1) {
            butex_wait(b, 1, nullptr);
        }
    });
    usleep(10000);
    fiber_t tid;
    fiber_start_background(
        &tid, nullptr,
        [](void* arg) -> void* {
            void* b = arg;
            butex_word(b)->store(2);
            butex_wake_all(b);
            return nullptr;
        },
        b);
    fiber_join(tid, nullptr);
    waiter.join();
    butex_destroy(b);
}

TEST(FiberSync, MutexContention) {
    FiberMutex mu;
    int counter = 0;  // protected by mu
    struct Ctx {
        FiberMutex* mu;
        int* counter;
    } ctx{&mu, &counter};
    std::vector<fiber_t> tids(16);
    for (auto& tid : tids) {
        fiber_start_background(
            &tid, nullptr,
            [](void* arg) -> void* {
                Ctx* c = (Ctx*)arg;
                for (int i = 0; i < 100; ++i) {
                    c->mu->lock();
                    ++*c->counter;
                    if (i % 10 == 0) fiber_yield();  // hold across yield
                    c->mu->unlock();
                }
                return nullptr;
            },
            &ctx);
    }
    for (auto tid : tids) fiber_join(tid, nullptr);
    EXPECT_EQ(counter, 1600);
}

TEST(FiberSync, CondPingPong) {
    struct Ctx {
        FiberMutex mu;
        FiberCond cond;
        int turn = 0;  // 0: ping's turn, 1: pong's turn
        int rounds = 0;
    } ctx;
    auto body = [](void* arg, int me) {
        Ctx* c = (Ctx*)arg;
        for (int i = 0; i < 50; ++i) {
            c->mu.lock();
            while (c->turn != me) c->cond.wait(c->mu);
            c->turn = 1 - me;
            ++c->rounds;
            c->cond.notify_all();
            c->mu.unlock();
        }
    };
    fiber_t ping, pong;
    struct Thunk {
        void* ctx;
        int me;
        void (*body)(void*, int);
    };
    static auto trampoline = [](void* a) -> void* {
        Thunk* t = (Thunk*)a;
        t->body(t->ctx, t->me);
        return nullptr;
    };
    void (*body_fn)(void*, int) = body;
    Thunk t0{&ctx, 0, body_fn}, t1{&ctx, 1, body_fn};
    fiber_start_background(&ping, nullptr, trampoline, &t0);
    fiber_start_background(&pong, nullptr, trampoline, &t1);
    fiber_join(ping, nullptr);
    fiber_join(pong, nullptr);
    EXPECT_EQ(ctx.rounds, 100);
}

TEST(FiberSync, CountdownFromPthread) {
    CountdownEvent ev(3);
    for (int i = 0; i < 3; ++i) {
        fiber_t tid;
        fiber_start_background(
            &tid, nullptr,
            [](void* arg) -> void* {
                fiber_usleep(5000);
                ((CountdownEvent*)arg)->signal();
                return nullptr;
            },
            &ev);
    }
    EXPECT_EQ(ev.wait(), 0);  // waits on this plain pthread
}

TEST(FiberSync, CountdownTimeout) {
    CountdownEvent ev(1);
    const int64_t abst = monotonic_time_us() + 20000;
    EXPECT_EQ(ev.wait(&abst), ETIMEDOUT);
    ev.signal();
    EXPECT_EQ(ev.wait(), 0);
}

TEST(WSQ, OwnerPushPopThiefSteal) {
    WorkStealingQueue<int> q;
    ASSERT_EQ(q.init(64), 0);
    for (int i = 0; i < 10; ++i) EXPECT_TRUE(q.push(i));
    int v;
    // Owner pops LIFO (bottom).
    EXPECT_TRUE(q.pop(&v));
    EXPECT_EQ(v, 9);
    // Thief steals FIFO (top) from another thread.
    std::atomic<int> stolen{-1};
    std::thread thief([&] {
        int s;
        if (q.steal(&s)) stolen.store(s);
    });
    thief.join();
    EXPECT_EQ(stolen.load(), 0);
    size_t left = 0;
    while (q.pop(&v)) ++left;
    EXPECT_EQ(left, 8u);
}

TEST(WSQ, ConcurrentStealAndPop) {
    WorkStealingQueue<int> q;
    ASSERT_EQ(q.init(2048), 0);
    std::atomic<int64_t> sum{0};
    std::atomic<bool> done{false};
    int64_t expect = 0;
    std::thread thief1([&] {
        int v;
        while (!done.load(std::memory_order_acquire)) {
            if (q.steal(&v)) sum.fetch_add(v);
        }
        while (q.steal(&v)) sum.fetch_add(v);
    });
    for (int round = 0; round < 50; ++round) {
        for (int i = 1; i <= 20; ++i) {
            if (q.push(i)) expect += i;
        }
        int v;
        while (q.pop(&v)) sum.fetch_add(v);
    }
    done.store(true, std::memory_order_release);
    thief1.join();
    EXPECT_EQ(sum.load(), expect);
}

TEST(ExecutionQueue, SerializedFifo) {
    struct Sink {
        std::vector<int> seen;
        std::atomic<int> batches{0};
    } sink;
    ExecutionQueue<int> q;
    q.start(
        [](void* meta, ExecutionQueue<int>::TaskIterator& it) -> int {
            Sink* s = (Sink*)meta;
            for (; it; ++it) s->seen.push_back(*it);
            s->batches.fetch_add(1);
            return 0;
        },
        &sink);
    for (int i = 0; i < 200; ++i) {
        ASSERT_EQ(q.execute(i), 0);
    }
    q.stop();
    q.join();
    ASSERT_EQ(sink.seen.size(), 200u);
    for (int i = 0; i < 200; ++i) EXPECT_EQ(sink.seen[i], i);
    EXPECT_EQ(q.execute(1), -1);  // stopped
}

TEST(ExecutionQueue, MultiProducer) {
    struct Sink {
        std::atomic<int64_t> sum{0};
    } sink;
    ExecutionQueue<int> q;
    q.start(
        [](void* meta, ExecutionQueue<int>::TaskIterator& it) -> int {
            for (; it; ++it) ((Sink*)meta)->sum.fetch_add(*it);
            return 0;
        },
        &sink);
    std::vector<std::thread> producers;
    for (int t = 0; t < 4; ++t) {
        producers.emplace_back([&q] {
            for (int i = 1; i <= 500; ++i) q.execute(i);
        });
    }
    for (auto& t : producers) t.join();
    q.stop();
    q.join();
    EXPECT_EQ(sink.sum.load(), 4 * 500 * 501 / 2);
}

TEST(Fiber, PingPongThroughput) {
    // Cooperative switch benchmark (reference test/bthread_ping_pong.cpp
    // style) — also a smoke test that heavy switching doesn't corrupt state.
    struct Ctx {
        void* b;
        int rounds = 0;
    } ctx;
    ctx.b = butex_create();
    butex_word(ctx.b)->store(0);
    auto runner = [](void* arg) -> void* {
        Ctx* c = (Ctx*)arg;
        for (int i = 0; i < 2000; ++i) {
            std::atomic<int>* w = butex_word(c->b);
            int v = w->load();
            w->store(v + 1);
            ++c->rounds;
            butex_wake(c->b);
            fiber_yield();
        }
        return nullptr;
    };
    fiber_t a, b2;
    fiber_start_background(&a, nullptr, runner, &ctx);
    fiber_start_background(&b2, nullptr, runner, &ctx);
    fiber_join(a, nullptr);
    fiber_join(b2, nullptr);
    EXPECT_EQ(ctx.rounds, 4000);
    butex_destroy(ctx.b);
}

// ---------------- fiber-local storage ----------------
// Reference: src/bthread/key.cpp (bthread_key_create/setspecific;
// KeyTable borrow/return pooling) — values are per-fiber, destructors run
// at fiber exit, deleted keys read null, and keytables recycle across
// fibers without leaking values ("session data reuse").

#include "tfiber/fiber_key.h"
#include "tfiber/task_group.h"
#include "tfiber/task_meta.h"

namespace {
std::atomic<int> g_fls_dtor_runs{0};
void fls_dtor(void* p) {
    g_fls_dtor_runs.fetch_add(1);
    delete (std::string*)p;
}
}  // namespace

TEST(FiberKey, PerFiberValuesAndDtors) {
    fiber_key_t key;
    ASSERT_EQ(0, fiber_key_create(&key, fls_dtor));
    g_fls_dtor_runs.store(0);

    struct Ctx {
        fiber_key_t key;
        std::atomic<int> ok{0};
    } ctx{key, {}};
    std::vector<fiber_t> tids(8);
    for (size_t i = 0; i < tids.size(); ++i) {
        fiber_start_background(
            &tids[i], nullptr,
            [](void* arg) -> void* {
                Ctx* c = (Ctx*)arg;
                // Fresh fiber: no inherited value.
                if (fiber_getspecific(c->key) != nullptr) return nullptr;
                auto* v = new std::string("fiber-" +
                                          std::to_string(fiber_self()));
                fiber_setspecific(c->key, v);
                fiber_usleep(1000);  // park: maybe migrate workers
                auto* got = (std::string*)fiber_getspecific(c->key);
                if (got == v) c->ok.fetch_add(1);
                return nullptr;
            },
            &ctx);
    }
    for (auto tid : tids) fiber_join(tid, nullptr);
    EXPECT_EQ(ctx.ok.load(), 8);
    // Every fiber's destructor ran at exit.
    EXPECT_EQ(g_fls_dtor_runs.load(), 8);
    fiber_key_delete(key);
}

TEST(FiberKey, DeletedKeyReadsNull) {
    fiber_key_t key;
    ASSERT_EQ(0, fiber_key_create(&key, nullptr));
    struct Ctx {
        fiber_key_t key;
        void* before = (void*)1;
        void* stale = (void*)1;
        int stale_set_rc = 0;
        void* after = (void*)1;
    } ctx{key};
    fiber_t tid;
    fiber_start_background(
        &tid, nullptr,
        [](void* arg) -> void* {
            Ctx* c = (Ctx*)arg;
            fiber_setspecific(c->key, (void*)0x1234);
            c->before = fiber_getspecific(c->key);
            fiber_key_delete(c->key);
            // The header's contract: a deleted key handle reads null and
            // rejects writes (validated against the registry's current
            // slot generation).
            c->stale = fiber_getspecific(c->key);
            c->stale_set_rc = fiber_setspecific(c->key, (void*)0x5678);
            // And a RECREATED key on the same slot must never see the
            // previous generation's value.
            fiber_key_t key2;
            fiber_key_create(&key2, nullptr);
            c->after = fiber_getspecific(key2);
            fiber_key_delete(key2);
            return nullptr;
        },
        &ctx);
    fiber_join(tid, nullptr);
    EXPECT_EQ(ctx.before, (void*)0x1234);
    EXPECT_EQ(ctx.stale, nullptr);
    EXPECT_EQ(ctx.stale_set_rc, EINVAL);
    EXPECT_EQ(ctx.after, nullptr);
}

TEST(FiberKey, PthreadFallbackOutsideWorkers) {
    fiber_key_t key;
    ASSERT_EQ(0, fiber_key_create(&key, nullptr));
    EXPECT_EQ(nullptr, fiber_getspecific(key));
    ASSERT_EQ(0, fiber_setspecific(key, (void*)0xabcd));
    EXPECT_EQ((void*)0xabcd, fiber_getspecific(key));
    fiber_key_delete(key);
}

// ---------------- rwlock + once ----------------
// Reference: src/bthread/rwlock.cpp (writer-preferring) + bthread_once.

TEST(FiberRWLock, ReadersShareWriterExcludes) {
    FiberRWLock rw;
    std::atomic<int> readers_in{0};
    std::atomic<int> max_readers{0};
    std::atomic<int64_t> counter{0};
    std::atomic<bool> writer_saw_exclusive{true};

    struct Ctx {
        FiberRWLock* rw;
        std::atomic<int>* readers_in;
        std::atomic<int>* max_readers;
        std::atomic<int64_t>* counter;
        std::atomic<bool>* excl;
    } ctx{&rw, &readers_in, &max_readers, &counter, &writer_saw_exclusive};

    std::vector<fiber_t> tids;
    for (int i = 0; i < 6; ++i) {
        fiber_t tid;
        fiber_start_background(
            &tid, nullptr,
            [](void* arg) -> void* {
                Ctx* c = (Ctx*)arg;
                for (int k = 0; k < 40; ++k) {
                    c->rw->rdlock();
                    const int in = c->readers_in->fetch_add(1) + 1;
                    int mx = c->max_readers->load();
                    while (in > mx &&
                           !c->max_readers->compare_exchange_weak(mx, in)) {
                    }
                    if (in <= 0) c->excl->store(false);
                    fiber_usleep(500);  // hold: readers must overlap
                    c->readers_in->fetch_sub(1);
                    c->rw->rdunlock();
                }
                return nullptr;
            },
            &ctx);
        tids.push_back(tid);
    }
    for (int i = 0; i < 2; ++i) {
        fiber_t tid;
        fiber_start_background(
            &tid, nullptr,
            [](void* arg) -> void* {
                Ctx* c = (Ctx*)arg;
                for (int k = 0; k < 25; ++k) {
                    c->rw->wrlock();
                    // No reader may be inside while the writer holds.
                    if (c->readers_in->load() != 0) c->excl->store(false);
                    c->counter->fetch_add(1);
                    c->rw->wrunlock();
                }
                return nullptr;
            },
            &ctx);
        tids.push_back(tid);
    }
    for (auto tid : tids) fiber_join(tid, nullptr);
    EXPECT_TRUE(writer_saw_exclusive.load());
    EXPECT_EQ(counter.load(), 50);
    EXPECT_GT(max_readers.load(), 1);  // readers actually overlapped
}

namespace {
std::atomic<int> g_once_runs{0};
void once_fn() {
    usleep(20000);  // widen the race window
    g_once_runs.fetch_add(1);
}
}  // namespace

TEST(FiberOnce, RunsExactlyOnceAcrossFibers) {
    FiberOnce once;
    g_once_runs.store(0);
    struct Ctx {
        FiberOnce* once;
        std::atomic<int> after{0};
    } ctx{&once, {}};
    std::vector<fiber_t> tids(8);
    for (auto& tid : tids) {
        fiber_start_background(
            &tid, nullptr,
            [](void* arg) -> void* {
                Ctx* c = (Ctx*)arg;
                c->once->call(once_fn);
                // By the time call() returns, the fn has completed.
                if (g_once_runs.load() == 1) c->after.fetch_add(1);
                return nullptr;
            },
            &ctx);
    }
    for (auto tid : tids) fiber_join(tid, nullptr);
    EXPECT_EQ(g_once_runs.load(), 1);
    EXPECT_EQ(ctx.after.load(), 8);
}

// ---------------- worker tags ----------------
// Reference: bthread_tag_t (types.h:37-39) — nonzero tags get an
// ISOLATED worker pool; tagged work can neither starve nor be starved by
// the default pool, and cross-pool wakeups land on the right pool.

TEST(WorkerTags, TaggedFibersRunOnTheirOwnPool) {
    struct Ctx {
        std::atomic<int> ok{0};
        std::atomic<int> wrong_pool{0};
    } ctx;
    FiberAttr tagged = FIBER_ATTR_NORMAL;
    tagged.tag = 7;
    std::vector<fiber_t> tids(6);
    for (auto& tid : tids) {
        fiber_start_background(
            &tid, &tagged,
            [](void* arg) -> void* {
                Ctx* c = (Ctx*)arg;
                TaskGroup* g = TaskGroup::tls_group();
                if (g == nullptr ||
                    g->control() != TaskControl::of_tag(7)) {
                    c->wrong_pool.fetch_add(1);
                }
                fiber_usleep(2000);  // park + resume: still our pool
                g = TaskGroup::tls_group();
                if (g == nullptr ||
                    g->control() != TaskControl::of_tag(7)) {
                    c->wrong_pool.fetch_add(1);
                    return nullptr;
                }
                c->ok.fetch_add(1);
                return nullptr;
            },
            &ctx);
    }
    for (auto tid : tids) fiber_join(tid, nullptr);
    EXPECT_EQ(ctx.ok.load(), 6);
    EXPECT_EQ(ctx.wrong_pool.load(), 0);
}

TEST(WorkerTags, TaggedPoolNotStarvedByDefaultPool) {
    // Saturate the DEFAULT pool with spinning fibers; a tagged fiber must
    // still make progress promptly on its own workers.
    std::atomic<bool> stop{false};
    std::vector<fiber_t> hogs(16);
    for (auto& tid : hogs) {
        fiber_start_background(
            &tid, nullptr,
            [](void* arg) -> void* {
                auto* s = (std::atomic<bool>*)arg;
                while (!s->load(std::memory_order_relaxed)) {
                    // busy spin with occasional yield: keeps default
                    // workers saturated.
                    for (volatile int i = 0; i < 20000; ++i) {
                    }
                    fiber_yield();
                }
                return nullptr;
            },
            &stop);
    }
    FiberAttr tagged = FIBER_ATTR_NORMAL;
    tagged.tag = 9;
    std::atomic<int64_t> latency_us{-1};
    struct Ctx {
        std::atomic<int64_t>* lat;
        int64_t t0;
    } ctx{&latency_us, monotonic_time_us()};
    fiber_t tid;
    fiber_start_background(
        &tid, &tagged,
        [](void* arg) -> void* {
            Ctx* c = (Ctx*)arg;
            c->lat->store(monotonic_time_us() - c->t0);
            return nullptr;
        },
        &ctx);
    fiber_join(tid, nullptr);
    stop.store(true);
    for (auto t : hogs) fiber_join(t, nullptr);
    EXPECT_GE(latency_us.load(), 0);
    // Scheduled on its own pool: starts quickly despite the saturated
    // default pool (generous bound for the 1-core CI box).
    EXPECT_LT(latency_us.load(), 200 * 1000);
}

// ---------------- urgent scheduling + pool growth + remote queue ----------------
// Reference: src/bthread/task_group.cpp start_foreground (run the new
// bthread immediately, requeue the caller), TaskControl::add_workers,
// remote_task_queue.h.

#include "tbase/flags.h"
#include "tbase/mpmc_queue.h"

DECLARE_int32(fiber_tagged_worker_count);

TEST(FiberUrgent, ChildRunsBeforeCallerResumes) {
    // A single-worker tagged pool makes the ordering deterministic: the
    // lone worker must run the urgent child before it can resume the
    // requeued caller.
    FLAGS_fiber_tagged_worker_count.set(1);
    FiberAttr tagged = FIBER_ATTR_NORMAL;
    tagged.tag = 11;  // fresh tag: pool starts now, with 1 worker
    struct Ctx {
        std::atomic<int> seq{0};
        int child_at = -1;
        int caller_resumed_at = -1;
        FiberAttr attr;
    } ctx;
    ctx.attr = tagged;
    fiber_t outer;
    fiber_start_background(
        &outer, &tagged,
        [](void* arg) -> void* {
            Ctx* c = (Ctx*)arg;
            fiber_t child;
            struct Inner {
                Ctx* c;
            } inner{c};
            fiber_start_urgent(
                &child, &c->attr,
                [](void* a) -> void* {
                    Ctx* c = ((Inner*)a)->c;
                    c->child_at = c->seq.fetch_add(1);
                    return nullptr;
                },
                &inner);
            c->caller_resumed_at = c->seq.fetch_add(1);
            fiber_join(child, nullptr);
            return nullptr;
        },
        &ctx);
    fiber_join(outer, nullptr);
    FLAGS_fiber_tagged_worker_count.set(2);
    ASSERT_GE(ctx.child_at, 0);
    ASSERT_GE(ctx.caller_resumed_at, 0);
    EXPECT_LT(ctx.child_at, ctx.caller_resumed_at);
}

TEST(TaskControlGrowth, SetConcurrencyAddsWorkersAfterStart) {
    TaskControl* c = TaskControl::singleton();
    c->ensure_started();
    const int before = c->concurrency();
    c->set_concurrency(before + 2);
    EXPECT_EQ(c->concurrency(), before + 2);
    // The grown pool still schedules: run a burst of fibers to completion.
    std::atomic<int> done{0};
    std::vector<fiber_t> tids(64);
    for (auto& tid : tids) {
        fiber_start_background(
            &tid, nullptr,
            [](void* arg) -> void* {
                ((std::atomic<int>*)arg)->fetch_add(1);
                return nullptr;
            },
            &done);
    }
    for (auto tid : tids) fiber_join(tid, nullptr);
    EXPECT_EQ(done.load(), 64);
    // Shrink is a documented no-op.
    c->set_concurrency(1);
    EXPECT_EQ(c->concurrency(), before + 2);
}

TEST(TaskControlGrowth, RemoteSpawnBurstFromPthreads) {
    // Hammer the lock-free remote ring (and its overflow spill) from
    // plain pthreads: every spawn goes through ready_to_run_remote.
    std::atomic<int> done{0};
    std::vector<std::thread> producers;
    std::vector<std::vector<fiber_t>> tids(4, std::vector<fiber_t>(2000));
    for (int t = 0; t < 4; ++t) {
        producers.emplace_back([&, t] {
            for (auto& tid : tids[t]) {
                while (fiber_start_background(
                           &tid, nullptr,
                           [](void* arg) -> void* {
                               ((std::atomic<int>*)arg)->fetch_add(1);
                               return nullptr;
                           },
                           &done) != 0) {
                    std::this_thread::yield();
                }
            }
        });
    }
    for (auto& p : producers) p.join();
    for (auto& v : tids) {
        for (auto tid : v) fiber_join(tid, nullptr);
    }
    EXPECT_EQ(done.load(), 8000);
}

TEST(MpmcQueue, ConcurrentSumConserved) {
    MpmcBoundedQueue<int> q;
    ASSERT_EQ(0, q.init(256));
    EXPECT_NE(0, q.init(100));  // non-power-of-two rejected
    ASSERT_EQ(0, q.init(256));
    constexpr int kPerProducer = 20000;
    std::atomic<int64_t> popped_sum{0};
    std::atomic<int> popped_n{0};
    std::atomic<bool> done_producing{false};
    std::vector<std::thread> threads;
    for (int p = 0; p < 2; ++p) {
        threads.emplace_back([&, p] {
            for (int i = 0; i < kPerProducer; ++i) {
                const int v = p * kPerProducer + i + 1;
                while (!q.push(v)) std::this_thread::yield();
            }
        });
    }
    for (int cix = 0; cix < 2; ++cix) {
        threads.emplace_back([&] {
            int v;
            while (true) {
                if (q.pop(&v)) {
                    popped_sum.fetch_add(v);
                    popped_n.fetch_add(1);
                } else if (done_producing.load() &&
                           popped_n.load() == 2 * kPerProducer) {
                    return;
                } else {
                    std::this_thread::yield();
                }
            }
        });
    }
    threads[0].join();
    threads[1].join();
    done_producing.store(true);
    threads[2].join();
    threads[3].join();
    const int64_t n = 2 * kPerProducer;
    EXPECT_EQ(popped_n.load(), n);
    EXPECT_EQ(popped_sum.load(), n * (n + 1) / 2);
}

// ---------------- TaskTracer (reference bthread/task_tracer.h) ----------------

#include "tfiber/task_tracer.h"

TEST(TaskTracer, ParkedFiberStackShowsParkSite) {
    // A fiber parked in fiber_usleep: its dumped stack must contain its
    // park site (sched_park / usleep frames) and its body function.
    std::atomic<bool> parked{false};
    std::atomic<bool> release{false};
    struct Ctx {
        std::atomic<bool>* parked;
        std::atomic<bool>* release;
    } ctx{&parked, &release};
    fiber_t tid;
    fiber_start_background(
        &tid, nullptr,
        [](void* arg) -> void* {
            Ctx* c = (Ctx*)arg;
            c->parked->store(true);
            while (!c->release->load()) {
                fiber_usleep(50 * 1000);
            }
            return nullptr;
        },
        &ctx);
    while (!parked.load()) fiber_usleep(1000);
    fiber_usleep(20 * 1000);  // let it reach the park
    const std::string dump = DumpFiberStacks();
    release.store(true);
    fiber_join(tid, nullptr);
    EXPECT_NE(dump.find("live fiber"), std::string::npos);
    EXPECT_NE(dump.find("[suspended]"), std::string::npos);
    // The park site: the saved RIP points into the suspend machinery
    // (sched_park is the direct tf_jump_fcontext caller; usleep frames
    // follow on the fp chain).
    const bool has_park =
        dump.find("sched_park") != std::string::npos ||
        dump.find("usleep") != std::string::npos;
    EXPECT_TRUE(has_park);
}
