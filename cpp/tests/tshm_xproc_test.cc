// Cross-process ICI transport tests: TCP-handshake bootstrap + shared-
// memory data plane between two real processes (reference analog:
// test/brpc_socket_unittest + rdma handshake paths; SURVEY §2.9).
//
// The server side is `echo_bench --ici-server`, spawned fork+exec (exec
// immediately — forking a threaded test binary is only safe up to exec).
// Covers: echo across processes, handshake rejection (bad version),
// client half-close (server survives, accepts a new link), and peer
// crash (SIGKILL mid-link fails the socket via the TCP failure detector).
#include <libgen.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <sys/socket.h>
#include <sys/wait.h>
#include <unistd.h>

#include <cstring>
#include <string>

#include "bench_echo.pb.h"
#include "tbase/crc32c.h"
#include "tbase/endpoint.h"
#include "tici/block_pool.h"
#include "tici/shm_link.h"
#include "tnet/socket.h"
#include "trpc/channel.h"
#include "trpc/controller.h"
#include "ttest/ttest.h"

using namespace tpurpc;

namespace {

std::string bench_binary_path() {
    char self[4096];
    const ssize_t n = readlink("/proc/self/exe", self, sizeof(self) - 1);
    if (n <= 0) return "";
    self[n] = '\0';
    return std::string(dirname(self)) + "/echo_bench";
}

struct ServerChild {
    pid_t pid = -1;
    int port = 0;
    int stdin_wr = -1;  // closing it shuts the child down

    bool Spawn() {
        const std::string bin = bench_binary_path();
        int out_pipe[2], in_pipe[2];
        if (pipe(out_pipe) != 0 || pipe(in_pipe) != 0) return false;
        pid = fork();
        if (pid < 0) return false;
        if (pid == 0) {
            dup2(out_pipe[1], 1);
            dup2(in_pipe[0], 0);
            close(out_pipe[0]);
            close(out_pipe[1]);
            close(in_pipe[0]);
            close(in_pipe[1]);
            execl(bin.c_str(), "echo_bench", "--ici-server", (char*)nullptr);
            _exit(127);
        }
        close(out_pipe[1]);
        close(in_pipe[0]);
        stdin_wr = in_pipe[1];
        char line[64];
        size_t got = 0;
        while (got < sizeof(line) - 1) {
            const ssize_t r = read(out_pipe[0], line + got, 1);
            if (r <= 0) break;
            if (line[got] == '\n') break;
            ++got;
        }
        line[got] = '\0';
        close(out_pipe[0]);
        return sscanf(line, "PORT %d", &port) == 1;
    }

    void Shutdown() {
        if (stdin_wr >= 0) {
            close(stdin_wr);
            stdin_wr = -1;
        }
        if (pid > 0) {
            // Bounded wait, then escalate.
            for (int i = 0; i < 300; ++i) {
                if (waitpid(pid, nullptr, WNOHANG) == pid) {
                    pid = -1;
                    return;
                }
                usleep(10000);
            }
            kill(pid, SIGKILL);
            waitpid(pid, nullptr, 0);
            pid = -1;
        }
    }

    void Kill9() {
        if (pid > 0) {
            kill(pid, SIGKILL);
            waitpid(pid, nullptr, 0);
            pid = -1;
        }
        if (stdin_wr >= 0) {
            close(stdin_wr);
            stdin_wr = -1;
        }
    }

    ~ServerChild() { Kill9(); }
};

int DoEcho(Channel& ch, const std::string& payload, std::string* echoed) {
    benchpb::EchoService_Stub stub(&ch);
    Controller cntl;
    cntl.set_timeout_ms(3000);
    benchpb::EchoRequest req;
    benchpb::EchoResponse res;
    req.set_send_ts_us(42);
    cntl.request_attachment().append(payload);
    stub.Echo(&cntl, &req, &res, nullptr);
    if (cntl.Failed()) return cntl.ErrorCode();
    *echoed = cntl.response_attachment().to_string();
    return 0;
}

}  // namespace

TEST(ShmXproc, EchoAcrossProcesses) {
    ASSERT_EQ(0, IciBlockPool::Init());
    ServerChild child;
    ASSERT_TRUE(child.Spawn());
    EndPoint ep;
    str2endpoint("127.0.0.1", child.port, &ep);
    Channel ch;
    ChannelOptions copts;
    copts.timeout_ms = 3000;
    ASSERT_EQ(0, ch.InitIci(ep, &copts));
    // Small payload and a payload larger than one block (spans multiple
    // descriptors + exercises the ring).
    std::string echoed;
    ASSERT_EQ(0, DoEcho(ch, "hello-over-shm", &echoed));
    EXPECT_EQ("hello-over-shm", echoed);
    std::string big(512 * 1024, 'x');
    for (size_t i = 0; i < big.size(); i += 4096) big[i] = (char)('a' + (i / 4096) % 26);
    ASSERT_EQ(0, DoEcho(ch, big, &echoed));
    EXPECT_TRUE(echoed == big);
    child.Shutdown();
}

TEST(ShmXproc, PoolDescriptorHandoffIsZeroCopy) {
    // One-sided descriptor across REAL process boundaries (ISSUE 9b):
    // the attachment bytes stay in OUR pool; the server resolves the
    // (pool_id, offset, len, crc) meta against its handshake-time
    // mapping of that pool and answers with the crc it computed from
    // the in-place view — 'inline=0' in the verdict proves no payload
    // bytes crossed the wire beside the descriptor.
    ASSERT_EQ(0, IciBlockPool::Init());
    ServerChild child;
    ASSERT_TRUE(child.Spawn());
    EndPoint ep;
    str2endpoint("127.0.0.1", child.port, &ep);
    Channel ch;
    ChannelOptions copts;
    copts.timeout_ms = 3000;
    ASSERT_EQ(0, ch.InitIci(ep, &copts));
    benchpb::EchoService_Stub stub(&ch);

    const size_t kBytes = 200000;
    const size_t live0 = IciBlockPool::slab_allocated();
    for (int round = 0; round < 3; ++round) {
        IOBuf att;
        char* data = nullptr;
        ASSERT_TRUE(
            IciBlockPool::AllocatePoolAttachment(kBytes, &att, &data));
        for (size_t i = 0; i < kBytes; ++i) {
            data[i] = (char)((i * 131 + round) >> 2);
        }
        const uint32_t crc = crc32c_extend(0, data, kBytes);
        Controller cntl;
        cntl.set_timeout_ms(3000);
        cntl.set_request_pool_attachment(std::move(att));
        ASSERT_TRUE(cntl.has_request_pool_attachment());
        benchpb::EchoRequest req;
        benchpb::EchoResponse res;
        req.set_send_ts_us(round);
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_FALSE(cntl.Failed());
        char expect[96];
        snprintf(expect, sizeof(expect), "crc32c=%08x len=%zu inline=0",
                 crc, kBytes);
        EXPECT_EQ(std::string(expect), res.payload());
    }
    // Completion returned every pinned block to this pool's slab class.
    EXPECT_EQ(live0, IciBlockPool::slab_allocated());
    child.Shutdown();
}

TEST(ShmXproc, ResponseDescriptorHandoffIsZeroCopy) {
    // Response-direction one-sided descriptor across REAL process
    // boundaries (ISSUE 12): the SERVER answers with a reference into
    // ITS pool; this client resolves it against the mapping the
    // handshake made of that pool and reads the seeded pattern in place
    // — zero inline payload bytes in the response. Releasing the view
    // (controller reuse) sends the desc_ack that unpins the server's
    // block.
    ASSERT_EQ(0, IciBlockPool::Init());
    ServerChild child;
    ASSERT_TRUE(child.Spawn());
    EndPoint ep;
    str2endpoint("127.0.0.1", child.port, &ep);
    Channel ch;
    ChannelOptions copts;
    copts.timeout_ms = 3000;
    ASSERT_EQ(0, ch.InitIci(ep, &copts));
    benchpb::EchoService_Stub stub(&ch);

    const size_t kBytes = 150000;
    for (int round = 0; round < 3; ++round) {
        Controller cntl;
        cntl.set_timeout_ms(3000);
        benchpb::EchoRequest req;
        benchpb::EchoResponse res;
        char ask[64];
        snprintf(ask, sizeof(ask), "desc_rsp:%zu:%d", kBytes, round);
        req.set_payload(ask);
        req.set_send_ts_us(round);
        stub.Echo(&cntl, &req, &res, nullptr);
        ASSERT_FALSE(cntl.Failed());
        const Controller::PoolAttachment& view =
            cntl.response_pool_attachment();
        ASSERT_TRUE(view.data != nullptr);
        EXPECT_EQ((uint64_t)kBytes, view.length);
        // The view lives in the MAPPED PEER pool, not ours — the bytes
        // never entered this process's pool or the wire.
        EXPECT_FALSE(IciBlockPool::Contains(view.data));
        EXPECT_EQ((size_t)0, cntl.response_attachment().size());
        EXPECT_EQ((char)round, view.data[0]);
        EXPECT_EQ((char)('a' + round % 26), view.data[1]);
        // No local pin for a response-direction descriptor: the pin
        // lives in the SERVER process.
        EXPECT_EQ((uint64_t)0, cntl.response_pool_lease_id());
        // cntl teardown acks the server's pin.
    }
    child.Shutdown();
}

TEST(ShmXproc, HandshakeBadVersionRejected) {
    ServerChild child;
    ASSERT_TRUE(child.Spawn());
    // Craft a handshake with an unsupported version directly over TCP.
    const int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    ASSERT_GE(fd, 0);
    sockaddr_in addr;
    EndPoint ep;
    str2endpoint("127.0.0.1", child.port, &ep);
    endpoint2sockaddr(ep, &addr);
    ASSERT_EQ(0, ::connect(fd, (sockaddr*)&addr, sizeof(addr)));
    shm_internal::HandshakeRequest req;
    memset(&req, 0, sizeof(req));
    memcpy(req.magic, "TICI", 4);
    req.version = 99;
    snprintf(req.pool_name, sizeof(req.pool_name), "/nonexistent");
    req.pool_size = 1 << 20;
    snprintf(req.link_name, sizeof(req.link_name), "/nonexistent");
    req.link_size = sizeof(shm_internal::ShmLinkCtrl);
    ASSERT_EQ((ssize_t)sizeof(req), write(fd, &req, sizeof(req)));
    shm_internal::HandshakeResponse rsp;
    size_t got = 0;
    while (got < sizeof(rsp)) {
        const ssize_t r = read(fd, (char*)&rsp + got, sizeof(rsp) - got);
        if (r <= 0) break;
        got += (size_t)r;
    }
    ASSERT_EQ(sizeof(rsp), got);
    EXPECT_EQ(0, memcmp(rsp.magic, "TICJ", 4));
    EXPECT_NE(0u, rsp.status);
    close(fd);
    child.Shutdown();
}

TEST(ShmXproc, HalfCloseThenReconnect) {
    ASSERT_EQ(0, IciBlockPool::Init());
    ServerChild child;
    ASSERT_TRUE(child.Spawn());
    EndPoint ep;
    str2endpoint("127.0.0.1", child.port, &ep);
    {
        // First link: use it, then fail the client socket (half-close).
        SocketId sid;
        ASSERT_EQ(0, IciConnect(ep, Channel::client_messenger(), &sid));
        Channel ch;
        ASSERT_EQ(0, ch.InitWithSocketId(sid, nullptr));
        std::string echoed;
        ASSERT_EQ(0, DoEcho(ch, "first-link", &echoed));
        SocketUniquePtr s = SocketUniquePtr::FromId(sid);
        ASSERT_TRUE((bool)s);
        s->SetFailed();  // client-side close: transport Close -> EOF at peer
    }
    // The server must survive the half-close and accept a fresh link.
    Channel ch2;
    ASSERT_EQ(0, ch2.InitIci(ep, nullptr));
    std::string echoed;
    ASSERT_EQ(0, DoEcho(ch2, "second-link", &echoed));
    EXPECT_EQ("second-link", echoed);
    child.Shutdown();
}

TEST(ShmXproc, PeerCrashFailsSocket) {
    ASSERT_EQ(0, IciBlockPool::Init());
    ServerChild child;
    ASSERT_TRUE(child.Spawn());
    EndPoint ep;
    str2endpoint("127.0.0.1", child.port, &ep);
    SocketId sid;
    ASSERT_EQ(0, IciConnect(ep, Channel::client_messenger(), &sid));
    Channel ch;
    ASSERT_EQ(0, ch.InitWithSocketId(sid, nullptr));
    std::string echoed;
    ASSERT_EQ(0, DoEcho(ch, "pre-crash", &echoed));
    // SIGKILL the server: no orderly close, only the TCP RST/EOF failure
    // detector. The client socket must fail (promptly, via the dispatcher)
    // and subsequent RPCs must error rather than hang.
    child.Kill9();
    int rc = -1;
    for (int i = 0; i < 100; ++i) {
        rc = DoEcho(ch, "post-crash", &echoed);
        if (rc != 0) break;
        usleep(20000);
    }
    EXPECT_NE(0, rc);
    SocketUniquePtr s = SocketUniquePtr::FromId(sid);
    // The versioned id must now be stale (socket failed) or at least the
    // endpoint must report not-established.
    if (s) {
        EXPECT_TRUE(s->Failed() || !s->transport()->Established());
    }
}
