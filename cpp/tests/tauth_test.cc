// Authenticator hooks: tpu_std first-message auth (+ the auth fight on
// shared connections) and gRPC authorization-header verification.
// Reference parity: src/brpc/authenticator.h, protocol.h verify hook,
// socket.h:515 FightAuthentication.
#include <atomic>
#include <string>
#include <vector>

#include "echo.pb.h"
#include "tbase/endpoint.h"
#include "tbase/errno.h"
#include "tfiber/fiber.h"
#include "trpc/auth.h"
#include "trpc/channel.h"
#include "trpc/controller.h"
#include "trpc/server.h"
#include "ttest/ttest.h"

using namespace tpurpc;

namespace {

class CountingAuth : public Authenticator {
public:
    explicit CountingAuth(std::string secret, bool present_wrong = false)
        : secret_(std::move(secret)), present_wrong_(present_wrong) {}

    int GenerateCredential(std::string* auth_str) const override {
        generated_.fetch_add(1);
        *auth_str = present_wrong_ ? "wrong-" + secret_ : secret_;
        return 0;
    }

    int VerifyCredential(const std::string& auth_str, const EndPoint&,
                         AuthContext* ctx) const override {
        verified_.fetch_add(1);
        if (auth_str != secret_) return -1;
        ctx->set_user("tester");
        return 0;
    }

    int generated() const { return generated_.load(); }
    int verified() const { return verified_.load(); }

private:
    std::string secret_;
    bool present_wrong_;
    mutable std::atomic<int> generated_{0};
    mutable std::atomic<int> verified_{0};
};

class AuthEchoImpl : public test::EchoService {
public:
    void Echo(google::protobuf::RpcController*,
              const test::EchoRequest* request, test::EchoResponse* response,
              google::protobuf::Closure* done) override {
        if (request->sleep_us() > 0) fiber_usleep(request->sleep_us());
        response->set_message(request->message());
        done->Run();
    }
};

struct AuthServer {
    AuthEchoImpl service;
    Server server;
    EndPoint ep;

    bool start(const Authenticator* auth) {
        if (server.AddService(&service) != 0) return false;
        ServerOptions opts;
        opts.auth = auth;
        EndPoint listen;
        str2endpoint("127.0.0.1:0", &listen);
        if (server.Start(listen, &opts) != 0) return false;
        str2endpoint("127.0.0.1", server.listened_port(), &ep);
        return true;
    }
};

int DoEcho(Channel* ch, const std::string& msg) {
    test::EchoService_Stub stub(ch);
    Controller cntl;
    test::EchoRequest req;
    req.set_message(msg);
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    if (cntl.Failed()) return cntl.ErrorCode();
    return res.message() == msg ? 0 : -1;
}

}  // namespace

TEST(Auth, GoodCredentialAccepted) {
    CountingAuth server_auth("s3cret");
    CountingAuth client_auth("s3cret");
    AuthServer ts;
    ASSERT_TRUE(ts.start(&server_auth));
    Channel ch;
    ChannelOptions opts;
    opts.auth = &client_auth;
    opts.timeout_ms = 5000;
    ASSERT_EQ(0, ch.Init(ts.ep, &opts));
    EXPECT_EQ(0, DoEcho(&ch, "hello"));
    EXPECT_EQ(0, DoEcho(&ch, "again"));
    // Credential generated + verified once: the connection is trusted
    // after the first message (no per-request re-verification).
    EXPECT_EQ(client_auth.generated(), 1);
    EXPECT_EQ(server_auth.verified(), 1);
}

TEST(Auth, BadCredentialRejectedAndConnectionFailed) {
    CountingAuth server_auth("s3cret");
    CountingAuth client_auth("s3cret", /*present_wrong=*/true);
    AuthServer ts;
    ASSERT_TRUE(ts.start(&server_auth));
    Channel ch;
    ChannelOptions opts;
    opts.auth = &client_auth;
    opts.max_retry = 0;
    opts.timeout_ms = 5000;
    ASSERT_EQ(0, ch.Init(ts.ep, &opts));
    test::EchoService_Stub stub(&ch);
    Controller cntl;
    test::EchoRequest req;
    req.set_message("x");
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    EXPECT_TRUE(cntl.Failed());
    EXPECT_EQ(cntl.ErrorCode(), TERR_AUTH);
}

TEST(Auth, MissingCredentialRejected) {
    CountingAuth server_auth("s3cret");
    AuthServer ts;
    ASSERT_TRUE(ts.start(&server_auth));
    Channel ch;  // NO authenticator on the client
    ChannelOptions opts;
    opts.max_retry = 0;
    opts.timeout_ms = 5000;
    ASSERT_EQ(0, ch.Init(ts.ep, &opts));
    test::EchoService_Stub stub(&ch);
    Controller cntl;
    test::EchoRequest req;
    req.set_message("x");
    test::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);
    EXPECT_TRUE(cntl.Failed());
}

TEST(Auth, ConcurrentFirstWritesAuthenticateExactlyOnce) {
    // 16 fibers race the FIRST calls on one shared connection: exactly
    // one attaches the credential (the others wait out the fight), and
    // every call succeeds.
    CountingAuth server_auth("s3cret");
    CountingAuth client_auth("s3cret");
    AuthServer ts;
    ASSERT_TRUE(ts.start(&server_auth));
    Channel ch;
    ChannelOptions opts;
    opts.auth = &client_auth;
    opts.timeout_ms = 10000;
    ASSERT_EQ(0, ch.Init(ts.ep, &opts));
    struct Ctx {
        Channel* ch;
        std::atomic<int> ok{0};
    } ctx{&ch, {}};
    std::vector<fiber_t> tids(16);
    for (auto& tid : tids) {
        fiber_start_background(
            &tid, nullptr,
            [](void* arg) -> void* {
                Ctx* c = (Ctx*)arg;
                if (DoEcho(c->ch, "fight") == 0) c->ok.fetch_add(1);
                return nullptr;
            },
            &ctx);
    }
    for (auto tid : tids) fiber_join(tid, nullptr);
    EXPECT_EQ(ctx.ok.load(), 16);
    EXPECT_EQ(client_auth.generated(), 1);
    EXPECT_EQ(server_auth.verified(), 1);
    EXPECT_EQ(ts.server.acceptor()->accepted_count(), 1);
}

TEST(AuthGrpc, HeaderVerifiedPerCall) {
    CountingAuth server_auth("Bearer tok-123");
    CountingAuth good("Bearer tok-123");
    CountingAuth bad("Bearer tok-123", /*present_wrong=*/true);
    AuthServer ts;
    ASSERT_TRUE(ts.start(&server_auth));
    {
        Channel ch;
        ChannelOptions opts;
        opts.protocol = "grpc";
        opts.auth = &good;
        opts.timeout_ms = 10000;
        ASSERT_EQ(0, ch.Init(ts.ep, &opts));
        EXPECT_EQ(0, DoEcho(&ch, "authed"));
    }
    {
        Channel ch;
        ChannelOptions opts;
        opts.protocol = "grpc";
        opts.auth = &bad;
        opts.max_retry = 0;
        opts.timeout_ms = 10000;
        ASSERT_EQ(0, ch.Init(ts.ep, &opts));
        test::EchoService_Stub stub(&ch);
        Controller cntl;
        test::EchoRequest req;
        req.set_message("x");
        test::EchoResponse res;
        stub.Echo(&cntl, &req, &res, nullptr);
        EXPECT_TRUE(cntl.Failed());  // grpc-status 16 UNAUTHENTICATED
    }
}

#include "trpc/redis.h"

TEST(AuthRedis, NoauthUntilAuthCommand) {
    // ServerOptions::auth covers RESP too: commands before a valid AUTH
    // get -NOAUTH; AUTH with the right credential unlocks the connection.
    CountingAuth server_auth("hunter2");
    AuthServer ts;
    ASSERT_TRUE(ts.start(&server_auth));
    RedisService kv;
    kv.AddBasicKvCommands();
    ts.server.set_redis_service(&kv);  // set post-start is fine for tests

    Channel ch;
    ChannelOptions opts;
    opts.protocol = "redis";
    opts.timeout_ms = 5000;
    ASSERT_EQ(0, ch.Init(ts.ep, &opts));

    RedisRequest req;
    req.AddCommand({"PING"});                 // -> NOAUTH
    req.AddCommand({"AUTH", "wrong"});        // -> ERR
    req.AddCommand({"AUTH", "hunter2"});      // -> OK
    req.AddCommand({"PING"});                 // -> PONG
    RedisResponse res;
    Controller cntl;
    RedisCall(&ch, &cntl, req, &res);
    ASSERT_FALSE(cntl.Failed());
    ASSERT_EQ(res.reply_count(), 4u);
    EXPECT_TRUE(res.reply(0).is_error());
    EXPECT_EQ(res.reply(0).str.compare(0, 6, "NOAUTH"), 0);
    EXPECT_TRUE(res.reply(1).is_error());
    EXPECT_EQ(res.reply(2).str, "OK");
    EXPECT_EQ(res.reply(3).str, "PONG");
}

#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

TEST(AuthHttp, JsonTranscodingRequiresAuthorization) {
    // The json door honors ServerOptions::auth too: bare POST is 401,
    // with the credential in `authorization` it runs.
    CountingAuth server_auth("open-sesame");
    AuthServer ts;
    ASSERT_TRUE(ts.start(&server_auth));
    auto fetch = [&](const std::string& req_str) {
        const int fd = ::socket(AF_INET, SOCK_STREAM, 0);
        sockaddr_in addr;
        endpoint2sockaddr(ts.ep, &addr);
        if (::connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
            ::close(fd);
            return std::string("connect-failed");
        }
        (void)!::send(fd, req_str.data(), req_str.size(), 0);
        std::string out;
        char buf[4096];
        ssize_t r;
        while ((r = ::recv(fd, buf, sizeof(buf), 0)) > 0) {
            out.append(buf, (size_t)r);
            if (out.find("\r\n\r\n") != std::string::npos &&
                out.find("}") != std::string::npos) {
                break;
            }
        }
        ::close(fd);
        return out;
    };
    const std::string body = "{\"message\": \"sesame\"}";
    char req[512];
    snprintf(req, sizeof(req),
             "POST /EchoService/Echo HTTP/1.1\r\nHost: x\r\n"
             "Content-Type: application/json\r\nContent-Length: %zu\r\n"
             "\r\n%s",
             body.size(), body.c_str());
    const std::string denied = fetch(req);
    EXPECT_NE(denied.find("401"), std::string::npos);
    snprintf(req, sizeof(req),
             "POST /EchoService/Echo HTTP/1.1\r\nHost: x\r\n"
             "Authorization: open-sesame\r\n"
             "Content-Type: application/json\r\nContent-Length: %zu\r\n"
             "\r\n%s",
             body.size(), body.c_str());
    const std::string ok = fetch(req);
    EXPECT_NE(ok.find("200"), std::string::npos);
    EXPECT_NE(ok.find("sesame"), std::string::npos);
}
