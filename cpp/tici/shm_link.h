// Cross-process ICI link: a queue pair between two PROCESSES, bootstrapped
// by a handshake over TCP — the cross-host shape of the ICI transport.
//
// Mirrors the reference RDMA endpoint's lifecycle exactly (SURVEY §2.9,
// reference src/brpc/rdma/rdma_endpoint.h:127-130): a plain TCP connection
// performs the handshake — here exchanging shared-memory segment names
// instead of GID/QPN — then the data plane runs over registered memory
// while TCP stays idle as the failure detector. On a real multi-host
// TPU-VM deployment the peer-pool mapping becomes libtpu transfer-engine
// registration and the descriptor rings become ICI send/recv queues; the
// handshake, framing, credit flow control and teardown logic are
// identical.
//
// Memory layout:
//  - Each process's IciBlockPool primary region is a named POSIX shm
//    segment (its "registered memory", block_pool.h). The handshake
//    exchanges the two names; each side maps the peer's pool READ-ONLY.
//  - Per link, the CLIENT creates a small control segment holding two
//    ShmPipe descriptor rings (client->server and server->client). A
//    posted descriptor is (offset into sender's pool, length); the
//    receiver resolves it against its mapping of the sender's pool and
//    copies once into its IOPortal (what the interconnect DMA engine
//    does in hardware).
//  - Doorbells ride the TCP connection as single bytes (event-suppressed:
//    only sent when the other side armed), so completions enter the
//    normal EventDispatcher through the socket's fd — pillar 4, and the
//    reason peer death is detected for free (TCP EOF/RST).
//
// Send blocks not inside the shared pool region (pre-pool allocations,
// overflow regions) are bounce-copied into pool blocks — the same rule
// the reference applies to non-registered memory.
#pragma once

#include <atomic>
#include <cstdint>

#include "tbase/endpoint.h"
#include "tbase/iobuf.h"
#include "tnet/socket.h"
#include "tnet/transport.h"

namespace tpurpc {

class InputMessenger;

namespace shm_internal {

// One direction of the link, living in the shared control segment.
// Single producer (sender's elected writer fiber), single consumer
// (receiver's input-event fiber). POD + lock-free atomics only: this
// struct is shared between processes.
struct ShmPipe {
    static constexpr uint32_t kDepth = 1024;  // flow-control window

    struct Desc {
        uint64_t off;  // byte offset into the SENDER's pool shm segment
        uint32_t len;
        uint32_t pad;
    };

    alignas(64) std::atomic<uint64_t> head;  // producer: next slot to fill
    alignas(64) std::atomic<uint64_t> tail;  // consumer: [tail,head) pending
    alignas(64) std::atomic<uint32_t> closed;
    // Event suppression: consumer arms before sleeping; producer sends a
    // TCP doorbell byte only when armed.
    std::atomic<uint32_t> rx_armed;
    // Producer parked on credits; consumer sends a doorbell after
    // consuming when set.
    std::atomic<uint32_t> tx_waiting;
    Desc ring[kDepth];

    void InitPipe() {
        head.store(0, std::memory_order_relaxed);
        tail.store(0, std::memory_order_relaxed);
        closed.store(0, std::memory_order_relaxed);
        rx_armed.store(1, std::memory_order_relaxed);
        tx_waiting.store(0, std::memory_order_relaxed);
    }
};

// The control segment (created by the connecting client).
struct ShmLinkCtrl {
    static constexpr uint64_t kMagic = 0x49434954'4c4e4b31ull;  // "ICITLNK1"
    uint64_t magic;  // set LAST by the creator
    uint32_t version;
    uint32_t pad;
    ShmPipe c2s;  // client produces
    ShmPipe s2c;  // server produces
};

// Handshake frames exchanged over the TCP connection before the data
// plane starts (the ProcessHandshakeAtClient/AtServer analog).
//
// Version 2 (ISSUE 10): the structs grew a raw pool_epoch field, which
// changes their SIZE — and the exchange is a fixed-size raw read, so a
// version-1 peer would either starve the parser (shorter request) or
// leave trailing bytes to be mis-sniffed (longer one). The bumped
// version makes the mismatch an explicit clean rejection instead; the
// "epoch 0 = fence disabled" escape below is for same-size forward
// compatibility only.
constexpr uint32_t kIciHandshakeVersion = 2;

struct HandshakeRequest {
    char magic[4];  // "TICI"
    uint32_t version;
    char pool_name[64];  // client's pool shm segment
    uint64_t pool_size;
    char link_name[64];  // control segment (created by client)
    uint64_t link_size;
    // Pool generation at handshake time (epoch fencing, ISSUE 10b): the
    // receiver records it on the mapping; descriptors carrying a
    // different epoch are fenced with TERR_STALE_EPOCH. 0 from
    // pre-epoch binaries = fence disabled for that peer.
    uint64_t pool_epoch;
};

struct HandshakeResponse {
    char magic[4];  // "TICJ"
    uint32_t status;     // 0 = ok, else terrno
    char pool_name[64];  // server's pool shm segment
    uint64_t pool_size;
    uint64_t pool_epoch;  // server pool generation (see HandshakeRequest)
};

// Process-global registry of mapped peer pools (one mapping per peer
// process, shared by every link to it, refcounted).
struct PeerPool {
    char* base;
    size_t size;
};
// `epoch` is the owner's pool generation announced in the handshake
// (registered with the mapping for the stale-descriptor fence).
int AcquirePeerPool(const char* name, size_t size, uint64_t epoch,
                    PeerPool* out);
void ReleasePeerPool(const char* name);
// True when `name` is a safe single-component shm name ("/x...").
bool valid_shm_name(const char* name);

}  // namespace shm_internal

// One side of a cross-process link. The socket's fd IS the bootstrap TCP
// connection: doorbell bytes and peer-death events arrive through the
// normal dispatcher.
class ShmIciEndpoint : public TransportEndpoint {
public:
    int event_fd() const override { return tcp_fd_; }
    bool Established() const override;
    ssize_t CutFromIOBufList(IOBuf* const* pieces, size_t count) override;
    int WaitWritable(int64_t abstime_us) override;
    ssize_t Pump(IOPortal* dst) override;
    void Close() override;
    void Release() override;
    int tier() const override { return TierShmXproc(); }

    uint64_t signals_sent() const {
        return signals_sent_.load(std::memory_order_relaxed);
    }

    // Build one side. Takes ownership of tcp_fd and of the ctrl mapping;
    // acquires a ref on the peer pool (released in Release()).
    // `is_client`: which pipe this side produces into. `peer` is the
    // remote's endpoint (server address on the client side, ephemeral
    // peer address on the server side) — used for per-peer
    // fault-injection scoping (tnet/fault_injection.h).
    static ShmIciEndpoint* Create(int tcp_fd, void* ctrl_mapping,
                                  size_t ctrl_size, bool is_client,
                                  const char* peer_pool_name,
                                  const shm_internal::PeerPool& peer_pool,
                                  const EndPoint& peer);

private:
    ShmIciEndpoint() = default;
    ~ShmIciEndpoint() override;

    void ReleaseCompleted();
    void SendDoorbell();

    int tcp_fd_ = -1;
    EndPoint peer_ep_;  // fault-injection scoping identity
    shm_internal::ShmLinkCtrl* ctrl_ = nullptr;
    size_t ctrl_size_ = 0;
    shm_internal::ShmPipe* out_ = nullptr;
    shm_internal::ShmPipe* in_ = nullptr;
    char peer_pool_name_[64] = "";
    char* peer_base_ = nullptr;
    size_t peer_size_ = 0;
    // Sender-local shadow of the out ring: the block (one ref held) each
    // posted descriptor points into — the `_sbuf` of the RDMA endpoint.
    IOBuf::Block* sbuf_[shm_internal::ShmPipe::kDepth] = {};
    std::atomic<uint64_t> released_{0};  // refs freed up to this slot
    std::atomic<bool> releasing_{false};
    std::atomic<bool> tcp_eof_{false};  // failure detector tripped
    void* writable_butex_ = nullptr;
    std::atomic<uint64_t> signals_sent_{0};
};

// Client side: TCP-connect to `server`, run the handshake, and produce a
// connected Socket whose data plane is the shared-memory queue pair.
// Returns 0 and fills *id on success; -1 with errno/log on failure.
// Requires IciBlockPool::Init() with a shared primary region.
int IciConnect(const EndPoint& server, InputMessenger* messenger,
               SocketId* id, int timeout_ms = 3000);

// Server side: protocol index of the handshake sniffer (registered by
// GlobalInitializeOrDie; Server::StartNoListen adds it to the messenger
// so any accepted TCP connection can upgrade to the shm data plane).
int IciHandshakeProtocolIndex();
void RegisterIciHandshakeProtocol();  // idempotent; called from global init

}  // namespace tpurpc
