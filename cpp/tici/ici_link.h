// IciLink: a software queue pair — the loopback ICI transport.
//
// Plays the role reference src/brpc/rdma/rdma_endpoint.{h,cpp} plays over
// verbs, with the same four design pillars (SURVEY §2.9):
//   1. zero-copy block posting: the sender moves IOBuf BlockRefs into the
//      send ring (refs held in the ring — the `_sbuf` equivalent,
//      rdma_endpoint.cpp:777 CutFromIOBufList) and releases them only
//      after the receiver's consumed counter passes them (the remote
//      completion, rdma_endpoint.cpp:937 HandleCompletion).
//   2. windowed credit flow control: ring depth = the window; consumed
//      counts are published back like piggybacked ACKs
//      (rdma_endpoint.cpp:907 SendAck / window fields h:256-261).
//   3. event suppression: the doorbell eventfd is only signaled when the
//      consumer armed it (solicited-event flag; CQ arm/disarm pattern).
//   4. completions unified into the dispatcher: each endpoint's eventfd is
//      registered with the normal EventDispatcher as the Socket's fd, so
//      the upper stack is transport-agnostic (comp-channel-fd pattern,
//      rdma_endpoint.cpp:1364 PollCq feeding InputMessenger).
//
// The "DMA" is performed at the receiver: Pump copies posted spans into
// pool blocks appended to the socket's IOPortal (one copy per byte — what
// the interconnect DMA engine does in hardware; loopback TCP pays four).
// On a real TPU-VM this class is the seam where libtpu transfer queues
// slot in: post -> ici enqueue, Pump -> completion-queue drain, the
// rings' shared counters -> device doorbells. Cross-host setup runs the
// same handshake-over-DCN scheme as the RDMA endpoint (GID/QPN exchange
// over TCP, rdma_endpoint.h:127) — see IciHandshake below.
#pragma once

#include <atomic>
#include <cstdint>
#include <memory>

#include "tbase/iobuf.h"
#include "tnet/transport.h"

namespace tpurpc {

class IciLink;

namespace ici_internal {

// One direction of the link. Single producer (the socket's elected
// writer), single consumer (the socket's input-event fiber).
struct Pipe {
    static constexpr uint32_t kDepth = 256;  // the flow-control window

    struct Desc {
        IOBuf::Block* block;  // producer holds one ref until released
        uint32_t offset;
        uint32_t length;
    };

    Desc ring[kDepth];
    char pad0[64];
    std::atomic<uint64_t> head{0};      // producer: next slot to fill
    char pad1[64];
    std::atomic<uint64_t> tail{0};      // consumer: slots [.,head) pending
    char pad2[64];
    std::atomic<bool> closed{false};
    // Event suppression: consumer arms before sleeping; producer signals
    // the doorbell only when armed (batched completions otherwise).
    std::atomic<bool> rx_armed{true};
    // Producer parked waiting for window credits; consumer rings the
    // producer's doorbell when it consumes.
    std::atomic<bool> tx_waiting{false};

    // Refs freed up to this slot. Advanced ONLY after the dec_refs are
    // done (single claimer via `releasing`): the producer's reuse window
    // is bounded by `released`, so a slot is never overwritten while its
    // old block pointer is still pending a dec_ref.
    std::atomic<uint64_t> released{0};
    std::atomic<bool> releasing{false};

    // Producer credits: bounded by RELEASED (not consumed) slots — a
    // consumed-but-unreleased slot still holds an owned block pointer.
    uint32_t credits() const {
        return kDepth - (uint32_t)(head.load(std::memory_order_relaxed) -
                                   released.load(std::memory_order_acquire));
    }
};

}  // namespace ici_internal

// One side of an IciLink. Implements the Socket transport seam.
class IciEndpoint : public TransportEndpoint {
public:
    int event_fd() const override { return evfd_; }
    bool Established() const override;
    ssize_t CutFromIOBufList(IOBuf* const* pieces, size_t count) override;
    int WaitWritable(int64_t abstime_us) override;
    ssize_t Pump(IOPortal* dst) override;
    void Close() override;
    void Release() override;  // link frees itself after both sides release
    int tier() const override { return TierIci(); }

    // Doorbell signal count (tests: event-suppression assertions).
    uint64_t signals_sent() const {
        return signals_sent_.load(std::memory_order_relaxed);
    }

private:
    friend class IciLink;
    IciEndpoint() = default;

    void ReleaseCompleted();  // free sent refs the peer consumed

    IciLink* link_ = nullptr;
    ici_internal::Pipe* out_ = nullptr;  // we produce
    ici_internal::Pipe* in_ = nullptr;   // we consume
    int evfd_ = -1;                      // our doorbell (Socket's fd)
    IciEndpoint* peer_ = nullptr;
    void* writable_butex_ = nullptr;
    std::atomic<uint64_t> signals_sent_{0};
};

// A connected pair of endpoints (the fake-ICI "cable"). In-process for
// tests/bench; the shm + handshake-over-DCN variant keeps this exact
// layout in a MAP_SHARED segment.
//
// Lifetime: heap-only (Create). Each endpoint carries one owner
// reference (typically a Socket created with owns_transport); the link
// deletes itself when both are Release()d, so the two sockets can fail
// and recycle in any order without dangling pipes.
class IciLink {
public:
    static IciLink* Create() { return new IciLink; }

    IciEndpoint* first() { return &a_; }
    IciEndpoint* second() { return &b_; }

private:
    friend class IciEndpoint;
    IciLink();
    ~IciLink();
    void EndpointReleased();

    ici_internal::Pipe ab_;  // a produces, b consumes
    ici_internal::Pipe ba_;
    IciEndpoint a_;
    IciEndpoint b_;
    std::atomic<int> live_endpoints_{2};
};

}  // namespace tpurpc
