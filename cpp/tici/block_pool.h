// Registered-memory block pool for the ICI transport: takes over IOBuf's
// block allocator so every payload block lives in transfer-registered
// memory and can be posted to the interconnect zero-copy.
//
// Modeled on reference src/brpc/rdma/block_pool.{h,cpp} (628 LoC): the
// RDMA build registers GB-step regions with the NIC and swaps IOBuf's
// `blockmem_allocate` hook (butil/iobuf.cpp:168) so send buffers need no
// bounce copy. Here "registered" means: the PRIMARY region is a named
// POSIX shared-memory segment other processes can map (the cross-process
// "memory registration"), so a peer can resolve posted (offset,length)
// descriptors against its read-only mapping of this pool — on real
// TPU-VM hosts this seam becomes libtpu-registered / pinned host
// buffers. Overflow regions are anonymous (non-transferable; the send
// path bounce-copies from them). Structure kept from the reference:
// regions grown in fixed steps, freelist under a mutex (the per-thread
// IOBuf block cache in front absorbs nearly all traffic), O(1)
// Contains() via the region list.
#pragma once

#include <atomic>
#include <cstddef>
#include <cstdint>
#include <string>

namespace tpurpc {

class IciBlockPool {
public:
    // Install the pool as IOBuf's block allocator. Idempotent.
    // `region_bytes` sizes the primary (shared, transferable) region;
    // overflow grows in anonymous regions of the same step.
    static int Init(size_t region_bytes = 64u << 20);

    // Allocator pair installed into IOBuf::blockmem_allocate/deallocate.
    static void* Allocate(size_t n);
    static void Deallocate(void* p);
    // A DEFAULT_BLOCK_SIZE block guaranteed inside the shared region, or
    // null when none is free (bounce buffers for the cross-process send
    // path, which must be peer-visible). Deallocate() as usual.
    static void* AllocateSharedBlock();

    // A large contiguous chunk carved from registered region memory —
    // staging buffers for device DMA (the JAX device-path benchmark
    // device_puts straight out of these). Carve-only: chunks live for the
    // process (free is a no-op); intended for long-lived transfer
    // arenas, like the reference's GB-step RDMA regions
    // (/root/reference/src/brpc/rdma/block_pool.cpp RegisterMemory).
    static void* AllocateRegistered(size_t n);
    // Deallocator for bounce blocks: same routing as Deallocate, but a
    // DISTINCT function pointer so IOBuf::Block::dec_ref bypasses the TLS
    // block cache (bounce blocks must return to the shared freelist where
    // AllocateSharedBlock can find them, not vanish into a thread cache).
    static void DeallocateShared(void* p);

    // True if p lies inside a registered region (pool memory; primary or
    // overflow).
    static bool Contains(const void* p);

    // ---- slab-class registered allocator (ISSUE 9c) ----
    // Recyclable registered memory in size classes (8K/64K/256K/1M/4M).
    // Each class carves large aligned slab ARENAS out of the registered
    // regions and chops them into fixed slots; freed slots recycle
    // through a per-thread slot cache in front of a per-class freelist
    // (its own mutex), so descriptor/staging traffic never bounces on
    // the pool's central mutex. Requests above the largest class fall
    // back to AllocateRegistered (carve-only, process lifetime).
    static void* AllocateSlab(size_t n);
    // Recycles p into its class (TLS cache first). p MUST come from
    // AllocateSlab; non-slab pool pointers are ignored (carve-only).
    static void FreeSlab(void* p);
    // Class index serving n bytes, or -1 when n exceeds the largest
    // class (tests + sizing diagnostics).
    static int SlabClassOf(size_t n);
    static size_t slab_class_bytes(int cls);
    // Counters: live slots, frees that found a cache/freelist home, and
    // class-mutex acquisitions (the contention diagnostic the per-thread
    // cache is meant to keep near zero on steady-state traffic).
    static size_t slab_allocated();
    static size_t slab_recycled();
    static size_t slab_mutex_acquisitions();
    // Per-class occupancy (the /pools page): live slots, freelist depth
    // (central list only — TLS-cached slots count as live-capable but
    // not listed), and slots carved so far.
    struct SlabClassStat {
        size_t live = 0;
        size_t freelist = 0;
        size_t carved = 0;
    };
    static SlabClassStat slab_class_stat(int cls);

    // Build a single-block IOBuf of n writable bytes inside the SHARED
    // registered pool — the eligible shape for one-sided descriptors
    // (Controller::set_request_pool_attachment): one contiguous ref a
    // single (offset, len) can name. The block wraps a slab slot
    // (placement-new IOBuf::Block header, FreeSlab deallocator), so the
    // last release recycles the slot into its class. Returns false when
    // n exceeds the largest slab class or the slab landed outside the
    // shared primary (caller falls back to inline attachment bytes).
    static bool AllocatePoolAttachment(size_t n, class IOBuf* out,
                                       char** data);

    // Chunk-leasing helper for pipelined transfers (ISSUE 13): allocate
    // a descriptor-eligible pool block and fill it from `src` in one
    // step — the shape every collective chunk send needs. Returns false
    // (out untouched) when the pool can't serve a shared slab of n
    // bytes; the caller falls back to inline attachment bytes.
    static bool AllocatePoolAttachmentCopy(const void* src, size_t n,
                                           class IOBuf* out);

    // ---- cross-process registration (the shared primary region) ----
    // Name of the shm segment backing the primary region ("" when the
    // pool fell back to anonymous memory). Peers shm_open this name
    // during the ICI handshake.
    static const char* shm_name();
    static size_t shm_size();
    static char* shm_base();
    // True + byte offset into the shared region when p points into it —
    // i.e. the bytes at p can be posted to a peer zero-copy.
    static bool OffsetOf(const void* p, uint64_t* offset);

    // Stable identity of this process's shared primary region (FNV-1a of
    // the shm name; 0 when the pool is anonymous/process-local). The
    // pool_id of one-sided descriptors posted from this pool.
    static uint64_t pool_id();

    // ---- epoch fencing (ISSUE 10b) ----
    // Generation of this process's pool mapping: 1 at Init, bumped on
    // any create/remap/restart event (and by chaos/tests). Descriptors
    // carry the epoch they were minted under (RpcMeta.pool_attachment.
    // pool_epoch); a receiver resolving against a mapping whose epoch
    // differs fails ONLY that call with the retriable TERR_STALE_EPOCH —
    // a stale reference must never take down the connection or the
    // process, just trigger a re-handshake/remap upstream.
    static uint64_t pool_epoch();
    // Bump the local pool's generation (simulated remap/restart; also
    // re-stamps the pool's own registry entry so in-process resolution
    // stays consistent). Returns the new epoch.
    static uint64_t BumpEpoch();

    static bool initialized();
    static size_t allocated_blocks();  // live default-size blocks
    static size_t free_blocks();       // freelist depth
};

// ---- pool registry (one-sided descriptors, ISSUE 9b) ----
// Maps pool_id -> a mapping of that pool in THIS process's address
// space: the local pool (registered at IciBlockPool::Init) and every
// peer pool mapped during an ICI handshake (shm_link AcquirePeerPool).
// A receiver resolves a wire (pool_id, offset, len) descriptor here and
// reads the bytes in place — the one-sided read of the transfer.
namespace pool_registry {
uint64_t IdFromName(const char* name);  // FNV-1a 64 over the shm name
// `epoch` is the pool generation this mapping was made under (learned
// from the owner at handshake; the local pool registers its own).
void Register(uint64_t id, const char* base, size_t size,
              uint64_t epoch = 1);
void Unregister(uint64_t id);
// Re-stamp a mapping's generation without remapping (the local pool's
// BumpEpoch, and chaos-driven staleness in tests). Absolute write —
// test hook; production paths use RaiseEpoch.
void SetEpoch(uint64_t id, uint64_t epoch);
// Monotonic re-stamp: only raises the mapping's generation. The
// handshake path uses this — a slow/racing link whose response was
// written before the owner's bump must not REGRESS the epoch (stale
// descriptors would then pass the fence again).
void RaiseEpoch(uint64_t id, uint64_t epoch);
// True + the mapped span when id is known. The span stays valid while
// the mapping is held (local pool: process lifetime; peer pools: while
// any link to that peer lives — the Socket holding the descriptor's
// connection holds the link, so resolution during request processing is
// safe). `epoch` (when non-null) receives the mapping's generation for
// the caller's stale-descriptor fence.
bool Resolve(uint64_t id, const char** base, size_t* size,
             uint64_t* epoch = nullptr);
// Pool id -> shm segment name (ISSUE 18). The verbs layer needs the
// NAME to open its own WRITABLE mapping of a peer pool (the handshake
// mapping is PROT_READ; a granted REMOTE_WRITE window is the rkey-
// equivalent authorization to remap O_RDWR). Registered alongside the
// mapping; survives Unregister so a re-grant after link churn can
// still find the segment. NameOf copies into buf (NUL-terminated),
// false when unknown or buf too small.
void SetName(uint64_t id, const char* name);
bool NameOf(uint64_t id, char* buf, size_t n);
// Resolution stats (tests + /vars).
uint64_t resolves();
uint64_t resolve_failures();
// One "pool <id> size=<n> epoch=<e> local=<0|1>" line per mapping (the
// /pools page body).
std::string DebugString();
}  // namespace pool_registry

// ---- device staging ring (ISSUE 9a) ----
// A depth-N ring of registered staging slots driving the pipelined
// device data path: slot i holds chunk i's framed bytes while H2D of
// chunk i+1, the on-device integrity kernel on chunk i, and D2H of
// chunk i-1 overlap. Slots are handed out in strict FIFO order
// (Acquire blocks while the oldest slot is still in flight) and become
// reusable only when every predecessor has completed — the same
// released_-counter protocol as the shm/ici descriptor rings, which is
// what makes out-of-order Complete() calls safe under many threads.
//
// Thread contract: plain std::mutex/condvar (NOT fibers) — the ring is
// driven from Python threads through the C ABI.
class DeviceStagingRing {
public:
    // Slots come from AllocateSlab: registered memory, recycled on
    // destroy. Returns null when depth/slot_bytes is zero or the pool
    // has no memory.
    static DeviceStagingRing* Create(uint32_t depth, size_t slot_bytes);
    ~DeviceStagingRing();

    // Next slot in FIFO order; blocks up to timeout_us (<0 = forever,
    // 0 = non-blocking try) while all depth slots are in flight.
    // Returns the slot index, -1 on timeout, or -2 once the ring is
    // aborted (waiters unblock immediately — the deadline/cancellation
    // contract of ISSUE 10c).
    int Acquire(int64_t timeout_us);
    // Mark slot done. Out-of-order completes are held; the slot is
    // reusable once all earlier acquires completed. Returns 0, or -1
    // for an index that is not currently in flight. Chaos may delay or
    // drop a complete (chaos_pool ring_delay/ring_drop): a dropped
    // complete returns 0 but never advances the window — exactly the
    // lost-completion failure Acquire's timeout path must survive.
    int Complete(uint32_t slot);
    // Poison the ring (device stream error / shutdown): every parked and
    // future Acquire returns -2 instead of wedging a Python thread
    // forever; in-flight Completes still settle accounting.
    void Abort();
    bool aborted() const {
        return aborted_.load(std::memory_order_acquire);
    }

    char* slot(uint32_t i) { return slots_[i % depth_]; }
    uint32_t depth() const { return depth_; }
    size_t slot_bytes() const { return slot_bytes_; }
    bool registered() const { return registered_; }
    uint64_t acquires() const {
        return head_.load(std::memory_order_relaxed);
    }
    uint64_t completes() const {
        return completed_.load(std::memory_order_relaxed);
    }
    // Highest number of slots ever simultaneously in flight (ordering
    // tests: never exceeds depth).
    uint32_t inflight_highwater() const {
        return highwater_.load(std::memory_order_relaxed);
    }

private:
    DeviceStagingRing() = default;

    void* mu_ = nullptr;  // std::mutex + condvar behind an opaque ptr
    char** slots_ = nullptr;
    // How each slot was obtained (0 = slab class / recyclable, 1 =
    // malloc fallback, 2 = carve-only registered chunk): ~Ring must
    // route each pointer back to the right deallocator.
    uint8_t* slot_kind_ = nullptr;
    bool* done_ = nullptr;
    uint32_t depth_ = 0;
    size_t slot_bytes_ = 0;
    bool registered_ = false;
    // Counters mutate under mu_ but are read lock-free by the accessors.
    std::atomic<uint64_t> head_{0};       // acquired count
    std::atomic<uint64_t> tail_{0};       // contiguously-completed count
    std::atomic<uint64_t> completed_{0};  // total completes
    std::atomic<uint32_t> highwater_{0};
    std::atomic<bool> aborted_{false};    // poisoned: Acquire returns -2
};

}  // namespace tpurpc
