// Registered-memory block pool for the ICI transport: takes over IOBuf's
// block allocator so every payload block lives in transfer-registered
// memory and can be posted to the interconnect zero-copy.
//
// Modeled on reference src/brpc/rdma/block_pool.{h,cpp} (628 LoC): the
// RDMA build registers GB-step regions with the NIC and swaps IOBuf's
// `blockmem_allocate` hook (butil/iobuf.cpp:168) so send buffers need no
// bounce copy. Here "registered" means: carved from mmap'd regions the
// transfer engine may DMA from — on real TPU-VM hosts these become
// libtpu-registered / pinned host buffers; the fake-ICI loopback treats
// any pool region as transferable. Structure kept: regions grown in
// fixed steps, freelist under a mutex (the per-thread IOBuf block cache
// in front absorbs nearly all traffic), O(1) Contains() via region list.
#pragma once

#include <cstddef>
#include <cstdint>

namespace tpurpc {

class IciBlockPool {
public:
    // Install the pool as IOBuf's block allocator. Idempotent.
    // `region_bytes` is the mmap growth step (default 64MB).
    static int Init(size_t region_bytes = 64u << 20);

    // Allocator pair installed into IOBuf::blockmem_allocate/deallocate.
    static void* Allocate(size_t n);
    static void Deallocate(void* p);

    // True if p lies inside a registered region (i.e. transferable).
    static bool Contains(const void* p);

    static bool initialized();
    static size_t allocated_blocks();  // live default-size blocks
    static size_t free_blocks();       // freelist depth
};

}  // namespace tpurpc
