// Registered-memory block pool for the ICI transport: takes over IOBuf's
// block allocator so every payload block lives in transfer-registered
// memory and can be posted to the interconnect zero-copy.
//
// Modeled on reference src/brpc/rdma/block_pool.{h,cpp} (628 LoC): the
// RDMA build registers GB-step regions with the NIC and swaps IOBuf's
// `blockmem_allocate` hook (butil/iobuf.cpp:168) so send buffers need no
// bounce copy. Here "registered" means: the PRIMARY region is a named
// POSIX shared-memory segment other processes can map (the cross-process
// "memory registration"), so a peer can resolve posted (offset,length)
// descriptors against its read-only mapping of this pool — on real
// TPU-VM hosts this seam becomes libtpu-registered / pinned host
// buffers. Overflow regions are anonymous (non-transferable; the send
// path bounce-copies from them). Structure kept from the reference:
// regions grown in fixed steps, freelist under a mutex (the per-thread
// IOBuf block cache in front absorbs nearly all traffic), O(1)
// Contains() via the region list.
#pragma once

#include <cstddef>
#include <cstdint>

namespace tpurpc {

class IciBlockPool {
public:
    // Install the pool as IOBuf's block allocator. Idempotent.
    // `region_bytes` sizes the primary (shared, transferable) region;
    // overflow grows in anonymous regions of the same step.
    static int Init(size_t region_bytes = 64u << 20);

    // Allocator pair installed into IOBuf::blockmem_allocate/deallocate.
    static void* Allocate(size_t n);
    static void Deallocate(void* p);
    // A DEFAULT_BLOCK_SIZE block guaranteed inside the shared region, or
    // null when none is free (bounce buffers for the cross-process send
    // path, which must be peer-visible). Deallocate() as usual.
    static void* AllocateSharedBlock();

    // A large contiguous chunk carved from registered region memory —
    // staging buffers for device DMA (the JAX device-path benchmark
    // device_puts straight out of these). Carve-only: chunks live for the
    // process (free is a no-op); intended for long-lived transfer
    // arenas, like the reference's GB-step RDMA regions
    // (/root/reference/src/brpc/rdma/block_pool.cpp RegisterMemory).
    static void* AllocateRegistered(size_t n);
    // Deallocator for bounce blocks: same routing as Deallocate, but a
    // DISTINCT function pointer so IOBuf::Block::dec_ref bypasses the TLS
    // block cache (bounce blocks must return to the shared freelist where
    // AllocateSharedBlock can find them, not vanish into a thread cache).
    static void DeallocateShared(void* p);

    // True if p lies inside a registered region (pool memory; primary or
    // overflow).
    static bool Contains(const void* p);

    // ---- cross-process registration (the shared primary region) ----
    // Name of the shm segment backing the primary region ("" when the
    // pool fell back to anonymous memory). Peers shm_open this name
    // during the ICI handshake.
    static const char* shm_name();
    static size_t shm_size();
    static char* shm_base();
    // True + byte offset into the shared region when p points into it —
    // i.e. the bytes at p can be posted to a peer zero-copy.
    static bool OffsetOf(const void* p, uint64_t* offset);

    static bool initialized();
    static size_t allocated_blocks();  // live default-size blocks
    static size_t free_blocks();       // freelist depth
};

}  // namespace tpurpc
