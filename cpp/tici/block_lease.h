// Block leases: crash-safe ownership of pinned zero-copy pool blocks.
//
// A one-sided PoolDescriptor (rpc_meta.proto) tells the peer "read my
// pool at (offset, len)"; the sender must keep the underlying slab slot
// pinned until the RPC completes — and BEFORE this layer existed, the
// pin lived as a raw IOBuf ref inside the Controller, so a peer that
// died mid-RPC (or a wedged call that never reached EndRPC) leaked the
// slot forever: the classic dangling-pin hazard of RDMA-style data
// paths ("RPC Considered Harmful" §4, arXiv:1805.08430).
//
// The lease registry OWNS every pin:
//  - Pin() takes the pinned IOBuf (one contiguous pool block ref) and
//    hands back a lease id; the controller keeps only the id plus the
//    raw descriptor fields.
//  - Release(id) is exactly-once by construction: the first caller —
//    EndRPC, the expiry reaper, or peer-death reclamation — drops the
//    registry's ref (recycling the slab slot); later callers get false.
//    Double-release across the retry/backup re-issue flow is therefore
//    structurally impossible.
//  - Arm(id, call, deadline, peer) stamps the owning call id, an expiry
//    deadline derived from the RPC's propagated deadline (+ grace;
//    -pool_lease_default_ms bounds deadline-less calls), and the socket
//    the descriptor was posted on. Re-issues re-arm (new peer key).
//  - A reaper thread (started lazily at the first Pin; interval
//    -pool_lease_reap_ms) reclaims expired leases: rpc_pool_reaped /
//    rpc_pool_lease_expired count them, and the slab live count returns
//    to baseline even when EndRPC never runs.
//  - ReleasePeer(peer_key) frees every lease armed against a dead
//    peer's socket — called from the same failure-observer path that
//    already cancels that socket's server calls, so a SIGKILLed node
//    cannot strand pins on the survivors.
//
// Thread contract: plain std::mutex (called from fibers, Python threads
// through the C ABI, and the reaper thread alike — never holds the lock
// across user code). pb-free: links into the standalone pool suite.
#pragma once

#include <cstdint>
#include <string>

#include "tbase/iobuf.h"

namespace tpurpc {
namespace block_lease {

// Pin `buf` (ownership moves into the registry). Returns a nonzero
// lease id. The bytes stay readable by peers until the first Release.
// `direction` tags the lease for the /pools ledger: "req" = a client
// pinning a request attachment (released at EndRPC), "rsp" = a server
// pinning a response attachment (released by the client's desc_ack).
// Must be a string with static storage duration.
uint64_t Pin(IOBuf&& buf, const char* direction = "req");

// Stamp ownership + expiry on a pinned lease (idempotent). `deadline_us`
// is an absolute monotonic_time_us instant; <= 0 applies now +
// -pool_lease_default_ms. `add_peer=false` REPLACES the entitled-peer
// key (a retry: the previous try is finished); true ADDS it alongside
// the existing one (a backup request: the original try's peer may
// still read the block, so peer-death reclamation frees the pin only
// when EVERY entitled peer is gone — two keys held max). Returns false
// when the lease no longer exists (already released or reclaimed) —
// the arm IS the caller's liveness check, under the same lock, so no
// reclamation can land between a separate probe and the arm.
bool Arm(uint64_t lease_id, uint64_t call_id, int64_t deadline_us,
         uint64_t peer_key, bool add_peer = false);

// Exactly-once release: true when THIS call dropped the pin; false when
// the lease was already released (reaper / peer death / earlier call)
// or never existed.
bool Release(uint64_t lease_id);

// True while the lease still holds its pin.
bool Alive(uint64_t lease_id);

// Reap leases whose deadline has passed (the reaper thread's body, split
// out so tests can drive it with a fake `now`). Returns reaped count.
size_t ReapExpired(int64_t now_us);

// Release every lease armed with `peer_key` (socket failure observer /
// shm-link teardown). Returns released count.
size_t ReleasePeer(uint64_t peer_key);

// Release every lease armed with `call_id` AND entitled to `peer_key` —
// the response-direction completion: the client's desc_ack names the
// wire correlation id the server armed its response pin under, and the
// ack arrives on the very connection the descriptor left on. BOTH keys
// must match: correlation ids are only unique within one client
// process, so an unscoped release could free another connection's pin.
// Exactly-once like Release (a duplicate ack finds nothing). Returns
// released count. O(live leases) scan — the token-less fallback; acks
// carrying the descriptor's ack_token take the O(log n) ReleaseAcked
// path instead.
size_t ReleaseByCall(uint64_t call_id, uint64_t peer_key);

// O(log n) scoped release by the ack token (= the lease id the server
// embedded in the response descriptor): direct lookup, then the SAME
// call-id + entitled-peer validation as ReleaseByCall — a forged or
// cross-connection token frees nothing. True when this ack dropped the
// pin.
bool ReleaseAcked(uint64_t lease_id, uint64_t call_id,
                  uint64_t peer_key);

// Counters (also exposed as rpc_pool_{pinned_blocks,lease_expired,
// reaped,peer_released} tvars).
uint64_t pinned();         // live leases
uint64_t pins_total();     // lifetime Pin() calls
uint64_t released();       // releases via Release() (EndRPC path)
uint64_t expired_reaped(); // releases via ReapExpired
uint64_t peer_released();  // releases via ReleasePeer

// One "key value" line per stat + one "lease <id> dir=<req|rsp>
// call=<c> deadline_in_ms=<d> peer=<p>" line per live lease (the /pools
// page body; bounded to the first 64 leases).
std::string DebugString();

// JSON array of live leases with a direction column (the /pools
// ?format=json "leases" field; bounded to `max` entries).
std::string JsonLeases(size_t max);

// Start the background reaper thread (idempotent; Pin() calls it).
void StartReaper();

// Register the rpc_pool_* tvar families (idempotent; StartReaper and
// every portal-carrying Server call it so /metrics and the lint see
// the families even before the first pin).
void ExposeVars();

}  // namespace block_lease
}  // namespace tpurpc
