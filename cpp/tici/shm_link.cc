#include "tici/shm_link.h"

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <unistd.h>

#include <cerrno>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <string>

#include "tbase/errno.h"
#include "tbase/fast_rand.h"
#include "tbase/logging.h"
#include "tbase/time.h"
#include "tfiber/butex.h"
#include "tfiber/fiber.h"
#include "tici/block_pool.h"
#include "tnet/fault_injection.h"
#include "tnet/input_messenger.h"

namespace tpurpc {

using shm_internal::HandshakeRequest;
using shm_internal::HandshakeResponse;
using shm_internal::PeerPool;
using shm_internal::ShmLinkCtrl;
using shm_internal::ShmPipe;

namespace shm_internal {

// ---------------- peer pool registry ----------------

namespace {
struct PeerPoolEntry {
    char* base;
    size_t size;
    int refs;
};
// Immortal singletons: endpoint Release() runs from Socket recycling,
// which a static Server's destructor can trigger during exit — after
// ordinary statics are gone. Leak the registry so teardown order can't
// use-after-free it.
std::mutex& pp_mu() {
    static std::mutex* mu = new std::mutex;
    return *mu;
}
std::map<std::string, PeerPoolEntry>& peer_pools() {
    static auto* m = new std::map<std::string, PeerPoolEntry>;
    return *m;
}

}  // namespace

// shm names must be a single path component ("/name"): reject anything
// else before it reaches shm_open (applies to peer-supplied pool AND
// link names).
bool valid_shm_name(const char* name) {
    if (name[0] != '/' || name[1] == '\0') return false;
    for (const char* c = name + 1; *c; ++c) {
        if (*c == '/') return false;
    }
    return strnlen(name, 64) < 64;
}

int AcquirePeerPool(const char* name, size_t size, uint64_t epoch,
                    PeerPool* out) {
    if (!valid_shm_name(name) || size == 0 || size > (4ull << 30)) {
        errno = EINVAL;
        return -1;
    }
    std::lock_guard<std::mutex> g(pp_mu());
    auto& pools = peer_pools();
    auto it = pools.find(name);
    if (it != pools.end()) {
        if (it->second.size < size) {
            errno = EINVAL;  // peer reported a bigger pool than we mapped
            return -1;
        }
        ++it->second.refs;
        // A later link re-announcing a NEWER generation re-stamps the
        // shared mapping: the owner remapped/restarted parts of its
        // pool, and descriptors minted before the bump must now fence.
        // Monotonic (RaiseEpoch): a slow handshake whose response was
        // written BEFORE the owner's bump must not regress the epoch
        // and re-admit genuinely stale descriptors.
        if (epoch != 0) {
            const uint64_t id = pool_registry::IdFromName(name);
            if (id != IciBlockPool::pool_id()) {
                pool_registry::RaiseEpoch(id, epoch);
            }
        }
        out->base = it->second.base;
        out->size = it->second.size;
        return 0;
    }
    const int fd = shm_open(name, O_RDONLY, 0);
    if (fd < 0) return -1;
    struct stat st;
    if (fstat(fd, &st) != 0 || (size_t)st.st_size < size) {
        close(fd);
        errno = EINVAL;
        return -1;
    }
    // Read-only: the receiver only resolves descriptors against the
    // peer's registered memory; it never writes into it.
    void* mem = mmap(nullptr, size, PROT_READ, MAP_SHARED, fd, 0);
    close(fd);
    if (mem == MAP_FAILED) return -1;
    pools[name] = PeerPoolEntry{(char*)mem, size, 1};
    // One-sided descriptors (ISSUE 9): mapping a peer pool IS the
    // memory registration descriptors resolve against — publish it
    // under the peer's pool id so a (pool_id, offset, len) meta field
    // from this peer reads in place. Our OWN pool (an in-process
    // loopback handshake maps it too) keeps its Init-time registration:
    // overwriting it with this transient mapping would let a later
    // link teardown unregister the local pool for good.
    const uint64_t id = pool_registry::IdFromName(name);
    if (id != IciBlockPool::pool_id()) {
        pool_registry::Register(id, (char*)mem, size,
                                epoch != 0 ? epoch : 1);
        // The verbs layer remaps peer pools O_RDWR by NAME for granted
        // REMOTE_WRITE windows (this handshake mapping is read-only).
        pool_registry::SetName(id, name);
    }
    out->base = (char*)mem;
    out->size = size;
    return 0;
}

void ReleasePeerPool(const char* name) {
    std::lock_guard<std::mutex> g(pp_mu());
    auto& pools = peer_pools();
    auto it = pools.find(name);
    if (it == pools.end()) return;
    if (--it->second.refs == 0) {
        const uint64_t id = pool_registry::IdFromName(name);
        if (id != IciBlockPool::pool_id()) {
            pool_registry::Unregister(id);
        }
        munmap(it->second.base, it->second.size);
        pools.erase(it);
    }
}

}  // namespace shm_internal

// ---------------- endpoint ----------------

ShmIciEndpoint* ShmIciEndpoint::Create(int tcp_fd, void* ctrl_mapping,
                                       size_t ctrl_size, bool is_client,
                                       const char* peer_pool_name,
                                       const PeerPool& peer_pool,
                                       const EndPoint& peer) {
    auto* e = new ShmIciEndpoint;
    e->tcp_fd_ = tcp_fd;
    e->peer_ep_ = peer;
    e->ctrl_ = (ShmLinkCtrl*)ctrl_mapping;
    e->ctrl_size_ = ctrl_size;
    e->out_ = is_client ? &e->ctrl_->c2s : &e->ctrl_->s2c;
    e->in_ = is_client ? &e->ctrl_->s2c : &e->ctrl_->c2s;
    snprintf(e->peer_pool_name_, sizeof(e->peer_pool_name_), "%s",
             peer_pool_name);
    e->peer_base_ = peer_pool.base;
    e->peer_size_ = peer_pool.size;
    e->writable_butex_ = butex_create();
    return e;
}

ShmIciEndpoint::~ShmIciEndpoint() {
    // Free refs of posted-but-never-consumed descriptors (our own blocks;
    // the peer may be gone).
    if (out_ != nullptr) {
        const uint64_t head = out_->head.load(std::memory_order_acquire);
        for (uint64_t i = released_.load(std::memory_order_relaxed);
             i < head; ++i) {
            IOBuf::Block* b = sbuf_[i % ShmPipe::kDepth];
            if (b != nullptr) b->dec_ref();
        }
    }
    if (ctrl_ != nullptr) munmap(ctrl_, ctrl_size_);
    if (peer_pool_name_[0] != '\0') {
        shm_internal::ReleasePeerPool(peer_pool_name_);
    }
    if (tcp_fd_ >= 0) close(tcp_fd_);
    if (writable_butex_ != nullptr) butex_destroy(writable_butex_);
}

bool ShmIciEndpoint::Established() const {
    return !tcp_eof_.load(std::memory_order_acquire) &&
           out_->closed.load(std::memory_order_acquire) == 0 &&
           in_->closed.load(std::memory_order_acquire) == 0;
}

void ShmIciEndpoint::SendDoorbell() {
    // One byte on the bootstrap TCP connection: wakes the peer's
    // dispatcher, which pumps. EAGAIN (buffer full of doorbells) means
    // the peer stopped draining — the TCP failure detector covers that;
    // dropping the byte here is safe because a stuck peer re-arms and a
    // dead one never reads again.
    const char b = 'D';
    ssize_t r = send(tcp_fd_, &b, 1, MSG_NOSIGNAL | MSG_DONTWAIT);
    (void)r;
    signals_sent_.fetch_add(1, std::memory_order_relaxed);
}

void ShmIciEndpoint::ReleaseCompleted() {
    // Single claimer (writer fiber vs pump fiber); `released_` advances
    // only after the dec_refs are done so no slot is reused while its
    // old block pointer is pending — same protocol as the in-process
    // link (ici_link.cc).
    bool expected = false;
    if (!releasing_.compare_exchange_strong(expected, true,
                                            std::memory_order_acquire)) {
        return;
    }
    // Clamp to our own head: the tail counter is peer-writable shared
    // memory; a corrupt/hostile value past head must not dec_ref slots
    // still pending consumption (use-after-free) or overshoot the
    // credit window.
    const uint64_t head = out_->head.load(std::memory_order_relaxed);
    uint64_t consumed = out_->tail.load(std::memory_order_acquire);
    if (consumed > head) consumed = head;
    const uint64_t from = released_.load(std::memory_order_relaxed);
    for (uint64_t i = from; i < consumed; ++i) {
        IOBuf::Block* b = sbuf_[i % ShmPipe::kDepth];
        sbuf_[i % ShmPipe::kDepth] = nullptr;
        if (b != nullptr) b->dec_ref();
    }
    released_.store(consumed, std::memory_order_release);
    releasing_.store(false, std::memory_order_release);
}

ssize_t ShmIciEndpoint::CutFromIOBufList(IOBuf* const* pieces, size_t count) {
    if (!Established()) {
        errno = EPIPE;
        return -1;
    }
    ReleaseCompleted();
    ShmPipe* p = out_;
    uint64_t head = p->head.load(std::memory_order_relaxed);
    const uint64_t limit =
        released_.load(std::memory_order_acquire) + ShmPipe::kDepth;
    ssize_t posted = 0;
    size_t pending_bytes = 0;
    for (size_t i = 0; i < count; ++i) pending_bytes += pieces[i]->size();
    if (pending_bytes == 0) {
        return 0;  // all-empty pieces: match writev-on-empty semantics
    }
    // Chaos seam (tnet/fault_injection.h), scoped by the link's peer.
    FaultAction fault;
    size_t post_cap = (size_t)-1;
    bool corrupt_next = false;
    if (__builtin_expect(fault_injection_enabled(), 0)) {
        fault = FaultInjection::Decide(FaultOp::kWrite, peer_ep_,
                                       pending_bytes);
        switch (fault.kind) {
            case FaultAction::kReset:
                errno = ECONNRESET;
                return -1;
            case FaultAction::kDelay:
                // Safe to park: with chaos enabled, Socket::FlushOnce
                // routes every write through the KeepWrite fiber.
                fiber_usleep(fault.delay_us);
                break;
            case FaultAction::kDrop:
                for (size_t i = 0; i < count; ++i) {
                    pieces[i]->pop_front(pieces[i]->size());
                }
                return (ssize_t)pending_bytes;  // claimed, never posted
            case FaultAction::kShort:
                post_cap = fault.max_bytes > 0 ? fault.max_bytes : 1;
                break;
            case FaultAction::kCorrupt:
                // Force the first fragment through the bounce path so
                // the flip lands in OUR copy, never in a shared source
                // block.
                corrupt_next = true;
                break;
            default:
                break;
        }
    }
    for (size_t i = 0; i < count && head < limit; ++i) {
        IOBuf* buf = pieces[i];
        while (head < limit && !buf->empty() && (size_t)posted < post_cap) {
            ShmPipe::Desc& d = p->ring[head % ShmPipe::kDepth];
            size_t flen = 0;
            const char* fdata = buf->backing_block_data(0, &flen);
            uint64_t off;
            if (!corrupt_next && IciBlockPool::OffsetOf(fdata, &off)) {
                // Zero-copy: the bytes already live in our registered
                // (shared) region; post the offset and hold the block ref
                // until the peer's consumed counter passes it.
                IOBuf::BlockRef ref;
                buf->cut_front_ref(&ref);
                d.off = off;
                d.len = ref.length;
                sbuf_[head % ShmPipe::kDepth] = ref.block;
            } else {
                // Bounce: copy into a block guaranteed inside the shared
                // region (non-registered source memory — same rule as the
                // reference RDMA path). create_block() won't do: the TLS
                // cache / freelist may hand back an overflow-region block
                // the peer can't see.
                void* mem = IciBlockPool::AllocateSharedBlock();
                if (mem == nullptr && posted > 0) {
                    // Descriptors already written must not sit behind a
                    // reclaim wait: publish them now; the caller's
                    // normal backpressure retries the rest.
                    break;
                }
                if (mem == nullptr) {
                    // Shared blocks are circulating through per-thread
                    // caches; the failed call raised the pool's pressure
                    // flag (block_pool.cc), which reroutes them back to
                    // the shared freelist as they free. Flush our own
                    // cache and give the rest a short grace to drain.
                    // (Blocks parked in IDLE threads' caches stay out of
                    // reach — the dedicated bounce band exists precisely
                    // so that worst case is bounded to ring-depth bytes.)
                    IOBuf::flush_tls_cache();
                    for (int spin = 0;
                         spin < 50 && mem == nullptr; ++spin) {
                        mem = IciBlockPool::AllocateSharedBlock();
                        if (mem == nullptr) fiber_usleep(1000);
                    }
                }
                if (mem == nullptr) {
                    if (posted > 0) break;  // publish what we have
                    LOG(ERROR) << "ShmIciEndpoint: shared pool region "
                                  "exhausted; cannot bounce-copy";
                    errno = ENOMEM;
                    return -1;
                }
                auto* b = new (mem) IOBuf::Block;
                b->nshared.store(1, std::memory_order_relaxed);
                b->size = 0;
                b->cap = (uint32_t)(IOBuf::DEFAULT_BLOCK_SIZE -
                                    offsetof(IOBuf::Block, data));
                b->portal_next = nullptr;
                // Distinct deallocator: returns to the shared freelist,
                // never the TLS cache (see DeallocateShared).
                b->dealloc = IciBlockPool::DeallocateShared;
                uint64_t boff = 0;
                IciBlockPool::OffsetOf(b->data, &boff);
                const size_t n =
                    flen < (size_t)b->cap ? flen : (size_t)b->cap;
                buf->copy_to(b->data, n, 0);
                buf->pop_front(n);
                if (corrupt_next && n > 0) {
                    b->data[fault.aux % n] ^= 0x20;  // our bounce copy
                    corrupt_next = false;
                }
                d.off = boff;
                d.len = (uint32_t)n;
                sbuf_[head % ShmPipe::kDepth] = b;
            }
            posted += d.len;
            ++head;
        }
    }
    if (posted == 0) {
        errno = EAGAIN;  // window full: real back-pressure
        return -1;
    }
    p->head.store(head, std::memory_order_release);
    if (p->rx_armed.exchange(0, std::memory_order_acq_rel) != 0) {
        SendDoorbell();
    }
    return posted;
}

int ShmIciEndpoint::WaitWritable(int64_t abstime_us) {
    ShmPipe* p = out_;
    std::atomic<int>* word = butex_word(writable_butex_);
    const int expected = word->load(std::memory_order_acquire);
    p->tx_waiting.store(1, std::memory_order_release);
    // Fold consumed slots into released_ before the credit re-check (the
    // consume may have landed before tx_waiting was visible — no doorbell
    // was sent for it).
    ReleaseCompleted();
    const uint32_t credits =
        ShmPipe::kDepth -
        (uint32_t)(p->head.load(std::memory_order_relaxed) -
                   released_.load(std::memory_order_acquire));
    if (credits > 0 || !Established()) {
        p->tx_waiting.store(0, std::memory_order_release);
        return Established() ? 0 : -1;
    }
    butex_wait(writable_butex_, expected, &abstime_us);
    p->tx_waiting.store(0, std::memory_order_release);
    // Timeout is not fatal (same contract as WaitEpollOut): the caller
    // re-checks and re-arms. Only a dead link is an error.
    return Established() ? 0 : -1;
}

ssize_t ShmIciEndpoint::Pump(IOPortal* dst) {
    // 1. Drain doorbell bytes off the TCP connection; EOF/RST here is the
    //    failure detector (peer process died or closed).
    char tbuf[512];
    while (true) {
        const ssize_t r = recv(tcp_fd_, tbuf, sizeof(tbuf), MSG_DONTWAIT);
        if (r > 0) continue;
        if (r == 0) {
            tcp_eof_.store(true, std::memory_order_release);
            break;
        }
        if (errno == EINTR) continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        tcp_eof_.store(true, std::memory_order_release);  // RST etc.
        break;
    }
    // 2. Send-side completions: free refs the peer consumed, wake writers
    //    (they re-check credits; spurious wakes are harmless).
    ReleaseCompleted();
    butex_word(writable_butex_)->fetch_add(1, std::memory_order_release);
    butex_wake_all(writable_butex_);

    // Chaos seam: inbound faults on the resolved descriptor payloads.
    FaultAction fault;
    if (__builtin_expect(fault_injection_enabled(), 0)) {
        fault = FaultInjection::Decide(FaultOp::kRead, peer_ep_, 0);
        if (fault.kind == FaultAction::kReset) {
            tcp_eof_.store(true, std::memory_order_release);
            errno = ECONNRESET;
            return -1;
        }
        if (fault.kind == FaultAction::kDelay) {
            fiber_usleep(fault.delay_us);
        }
    }

    // 3. Receive: resolve descriptors against the peer's registered
    //    memory and copy once into dst (the "DMA").
    ShmPipe* p = in_;
    ssize_t received = 0;
    while (true) {
        uint64_t tail = p->tail.load(std::memory_order_relaxed);
        const uint64_t head = p->head.load(std::memory_order_acquire);
        if (tail == head) {
            if (received > 0) return received;
            if (p->closed.load(std::memory_order_acquire) != 0 ||
                tcp_eof_.load(std::memory_order_acquire)) {
                return 0;  // EOF only after the ring is drained
            }
            // Arm the doorbell, then re-check (a post may race the arm).
            p->rx_armed.store(1, std::memory_order_seq_cst);
            if (p->head.load(std::memory_order_seq_cst) != tail ||
                p->closed.load(std::memory_order_acquire) != 0) {
                continue;
            }
            errno = EAGAIN;
            return -1;
        }
        while (tail != head) {
            const ShmPipe::Desc d = p->ring[tail % ShmPipe::kDepth];
            // Bounds-check against the mapped peer region: a corrupt or
            // hostile descriptor must not read out of the mapping.
            if (d.off > peer_size_ || d.len > peer_size_ - d.off) {
                LOG(ERROR) << "ShmIciEndpoint: descriptor out of bounds "
                           << d.off << "+" << d.len << " > " << peer_size_;
                tcp_eof_.store(true, std::memory_order_release);
                errno = TERR_REQUEST;
                return -1;
            }
            if (fault.kind == FaultAction::kDrop) {
                // Consume without delivering: the bytes vanish (the
                // sender's credits are still returned).
            } else if (fault.kind == FaultAction::kCorrupt &&
                       received == 0 && d.len > 0) {
                // Flip one byte of the first fragment via a copy window
                // (the peer's pool is mapped read-only).
                char window[512];
                const size_t wn =
                    d.len < sizeof(window) ? d.len : sizeof(window);
                memcpy(window, peer_base_ + d.off, wn);
                window[fault.aux % wn] ^= 0x20;
                dst->append(window, wn);
                if (d.len > wn) {
                    dst->append(peer_base_ + d.off + wn, d.len - wn);
                }
            } else {
                dst->append(peer_base_ + d.off, d.len);
            }
            received += d.len;
            ++tail;
            p->tail.store(tail, std::memory_order_release);
            if (fault.kind == FaultAction::kShort) {
                // Short read: deliver only this first descriptor; the
                // rest stays ring-buffered for the next pump.
                if (p->tx_waiting.load(std::memory_order_acquire) != 0) {
                    SendDoorbell();
                }
                return received;
            }
        }
        // Consumed -> credits freed on the peer: ring its doorbell if its
        // writer parked (piggybacked-ACK wakeup).
        if (p->tx_waiting.load(std::memory_order_acquire) != 0) {
            SendDoorbell();
        }
    }
}

void ShmIciEndpoint::Close() {
    if (out_->closed.exchange(1, std::memory_order_acq_rel) == 0) {
        // Wake the peer's pump (sees closed after draining) and our own
        // parked writers. shutdown() makes the close visible through the
        // failure detector even if the peer never reads the shm flag.
        SendDoorbell();
        shutdown(tcp_fd_, SHUT_WR);
        butex_word(writable_butex_)->fetch_add(1, std::memory_order_release);
        butex_wake_all(writable_butex_);
    }
}

void ShmIciEndpoint::Release() { delete this; }

// ---------------- client connect ----------------

namespace {

int write_all_timeout(int fd, const void* data, size_t n, int timeout_ms) {
    const char* p = (const char*)data;
    const int64_t deadline = monotonic_time_us() + timeout_ms * 1000ll;
    while (n > 0) {
        const ssize_t r = send(fd, p, n, MSG_NOSIGNAL);
        if (r > 0) {
            p += r;
            n -= (size_t)r;
            continue;
        }
        if (r < 0 && (errno == EINTR)) continue;
        if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
            if (monotonic_time_us() >= deadline) {
                errno = ETIMEDOUT;
                return -1;
            }
            pollfd pfd{fd, POLLOUT, 0};
            poll(&pfd, 1, 20);
            continue;
        }
        return -1;
    }
    return 0;
}

int read_all_timeout(int fd, void* data, size_t n, int timeout_ms) {
    char* p = (char*)data;
    const int64_t deadline = monotonic_time_us() + timeout_ms * 1000ll;
    while (n > 0) {
        const ssize_t r = recv(fd, p, n, 0);
        if (r > 0) {
            p += r;
            n -= (size_t)r;
            continue;
        }
        if (r == 0) {
            errno = ECONNRESET;
            return -1;
        }
        if (errno == EINTR) continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
            if (monotonic_time_us() >= deadline) {
                errno = ETIMEDOUT;
                return -1;
            }
            pollfd pfd{fd, POLLIN, 0};
            poll(&pfd, 1, 20);
            continue;
        }
        return -1;
    }
    return 0;
}

}  // namespace

int IciConnect(const EndPoint& server, InputMessenger* messenger,
               SocketId* id, int timeout_ms) {
    if (!IciBlockPool::initialized() || IciBlockPool::shm_name()[0] == '\0') {
        LOG(ERROR) << "IciConnect: IciBlockPool not initialized with a "
                      "shared region (call IciBlockPool::Init first)";
        errno = EINVAL;
        return -1;
    }
    // 1. Create the control segment (we are the client).
    char link_name[64];
    snprintf(link_name, sizeof(link_name), "/tpurpc_link_%d_%08lx",
             (int)getpid(), (unsigned long)fast_rand());
    int sfd = shm_open(link_name, O_CREAT | O_EXCL | O_RDWR, 0600);
    if (sfd < 0) {
        PLOG(ERROR) << "IciConnect: shm_open " << link_name;
        return -1;
    }
    if (ftruncate(sfd, (off_t)sizeof(ShmLinkCtrl)) != 0) {
        close(sfd);
        shm_unlink(link_name);
        return -1;
    }
    void* mem = mmap(nullptr, sizeof(ShmLinkCtrl), PROT_READ | PROT_WRITE,
                     MAP_SHARED, sfd, 0);
    close(sfd);
    if (mem == MAP_FAILED) {
        shm_unlink(link_name);
        return -1;
    }
    auto* ctrl = (ShmLinkCtrl*)mem;
    ctrl->version = 1;
    ctrl->c2s.InitPipe();
    ctrl->s2c.InitPipe();
    // Publish the initialized pipes before the magic the server validates.
    std::atomic_thread_fence(std::memory_order_release);
    ctrl->magic = ShmLinkCtrl::kMagic;

    auto fail = [&](const char* what) -> int {
        const int saved = errno;
        LOG(ERROR) << "IciConnect: " << what << ": " << strerror(saved);
        munmap(mem, sizeof(ShmLinkCtrl));
        shm_unlink(link_name);
        errno = saved;
        return -1;
    };

    // 2. TCP connect (the bootstrap/failure-detector connection).
    const int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return fail("socket");
    timeval tv{timeout_ms / 1000, (timeout_ms % 1000) * 1000};
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    sockaddr_in addr;
    endpoint2sockaddr(server, &addr);
    if (::connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
        close(fd);
        return fail("connect");
    }

    // 3. Handshake: send our pool + link params, read the server's pool.
    HandshakeRequest req;
    memset(&req, 0, sizeof(req));
    memcpy(req.magic, "TICI", 4);
    req.version = shm_internal::kIciHandshakeVersion;
    snprintf(req.pool_name, sizeof(req.pool_name), "%s",
             IciBlockPool::shm_name());
    req.pool_size = IciBlockPool::shm_size();
    req.pool_epoch = IciBlockPool::pool_epoch();
    snprintf(req.link_name, sizeof(req.link_name), "%s", link_name);
    req.link_size = sizeof(ShmLinkCtrl);
    if (write_all_timeout(fd, &req, sizeof(req), timeout_ms) != 0) {
        close(fd);
        return fail("handshake send");
    }
    HandshakeResponse rsp;
    if (read_all_timeout(fd, &rsp, sizeof(rsp), timeout_ms) != 0) {
        close(fd);
        return fail("handshake recv");
    }
    if (memcmp(rsp.magic, "TICJ", 4) != 0) {
        close(fd);
        errno = TERR_RESPONSE;
        return fail("bad handshake response magic");
    }
    if (rsp.status != 0) {
        close(fd);
        errno = (int)rsp.status;
        return fail("server rejected handshake");
    }
    rsp.pool_name[sizeof(rsp.pool_name) - 1] = '\0';

    // 4. Map the server's registered memory (recording its announced
    //    pool generation for the stale-descriptor fence).
    PeerPool pp;
    if (shm_internal::AcquirePeerPool(rsp.pool_name, rsp.pool_size,
                                      rsp.pool_epoch, &pp) != 0) {
        close(fd);
        return fail("map server pool");
    }
    // Both sides have the control segment mapped now; drop the name.
    shm_unlink(link_name);

    // 5. Endpoint + socket: the TCP fd doubles as the socket's event fd.
    ShmIciEndpoint* ep =
        ShmIciEndpoint::Create(fd, mem, sizeof(ShmLinkCtrl),
                               /*is_client=*/true, rsp.pool_name, pp, server);
    SocketOptions opts;
    opts.fd = fd;
    opts.remote_side = server;
    opts.transport = ep;
    opts.owns_transport = true;
    opts.on_edge_triggered_events = InputMessenger::OnNewMessages;
    opts.user = messenger;
    if (Socket::Create(opts, id) != 0) {
        // Ambiguous ownership on this can't-happen path: depending on
        // where Create failed, either it closed the fd (slot exhaustion)
        // or the recycling socket already Release()d the endpoint
        // (dispatcher failure). Releasing here could double-free either
        // one — leak the endpoint instead and say so.
        LOG(ERROR) << "IciConnect: Socket::Create failed after handshake; "
                      "leaking endpoint";
        return -1;
    }
    {
        // Descriptor scope: responses/requests on this connection may
        // reference exactly the server pool the handshake mapped.
        SocketUniquePtr created;
        if (Socket::AddressSocket(*id, &created) == 0) {
            created->set_peer_pool_id(
                pool_registry::IdFromName(rsp.pool_name));
        }
    }
    return 0;
}

// ---------------- server handshake protocol ----------------

namespace {

struct IciHandshakeMessage : public InputMessageBase {
    HandshakeRequest req;
};

ParseResult ParseIciHandshake(IOBuf* source, Socket* s, bool read_eof,
                              const void*) {
    (void)read_eof;
    char mag[4];
    const size_t have = source->size() < 4 ? source->size() : 4;
    source->copy_to(mag, have, 0);
    if (memcmp(mag, "TICI", have) != 0) {
        return ParseResult::make(ParseError::TRY_OTHERS);
    }
    if (s->transport() != nullptr) {
        // Already upgraded: "TICI" can only be payload of another protocol.
        return ParseResult::make(ParseError::TRY_OTHERS);
    }
    if (source->size() < sizeof(HandshakeRequest)) {
        return ParseResult::make(ParseError::NOT_ENOUGH_DATA);
    }
    auto* msg = new IciHandshakeMessage;
    source->cutn(&msg->req, sizeof(msg->req));
    return ParseResult::make_ok(msg);
}

void ProcessIciHandshake(InputMessageBase* msg_base) {
    std::unique_ptr<IciHandshakeMessage> msg(
        (IciHandshakeMessage*)msg_base);
    SocketUniquePtr s = SocketUniquePtr::FromId(msg->socket_id);
    if (!s) return;
    HandshakeRequest& req = msg->req;
    req.pool_name[sizeof(req.pool_name) - 1] = '\0';
    req.link_name[sizeof(req.link_name) - 1] = '\0';

    HandshakeResponse rsp;
    memset(&rsp, 0, sizeof(rsp));
    memcpy(rsp.magic, "TICJ", 4);

    void* ctrl_mem = nullptr;
    bool pool_acquired = false;
    PeerPool pp{nullptr, 0};
    int err = 0;
    do {
        if (req.version != shm_internal::kIciHandshakeVersion ||
            req.link_size != sizeof(ShmLinkCtrl) ||
            !shm_internal::valid_shm_name(req.link_name)) {
            err = TERR_REQUEST;  // version/ABI mismatch or bad shm name
            break;
        }
        // Lazily give this process a registered pool if the server didn't.
        IciBlockPool::Init();
        if (IciBlockPool::shm_name()[0] == '\0') {
            err = ENOMEM;
            break;
        }
        // Map the client's control segment + registered memory.
        const int cfd = shm_open(req.link_name, O_RDWR, 0);
        if (cfd < 0) {
            err = errno != 0 ? errno : ENOENT;
            break;
        }
        struct stat st;
        if (fstat(cfd, &st) != 0 ||
            (size_t)st.st_size < sizeof(ShmLinkCtrl)) {
            close(cfd);
            err = TERR_REQUEST;
            break;
        }
        ctrl_mem = mmap(nullptr, sizeof(ShmLinkCtrl),
                        PROT_READ | PROT_WRITE, MAP_SHARED, cfd, 0);
        close(cfd);
        if (ctrl_mem == MAP_FAILED) {
            ctrl_mem = nullptr;
            err = errno != 0 ? errno : ENOMEM;
            break;
        }
        if (((ShmLinkCtrl*)ctrl_mem)->magic != ShmLinkCtrl::kMagic) {
            err = TERR_REQUEST;
            break;
        }
        std::atomic_thread_fence(std::memory_order_acquire);
        if (shm_internal::AcquirePeerPool(req.pool_name, req.pool_size,
                                          req.pool_epoch, &pp) != 0) {
            err = errno != 0 ? errno : ENOENT;
            break;
        }
        pool_acquired = true;
    } while (false);

    if (err != 0) {
        LOG(WARNING) << "ICI handshake from "
                     << endpoint2str(s->remote_side())
                     << " rejected: " << terror(err);
        if (ctrl_mem != nullptr) munmap(ctrl_mem, sizeof(ShmLinkCtrl));
        if (pool_acquired) shm_internal::ReleasePeerPool(req.pool_name);
        rsp.status = (uint32_t)err;
        write_all_timeout(s->fd(), &rsp, sizeof(rsp), 1000);
        s->SetFailedWithError(err);
        return;
    }

    // Install the data plane BEFORE replying: once the client sees the
    // response it may immediately post descriptors + doorbells, and those
    // doorbell bytes must be drained by Pump, not parsed as a protocol.
    ShmIciEndpoint* ep = ShmIciEndpoint::Create(
        s->fd(), ctrl_mem, sizeof(ShmLinkCtrl), /*is_client=*/false,
        req.pool_name, pp, s->remote_side());
    s->InstallTransport(ep);
    // Descriptor scope: this connection may reference exactly the pool
    // its handshake mapped.
    s->set_peer_pool_id(pool_registry::IdFromName(req.pool_name));
    snprintf(rsp.pool_name, sizeof(rsp.pool_name), "%s",
             IciBlockPool::shm_name());
    rsp.pool_size = IciBlockPool::shm_size();
    rsp.pool_epoch = IciBlockPool::pool_epoch();
    if (write_all_timeout(s->fd(), &rsp, sizeof(rsp), 1000) != 0) {
        s->SetFailedWithError(TERR_FAILED_SOCKET);
        return;
    }
    LOG(INFO) << "ICI link established with "
              << endpoint2str(s->remote_side()) << " (pool "
              << req.pool_name << ", " << req.pool_size << " bytes)";
}

int g_ici_hs_index = -1;

}  // namespace

void RegisterIciHandshakeProtocol() {
    if (g_ici_hs_index >= 0) return;
    Protocol p;
    p.parse = ParseIciHandshake;
    p.process = ProcessIciHandshake;
    p.name = "ici_handshake";
    g_ici_hs_index = RegisterProtocol(p);
}

int IciHandshakeProtocolIndex() { return g_ici_hs_index; }

}  // namespace tpurpc
