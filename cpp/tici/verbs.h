// One-sided verbs on leased pool windows (ISSUE 18).
//
// The descriptor plane (ISSUE 9/10) moves payloads as references, but
// every chunk still costs a remote dispatch: a handler fiber parses the
// descriptor, resolves it, and writes a response frame. "RPC Considered
// Harmful" (arXiv:1805.08430) argues DL data movement wants one-sided
// memory semantics — zero remote CPU on the data path — and the
// reference's RdmaEndpoint (src/brpc/rdma/rdma_endpoint.cpp) is the
// shape template: post work requests against registered remote memory,
// collect completions from a queue, ring a doorbell.
//
// This layer reproduces that shape on the pool/transport substrate:
//
//  - WINDOW = pool_id + epoch + (offset, len) lease carved from
//    IciBlockPool. The grantor allocates a descriptor-eligible slab,
//    pins it through block_lease (direction "win", armed against the
//    requesting link's socket), and answers a `window_grant` meta
//    exchange with the rkey-equivalent: the (window_id, pool, offset,
//    len, epoch, lease) tuple. Every guard the descriptor plane
//    already has applies unchanged — epoch fencing, crc32c, lease
//    expiry reaping, peer-death reclamation — so a stale or reclaimed
//    window answers TERR_STALE_EPOCH, never recycled bytes.
//
//  - VERBS = REMOTE_READ / REMOTE_WRITE posted by the initiator
//    against a granted window, each carrying a scatter-gather list so
//    one post covers N local blocks. On a one-sided-capable tier
//    (TransportTier.one_sided: shm_xproc/ici today) the data moves by
//    direct memcpy against the mapped pool — the handshake mapping is
//    read-only, so REMOTE_WRITE lazily re-opens the peer segment
//    O_RDWR by name (pool_registry::NameOf); the grant IS the write
//    authorization. Verb-incapable tiers (dcn/tcp) degrade to an
//    emulated two-sided exchange through wire hooks the policy layer
//    registers — same post/completion API, the seam just schedules a
//    meta frame instead of a memcpy.
//
//  - COMPLETION QUEUE = the doorbell: completions land in a per-
//    endpoint CQ the initiator polls or parks on, with exactly-once
//    arbitration — a completion is delivered only by whoever erases
//    the pending work request (wire completion vs. timeout reaper vs.
//    peer-death sweep race safely), and a bounded recent-wr_id set
//    absorbs duplicated wire completions.
//
// Failure model: a posted verb that vanishes (chaos verb_drop, peer
// death mid-flight) is reaped by its per-attempt deadline and retried
// a bounded number of times before completing TERR_RPC_TIMEDOUT; a
// window past its lease deadline is refused initiator-side BEFORE the
// grantor's reaper frees the pin (the grant carries the lease span;
// same-host CLOCK_MONOTONIC makes the comparison meaningful, and the
// reaper's -pool_lease_grace_ms covers the skew).
//
// Thread contract: plain std::mutex/condvar (fibers, Python threads
// through the C ABI, and plain test threads all post). pb-free: links
// into the standalone ASan/UBSan suite with no proto runtime.
#pragma once

#include <cstddef>
#include <cstdint>
#include <string>

#include "tbase/iobuf.h"

namespace tpurpc {
namespace verbs {

enum VerbOp {
    kRemoteRead = 1,   // window bytes -> local SGL
    kRemoteWrite = 2,  // local SGL -> window bytes
};

// Window access mode bits (grant request / validation).
enum : uint32_t {
    kWinRead = 1u,
    kWinWrite = 2u,
};

// One scatter-gather entry: a local span the verb reads into (READ) or
// gathers from (WRITE). The memory must stay valid until the post's
// completion is delivered.
struct Sge {
    char* addr = nullptr;
    uint64_t len = 0;
};

// The initiator's handle on a granted remote window.
struct RemoteWindow {
    uint64_t window_id = 0;
    uint64_t pool_id = 0;
    uint64_t offset = 0;  // into the grantor's pool
    uint64_t length = 0;
    uint64_t epoch = 0;  // grantor pool epoch at grant time
    uint32_t mode = 0;   // kWinRead|kWinWrite
    uint64_t peer = 0;   // SocketId of the granting link (0 = loopback)
    // Initiator-side refusal fence: posts after this monotonic instant
    // complete TERR_STALE_EPOCH locally (the grantor's reaper may free
    // the pin any time after; its grace period covers the skew).
    int64_t deadline_us = 0;
};

struct Completion {
    uint64_t wr_id = 0;
    int status = 0;  // 0 = ok, else TERR_* (stale/timeout/failed socket)
    uint64_t bytes = 0;
    int op = 0;  // VerbOp
};

// Doorbell completion queue: one per initiating endpoint (or per
// collective lane). Push-side arbitration is exactly-once; the
// consumer either polls opportunistically or parks a fiber/thread.
class CompletionQueue {
public:
    CompletionQueue();
    ~CompletionQueue();
    CompletionQueue(const CompletionQueue&) = delete;
    CompletionQueue& operator=(const CompletionQueue&) = delete;

    // Non-blocking: true + one completion when one is ready (chaos
    // doorbell_delay may hold entries back; they become visible once
    // their delay elapses). Drives the pending-post reaper as a side
    // effect, so a dropped verb's retry/timeout needs no extra thread.
    bool Poll(Completion* out);

    // Blocking poll: parks up to timeout_us (<0 = forever). False on
    // timeout or shutdown. Each wait that actually parks bumps
    // rpc_verbs_cq_parks.
    bool Park(Completion* out, int64_t timeout_us);

    // Wake every parked waiter; subsequent Parks return false
    // immediately. Pending posts routed here still complete (Poll
    // after shutdown drains them).
    void Shutdown();

    size_t depth();  // entries queued (ready or delay-held)

    // Internal delivery seam (the verbs layer pushes through this; not
    // a consumer API). Dedupes by wr_id against a bounded recent set;
    // ready_at_us > now holds the entry back (chaos doorbell_delay).
    void Push(const Completion& c, int64_t ready_at_us);

private:
    struct Impl;
    Impl* impl_;
};

// ---- grantor side ----

// Wire-facing grant fields (what the window_grant response carries).
struct WindowInfo {
    uint64_t window_id = 0;
    uint64_t pool_id = 0;
    uint64_t offset = 0;
    uint64_t length = 0;
    uint64_t epoch = 0;
    uint32_t mode = 0;
    int64_t lease_ms = 0;
};

// Carve + pin + arm a window for `peer_key` (the requesting link's
// SocketId; 0 for in-process tests). Returns 0 and fills *out, or
// TERR_OVERLOAD when the pool cannot serve the slab. lease_ms <= 0
// applies the default (-verbs_lease_default_ms).
int GrantWindow(uint64_t peer_key, uint64_t length, uint32_t mode,
                int64_t lease_ms, WindowInfo* out);

// Release a granted window (idempotent; the lease release is
// exactly-once underneath). True when this call dropped it.
bool CloseWindow(uint64_t window_id);

// Validate + resolve a local window span for an incoming wire verb or
// a doorbell apply: window exists, lease alive, `wire_epoch` matches
// both the grant and the CURRENT pool epoch, bounds hold, `need` mode
// granted. Returns 0 and sets *ptr, TERR_STALE_EPOCH on any
// staleness/reclamation (counted in rpc_verbs_stale_rejects), or
// TERR_REQUEST on bounds/mode violations.
int WindowPtr(uint64_t window_id, uint64_t offset, uint64_t len,
              uint64_t wire_epoch, uint32_t need, char** ptr);

// Peer-death reclamation: drop every window granted to `peer_key` and
// fail (TERR_FAILED_SOCKET) every pending post / grant wait against
// it. Called from the same socket-failure observer that already runs
// block_lease::ReleasePeer.
void OnPeerDead(uint64_t peer_key);

// ---- initiator side ----

// Ask `sid` for a window of `length` bytes (blocking, timeout_ms).
// Returns 0 and fills *out, or TERR_* (timeout / refusal / no sender
// hook registered).
int RequestWindow(uint64_t sid, uint64_t length, uint32_t mode,
                  int64_t timeout_ms, RemoteWindow* out);

// Post one verb. wr_id must be unique process-wide among pending
// posts (TERR_REQUEST otherwise); sgl spans must stay valid until the
// completion is delivered into *cq. Returns 0 when the post was
// accepted (the outcome arrives as a Completion), TERR_REQUEST for
// malformed posts (bad sgl, length overflow, wrong mode, sgl_max
// exceeded). A window past its deadline still accepts the post — the
// completion carries TERR_STALE_EPOCH.
int PostRead(CompletionQueue* cq, uint64_t wr_id, const RemoteWindow& w,
             uint64_t window_off, Sge* sgl, uint32_t nsge);
int PostWrite(CompletionQueue* cq, uint64_t wr_id, const RemoteWindow& w,
              uint64_t window_off, const Sge* sgl, uint32_t nsge);

// ---- policy wiring (hooks; pb lives above this layer) ----

// Send a window_grant REQUEST on `sid`; `token` correlates the
// response back into HandleGrantResponse. Returns 0 when queued.
void SetGrantRequestSender(int (*fn)(uint64_t sid, uint64_t token,
                                     uint64_t length, uint32_t mode,
                                     int64_t lease_ms));

// Send one emulated wire verb on `sid` (payload = gathered WRITE
// bytes + its crc32c; empty for READ). Returns 0 when queued.
void SetVerbWireSender(int (*fn)(uint64_t sid, int op, uint64_t wr_id,
                                 uint64_t window_id, uint64_t offset,
                                 uint64_t len, uint64_t epoch,
                                 uint32_t crc, const IOBuf& payload));

// May verbs move data DIRECTLY (memcpy against the mapped pool) on
// this socket? The policy layer answers with the transport tier's
// one_sided bit. Unregistered (unit tests): direct whenever the pool
// resolves locally.
void SetOneSidedProbe(bool (*fn)(uint64_t sid));
// Max SGL entries the socket's tier accepts (0 = emulate-only caller
// should split). Unregistered: kDefaultSglMax.
void SetSglMaxProbe(uint32_t (*fn)(uint64_t sid));

// Inbound dispatch (called by the policy layer):
// grant REQUEST arrived on `sid` -> grant + fill *out; returns status
// for the response.
int HandleGrantRequest(uint64_t sid, uint64_t length, uint32_t mode,
                       int64_t lease_ms, WindowInfo* out);
// grant RESPONSE arrived: wake the RequestWindow waiter.
void HandleGrantResponse(uint64_t token, int status,
                         const WindowInfo& info);
// Emulated wire verb arrived at the TARGET: validates via WindowPtr,
// applies WRITE payload (crc-checked) or fills *out with READ bytes
// (+ *out_crc). Returns the status the completion frame should carry.
int HandleWireVerb(int op, uint64_t wr_id, uint64_t window_id,
                   uint64_t offset, uint64_t len, uint64_t epoch,
                   uint32_t crc, const IOBuf& payload, IOBuf* out,
                   uint32_t* out_crc);
// Wire completion arrived back at the INITIATOR.
void HandleWireCompletion(uint64_t wr_id, int status,
                          const IOBuf& payload, uint32_t crc);

// Default/bounds.
enum : uint32_t { kDefaultSglMax = 16 };

// ---- observability ----
// rpc_verbs_{posted,completed,bytes,stale_rejects,cq_parks} tvars.
void ExposeVars();
int64_t posted();
int64_t completed();
int64_t bytes_moved();
int64_t stale_rejects();
int64_t cq_parks();
size_t window_count();   // live granted windows
size_t pending_posts();  // posts awaiting completion
// "window <id> len=.. mode=.. peer=.. deadline_in_ms=.." lines + the
// counter block (the /pools verbs section).
std::string DebugString();

}  // namespace verbs
}  // namespace tpurpc
