#include "tici/ici_link.h"

#include <sys/eventfd.h>
#include <unistd.h>

#include <cerrno>
#include <cstring>

#include "tbase/logging.h"
#include "tfiber/butex.h"

namespace tpurpc {

using ici_internal::Pipe;

// ---------------- link ----------------

IciLink::IciLink() {
    a_.link_ = this;
    b_.link_ = this;
    a_.out_ = &ab_;
    a_.in_ = &ba_;
    b_.out_ = &ba_;
    b_.in_ = &ab_;
    a_.peer_ = &b_;
    b_.peer_ = &a_;
    a_.evfd_ = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
    b_.evfd_ = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
    a_.writable_butex_ = butex_create();
    b_.writable_butex_ = butex_create();
}

IciLink::~IciLink() {
    a_.Close();
    b_.Close();
    // Drain any refs still parked in the rings (posted but never
    // consumed): each producer frees its own unreleased descriptors.
    for (IciEndpoint* e : {&a_, &b_}) {
        Pipe* p = e->out_;
        const uint64_t head = p->head.load(std::memory_order_acquire);
        const uint64_t from = p->released.load(std::memory_order_acquire);
        for (uint64_t i = from; i < head; ++i) {
            p->ring[i % Pipe::kDepth].block->dec_ref();
        }
        p->released.store(head, std::memory_order_release);
    }
    if (a_.evfd_ >= 0) close(a_.evfd_);
    if (b_.evfd_ >= 0) close(b_.evfd_);
    butex_destroy(a_.writable_butex_);
    butex_destroy(b_.writable_butex_);
}

// ---------------- endpoint ----------------

bool IciEndpoint::Established() const {
    return !out_->closed.load(std::memory_order_acquire) &&
           !in_->closed.load(std::memory_order_acquire);
}

void IciEndpoint::ReleaseCompleted() {
    Pipe* p = out_;
    // Single claimer: the writer fiber and the pump fiber both call this
    // concurrently. The loser simply skips — the holder is about to free
    // the same range, and `released` (hence producer credits) only
    // advances AFTER the dec_refs are done, so no slot is reused while
    // its old block pointer is pending.
    bool expected = false;
    if (!p->releasing.compare_exchange_strong(expected, true,
                                              std::memory_order_acquire)) {
        return;
    }
    const uint64_t consumed = p->tail.load(std::memory_order_acquire);
    const uint64_t from = p->released.load(std::memory_order_relaxed);
    for (uint64_t i = from; i < consumed; ++i) {
        p->ring[i % Pipe::kDepth].block->dec_ref();
    }
    p->released.store(consumed, std::memory_order_release);
    p->releasing.store(false, std::memory_order_release);
}

ssize_t IciEndpoint::CutFromIOBufList(IOBuf* const* pieces, size_t count) {
    if (out_->closed.load(std::memory_order_acquire) ||
        in_->closed.load(std::memory_order_acquire)) {
        errno = EPIPE;
        return -1;
    }
    ReleaseCompleted();
    Pipe* p = out_;
    uint64_t head = p->head.load(std::memory_order_relaxed);
    // Reuse bounded by RELEASED slots (see Pipe::credits): slots in
    // [released, tail) still hold owned block pointers.
    const uint64_t limit =
        p->released.load(std::memory_order_acquire) + Pipe::kDepth;
    ssize_t posted = 0;
    size_t pending_bytes = 0;
    for (size_t i = 0; i < count; ++i) pending_bytes += pieces[i]->size();
    if (pending_bytes == 0) {
        return 0;  // all-empty pieces: match writev-on-empty so the
                   // caller's drop loop advances instead of livelocking
    }
    for (size_t i = 0; i < count && head < limit; ++i) {
        IOBuf* buf = pieces[i];
        while (head < limit && !buf->empty()) {
            IOBuf::BlockRef ref;
            if (!buf->cut_front_ref(&ref)) break;
            Pipe::Desc& d = p->ring[head % Pipe::kDepth];
            d.block = ref.block;  // ref ownership moves into the ring
            d.offset = ref.offset;
            d.length = ref.length;
            ++head;
            posted += ref.length;
        }
    }
    if (posted == 0) {
        errno = EAGAIN;  // real back-pressure: window full
        return -1;
    }
    p->head.store(head, std::memory_order_release);
    // Doorbell: suppressed unless the peer armed it (event suppression,
    // pillar 3). The arm flag for the peer's reads of this pipe lives on
    // the pipe itself.
    if (p->rx_armed.exchange(false, std::memory_order_acq_rel)) {
        uint64_t one = 1;
        ssize_t r = write(peer_->evfd_, &one, sizeof(one));
        (void)r;
        signals_sent_.fetch_add(1, std::memory_order_relaxed);
    }
    return posted;
}

int IciEndpoint::WaitWritable(int64_t abstime_us) {
    Pipe* p = out_;
    std::atomic<int>* word = butex_word(writable_butex_);
    const int expected = word->load(std::memory_order_acquire);
    // Tell the consumer to ring our doorbell when it consumes, then
    // re-check credits (the consume may have happened in between).
    p->tx_waiting.store(true, std::memory_order_release);
    // Fold already-consumed slots into `released` before the credit
    // re-check: credits() reads the producer-side `released` counter,
    // which only advances here — a consume that landed between the last
    // release pass and the tx_waiting store above produced no doorbell
    // (tx_waiting was still false), and without this the writer parks for
    // the whole wait despite free credits.
    ReleaseCompleted();
    if (p->credits() > 0 || p->closed.load(std::memory_order_acquire) ||
        in_->closed.load(std::memory_order_acquire)) {
        p->tx_waiting.store(false, std::memory_order_release);
        return 0;
    }
    butex_wait(writable_butex_, expected, &abstime_us);
    p->tx_waiting.store(false, std::memory_order_release);
    // Timeout is NOT fatal — same contract as the fd path's WaitEpollOut
    // (a server stalled past the wait window must not kill the link, it
    // just re-arms and waits again). Only a closed link is an error.
    return Established() ? 0 : -1;
}

ssize_t IciEndpoint::Pump(IOPortal* dst) {
    // Drain our doorbell so the edge re-arms at the eventfd level.
    uint64_t junk;
    while (read(evfd_, &junk, sizeof(junk)) > 0) {
    }
    // Send-side completions: free refs the peer consumed and wake any
    // writer parked on the window (waiters re-check credits, so a
    // spurious wake is harmless and cheaper than exact bookkeeping).
    ReleaseCompleted();
    butex_word(writable_butex_)->fetch_add(1, std::memory_order_release);
    butex_wake_all(writable_butex_);

    // Receive side: "DMA" pending descriptors into dst (pillar: the copy
    // happens once, at the target, like the interconnect engine).
    Pipe* p = in_;
    ssize_t received = 0;
    while (true) {
        uint64_t tail = p->tail.load(std::memory_order_relaxed);
        const uint64_t head = p->head.load(std::memory_order_acquire);
        if (tail == head) {
            if (p->closed.load(std::memory_order_acquire) && received == 0) {
                return 0;  // EOF
            }
            if (received > 0) return received;
            // Arm the doorbell, then re-check (a post may have raced the
            // arm; without the re-check it would be silently lost).
            p->rx_armed.store(true, std::memory_order_seq_cst);
            if (p->head.load(std::memory_order_seq_cst) != tail ||
                p->closed.load(std::memory_order_acquire)) {
                continue;
            }
            errno = EAGAIN;
            return -1;
        }
        while (tail != head) {
            const Pipe::Desc& d = p->ring[tail % Pipe::kDepth];
            // Zero-copy receive: same address space, so the "DMA" is a
            // reference — append_ref takes its own block ref (the
            // parser's cutn then moves pointers, never bytes). The
            // producer's ring ref releases independently via `released`;
            // disjoint byte ranges make concurrent tail-appends to a
            // shared TLS block benign. The cross-process shm link keeps
            // the copy (separate address spaces = a real transfer).
            dst->append_ref({d.offset, d.length, d.block});
            received += d.length;
            ++tail;
            p->tail.store(tail, std::memory_order_release);
        }
        // Consumed -> credits freed: ring the producer's doorbell if it
        // parked (piggybacked-ACK wakeup).
        if (p->tx_waiting.load(std::memory_order_acquire)) {
            uint64_t one = 1;
            ssize_t r = write(peer_->evfd_, &one, sizeof(one));
            (void)r;
            butex_word(peer_->writable_butex_)
                ->fetch_add(1, std::memory_order_release);
            butex_wake_all(peer_->writable_butex_);
        }
    }
}

void IciEndpoint::Release() {
    Close();
    link_->EndpointReleased();
}

void IciLink::EndpointReleased() {
    if (live_endpoints_.fetch_sub(1, std::memory_order_acq_rel) == 1) {
        delete this;
    }
}

void IciEndpoint::Close() {
    if (!out_->closed.exchange(true, std::memory_order_acq_rel)) {
        in_->closed.store(true, std::memory_order_release);
        // Wake the peer's pump (EOF) and any of our parked writers.
        uint64_t one = 1;
        ssize_t r = write(peer_->evfd_, &one, sizeof(one));
        (void)r;
        r = write(evfd_, &one, sizeof(one));
        (void)r;
        butex_word(writable_butex_)->fetch_add(1, std::memory_order_release);
        butex_wake_all(writable_butex_);
        butex_word(peer_->writable_butex_)
            ->fetch_add(1, std::memory_order_release);
        butex_wake_all(peer_->writable_butex_);
    }
}

}  // namespace tpurpc
