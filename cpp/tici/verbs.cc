#include "tici/verbs.h"

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <set>
#include <vector>

#include "tbase/crc32c.h"
#include "tbase/errno.h"
#include "tbase/flags.h"
#include "tbase/flight_recorder.h"
#include "tbase/logging.h"
#include "tbase/time.h"
#include "tici/block_lease.h"
#include "tici/block_pool.h"
#include "tnet/fault_injection.h"
#include "tvar/reducer.h"

DEFINE_int64(verbs_lease_default_ms, 10000,
             "default lease span of a granted verb window when the "
             "grant request names none; the grantor's reaper frees the "
             "pin after this + -pool_lease_grace_ms");
DEFINE_int64(verbs_post_timeout_ms, 500,
             "per-attempt deadline of a posted verb: a post whose "
             "completion has not arrived (chaos verb_drop, lost wire "
             "frame, dead peer) is retried after this long");
DEFINE_int64(verbs_post_retries, 3,
             "attempts per posted verb before it completes "
             "TERR_RPC_TIMEDOUT");

namespace tpurpc {
namespace verbs {

namespace {

static LazyAdder g_posted("rpc_verbs_posted");
static LazyAdder g_completed("rpc_verbs_completed");
static LazyAdder g_bytes("rpc_verbs_bytes");
static LazyAdder g_stale("rpc_verbs_stale_rejects");
static LazyAdder g_parks("rpc_verbs_cq_parks");

// Initiator-side margin subtracted from a grant's lease span: a post
// inside the margin is refused locally, well before the grantor's
// reaper (deadline + grace) could free the pin under it.
constexpr int64_t kDeadlineMarginUs = 20 * 1000;

uint32_t CrcIOBuf(const IOBuf& b) {
    uint32_t crc = 0;
    for (size_t i = 0; i < b.backing_block_num(); ++i) {
        size_t len = 0;
        const char* d = b.backing_block_data(i, &len);
        crc = crc32c_extend(crc, d, len);
    }
    return crc;
}

// ---- grantor state ----

struct Window {
    uint64_t lease = 0;  // block_lease id (also the window_id)
    char* data = nullptr;
    uint64_t pool_off = 0;
    uint64_t len = 0;
    uint32_t mode = 0;
    uint64_t epoch = 0;  // pool epoch at grant
    uint64_t peer = 0;
};

// ---- initiator state ----

struct GrantWait {
    std::condition_variable cv;
    bool done = false;
    int status = TERR_RPC_TIMEDOUT;
    WindowInfo info;
    uint64_t sid = 0;
};

struct PendingWr {
    CompletionQueue* cq = nullptr;
    int op = 0;
    RemoteWindow w;
    uint64_t window_off = 0;
    std::vector<Sge> sgl;
    uint64_t total = 0;
    int64_t deadline_us = 0;  // this attempt's reap instant
    int attempts = 0;
};

// Writable remap of a peer pool for direct REMOTE_WRITE: the handshake
// mapping is PROT_READ, so the first write against a granted window
// re-opens the segment O_RDWR by name. Keyed by pool id; re-mapped
// when the registry epoch moved (owner restart = new segment bytes).
struct WritableMap {
    char* base = nullptr;
    size_t size = 0;
    uint64_t epoch = 0;
};

struct VerbsStateImpl {
    std::mutex mu;
    std::condition_variable cv;  // shared by GrantWait parks
    std::map<uint64_t, Window> windows;
    std::map<uint64_t, GrantWait*> grant_waits;  // token -> waiter
    std::map<uint64_t, PendingWr> pending;       // wr_id -> post
    std::map<uint64_t, WritableMap> writable;    // pool_id -> RW remap
    std::atomic<uint64_t> next_token{1};

    int (*grant_sender)(uint64_t, uint64_t, uint64_t, uint32_t,
                        int64_t) = nullptr;
    int (*wire_sender)(uint64_t, int, uint64_t, uint64_t, uint64_t,
                       uint64_t, uint64_t, uint32_t,
                       const IOBuf&) = nullptr;
    bool (*one_sided_probe)(uint64_t) = nullptr;
    uint32_t (*sgl_max_probe)(uint64_t) = nullptr;
};

// Immortal (same teardown rationale as the pool registry: completions
// may land from socket recycling during exit).
VerbsStateImpl& S() {
    static VerbsStateImpl* s = new VerbsStateImpl;
    return *s;
}

}  // namespace

// ---- completion queue ----

struct CompletionQueue::Impl {
    std::mutex mu;
    std::condition_variable cv;
    struct Entry {
        Completion c;
        int64_t ready_at_us = 0;  // chaos doorbell_delay holds it back
    };
    std::deque<Entry> q;
    // Bounded recent-wr_id memory absorbing duplicated wire
    // completions after the pending entry was already consumed.
    std::set<uint64_t> recent;
    std::deque<uint64_t> recent_order;
    bool shutdown = false;

    bool PushLocked(const Completion& c, int64_t ready_at) {
        if (recent.count(c.wr_id) != 0) return false;
        recent.insert(c.wr_id);
        recent_order.push_back(c.wr_id);
        while (recent_order.size() > 1024) {
            recent.erase(recent_order.front());
            recent_order.pop_front();
        }
        q.push_back(Entry{c, ready_at});
        return true;
    }

    bool TakeReadyLocked(int64_t now, Completion* out, int64_t* next) {
        *next = 0;
        for (auto it = q.begin(); it != q.end(); ++it) {
            if (it->ready_at_us <= now) {
                *out = it->c;
                q.erase(it);
                return true;
            }
            if (*next == 0 || it->ready_at_us < *next) {
                *next = it->ready_at_us;
            }
        }
        return false;
    }
};

CompletionQueue::CompletionQueue() : impl_(new Impl) {}
CompletionQueue::~CompletionQueue() { delete impl_; }

size_t CompletionQueue::depth() {
    std::lock_guard<std::mutex> g(impl_->mu);
    return impl_->q.size();
}

void CompletionQueue::Shutdown() {
    std::lock_guard<std::mutex> g(impl_->mu);
    impl_->shutdown = true;
    impl_->cv.notify_all();
}

void CompletionQueue::Push(const Completion& c, int64_t ready_at_us) {
    std::lock_guard<std::mutex> g(impl_->mu);
    if (impl_->PushLocked(c, ready_at_us)) impl_->cv.notify_all();
}

namespace {

// Deliver a completion into its CQ with exactly-once arbitration: the
// caller must already own the pending erase (or be an inline direct
// completion that never pended). Consults chaos kCqComplete — a
// delayed doorbell parks pollers instead of sleeping the deliverer.
void Deliver(CompletionQueue* cq, const Completion& c) {
    int64_t ready_at = 0;
    if (__builtin_expect(fault_injection_enabled(), 0)) {
        const FaultAction a = FaultInjection::Decide(
            FaultOp::kCqComplete, EndPoint(), (size_t)c.bytes);
        if (a.kind == FaultAction::kDelay) {
            ready_at = monotonic_time_us() + a.delay_us;
        }
    }
    *g_completed << 1;
    if (c.status == 0) *g_bytes << (int64_t)c.bytes;
    cq->Push(c, ready_at);
}

// Forward decl: Poll/Park drive the reaper.
void ReapPendingPosts(int64_t now);

int ExecutePending(uint64_t wr_id);

}  // namespace

bool CompletionQueue::Poll(Completion* out) {
    const int64_t now = monotonic_time_us();
    ReapPendingPosts(now);
    std::lock_guard<std::mutex> g(impl_->mu);
    int64_t next = 0;
    return impl_->TakeReadyLocked(now, out, &next);
}

bool CompletionQueue::Park(Completion* out, int64_t timeout_us) {
    const int64_t start = monotonic_time_us();
    const int64_t park_deadline =
        timeout_us < 0 ? 0 : start + timeout_us;
    bool counted = false;
    for (;;) {
        const int64_t now = monotonic_time_us();
        ReapPendingPosts(now);
        std::unique_lock<std::mutex> lk(impl_->mu);
        int64_t next_ready = 0;
        if (impl_->TakeReadyLocked(now, out, &next_ready)) return true;
        if (impl_->shutdown) return false;
        if (park_deadline != 0 && now >= park_deadline) return false;
        if (!counted) {
            *g_parks << 1;
            counted = true;
        }
        // Wake for: a push, the earliest delay-held entry maturing, the
        // park deadline, or the next pending-post reap tick — bounded
        // so a dropped verb's retry fires without a dedicated thread.
        int64_t wake = now + FLAGS_verbs_post_timeout_ms.get() * 1000;
        if (next_ready != 0 && next_ready < wake) wake = next_ready;
        if (park_deadline != 0 && park_deadline < wake) {
            wake = park_deadline;
        }
        impl_->cv.wait_for(lk, std::chrono::microseconds(wake - now));
    }
}

// ---- grantor side ----

int GrantWindow(uint64_t peer_key, uint64_t length, uint32_t mode,
                int64_t lease_ms, WindowInfo* out) {
    if (length == 0 || out == nullptr ||
        (mode & (kWinRead | kWinWrite)) == 0) {
        return TERR_REQUEST;
    }
    IOBuf buf;
    char* data = nullptr;
    if (!IciBlockPool::AllocatePoolAttachment((size_t)length, &buf,
                                              &data)) {
        return TERR_OVERLOAD;  // pool dry / length above slab classes
    }
    uint64_t off = 0;
    if (!IciBlockPool::OffsetOf(data, &off)) {
        return TERR_OVERLOAD;
    }
    if (lease_ms <= 0) lease_ms = FLAGS_verbs_lease_default_ms.get();
    const uint64_t lease = block_lease::Pin(std::move(buf), "win");
    const int64_t deadline = monotonic_time_us() + lease_ms * 1000;
    // The arm is the liveness registration: the reaper and peer-death
    // reclamation free the pin through the SAME lease machinery the
    // descriptor plane uses (call id = window id for the ledger).
    block_lease::Arm(lease, lease, deadline, peer_key);
    VerbsStateImpl& s = S();
    Window w;
    w.lease = lease;
    w.data = data;
    w.pool_off = off;
    w.len = length;
    w.mode = mode;
    w.epoch = IciBlockPool::pool_epoch();
    w.peer = peer_key;
    {
        std::lock_guard<std::mutex> g(s.mu);
        s.windows[lease] = w;
    }
    out->window_id = lease;
    out->pool_id = IciBlockPool::pool_id();
    out->offset = off;
    out->length = length;
    out->epoch = w.epoch;
    out->mode = mode;
    out->lease_ms = lease_ms;
    return 0;
}

bool CloseWindow(uint64_t window_id) {
    VerbsStateImpl& s = S();
    uint64_t lease = 0;
    {
        std::lock_guard<std::mutex> g(s.mu);
        auto it = s.windows.find(window_id);
        if (it == s.windows.end()) return false;
        lease = it->second.lease;
        s.windows.erase(it);
    }
    block_lease::Release(lease);
    return true;
}

int WindowPtr(uint64_t window_id, uint64_t offset, uint64_t len,
              uint64_t wire_epoch, uint32_t need, char** ptr) {
    VerbsStateImpl& s = S();
    std::lock_guard<std::mutex> g(s.mu);
    auto it = s.windows.find(window_id);
    if (it == s.windows.end()) {
        *g_stale << 1;  // reclaimed/unknown: never recycled bytes
        return TERR_STALE_EPOCH;
    }
    Window& w = it->second;
    if (!block_lease::Alive(w.lease)) {
        // The reaper or peer-death sweep beat us: the slab may already
        // be recycled into another call's payload.
        s.windows.erase(it);
        *g_stale << 1;
        return TERR_STALE_EPOCH;
    }
    if (wire_epoch != w.epoch ||
        w.epoch != IciBlockPool::pool_epoch()) {
        *g_stale << 1;
        return TERR_STALE_EPOCH;
    }
    if ((w.mode & need) != need) return TERR_REQUEST;
    if (len == 0 || offset > w.len || len > w.len - offset) {
        return TERR_REQUEST;
    }
    if (ptr != nullptr) *ptr = w.data + offset;
    return 0;
}

// ---- initiator helpers ----

namespace {

uint64_t SglTotal(const Sge* sgl, uint32_t nsge) {
    uint64_t t = 0;
    for (uint32_t i = 0; i < nsge; ++i) {
        if (sgl[i].addr == nullptr || sgl[i].len == 0) return 0;
        t += sgl[i].len;
    }
    return t;
}

// Resolve the window's pool for DIRECT access. Returns the span base
// (already offset to the window) or null; *stale set when the mapping
// exists but its generation moved (the caller completes
// TERR_STALE_EPOCH instead of degrading to the wire).
char* DirectBase(const RemoteWindow& w, bool writable, bool* stale) {
    *stale = false;
    const char* base = nullptr;
    size_t size = 0;
    uint64_t ep = 0;
    if (!pool_registry::Resolve(w.pool_id, &base, &size, &ep)) {
        return nullptr;
    }
    if (ep != w.epoch) {
        *stale = true;
        return nullptr;
    }
    if (w.offset + w.length > size) {
        *stale = true;
        return nullptr;
    }
    if (!writable) return const_cast<char*>(base) + w.offset;
    // Writes against our OWN pool use the Init-time RW mapping.
    if (w.pool_id == IciBlockPool::pool_id()) {
        return IciBlockPool::shm_base() + w.offset;
    }
    // Peer pool: the handshake mapping is PROT_READ — re-open the
    // segment O_RDWR by name (the grant is the authorization; same-
    // user shm). Cached per pool, invalidated when the registry epoch
    // moves (owner restart = different segment bytes).
    VerbsStateImpl& s = S();
    std::lock_guard<std::mutex> g(s.mu);
    auto it = s.writable.find(w.pool_id);
    if (it != s.writable.end() && it->second.epoch == ep &&
        it->second.size >= w.offset + w.length) {
        return it->second.base + w.offset;
    }
    char name[128];
    if (!pool_registry::NameOf(w.pool_id, name, sizeof(name))) {
        return nullptr;
    }
    const int fd = shm_open(name, O_RDWR, 0);
    if (fd < 0) return nullptr;
    struct stat st;
    if (fstat(fd, &st) != 0 || (size_t)st.st_size < size) {
        close(fd);
        return nullptr;
    }
    void* mem =
        mmap(nullptr, size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    close(fd);
    if (mem == MAP_FAILED) return nullptr;
    if (it != s.writable.end()) {
        munmap(it->second.base, it->second.size);
        s.writable.erase(it);
    }
    s.writable[w.pool_id] = WritableMap{(char*)mem, size, ep};
    return (char*)mem + w.offset;
}

bool DirectAllowed(const RemoteWindow& w) {
    VerbsStateImpl& s = S();
    bool (*probe)(uint64_t) = s.one_sided_probe;
    // Loopback grants (peer 0: in-process tests, local lanes) always
    // may touch the local mapping; real links defer to the transport
    // tier's one_sided bit when the policy registered the probe.
    if (w.peer == 0) return true;
    if (probe != nullptr) return probe(w.peer);
    return true;
}

// Finish wr_id with `status` if still pending: erase-then-deliver (the
// erase is the exactly-once arbitration point). `payload` scatters
// into the READ sgl on success.
void CompletePending(uint64_t wr_id, int status, const IOBuf* payload) {
    VerbsStateImpl& s = S();
    PendingWr e;
    {
        std::lock_guard<std::mutex> g(s.mu);
        auto it = s.pending.find(wr_id);
        if (it == s.pending.end()) return;  // lost the race: delivered
        e = std::move(it->second);
        s.pending.erase(it);
    }
    if (status == 0 && e.op == kRemoteRead && payload != nullptr) {
        size_t pos = 0;
        for (const Sge& sg : e.sgl) {
            payload->copy_to(sg.addr, (size_t)sg.len, pos);
            pos += (size_t)sg.len;
        }
    }
    Completion c;
    c.wr_id = wr_id;
    c.status = status;
    c.bytes = status == 0 ? e.total : 0;
    c.op = e.op;
    flight::Record(flight::kVerbComplete, wr_id, (uint64_t)(uint32_t)status);
    Deliver(e.cq, c);
}

// One attempt of a pending post against a SNAPSHOT of the entry (no
// lock held: the memcpy/wire send must not serialize every post):
// direct memcpy when the tier allows and the mapping is current, else
// the emulated wire path. Chaos kVerbPost may make the attempt vanish
// (the per-attempt deadline retries it). Returns 1 when in flight on
// the wire; 0 otherwise, with *terminal_status >= 0 when the attempt
// reached a verdict.
int ExecuteAttempt(PendingWr* e, uint64_t wr_id, int* terminal_status) {
    const int64_t now = monotonic_time_us();
    if (e->w.deadline_us != 0 &&
        now > e->w.deadline_us - kDeadlineMarginUs) {
        *g_stale << 1;
        *terminal_status = TERR_STALE_EPOCH;
        return 0;
    }
    if (__builtin_expect(fault_injection_enabled(), 0)) {
        const FaultAction a = FaultInjection::Decide(
            FaultOp::kVerbPost, EndPoint(), (size_t)e->total);
        if (a.kind == FaultAction::kDrop) {
            // The post vanishes in flight: no completion will arrive;
            // the per-attempt deadline reaps and retries it.
            return 0;
        }
    }
    if (DirectAllowed(e->w)) {
        bool stale = false;
        const bool writable = e->op == kRemoteWrite;
        char* base = DirectBase(e->w, writable, &stale);
        if (stale) {
            *g_stale << 1;
            *terminal_status = TERR_STALE_EPOCH;
            return 0;
        }
        if (base != nullptr) {
            char* p = base + e->window_off;
            if (e->op == kRemoteWrite) {
                for (const Sge& sg : e->sgl) {
                    memcpy(p, sg.addr, (size_t)sg.len);
                    p += sg.len;
                }
            } else {
                for (const Sge& sg : e->sgl) {
                    memcpy(sg.addr, p, (size_t)sg.len);
                    p += sg.len;
                }
            }
            *terminal_status = 0;
            return 0;
        }
        // Pool not mapped here (or RW remap failed): fall through to
        // the wire emulation — same verbs, two-sided underneath.
    }
    VerbsStateImpl& s = S();
    int (*sender)(uint64_t, int, uint64_t, uint64_t, uint64_t, uint64_t,
                  uint64_t, uint32_t, const IOBuf&) = s.wire_sender;
    if (sender == nullptr || e->w.peer == 0) {
        *terminal_status = TERR_INTERNAL;
        return 0;
    }
    IOBuf payload;
    uint32_t crc = 0;
    if (e->op == kRemoteWrite) {
        for (const Sge& sg : e->sgl) {
            payload.append(sg.addr, (size_t)sg.len);
            crc = crc32c_extend(crc, sg.addr, (size_t)sg.len);
        }
    }
    if (sender(e->w.peer, e->op, wr_id, e->w.window_id, e->window_off,
               e->total, e->w.epoch, crc, payload) != 0) {
        *terminal_status = TERR_FAILED_SOCKET;
        return 0;
    }
    return 1;  // in flight: completion (or the reaper) finishes it
}

int ExecutePending(uint64_t wr_id) {
    VerbsStateImpl& s = S();
    PendingWr snapshot;
    {
        std::lock_guard<std::mutex> g(s.mu);
        auto it = s.pending.find(wr_id);
        if (it == s.pending.end()) return 0;
        it->second.attempts++;
        it->second.deadline_us =
            monotonic_time_us() +
            FLAGS_verbs_post_timeout_ms.get() * 1000;
        snapshot = it->second;
    }
    int terminal = -1;
    const int r = ExecuteAttempt(&snapshot, wr_id, &terminal);
    if (r == 0 && terminal >= 0) CompletePending(wr_id, terminal, nullptr);
    return 0;
}

void ReapPendingPosts(int64_t now) {
    VerbsStateImpl& s = S();
    std::vector<uint64_t> retry, timed_out;
    {
        std::lock_guard<std::mutex> g(s.mu);
        for (auto& kv : s.pending) {
            if (kv.second.deadline_us > now) continue;
            if (kv.second.attempts >=
                (int)FLAGS_verbs_post_retries.get()) {
                timed_out.push_back(kv.first);
            } else {
                retry.push_back(kv.first);
            }
        }
    }
    for (uint64_t id : timed_out) {
        flight::Record(flight::kVerbReap, id, (uint64_t)TERR_RPC_TIMEDOUT);
        CompletePending(id, TERR_RPC_TIMEDOUT, nullptr);
    }
    for (uint64_t id : retry) ExecutePending(id);
}

int Post(CompletionQueue* cq, int op, uint64_t wr_id,
         const RemoteWindow& w, uint64_t window_off, const Sge* sgl,
         uint32_t nsge) {
    if (cq == nullptr || sgl == nullptr || nsge == 0 ||
        w.window_id == 0) {
        return TERR_REQUEST;
    }
    VerbsStateImpl& s = S();
    uint32_t sgl_max = kDefaultSglMax;
    if (s.sgl_max_probe != nullptr && w.peer != 0) {
        const uint32_t m = s.sgl_max_probe(w.peer);
        if (m != 0) sgl_max = m;
    }
    if (nsge > sgl_max) return TERR_REQUEST;
    const uint64_t total = SglTotal(sgl, nsge);
    if (total == 0 || window_off > w.length ||
        total > w.length - window_off) {
        return TERR_REQUEST;
    }
    const uint32_t need = op == kRemoteWrite ? kWinWrite : kWinRead;
    if ((w.mode & need) != need) return TERR_REQUEST;
    PendingWr e;
    e.cq = cq;
    e.op = op;
    e.w = w;
    e.window_off = window_off;
    e.sgl.assign(sgl, sgl + nsge);
    e.total = total;
    e.attempts = 0;
    e.deadline_us =
        monotonic_time_us() + FLAGS_verbs_post_timeout_ms.get() * 1000;
    {
        std::lock_guard<std::mutex> g(s.mu);
        if (s.pending.count(wr_id) != 0) return TERR_REQUEST;
        s.pending[wr_id] = e;
    }
    *g_posted << 1;
    flight::Record(flight::kVerbPost, wr_id,
                   ((uint64_t)(uint32_t)op << 32) | (total & 0xffffffffu));
    ExecutePending(wr_id);
    return 0;
}

}  // namespace

int PostRead(CompletionQueue* cq, uint64_t wr_id, const RemoteWindow& w,
             uint64_t window_off, Sge* sgl, uint32_t nsge) {
    return Post(cq, kRemoteRead, wr_id, w, window_off, sgl, nsge);
}

int PostWrite(CompletionQueue* cq, uint64_t wr_id, const RemoteWindow& w,
              uint64_t window_off, const Sge* sgl, uint32_t nsge) {
    return Post(cq, kRemoteWrite, wr_id, w, window_off, sgl, nsge);
}

// ---- grant exchange ----

void SetGrantRequestSender(int (*fn)(uint64_t, uint64_t, uint64_t,
                                     uint32_t, int64_t)) {
    S().grant_sender = fn;
}
void SetVerbWireSender(int (*fn)(uint64_t, int, uint64_t, uint64_t,
                                 uint64_t, uint64_t, uint64_t, uint32_t,
                                 const IOBuf&)) {
    S().wire_sender = fn;
}
void SetOneSidedProbe(bool (*fn)(uint64_t)) { S().one_sided_probe = fn; }
void SetSglMaxProbe(uint32_t (*fn)(uint64_t)) { S().sgl_max_probe = fn; }

int RequestWindow(uint64_t sid, uint64_t length, uint32_t mode,
                  int64_t timeout_ms, RemoteWindow* out) {
    if (out == nullptr || length == 0) return TERR_REQUEST;
    VerbsStateImpl& s = S();
    int (*sender)(uint64_t, uint64_t, uint64_t, uint32_t, int64_t) =
        s.grant_sender;
    if (sender == nullptr) return TERR_INTERNAL;
    const uint64_t token =
        s.next_token.fetch_add(1, std::memory_order_relaxed);
    GrantWait wait;
    wait.sid = sid;
    {
        std::lock_guard<std::mutex> g(s.mu);
        s.grant_waits[token] = &wait;
    }
    const int64_t lease_ms = FLAGS_verbs_lease_default_ms.get();
    if (sender(sid, token, length, mode, lease_ms) != 0) {
        std::lock_guard<std::mutex> g(s.mu);
        s.grant_waits.erase(token);
        return TERR_FAILED_SOCKET;
    }
    int status;
    WindowInfo info;
    {
        std::unique_lock<std::mutex> lk(s.mu);
        if (timeout_ms <= 0) timeout_ms = 1000;
        wait.cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                         [&wait] { return wait.done; });
        status = wait.done ? wait.status : TERR_RPC_TIMEDOUT;
        info = wait.info;
        s.grant_waits.erase(token);
    }
    if (status != 0) return status;
    out->window_id = info.window_id;
    out->pool_id = info.pool_id;
    out->offset = info.offset;
    out->length = info.length;
    out->epoch = info.epoch;
    out->mode = info.mode;
    out->peer = sid;
    out->deadline_us = monotonic_time_us() + info.lease_ms * 1000;
    return 0;
}

int HandleGrantRequest(uint64_t sid, uint64_t length, uint32_t mode,
                       int64_t lease_ms, WindowInfo* out) {
    return GrantWindow(sid, length, mode, lease_ms, out);
}

void HandleGrantResponse(uint64_t token, int status,
                         const WindowInfo& info) {
    VerbsStateImpl& s = S();
    std::lock_guard<std::mutex> g(s.mu);
    auto it = s.grant_waits.find(token);
    if (it == s.grant_waits.end()) return;  // waiter timed out already
    it->second->done = true;
    it->second->status = status;
    it->second->info = info;
    it->second->cv.notify_all();
}

int HandleWireVerb(int op, uint64_t wr_id, uint64_t window_id,
                   uint64_t offset, uint64_t len, uint64_t epoch,
                   uint32_t crc, const IOBuf& payload, IOBuf* out,
                   uint32_t* out_crc) {
    // Grantor-side wire event: the initiator's kVerbPost for this wr_id
    // pairs with this record in the merged cross-node timeline.
    flight::Record(flight::kVerbWire, wr_id,
                   ((uint64_t)(uint32_t)op << 32) | (len & 0xffffffffu));
    // The wire-verb resolve seam inherits the chaos pool_stale kind (the
    // same fence the descriptor resolve path injects): answer the
    // retriable stale error without touching window state, so the soak
    // proves initiators survive a fenced grantor.
    if (__builtin_expect(fault_injection_enabled(), 0)) {
        const FaultAction a = FaultInjection::Decide(
            FaultOp::kPoolResolve, EndPoint(), (size_t)len);
        if (a.kind == FaultAction::kStaleEpoch) {
            *g_stale << 1;
            return TERR_STALE_EPOCH;
        }
    }
    const uint32_t need = op == kRemoteWrite ? kWinWrite : kWinRead;
    char* p = nullptr;
    const int rc = WindowPtr(window_id, offset, len, epoch, need, &p);
    if (rc != 0) return rc;
    if (op == kRemoteWrite) {
        if (payload.size() != len) return TERR_REQUEST;
        if (CrcIOBuf(payload) != crc) return TERR_REQUEST;
        payload.copy_to(p, (size_t)len);
        return 0;
    }
    if (op != kRemoteRead || out == nullptr) return TERR_REQUEST;
    out->append(p, (size_t)len);
    if (out_crc != nullptr) *out_crc = crc32c_extend(0, p, (size_t)len);
    return 0;
}

void HandleWireCompletion(uint64_t wr_id, int status,
                          const IOBuf& payload, uint32_t crc) {
    if (status == 0 && !payload.empty() && CrcIOBuf(payload) != crc) {
        // Bytes damaged in flight: fail the post retriable.
        CompletePending(wr_id, TERR_REQUEST, nullptr);
        return;
    }
    CompletePending(wr_id, status, &payload);
}

void OnPeerDead(uint64_t peer_key) {
    if (peer_key == 0) return;
    VerbsStateImpl& s = S();
    std::vector<uint64_t> leases, posts;
    {
        std::lock_guard<std::mutex> g(s.mu);
        for (auto it = s.windows.begin(); it != s.windows.end();) {
            if (it->second.peer == peer_key) {
                leases.push_back(it->second.lease);
                it = s.windows.erase(it);
            } else {
                ++it;
            }
        }
        for (auto& kv : s.pending) {
            if (kv.second.w.peer == peer_key) posts.push_back(kv.first);
        }
        for (auto& kv : s.grant_waits) {
            if (kv.second->sid == peer_key && !kv.second->done) {
                kv.second->done = true;
                kv.second->status = TERR_FAILED_SOCKET;
                kv.second->cv.notify_all();
            }
        }
    }
    // block_lease::ReleasePeer (the caller's sibling sweep) may race
    // these releases — Release is exactly-once, both orders are safe.
    for (uint64_t l : leases) block_lease::Release(l);
    for (uint64_t id : posts) {
        CompletePending(id, TERR_FAILED_SOCKET, nullptr);
    }
}

// ---- observability ----

void ExposeVars() {
    *g_posted << 0;
    *g_completed << 0;
    *g_bytes << 0;
    *g_stale << 0;
    *g_parks << 0;
}

int64_t posted() { return (*g_posted).get_value(); }
int64_t completed() { return (*g_completed).get_value(); }
int64_t bytes_moved() { return (*g_bytes).get_value(); }
int64_t stale_rejects() { return (*g_stale).get_value(); }
int64_t cq_parks() { return (*g_parks).get_value(); }

size_t window_count() {
    VerbsStateImpl& s = S();
    std::lock_guard<std::mutex> g(s.mu);
    return s.windows.size();
}

size_t pending_posts() {
    VerbsStateImpl& s = S();
    std::lock_guard<std::mutex> g(s.mu);
    return s.pending.size();
}

std::string DebugString() {
    VerbsStateImpl& s = S();
    std::string out;
    char line[192];
    snprintf(line, sizeof(line),
             "verbs posted=%lld completed=%lld bytes=%lld "
             "stale_rejects=%lld cq_parks=%lld pending=%zu\n",
             (long long)posted(), (long long)completed(),
             (long long)bytes_moved(), (long long)stale_rejects(),
             (long long)cq_parks(), pending_posts());
    out += line;
    std::lock_guard<std::mutex> g(s.mu);
    size_t shown = 0;
    for (const auto& kv : s.windows) {
        if (++shown > 64) break;
        snprintf(line, sizeof(line),
                 "window %llu len=%llu mode=%u peer=%llu epoch=%llu\n",
                 (unsigned long long)kv.first,
                 (unsigned long long)kv.second.len, kv.second.mode,
                 (unsigned long long)kv.second.peer,
                 (unsigned long long)kv.second.epoch);
        out += line;
    }
    return out;
}

}  // namespace verbs
}  // namespace tpurpc
