#include "tici/block_lease.h"

#include <atomic>
#include <chrono>
#include <map>
#include <mutex>
#include <thread>
#include <vector>

#include "tbase/flags.h"
#include "tbase/flight_recorder.h"
#include "tbase/logging.h"
#include "tbase/time.h"
#include "tvar/reducer.h"

DEFINE_int64(pool_lease_default_ms, 30000,
             "pin lifetime for pool-descriptor blocks whose RPC carries "
             "no deadline; the reaper reclaims older pins");
DEFINE_int64(pool_lease_grace_ms, 2000,
             "slack added to an RPC's propagated deadline before its "
             "pinned block is reapable (EndRPC normally releases first; "
             "the reaper is the backstop for wedged calls)");
DEFINE_int64(pool_lease_reap_ms, 200,
             "expiry-reaper scan interval for pinned pool blocks");

namespace tpurpc {
namespace block_lease {

namespace {

struct Lease {
    IOBuf pinned;        // the one ref keeping the slab slot alive
    // Ledger direction: "req" (client request pin, EndRPC releases) or
    // "rsp" (server response pin, the client's desc_ack releases).
    const char* direction = "req";
    uint64_t call_id = 0;
    // Always > 0: Pin stamps now + -pool_lease_default_ms so even a
    // lease whose owner dies before Arm is reapable (no unreapable
    // state exists); Arm tightens it to the RPC deadline + grace.
    int64_t deadline_us = 0;
    // Sockets whose peer may read this block. TWO slots: a backup
    // request leaves the original try in flight on another socket, so
    // the backup's arm ADDS its key; only when every entitled peer is
    // gone may peer-death reclamation free the pin (a retry, whose
    // previous try is finished, REPLACES instead).
    uint64_t peer_keys[2] = {0, 0};
    int npeers = 0;
};

// Immortal singletons: Release runs from EndRPC, which Socket recycling
// can reach during static teardown (same rule as the peer-pool
// registry in shm_link.cc).
std::mutex& mu() {
    static std::mutex* m = new std::mutex;
    return *m;
}
std::map<uint64_t, Lease>& leases() {
    static auto* m = new std::map<uint64_t, Lease>;
    return *m;
}

std::atomic<uint64_t> g_next_id{1};
std::atomic<uint64_t> g_pinned{0};
std::atomic<uint64_t> g_pins_total{0};
std::atomic<uint64_t> g_released{0};
std::atomic<uint64_t> g_expired{0};
std::atomic<uint64_t> g_peer_released{0};

// rpc_pool_* observability (satellite): live pins as a passive gauge,
// reclamation paths as counters — the leak signature of a descriptor
// data path is "pinned_blocks grows while reaped stays 0".
int64_t read_pinned(void*) {
    return (int64_t)g_pinned.load(std::memory_order_relaxed);
}
struct GaugeExposer {
    GaugeExposer() {
        auto* g = new PassiveStatus<int64_t>(&read_pinned, nullptr);
        g->expose("rpc_pool_pinned_blocks");
    }
};
static LazyAdder g_var_expired("rpc_pool_lease_expired");
static LazyAdder g_var_reaped("rpc_pool_reaped");
static LazyAdder g_var_peer_released("rpc_pool_peer_released");

std::atomic<bool> g_reaper_started{false};

void ReaperLoop() {
    while (true) {
        int64_t interval = FLAGS_pool_lease_reap_ms.get();
        if (interval < 10) interval = 10;
        std::this_thread::sleep_for(std::chrono::milliseconds(interval));
        ReapExpired(monotonic_time_us());
    }
}

// Drop a lease's pin OUTSIDE the registry lock: the IOBuf release runs
// the block deallocator (slab recycle), which must never nest under
// this mutex (FreeSlab takes the class mutex; a resolver thread could
// hold it while calling into the registry).
void drop_pins(std::vector<IOBuf>* pins) { pins->clear(); }

}  // namespace

uint64_t Pin(IOBuf&& buf, const char* direction) {
    StartReaper();
    const uint64_t id =
        g_next_id.fetch_add(1, std::memory_order_relaxed);
    flight::Record(flight::kLeasePin, id, buf.size());
    {
        std::lock_guard<std::mutex> g(mu());
        Lease& l = leases()[id];
        l.pinned = std::move(buf);
        l.direction = direction;
        // Default lifetime from the moment of the pin: a lease whose
        // owner never reaches Arm (setup failure + dropped release) is
        // still reapable — no unreapable pin state exists.
        l.deadline_us = monotonic_time_us() +
                        FLAGS_pool_lease_default_ms.get() * 1000;
    }
    g_pinned.fetch_add(1, std::memory_order_relaxed);
    g_pins_total.fetch_add(1, std::memory_order_relaxed);
    return id;
}

bool Arm(uint64_t lease_id, uint64_t call_id, int64_t deadline_us,
         uint64_t peer_key, bool add_peer) {
    if (lease_id == 0) return false;
    const int64_t now = monotonic_time_us();
    int64_t expiry;
    if (deadline_us > 0) {
        expiry = deadline_us + FLAGS_pool_lease_grace_ms.get() * 1000;
    } else {
        expiry = now + FLAGS_pool_lease_default_ms.get() * 1000;
    }
    std::lock_guard<std::mutex> g(mu());
    auto it = leases().find(lease_id);
    if (it == leases().end()) return false;  // already reaped/released
    Lease& l = it->second;
    l.call_id = call_id;
    l.deadline_us = expiry;
    if (add_peer && l.npeers == 1 && l.peer_keys[0] != peer_key) {
        // Backup request: the original try's peer stays entitled to
        // read the block — hold BOTH keys.
        l.peer_keys[1] = peer_key;
        l.npeers = 2;
    } else {
        l.peer_keys[0] = peer_key;
        l.peer_keys[1] = 0;
        l.npeers = peer_key != 0 ? 1 : 0;
    }
    flight::Record(flight::kLeaseArm, lease_id, call_id);
    return true;
}

bool Release(uint64_t lease_id) {
    if (lease_id == 0) return false;
    IOBuf pin;
    {
        std::lock_guard<std::mutex> g(mu());
        auto it = leases().find(lease_id);
        if (it == leases().end()) return false;
        pin = std::move(it->second.pinned);
        leases().erase(it);
    }
    g_pinned.fetch_sub(1, std::memory_order_relaxed);
    g_released.fetch_add(1, std::memory_order_relaxed);
    flight::Record(flight::kLeaseRelease, lease_id, pin.size());
    pin.clear();  // the dec_ref -> slab recycle, outside the lock
    return true;
}

bool Alive(uint64_t lease_id) {
    if (lease_id == 0) return false;
    std::lock_guard<std::mutex> g(mu());
    return leases().count(lease_id) != 0;
}

size_t ReapExpired(int64_t now_us) {
    std::vector<IOBuf> pins;
    {
        std::lock_guard<std::mutex> g(mu());
        auto& m = leases();
        for (auto it = m.begin(); it != m.end();) {
            if (it->second.deadline_us > 0 &&
                now_us >= it->second.deadline_us) {
                flight::Record(
                    flight::kLeaseExpire, it->first,
                    (uint64_t)((now_us - it->second.deadline_us) / 1000));
                pins.push_back(std::move(it->second.pinned));
                it = m.erase(it);
            } else {
                ++it;
            }
        }
    }
    const size_t n = pins.size();
    if (n > 0) {
        g_pinned.fetch_sub(n, std::memory_order_relaxed);
        g_expired.fetch_add(n, std::memory_order_relaxed);
        *g_var_expired << (int64_t)n;
        *g_var_reaped << (int64_t)n;
        LOG(WARNING) << "block_lease: reaped " << n
                     << " expired pinned pool block(s) (owner never "
                        "released — wedged call or leaked pin)";
        drop_pins(&pins);
    }
    return n;
}

size_t ReleasePeer(uint64_t peer_key) {
    if (peer_key == 0) return 0;
    std::vector<IOBuf> pins;
    {
        std::lock_guard<std::mutex> g(mu());
        auto& m = leases();
        for (auto it = m.begin(); it != m.end();) {
            Lease& l = it->second;
            bool held = false;
            for (int i = 0; i < l.npeers; ++i) {
                if (l.peer_keys[i] == peer_key) {
                    // Drop this peer's entitlement; compact.
                    l.peer_keys[i] = l.peer_keys[l.npeers - 1];
                    l.peer_keys[--l.npeers] = 0;
                    held = true;
                    break;
                }
            }
            if (held && l.npeers == 0) {
                // No surviving peer may read the block: reclaim. (With
                // a backup's second key still present — the original
                // try's server may be mid-read — the pin stays until
                // that peer dies too, EndRPC, or the lease expires.)
                pins.push_back(std::move(l.pinned));
                it = m.erase(it);
            } else {
                ++it;
            }
        }
    }
    const size_t n = pins.size();
    if (n > 0) {
        g_pinned.fetch_sub(n, std::memory_order_relaxed);
        g_peer_released.fetch_add(n, std::memory_order_relaxed);
        *g_var_peer_released << (int64_t)n;
        *g_var_reaped << (int64_t)n;
        flight::Record(flight::kLeasePeerDeath, peer_key, n);
        drop_pins(&pins);
    }
    return n;
}

size_t ReleaseByCall(uint64_t call_id, uint64_t peer_key) {
    if (call_id == 0) return 0;
    std::vector<IOBuf> pins;
    {
        std::lock_guard<std::mutex> g(mu());
        auto& m = leases();
        for (auto it = m.begin(); it != m.end();) {
            Lease& l = it->second;
            bool entitled = false;
            for (int i = 0; i < l.npeers; ++i) {
                entitled = entitled || l.peer_keys[i] == peer_key;
            }
            if (l.call_id == call_id && entitled) {
                pins.push_back(std::move(l.pinned));
                it = m.erase(it);
            } else {
                ++it;
            }
        }
    }
    const size_t n = pins.size();
    if (n > 0) {
        g_pinned.fetch_sub(n, std::memory_order_relaxed);
        g_released.fetch_add(n, std::memory_order_relaxed);
        drop_pins(&pins);
    }
    return n;
}

bool ReleaseAcked(uint64_t lease_id, uint64_t call_id,
                  uint64_t peer_key) {
    if (lease_id == 0 || call_id == 0) return false;
    IOBuf pin;
    {
        std::lock_guard<std::mutex> g(mu());
        auto it = leases().find(lease_id);
        if (it == leases().end()) return false;  // already released
        Lease& l = it->second;
        bool entitled = false;
        for (int i = 0; i < l.npeers; ++i) {
            entitled = entitled || l.peer_keys[i] == peer_key;
        }
        if (l.call_id != call_id || !entitled) return false;
        pin = std::move(l.pinned);
        leases().erase(it);
    }
    g_pinned.fetch_sub(1, std::memory_order_relaxed);
    g_released.fetch_add(1, std::memory_order_relaxed);
    flight::Record(flight::kLeaseRelease, lease_id, pin.size());
    pin.clear();  // dec_ref -> slab recycle, outside the lock
    return true;
}

uint64_t pinned() { return g_pinned.load(std::memory_order_relaxed); }
uint64_t pins_total() {
    return g_pins_total.load(std::memory_order_relaxed);
}
uint64_t released() { return g_released.load(std::memory_order_relaxed); }
uint64_t expired_reaped() {
    return g_expired.load(std::memory_order_relaxed);
}
uint64_t peer_released() {
    return g_peer_released.load(std::memory_order_relaxed);
}

std::string DebugString() {
    char line[160];
    std::string out;
    snprintf(line, sizeof(line), "pinned %llu\n",
             (unsigned long long)pinned());
    out += line;
    snprintf(line, sizeof(line), "pins_total %llu\n",
             (unsigned long long)pins_total());
    out += line;
    snprintf(line, sizeof(line), "released %llu\n",
             (unsigned long long)released());
    out += line;
    snprintf(line, sizeof(line), "lease_expired %llu\n",
             (unsigned long long)expired_reaped());
    out += line;
    snprintf(line, sizeof(line), "peer_released %llu\n",
             (unsigned long long)peer_released());
    out += line;
    const int64_t now = monotonic_time_us();
    std::lock_guard<std::mutex> g(mu());
    int shown = 0;
    for (const auto& kv : leases()) {
        if (++shown > 64) {
            out += "...\n";
            break;
        }
        const Lease& l = kv.second;
        snprintf(line, sizeof(line),
                 "lease %llu dir=%s bytes=%zu call=%llu "
                 "deadline_in_ms=%lld peer=%llu peer2=%llu\n",
                 (unsigned long long)kv.first, l.direction,
                 l.pinned.size(), (unsigned long long)l.call_id,
                 (long long)((l.deadline_us - now) / 1000),
                 (unsigned long long)l.peer_keys[0],
                 (unsigned long long)l.peer_keys[1]);
        out += line;
    }
    return out;
}

std::string JsonLeases(size_t max) {
    const int64_t now = monotonic_time_us();
    std::string out = "[";
    char line[192];
    std::lock_guard<std::mutex> g(mu());
    size_t shown = 0;
    for (const auto& kv : leases()) {
        if (shown >= max) break;
        const Lease& l = kv.second;
        snprintf(line, sizeof(line),
                 "%s{\"id\": %llu, \"direction\": \"%s\", \"bytes\": %zu, "
                 "\"call\": %llu, \"deadline_in_ms\": %lld, "
                 "\"peer\": %llu}",
                 shown == 0 ? "" : ", ", (unsigned long long)kv.first,
                 l.direction, l.pinned.size(),
                 (unsigned long long)l.call_id,
                 (long long)((l.deadline_us - now) / 1000),
                 (unsigned long long)l.peer_keys[0]);
        out += line;
        ++shown;
    }
    out += "]";
    return out;
}

void ExposeVars() {
    static std::atomic<bool> done{false};
    if (done.exchange(true, std::memory_order_acq_rel)) return;
    static GaugeExposer expose_gauge;
    // Touch the lazy adders so the families exist in /metrics from the
    // first scrape (a 0-valued counter is data; a missing one is not).
    *g_var_expired << 0;
    *g_var_reaped << 0;
    *g_var_peer_released << 0;
}

void StartReaper() {
    if (g_reaper_started.exchange(true, std::memory_order_acq_rel)) {
        return;
    }
    ExposeVars();
    std::thread(ReaperLoop).detach();
}

}  // namespace block_lease
}  // namespace tpurpc
