#include "tici/block_pool.h"

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <vector>

#include "tbase/iobuf.h"
#include "tbase/logging.h"
#include "tbase/fast_rand.h"

namespace tpurpc {

namespace {

struct Region {
    char* base;
    size_t size;
};

struct PoolState {
    std::mutex mu;
    std::vector<Region> regions;   // [0] is the shared primary (if any)
    // Freed default-size blocks, partitioned by transferability: blocks
    // inside the shared primary can be posted to peers zero-copy and are
    // preferred on allocation (keeps the zero-copy rate high after the
    // pool has ever overflowed into anonymous regions).
    std::vector<void*> freelist_shared;
    std::vector<void*> freelist_other;
    // Bounce reserve: the TAIL of the primary is carved exclusively by
    // AllocateSharedBlock, with its own freelist — general traffic must
    // not be able to strand the cross-process copy path's memory in
    // per-thread caches (bounce blocks themselves bypass caches via the
    // DeallocateShared dealloc pointer, so this band self-recycles).
    std::vector<void*> freelist_bounce;
    size_t bounce_reserve = 8u << 20;
    size_t bounce_carve = 0;  // into the reserved band
    size_t region_step = 64u << 20;
    size_t carve_offset = 0;       // into regions.back()
    std::atomic<size_t> live{0};
    std::atomic<bool> inited{false};
    char shm_name[64] = "";
    char* shm_base = nullptr;
    size_t shm_size = 0;

    bool in_shared(const void* ptr) const {
        const char* c = (const char*)ptr;
        return shm_base != nullptr && c >= shm_base && c < shm_base + shm_size;
    }
    bool in_bounce_band(const void* ptr) const {
        const char* c = (const char*)ptr;
        return shm_base != nullptr &&
               c >= shm_base + (shm_size - bounce_reserve) &&
               c < shm_base + shm_size;
    }
    // General carve limit within the CURRENT back region.
    size_t carve_limit() const {
        const Region& r = regions.back();
        return r.base == shm_base ? r.size - bounce_reserve : r.size;
    }
};

PoolState& pool() {
    static PoolState p;
    return p;
}

// Cross-process pressure: set when AllocateSharedBlock runs dry; while
// set, dec_ref routes SHARED-region blocks straight back to the pool
// (IOBuf::blockmem_cache_veto) instead of per-thread caches, refilling
// freelist_shared until the watermark clears it. Keeps the hot path at
// one relaxed load when the shm transport isn't starved.
std::atomic<bool> g_shared_pressure{false};
constexpr size_t kSharedRefillWatermark = 256;

bool shared_cache_veto(const void* p) {
    return g_shared_pressure.load(std::memory_order_relaxed) &&
           pool().in_shared(p);
}

void unlink_shm_at_exit() {
    PoolState& p = pool();
    if (p.shm_name[0] != '\0') shm_unlink(p.shm_name);
}

// Create the primary region as a named POSIX shm segment so peers can map
// it (the "memory registration" of this transport). Returns false on any
// failure; caller falls back to an anonymous region. Caller holds mu.
bool create_shared_primary_locked(PoolState& p) {
    snprintf(p.shm_name, sizeof(p.shm_name), "/tpurpc_pool_%d_%08lx",
             (int)getpid(), (unsigned long)fast_rand());
    const int fd = shm_open(p.shm_name, O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd < 0) {
        PLOG(WARNING) << "IciBlockPool: shm_open " << p.shm_name
                      << " failed; pool is process-local";
        p.shm_name[0] = '\0';
        return false;
    }
    if (ftruncate(fd, (off_t)p.region_step) != 0) {
        PLOG(ERROR) << "IciBlockPool: ftruncate failed";
        close(fd);
        shm_unlink(p.shm_name);
        p.shm_name[0] = '\0';
        return false;
    }
    void* mem = mmap(nullptr, p.region_step, PROT_READ | PROT_WRITE,
                     MAP_SHARED, fd, 0);
    close(fd);  // the mapping keeps the segment alive
    if (mem == MAP_FAILED) {
        PLOG(ERROR) << "IciBlockPool: mmap shared primary failed";
        shm_unlink(p.shm_name);
        p.shm_name[0] = '\0';
        return false;
    }
    p.shm_base = (char*)mem;
    p.shm_size = p.region_step;
    p.regions.push_back(Region{(char*)mem, p.region_step});
    p.carve_offset = 0;
    // The name must outlive process setup so late-connecting peers can
    // map it; unlink on orderly exit (a crash leaves a /dev/shm entry the
    // next Init from the same pid range won't collide with — names embed
    // pid+random).
    atexit(unlink_shm_at_exit);
    return true;
}

// mmap one more (anonymous, process-local) region. Caller holds mu.
bool grow_locked(PoolState& p) {
    void* mem = mmap(nullptr, p.region_step, PROT_READ | PROT_WRITE,
                     MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
    if (mem == MAP_FAILED) {
        PLOG(ERROR) << "IciBlockPool: mmap " << p.region_step << " failed";
        return false;
    }
    p.regions.push_back(Region{(char*)mem, p.region_step});
    p.carve_offset = 0;
    return true;
}

}  // namespace

void* IciBlockPool::Allocate(size_t n) {
    PoolState& p = pool();
    if (n == IOBuf::DEFAULT_BLOCK_SIZE) {
        std::lock_guard<std::mutex> g(p.mu);
        if (!p.freelist_shared.empty()) {
            void* b = p.freelist_shared.back();
            p.freelist_shared.pop_back();
            p.live.fetch_add(1, std::memory_order_relaxed);
            return b;
        }
        if (!p.freelist_other.empty()) {
            void* b = p.freelist_other.back();
            p.freelist_other.pop_back();
            p.live.fetch_add(1, std::memory_order_relaxed);
            return b;
        }
        if (p.regions.empty() || p.carve_offset + n > p.carve_limit()) {
            if (!grow_locked(p)) return nullptr;
        }
        void* b = p.regions.back().base + p.carve_offset;
        p.carve_offset += n;
        p.live.fetch_add(1, std::memory_order_relaxed);
        return b;
    }
    // Odd-size block: plain malloc, tagged so Deallocate can tell it from
    // a pool block (a real libtpu build would register these mappings on
    // demand; the send path bounce-copies them into pool blocks).
    void* mem = malloc(n);
    return mem;
}

void IciBlockPool::Deallocate(void* b) {
    PoolState& p = pool();
    {
        std::lock_guard<std::mutex> g(p.mu);
        const char* c = (const char*)b;
        for (const Region& r : p.regions) {
            if (c >= r.base && c < r.base + r.size) {
                if (p.in_bounce_band(b)) {
                    p.freelist_bounce.push_back(b);
                } else if (p.in_shared(b)) {
                    p.freelist_shared.push_back(b);
                    if (p.freelist_shared.size() >= kSharedRefillWatermark) {
                        g_shared_pressure.store(
                            false, std::memory_order_relaxed);
                    }
                } else {
                    p.freelist_other.push_back(b);
                }
                p.live.fetch_sub(1, std::memory_order_relaxed);
                return;
            }
        }
    }
    free(b);  // odd-size malloc'd block
}

void IciBlockPool::DeallocateShared(void* p) { Deallocate(p); }

void* IciBlockPool::AllocateSharedBlock() {
    PoolState& p = pool();
    std::lock_guard<std::mutex> g(p.mu);
    if (p.shm_base == nullptr) return nullptr;
    // A successful allocation means starvation is over: unlatch the
    // pressure flag here (the freelist watermark alone is unreachable
    // for small pools, and a latched flag would disable the TLS block
    // caches forever).
    g_shared_pressure.store(false, std::memory_order_relaxed);
    // The reserved band first: its blocks recycle through
    // freelist_bounce only (never via per-thread caches), so the bounce
    // path can't be starved by general traffic. In-flight bounce data
    // is bounded by the descriptor rings (kDepth slots x 8KB per pipe),
    // so the reserve covers the bounce workload structurally; the
    // pressure fallback below is belt-and-braces for many-link setups.
    if (!p.freelist_bounce.empty()) {
        void* b = p.freelist_bounce.back();
        p.freelist_bounce.pop_back();
        p.live.fetch_add(1, std::memory_order_relaxed);
        return b;
    }
    if (p.bounce_carve + IOBuf::DEFAULT_BLOCK_SIZE <= p.bounce_reserve) {
        void* b =
            p.shm_base + (p.shm_size - p.bounce_reserve) + p.bounce_carve;
        p.bounce_carve += IOBuf::DEFAULT_BLOCK_SIZE;
        p.live.fetch_add(1, std::memory_order_relaxed);
        return b;
    }
    // Reserve exhausted (more than 8MB of bounce data in flight): fall
    // back to the general shared freelist / carve.
    if (!p.freelist_shared.empty()) {
        void* b = p.freelist_shared.back();
        p.freelist_shared.pop_back();
        p.live.fetch_add(1, std::memory_order_relaxed);
        return b;
    }
    if (!p.regions.empty() && p.regions.back().base == p.shm_base &&
        p.carve_offset + IOBuf::DEFAULT_BLOCK_SIZE <= p.carve_limit()) {
        void* b = p.regions.back().base + p.carve_offset;
        p.carve_offset += IOBuf::DEFAULT_BLOCK_SIZE;
        p.live.fetch_add(1, std::memory_order_relaxed);
        return b;
    }
    // Dry: shared blocks are circulating in per-thread caches. Raise the
    // pressure flag so dec_ref routes them back here; callers retry.
    g_shared_pressure.store(true, std::memory_order_relaxed);
    return nullptr;
}

void* IciBlockPool::AllocateRegistered(size_t n) {
    PoolState& p = pool();
    std::lock_guard<std::mutex> g(p.mu);
    if (p.regions.empty()) return nullptr;
    n = (n + 4095) & ~(size_t)4095;  // page-align carve for DMA
    if (n > p.region_step) {
        // One-off oversized region of its own.
        void* mem = mmap(nullptr, n, PROT_READ | PROT_WRITE,
                         MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
        if (mem == MAP_FAILED) return nullptr;
        p.regions.push_back(Region{(char*)mem, n});
        // Keep the carve pointer on the PREVIOUS region: this one is
        // fully consumed by the chunk.
        std::swap(p.regions[p.regions.size() - 2],
                  p.regions[p.regions.size() - 1]);
        return mem;
    }
    if (p.carve_offset + n > p.carve_limit()) {
        if (!grow_locked(p)) return nullptr;
    }
    void* b = p.regions.back().base + p.carve_offset;
    p.carve_offset += n;
    return b;
}

bool IciBlockPool::Contains(const void* ptr) {
    PoolState& p = pool();
    std::lock_guard<std::mutex> g(p.mu);
    const char* c = (const char*)ptr;
    for (const Region& r : p.regions) {
        if (c >= r.base && c < r.base + r.size) return true;
    }
    return false;
}

const char* IciBlockPool::shm_name() { return pool().shm_name; }
size_t IciBlockPool::shm_size() { return pool().shm_size; }
char* IciBlockPool::shm_base() { return pool().shm_base; }

bool IciBlockPool::OffsetOf(const void* ptr, uint64_t* offset) {
    PoolState& p = pool();
    // shm_base/shm_size are written once under Init's mu and read-only
    // after; no lock needed on this hot path.
    const char* c = (const char*)ptr;
    if (p.shm_base == nullptr || c < p.shm_base ||
        c >= p.shm_base + p.shm_size) {
        return false;
    }
    *offset = (uint64_t)(c - p.shm_base);
    return true;
}

int IciBlockPool::Init(size_t region_bytes) {
    PoolState& p = pool();
    bool expected = false;
    if (!p.inited.compare_exchange_strong(expected, true)) return 0;
    {
        std::lock_guard<std::mutex> g(p.mu);
        p.region_step = region_bytes < (1u << 20) ? (1u << 20) : region_bytes;
        // The bounce reserve must fit INSIDE the primary (a reserve >=
        // the region would underflow carve_limit into an unbounded carve
        // — heap corruption): cap it at a quarter of the region.
        p.bounce_reserve =
            std::min(p.bounce_reserve, p.region_step / 4);
        // Primary region: shared (cross-process transferable). Fall back
        // to anonymous when /dev/shm is unavailable — in-process links
        // still work, cross-process connects will refuse.
        if (!create_shared_primary_locked(p) && !grow_locked(p)) {
            p.inited.store(false);
            return -1;
        }
    }
    // From here on every new IOBuf block is transferable memory (the
    // TLS block cache only recycles blocks whose deallocator matches the
    // current pair, so stale malloc'd blocks are not handed back out).
    // Deallocate hook FIRST: Init may run lazily (first ICI handshake on
    // a busy server) while other threads allocate; a racer that sees the
    // new allocator must also see a deallocator that can free its block
    // (Deallocate falls back to free() for non-pool pointers, so the
    // reverse mix is safe — free() on a pool block is not).
    IOBuf::blockmem_deallocate = &IciBlockPool::Deallocate;
    IOBuf::blockmem_allocate = &IciBlockPool::Allocate;
    IOBuf::blockmem_cache_veto = &shared_cache_veto;
    return 0;
}

bool IciBlockPool::initialized() {
    return pool().inited.load(std::memory_order_acquire);
}

size_t IciBlockPool::allocated_blocks() {
    return pool().live.load(std::memory_order_relaxed);
}

size_t IciBlockPool::free_blocks() {
    PoolState& p = pool();
    std::lock_guard<std::mutex> g(p.mu);
    return p.freelist_shared.size() + p.freelist_other.size();
}

}  // namespace tpurpc
