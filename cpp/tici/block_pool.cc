#include "tici/block_pool.h"

#include <sys/mman.h>

#include <atomic>
#include <cstdlib>
#include <mutex>
#include <vector>

#include "tbase/iobuf.h"
#include "tbase/logging.h"

namespace tpurpc {

namespace {

struct Region {
    char* base;
    size_t size;
};

struct PoolState {
    std::mutex mu;
    std::vector<Region> regions;
    std::vector<void*> freelist;   // default-size blocks
    size_t region_step = 64u << 20;
    size_t carve_offset = 0;       // into regions.back()
    std::atomic<size_t> live{0};
    std::atomic<bool> inited{false};
};

PoolState& pool() {
    static PoolState p;
    return p;
}

// mmap one more region. Caller holds mu.
bool grow_locked(PoolState& p) {
    void* mem = mmap(nullptr, p.region_step, PROT_READ | PROT_WRITE,
                     MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
    if (mem == MAP_FAILED) {
        PLOG(ERROR) << "IciBlockPool: mmap " << p.region_step << " failed";
        return false;
    }
    p.regions.push_back(Region{(char*)mem, p.region_step});
    p.carve_offset = 0;
    return true;
}

}  // namespace

void* IciBlockPool::Allocate(size_t n) {
    PoolState& p = pool();
    if (n == IOBuf::DEFAULT_BLOCK_SIZE) {
        std::lock_guard<std::mutex> g(p.mu);
        if (!p.freelist.empty()) {
            void* b = p.freelist.back();
            p.freelist.pop_back();
            p.live.fetch_add(1, std::memory_order_relaxed);
            return b;
        }
        if (p.regions.empty() ||
            p.carve_offset + n > p.regions.back().size) {
            if (!grow_locked(p)) return nullptr;
        }
        void* b = p.regions.back().base + p.carve_offset;
        p.carve_offset += n;
        p.live.fetch_add(1, std::memory_order_relaxed);
        return b;
    }
    // Odd-size block: plain malloc, tagged so Deallocate can tell it from
    // a pool block (a real libtpu build would register these mappings on
    // demand; the fake-ICI loopback can transfer from any memory).
    void* mem = malloc(n);
    return mem;
}

void IciBlockPool::Deallocate(void* b) {
    PoolState& p = pool();
    {
        std::lock_guard<std::mutex> g(p.mu);
        const char* c = (const char*)b;
        for (const Region& r : p.regions) {
            if (c >= r.base && c < r.base + r.size) {
                p.freelist.push_back(b);
                p.live.fetch_sub(1, std::memory_order_relaxed);
                return;
            }
        }
    }
    free(b);  // odd-size malloc'd block
}

bool IciBlockPool::Contains(const void* ptr) {
    PoolState& p = pool();
    std::lock_guard<std::mutex> g(p.mu);
    const char* c = (const char*)ptr;
    for (const Region& r : p.regions) {
        if (c >= r.base && c < r.base + r.size) return true;
    }
    return false;
}

int IciBlockPool::Init(size_t region_bytes) {
    PoolState& p = pool();
    bool expected = false;
    if (!p.inited.compare_exchange_strong(expected, true)) return 0;
    {
        std::lock_guard<std::mutex> g(p.mu);
        p.region_step = region_bytes < (1u << 20) ? (1u << 20) : region_bytes;
        if (!grow_locked(p)) {
            p.inited.store(false);
            return -1;
        }
    }
    // From here on every new IOBuf block is transferable memory (the
    // TLS block cache only recycles blocks whose deallocator matches the
    // current pair, so stale malloc'd blocks are not handed back out).
    IOBuf::blockmem_allocate = &IciBlockPool::Allocate;
    IOBuf::blockmem_deallocate = &IciBlockPool::Deallocate;
    return 0;
}

bool IciBlockPool::initialized() {
    return pool().inited.load(std::memory_order_acquire);
}

size_t IciBlockPool::allocated_blocks() {
    return pool().live.load(std::memory_order_relaxed);
}

size_t IciBlockPool::free_blocks() {
    PoolState& p = pool();
    std::lock_guard<std::mutex> g(p.mu);
    return p.freelist.size();
}

}  // namespace tpurpc
