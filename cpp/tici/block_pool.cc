#include "tici/block_pool.h"

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <vector>

#include "tbase/iobuf.h"
#include "tbase/logging.h"
#include "tbase/fast_rand.h"
#include "tnet/fault_injection.h"
#include "tnet/transport.h"

namespace tpurpc {

namespace {

struct Region {
    char* base;
    size_t size;
};

struct PoolState {
    std::mutex mu;
    std::vector<Region> regions;   // [0] is the shared primary (if any)
    // Freed default-size blocks, partitioned by transferability: blocks
    // inside the shared primary can be posted to peers zero-copy and are
    // preferred on allocation (keeps the zero-copy rate high after the
    // pool has ever overflowed into anonymous regions).
    std::vector<void*> freelist_shared;
    std::vector<void*> freelist_other;
    // Bounce reserve: the TAIL of the primary is carved exclusively by
    // AllocateSharedBlock, with its own freelist — general traffic must
    // not be able to strand the cross-process copy path's memory in
    // per-thread caches (bounce blocks themselves bypass caches via the
    // DeallocateShared dealloc pointer, so this band self-recycles).
    std::vector<void*> freelist_bounce;
    size_t bounce_reserve = 8u << 20;
    size_t bounce_carve = 0;  // into the reserved band
    size_t region_step = 64u << 20;
    size_t carve_offset = 0;       // into regions.back()
    std::atomic<size_t> live{0};
    std::atomic<bool> inited{false};
    char shm_name[64] = "";
    char* shm_base = nullptr;
    size_t shm_size = 0;

    bool in_shared(const void* ptr) const {
        const char* c = (const char*)ptr;
        return shm_base != nullptr && c >= shm_base && c < shm_base + shm_size;
    }
    bool in_bounce_band(const void* ptr) const {
        const char* c = (const char*)ptr;
        return shm_base != nullptr &&
               c >= shm_base + (shm_size - bounce_reserve) &&
               c < shm_base + shm_size;
    }
    // General carve limit within the CURRENT back region.
    size_t carve_limit() const {
        const Region& r = regions.back();
        return r.base == shm_base ? r.size - bounce_reserve : r.size;
    }
};

PoolState& pool() {
    static PoolState p;
    return p;
}

// Cross-process pressure: set when AllocateSharedBlock runs dry; while
// set, dec_ref routes SHARED-region blocks straight back to the pool
// (IOBuf::blockmem_cache_veto) instead of per-thread caches, refilling
// freelist_shared until the watermark clears it. Keeps the hot path at
// one relaxed load when the shm transport isn't starved.
std::atomic<bool> g_shared_pressure{false};
constexpr size_t kSharedRefillWatermark = 256;

bool shared_cache_veto(const void* p) {
    return g_shared_pressure.load(std::memory_order_relaxed) &&
           pool().in_shared(p);
}

void unlink_shm_at_exit() {
    PoolState& p = pool();
    if (p.shm_name[0] != '\0') shm_unlink(p.shm_name);
}

// Create the primary region as a named POSIX shm segment so peers can map
// it (the "memory registration" of this transport). Returns false on any
// failure; caller falls back to an anonymous region. Caller holds mu.
bool create_shared_primary_locked(PoolState& p) {
    snprintf(p.shm_name, sizeof(p.shm_name), "/tpurpc_pool_%d_%08lx",
             (int)getpid(), (unsigned long)fast_rand());
    const int fd = shm_open(p.shm_name, O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd < 0) {
        PLOG(WARNING) << "IciBlockPool: shm_open " << p.shm_name
                      << " failed; pool is process-local";
        p.shm_name[0] = '\0';
        return false;
    }
    if (ftruncate(fd, (off_t)p.region_step) != 0) {
        PLOG(ERROR) << "IciBlockPool: ftruncate failed";
        close(fd);
        shm_unlink(p.shm_name);
        p.shm_name[0] = '\0';
        return false;
    }
    void* mem = mmap(nullptr, p.region_step, PROT_READ | PROT_WRITE,
                     MAP_SHARED, fd, 0);
    close(fd);  // the mapping keeps the segment alive
    if (mem == MAP_FAILED) {
        PLOG(ERROR) << "IciBlockPool: mmap shared primary failed";
        shm_unlink(p.shm_name);
        p.shm_name[0] = '\0';
        return false;
    }
    p.shm_base = (char*)mem;
    p.shm_size = p.region_step;
    p.regions.push_back(Region{(char*)mem, p.region_step});
    p.carve_offset = 0;
    // The name must outlive process setup so late-connecting peers can
    // map it; unlink on orderly exit (a crash leaves a /dev/shm entry the
    // next Init from the same pid range won't collide with — names embed
    // pid+random).
    atexit(unlink_shm_at_exit);
    return true;
}

// mmap one more (anonymous, process-local) region. Caller holds mu.
bool grow_locked(PoolState& p) {
    void* mem = mmap(nullptr, p.region_step, PROT_READ | PROT_WRITE,
                     MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
    if (mem == MAP_FAILED) {
        PLOG(ERROR) << "IciBlockPool: mmap " << p.region_step << " failed";
        return false;
    }
    p.regions.push_back(Region{(char*)mem, p.region_step});
    p.carve_offset = 0;
    return true;
}

}  // namespace

void* IciBlockPool::Allocate(size_t n) {
    PoolState& p = pool();
    if (n == IOBuf::DEFAULT_BLOCK_SIZE) {
        std::lock_guard<std::mutex> g(p.mu);
        if (!p.freelist_shared.empty()) {
            void* b = p.freelist_shared.back();
            p.freelist_shared.pop_back();
            p.live.fetch_add(1, std::memory_order_relaxed);
            return b;
        }
        if (!p.freelist_other.empty()) {
            void* b = p.freelist_other.back();
            p.freelist_other.pop_back();
            p.live.fetch_add(1, std::memory_order_relaxed);
            return b;
        }
        if (p.regions.empty() || p.carve_offset + n > p.carve_limit()) {
            if (!grow_locked(p)) return nullptr;
        }
        void* b = p.regions.back().base + p.carve_offset;
        p.carve_offset += n;
        p.live.fetch_add(1, std::memory_order_relaxed);
        return b;
    }
    // Odd-size block: plain malloc, tagged so Deallocate can tell it from
    // a pool block (a real libtpu build would register these mappings on
    // demand; the send path bounce-copies them into pool blocks).
    void* mem = malloc(n);
    return mem;
}

void IciBlockPool::Deallocate(void* b) {
    PoolState& p = pool();
    {
        std::lock_guard<std::mutex> g(p.mu);
        const char* c = (const char*)b;
        for (const Region& r : p.regions) {
            if (c >= r.base && c < r.base + r.size) {
                if (p.in_bounce_band(b)) {
                    p.freelist_bounce.push_back(b);
                } else if (p.in_shared(b)) {
                    p.freelist_shared.push_back(b);
                    if (p.freelist_shared.size() >= kSharedRefillWatermark) {
                        g_shared_pressure.store(
                            false, std::memory_order_relaxed);
                    }
                } else {
                    p.freelist_other.push_back(b);
                }
                p.live.fetch_sub(1, std::memory_order_relaxed);
                return;
            }
        }
    }
    free(b);  // odd-size malloc'd block
}

void IciBlockPool::DeallocateShared(void* p) { Deallocate(p); }

void* IciBlockPool::AllocateSharedBlock() {
    PoolState& p = pool();
    std::lock_guard<std::mutex> g(p.mu);
    if (p.shm_base == nullptr) return nullptr;
    // A successful allocation means starvation is over: unlatch the
    // pressure flag here (the freelist watermark alone is unreachable
    // for small pools, and a latched flag would disable the TLS block
    // caches forever).
    g_shared_pressure.store(false, std::memory_order_relaxed);
    // The reserved band first: its blocks recycle through
    // freelist_bounce only (never via per-thread caches), so the bounce
    // path can't be starved by general traffic. In-flight bounce data
    // is bounded by the descriptor rings (kDepth slots x 8KB per pipe),
    // so the reserve covers the bounce workload structurally; the
    // pressure fallback below is belt-and-braces for many-link setups.
    if (!p.freelist_bounce.empty()) {
        void* b = p.freelist_bounce.back();
        p.freelist_bounce.pop_back();
        p.live.fetch_add(1, std::memory_order_relaxed);
        return b;
    }
    if (p.bounce_carve + IOBuf::DEFAULT_BLOCK_SIZE <= p.bounce_reserve) {
        void* b =
            p.shm_base + (p.shm_size - p.bounce_reserve) + p.bounce_carve;
        p.bounce_carve += IOBuf::DEFAULT_BLOCK_SIZE;
        p.live.fetch_add(1, std::memory_order_relaxed);
        return b;
    }
    // Reserve exhausted (more than 8MB of bounce data in flight): fall
    // back to the general shared freelist / carve.
    if (!p.freelist_shared.empty()) {
        void* b = p.freelist_shared.back();
        p.freelist_shared.pop_back();
        p.live.fetch_add(1, std::memory_order_relaxed);
        return b;
    }
    if (!p.regions.empty() && p.regions.back().base == p.shm_base &&
        p.carve_offset + IOBuf::DEFAULT_BLOCK_SIZE <= p.carve_limit()) {
        void* b = p.regions.back().base + p.carve_offset;
        p.carve_offset += IOBuf::DEFAULT_BLOCK_SIZE;
        p.live.fetch_add(1, std::memory_order_relaxed);
        return b;
    }
    // Dry: shared blocks are circulating in per-thread caches. Raise the
    // pressure flag so dec_ref routes them back here; callers retry.
    g_shared_pressure.store(true, std::memory_order_relaxed);
    return nullptr;
}

void* IciBlockPool::AllocateRegistered(size_t n) {
    PoolState& p = pool();
    std::lock_guard<std::mutex> g(p.mu);
    if (p.regions.empty()) return nullptr;
    n = (n + 4095) & ~(size_t)4095;  // page-align carve for DMA
    if (n > p.region_step) {
        // One-off oversized region of its own.
        void* mem = mmap(nullptr, n, PROT_READ | PROT_WRITE,
                         MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
        if (mem == MAP_FAILED) return nullptr;
        p.regions.push_back(Region{(char*)mem, n});
        // Keep the carve pointer on the PREVIOUS region: this one is
        // fully consumed by the chunk.
        std::swap(p.regions[p.regions.size() - 2],
                  p.regions[p.regions.size() - 1]);
        return mem;
    }
    if (p.carve_offset + n > p.carve_limit()) {
        if (!grow_locked(p)) return nullptr;
    }
    void* b = p.regions.back().base + p.carve_offset;
    p.carve_offset += n;
    return b;
}

bool IciBlockPool::Contains(const void* ptr) {
    PoolState& p = pool();
    std::lock_guard<std::mutex> g(p.mu);
    const char* c = (const char*)ptr;
    for (const Region& r : p.regions) {
        if (c >= r.base && c < r.base + r.size) return true;
    }
    return false;
}

const char* IciBlockPool::shm_name() { return pool().shm_name; }
size_t IciBlockPool::shm_size() { return pool().shm_size; }
char* IciBlockPool::shm_base() { return pool().shm_base; }

bool IciBlockPool::OffsetOf(const void* ptr, uint64_t* offset) {
    PoolState& p = pool();
    // shm_base/shm_size are written once under Init's mu and read-only
    // after; no lock needed on this hot path.
    const char* c = (const char*)ptr;
    if (p.shm_base == nullptr || c < p.shm_base ||
        c >= p.shm_base + p.shm_size) {
        return false;
    }
    *offset = (uint64_t)(c - p.shm_base);
    return true;
}

// ---------------- slab-class registered allocator (ISSUE 9c) ----------------

namespace {

// Size classes: 8K covers descriptor/meta staging, 1M the default device
// chunk, 4M jumbo chunks. Arena size is chosen so one carve amortizes
// ~16 slots of the class (one central-mutex touch per 16 allocations
// even with a cold cache).
constexpr size_t kSlabClassBytes[] = {8u << 10, 64u << 10, 256u << 10,
                                      1u << 20, 4u << 20};
constexpr int kSlabClasses =
    (int)(sizeof(kSlabClassBytes) / sizeof(kSlabClassBytes[0]));
constexpr int kTlsSlotsPerClass = 8;

// One registered arena, chopped into slots of a single class. The arena
// table is append-only and scanned lock-free (count published with
// release/acquire) — FreeSlab derives the class of a pointer from it on
// every TLS-cache overflow without touching any mutex.
struct SlabArena {
    char* base;
    size_t size;
    int cls;
};
SlabArena g_arenas[256];
std::atomic<uint32_t> g_arena_count{0};
// Serializes appends only (two CLASSES can grow arenas concurrently
// under their own class mutexes); readers stay lock-free.
std::mutex g_arena_append_mu;

// Per-class central state: freelist + carve cursor, each class behind
// its OWN mutex so concurrent traffic in different classes never
// serializes (and same-class traffic mostly stays in the TLS cache).
struct SlabClass {
    std::mutex mu;
    std::vector<void*> freelist;
    char* carve_base = nullptr;
    size_t carve_off = 0;
    size_t carve_size = 0;
};
SlabClass& slab_class(int cls) {
    static SlabClass* classes = new SlabClass[kSlabClasses];
    return classes[cls];
}

std::atomic<size_t> g_slab_live{0};
std::atomic<size_t> g_slab_recycled{0};
std::atomic<size_t> g_slab_mutex_acquisitions{0};
// Per-class occupancy for /pools (relaxed: diagnostic, not invariant).
std::atomic<size_t> g_class_live[kSlabClasses] = {};
std::atomic<size_t> g_class_carved[kSlabClasses] = {};

int slab_class_of(size_t n) {
    for (int c = 0; c < kSlabClasses; ++c) {
        if (n <= kSlabClassBytes[c]) return c;
    }
    return -1;
}

int arena_class_of(const void* p) {
    const uint32_t count = g_arena_count.load(std::memory_order_acquire);
    const char* c = (const char*)p;
    for (uint32_t i = 0; i < count; ++i) {
        if (c >= g_arenas[i].base && c < g_arenas[i].base + g_arenas[i].size) {
            return g_arenas[i].cls;
        }
    }
    return -1;
}

// Per-thread slot cache. On thread exit the destructor drains every
// cached slot back to its class freelist so no registered memory is
// stranded in dead threads.
struct TlsSlabCache {
    void* slots[kSlabClasses][kTlsSlotsPerClass];
    int n[kSlabClasses] = {};

    ~TlsSlabCache() {
        for (int c = 0; c < kSlabClasses; ++c) {
            if (n[c] == 0) continue;
            SlabClass& sc = slab_class(c);
            g_slab_mutex_acquisitions.fetch_add(1,
                                                std::memory_order_relaxed);
            std::lock_guard<std::mutex> g(sc.mu);
            for (int i = 0; i < n[c]; ++i) sc.freelist.push_back(slots[c][i]);
            n[c] = 0;
        }
    }
};
thread_local TlsSlabCache g_tls_slabs;

}  // namespace

int IciBlockPool::SlabClassOf(size_t n) { return slab_class_of(n); }
size_t IciBlockPool::slab_class_bytes(int cls) {
    return cls >= 0 && cls < kSlabClasses ? kSlabClassBytes[cls] : 0;
}
size_t IciBlockPool::slab_allocated() {
    return g_slab_live.load(std::memory_order_relaxed);
}
size_t IciBlockPool::slab_recycled() {
    return g_slab_recycled.load(std::memory_order_relaxed);
}
size_t IciBlockPool::slab_mutex_acquisitions() {
    return g_slab_mutex_acquisitions.load(std::memory_order_relaxed);
}

IciBlockPool::SlabClassStat IciBlockPool::slab_class_stat(int cls) {
    SlabClassStat st;
    if (cls < 0 || cls >= kSlabClasses) return st;
    st.live = g_class_live[cls].load(std::memory_order_relaxed);
    st.carved = g_class_carved[cls].load(std::memory_order_relaxed);
    SlabClass& sc = slab_class(cls);
    std::lock_guard<std::mutex> g(sc.mu);
    st.freelist = sc.freelist.size();
    return st;
}

void* IciBlockPool::AllocateSlab(size_t n) {
    const int cls = slab_class_of(n);
    if (cls < 0) {
        // Above the largest class: one-off registered carve (no recycle).
        return AllocateRegistered(n);
    }
    // 1. TLS cache: the steady-state path, no locks at all.
    TlsSlabCache& tls = g_tls_slabs;
    if (tls.n[cls] > 0) {
        void* p = tls.slots[cls][--tls.n[cls]];
        g_slab_live.fetch_add(1, std::memory_order_relaxed);
        g_class_live[cls].fetch_add(1, std::memory_order_relaxed);
        g_slab_recycled.fetch_add(1, std::memory_order_relaxed);
        return p;
    }
    // 2. Class freelist / arena carve under the CLASS mutex.
    SlabClass& sc = slab_class(cls);
    g_slab_mutex_acquisitions.fetch_add(1, std::memory_order_relaxed);
    std::lock_guard<std::mutex> g(sc.mu);
    if (!sc.freelist.empty()) {
        void* p = sc.freelist.back();
        sc.freelist.pop_back();
        g_slab_live.fetch_add(1, std::memory_order_relaxed);
        g_class_live[cls].fetch_add(1, std::memory_order_relaxed);
        g_slab_recycled.fetch_add(1, std::memory_order_relaxed);
        return p;
    }
    const size_t slot = kSlabClassBytes[cls];
    if (sc.carve_base == nullptr || sc.carve_off + slot > sc.carve_size) {
        // New arena: a large aligned registered slab (~16 slots, min 1
        // region-friendly chunk) carved from the pool's regions, then
        // published append-only for lock-free class lookup. Capped at
        // 16MB: the jumbo classes must still fit INSIDE the shm region
        // (a 4MB-class x16 arena would be the whole 64MB pool, land in
        // an anonymous overflow region, and silently disqualify every
        // jumbo slot from descriptor/verb-window use forever).
        const size_t arena_bytes =
            std::min<size_t>(slot * 16,
                             std::max<size_t>(slot, (size_t)16 << 20));
        char* base = (char*)AllocateRegistered(arena_bytes);
        if (base == nullptr) return nullptr;
        {
            std::lock_guard<std::mutex> ag(g_arena_append_mu);
            const uint32_t idx =
                g_arena_count.load(std::memory_order_relaxed);
            if (idx < sizeof(g_arenas) / sizeof(g_arenas[0])) {
                g_arenas[idx] = SlabArena{base, arena_bytes, cls};
                g_arena_count.store(idx + 1, std::memory_order_release);
            } else {
                // Lookup table full: still carve from this arena (the
                // memory is valid registered pool) — its slots just
                // won't recycle (FreeSlab can't classify them), which
                // beats leaking a full arena per cache miss forever.
                LOG_EVERY_N(ERROR, 1000)
                    << "IciBlockPool: slab arena table full; class "
                    << cls << " slots from this arena will not recycle";
            }
        }
        sc.carve_base = base;
        sc.carve_off = 0;
        sc.carve_size = arena_bytes;
    }
    void* p = sc.carve_base + sc.carve_off;
    sc.carve_off += slot;
    g_slab_live.fetch_add(1, std::memory_order_relaxed);
    g_class_live[cls].fetch_add(1, std::memory_order_relaxed);
    g_class_carved[cls].fetch_add(1, std::memory_order_relaxed);
    return p;
}

void IciBlockPool::FreeSlab(void* p) {
    if (p == nullptr) return;
    const int cls = arena_class_of(p);
    if (cls < 0) return;  // oversized/non-slab carve: process lifetime
    g_slab_live.fetch_sub(1, std::memory_order_relaxed);
    g_class_live[cls].fetch_sub(1, std::memory_order_relaxed);
    TlsSlabCache& tls = g_tls_slabs;
    if (tls.n[cls] < kTlsSlotsPerClass) {
        tls.slots[cls][tls.n[cls]++] = p;
        return;
    }
    SlabClass& sc = slab_class(cls);
    g_slab_mutex_acquisitions.fetch_add(1, std::memory_order_relaxed);
    std::lock_guard<std::mutex> g(sc.mu);
    sc.freelist.push_back(p);
}

bool IciBlockPool::AllocatePoolAttachment(size_t n, IOBuf* out,
                                          char** data) {
    const size_t total = n + offsetof(IOBuf::Block, data);
    const int cls = slab_class_of(total);
    if (cls < 0) return false;
    void* mem = AllocateSlab(total);
    if (mem == nullptr) return false;
    uint64_t off = 0;
    if (!OffsetOf(mem, &off)) {
        // Slab arena landed in an overflow (non-shared) region: not
        // descriptor-eligible. Recycle and let the caller fall back.
        FreeSlab(mem);
        return false;
    }
    auto* b = new (mem) IOBuf::Block;
    b->nshared.store(1, std::memory_order_relaxed);
    b->size = (uint32_t)n;
    b->cap = (uint32_t)(kSlabClassBytes[cls] -
                        offsetof(IOBuf::Block, data));
    b->portal_next = nullptr;
    // Custom deallocator: the last dec_ref recycles the slot into its
    // slab class (never the TLS block cache — dealloc differs from the
    // installed pair, so dec_ref frees directly through it).
    b->dealloc = &IciBlockPool::FreeSlab;
    IOBuf::BlockRef ref;
    ref.offset = 0;
    ref.length = (uint32_t)n;
    ref.block = b;
    out->clear();
    // append_ref takes its own reference; drop ours so the IOBuf holds
    // the only one and its release recycles the slot.
    out->append_ref(ref);
    b->dec_ref();
    *data = b->data;
    return true;
}

bool IciBlockPool::AllocatePoolAttachmentCopy(const void* src, size_t n,
                                              IOBuf* out) {
    IOBuf buf;
    char* data = nullptr;
    if (!AllocatePoolAttachment(n, &buf, &data)) return false;
    memcpy(data, src, n);
    out->swap(buf);
    return true;
}

// ---------------- pool registry (ISSUE 9b) ----------------

namespace pool_registry {

namespace {
struct Mapping {
    const char* base;
    size_t size;
    uint64_t epoch;
};
// Immortal (same teardown-order rationale as the shm_link peer-pool
// registry: resolution can run from Socket recycling during exit).
std::mutex& reg_mu() {
    static std::mutex* mu = new std::mutex;
    return *mu;
}
std::map<uint64_t, Mapping>& reg() {
    static auto* m = new std::map<uint64_t, Mapping>;
    return *m;
}
std::atomic<uint64_t> g_resolves{0};
std::atomic<uint64_t> g_resolve_failures{0};
// id -> shm name (ISSUE 18): kept apart from the mapping table — it
// survives Unregister so a verbs re-grant after link churn can still
// locate the segment for a writable remap.
std::map<uint64_t, std::string>& name_reg() {
    static auto* m = new std::map<uint64_t, std::string>;
    return *m;
}
}  // namespace

uint64_t IdFromName(const char* name) {
    uint64_t h = 1469598103934665603ull;  // FNV-1a 64
    for (const char* c = name; *c != '\0'; ++c) {
        h ^= (uint64_t)(unsigned char)*c;
        h *= 1099511628211ull;
    }
    return h != 0 ? h : 1;  // 0 is reserved for "no pool"
}

void Register(uint64_t id, const char* base, size_t size,
              uint64_t epoch) {
    if (id == 0 || base == nullptr) return;
    std::lock_guard<std::mutex> g(reg_mu());
    reg()[id] = Mapping{base, size, epoch != 0 ? epoch : 1};
}

void Unregister(uint64_t id) {
    std::lock_guard<std::mutex> g(reg_mu());
    reg().erase(id);
}

void SetEpoch(uint64_t id, uint64_t epoch) {
    std::lock_guard<std::mutex> g(reg_mu());
    auto it = reg().find(id);
    if (it != reg().end()) it->second.epoch = epoch != 0 ? epoch : 1;
}

void RaiseEpoch(uint64_t id, uint64_t epoch) {
    std::lock_guard<std::mutex> g(reg_mu());
    auto it = reg().find(id);
    if (it != reg().end() && epoch > it->second.epoch) {
        it->second.epoch = epoch;
    }
}

bool Resolve(uint64_t id, const char** base, size_t* size,
             uint64_t* epoch) {
    std::lock_guard<std::mutex> g(reg_mu());
    auto it = reg().find(id);
    if (it == reg().end()) {
        g_resolve_failures.fetch_add(1, std::memory_order_relaxed);
        return false;
    }
    g_resolves.fetch_add(1, std::memory_order_relaxed);
    *base = it->second.base;
    *size = it->second.size;
    if (epoch != nullptr) *epoch = it->second.epoch;
    return true;
}

std::string DebugString() {
    std::string out;
    char line[128];
    std::lock_guard<std::mutex> g(reg_mu());
    for (const auto& kv : reg()) {
        snprintf(line, sizeof(line),
                 "pool %llu size=%zu epoch=%llu local=%d\n",
                 (unsigned long long)kv.first, kv.second.size,
                 (unsigned long long)kv.second.epoch,
                 kv.first == IciBlockPool::pool_id() ? 1 : 0);
        out += line;
    }
    return out;
}

void SetName(uint64_t id, const char* name) {
    if (id == 0 || name == nullptr || name[0] == '\0') return;
    std::lock_guard<std::mutex> g(reg_mu());
    name_reg()[id] = name;
}

bool NameOf(uint64_t id, char* buf, size_t n) {
    if (buf == nullptr || n == 0) return false;
    std::lock_guard<std::mutex> g(reg_mu());
    auto it = name_reg().find(id);
    if (it == name_reg().end() || it->second.size() + 1 > n) return false;
    memcpy(buf, it->second.c_str(), it->second.size() + 1);
    return true;
}

uint64_t resolves() { return g_resolves.load(std::memory_order_relaxed); }
uint64_t resolve_failures() {
    return g_resolve_failures.load(std::memory_order_relaxed);
}

}  // namespace pool_registry

uint64_t IciBlockPool::pool_id() {
    PoolState& p = pool();
    if (p.shm_name[0] == '\0') return 0;
    return pool_registry::IdFromName(p.shm_name);
}

// ---------------- epoch fencing (ISSUE 10b) ----------------

namespace {
// 1 once the pool exists; bumped on remap/restart events. A descriptor
// minted under epoch N is only honored while the mapping is at N.
std::atomic<uint64_t> g_pool_epoch{1};
}  // namespace

uint64_t IciBlockPool::pool_epoch() {
    return g_pool_epoch.load(std::memory_order_acquire);
}

uint64_t IciBlockPool::BumpEpoch() {
    const uint64_t e =
        g_pool_epoch.fetch_add(1, std::memory_order_acq_rel) + 1;
    // Keep the in-process registry honest: handlers resolving our OWN
    // descriptors (loopback links) must see the new generation too.
    const uint64_t id = pool_id();
    if (id != 0) pool_registry::SetEpoch(id, e);
    return e;
}

// ---------------- device staging ring (ISSUE 9a) ----------------

namespace {
struct RingSync {
    std::mutex mu;
    std::condition_variable cv;
};
}  // namespace

DeviceStagingRing* DeviceStagingRing::Create(uint32_t depth,
                                             size_t slot_bytes) {
    if (depth == 0 || depth > 1024 || slot_bytes == 0) return nullptr;
    auto* r = new DeviceStagingRing;
    r->depth_ = depth;
    r->slot_bytes_ = slot_bytes;
    r->slots_ = new char*[depth];
    r->slot_kind_ = new uint8_t[depth]();
    r->done_ = new bool[depth]();
    r->mu_ = new RingSync;
    r->registered_ = true;
    const bool slab_sized = IciBlockPool::SlabClassOf(slot_bytes) >= 0;
    for (uint32_t i = 0; i < depth; ++i) {
        char* s = (char*)IciBlockPool::AllocateSlab(slot_bytes);
        uint8_t kind = slab_sized ? 0 : 2;  // slab vs carve-only chunk
        if (s == nullptr) {
            // Pool dry/uninitialized: plain aligned memory keeps the ring
            // usable (the benchmark reports registered=false honestly).
            s = (char*)aligned_alloc(4096, (slot_bytes + 4095) & ~4095ul);
            kind = 1;
        }
        if (s == nullptr) {
            r->depth_ = i;  // free only what was built
            delete r;
            return nullptr;
        }
        r->slots_[i] = s;
        r->slot_kind_[i] = kind;
        r->registered_ = r->registered_ && IciBlockPool::Contains(s);
    }
    return r;
}

DeviceStagingRing::~DeviceStagingRing() {
    for (uint32_t i = 0; i < depth_; ++i) {
        switch (slot_kind_[i]) {
            case 0:
                IciBlockPool::FreeSlab(slots_[i]);
                break;
            case 1:
                free(slots_[i]);
                break;
            default:
                break;  // carve-only registered chunk: process lifetime
        }
    }
    delete[] slots_;
    delete[] slot_kind_;
    delete[] done_;
    delete (RingSync*)mu_;
}

int DeviceStagingRing::Acquire(int64_t timeout_us) {
    RingSync* sync = (RingSync*)mu_;
    std::unique_lock<std::mutex> lk(sync->mu);
    // Wake on EITHER a free slot or an abort: a poisoned ring (device
    // stream error, shutdown) must unblock parked Python threads
    // immediately instead of letting them wedge to their timeout.
    const auto ready = [this] {
        return aborted_.load(std::memory_order_relaxed) ||
               head_.load(std::memory_order_relaxed) -
                       tail_.load(std::memory_order_relaxed) <
                   depth_;
    };
    if (timeout_us < 0) {
        sync->cv.wait(lk, ready);
    } else if (!sync->cv.wait_for(lk, std::chrono::microseconds(timeout_us),
                                  ready)) {
        return -1;
    }
    if (aborted_.load(std::memory_order_relaxed)) {
        return -2;
    }
    const uint64_t seq = head_.fetch_add(1, std::memory_order_relaxed);
    const uint32_t inflight =
        (uint32_t)(seq + 1 - tail_.load(std::memory_order_relaxed));
    if (inflight > highwater_.load(std::memory_order_relaxed)) {
        highwater_.store(inflight, std::memory_order_relaxed);
    }
    return (int)(seq % depth_);
}

void DeviceStagingRing::Abort() {
    RingSync* sync = (RingSync*)mu_;
    {
        std::lock_guard<std::mutex> lk(sync->mu);
        aborted_.store(true, std::memory_order_release);
    }
    sync->cv.notify_all();
}

int DeviceStagingRing::Complete(uint32_t slot) {
    // Chaos seam (chaos_pool, ISSUE 10d): a delayed or dropped device
    // completion — the ring analog of a lost DMA interrupt. Decided
    // OUTSIDE the ring mutex; plain usleep, this path runs on Python /
    // driver threads, never fibers. A dropped complete leaves the
    // window stuck: Acquire's timeout (or Abort) is the proven escape.
    if (__builtin_expect(fault_injection_enabled(), 0)) {
        const FaultAction fault =
            FaultInjection::Decide(FaultOp::kRingComplete, EndPoint(), 0);
        if (fault.kind == FaultAction::kDelay) {
            usleep((useconds_t)fault.delay_us);
        } else if (fault.kind == FaultAction::kDrop) {
            return 0;  // claimed done, never completed
        }
    }
    RingSync* sync = (RingSync*)mu_;
    std::lock_guard<std::mutex> lk(sync->mu);
    const uint64_t head = head_.load(std::memory_order_relaxed);
    uint64_t tail = tail_.load(std::memory_order_relaxed);
    // `slot` must name an in-flight acquire: within [tail, head) and not
    // already marked done.
    bool inflight = false;
    for (uint64_t i = tail; i < head; ++i) {
        if ((uint32_t)(i % depth_) == slot) {
            inflight = !done_[slot];
            break;
        }
    }
    if (!inflight) return -1;
    done_[slot] = true;
    completed_.fetch_add(1, std::memory_order_relaxed);
    // Device tier attribution: one staged slot cycled through the ring
    // (ops only — the framed length inside the slot is the caller's).
    transport_stats::AddOp(TierDevice());
    // FIFO reuse: advance the reusable frontier only over a contiguous
    // prefix of completed slots (out-of-order completes wait here).
    while (tail < head && done_[tail % depth_]) {
        done_[tail % depth_] = false;
        ++tail;
    }
    tail_.store(tail, std::memory_order_relaxed);
    sync->cv.notify_all();
    return 0;
}

int IciBlockPool::Init(size_t region_bytes) {
    PoolState& p = pool();
    bool expected = false;
    if (!p.inited.compare_exchange_strong(expected, true)) return 0;
    {
        std::lock_guard<std::mutex> g(p.mu);
        p.region_step = region_bytes < (1u << 20) ? (1u << 20) : region_bytes;
        // The bounce reserve must fit INSIDE the primary (a reserve >=
        // the region would underflow carve_limit into an unbounded carve
        // — heap corruption): cap it at a quarter of the region.
        p.bounce_reserve =
            std::min(p.bounce_reserve, p.region_step / 4);
        // Primary region: shared (cross-process transferable). Fall back
        // to anonymous when /dev/shm is unavailable — in-process links
        // still work, cross-process connects will refuse.
        if (!create_shared_primary_locked(p) && !grow_locked(p)) {
            p.inited.store(false);
            return -1;
        }
    }
    // Publish our own pool under its descriptor id: in-process loopback
    // links (and any handler resolving a descriptor we posted to
    // ourselves) resolve against the same registry peers use.
    if (pool().shm_name[0] != '\0') {
        pool_registry::Register(pool_registry::IdFromName(pool().shm_name),
                                pool().shm_base, pool().shm_size,
                                pool_epoch());
        pool_registry::SetName(pool_registry::IdFromName(pool().shm_name),
                               pool().shm_name);
    }
    // Teach the Transport tier how to name this process's pool: the
    // descriptor-eligibility seam (tnet/transport.h) answers "may a
    // descriptor ride/resolve here" for every endpoint type without
    // tnet depending on the pool layer.
    SetLocalPoolIdProvider(&IciBlockPool::pool_id);
    // From here on every new IOBuf block is transferable memory (the
    // TLS block cache only recycles blocks whose deallocator matches the
    // current pair, so stale malloc'd blocks are not handed back out).
    // Deallocate hook FIRST: Init may run lazily (first ICI handshake on
    // a busy server) while other threads allocate; a racer that sees the
    // new allocator must also see a deallocator that can free its block
    // (Deallocate falls back to free() for non-pool pointers, so the
    // reverse mix is safe — free() on a pool block is not).
    IOBuf::blockmem_deallocate = &IciBlockPool::Deallocate;
    IOBuf::blockmem_allocate = &IciBlockPool::Allocate;
    IOBuf::blockmem_cache_veto = &shared_cache_veto;
    return 0;
}

bool IciBlockPool::initialized() {
    return pool().inited.load(std::memory_order_acquire);
}

size_t IciBlockPool::allocated_blocks() {
    return pool().live.load(std::memory_order_relaxed);
}

size_t IciBlockPool::free_blocks() {
    PoolState& p = pool();
    std::lock_guard<std::mutex> g(p.mu);
    return p.freelist_shared.size() + p.freelist_other.size();
}

}  // namespace tpurpc
