#include "tnet/circuit_breaker.h"

#include "tbase/flags.h"

// Defaults shaped like the reference's (src/brpc/circuit_breaker.cpp
// flags circuit_breaker_short_window_size/..._error_percent etc.).
DEFINE_bool(enable_circuit_breaker, true,
            "Isolate servers whose error rate trips the breaker");
DEFINE_int32(circuit_breaker_short_window_size, 100,
             "EMA window (calls) for bursty-failure detection");
DEFINE_double(circuit_breaker_short_window_error_percent, 30.0,
              "Error percent tripping the short window");
DEFINE_int32(circuit_breaker_long_window_size, 1000,
             "EMA window (calls) for chronic-failure detection");
DEFINE_double(circuit_breaker_long_window_error_percent, 5.0,
              "Error percent tripping the long window");

namespace tpurpc {

void CircuitBreaker::Reset() {
    short_.Init(FLAGS_circuit_breaker_short_window_size.get(),
                FLAGS_circuit_breaker_short_window_error_percent.get());
    long_.Init(FLAGS_circuit_breaker_long_window_size.get(),
               FLAGS_circuit_breaker_long_window_error_percent.get());
    broken_.store(false, std::memory_order_release);
}

bool CircuitBreaker::OnCallEnd(int error_code, int64_t latency_us) {
    (void)latency_us;  // reserved: latency-weighted error cost
    if (!FLAGS_enable_circuit_breaker.get()) return true;
    if (IsBroken()) return false;
    const bool error = error_code != 0;
    bool ok = short_.OnCallEnd(error);
    ok = long_.OnCallEnd(error) && ok;
    if (!ok) MarkAsBroken();
    return ok;
}

}  // namespace tpurpc
