#include "tnet/circuit_breaker.h"

#include <cerrno>

#include "tbase/errno.h"
#include "tbase/flags.h"
#include "tvar/reducer.h"

// Defaults shaped like the reference's (src/brpc/circuit_breaker.cpp
// flags circuit_breaker_short_window_size/..._error_percent etc.).
DEFINE_bool(enable_circuit_breaker, true,
            "Isolate servers whose error rate trips the breaker");
DEFINE_int32(circuit_breaker_short_window_size, 100,
             "EMA window (calls) for bursty-failure detection");
DEFINE_double(circuit_breaker_short_window_error_percent, 30.0,
              "Error percent tripping the short window");
DEFINE_int32(circuit_breaker_long_window_size, 1000,
             "EMA window (calls) for chronic-failure detection");
DEFINE_double(circuit_breaker_long_window_error_percent, 5.0,
              "Error percent tripping the long window");
DEFINE_int32(circuit_breaker_min_isolation_duration_ms, 100,
             "Isolation duration after the first trip");
DEFINE_int32(circuit_breaker_max_isolation_duration_ms, 30000,
             "Isolation duration cap (doubles per repeated trip)");

namespace tpurpc {

void CircuitBreaker::Reset() {
    short_.Init(FLAGS_circuit_breaker_short_window_size.get(),
                FLAGS_circuit_breaker_short_window_error_percent.get());
    long_.Init(FLAGS_circuit_breaker_long_window_size.get(),
               FLAGS_circuit_breaker_long_window_error_percent.get());
    broken_.store(false, std::memory_order_release);
}

// Client-local conditions must not count against the server: a cancelled
// RPC or local write back-pressure says nothing about remote health, and
// feeding them in would isolate healthy servers (reference feeds only
// server-attributable codes into the breaker). A QoS overload shed
// (TERR_OVERLOAD) is excluded too: it is the server WORKING as designed
// — isolating it would tear down the shared connection for every tenant
// (including the protected ones) and amplify the very storm being shed;
// steering happens through the LB feedback/backoff instead.
// TERR_STALE_EPOCH likewise: an epoch fence rejecting one stale
// descriptor proves the server is protecting itself correctly, not
// failing.
static bool ClientLocalError(int error_code) {
    return error_code == ECANCELED || error_code == TERR_OVERCROWDED ||
           error_code == TERR_BACKUP_REQUEST ||
           error_code == TERR_OVERLOAD ||
           error_code == TERR_STALE_EPOCH;
}

bool CircuitBreaker::OnCallEnd(int error_code, int64_t latency_us) {
    (void)latency_us;  // reserved: latency-weighted error cost
    if (!FLAGS_enable_circuit_breaker.get()) return true;
    if (IsBroken()) return false;
    if (ClientLocalError(error_code)) return true;
    const bool error = error_code != 0;
    bool ok = short_.OnCallEnd(error);
    ok = long_.OnCallEnd(error) && ok;
    if (!ok && MarkAsBroken()) {
        // Per-process isolation count, observable in /vars and /metrics
        // (the mesh chaos soak asserts on it).
        static LazyAdder isolations("rpc_circuit_breaker_isolations");
        *isolations << 1;
    }
    return ok;
}

int CircuitBreaker::isolation_duration_ms() const {
    const int times = isolated_times_.load(std::memory_order_relaxed);
    if (times <= 0) return 0;
    const int64_t base = FLAGS_circuit_breaker_min_isolation_duration_ms.get();
    const int64_t cap = FLAGS_circuit_breaker_max_isolation_duration_ms.get();
    const int shift = times - 1 > 16 ? 16 : times - 1;
    const int64_t d = base << shift;
    return (int)(d > cap ? cap : d);
}

}  // namespace tpurpc
