// The Transport tier: the first-class peer-endpoint seam of the stack.
//
// Two layers live here:
//
//  1. TransportEndpoint — the pluggable DATA-PLANE of one Socket: how an
//     ICI/shm queue-pair (or TLS) transport takes over reads and writes
//     while Socket keeps the id/lifecycle/wait-free-queue semantics.
//     Modeled on the role of reference src/brpc/rdma/rdma_endpoint.h: the
//     RDMA endpoint bypasses the fd write path (CutFromIOBufList
//     rdma_endpoint.cpp:777 posts IOBuf blocks as SGEs zero-copy),
//     delivers completions through a comp-channel fd registered with the
//     normal EventDispatcher (PollCq rdma_endpoint.cpp:1364), and rejoins
//     the standard InputMessenger parse pipeline
//     (input_messenger.cpp:416). The four pillars preserved here (SURVEY
//     §2.9): zero-copy block posting, windowed credit flow control, event
//     suppression/batched completions, completions unified into the one
//     event dispatcher.
//
//  2. TransportTier — the REGISTRY of endpoint types (ISSUE 12): fd/tcp,
//     in-process ici, cross-process shm, device staging — each described
//     once (name, descriptor capability, zero-copy, process scope) so
//     descriptor eligibility, credit-flow accounting, and byte
//     attribution live in ONE seam instead of per-transport special
//     cases scattered through socket/policy code. This is the layering
//     the reference's RDMA endpoint implies and the prerequisite for a
//     DCN-class tier: a new transport is a new registry entry + endpoint
//     implementation, not a fork of the data path.
#pragma once

#include <sys/types.h>

#include <cstdint>
#include <string>

#include "tbase/iobuf.h"

namespace tpurpc {

class Socket;

// ---- the transport tier registry ----

// Static properties of one peer-endpoint type. Registered once; the id
// is stable for the process lifetime and labels the per-tier
// rpc_transport_* attribution families.
struct TransportTier {
    const char* name = "";
    // One-sided pool descriptors may ride this transport: the peers'
    // handshake maps each other's registered pools (or the peer IS this
    // process), so a (pool_id, offset, len) reference resolves on the
    // other side. Send-side eligibility AND resolve-side scope both
    // consult this — the one seam deciding "may a payload cross as a
    // reference here".
    bool descriptor_capable = false;
    // Payload blocks post by reference (ring descriptors), not by copy
    // through a byte stream.
    bool zero_copy = false;
    // The peer lives in another process (its pool is mapped shm, not
    // this process's own allocator).
    bool cross_process = false;
    // One-sided verbs (ISSUE 18): REMOTE_READ/REMOTE_WRITE posted
    // against leased pool windows move data with ZERO remote CPU on the
    // data path (shm_xproc memcpy-direct today). Tiers without the bit
    // degrade to wire-emulated two-sided verbs through the same seam.
    bool one_sided = false;
    // Max scatter-gather entries one posted verb may carry (0 = no SGL;
    // a multi-block post must be emulated entry-by-entry).
    uint32_t sgl_max = 0;
};

// Register a tier; returns its id (stable, small). Re-registering an
// existing name returns the existing id. Bounded (16) — a runaway
// registration is a bug, not a workload.
int RegisterTransportTier(const TransportTier& t);
const TransportTier* GetTransportTier(int tier);  // null for bad ids
int FindTransportTier(const char* name);          // -1 when unknown
int TransportTierCount();

// Built-in tiers, registered lazily on first use (stable within a
// process; always present once any socket/pool code ran).
int TierTcp();       // plain fd byte stream (TLS included)
int TierIci();       // in-process queue-pair link (loopback ICI)
int TierShmXproc();  // cross-process shared-memory queue pair
int TierDevice();    // device staging ring (peer = the accelerator)
// Cross-pod data-center-network tier (ISSUE 14): a plain fd byte
// stream to a peer in ANOTHER pod. Descriptor-INCAPABLE — the peers
// share no pool mapping, so descriptor-pinned tries degrade to inline
// through the existing seam — and shaped by the -dcn_emu_* knobs so
// non-datacenter containers can emulate WAN latency/bandwidth.
int TierDcn();

// ---- emulated-WAN shaping for the dcn tier (ISSUE 14) ----
// Microseconds a writer should park before moving `bytes` on `tier`:
// -dcn_emu_latency_us (per write op) + bytes/-dcn_emu_mbps. 0 for
// non-dcn tiers or when both knobs are off. Per-connection shaping by
// design (each KeepWrite fiber sleeps independently) — the knob
// emulates a WAN pipe per flow, not an aggregate trunk.
int64_t DcnShapeDelayUs(int tier, size_t bytes);
// The inbound half: bytes/-dcn_emu_mbps ONLY — latency is charged once
// per message at the writer; read-burst boundaries are an artifact of
// kernel buffer sizes, not messages, so charging the fixed latency per
// read would tax a large transfer by how it happened to fragment.
int64_t DcnShapeReadDelayUs(int tier, size_t bytes);
// One relaxed check for the write hot path: true when any shaping knob
// is live (writers then route through the KeepWrite fiber, where
// sleeping is legal).
bool DcnShapingEnabled();

// ---- descriptor eligibility / scope (the one seam) ----

// The pool layer (tici/block_pool.cc Init) tells the transport tier how
// to name THIS process's shared pool without tnet depending on tici.
void SetLocalPoolIdProvider(uint64_t (*provider)());
uint64_t TransportLocalPoolId();  // 0 when no shared pool exists

// Send-side eligibility: may a pool descriptor (either direction) ride
// this socket? True exactly when the socket's tier is
// descriptor-capable — the peer either mapped our pool at handshake
// (cross-process tiers map both ways) or IS this process (in-process
// tiers resolve the local pool directly).
bool TransportDescriptorCapable(const Socket* s);

// Resolve-side scope: may a descriptor arriving ON this socket name
// `pool_id`? Only the pool this connection's handshake mapped
// (Socket::peer_pool_id) or — on an in-process transport — this
// process's own pool. The global pool registry alone must never
// authorize: any connection could otherwise name another tenant's
// mapped pool and read memory it was never handed.
bool TransportDescriptorScopeOk(const Socket* s, uint64_t pool_id);

// Verb eligibility (ISSUE 18): may one-sided verbs move data directly
// on this socket? Tier one_sided bit AND descriptor eligibility (a
// window is a pool reference, so the same pool-mapping evidence
// applies). False routes posts through the emulated two-sided path.
bool TransportOneSided(const Socket* s);
// The socket tier's sgl_max (0 when null/one-sided-incapable).
uint32_t TransportSglMax(const Socket* s);

// ---- per-tier byte/credit attribution ----
// Every transport's data-plane volume lands in one labelled family set
// (rpc_transport_{in,out}_bytes / rpc_transport_desc_{in,out}_bytes /
// rpc_transport_credit_stalls / rpc_transport_ops{transport=...}) so
// /pools and /metrics show WHERE bytes move without per-transport
// special cases. Hot paths add to pre-resolved cells — one relaxed
// fetch_add per call.
namespace transport_stats {
void AddIn(int tier, int64_t bytes);    // bytes received/pumped
void AddOut(int tier, int64_t bytes);   // bytes written/posted
void AddDescIn(int tier, int64_t bytes);   // descriptor-referenced, in
void AddDescOut(int tier, int64_t bytes);  // descriptor-referenced, out
void AddCreditStall(int tier);  // writer parked waiting for window credits
void AddOp(int tier);           // writes/pumps/ring completes

// Test/portal reads.
int64_t in_bytes(int tier);
int64_t out_bytes(int tier);
int64_t desc_in_bytes(int tier);
int64_t desc_out_bytes(int tier);
int64_t credit_stalls(int tier);
int64_t ops(int tier);

// One "tier <name> caps=... in=... out=... desc_in=... desc_out=...
// stalls=... ops=..." line per registered tier (the /pools section).
std::string DebugString();
// Register the labelled rpc_transport_* families eagerly (idempotent)
// so /metrics and the lint see them before the first byte moves.
void ExposeVars();
}  // namespace transport_stats

// ---- the per-socket data-plane endpoint ----

class TransportEndpoint {
public:
    virtual ~TransportEndpoint() = default;

    // The doorbell/completion fd. Registered with the EventDispatcher as
    // the Socket's fd (the comp-channel-fd pattern): readable when data
    // arrived or credits freed.
    virtual int event_fd() const = 0;

    // True once the endpoint can carry data (post-handshake).
    virtual bool Established() const = 0;

    // Post bytes from pieces[0..count) into the send queue, zero-copy:
    // block references are held by the queue until the remote side
    // completes them. Returns bytes posted (pieces are pop_front'd);
    // -1/EAGAIN when out of window credits; -1/other errno on failure.
    virtual ssize_t CutFromIOBufList(IOBuf* const* pieces, size_t count) = 0;

    // Block the calling fiber until credits may be available (woken by the
    // pump when the peer consumes). Returns 0, or -1 on timeout/failure.
    virtual int WaitWritable(int64_t abstime_us) = 0;

    // Drain the completion queue: move received bytes into *dst, release
    // send-side refs completed by the peer, wake writable waiters.
    // fd-read semantics: >0 bytes appended; 0 = peer closed (EOF);
    // -1/EAGAIN = nothing pending.
    virtual ssize_t Pump(IOPortal* dst) = 0;

    // Half-close: peer's next drained Pump returns EOF. Idempotent.
    virtual void Close() = 0;

    // Drop the owner's reference (a Socket with owns_transport, or the
    // harness). The endpoint's backing link frees itself when every
    // endpoint is released — the socket and the peer's socket can tear
    // down in any order without dangling pipes.
    virtual void Release() {}

    // Which registry tier this endpoint belongs to. The TLS transport is
    // still the fd byte-stream tier (encrypted TCP); queue-pair
    // endpoints override with their own tier.
    virtual int tier() const { return TierTcp(); }
};

}  // namespace tpurpc
