// TransportEndpoint: the pluggable data-plane seam of Socket — how an
// ICI/shm queue-pair transport takes over reads and writes while Socket
// keeps the id/lifecycle/wait-free-queue semantics.
//
// Modeled on the role of reference src/brpc/rdma/rdma_endpoint.h: the
// RDMA endpoint bypasses the fd write path (CutFromIOBufList
// rdma_endpoint.cpp:777 posts IOBuf blocks as SGEs zero-copy), delivers
// completions through a comp-channel fd registered with the normal
// EventDispatcher (PollCq rdma_endpoint.cpp:1364), and rejoins the
// standard InputMessenger parse pipeline (input_messenger.cpp:416). The
// four pillars preserved here (SURVEY §2.9): zero-copy block posting,
// windowed credit flow control, event suppression/batched completions,
// completions unified into the one event dispatcher.
#pragma once

#include <sys/types.h>

#include "tbase/iobuf.h"

namespace tpurpc {

class TransportEndpoint {
public:
    virtual ~TransportEndpoint() = default;

    // The doorbell/completion fd. Registered with the EventDispatcher as
    // the Socket's fd (the comp-channel-fd pattern): readable when data
    // arrived or credits freed.
    virtual int event_fd() const = 0;

    // True once the endpoint can carry data (post-handshake).
    virtual bool Established() const = 0;

    // Post bytes from pieces[0..count) into the send queue, zero-copy:
    // block references are held by the queue until the remote side
    // completes them. Returns bytes posted (pieces are pop_front'd);
    // -1/EAGAIN when out of window credits; -1/other errno on failure.
    virtual ssize_t CutFromIOBufList(IOBuf* const* pieces, size_t count) = 0;

    // Block the calling fiber until credits may be available (woken by the
    // pump when the peer consumes). Returns 0, or -1 on timeout/failure.
    virtual int WaitWritable(int64_t abstime_us) = 0;

    // Drain the completion queue: move received bytes into *dst, release
    // send-side refs completed by the peer, wake writable waiters.
    // fd-read semantics: >0 bytes appended; 0 = peer closed (EOF);
    // -1/EAGAIN = nothing pending.
    virtual ssize_t Pump(IOPortal* dst) = 0;

    // Half-close: peer's next drained Pump returns EOF. Idempotent.
    virtual void Close() = 0;

    // Drop the owner's reference (a Socket with owns_transport, or the
    // harness). The endpoint's backing link frees itself when every
    // endpoint is released — the socket and the peer's socket can tear
    // down in any order without dangling pipes.
    virtual void Release() {}
};

}  // namespace tpurpc
