// TLS transport: SSL/TLS as a TransportEndpoint over the raw fd, with
// ALPN (h2) negotiation. OpenSSL 3 is reached via dlopen(libssl.so.3) +
// hand-declared prototypes — this image ships the runtime library but
// not the dev headers, and the libssl C ABI is stable. When libssl is
// absent, TlsAvailable() is false and TLS-configured servers/channels
// fail Init cleanly.
//
// Reference parity: /root/reference/src/brpc/details/ssl_helper.cpp
// (CreateClientSSLContext/CreateServerSSLContext, ALPN in
// server.cpp/ssl_helper) — re-shaped as a transport so every protocol
// (h2, HTTP/1, gRPC) rides it unchanged, the way the RDMA endpoint
// slots under the socket.
#pragma once

#include <string>

#include "tnet/transport.h"

namespace tpurpc {

bool TlsAvailable();

// Process-wide server TLS context from PEM files. Returns 0, or -1
// (missing libssl / bad cert). ALPN: advertises+selects "h2" and
// "http/1.1" (the h2-before-HTTP/1 sniff order of the InputMessenger
// then routes either result; nothing needs the negotiated name).
int TlsServerInit(const std::string& cert_pem_path,
                  const std::string& key_pem_path);

// Wrap an accepted fd in a server-side TLS session (handshake driven
// lazily by Pump/CutFromIOBufList). Null on failure.
TransportEndpoint* NewTlsServerTransport(int fd);

// Wrap a connected client fd; `alpn` e.g. "h2" (empty = no ALPN),
// `sni` the server name (empty = none). Certificate verification is
// OFF by default (self-signed test rigs; the reference's default is
// VERIFY_NONE too).
TransportEndpoint* NewTlsClientTransport(int fd, const std::string& alpn,
                                         const std::string& sni);

}  // namespace tpurpc
