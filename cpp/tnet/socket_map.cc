#include "tnet/socket_map.h"

#include "tnet/input_messenger.h"

namespace tpurpc {

SocketMap* SocketMap::singleton() {
    static SocketMap* m = new SocketMap;
    return m;
}

int SocketMap::GetOrCreate(const EndPoint& remote, InputMessenger* messenger,
                           SocketId* id) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = map_.find(remote);
    if (it != map_.end()) {
        // Verify liveness: a failed socket is replaced.
        Socket* s = Socket::Address(it->second);
        if (s != nullptr) {
            *id = it->second;
            s->Dereference();
            return 0;
        }
        map_.erase(it);
    }
    SocketOptions opts;
    opts.fd = -1;  // connect on first write
    opts.remote_side = remote;
    opts.on_edge_triggered_events = &InputMessenger::OnNewMessages;
    opts.user = messenger;
    if (Socket::Create(opts, id) != 0) return -1;
    map_[remote] = *id;
    return 0;
}

void SocketMap::Remove(const EndPoint& remote, SocketId expected_id) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = map_.find(remote);
    if (it != map_.end() && it->second == expected_id) {
        map_.erase(it);
    }
}

}  // namespace tpurpc
