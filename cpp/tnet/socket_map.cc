#include "tnet/socket_map.h"

#include "tbase/flags.h"
#include "tbase/time.h"
#include "tfiber/fiber.h"
#include "tnet/input_messenger.h"

// Must comfortably exceed the expected per-server concurrency: a caller
// that can't find an idle pooled connection creates a fresh one, and
// Return() CLOSES it when the pool is at capacity — an undersized cap
// turns pooled mode into connect-per-call (the reference's
// max_connection_pool_size defaults to 100 for the same reason).
DEFINE_int32(max_pooled_connections_per_remote, 128,
             "idle pooled connections kept per server");
DEFINE_int32(pooled_idle_close_s, 30,
             "close pooled connections idle this long; <=0 disables");

namespace tpurpc {

int CreateClientSocket(const EndPoint& remote, InputMessenger* messenger,
                       SocketId* id, int tier) {
    SocketOptions opts;
    opts.fd = -1;  // connect on first write
    opts.remote_side = remote;
    opts.on_edge_triggered_events = &InputMessenger::OnNewMessages;
    opts.user = messenger;
    opts.forced_transport_tier = tier;
    return Socket::Create(opts, id);
}

SocketMap* SocketMap::singleton() {
    static SocketMap* m = new SocketMap;
    return m;
}

int SocketMap::GetOrCreate(const EndPoint& remote, InputMessenger* messenger,
                           SocketId* id, int tier) {
    std::lock_guard<std::mutex> g(mu_);
    const Key key{remote, tier};
    auto it = map_.find(key);
    if (it != map_.end()) {
        // Verify liveness: a failed socket is replaced.
        Socket* s = Socket::Address(it->second);
        if (s != nullptr) {
            *id = it->second;
            s->Dereference();
            return 0;
        }
        map_.erase(it);
    }
    if (CreateClientSocket(remote, messenger, id, tier) != 0) return -1;
    map_[key] = *id;
    return 0;
}

void SocketMap::Remove(const EndPoint& remote, SocketId expected_id,
                       int tier) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = map_.find(Key{remote, tier});
    if (it != map_.end() && it->second == expected_id) {
        map_.erase(it);
    }
}

std::vector<EndPoint> SocketMap::endpoints() {
    std::lock_guard<std::mutex> g(mu_);
    std::vector<EndPoint> out;
    out.reserve(map_.size());
    for (const auto& kv : map_) {
        // One entry per remote even when both a tcp and a dcn socket
        // exist (the stitcher fans out per address, not per tier).
        if (out.empty() || !(out.back() == kv.first.first)) {
            out.push_back(kv.first.first);
        }
    }
    return out;
}


// ---------------- SocketPool ----------------

SocketPool* SocketPool::singleton() {
    static SocketPool* p = new SocketPool;
    return p;
}

int SocketPool::Get(const EndPoint& remote, InputMessenger* messenger,
                    SocketId* id, int tier) {
    {
        std::lock_guard<std::mutex> g(mu_);
        auto it = pools_.find(Key{remote, tier});
        if (it != pools_.end()) {
            auto& idle = it->second;
            // FIFO: take the LEAST recently returned member so load
            // round-robins across the pool (and thus across the epoll
            // loops its fds shard onto) instead of convoying on the
            // hottest socket.
            while (!idle.empty()) {
                const SocketId cand = idle.front().id;
                idle.pop_front();
                Socket* s = Socket::Address(cand);
                if (s != nullptr) {
                    s->Dereference();
                    *id = cand;
                    return 0;
                }
                // failed while idle: skip
            }
        }
        if (!sweeping_ && FLAGS_pooled_idle_close_s.get() > 0) {
            sweeping_ = true;
            fiber_t tid;
            auto* self = this;
            if (fiber_start_background(
                    &tid, nullptr,
                    [](void* arg) -> void* {
                        ((SocketPool*)arg)->SweepLoop();
                        return nullptr;
                    },
                    self) != 0) {
                sweeping_ = false;
            }
        }
    }
    return CreateClientSocket(remote, messenger, id, tier);
}

void SocketPool::Return(SocketId id) {
    SocketUniquePtr s = SocketUniquePtr::FromId(id);
    if (!s) return;  // failed meanwhile: nothing to pool
    std::lock_guard<std::mutex> g(mu_);
    // The tier half of the key comes back off the socket itself, so a
    // dcn fly connection returns to the dcn pool it was drawn from.
    auto& idle = pools_[Key{s->remote_side(), s->forced_transport_tier()}];
    if ((int)idle.size() >= FLAGS_max_pooled_connections_per_remote.get()) {
        s->SetFailed();  // over capacity: close instead
        return;
    }
    idle.push_back(IdleConn{id, monotonic_time_us()});
}

size_t SocketPool::idle_count(const EndPoint& remote, int tier) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = pools_.find(Key{remote, tier});
    return it == pools_.end() ? 0 : it->second.size();
}

void SocketPool::SweepLoop() {
    while (true) {
        fiber_usleep(2 * 1000 * 1000);
        const int64_t idle_limit_us =
            (int64_t)FLAGS_pooled_idle_close_s.get() * 1000 * 1000;
        if (idle_limit_us <= 0) continue;
        const int64_t now = monotonic_time_us();
        std::vector<SocketId> to_close;
        {
            std::lock_guard<std::mutex> g(mu_);
            for (auto& kv : pools_) {
                auto& idle = kv.second;
                size_t w = 0;
                for (size_t i = 0; i < idle.size(); ++i) {
                    if (now - idle[i].returned_us > idle_limit_us) {
                        to_close.push_back(idle[i].id);
                    } else {
                        idle[w++] = idle[i];
                    }
                }
                idle.resize(w);
            }
            for (auto it = pools_.begin(); it != pools_.end();) {
                it = it->second.empty() ? pools_.erase(it) : std::next(it);
            }
        }
        for (SocketId id : to_close) {
            Socket::SetFailedById(id);
        }
    }
}

}  // namespace tpurpc
