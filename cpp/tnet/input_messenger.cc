#include "tnet/input_messenger.h"

#include <sys/socket.h>

#include <algorithm>
#include <cerrno>

#include "tbase/errno.h"
#include "tbase/flags.h"
#include "tbase/flight_recorder.h"
#include "tbase/logging.h"
#include "tfiber/fiber.h"
#include "tfiber/task_group.h"
#include "tnet/fault_injection.h"
#include "tnet/transport.h"
#include "tvar/reducer.h"

// Run-to-completion dispatch (ISSUE 7): up to this many small messages
// per readiness burst process ON the input fiber (no spawn, no switch);
// the rest fan out to fibers as before, so a huge burst still uses every
// core. 0 disables inlining entirely.
DEFINE_int32(inline_dispatch_budget, 64,
             "inline-safe messages processed on the input fiber per "
             "readiness burst before fanning out; 0 disables");
DEFINE_int32(inline_dispatch_max_bytes, 16384,
             "largest message (header+body) eligible for inline dispatch");

namespace tpurpc {

// ---------------- inline dispatch budget ----------------

namespace inline_dispatch {

namespace {
// Armed/spent budget of the current thread's messenger round. Reset on
// fiber park via the task_group park hook (the resumed fiber may be on
// another thread; its round is conservatively over).
thread_local int g_budget = 0;
thread_local bool g_armed = false;
// True only while a message that Acquire() admitted is being processed
// inline — the Refund() guard: fan-out paths (pending chain, process
// fibers) also reach the RPC layer, but never through an Acquire, and
// must not give back budget they never took.
thread_local bool g_acquired_current = false;

LazyAdder* dispatches_adder() {
    static auto* a = new LazyAdder("rpc_dispatcher_inline_dispatches");
    return a;
}
LazyAdder* overflows_adder() {
    static auto* a = new LazyAdder("rpc_dispatcher_inline_overflows");
    return a;
}
LazyAdder* handler_adder() {
    static auto* a = new LazyAdder("rpc_server_inline_handlers");
    return a;
}
LazyAdder* desc_exempt_adder() {
    static auto* a =
        new LazyAdder("rpc_dispatcher_descriptor_exempt_bytes");
    return a;
}

void ResetOnPark() {
    g_budget = 0;
    g_armed = false;
    g_acquired_current = false;
}

void ArmRound() {
    static const bool hook_registered = [] {
        register_park_hook(&ResetOnPark);
        return true;
    }();
    (void)hook_registered;
    g_budget = FLAGS_inline_dispatch_budget.get();
    g_armed = g_budget > 0;
}

void DisarmRound() {
    g_budget = 0;
    g_armed = false;
    g_acquired_current = false;
}

void EndInlineProcess() { g_acquired_current = false; }
}  // namespace

bool RoundArmed() { return g_armed; }

bool Acquire(size_t nbytes) {
    if (!g_armed || nbytes == 0 ||
        nbytes > (size_t)FLAGS_inline_dispatch_max_bytes.get()) {
        return false;
    }
    if (g_budget <= 0) {
        **overflows_adder() << 1;
        return false;
    }
    --g_budget;
    g_acquired_current = true;
    **dispatches_adder() << 1;
    flight::Record(flight::kSchedInline, nbytes, 0);
    return true;
}

void Refund() {
    // Only a message Acquire() admitted may give its unit back — and it
    // did NOT run to completion after all (the layer above fanned it
    // out), so take back Acquire's count too: inline_dispatches reports
    // actual run-to-completion messages.
    if (g_armed && g_acquired_current) {
        ++g_budget;
        g_acquired_current = false;
        **dispatches_adder() << -1;
    }
}

int64_t dispatches() { return (**dispatches_adder()).get_value(); }
int64_t overflows() { return (**overflows_adder()).get_value(); }
int64_t handler_inlines() { return (**handler_adder()).get_value(); }
void CountHandlerInline() { **handler_adder() << 1; }
void ExemptDescriptorBytes(size_t nbytes) {
    **desc_exempt_adder() << (int64_t)nbytes;
}
int64_t descriptor_exempt_bytes() {
    return (**desc_exempt_adder()).get_value();
}

}  // namespace inline_dispatch

namespace {

constexpr size_t kReadBurst = 512 * 1024;

// Chaos seam for the plain-fd read path (transports consult the
// injection layer inside their own Pump implementations). Same contract
// as append_from_file_descriptor: >0 bytes made progress, 0 EOF, -1 with
// errno (EAGAIN = drained).
ssize_t ChaosReadFromFd(Socket* s) {
    const FaultAction fa =
        FaultInjection::Decide(FaultOp::kRead, s->remote_side(), kReadBurst);
    switch (fa.kind) {
        case FaultAction::kReset:
            errno = ECONNRESET;
            return -1;
        case FaultAction::kDelay:
            fiber_usleep(fa.delay_us);
            break;
        case FaultAction::kShort:
            return s->read_buf.append_from_file_descriptor(
                s->fd(), std::max<size_t>(1, fa.max_bytes));
        case FaultAction::kDrop: {
            // Read and discard: bytes vanish from the stream (the peer
            // believes they arrived). Returning r > 0 with nothing
            // appended just reports progress to the caller's loop.
            char tmp[4096];
            const ssize_t r = recv(s->fd(), tmp, sizeof(tmp), 0);
            return r;
        }
        case FaultAction::kCorrupt: {
            char tmp[4096];
            const ssize_t r = recv(s->fd(), tmp, sizeof(tmp), 0);
            if (r <= 0) return r;
            tmp[fa.aux % (uint64_t)r] ^= 0x20;
            s->read_buf.append(tmp, (size_t)r);
            return r;
        }
        default:
            break;
    }
    return s->read_buf.append_from_file_descriptor(s->fd(), kReadBurst);
}

struct ProcessArgs {
    InputMessageBase* msg;
    const Protocol* proto;
};

void* process_msg_thunk(void* arg) {
    ProcessArgs* pa = (ProcessArgs*)arg;
    pa->proto->process(pa->msg);
    delete pa;
    return nullptr;
}

// Cut one message. Returns OK/NOT_ENOUGH_DATA/ERROR (TRY_OTHERS resolved
// internally by iterating the messenger's protocol set).
ParseResult CutInputMessage(Socket* s, const std::vector<int>& protocols,
                            bool read_eof) {
    // Preferred protocol first (sniffed once per connection, reference
    // input_messenger.cpp:84).
    if (s->preferred_protocol_index >= 0) {
        const Protocol* p = GetProtocol(s->preferred_protocol_index);
        // Zero-cut fast path (ISSUE 7): peek the fixed header from
        // contiguous bytes, learn the full frame size ONCE, then skip
        // parse entirely until the frame is complete — a large message
        // arriving in many reads costs one peek instead of a cut/re-parse
        // per read.
        if (p->peek != nullptr) {
            if (s->pending_frame_bytes == 0) {
                if (s->read_buf.size() < p->peek_len) {
                    // Split header: wait (only sticky sockets take this
                    // path, so the bytes can only be this protocol's).
                    return ParseResult::make(ParseError::NOT_ENOUGH_DATA);
                }
                char aux[64];
                CHECK_LE(p->peek_len, sizeof(aux));
                const char* hdr =
                    (const char*)s->read_buf.fetch(aux, p->peek_len);
                const int64_t total = p->peek(hdr, s);
                if (total < 0) {
                    return ParseResult::make(ParseError::ERROR);
                }
                if (total == 0) {
                    // Not this protocol after all: drop stickiness and
                    // re-sniff below (the TRY_OTHERS contract).
                    s->preferred_protocol_index = -1;
                } else {
                    s->pending_frame_bytes = total;
                }
            }
            if (s->pending_frame_bytes > 0) {
                if (s->read_buf.size() < (size_t)s->pending_frame_bytes) {
                    return ParseResult::make(ParseError::NOT_ENOUGH_DATA);
                }
                s->pending_frame_bytes = 0;
                ParseResult r =
                    p->parse(&s->read_buf, s, read_eof, p->parse_arg);
                if (r.error == ParseError::OK) {
                    r.msg->protocol_index = s->preferred_protocol_index;
                    return r;
                }
                if (r.error == ParseError::ERROR) return r;
                // A complete peeked frame the parser then refused:
                // inconsistent parser state — drop stickiness and
                // re-sniff (defensive; peek and parse agree by
                // construction).
                s->preferred_protocol_index = -1;
            }
        } else {
            ParseResult r = p->parse(&s->read_buf, s, read_eof, p->parse_arg);
            if (r.error != ParseError::TRY_OTHERS) {
                if (r.error == ParseError::OK) {
                    r.msg->protocol_index = s->preferred_protocol_index;
                }
                return r;
            }
            s->preferred_protocol_index = -1;  // re-sniff
        }
    }
    for (int idx : protocols) {
        const Protocol* p = GetProtocol(idx);
        if (p == nullptr || p->parse == nullptr) continue;
        ParseResult r = p->parse(&s->read_buf, s, read_eof, p->parse_arg);
        if (r.error == ParseError::OK) {
            s->preferred_protocol_index = idx;
            r.msg->protocol_index = idx;
            return r;
        }
        if (r.error == ParseError::NOT_ENOUGH_DATA ||
            r.error == ParseError::ERROR) {
            return r;
        }
        // TRY_OTHERS: next protocol.
    }
    return ParseResult::make(s->read_buf.empty() ? ParseError::NOT_ENOUGH_DATA
                                                 : ParseError::TRY_OTHERS);
}

}  // namespace

void InputMessenger::OnNewMessages(Socket* s) {
    InputMessenger* m = (InputMessenger*)s->user();
    if (m == nullptr) return;
    bool read_eof = false;
    // Round scopes (ISSUE 7), flushed once per cut round below: fiber
    // wakeups batch into one futex signal per pool, responses written
    // during the round coalesce into one writev per socket. Chaos mode
    // skips the read-path arming implicitly: injected delays park this
    // fiber, and sched_park flushes + detaches both scopes safely.
    WakeBatcher wake_batch;
    WriteCoalesceScope write_scope;
    while (!s->Failed()) {
        if (!read_eof) {
            // ICI transport sockets pump their completion queue (identical
            // nr semantics); fd sockets readv (reference
            // input_messenger.cpp:416 checks _rdma_state the same way).
            ssize_t nr;
            if (s->transport() != nullptr) {
                nr = s->transport()->Pump(&s->read_buf);
            } else if (__builtin_expect(fault_injection_enabled(), 0)) {
                nr = ChaosReadFromFd(s);
            } else {
                nr = s->read_buf.append_from_file_descriptor(s->fd(),
                                                             kReadBurst);
            }
            if (nr > 0) {
                s->add_bytes_read(nr);
                // Per-tier byte attribution (the Transport seam).
                transport_stats::AddIn(s->transport_tier(), nr);
                // Emulated-WAN shaping, inbound half (ISSUE 14): a
                // dcn-tier socket charges received bytes too — the
                // peer's half of the link is an accepted socket with no
                // forced tier, so without this the response direction
                // would ride the WAN for free. Each direction is shaped
                // exactly once (writes on the dcn socket, reads on the
                // dcn socket). Parking this fiber is legal here, same
                // as the chaos delay path (the round scopes flush and
                // detach on park).
                if (__builtin_expect(s->forced_transport_tier() >= 0, 0) &&
                    DcnShapingEnabled()) {
                    const int64_t d = DcnShapeReadDelayUs(
                        s->transport_tier(), (size_t)nr);
                    if (d > 0) fiber_usleep(d);
                }
            } else if (nr == 0) {
                read_eof = true;
            } else {
                if (errno == EAGAIN || errno == EWOULDBLOCK) {
                    return;  // burst drained; next edge re-triggers
                }
                if (errno == EINTR) continue;
                s->SetFailedWithError(errno);
                return;
            }
        }
        // Cut as many whole messages as the buffer holds. Dispatch policy
        // (run-to-completion, ISSUE 7): small messages of inline-safe
        // protocols process RIGHT HERE on the input fiber while the
        // per-wake budget lasts — no spawn, no context switch, and their
        // response writes coalesce in this round's scope. Past the budget
        // (or for large/unsafe messages) the old fan-out applies: one
        // fiber per message, keeping the LAST message inline for cache
        // locality (reference input_messenger.cpp:194-234 QueueMessage),
        // so a slow handler can't block parsing.
        inline_dispatch::ArmRound();
        InputMessageBase* pending_msg = nullptr;
        const Protocol* pending_proto = nullptr;
        while (!s->read_buf.empty()) {
            ParseResult r = CutInputMessage(s, m->protocols_, read_eof);
            if (r.error == ParseError::OK) {
                r.msg->socket_id = s->id();
                const Protocol* p = GetProtocol(r.msg->protocol_index);
                if (p->process_in_order) {
                    // No correlation ids on this protocol: responses must
                    // leave in request order, so run inline right now.
                    p->process(r.msg);
                    continue;
                }
                if (p->inline_safe &&
                    inline_dispatch::Acquire(r.msg->byte_size)) {
                    p->process(r.msg);  // run-to-completion
                    inline_dispatch::EndInlineProcess();
                    continue;
                }
                if (pending_msg != nullptr) {
                    auto* pa = new ProcessArgs{pending_msg, pending_proto};
                    fiber_t tid;
                    if (fiber_start_background(&tid, nullptr,
                                               process_msg_thunk, pa) != 0) {
                        pending_proto->process(pending_msg);
                        delete pa;
                    }
                }
                pending_msg = r.msg;
                pending_proto = p;
                continue;
            }
            if (r.error == ParseError::NOT_ENOUGH_DATA) break;
            // TRY_OTHERS with data left or hard ERROR: broken stream.
            inline_dispatch::DisarmRound();
            s->SetFailedWithError(TERR_REQUEST);
            if (pending_msg != nullptr) pending_proto->process(pending_msg);
            return;
        }
        if (pending_msg != nullptr) {
            pending_proto->process(pending_msg);
        }
        inline_dispatch::DisarmRound();
        // End of round: queued responses leave in one writev per socket,
        // woken fibers get one futex signal per pool.
        write_scope.FlushDeferred();
        wake_batch.Flush();
        if (read_eof) {
            s->SetFailedWithError(TERR_EOF);
            return;
        }
    }
}

}  // namespace tpurpc
