#include "tnet/input_messenger.h"

#include <sys/socket.h>

#include <algorithm>
#include <cerrno>

#include "tbase/errno.h"
#include "tbase/logging.h"
#include "tfiber/fiber.h"
#include "tnet/fault_injection.h"
#include "tnet/transport.h"

namespace tpurpc {

namespace {

constexpr size_t kReadBurst = 512 * 1024;

// Chaos seam for the plain-fd read path (transports consult the
// injection layer inside their own Pump implementations). Same contract
// as append_from_file_descriptor: >0 bytes made progress, 0 EOF, -1 with
// errno (EAGAIN = drained).
ssize_t ChaosReadFromFd(Socket* s) {
    const FaultAction fa =
        FaultInjection::Decide(FaultOp::kRead, s->remote_side(), kReadBurst);
    switch (fa.kind) {
        case FaultAction::kReset:
            errno = ECONNRESET;
            return -1;
        case FaultAction::kDelay:
            fiber_usleep(fa.delay_us);
            break;
        case FaultAction::kShort:
            return s->read_buf.append_from_file_descriptor(
                s->fd(), std::max<size_t>(1, fa.max_bytes));
        case FaultAction::kDrop: {
            // Read and discard: bytes vanish from the stream (the peer
            // believes they arrived). Returning r > 0 with nothing
            // appended just reports progress to the caller's loop.
            char tmp[4096];
            const ssize_t r = recv(s->fd(), tmp, sizeof(tmp), 0);
            return r;
        }
        case FaultAction::kCorrupt: {
            char tmp[4096];
            const ssize_t r = recv(s->fd(), tmp, sizeof(tmp), 0);
            if (r <= 0) return r;
            tmp[fa.aux % (uint64_t)r] ^= 0x20;
            s->read_buf.append(tmp, (size_t)r);
            return r;
        }
        default:
            break;
    }
    return s->read_buf.append_from_file_descriptor(s->fd(), kReadBurst);
}

struct ProcessArgs {
    InputMessageBase* msg;
    const Protocol* proto;
};

void* process_msg_thunk(void* arg) {
    ProcessArgs* pa = (ProcessArgs*)arg;
    pa->proto->process(pa->msg);
    delete pa;
    return nullptr;
}

// Cut one message. Returns OK/NOT_ENOUGH_DATA/ERROR (TRY_OTHERS resolved
// internally by iterating the messenger's protocol set).
ParseResult CutInputMessage(Socket* s, const std::vector<int>& protocols,
                            bool read_eof) {
    // Preferred protocol first (sniffed once per connection, reference
    // input_messenger.cpp:84).
    if (s->preferred_protocol_index >= 0) {
        const Protocol* p = GetProtocol(s->preferred_protocol_index);
        ParseResult r = p->parse(&s->read_buf, s, read_eof, p->parse_arg);
        if (r.error != ParseError::TRY_OTHERS) {
            if (r.error == ParseError::OK) {
                r.msg->protocol_index = s->preferred_protocol_index;
            }
            return r;
        }
        s->preferred_protocol_index = -1;  // re-sniff
    }
    for (int idx : protocols) {
        const Protocol* p = GetProtocol(idx);
        if (p == nullptr || p->parse == nullptr) continue;
        ParseResult r = p->parse(&s->read_buf, s, read_eof, p->parse_arg);
        if (r.error == ParseError::OK) {
            s->preferred_protocol_index = idx;
            r.msg->protocol_index = idx;
            return r;
        }
        if (r.error == ParseError::NOT_ENOUGH_DATA ||
            r.error == ParseError::ERROR) {
            return r;
        }
        // TRY_OTHERS: next protocol.
    }
    return ParseResult::make(s->read_buf.empty() ? ParseError::NOT_ENOUGH_DATA
                                                 : ParseError::TRY_OTHERS);
}

}  // namespace

void InputMessenger::OnNewMessages(Socket* s) {
    InputMessenger* m = (InputMessenger*)s->user();
    if (m == nullptr) return;
    bool read_eof = false;
    while (!s->Failed()) {
        if (!read_eof) {
            // ICI transport sockets pump their completion queue (identical
            // nr semantics); fd sockets readv (reference
            // input_messenger.cpp:416 checks _rdma_state the same way).
            ssize_t nr;
            if (s->transport() != nullptr) {
                nr = s->transport()->Pump(&s->read_buf);
            } else if (__builtin_expect(fault_injection_enabled(), 0)) {
                nr = ChaosReadFromFd(s);
            } else {
                nr = s->read_buf.append_from_file_descriptor(s->fd(),
                                                             kReadBurst);
            }
            if (nr > 0) {
                s->add_bytes_read(nr);
            } else if (nr == 0) {
                read_eof = true;
            } else {
                if (errno == EAGAIN || errno == EWOULDBLOCK) {
                    return;  // burst drained; next edge re-triggers
                }
                if (errno == EINTR) continue;
                s->SetFailedWithError(errno);
                return;
            }
        }
        // Cut as many whole messages as the buffer holds. A message is
        // processed inline when it is the last one cut from this burst
        // (reference input_messenger.cpp:194-234 QueueMessage keeps the
        // LAST message in-place for cache locality); earlier messages get
        // their own processing fiber so a slow handler can't block parsing.
        InputMessageBase* pending_msg = nullptr;
        const Protocol* pending_proto = nullptr;
        while (!s->read_buf.empty()) {
            ParseResult r = CutInputMessage(s, m->protocols_, read_eof);
            if (r.error == ParseError::OK) {
                r.msg->socket_id = s->id();
                const Protocol* p = GetProtocol(r.msg->protocol_index);
                if (p->process_in_order) {
                    // No correlation ids on this protocol: responses must
                    // leave in request order, so run inline right now.
                    p->process(r.msg);
                    continue;
                }
                if (pending_msg != nullptr) {
                    auto* pa = new ProcessArgs{pending_msg, pending_proto};
                    fiber_t tid;
                    if (fiber_start_background(&tid, nullptr,
                                               process_msg_thunk, pa) != 0) {
                        pending_proto->process(pending_msg);
                        delete pa;
                    }
                }
                pending_msg = r.msg;
                pending_proto = p;
                continue;
            }
            if (r.error == ParseError::NOT_ENOUGH_DATA) break;
            // TRY_OTHERS with data left or hard ERROR: broken stream.
            s->SetFailedWithError(TERR_REQUEST);
            if (pending_msg != nullptr) pending_proto->process(pending_msg);
            return;
        }
        if (pending_msg != nullptr) {
            pending_proto->process(pending_msg);
        }
        if (read_eof) {
            s->SetFailedWithError(TERR_EOF);
            return;
        }
    }
}

}  // namespace tpurpc
