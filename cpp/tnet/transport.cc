#include "tnet/transport.h"

#include <atomic>
#include <cstring>
#include <mutex>

#include "tbase/flags.h"
#include "tnet/socket.h"
#include "tvar/multi_dimension.h"
#include "tvar/reducer.h"

// Emulated WAN characteristics of the dcn tier (ISSUE 14): containers
// without a real data-center network can still exercise cross-pod
// routing, spill and hierarchical-collective economics. Applied per
// write op on the KeepWrite fiber; 0/0 = no shaping (LAN-speed dcn).
DEFINE_int64(dcn_emu_latency_us, 0,
             "emulated one-way latency added to every dcn-tier write op "
             "(0 = off)");
DEFINE_int64(dcn_emu_mbps, 0,
             "emulated per-connection dcn bandwidth cap in MB/s; writers "
             "sleep bytes/this per op (0 = unlimited)");

namespace tpurpc {

namespace {

constexpr int kMaxTiers = 16;

// Per-tier attribution cells, pre-resolved at registration so the hot
// paths (socket write/read, ring complete, descriptor resolve) pay one
// relaxed fetch_add — the PR-5 IntCell discipline.
struct TierSlot {
    TransportTier tier;
    IntCell* in = nullptr;
    IntCell* out = nullptr;
    IntCell* desc_in = nullptr;
    IntCell* desc_out = nullptr;
    IntCell* credit_stalls = nullptr;
    IntCell* ops = nullptr;
};

// Immortal registry: attribution runs from socket recycling, which can
// land during static teardown (same rule as the lease registry).
struct Registry {
    std::mutex mu;
    TierSlot slots[kMaxTiers];
    std::atomic<int> count{0};
    LabelledMetric<IntCell>* fam_in;
    LabelledMetric<IntCell>* fam_out;
    LabelledMetric<IntCell>* fam_desc_in;
    LabelledMetric<IntCell>* fam_desc_out;
    LabelledMetric<IntCell>* fam_stalls;
    LabelledMetric<IntCell>* fam_ops;
    Registry() {
        const std::vector<std::string> labels{"transport"};
        fam_in = new LabelledMetric<IntCell>("rpc_transport_in_bytes",
                                             labels);
        fam_out = new LabelledMetric<IntCell>("rpc_transport_out_bytes",
                                              labels);
        fam_desc_in = new LabelledMetric<IntCell>(
            "rpc_transport_desc_in_bytes", labels);
        fam_desc_out = new LabelledMetric<IntCell>(
            "rpc_transport_desc_out_bytes", labels);
        fam_stalls = new LabelledMetric<IntCell>(
            "rpc_transport_credit_stalls", labels);
        fam_ops = new LabelledMetric<IntCell>("rpc_transport_ops", labels);
    }
};

Registry& reg() {
    static Registry* r = new Registry;
    return *r;
}

std::atomic<uint64_t (*)()> g_local_pool_provider{nullptr};

}  // namespace

int RegisterTransportTier(const TransportTier& t) {
    Registry& r = reg();
    std::lock_guard<std::mutex> g(r.mu);
    const int n = r.count.load(std::memory_order_relaxed);
    for (int i = 0; i < n; ++i) {
        if (strcmp(r.slots[i].tier.name, t.name) == 0) return i;
    }
    if (n >= kMaxTiers) return -1;
    TierSlot& s = r.slots[n];
    s.tier = t;
    const std::vector<std::string> v{t.name};
    s.in = r.fam_in->get_stats(v);
    s.out = r.fam_out->get_stats(v);
    s.desc_in = r.fam_desc_in->get_stats(v);
    s.desc_out = r.fam_desc_out->get_stats(v);
    s.credit_stalls = r.fam_stalls->get_stats(v);
    s.ops = r.fam_ops->get_stats(v);
    // Publish AFTER the slot is fully built: lock-free readers index by
    // id without taking the mutex.
    r.count.store(n + 1, std::memory_order_release);
    return n;
}

const TransportTier* GetTransportTier(int tier) {
    Registry& r = reg();
    if (tier < 0 || tier >= r.count.load(std::memory_order_acquire)) {
        return nullptr;
    }
    return &r.slots[tier].tier;
}

int FindTransportTier(const char* name) {
    Registry& r = reg();
    const int n = r.count.load(std::memory_order_acquire);
    for (int i = 0; i < n; ++i) {
        if (strcmp(r.slots[i].tier.name, name) == 0) return i;
    }
    return -1;
}

int TransportTierCount() {
    return reg().count.load(std::memory_order_acquire);
}

// Built-ins: one static per tier keeps the id resolution free after the
// first call, and the registration order deterministic per process.
int TierTcp() {
    static const int id = RegisterTransportTier(
        {"tcp", /*descriptor_capable=*/false, /*zero_copy=*/false,
         /*cross_process=*/true});
    return id;
}
int TierIci() {
    static const int id = RegisterTransportTier(
        {"ici", /*descriptor_capable=*/true, /*zero_copy=*/true,
         /*cross_process=*/false, /*one_sided=*/true, /*sgl_max=*/16});
    return id;
}
int TierShmXproc() {
    static const int id = RegisterTransportTier(
        {"shm_xproc", /*descriptor_capable=*/true, /*zero_copy=*/true,
         /*cross_process=*/true, /*one_sided=*/true, /*sgl_max=*/16});
    return id;
}
int TierDevice() {
    static const int id = RegisterTransportTier(
        {"device", /*descriptor_capable=*/true, /*zero_copy=*/true,
         /*cross_process=*/false});
    return id;
}
int TierDcn() {
    static const int id = RegisterTransportTier(
        {"dcn", /*descriptor_capable=*/false, /*zero_copy=*/false,
         /*cross_process=*/true});
    return id;
}

bool DcnShapingEnabled() {
    return FLAGS_dcn_emu_latency_us.get() > 0 ||
           FLAGS_dcn_emu_mbps.get() > 0;
}

int64_t DcnShapeDelayUs(int tier, size_t bytes) {
    if (tier != TierDcn()) return 0;
    int64_t us = FLAGS_dcn_emu_latency_us.get();
    if (us < 0) us = 0;
    const int64_t mbps = FLAGS_dcn_emu_mbps.get();
    if (mbps > 0) us += (int64_t)bytes / mbps;  // 1 MB/s == 1 byte/us
    return us;
}

int64_t DcnShapeReadDelayUs(int tier, size_t bytes) {
    if (tier != TierDcn()) return 0;
    const int64_t mbps = FLAGS_dcn_emu_mbps.get();
    return mbps > 0 ? (int64_t)bytes / mbps : 0;
}

void SetLocalPoolIdProvider(uint64_t (*provider)()) {
    g_local_pool_provider.store(provider, std::memory_order_release);
}

uint64_t TransportLocalPoolId() {
    uint64_t (*p)() = g_local_pool_provider.load(std::memory_order_acquire);
    return p != nullptr ? p() : 0;
}

bool TransportDescriptorCapable(const Socket* s) {
    if (s == nullptr) return false;
    const TransportTier* t = GetTransportTier(s->transport_tier());
    if (t == nullptr || !t->descriptor_capable) return false;
    // A capable tier still needs a pool to reference: cross-process
    // peers mapped ours at handshake (peer_pool_id is the evidence the
    // handshake ran); in-process peers resolve the local pool directly.
    if (!t->cross_process) return TransportLocalPoolId() != 0;
    return s->peer_pool_id() != 0 || TransportLocalPoolId() != 0;
}

bool TransportOneSided(const Socket* s) {
    if (s == nullptr) return false;
    const TransportTier* t = GetTransportTier(s->transport_tier());
    if (t == nullptr || !t->one_sided) return false;
    // A window is a pool reference — the same mapping evidence that
    // gates descriptors gates direct verb data movement.
    return TransportDescriptorCapable(s);
}

uint32_t TransportSglMax(const Socket* s) {
    if (s == nullptr) return 0;
    const TransportTier* t = GetTransportTier(s->transport_tier());
    return (t != nullptr && t->one_sided) ? t->sgl_max : 0;
}

bool TransportDescriptorScopeOk(const Socket* s, uint64_t pool_id) {
    if (s == nullptr || pool_id == 0) return false;
    const TransportTier* t = GetTransportTier(s->transport_tier());
    if (t == nullptr || !t->descriptor_capable) return false;
    if (pool_id == s->peer_pool_id()) return true;
    // In-process transport links (and loopback xproc links in one
    // process) may reference this process's own pool.
    return pool_id == TransportLocalPoolId();
}

namespace transport_stats {

namespace {
inline TierSlot* slot(int tier) {
    Registry& r = reg();
    if (tier < 0 || tier >= r.count.load(std::memory_order_acquire)) {
        return nullptr;
    }
    return &r.slots[tier];
}
}  // namespace

void AddIn(int tier, int64_t bytes) {
    TierSlot* s = slot(tier);
    if (s != nullptr) s->in->add(bytes);
}
void AddOut(int tier, int64_t bytes) {
    TierSlot* s = slot(tier);
    if (s != nullptr) s->out->add(bytes);
}
void AddDescIn(int tier, int64_t bytes) {
    TierSlot* s = slot(tier);
    if (s != nullptr) s->desc_in->add(bytes);
}
void AddDescOut(int tier, int64_t bytes) {
    TierSlot* s = slot(tier);
    if (s != nullptr) s->desc_out->add(bytes);
}
void AddCreditStall(int tier) {
    TierSlot* s = slot(tier);
    if (s != nullptr) s->credit_stalls->add(1);
}
void AddOp(int tier) {
    TierSlot* s = slot(tier);
    if (s != nullptr) s->ops->add(1);
}

int64_t in_bytes(int tier) {
    TierSlot* s = slot(tier);
    return s != nullptr ? s->in->get() : 0;
}
int64_t out_bytes(int tier) {
    TierSlot* s = slot(tier);
    return s != nullptr ? s->out->get() : 0;
}
int64_t desc_in_bytes(int tier) {
    TierSlot* s = slot(tier);
    return s != nullptr ? s->desc_in->get() : 0;
}
int64_t desc_out_bytes(int tier) {
    TierSlot* s = slot(tier);
    return s != nullptr ? s->desc_out->get() : 0;
}
int64_t credit_stalls(int tier) {
    TierSlot* s = slot(tier);
    return s != nullptr ? s->credit_stalls->get() : 0;
}
int64_t ops(int tier) {
    TierSlot* s = slot(tier);
    return s != nullptr ? s->ops->get() : 0;
}

std::string DebugString() {
    ExposeVars();
    Registry& r = reg();
    const int n = r.count.load(std::memory_order_acquire);
    std::string out;
    char line[256];
    for (int i = 0; i < n; ++i) {
        const TierSlot& s = r.slots[i];
        snprintf(line, sizeof(line),
                 "tier %-9s desc=%d zero_copy=%d xproc=%d one_sided=%d "
                 "sgl_max=%u in=%lld "
                 "out=%lld desc_in=%lld desc_out=%lld stalls=%lld "
                 "ops=%lld\n",
                 s.tier.name, s.tier.descriptor_capable ? 1 : 0,
                 s.tier.zero_copy ? 1 : 0, s.tier.cross_process ? 1 : 0,
                 s.tier.one_sided ? 1 : 0, s.tier.sgl_max,
                 (long long)s.in->get(), (long long)s.out->get(),
                 (long long)s.desc_in->get(), (long long)s.desc_out->get(),
                 (long long)s.credit_stalls->get(),
                 (long long)s.ops->get());
        out += line;
    }
    return out;
}

void ExposeVars() {
    // Touch the built-ins so the five baseline tiers (and their labelled
    // family series) exist from the first scrape even on a server that
    // never moved a transport byte.
    TierTcp();
    TierIci();
    TierShmXproc();
    TierDevice();
    TierDcn();
}

}  // namespace transport_stats

}  // namespace tpurpc
