#include "tnet/acceptor.h"

#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <string>

#include "tbase/logging.h"
#include "tbase/time.h"
#include "tfiber/fiber.h"
#include "tnet/fault_injection.h"
#include "tnet/tls.h"

namespace tpurpc {

int Acceptor::StartAccept(const EndPoint& ep) {
    const int listen_fd =
        ::socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
    if (listen_fd < 0) return -1;
    int one = 1;
    setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr;
    endpoint2sockaddr(ep, &addr);
    if (bind(listen_fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
        listen(listen_fd, 1024) != 0) {
        close(listen_fd);
        return -1;
    }
    sockaddr_in bound;
    socklen_t blen = sizeof(bound);
    getsockname(listen_fd, (sockaddr*)&bound, &blen);
    listened_port_ = ntohs(bound.sin_port);

    SocketOptions opts;
    opts.fd = listen_fd;
    opts.on_edge_triggered_events = &Acceptor::OnNewConnections;
    opts.user = this;
    opts.on_recycle = &Acceptor::ListenRecycled;
    opts.recycle_arg = this;
    paused_.store(false, std::memory_order_release);  // restart path
    listen_live_.store(true, std::memory_order_release);
    if (Socket::Create(opts, &listen_id_) != 0) {
        // Socket::Create owns (and closed) listen_fd on failure; the
        // recycle callback already reset listen_live_.
        listen_id_ = INVALID_VREF_ID;
        return -1;
    }
    return 0;
}

// Both recycle callbacks follow the same teardown-safe protocol as
// Server::EndRequest: every touch of the Acceptor happens BEFORE the
// releasing store/decrement that lets StopAccept return (the object is
// pinned until then), the butex pointer is captured into a local, and the
// only post-release action is butex_wake_all on that local — which on a
// recycled slot is at worst a spurious wake (butex.cc pool contract; the
// word itself is bumped pre-release so slot reuse cannot be corrupted).

void Acceptor::ListenRecycled(void* arg, SocketId) {
    auto* a = (Acceptor*)arg;
    void* qb = a->quiesce_butex_;
    butex_word(qb)->fetch_add(1, std::memory_order_release);
    a->listen_live_.store(false, std::memory_order_release);
    // `a` may be freed from here on.
    butex_wake_all(qb);
}

void Acceptor::ConnRecycled(void* arg, SocketId id) {
    auto* a = (Acceptor*)arg;
    if (id != INVALID_VREF_ID) {
        std::lock_guard<std::mutex> g(a->conn_mu_);
        a->conn_ids_.erase(id);
    }
    void* qb = a->quiesce_butex_;
    butex_word(qb)->fetch_add(1, std::memory_order_release);
    if (a->live_conns_.fetch_sub(1, std::memory_order_acq_rel) == 1) {
        // `a` may be freed from here on.
        butex_wake_all(qb);
    }
}

void Acceptor::StopAccept() {
    if (listen_id_ != INVALID_VREF_ID) {
        Socket::SetFailedById(listen_id_);
        listen_id_ = INVALID_VREF_ID;
    }
    // Fail every accepted connection (copy ids first: the recycle callback
    // takes conn_mu_, possibly inline from SetFailedById's last deref).
    std::vector<SocketId> ids;
    {
        std::lock_guard<std::mutex> g(conn_mu_);
        ids.assign(conn_ids_.begin(), conn_ids_.end());
    }
    for (SocketId id : ids) {
        Socket::SetFailedById(id);
    }
    // Quiesce: no accepted socket (or the listen socket) may survive this
    // function — a live one means some fiber can still reach the Server
    // this Acceptor is embedded in.
    const int64_t quiesce_t0 = monotonic_time_us();
    int64_t next_warn_us = quiesce_t0 + 2 * 1000 * 1000;
    while (live_conns_.load(std::memory_order_acquire) > 0 ||
           listen_live_.load(std::memory_order_acquire)) {
        const int seq =
            butex_word(quiesce_butex_)->load(std::memory_order_acquire);
        if (live_conns_.load(std::memory_order_acquire) <= 0 &&
            !listen_live_.load(std::memory_order_acquire)) {
            break;
        }
        if (monotonic_time_us() >= next_warn_us) {
            next_warn_us += 2 * 1000 * 1000;
            std::string detail;
            {
                std::lock_guard<std::mutex> g(conn_mu_);
                for (SocketId cid : conn_ids_) {
                    Socket* raw = address_resource<Socket>(VRefSlot(cid));
                    char buf[64];
                    snprintf(buf, sizeof(buf), " id=%llu nref=%d",
                             (unsigned long long)cid,
                             raw != nullptr ? raw->nref() : -1);
                    detail += buf;
                }
            }
            LOG(WARNING) << "StopAccept quiescing for "
                         << (monotonic_time_us() - quiesce_t0) / 1000
                         << "ms: live_conns=" << live_conns_.load()
                         << " listen_live=" << listen_live_.load()
                         << detail;
        }
        // Backstop timeout: wake-before-wait races resolve on re-check.
        const int64_t abst = monotonic_time_us() + 50 * 1000;
        butex_wait(quiesce_butex_, seq, &abst);
    }
}

std::vector<SocketId> Acceptor::connections() {
    std::lock_guard<std::mutex> g(conn_mu_);
    // The recycle callback erases dead ids, so everything here is live or
    // at worst mid-failure.
    return std::vector<SocketId>(conn_ids_.begin(), conn_ids_.end());
}

void Acceptor::ResumeAccept() {
    paused_.store(false, std::memory_order_release);
    // Re-kick the accept loop: connections that completed their TCP
    // handshake in the backlog while paused produced no NEW edge event.
    if (listen_id_ != INVALID_VREF_ID) {
        Socket::OnInputEventById(listen_id_);
    }
}

void Acceptor::OnNewConnections(Socket* listen_socket) {
    Acceptor* a = (Acceptor*)listen_socket->user();
    while (!listen_socket->Failed()) {
        if (a->paused_.load(std::memory_order_acquire)) {
            // Drain gate: leave the backlog in the kernel. ResumeAccept
            // re-kicks this loop.
            return;
        }
        sockaddr_in peer;
        socklen_t plen = sizeof(peer);
        const int fd = accept4(listen_socket->fd(), (sockaddr*)&peer, &plen,
                               SOCK_NONBLOCK | SOCK_CLOEXEC);
        if (fd < 0) {
            if (errno == EAGAIN || errno == EWOULDBLOCK) return;
            if (errno == EINTR || errno == ECONNABORTED) continue;
            if (errno == EMFILE || errno == ENFILE) {
                // fd exhaustion: with an edge-triggered listen fd, returning
                // now would strand the queued backlog until a NEW connection
                // arrives. Pause on this fiber and retry (reference acceptor
                // does the same).
                fiber_usleep(100 * 1000);
                continue;
            }
            return;
        }
        // Chaos: accept-time connection refusal — the peer sees an
        // immediate close (EOF/RST), exercising its connect retry and
        // health-check paths. (The remote here is the peer's ephemeral
        // address, so per-peer plans usually scope this via an empty
        // peers filter.)
        if (__builtin_expect(fault_injection_enabled(), 0) &&
            FaultInjection::Decide(FaultOp::kAccept, sockaddr2endpoint(peer),
                                   0)
                    .kind == FaultAction::kRefuse) {
            close(fd);
            continue;
        }
        SocketOptions opts;
        opts.fd = fd;
        opts.remote_side = sockaddr2endpoint(peer);
        opts.on_edge_triggered_events = &InputMessenger::OnNewMessages;
        opts.user = a->messenger_;
        opts.on_recycle = &Acceptor::ConnRecycled;
        opts.recycle_arg = a;
        if (a->tls_) {
            opts.transport = NewTlsServerTransport(fd);
            if (opts.transport == nullptr) {
                close(fd);
                continue;
            }
            opts.owns_transport = true;
        }
        // Account BEFORE Create: the socket can fail+recycle (firing the
        // callback) before Create even returns; the liveness-checked
        // insert below then skips the already-recycled id. The accepted
        // counter too — a connection can serve a whole RPC between
        // Create (epoll registration) and any later increment, so
        // observers would otherwise see served > accepted.
        a->live_conns_.fetch_add(1, std::memory_order_acq_rel);
        a->accepted_.fetch_add(1, std::memory_order_relaxed);
        SocketId id;
        if (Socket::Create(opts, &id) != 0) {
            // Create closed fd and fired the callback (which balanced the
            // counter).
            continue;
        }
        // Address OUTSIDE conn_mu_, and drop the ref outside it too: if
        // ours is the last ref (instant peer RST), Dereference runs
        // OnRecycle inline, whose ConnRecycled callback locks conn_mu_ —
        // holding it here would self-deadlock.
        Socket* s = Socket::Address(id);
        if (s != nullptr) {
            {
                std::lock_guard<std::mutex> g(a->conn_mu_);
                a->conn_ids_.insert(id);
            }
            s->Dereference();
        }
        // Teardown handshake: StopAccept fails the listener BEFORE copying
        // conn_ids_ (under conn_mu_); we insert under conn_mu_ BEFORE this
        // check. So either our insert made StopAccept's copy (it fails the
        // conn), or our check observes the failed listener (we fail it).
        // Without this, a connection accepted by an in-flight burst right
        // after the copy is never failed and quiesce hangs forever.
        if (listen_socket->Failed()) {
            Socket::SetFailedById(id);
        }
    }
}

}  // namespace tpurpc
