#include "tnet/acceptor.h"

#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>

#include "tbase/logging.h"
#include "tfiber/fiber.h"

namespace tpurpc {

int Acceptor::StartAccept(const EndPoint& ep) {
    const int listen_fd =
        ::socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
    if (listen_fd < 0) return -1;
    int one = 1;
    setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr;
    endpoint2sockaddr(ep, &addr);
    if (bind(listen_fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
        listen(listen_fd, 1024) != 0) {
        close(listen_fd);
        return -1;
    }
    sockaddr_in bound;
    socklen_t blen = sizeof(bound);
    getsockname(listen_fd, (sockaddr*)&bound, &blen);
    listened_port_ = ntohs(bound.sin_port);

    SocketOptions opts;
    opts.fd = listen_fd;
    opts.on_edge_triggered_events = &Acceptor::OnNewConnections;
    opts.user = this;
    if (Socket::Create(opts, &listen_id_) != 0) {
        // Socket::Create owns (and closed) listen_fd on failure.
        return -1;
    }
    return 0;
}

void Acceptor::StopAccept() {
    if (listen_id_ != INVALID_VREF_ID) {
        Socket::SetFailedById(listen_id_);
        listen_id_ = INVALID_VREF_ID;
    }
    std::lock_guard<std::mutex> g(conn_mu_);
    for (SocketId id : conn_ids_) {
        Socket::SetFailedById(id);
    }
    conn_ids_.clear();
}

std::vector<SocketId> Acceptor::connections() {
    std::lock_guard<std::mutex> g(conn_mu_);
    std::vector<SocketId> live;
    for (auto it = conn_ids_.begin(); it != conn_ids_.end();) {
        Socket* s = Socket::Address(*it);
        if (s == nullptr) {
            it = conn_ids_.erase(it);  // prune dead ids
        } else {
            s->Dereference();
            live.push_back(*it);
            ++it;
        }
    }
    return live;
}

void Acceptor::record_connection(SocketId id) {
    std::lock_guard<std::mutex> g(conn_mu_);
    conn_ids_.insert(id);
    // Bound growth under connection churn: prune dead ids periodically.
    if (conn_ids_.size() > 1024 && (conn_ids_.size() & 1023) == 0) {
        for (auto it = conn_ids_.begin(); it != conn_ids_.end();) {
            Socket* s = Socket::Address(*it);
            if (s == nullptr) {
                it = conn_ids_.erase(it);
            } else {
                s->Dereference();
                ++it;
            }
        }
    }
}

void Acceptor::OnNewConnections(Socket* listen_socket) {
    Acceptor* a = (Acceptor*)listen_socket->user();
    while (true) {
        sockaddr_in peer;
        socklen_t plen = sizeof(peer);
        const int fd = accept4(listen_socket->fd(), (sockaddr*)&peer, &plen,
                               SOCK_NONBLOCK | SOCK_CLOEXEC);
        if (fd < 0) {
            if (errno == EAGAIN || errno == EWOULDBLOCK) return;
            if (errno == EINTR || errno == ECONNABORTED) continue;
            if (errno == EMFILE || errno == ENFILE) {
                // fd exhaustion: with an edge-triggered listen fd, returning
                // now would strand the queued backlog until a NEW connection
                // arrives. Pause on this fiber and retry (reference acceptor
                // does the same).
                fiber_usleep(100 * 1000);
                continue;
            }
            return;
        }
        SocketOptions opts;
        opts.fd = fd;
        opts.remote_side = sockaddr2endpoint(peer);
        opts.on_edge_triggered_events = &InputMessenger::OnNewMessages;
        opts.user = a->messenger_;
        SocketId id;
        if (Socket::Create(opts, &id) != 0) {
            // Socket::Create owns (and closed) fd on failure.
            continue;
        }
        a->record_connection(id);
        a->accepted_.fetch_add(1, std::memory_order_relaxed);
    }
}

}  // namespace tpurpc
