// InputMessenger: the protocol-agnostic message pump — reads bytes off a
// socket, sniffs/cuts messages with registered protocol parsers, and hands
// each message to a processing fiber.
//
// Modeled on reference src/brpc/input_messenger.{h,cpp}: OnNewMessages
// (:360) reads into an IOPortal; CutInputMessage (:84) tries the socket's
// last-successful protocol first then the others; QueueMessage (:194-234)
// spawns a fiber per message, keeping the LAST message inline for cache
// locality.
#pragma once

#include "tnet/protocol.h"
#include "tnet/socket.h"

namespace tpurpc {

class InputMessenger {
public:
    // The subset of registered protocols this messenger accepts, by index
    // (servers accept server protocols; a client channel accepts its own).
    explicit InputMessenger(std::vector<int> protocol_indexes = {})
        : protocols_(std::move(protocol_indexes)) {}

    void add_protocol(int index) { protocols_.push_back(index); }

    // Owner context (the Server* for server-side messengers; null for the
    // client messenger) — how protocol process() finds the server.
    void* context = nullptr;

    // Socket edge-trigger callback (runs on a fiber).
    static void OnNewMessages(Socket* s);

private:
    friend class Acceptor;
    std::vector<int> protocols_;
};

}  // namespace tpurpc
