// InputMessenger: the protocol-agnostic message pump — reads bytes off a
// socket, sniffs/cuts messages with registered protocol parsers, and hands
// each message to a processing fiber.
//
// Modeled on reference src/brpc/input_messenger.{h,cpp}: OnNewMessages
// (:360) reads into an IOPortal; CutInputMessage (:84) tries the socket's
// last-successful protocol first then the others; QueueMessage (:194-234)
// spawns a fiber per message, keeping the LAST message inline for cache
// locality.
//
// Raw-speed round (ISSUE 7): the pump is now a run-to-completion
// dispatcher. Per readiness burst it (a) arms an inline budget — small
// messages of inline-safe protocols process ON the input fiber instead of
// spawning a fiber each (budget exhausted -> the old fan-out, so large
// bursts still parallelize); (b) arms a WakeBatcher so the burst's fiber
// wakeups cost one futex signal per pool per round; (c) arms a
// WriteCoalesceScope so responses written during the round merge into one
// writev per socket; (d) uses Protocol::peek to classify sticky
// connections' frames from contiguous header bytes — no cutn, no
// re-parse loop while a partial frame trickles in.
#pragma once

#include "tnet/protocol.h"
#include "tnet/socket.h"

namespace tpurpc {

// Run-to-completion inline budget (ISSUE 7). Thread-local, armed by the
// messenger per readiness burst; protocol/RPC layers consult it to decide
// inline-vs-fiber. Zeroed on fiber park (a parked round is over).
namespace inline_dispatch {
// True while the current thread is inside an armed messenger round.
bool RoundArmed();
// Consume one budget unit for a message of `nbytes`; false when no round
// is armed, the budget is spent, or the message exceeds
// -inline_dispatch_max_bytes.
bool Acquire(size_t nbytes);
// Give back the unit Acquire consumed (the layer above decided to fan
// out after all — e.g. a request whose method is not inline-safe).
void Refund();
// Telemetry for /loops + tests.
int64_t dispatches();        // messages processed run-to-completion
int64_t overflows();         // inline-eligible messages past the budget
int64_t handler_inlines();   // server handlers run on the input fiber
void CountHandlerInline();   // called by the RPC layer's inline path
// One-sided descriptor exemption (ISSUE 9): a pool-descriptor message's
// LOGICAL payload (the referenced pool bytes) is exempt from the inline
// byte budget — only its wire bytes (header + meta) were charged by
// Acquire, because the referenced bytes never pass through the message
// path (they are mapped in place, not copied). Called by the RPC layer
// when it resolves a descriptor, so /loops can show how many logical
// bytes rode the run-to-completion path budget-free.
void ExemptDescriptorBytes(size_t nbytes);
int64_t descriptor_exempt_bytes();
}  // namespace inline_dispatch

class InputMessenger {
public:
    // The subset of registered protocols this messenger accepts, by index
    // (servers accept server protocols; a client channel accepts its own).
    explicit InputMessenger(std::vector<int> protocol_indexes = {})
        : protocols_(std::move(protocol_indexes)) {}

    void add_protocol(int index) { protocols_.push_back(index); }

    // Owner context (the Server* for server-side messengers; null for the
    // client messenger) — how protocol process() finds the server.
    void* context = nullptr;

    // Socket edge-trigger callback (runs on a fiber).
    static void OnNewMessages(Socket* s);

private:
    friend class Acceptor;
    std::vector<int> protocols_;
};

}  // namespace tpurpc
