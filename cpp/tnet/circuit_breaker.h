// CircuitBreaker: per-connection EMA error-rate tracker that isolates a
// server when its error rate exceeds thresholds in a short (bursty) or
// long (chronic) window.
//
// Modeled on reference src/brpc/circuit_breaker.h:25-85 (two
// EmaErrorRecorders; MarkAsBroken isolates the node and hands it to the
// health checker, which revives it). Lives in tnet because Socket embeds
// one; it has no upper-layer dependencies.
#pragma once

#include <atomic>
#include <cstdint>

namespace tpurpc {

// One EMA window: error rate estimated as an exponential moving average
// over the last ~window_size calls; trips after enough samples.
class EmaErrorRate {
public:
    void Init(int window_size, double max_error_percent) {
        window_size_ = window_size < 1 ? 1 : window_size;
        threshold_ = max_error_percent;
        Reset();
    }
    void Reset() {
        rate_fp_.store(0, std::memory_order_relaxed);
        samples_.store(0, std::memory_order_relaxed);
    }
    // Returns false when the window trips (error rate above threshold).
    bool OnCallEnd(bool error) {
        // rate' = rate * (N-1)/N + (error ? 100% : 0) / N in 2^20
        // fixed-point. Decay rounds UP so small rates still decay (a
        // truncating cur/N is 0 below N and the rate would only ratchet
        // upward). Lock-free CAS; races only blur the EMA.
        int64_t cur = rate_fp_.load(std::memory_order_relaxed);
        int64_t next;
        do {
            const int64_t decay = (cur + window_size_ - 1) / window_size_;
            next = cur - decay + (error ? kOne100 / window_size_ : 0);
        } while (!rate_fp_.compare_exchange_weak(
            cur, next, std::memory_order_relaxed));
        const int64_t n = samples_.fetch_add(1, std::memory_order_relaxed) + 1;
        // Demand a quarter window of evidence before tripping.
        return !(n >= window_size_ / 4 &&
                 (double)next / kOne > threshold_);
    }
    double error_percent() const {
        return (double)rate_fp_.load(std::memory_order_relaxed) / kOne;
    }

private:
    static constexpr int64_t kOne = 1 << 20;       // fixed-point 1 percent
    static constexpr int64_t kOne100 = kOne * 100;  // 100 percent
    int window_size_ = 100;
    double threshold_ = 100.0;
    std::atomic<int64_t> rate_fp_{0};
    std::atomic<int64_t> samples_{0};
};

class CircuitBreaker {
public:
    CircuitBreaker() { Reset(); }

    // Re-arm after health-check revive. Keeps the isolation history so
    // repeated isolation can back off harder (reference
    // circuit_breaker.cpp _isolation_duration_ms doubling).
    void Reset();

    // Full reset for a brand-new connection (socket slot reuse must not
    // inherit the previous tenant's isolation history).
    void ResetAll() {
        Reset();
        isolated_times_.store(0, std::memory_order_relaxed);
    }

    // Record one finished call. Returns false when the breaker trips:
    // the caller should isolate the connection (SetFailed -> health
    // check). error_code 0 = success.
    bool OnCallEnd(int error_code, int64_t latency_us);

    // Returns true for the ONE caller that transitioned this episode.
    bool MarkAsBroken() {
        // exchange: concurrent trippers in the same episode must count it
        // once or the backoff doubling overshoots.
        if (!broken_.exchange(true, std::memory_order_acq_rel)) {
            isolated_times_.fetch_add(1, std::memory_order_relaxed);
            return true;
        }
        return false;
    }

    // How long the node should stay isolated before the health checker may
    // revive it: min_isolation << (isolated_times-1), capped at
    // max_isolation (reference circuit_breaker.cpp _isolation_duration_ms
    // doubling). 0 when never isolated.
    int isolation_duration_ms() const;

    bool IsBroken() const { return broken_.load(std::memory_order_acquire); }
    int isolated_times() const {
        return isolated_times_.load(std::memory_order_relaxed);
    }
    double short_window_error_percent() const {
        return short_.error_percent();
    }
    double long_window_error_percent() const { return long_.error_percent(); }

private:
    EmaErrorRate short_;  // bursty failures (small window, high threshold)
    EmaErrorRate long_;   // chronic failures (large window, low threshold)
    std::atomic<bool> broken_{false};
    std::atomic<int> isolated_times_{0};
};

}  // namespace tpurpc
