#include "tnet/socket.h"

#include <fcntl.h>
#include <netinet/in.h>
#include <poll.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <cerrno>
#include <cstring>
#include <string>

#include "tbase/errno.h"
#include "tbase/flags.h"
#include "tbase/logging.h"
#include "tbase/time.h"
#include "tfiber/call_id.h"
#include "tfiber/task_group.h"
#include "tnet/event_dispatcher.h"
#include "tnet/fault_injection.h"
#include "tnet/tls.h"
#include "tnet/transport.h"
#include "tvar/latency_recorder.h"
#include "tvar/reducer.h"

DEFINE_int64(socket_max_unwritten_bytes, 64 * 1024 * 1024,
             "write backlog limit before EOVERCROWDED back-pressure");
// -1 keeps kernel autotuning (the right default: pinning a size disables
// both shrinking of idle connections and growth on high-BDP links).
// Benchmarks with windowed large messages set these explicitly.
DEFINE_int32(socket_send_buffer_size, -1,
             "SO_SNDBUF per connection; -1 = kernel autotune");
DEFINE_int32(socket_recv_buffer_size, -1,
             "SO_RCVBUF per connection; -1 = kernel autotune");
// Reference details/health_check.cpp:51-107 OnAppHealthCheckDone: beyond
// the TCP connect probe, require an APPLICATION-level answer before
// reviving an isolated server (a listening-but-broken process must stay
// isolated). Empty disables; servers in this framework always serve
// /health on their RPC port.
DEFINE_string(health_check_path, "",
              "HTTP path probed (expects 200) before reviving a failed "
              "server; empty = TCP connect probe only");

namespace tpurpc {

// Health-check revivals, observable in /vars and /metrics (the mesh
// chaos soak asserts on it).
static LazyAdder g_hc_revives("rpc_health_check_revives");

// Process-wide I/O attribution families (ISSUE 6): writev batch sizes
// as a real summary (small batches at high QPS = the write-coalescing
// opportunity of ROADMAP item 4), EOVERCROWDED incidents, and the
// biggest write backlog any connection reached. Per-connection views
// live on /connections.
static LazyAdder g_eovercrowded("rpc_socket_eovercrowded");

static LatencyRecorder* write_batch_recorder() {
    static LatencyRecorder* r = [] {
        auto* x = new LatencyRecorder;
        x->expose("rpc_socket_write_batch_bytes");
        return x;
    }();
    return r;
}

static IntCell* queued_write_highwater_cell() {
    static IntCell* c = [] {
        auto* x = new IntCell;
        x->expose("rpc_socket_queued_write_highwater");
        return x;
    }();
    return c;
}

static int make_non_blocking(int fd) {
    const int flags = fcntl(fd, F_GETFL, 0);
    if (flags < 0) return -1;
    return fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

static void ApplySocketBufferSizes(int fd) {
    const int snd = FLAGS_socket_send_buffer_size.get();
    if (snd > 0) setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &snd, sizeof(snd));
    const int rcv = FLAGS_socket_recv_buffer_size.get();
    if (rcv > 0) setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &rcv, sizeof(rcv));
}

// ---------------- creation / recycle ----------------

// Takes ownership of options.fd: on ANY failure path the fd is closed here
// (callers must not close it again — fd numbers recycle fast under load and
// a double close can kill an unrelated connection).
int Socket::Create(const SocketOptions& options, SocketId* id) {
    Socket* s = nullptr;
    if (VersionedRefWithId<Socket>::Create(id, &s) != 0) {
        if (options.transport != nullptr && options.owns_transport) {
            options.transport->Release();  // a TLS transport owns the fd
        } else if (options.fd >= 0) {
            close(options.fd);
        }
        // Keep the fires-exactly-once contract even when no slot was ever
        // allocated (callers pre-account and rely on the callback to undo).
        if (options.on_recycle != nullptr) {
            options.on_recycle(options.recycle_arg, INVALID_VREF_ID);
        }
        return -1;
    }
    // Slots are recycled without destruction: re-init everything.
    s->fd_.store(options.fd, std::memory_order_relaxed);
    s->remote_side_ = options.remote_side;
    s->local_side_ = EndPoint();
    s->on_edge_triggered_events_ = options.on_edge_triggered_events;
    s->user_ = options.user;
    s->transport_ = options.transport;
    s->owns_transport_ = options.owns_transport;
    s->forced_tier_ = options.forced_transport_tier;
    s->write_head_.store(nullptr, std::memory_order_relaxed);
    s->write_pending_.store(0, std::memory_order_relaxed);
    s->unwritten_bytes_.store(0, std::memory_order_relaxed);
    s->inflight_batch_.clear();
    s->inflight_index_ = 0;
    s->writer_consumed_ = 0;
    s->nevent_.store(0, std::memory_order_relaxed);
    s->error_code_.store(0, std::memory_order_relaxed);
    s->connecting_.store(false, std::memory_order_relaxed);
    s->read_buf.clear();
    s->preferred_protocol_index = -1;
    s->pending_frame_bytes = 0;
    s->health_check_interval_ms_ = options.health_check_interval_ms;
    s->tls_ = options.tls;
    s->tls_alpn_ = options.tls_alpn;
    s->tls_sni_ = options.tls_sni;
    s->hc_stop_.store(false, std::memory_order_relaxed);
    s->draining_.store(false, std::memory_order_relaxed);
    s->circuit_breaker_.ResetAll();
    // Install before any failure path below: AddConsumer failure recycles
    // the socket, which must still deliver the notification.
    s->on_recycle_ = options.on_recycle;
    s->recycle_arg_ = options.recycle_arg;
    s->conn_data_ = nullptr;
    s->conn_data_deleter_ = nullptr;
    s->bytes_read_.store(0, std::memory_order_relaxed);
    s->bytes_written_.store(0, std::memory_order_relaxed);
    s->descriptor_bytes_read_.store(0, std::memory_order_relaxed);
    s->peer_pool_id_.store(0, std::memory_order_relaxed);
    s->nwrite_batches_.store(0, std::memory_order_relaxed);
    s->max_write_batch_.store(0, std::memory_order_relaxed);
    s->queued_highwater_.store(0, std::memory_order_relaxed);
    s->novercrowded_.store(0, std::memory_order_relaxed);
    s->rate_scrape_us_.store(0, std::memory_order_relaxed);
    s->rate_scrape_in_.store(0, std::memory_order_relaxed);
    s->rate_scrape_out_.store(0, std::memory_order_relaxed);
    s->created_us_ = monotonic_time_us();
    s->last_active_us_.store(s->created_us_, std::memory_order_relaxed);
    if (s->epollout_butex_ == nullptr) s->epollout_butex_ = butex_create();
    if (s->connect_butex_ == nullptr) s->connect_butex_ = butex_create();
    if (s->auth_butex_ == nullptr) s->auth_butex_ = butex_create();
    s->auth_state_.store(0, std::memory_order_relaxed);
    s->auth_user_.clear();

    if (options.fd >= 0) {
        make_non_blocking(options.fd);
        int one = 1;
        setsockopt(options.fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        ApplySocketBufferSizes(options.fd);
        if (EventDispatcher::GetGlobalDispatcher(options.fd)
                .AddConsumer(*id, options.fd) != 0) {
            PLOG(ERROR) << "AddConsumer failed for fd=" << options.fd;
            Socket* addr = Address(*id);
            if (addr) {
                addr->SetFailed();
                addr->Dereference();
            }
            return -1;
        }
    }
    return 0;
}

namespace {
std::atomic<Socket::FailureObserver> g_failure_observer{nullptr};
std::atomic<Socket::ReviveObserver> g_revive_observer{nullptr};
}  // namespace

void Socket::set_failure_observer(FailureObserver ob) {
    g_failure_observer.store(ob, std::memory_order_release);
}

void Socket::set_revive_observer(ReviveObserver ob) {
    g_revive_observer.store(ob, std::memory_order_release);
}

void Socket::OnFailed() {
    // Upper-layer notification first: in-flight server calls on this
    // connection should learn of the death before the health-check
    // machinery starts resurrecting it.
    FailureObserver ob = g_failure_observer.load(std::memory_order_acquire);
    if (ob != nullptr) ob(id());
    // Wake anything parked on this socket so it observes the failure.
    butex_word(epollout_butex_)->fetch_add(1, std::memory_order_release);
    butex_wake_all(epollout_butex_);
    butex_word(connect_butex_)->fetch_add(1, std::memory_order_release);
    butex_wake_all(connect_butex_);
    butex_word(auth_butex_)->fetch_add(1, std::memory_order_release);
    butex_wake_all(auth_butex_);
    // Health check: keep the slot alive with our own ref and probe until
    // the remote answers, then Revive the SAME id (reference
    // src/brpc/details/health_check.cpp:140 HealthCheckTask).
    if (health_check_interval_ms_ > 0 &&
        !hc_stop_.load(std::memory_order_acquire)) {
        AddRef();  // released by HealthCheckLoop
        fiber_t tid;
        if (fiber_start_background(&tid, nullptr, HealthCheckThunk, this) !=
            0) {
            Dereference();
        }
    }
}

void* Socket::HealthCheckThunk(void* arg) {
    ((Socket*)arg)->HealthCheckLoop();
    return nullptr;
}

// Probe TCP connect with a bounded wait; returns 0 when the remote accepts.
static int ProbeConnect(const EndPoint& remote, int timeout_ms) {
    const int fd = ::socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (fd < 0) return -1;
    sockaddr_in addr;
    endpoint2sockaddr(remote, &addr);
    int rc = ::connect(fd, (sockaddr*)&addr, sizeof(addr));
    if (rc != 0 && errno == EINPROGRESS) {
        pollfd pfd{fd, POLLOUT, 0};
        rc = ::poll(&pfd, 1, timeout_ms) == 1 ? 0 : -1;
        if (rc == 0) {
            int err = 0;
            socklen_t len = sizeof(err);
            getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len);
            rc = err == 0 ? 0 : -1;
        }
    }
    ::close(fd);
    return rc;
}

// GET `path` and require a 200 within timeout_ms (one short-lived
// connection; the socket being revived is not touched).
static bool ProbeHttpHealth(const EndPoint& remote, const std::string& path,
                            int timeout_ms) {
    const int fd = ::socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (fd < 0) return false;
    sockaddr_in addr;
    endpoint2sockaddr(remote, &addr);
    int rc = ::connect(fd, (sockaddr*)&addr, sizeof(addr));
    if (rc != 0 && errno != EINPROGRESS) {
        close(fd);
        return false;
    }
    pollfd pfd{fd, POLLOUT, 0};
    if (rc != 0 && ::poll(&pfd, 1, timeout_ms) != 1) {
        close(fd);
        return false;
    }
    const std::string req =
        "GET " + path + " HTTP/1.1\r\nHost: hc\r\nConnection: close\r\n\r\n";
    if (send(fd, req.data(), req.size(), MSG_NOSIGNAL) !=
        (ssize_t)req.size()) {
        close(fd);
        return false;
    }
    char buf[256];
    size_t got = 0;
    const int64_t deadline = monotonic_time_us() + timeout_ms * 1000;
    // Read until the status line is complete (first CRLF) — byte offsets
    // must not be assumed: "HTTP/1.0 200" and reason-phrase-less replies
    // are legal and gate revival just the same.
    while (got < sizeof(buf) - 1 && monotonic_time_us() < deadline &&
           memchr(buf, '\n', got) == nullptr) {
        pollfd rp{fd, POLLIN, 0};
        if (::poll(&rp, 1, 50) != 1) continue;
        const ssize_t r = recv(fd, buf + got, sizeof(buf) - 1 - got, 0);
        if (r <= 0) break;
        got += (size_t)r;
    }
    close(fd);
    buf[got] = '\0';
    int status = 0;
    if (sscanf(buf, "HTTP/%*d.%*d %d", &status) != 1) return false;
    return status >= 200 && status < 300;
}

void Socket::HealthCheckLoop() {
    const int64_t interval_us = (int64_t)health_check_interval_ms_ * 1000;
    // Breaker-tripped sockets stay isolated for a duration that doubles
    // per repeated trip; a TCP-alive-but-RPC-failing server would
    // otherwise flap isolate->revive every interval, eating ~a window of
    // failed user calls per cycle.
    const int64_t iso_us =
        (int64_t)circuit_breaker_.isolation_duration_ms() * 1000;
    bool first = true;
    while (!hc_stop_.load(std::memory_order_acquire)) {
        fiber_usleep(first && iso_us > interval_us ? iso_us : interval_us);
        first = false;
        if (hc_stop_.load(std::memory_order_acquire)) break;
        // Only probe/revive once every other ref is gone: then no KeepWrite
        // or event fiber can race the connection-state reset below.
        if (nref() > 1) continue;
        // App-level probe (reference health_check.cpp:51-107) subsumes
        // the TCP connect probe — a process that accepts TCP but cannot
        // answer stays isolated; without a configured path, the connect
        // probe alone gates revival.
        const std::string hc_path = FLAGS_health_check_path.get();
        if (hc_path.empty()) {
            if (ProbeConnect(remote_side_, 200) != 0) continue;
        } else if (!ProbeHttpHealth(remote_side_, hc_path, 500)) {
            continue;
        }
        if (ReviveAfterHealthCheck() == 0) {
            // StopHealthCheck may have raced the probe window: a revived
            // socket nobody tracks anymore would leak alive forever. Undo.
            if (hc_stop_.load(std::memory_order_acquire)) SetFailed();
            break;
        }
    }
    Dereference();
}

int Socket::ReviveAfterHealthCheck() {
    // Drop every remnant of the dead connection. We are the only ref.
    CloseFdAndDropQueued();
    write_pending_.store(0, std::memory_order_relaxed);
    unwritten_bytes_.store(0, std::memory_order_relaxed);
    nevent_.store(0, std::memory_order_relaxed);
    read_buf.clear();
    preferred_protocol_index = -1;
    pending_frame_bytes = 0;
    error_code_.store(0, std::memory_order_relaxed);
    connecting_.store(false, std::memory_order_relaxed);
    local_side_ = EndPoint();
    circuit_breaker_.Reset();  // fresh windows for the revived server
    auth_state_.store(0, std::memory_order_relaxed);  // re-authenticate
    auth_user_.clear();
    // The drain announcement belonged to the previous (now restarted)
    // process: the revived server serves anew, so LBs must pick it again.
    draining_.store(false, std::memory_order_relaxed);
    const int rc = Revive();
    if (rc == 0) {
        *g_hc_revives << 1;
        LOG(INFO) << "Revived socket id=" << id()
                  << " remote=" << endpoint2str(remote_side_);
        // After the slot is LIVE: an ejected backend must re-enter via
        // the outlier probe ramp, not at full weight.
        ReviveObserver ob = g_revive_observer.load(std::memory_order_acquire);
        if (ob != nullptr) ob(id());
    }
    return rc;
}

namespace {
void* id_error_fiber(void* arg) {
    id_error((uint64_t)(uintptr_t)arg, TERR_FAILED_SOCKET);
    return nullptr;
}
}  // namespace

// Dropped (never-written) requests error-notify their RPCs — from a fresh
// fiber, never inline: OnRecycle can run under arbitrary locks (e.g.
// SocketMap::mu_ via Dereference) and the error handler may retry into
// those same locks.
void Socket::DropWriteRequest(WriteRequest* req) {
    if (req->notify_id != 0) {
        fiber_t tid;
        if (fiber_start_background(&tid, nullptr, id_error_fiber,
                                   (void*)(uintptr_t)req->notify_id) != 0) {
            id_error(req->notify_id, TERR_FAILED_SOCKET);
        }
    }
    delete req;
}

void Socket::OnRecycle() {
    CloseFdAndDropQueued();
    read_buf.clear();
    if (conn_data_ != nullptr) {
        if (conn_data_deleter_ != nullptr) conn_data_deleter_(conn_data_);
        conn_data_ = nullptr;
        conn_data_deleter_ = nullptr;
    }
    if (transport_ != nullptr) {
        if (owns_transport_) transport_->Release();
        transport_ = nullptr;
    }
    // Last: the recycle notification (quiesce signal for Acceptor/Server
    // teardown). After this fires the owner may free itself, so nothing
    // below may touch user_/recycle_arg_ again.
    if (on_recycle_ != nullptr) {
        auto cb = on_recycle_;
        void* arg = recycle_arg_;
        on_recycle_ = nullptr;
        recycle_arg_ = nullptr;
        cb(arg, id());
    }
}

// Shared teardown of a dead connection: close + deregister the fd and drop
// every queued write request (error-notifying their CallIds). Callers must
// be the sole toucher of write state (recycle: nref==0; revive: sole-ref
// health-check fiber).
void Socket::CloseFdAndDropQueued() {
    const int fd = fd_.exchange(-1, std::memory_order_acq_rel);
    if (fd >= 0) {
        EventDispatcher::GetGlobalDispatcher(fd).RemoveConsumer(fd);
        // A transport's doorbell fd is owned by the transport (its link
        // may outlive this socket); only plain TCP fds are ours to close.
        if (transport_ == nullptr) close(fd);
    }
    if (transport_ != nullptr) transport_->Close();
    // Pipelined calls whose replies will never arrive (same fiber-spawn
    // discipline as DropWriteRequest: the id's error handler runs user
    // completion code).
    for (const PipelinedInfo& pi : ResetPipelinedInfo()) {
        if (pi.id_wait == 0) continue;
        fiber_t tid;
        if (fiber_start_background(&tid, nullptr, id_error_fiber,
                                   (void*)(uintptr_t)pi.id_wait) != 0) {
            id_error(pi.id_wait, TERR_FAILED_SOCKET);
        }
    }
    for (size_t i = inflight_index_; i < inflight_batch_.size(); ++i) {
        DropWriteRequest(inflight_batch_[i]);
    }
    inflight_batch_.clear();
    inflight_index_ = 0;
    writer_consumed_ = 0;
    WriteRequest* head = write_head_.exchange(nullptr, std::memory_order_acq_rel);
    while (head != nullptr) {
        WriteRequest* next = head->next.load(std::memory_order_acquire);
        while (next == WriteRequest::unlinked()) {
            next = head->next.load(std::memory_order_acquire);
        }
        DropWriteRequest(head);
        head = next;
    }
}

int Socket::SetFailedWithError(int error_code) {
    error_code_.store(error_code, std::memory_order_release);
    return SetFailed();
}

// ---------------- write path ----------------

int Socket::Write(IOBuf* data, uint64_t notify_id) {
    if (Failed()) {
        errno = TERR_FAILED_SOCKET;
        return -1;
    }
    const int64_t sz = (int64_t)data->size();
    if (unwritten_bytes_.load(std::memory_order_relaxed) + sz >
        FLAGS_socket_max_unwritten_bytes.get()) {
        novercrowded_.fetch_add(1, std::memory_order_relaxed);
        *g_eovercrowded << 1;
        errno = TERR_OVERCROWDED;
        return -1;
    }
    WriteRequest* req = new WriteRequest;
    req->notify_id = notify_id;
    req->data.swap(*data);
    req->next.store(WriteRequest::unlinked(), std::memory_order_relaxed);
    const int64_t queued =
        unwritten_bytes_.fetch_add(sz, std::memory_order_relaxed) + sz;
    // Queued-write high-water: how deep the backlog got before the
    // writer caught up (per-socket + the process-wide gauge).
    if (queued > queued_highwater_.load(std::memory_order_relaxed)) {
        queued_highwater_.store(queued, std::memory_order_relaxed);
        queued_write_highwater_cell()->update_max(queued);
    }
    WriteRequest* old = write_head_.exchange(req, std::memory_order_acq_rel);
    req->next.store(old, std::memory_order_release);
    if (write_pending_.fetch_add(1, std::memory_order_acq_rel) != 0) {
        return 0;  // an active writer owns the queue
    }
    // Elected the writer. Inside a coalescing round, hold the flush: later
    // responses of this round pile onto the queue and leave in ONE writev
    // when the scope flushes (chaos mode keeps the per-write KeepWrite
    // discipline — its seams may sleep and must own their fiber).
    if (!__builtin_expect(fault_injection_enabled(), 0) &&
        WriteCoalesceScope::TryDefer(this)) {
        return 0;
    }
    StartKeepWriteIfNeeded();
    return 0;
}

// ---------------- write coalescing (ISSUE 7) ----------------

// Deferred-then-flushed elections: nonzero under load is the proof the
// run-to-completion path is merging same-socket responses.
static LazyAdder g_coalesced_writes("rpc_socket_coalesced_writes");

int64_t SocketCoalescedWrites() {
    return (*g_coalesced_writes).get_value();
}

namespace {
thread_local WriteCoalesceScope* g_write_scope = nullptr;
}  // namespace

WriteCoalesceScope::WriteCoalesceScope() {
    // One-time: flush-and-detach on fiber park (the parked fiber may
    // resume on another pthread; see task_group.h park hooks).
    static const bool hook_registered = [] {
        register_park_hook(&WriteCoalesceScope::FlushCurrent);
        return true;
    }();
    (void)hook_registered;
    if (g_write_scope == nullptr) {
        g_write_scope = this;
        armed_ = true;
    }
}

WriteCoalesceScope::~WriteCoalesceScope() {
    if (!armed_) return;
    FlushDeferred();
    // sched_park may have detached us (flushing on the old thread); only
    // clear the slot we still own.
    if (g_write_scope == this) g_write_scope = nullptr;
}

void WriteCoalesceScope::FlushDeferred() {
    for (int i = 0; i < nsockets_; ++i) {
        Socket* s = sockets_[i];
        // The deferred election is still ours: flush (inline first, then
        // a KeepWrite fiber for leftovers) or drain if the socket died
        // mid-round — exactly KeepWriteThunk's failed-socket duty.
        if (s->Failed()) {
            s->DrainWriteQueue();
        } else {
            s->StartKeepWriteIfNeeded();
        }
        s->Dereference();
    }
    nsockets_ = 0;
}

bool WriteCoalesceScope::TryDefer(Socket* s) {
    WriteCoalesceScope* scope = g_write_scope;
    if (scope == nullptr || scope->nsockets_ >= kMaxSockets) return false;
    // Only the ELECTED writer reaches here, and it stays elected until
    // the flush — the same socket can never be deferred twice in one
    // round, so no duplicate scan is needed.
    s->AddRef();
    scope->sockets_[scope->nsockets_++] = s;
    *g_coalesced_writes << 1;
    return true;
}

void WriteCoalesceScope::FlushCurrent() {
    WriteCoalesceScope* scope = g_write_scope;
    if (scope == nullptr) return;
    scope->FlushDeferred();
    scope->armed_ = false;
    g_write_scope = nullptr;
}

void Socket::StartKeepWriteIfNeeded() {
    // Try one inline non-blocking flush first (the common small-write case:
    // everything fits in the socket buffer, no fiber needed — reference
    // socket.cpp:1615 "write once in the calling thread").
    if (fd() >= 0) {
        if (FlushOnce(false)) return;  // fully drained + retired
    }
    // Leftovers (or not yet connected): hand off to a KeepWrite fiber.
    AddRef();  // ownership ref for the fiber; released there
    fiber_t tid;
    if (fiber_start_background(&tid, nullptr, &Socket::KeepWriteThunk,
                               (void*)(uintptr_t)id()) != 0) {
        Dereference();
        SetFailedWithError(TERR_INTERNAL);
    }
}

void* Socket::KeepWriteThunk(void* arg) {
    const SocketId id = (SocketId)(uintptr_t)arg;
    Socket* s = Address(id);
    if (s == nullptr) {
        // Socket failed before the fiber ran. We still own the writer role
        // (and the AddRef from StartKeepWriteIfNeeded pins the object):
        // drop the queued requests NOW — recycle-time cleanup is deferred
        // indefinitely on health-checked sockets — then balance the ref.
        Socket* raw = address_resource<Socket>(VRefSlot(id));
        if (raw != nullptr) {
            raw->DrainWriteQueue();
            raw->Dereference();
        }
        return nullptr;
    }
    SocketUniquePtr owned(s);
    s->Dereference();  // balance StartKeepWriteIfNeeded's AddRef
    s->KeepWrite();
    return nullptr;
}

void Socket::KeepWrite() {
    if (fd() < 0) {
        if (ConnectIfNot() != 0) {
            SetFailedWithError(errno ? errno : TERR_FAILED_SOCKET);
            DrainWriteQueue();
            return;
        }
    }
    while (true) {
        if (Failed()) {
            DrainWriteQueue();
            return;
        }
        if (FlushOnce(true)) return;  // retired (or failed + drained)
    }
}

void Socket::DrainWriteQueue() {
    int64_t& consumed = writer_consumed_;
    while (true) {
        if (inflight_index_ >= inflight_batch_.size()) {
            inflight_batch_.clear();
            inflight_index_ = 0;
            WriteRequest* grabbed =
                write_head_.exchange(nullptr, std::memory_order_acq_rel);
            for (WriteRequest* cur = grabbed; cur != nullptr;) {
                WriteRequest* next = cur->next.load(std::memory_order_acquire);
                while (next == WriteRequest::unlinked()) {
                    next = cur->next.load(std::memory_order_acquire);
                }
                inflight_batch_.push_back(cur);
                cur = next;
            }
        }
        if (inflight_index_ >= inflight_batch_.size()) {
            const int64_t prev =
                write_pending_.fetch_sub(consumed, std::memory_order_acq_rel);
            const bool retired = (prev == consumed);
            consumed = 0;
            if (retired) return;
            continue;  // racing Write slipped in: grab again
        }
        while (inflight_index_ < inflight_batch_.size()) {
            WriteRequest* req = inflight_batch_[inflight_index_];
            unwritten_bytes_.fetch_sub((int64_t)req->data.size(),
                                       std::memory_order_relaxed);
            DropWriteRequest(req);
            ++inflight_index_;
            ++consumed;
        }
    }
}

// The single-writer drain loop. Grabs LIFO segments from write_head_,
// reverses to FIFO, writevs across requests (the KeepWrite batching of
// reference socket.cpp:1920 DoWrite). Returns true when the writer retired
// (queue balanced) or the socket failed; false when it should continue
// (only with allow_block=false on EAGAIN).
bool Socket::FlushOnce(bool allow_block) {
    // Chaos mode routes EVERY write through the KeepWrite fiber: the
    // inline flush runs on the caller's fiber, possibly under its locks
    // (h2 senders hold the session mutex across Socket::Write), where an
    // injected delay's fiber_usleep could park and unlock a std::mutex
    // from another thread (UB). In the KeepWrite fiber every seam —
    // including the TLS/shm transports' own — may sleep safely.
    if (__builtin_expect(fault_injection_enabled(), 0) && !allow_block) {
        return false;  // caller spawns KeepWrite
    }
    // Emulated-WAN shaping (ISSUE 14): a shaped dcn-tier socket routes
    // every flush through the KeepWrite fiber too — the shaping sleep
    // must never park the caller's fiber under its locks. One member
    // load for the (vast) non-dcn majority.
    const bool shaped_dcn =
        __builtin_expect(forced_tier_ >= 0, 0) && transport_ == nullptr &&
        DcnShapingEnabled() && forced_tier_ == TierDcn();
    if (shaped_dcn && !allow_block) {
        return false;  // caller spawns KeepWrite
    }
    int64_t& consumed = writer_consumed_;
    while (true) {
        // Refill the owned batch.
        if (inflight_index_ >= inflight_batch_.size()) {
            inflight_batch_.clear();
            inflight_index_ = 0;
            WriteRequest* grabbed =
                write_head_.exchange(nullptr, std::memory_order_acq_rel);
            // Reverse newest->oldest chain into oldest-first order.
            std::vector<WriteRequest*> tmp;
            for (WriteRequest* cur = grabbed; cur != nullptr;) {
                WriteRequest* next = cur->next.load(std::memory_order_acquire);
                while (next == WriteRequest::unlinked()) {
                    next = cur->next.load(std::memory_order_acquire);
                }
                tmp.push_back(cur);
                cur = next;
            }
            inflight_batch_.assign(tmp.rbegin(), tmp.rend());
        }
        if (inflight_index_ >= inflight_batch_.size()) {
            // Nothing visible: try to retire.
            const int64_t prev =
                write_pending_.fetch_sub(consumed, std::memory_order_acq_rel);
            const bool retired = (prev == consumed);
            // Either way these requests are now accounted; the next writer
            // generation must start from zero or it over-subtracts the
            // election count and the queue wedges.
            consumed = 0;
            if (retired) return true;
            continue;  // more requests were queued; grab again
        }
        // Gather up to 64 iovecs from the batch tail.
        IOBuf* pieces[64];
        size_t npieces = 0;
        for (size_t i = inflight_index_;
             i < inflight_batch_.size() && npieces < 64; ++i) {
            pieces[npieces++] = &inflight_batch_[i]->data;
        }
        // Chaos seam (tnet/fault_injection.h): one flag load when
        // disabled; when a fault fires it replaces or perturbs this
        // round's writev. Plain-fd sockets only — TLS and shm transports
        // consult the injection layer inside their own
        // CutFromIOBufList/Pump (stacking both seams would double-count
        // decisions and double the effective fault rate), mirroring the
        // transport()==nullptr gate on the read path.
        ssize_t nw = 0;
        bool fault_io = false;
        if (__builtin_expect(fault_injection_enabled(), 0) &&
            transport_ == nullptr) {
            size_t total = 0;
            for (size_t i = 0; i < npieces; ++i) total += pieces[i]->size();
            const FaultAction fa =
                FaultInjection::Decide(FaultOp::kWrite, remote_side_, total);
            switch (fa.kind) {
                case FaultAction::kReset:
                    SetFailedWithError(ECONNRESET);
                    DrainWriteQueue();
                    return true;
                case FaultAction::kDelay:
                    // Safe: chaos mode runs every flush on the
                    // KeepWrite fiber (see the gate at the top).
                    fiber_usleep(fa.delay_us);
                    break;
                case FaultAction::kDrop:
                    // Claim success, discard the bytes: the peer sees a
                    // truncated stream (parse error / stall) and this
                    // side's RPCs ride their timeouts.
                    for (size_t i = 0; i < npieces; ++i) {
                        pieces[i]->pop_front(pieces[i]->size());
                    }
                    nw = (ssize_t)total;
                    fault_io = true;
                    break;
                case FaultAction::kShort:
                case FaultAction::kCorrupt: {
                    // Write a bounded copied prefix (flipping one byte
                    // for kCorrupt — never mutate the shared IOBuf
                    // blocks in place) and let the normal partial-write
                    // machinery handle the remainder.
                    char tmp[2048];
                    IOBuf* first = pieces[0];
                    size_t n = std::min(first->size(), sizeof(tmp));
                    if (fa.kind == FaultAction::kShort && fa.max_bytes > 0) {
                        n = std::min(n, fa.max_bytes);
                    }
                    n = first->copy_to(tmp, n);
                    if (n == 0) break;
                    if (fa.kind == FaultAction::kCorrupt) {
                        tmp[fa.aux % n] ^= 0x20;
                    }
                    const ssize_t w = ::write(fd(), tmp, n);
                    if (w > 0) first->pop_front((size_t)w);
                    nw = w;
                    fault_io = true;
                    break;
                }
                default:
                    break;
            }
        }
        // Emulated-WAN shaping: park for the configured latency + byte
        // time before this round's bytes leave. Runs on the KeepWrite
        // fiber only (the shaped_dcn gate above). A partial write
        // re-shapes its remainder next round — the emulated pipe is a
        // floor, not an exact clock.
        if (shaped_dcn && !fault_io) {
            size_t total = 0;
            for (size_t i = 0; i < npieces; ++i) total += pieces[i]->size();
            const int64_t d = DcnShapeDelayUs(transport_tier(), total);
            if (d > 0) fiber_usleep(d);
        }
        // Data plane: ICI queue pair when plugged (the RdmaEndpoint
        // bypass — reference socket.cpp checks _rdma_state on the write
        // path), else the fd.
        if (!fault_io) {
            nw = transport_ != nullptr
                     ? transport_->CutFromIOBufList(pieces, npieces)
                     : IOBuf::cut_multiple_into_file_descriptor(fd(), pieces,
                                                                npieces);
        }
        if (nw < 0) {
            if (errno == EAGAIN || errno == EWOULDBLOCK) {
                if (!allow_block) return false;  // caller spawns KeepWrite
                // Out of window credits (queue-pair tiers) or kernel
                // buffer (fd tier): the writer is about to park.
                transport_stats::AddCreditStall(transport_tier());
                const int wrc =
                    transport_ != nullptr
                        ? transport_->WaitWritable(monotonic_time_us() +
                                                   2 * 1000 * 1000)
                        : WaitEpollOut();
                if (wrc != 0) {
                    SetFailedWithError(TERR_FAILED_SOCKET);
                    DrainWriteQueue();
                    return true;
                }
                continue;
            }
            if (errno == EINTR) continue;
            SetFailedWithError(errno);
            DrainWriteQueue();
            return true;
        }
        unwritten_bytes_.fetch_sub(nw, std::memory_order_relaxed);
        add_bytes_written(nw);
        if (nw > 0) {
            // Per-tier byte attribution (the Transport seam, ISSUE 12).
            transport_stats::AddOut(transport_tier(), nw);
            transport_stats::AddOp(transport_tier());
            // Write-batch attribution: one writev round = one batch.
            nwrite_batches_.fetch_add(1, std::memory_order_relaxed);
            if (nw > max_write_batch_.load(std::memory_order_relaxed)) {
                max_write_batch_.store(nw, std::memory_order_relaxed);
            }
            *write_batch_recorder() << nw;
        }
        // Drop fully-written requests.
        while (inflight_index_ < inflight_batch_.size() &&
               inflight_batch_[inflight_index_]->data.empty()) {
            delete inflight_batch_[inflight_index_];
            ++inflight_index_;
            ++consumed;
        }
    }
}

int Socket::WaitAuthenticated(int64_t abstime_us) {
    std::atomic<int>* word = butex_word(auth_butex_);
    while (true) {
        const int st = auth_state_.load(std::memory_order_acquire);
        if (st == 2 || st == 0) break;  // done, or aborted (re-fight)
        if (Failed()) return -1;
        const int expected = word->load(std::memory_order_acquire);
        const int st2 = auth_state_.load(std::memory_order_acquire);
        if (st2 == 2 || st2 == 0) break;
        if (abstime_us > 0 && monotonic_time_us() >= abstime_us) return -1;
        const int64_t slice =
            abstime_us > 0
                ? std::min<int64_t>(abstime_us,
                                    monotonic_time_us() + 200 * 1000)
                : monotonic_time_us() + 200 * 1000;
        butex_wait(auth_butex_, expected, &slice);
    }
    return Failed() ? -1 : 0;
}

int Socket::WaitEpollOut() {
    const int the_fd = fd();
    if (the_fd < 0) return -1;
    std::atomic<int>* word = butex_word(epollout_butex_);
    const int expected = word->load(std::memory_order_acquire);
    EventDispatcher& d = EventDispatcher::GetGlobalDispatcher(the_fd);
    if (d.RegisterEpollOut(id(), the_fd, true) != 0) return -1;
    const int64_t abstime = monotonic_time_us() + 2 * 1000 * 1000;
    butex_wait(epollout_butex_, expected, &abstime);
    d.UnregisterEpollOut(id(), the_fd, true);
    return Failed() ? -1 : 0;
}

// ---------------- connect ----------------

int Socket::ConnectIfNot() {
    if (fd() >= 0) return 0;
    bool expected = false;
    if (!connecting_.compare_exchange_strong(expected, true)) {
        // Another fiber connects; wait for it.
        std::atomic<int>* word = butex_word(connect_butex_);
        while (fd() < 0 && !Failed()) {
            const int v = word->load(std::memory_order_acquire);
            if (fd() >= 0 || Failed()) break;
            const int64_t abst = monotonic_time_us() + 100 * 1000;
            butex_wait(connect_butex_, v, &abst);
        }
        return (fd() >= 0 && !Failed()) ? 0 : -1;
    }
    // Chaos: connect-time refusal — the client-side mirror of the
    // acceptor's refuse (exercises retry + LB re-selection).
    if (__builtin_expect(fault_injection_enabled(), 0) &&
        FaultInjection::Decide(FaultOp::kConnect, remote_side_, 0).kind ==
            FaultAction::kRefuse) {
        connecting_.store(false, std::memory_order_release);
        errno = ECONNREFUSED;
        return -1;
    }
    const int sock = ::socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (sock < 0) {
        connecting_.store(false, std::memory_order_release);
        return -1;
    }
    int one = 1;
    setsockopt(sock, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    ApplySocketBufferSizes(sock);
    sockaddr_in addr;
    endpoint2sockaddr(remote_side_, &addr);
    int rc = ::connect(sock, (sockaddr*)&addr, sizeof(addr));
    if (rc != 0 && errno != EINPROGRESS) {
        close(sock);
        connecting_.store(false, std::memory_order_release);
        return -1;
    }
    EventDispatcher& d = EventDispatcher::GetGlobalDispatcher(sock);
    std::atomic<int>* word = butex_word(connect_butex_);
    int seq = word->load(std::memory_order_acquire);
    if (d.AddConsumerWithEpollOut(id(), sock) != 0) {
        close(sock);
        connecting_.store(false, std::memory_order_release);
        return -1;
    }
    if (rc != 0) {
        // Await writability (= connect completion), 3s cap.
        const int64_t deadline = monotonic_time_us() + 3 * 1000 * 1000;
        while (!Failed()) {
            int err = 0;
            socklen_t len = sizeof(err);
            getsockopt(sock, SOL_SOCKET, SO_ERROR, &err, &len);
            if (err != 0) {
                errno = err;
                break;
            }
            // Poll connection state cheaply: getpeername succeeds once
            // connected.
            sockaddr_in peer;
            socklen_t plen = sizeof(peer);
            if (getpeername(sock, (sockaddr*)&peer, &plen) == 0) {
                rc = 0;
                break;
            }
            if (monotonic_time_us() >= deadline) {
                errno = ETIMEDOUT;
                break;
            }
            const int64_t abst = monotonic_time_us() + 50 * 1000;
            butex_wait(connect_butex_, seq, &abst);
            seq = word->load(std::memory_order_acquire);
        }
        if (rc != 0 || Failed()) {
            d.RemoveConsumer(sock);
            close(sock);
            connecting_.store(false, std::memory_order_release);
            word->fetch_add(1, std::memory_order_release);
            butex_wake_all(connect_butex_);
            return -1;
        }
    }
    // Connected: record sides, drop EPOLLOUT interest.
    sockaddr_in local;
    socklen_t llen = sizeof(local);
    if (getsockname(sock, (sockaddr*)&local, &llen) == 0) {
        local_side_ = sockaddr2endpoint(local);
    }
    d.UnregisterEpollOut(id(), sock, true);
    if (tls_) {
        // Wrap the freshly connected fd BEFORE fd_ becomes visible, so
        // every write/read path sees the transport together with the fd.
        TransportEndpoint* t =
            NewTlsClientTransport(sock, tls_alpn_, tls_sni_);
        if (t == nullptr) {
            d.RemoveConsumer(sock);
            close(sock);
            connecting_.store(false, std::memory_order_release);
            word->fetch_add(1, std::memory_order_release);
            butex_wake_all(connect_butex_);
            return -1;
        }
        transport_ = t;
        owns_transport_ = true;
    }
    fd_.store(sock, std::memory_order_release);
    connecting_.store(false, std::memory_order_release);
    word->fetch_add(1, std::memory_order_release);
    butex_wake_all(connect_butex_);
    return 0;
}

// ---------------- read events ----------------

void Socket::OnInputEventById(SocketId id) {
    Socket* s = Address(id);
    if (s == nullptr) return;
    SocketUniquePtr ptr(s);
    if (s->on_edge_triggered_events_ == nullptr) return;
    if (s->nevent_.fetch_add(1, std::memory_order_acq_rel) == 0) {
        // First event of a burst: elect one processing fiber.
        s->AddRef();
        fiber_t tid;
        if (fiber_start_background(&tid, nullptr, &Socket::ProcessEventThunk,
                                   (void*)(uintptr_t)id) != 0) {
            s->Dereference();
            s->nevent_.store(0, std::memory_order_release);
        }
    }
}

void* Socket::ProcessEventThunk(void* arg) {
    const SocketId id = (SocketId)(uintptr_t)arg;
    Socket* s = Address(id);
    if (s == nullptr) {
        // Balance the AddRef: the socket was failed but memory persists.
        // (Address failed => versioned ref says stale; the extra ref we
        // took in OnInputEventById still pins the object.)
        s = address_resource<Socket>(VRefSlot(id));
        if (s != nullptr) s->Dereference();
        return nullptr;
    }
    SocketUniquePtr ptr(s);
    s->Dereference();  // balance OnInputEventById's AddRef
    while (true) {
        const int n = s->nevent_.load(std::memory_order_acquire);
        // fd() < 0 means an async connect is still in flight: EPOLLERR/HUP
        // on the connecting fd routes here too, but the read callback must
        // not run against fd -1 (the connect loop surfaces the error).
        if (!s->Failed() && s->on_edge_triggered_events_ != nullptr &&
            s->fd() >= 0) {
            s->on_edge_triggered_events_(s);
        }
        if (s->nevent_.fetch_sub(n, std::memory_order_acq_rel) == n) {
            break;
        }
    }
    return nullptr;
}

void Socket::OnOutputEventById(SocketId id) {
    Socket* s = Address(id);
    if (s == nullptr) return;
    SocketUniquePtr ptr(s);
    // Wake connecters and blocked writers.
    butex_word(s->connect_butex_)->fetch_add(1, std::memory_order_release);
    butex_wake_all(s->connect_butex_);
    butex_word(s->epollout_butex_)->fetch_add(1, std::memory_order_release);
    butex_wake_all(s->epollout_butex_);
}

}  // namespace tpurpc
