#include "tnet/event_dispatcher.h"

#include <pthread.h>
#include <sched.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <unistd.h>

#include <algorithm>
#include <cerrno>
#include <cstring>
#include <string>

#include "tbase/flags.h"
#include "tbase/logging.h"
#include "tbase/time.h"
#include "tvar/multi_dimension.h"

// 0 = auto: one loop per ~4 cores, capped at 4 (the reference defaults to
// 1, which serializes all sockets through a single epoll loop — the main
// reason its multi-connection mode needs explicit tuning; multi-core TPU-VM
// hosts have cores to spare for I/O).
DEFINE_int32(event_dispatcher_num, 0, "number of epoll loops; 0 = auto");
// Per-core sharded loops: loop i is pinned to the i-th CPU of this list.
// An I/O loop that stays on one core keeps its socket/epoll state in one
// cache and never migrates mid-burst — the run-to-completion half of the
// sharded-loop design (ROADMAP item 4). Read once at loop start.
DEFINE_string(event_dispatcher_affinity, "",
              "comma-separated CPUs or ranges (e.g. \"0-3\" or \"0,2,4\") "
              "pinning epoll loop i to the i-th entry; empty = no pinning");

namespace tpurpc {

namespace {
// epoll_data carries the SocketId; EPOLLOUT interest is encoded in the
// registration mode only. The wakeup eventfd is registered with this
// sentinel (never a valid SocketId: VRef ids have a bounded slot part).
constexpr uint64_t kWakeupData = ~0ull;

// Labelled telemetry families, one series per loop ({loop="N"}).
// Process-lifetime, created on first dispatcher construction (runtime,
// never static-init).
LabelledMetric<IntCell>* loop_waits() {
    static auto* m =
        new LabelledMetric<IntCell>("rpc_dispatcher_epoll_waits", {"loop"});
    return m;
}
LabelledMetric<IntCell>* loop_events() {
    static auto* m =
        new LabelledMetric<IntCell>("rpc_dispatcher_events", {"loop"});
    return m;
}
LabelledMetric<IntCell>* loop_wakeups() {
    static auto* m =
        new LabelledMetric<IntCell>("rpc_dispatcher_wakeups", {"loop"});
    return m;
}
LabelledMetric<LatencyRecorder>* loop_events_per_wake() {
    static auto* m = new LabelledMetric<LatencyRecorder>(
        "rpc_dispatcher_events_per_wake", {"loop"});
    return m;
}
LabelledMetric<LatencyRecorder>* loop_wake_us() {
    static auto* m = new LabelledMetric<LatencyRecorder>(
        "rpc_dispatcher_wake_to_dispatch_us", {"loop"});
    return m;
}

// "0-3,8,10-11" -> {0,1,2,3,8,10,11}. Malformed entries are skipped with
// a log line rather than failing startup (affinity is an optimization).
std::vector<int> ParseCpuList(const std::string& spec) {
    std::vector<int> cpus;
    size_t pos = 0;
    while (pos < spec.size()) {
        size_t comma = spec.find(',', pos);
        if (comma == std::string::npos) comma = spec.size();
        const std::string tok = spec.substr(pos, comma - pos);
        pos = comma + 1;
        if (tok.empty()) continue;
        char* end = nullptr;
        const long lo = strtol(tok.c_str(), &end, 10);
        long hi = lo;
        if (end != nullptr && *end == '-') {
            hi = strtol(end + 1, &end, 10);
        }
        if (end == nullptr || *end != '\0' || lo < 0 || hi < lo ||
            hi >= 4096) {
            LOG(ERROR) << "bad -event_dispatcher_affinity entry: " << tok;
            continue;
        }
        for (long c = lo; c <= hi; ++c) cpus.push_back((int)c);
    }
    return cpus;
}
}  // namespace

EventDispatcher::EventDispatcher(int index) : index_(index) {
    const std::string loop = std::to_string(index);
    waits_cell_ = loop_waits()->get_stats({loop});
    events_cell_ = loop_events()->get_stats({loop});
    wakeups_cell_ = loop_wakeups()->get_stats({loop});
    events_per_wake_ = loop_events_per_wake()->get_stats({loop});
    wake_us_ = loop_wake_us()->get_stats({loop});
    epfd_ = epoll_create1(EPOLL_CLOEXEC);
    CHECK_GE(epfd_, 0) << "epoll_create1 failed";
    // Stop/wake channel: an eventfd IN the epoll set, so the loop can
    // block in epoll_wait indefinitely (no idle tick) and still wake
    // promptly. EFD_NONBLOCK: the drain read must never stall the loop.
    wakeup_fd_ = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
    CHECK_GE(wakeup_fd_, 0) << "eventfd failed";
    epoll_event evt;
    evt.events = EPOLLIN;
    evt.data.u64 = kWakeupData;
    CHECK_EQ(epoll_ctl(epfd_, EPOLL_CTL_ADD, wakeup_fd_, &evt), 0)
        << "registering wakeup eventfd failed";
    const std::vector<int> cpus =
        ParseCpuList(FLAGS_event_dispatcher_affinity.get());
    if (!cpus.empty()) {
        pinned_cpu_ = cpus[(size_t)index % cpus.size()];
    }
    thread_ = std::thread([this] { Run(); });
    if (pinned_cpu_ >= 0) {
        cpu_set_t set;
        CPU_ZERO(&set);
        CPU_SET(pinned_cpu_, &set);
        // pthread_* returns the error code (errno stays untouched).
        const int rc = pthread_setaffinity_np(thread_.native_handle(),
                                              sizeof(set), &set);
        if (rc != 0) {
            LOG(ERROR) << "pinning epoll loop " << index_ << " to cpu "
                       << pinned_cpu_ << " failed: " << strerror(rc);
            pinned_cpu_ = -1;
        }
    }
}

EventDispatcher::~EventDispatcher() {
    stop_.store(true, std::memory_order_release);
    Wakeup();
    if (thread_.joinable()) thread_.join();
    if (epfd_ >= 0) {
        close(epfd_);
        epfd_ = -1;
    }
    if (wakeup_fd_ >= 0) {
        close(wakeup_fd_);
        wakeup_fd_ = -1;
    }
}

void EventDispatcher::Wakeup() {
    const uint64_t one = 1;
    if (write(wakeup_fd_, &one, sizeof(one)) < 0 && errno != EAGAIN) {
        PLOG(ERROR) << "eventfd wakeup write failed";
    }
}

int EventDispatcher::AddConsumer(SocketId id, int fd) {
    epoll_event evt;
    evt.events = EPOLLIN | EPOLLET;
    evt.data.u64 = id;
    return epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &evt);
}

int EventDispatcher::AddConsumerWithEpollOut(SocketId id, int fd) {
    epoll_event evt;
    evt.events = EPOLLIN | EPOLLOUT | EPOLLET;
    evt.data.u64 = id;
    return epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &evt);
}

int EventDispatcher::RegisterEpollOut(SocketId id, int fd, bool pollin) {
    epoll_event evt;
    evt.data.u64 = id;
    evt.events = EPOLLOUT | EPOLLET | (pollin ? EPOLLIN : 0);
    if (pollin) {
        return epoll_ctl(epfd_, EPOLL_CTL_MOD, fd, &evt);
    }
    return epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &evt);
}

int EventDispatcher::UnregisterEpollOut(SocketId id, int fd, bool pollin) {
    if (pollin) {
        epoll_event evt;
        evt.data.u64 = id;
        evt.events = EPOLLIN | EPOLLET;
        return epoll_ctl(epfd_, EPOLL_CTL_MOD, fd, &evt);
    }
    return epoll_ctl(epfd_, EPOLL_CTL_DEL, fd, nullptr);
}

int EventDispatcher::RemoveConsumer(int fd) {
    return epoll_ctl(epfd_, EPOLL_CTL_DEL, fd, nullptr);
}

void EventDispatcher::Run() {
    // Adaptive batch: starts small (one cache line of events covers the
    // common case), doubles whenever a wake fills the whole array —
    // events_per_wake saturating at the array size means readiness was
    // truncated and the loop paid an extra epoll_wait per burst.
    std::vector<epoll_event> events(
        (size_t)batch_capacity_.load(std::memory_order_relaxed));
    constexpr size_t kMaxBatch = 4096;
    while (!stop_.load(std::memory_order_acquire)) {
        // Block until readiness or an eventfd kick — idle loops cost
        // nothing (the old loop woke every 100 ms unconditionally).
        const int n = epoll_wait(epfd_, events.data(), (int)events.size(),
                                 -1);
        if (n < 0) {
            if (errno == EINTR) continue;
            PLOG(ERROR) << "epoll_wait failed on loop " << index_;
            break;
        }
        // Hot-loop telemetry: one counter add per wake; the recorders
        // and the second clock read only run when events were delivered.
        waits_cell_->add(1);
        if (n == 0) continue;
        const int64_t t0 = monotonic_time_us();
        int ndispatched = 0;
        for (int i = 0; i < n; ++i) {
            if (events[i].data.u64 == kWakeupData) {
                uint64_t drained;
                while (read(wakeup_fd_, &drained, sizeof(drained)) > 0) {
                }
                wakeups_cell_->add(1);
                continue;
            }
            ++ndispatched;
            const SocketId id = events[i].data.u64;
            if (events[i].events & (EPOLLOUT | EPOLLERR | EPOLLHUP)) {
                Socket::OnOutputEventById(id);
            }
            if (events[i].events & (EPOLLIN | EPOLLERR | EPOLLHUP)) {
                Socket::OnInputEventById(id);
            }
        }
        if (ndispatched > 0) {
            events_cell_->add(ndispatched);
            *events_per_wake_ << ndispatched;
            // Wake→dispatch: how long a readiness burst takes to hand off
            // to fibers — when this climbs with events_per_wake, the loop
            // is the bottleneck (the per-core sharding argument of
            // ROADMAP item 4).
            *wake_us_ << (monotonic_time_us() - t0);
        }
        if ((size_t)n == events.size() && events.size() < kMaxBatch) {
            events.resize(events.size() * 2);
            batch_capacity_.store((int64_t)events.size(),
                                  std::memory_order_relaxed);
        }
    }
}

namespace {
struct Dispatchers {
    std::vector<EventDispatcher*> list;
};
std::atomic<Dispatchers*> g_dispatchers{nullptr};
}  // namespace

EventDispatcher& EventDispatcher::GetGlobalDispatcher(int fd) {
    static Dispatchers* d = [] {
        auto* dd = new Dispatchers;
        int n = FLAGS_event_dispatcher_num.get();
        if (n == 0) {
            const unsigned hc = std::thread::hardware_concurrency();
            n = (int)std::min(4u, std::max(1u, hc / 4));
        }
        if (n < 1) n = 1;
        for (int i = 0; i < n; ++i) {
            dd->list.push_back(new EventDispatcher(i));
        }
        g_dispatchers.store(dd, std::memory_order_release);
        return dd;
    }();
    return *d->list[(size_t)fd % d->list.size()];
}

void EventDispatcher::ForEachLoop(void (*fn)(int, const LoopStats&, void*),
                                  void* arg) {
    Dispatchers* d = g_dispatchers.load(std::memory_order_acquire);
    if (d == nullptr) return;
    for (size_t i = 0; i < d->list.size(); ++i) {
        const EventDispatcher* ed = d->list[i];
        LoopStats st;
        st.epoll_waits = ed->waits_cell_->get();
        st.events = ed->events_cell_->get();
        st.wakeups = ed->wakeups_cell_->get();
        st.batch_capacity =
            ed->batch_capacity_.load(std::memory_order_relaxed);
        st.cpu = ed->pinned_cpu_;
        st.events_per_wake = ed->events_per_wake_;
        st.wake_to_dispatch_us = ed->wake_us_;
        fn((int)i, st, arg);
    }
}

int64_t EventDispatcher::TotalEpollWaits() {
    int64_t total = 0;
    ForEachLoop(
        [](int, const LoopStats& st, void* arg) {
            *(int64_t*)arg += st.epoll_waits;
        },
        &total);
    return total;
}

void EventDispatcher::StopAll() {
    // Dispatchers are process-lifetime (like the reference); nothing to do.
}

}  // namespace tpurpc
