#include "tnet/event_dispatcher.h"

#include <sys/epoll.h>
#include <unistd.h>

#include <algorithm>
#include <cerrno>

#include "tbase/flags.h"
#include "tbase/logging.h"

// 0 = auto: one loop per ~4 cores, capped at 4 (the reference defaults to
// 1, which serializes all sockets through a single epoll loop — the main
// reason its multi-connection mode needs explicit tuning; multi-core TPU-VM
// hosts have cores to spare for I/O).
DEFINE_int32(event_dispatcher_num, 0, "number of epoll loops; 0 = auto");

namespace tpurpc {

namespace {
// epoll_data carries the SocketId; EPOLLOUT interest is encoded in the
// registration mode only.
}  // namespace

EventDispatcher::EventDispatcher() {
    epfd_ = epoll_create1(EPOLL_CLOEXEC);
    CHECK_GE(epfd_, 0) << "epoll_create1 failed";
    thread_ = std::thread([this] { Run(); });
}

EventDispatcher::~EventDispatcher() {
    stop_.store(true, std::memory_order_release);
    if (epfd_ >= 0) {
        // Wake the loop by closing; epoll_wait returns EBADF.
        close(epfd_);
        epfd_ = -1;
    }
    if (thread_.joinable()) thread_.join();
}

int EventDispatcher::AddConsumer(SocketId id, int fd) {
    epoll_event evt;
    evt.events = EPOLLIN | EPOLLET;
    evt.data.u64 = id;
    return epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &evt);
}

int EventDispatcher::AddConsumerWithEpollOut(SocketId id, int fd) {
    epoll_event evt;
    evt.events = EPOLLIN | EPOLLOUT | EPOLLET;
    evt.data.u64 = id;
    return epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &evt);
}

int EventDispatcher::RegisterEpollOut(SocketId id, int fd, bool pollin) {
    epoll_event evt;
    evt.data.u64 = id;
    evt.events = EPOLLOUT | EPOLLET | (pollin ? EPOLLIN : 0);
    if (pollin) {
        return epoll_ctl(epfd_, EPOLL_CTL_MOD, fd, &evt);
    }
    return epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &evt);
}

int EventDispatcher::UnregisterEpollOut(SocketId id, int fd, bool pollin) {
    if (pollin) {
        epoll_event evt;
        evt.data.u64 = id;
        evt.events = EPOLLIN | EPOLLET;
        return epoll_ctl(epfd_, EPOLL_CTL_MOD, fd, &evt);
    }
    return epoll_ctl(epfd_, EPOLL_CTL_DEL, fd, nullptr);
}

int EventDispatcher::RemoveConsumer(int fd) {
    return epoll_ctl(epfd_, EPOLL_CTL_DEL, fd, nullptr);
}

void EventDispatcher::Run() {
    epoll_event events[64];
    while (!stop_.load(std::memory_order_acquire)) {
        const int epfd = epfd_;
        if (epfd < 0) break;
        const int n = epoll_wait(epfd, events, 64, 100 /*ms*/);
        if (n < 0) {
            if (errno == EINTR) continue;
            break;  // epfd closed
        }
        for (int i = 0; i < n; ++i) {
            const SocketId id = events[i].data.u64;
            if (events[i].events & (EPOLLOUT | EPOLLERR | EPOLLHUP)) {
                Socket::OnOutputEventById(id);
            }
            if (events[i].events & (EPOLLIN | EPOLLERR | EPOLLHUP)) {
                Socket::OnInputEventById(id);
            }
        }
    }
}

namespace {
struct Dispatchers {
    std::vector<EventDispatcher*> list;
};
}  // namespace

EventDispatcher& EventDispatcher::GetGlobalDispatcher(int fd) {
    static Dispatchers* d = [] {
        auto* dd = new Dispatchers;
        int n = FLAGS_event_dispatcher_num.get();
        if (n == 0) {
            const unsigned hc = std::thread::hardware_concurrency();
            n = (int)std::min(4u, std::max(1u, hc / 4));
        }
        if (n < 1) n = 1;
        for (int i = 0; i < n; ++i) dd->list.push_back(new EventDispatcher);
        return dd;
    }();
    return *d->list[(size_t)fd % d->list.size()];
}

void EventDispatcher::StopAll() {
    // Dispatchers are process-lifetime (like the reference); nothing to do.
}

}  // namespace tpurpc
