#include "tnet/event_dispatcher.h"

#include <sys/epoll.h>
#include <unistd.h>

#include <algorithm>
#include <cerrno>
#include <string>

#include "tbase/flags.h"
#include "tbase/logging.h"
#include "tbase/time.h"
#include "tvar/multi_dimension.h"

// 0 = auto: one loop per ~4 cores, capped at 4 (the reference defaults to
// 1, which serializes all sockets through a single epoll loop — the main
// reason its multi-connection mode needs explicit tuning; multi-core TPU-VM
// hosts have cores to spare for I/O).
DEFINE_int32(event_dispatcher_num, 0, "number of epoll loops; 0 = auto");

namespace tpurpc {

namespace {
// epoll_data carries the SocketId; EPOLLOUT interest is encoded in the
// registration mode only.

// Labelled telemetry families, one series per loop ({loop="N"}).
// Process-lifetime, created on first dispatcher construction (runtime,
// never static-init).
LabelledMetric<IntCell>* loop_waits() {
    static auto* m =
        new LabelledMetric<IntCell>("rpc_dispatcher_epoll_waits", {"loop"});
    return m;
}
LabelledMetric<IntCell>* loop_events() {
    static auto* m =
        new LabelledMetric<IntCell>("rpc_dispatcher_events", {"loop"});
    return m;
}
LabelledMetric<LatencyRecorder>* loop_events_per_wake() {
    static auto* m = new LabelledMetric<LatencyRecorder>(
        "rpc_dispatcher_events_per_wake", {"loop"});
    return m;
}
LabelledMetric<LatencyRecorder>* loop_wake_us() {
    static auto* m = new LabelledMetric<LatencyRecorder>(
        "rpc_dispatcher_wake_to_dispatch_us", {"loop"});
    return m;
}
}  // namespace

EventDispatcher::EventDispatcher(int index) : index_(index) {
    const std::string loop = std::to_string(index);
    waits_cell_ = loop_waits()->get_stats({loop});
    events_cell_ = loop_events()->get_stats({loop});
    events_per_wake_ = loop_events_per_wake()->get_stats({loop});
    wake_us_ = loop_wake_us()->get_stats({loop});
    epfd_ = epoll_create1(EPOLL_CLOEXEC);
    CHECK_GE(epfd_, 0) << "epoll_create1 failed";
    thread_ = std::thread([this] { Run(); });
}

EventDispatcher::~EventDispatcher() {
    stop_.store(true, std::memory_order_release);
    if (epfd_ >= 0) {
        // Wake the loop by closing; epoll_wait returns EBADF.
        close(epfd_);
        epfd_ = -1;
    }
    if (thread_.joinable()) thread_.join();
}

int EventDispatcher::AddConsumer(SocketId id, int fd) {
    epoll_event evt;
    evt.events = EPOLLIN | EPOLLET;
    evt.data.u64 = id;
    return epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &evt);
}

int EventDispatcher::AddConsumerWithEpollOut(SocketId id, int fd) {
    epoll_event evt;
    evt.events = EPOLLIN | EPOLLOUT | EPOLLET;
    evt.data.u64 = id;
    return epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &evt);
}

int EventDispatcher::RegisterEpollOut(SocketId id, int fd, bool pollin) {
    epoll_event evt;
    evt.data.u64 = id;
    evt.events = EPOLLOUT | EPOLLET | (pollin ? EPOLLIN : 0);
    if (pollin) {
        return epoll_ctl(epfd_, EPOLL_CTL_MOD, fd, &evt);
    }
    return epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &evt);
}

int EventDispatcher::UnregisterEpollOut(SocketId id, int fd, bool pollin) {
    if (pollin) {
        epoll_event evt;
        evt.data.u64 = id;
        evt.events = EPOLLIN | EPOLLET;
        return epoll_ctl(epfd_, EPOLL_CTL_MOD, fd, &evt);
    }
    return epoll_ctl(epfd_, EPOLL_CTL_DEL, fd, nullptr);
}

int EventDispatcher::RemoveConsumer(int fd) {
    return epoll_ctl(epfd_, EPOLL_CTL_DEL, fd, nullptr);
}

void EventDispatcher::Run() {
    epoll_event events[64];
    while (!stop_.load(std::memory_order_acquire)) {
        const int epfd = epfd_;
        if (epfd < 0) break;
        const int n = epoll_wait(epfd, events, 64, 100 /*ms*/);
        if (n < 0) {
            if (errno == EINTR) continue;
            break;  // epfd closed
        }
        // Hot-loop telemetry: two counter adds per wake; the recorders
        // and the second clock read only run when events were delivered.
        waits_cell_->add(1);
        if (n == 0) continue;
        const int64_t t0 = monotonic_time_us();
        events_cell_->add(n);
        *events_per_wake_ << n;
        for (int i = 0; i < n; ++i) {
            const SocketId id = events[i].data.u64;
            if (events[i].events & (EPOLLOUT | EPOLLERR | EPOLLHUP)) {
                Socket::OnOutputEventById(id);
            }
            if (events[i].events & (EPOLLIN | EPOLLERR | EPOLLHUP)) {
                Socket::OnInputEventById(id);
            }
        }
        // Wake→dispatch: how long a readiness burst takes to hand off to
        // fibers — when this climbs with events_per_wake, the loop is the
        // bottleneck (the per-core sharding argument of ROADMAP item 4).
        *wake_us_ << (monotonic_time_us() - t0);
    }
}

namespace {
struct Dispatchers {
    std::vector<EventDispatcher*> list;
};
std::atomic<Dispatchers*> g_dispatchers{nullptr};
}  // namespace

EventDispatcher& EventDispatcher::GetGlobalDispatcher(int fd) {
    static Dispatchers* d = [] {
        auto* dd = new Dispatchers;
        int n = FLAGS_event_dispatcher_num.get();
        if (n == 0) {
            const unsigned hc = std::thread::hardware_concurrency();
            n = (int)std::min(4u, std::max(1u, hc / 4));
        }
        if (n < 1) n = 1;
        for (int i = 0; i < n; ++i) {
            dd->list.push_back(new EventDispatcher(i));
        }
        g_dispatchers.store(dd, std::memory_order_release);
        return dd;
    }();
    return *d->list[(size_t)fd % d->list.size()];
}

void EventDispatcher::ForEachLoop(void (*fn)(int, const LoopStats&, void*),
                                  void* arg) {
    Dispatchers* d = g_dispatchers.load(std::memory_order_acquire);
    if (d == nullptr) return;
    for (size_t i = 0; i < d->list.size(); ++i) {
        const EventDispatcher* ed = d->list[i];
        LoopStats st;
        st.epoll_waits = ed->waits_cell_->get();
        st.events = ed->events_cell_->get();
        st.events_per_wake = ed->events_per_wake_;
        st.wake_to_dispatch_us = ed->wake_us_;
        fn((int)i, st, arg);
    }
}

int64_t EventDispatcher::TotalEpollWaits() {
    int64_t total = 0;
    ForEachLoop(
        [](int, const LoopStats& st, void* arg) {
            *(int64_t*)arg += st.epoll_waits;
        },
        &total);
    return total;
}

void EventDispatcher::StopAll() {
    // Dispatchers are process-lifetime (like the reference); nothing to do.
}

}  // namespace tpurpc
