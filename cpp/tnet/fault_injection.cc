#include "tnet/fault_injection.h"

#include <algorithm>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <vector>

#include "tbase/doubly_buffered_data.h"
#include "tbase/flags.h"
#include "tbase/flight_recorder.h"
#include "tbase/logging.h"
#include "tvar/reducer.h"

// The whole chaos configuration is flag-driven so it can be set on the
// command line (--flagfile-less: flags parse via SetFlagValue), through
// /flags, or through the /chaos portal page — all three converge on
// FaultInjection::Reconfigure() via the flags' on-change hooks.
DEFINE_bool(chaos_enabled, false,
            "master switch for transport fault injection (the only check "
            "on the I/O hot path)");
DEFINE_int64(chaos_seed, 1,
             "seed of the deterministic injection sequence; replaying a "
             "seed against the same call sequence reproduces the same "
             "faults");
DEFINE_string(chaos_plan, "",
              "comma list of kind=probability[:param] entries; kinds: "
              "drop, delay (param = microseconds, default 2000), short, "
              "corrupt, reset (read/write ops), refuse "
              "(accept/connect), the zero-copy pool seams "
              "pool_corrupt, pool_stale (descriptor AND wire-verb "
              "resolve), "
              "pool_leak (pinned-block release), ring_delay (param = "
              "microseconds), ring_drop (staging-ring completes), and "
              "cost_inflate (param = multiplier, default 10: inflate a "
              "completion's measured cost before it feeds the QoS "
              "admission cost model), and the server-push stream seam "
              "stream_stall (param = microseconds, default 5000: delay a "
              "STREAM_DATA chunk send — a slow consumer) / "
              "stream_drop_chunk (discard a chunk send; the receiver's "
              "dup-ack retransmit recovers it from the replay ring), "
              "and the one-sided verb plane (ISSUE 18): verb_drop "
              "(discard a posted REMOTE_READ/REMOTE_WRITE in flight; "
              "the initiator's pending-wr deadline reaps and retries "
              "it) / doorbell_delay (param = microseconds, default "
              "2000: deliver a CQ completion late, parking pollers); "
              "and crash (ISSUE 19: a ticked decision kills the process "
              "with a real SIGSEGV so the flight recorder's black-box "
              "signal path fires); "
              "and the grey-failure handler seam (ISSUE 20): slow_node "
              "(param = MILLISECONDS, default 50: inflate service time "
              "at handler dispatch — the node stays healthy to connect "
              "probes, only slower) / error_rate (answer the call with a "
              "synthetic retriable failure without running the handler); "
              "e.g. 'drop=0.01,delay=0.05:2000,cost_inflate=1:8' or "
              "'slow_node=1:80,error_rate=0.05'");
DEFINE_string(chaos_peers, "",
              "comma list of ip:port remote endpoints the plan applies "
              "to; empty = all peers. Non-matching traffic neither "
              "injects nor consumes a decision tick");
DEFINE_string(chaos_partition_zone, "",
              "partition THIS node from every peer registered (via "
              "FaultInjection::SetPeerZone / mesh zone tags) in the "
              "named zone: their reads/writes reset, connects refuse — "
              "one command cuts a whole pod (ISSUE 14). Empty = no "
              "partition. Independent of chaos_plan and the "
              "deterministic decision sequence");

namespace tpurpc {

namespace fault_internal {
std::atomic<bool> g_chaos_on{false};
}  // namespace fault_internal

namespace {

// splitmix64: the canonical 64-bit mixer — decision n is mix(seed + n*phi).
inline uint64_t splitmix64(uint64_t x) {
    x += 0x9e3779b97f4a7c15ull;
    x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
    x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
    return x ^ (x >> 31);
}

inline double to_unit(uint64_t r) {
    return (double)(r >> 11) * 0x1.0p-53;  // uniform [0, 1)
}

// Kind -> name, indexed by FaultAction::Kind (tvar suffixes AND the
// /chaos page lines — one table so they can never desynchronize).
const char* const kKindNames[FaultAction::kKindCount] = {
    "none",    "delay",  "short",       "drop",         "corrupt",
    "reset",   "refuse", "stale_epoch", "cost_inflate", "crash",
    "fail"};

struct FaultPlan {
    // Read/write fault probabilities (selected by one uniform draw over
    // cumulative ranges, so at most one fault fires per operation).
    double drop = 0.0;
    double delay = 0.0;
    double short_io = 0.0;
    double corrupt = 0.0;
    double reset = 0.0;
    // Accept/connect-time probability.
    double refuse = 0.0;
    // Zero-copy pool/ring seams (ISSUE 10d): descriptor-resolve crc
    // corruption and stale-epoch injection, leaked-pin simulation at
    // release, delayed/dropped staging-ring completes.
    double pool_corrupt = 0.0;
    double pool_stale = 0.0;
    double pool_leak = 0.0;
    double ring_delay = 0.0;
    double ring_drop = 0.0;
    // Work-priced admission seam (ISSUE 15): probability that a
    // completion's measured cost is inflated before feeding the QoS
    // cost model, and the multiplier applied.
    double cost_inflate = 0.0;
    // Server-push stream seam (ISSUE 17): stall a chunk send (slow
    // consumer sim) or drop it outright (the receiver's dup-ack NAK
    // recovers it from the replay ring).
    double stream_stall = 0.0;
    double stream_drop_chunk = 0.0;
    // One-sided verb plane (ISSUE 18): drop a posted verb in flight
    // (the pending-wr deadline reaps and retries) or ring the doorbell
    // late (CQ completion delivered after doorbell_delay_us).
    double verb_drop = 0.0;
    double doorbell_delay = 0.0;
    // Process crash (ISSUE 19): probability that a ticked decision kills
    // the process with a genuine SIGSEGV — the flight recorder's
    // fatal-signal black-box path is the thing under test.
    double crash = 0.0;
    // Grey-failure handler seam (ISSUE 20): inflate service time
    // (slow_node, param in MILLISECONDS — grey degradation lives on the
    // handler timescale, not the I/O one) and/or answer calls with a
    // synthetic retriable failure without running the handler
    // (error_rate). Connection health stays perfect either way.
    double slow_node = 0.0;
    double error_rate = 0.0;
    int64_t delay_us = 2000;
    int64_t ring_delay_us = 2000;
    int64_t cost_inflate_mult = 10;
    int64_t stream_stall_us = 5000;
    int64_t doorbell_delay_us = 2000;
    int64_t slow_node_us = 50000;  // param is ms; stored as us (50ms default)
    std::vector<EndPoint> peers;  // empty = every peer
    // Zone partition (ISSUE 14): all traffic to peers of this zone is
    // cut. Lives in the doubly-buffered plan so the hot path reads it
    // with the same scoped read as everything else.
    std::string partition_zone;
    // Snapshot of chaos_enabled at apply time: a partition set while
    // the probability plan is HEALED (enable=0, plan string kept for
    // replay inspection) must cut the zone WITHOUT resurrecting the
    // plan — g_chaos_on alone can no longer distinguish the two.
    bool plan_enabled = false;

    bool Matches(const EndPoint& peer) const {
        if (peers.empty()) return true;
        for (const EndPoint& p : peers) {
            if (p == peer) return true;
        }
        return false;
    }
};

// Peer -> zone registry feeding the partition check. Small (one entry
// per configured mesh peer), mutated rarely (startup / naming refresh),
// read only while chaos is enabled.
struct ZoneRegistry {
    std::mutex mu;
    std::map<EndPoint, std::string> zones;
};
ZoneRegistry& zone_registry() {
    static ZoneRegistry* z = new ZoneRegistry;  // immortal, like Engine
    return *z;
}

struct Engine {
    DoublyBufferedData<FaultPlan> plan;
    std::atomic<uint64_t> seed{1};
    std::atomic<uint64_t> seq{0};  // decision counter (determinism core)
    Adder<int64_t> injected[FaultAction::kKindCount];
    Adder<int64_t> ndecisions;
    Adder<int64_t> zone_cuts;  // whole-zone partition hits (ISSUE 14)

    Engine() {
        for (int k = FaultAction::kDelay; k < FaultAction::kKindCount; ++k) {
            injected[k].expose(std::string("chaos_injected_") +
                               kKindNames[k]);
        }
        ndecisions.expose("chaos_decisions");
        zone_cuts.expose("chaos_zone_partition_cuts");
    }
};

Engine& engine() {
    // Leaked singleton: seams may consult it during static teardown of
    // server objects (same immortality rule as the shm peer-pool
    // registry).
    static Engine* e = new Engine;
    return *e;
}

bool parse_double(const char* s, const char* end, double* out) {
    if (s == end) return false;  // empty probability ("drop=") rejects
    char* e = nullptr;
    *out = strtod(s, &e);
    return e == end && *out >= 0.0 && *out <= 1.0;
}

// "drop=0.01,delay=0.05:2000,short=0.1,corrupt=0.001,reset=0.01,refuse=0.1"
bool ParsePlan(const std::string& text, FaultPlan* plan) {
    size_t pos = 0;
    while (pos < text.size()) {
        size_t comma = text.find(',', pos);
        if (comma == std::string::npos) comma = text.size();
        const std::string entry = text.substr(pos, comma - pos);
        pos = comma + 1;
        if (entry.empty()) continue;
        const size_t eq = entry.find('=');
        if (eq == std::string::npos) return false;
        const std::string kind = entry.substr(0, eq);
        std::string value = entry.substr(eq + 1);
        std::string param_str;
        const size_t colon = value.find(':');
        if (colon != std::string::npos) {
            param_str = value.substr(colon + 1);
            value.resize(colon);
            if (param_str.empty()) return false;  // trailing ':'
        }
        double prob = 0.0;
        if (!parse_double(value.c_str(), value.c_str() + value.size(),
                          &prob)) {
            return false;
        }
        // Only the delay kinds (param = microseconds) and cost_inflate
        // (param = multiplier) take a :param; junk like "5ms" or a
        // param on another kind must REJECT, not silently half-apply
        // (the /chaos page promises validate-before-mutate).
        if (!param_str.empty() && kind != "delay" &&
            kind != "ring_delay" && kind != "cost_inflate" &&
            kind != "stream_stall" && kind != "doorbell_delay" &&
            kind != "slow_node") {
            return false;
        }
        const auto parse_us = [&](int64_t* out) {
            if (param_str.empty()) return true;
            char* end = nullptr;
            const long long us = strtoll(param_str.c_str(), &end, 10);
            if (end == param_str.c_str() || *end != '\0' || us <= 0) {
                return false;
            }
            *out = us;
            return true;
        };
        if (kind == "drop") {
            plan->drop = prob;
        } else if (kind == "delay") {
            plan->delay = prob;
            if (!parse_us(&plan->delay_us)) return false;
        } else if (kind == "short") {
            plan->short_io = prob;
        } else if (kind == "corrupt") {
            plan->corrupt = prob;
        } else if (kind == "reset") {
            plan->reset = prob;
        } else if (kind == "refuse") {
            plan->refuse = prob;
        } else if (kind == "pool_corrupt") {
            plan->pool_corrupt = prob;
        } else if (kind == "pool_stale") {
            plan->pool_stale = prob;
        } else if (kind == "pool_leak") {
            plan->pool_leak = prob;
        } else if (kind == "ring_delay") {
            plan->ring_delay = prob;
            if (!parse_us(&plan->ring_delay_us)) return false;
        } else if (kind == "ring_drop") {
            plan->ring_drop = prob;
        } else if (kind == "cost_inflate") {
            plan->cost_inflate = prob;
            if (!parse_us(&plan->cost_inflate_mult)) return false;
        } else if (kind == "stream_stall") {
            plan->stream_stall = prob;
            if (!parse_us(&plan->stream_stall_us)) return false;
        } else if (kind == "stream_drop_chunk") {
            plan->stream_drop_chunk = prob;
        } else if (kind == "verb_drop") {
            plan->verb_drop = prob;
        } else if (kind == "doorbell_delay") {
            plan->doorbell_delay = prob;
            if (!parse_us(&plan->doorbell_delay_us)) return false;
        } else if (kind == "crash") {
            plan->crash = prob;
        } else if (kind == "slow_node") {
            // Param is MILLISECONDS (handler timescale) — stored as us.
            plan->slow_node = prob;
            int64_t ms = 50;
            if (!parse_us(&ms)) return false;
            plan->slow_node_us = ms * 1000;
        } else if (kind == "error_rate") {
            plan->error_rate = prob;
        } else {
            return false;
        }
    }
    return true;
}

bool ParsePeers(const std::string& text, std::vector<EndPoint>* peers) {
    size_t pos = 0;
    while (pos < text.size()) {
        size_t comma = text.find(',', pos);
        if (comma == std::string::npos) comma = text.size();
        const std::string entry = text.substr(pos, comma - pos);
        pos = comma + 1;
        if (entry.empty()) continue;
        EndPoint ep;
        if (str2endpoint(entry.c_str(), &ep) != 0) return false;
        peers->push_back(ep);
    }
    return true;
}

// Install the on-change hooks AFTER the flags above are constructed
// (top-down order within this TU guarantees that).
struct HookInstaller {
    HookInstaller() {
        // Seed/plan changes start a fresh deterministic sequence (and
        // zero the counters for replay comparison); enable/peers edits
        // must NOT — healing with enable=0 keeps the run's counters
        // readable.
        FLAGS_chaos_enabled.set_on_change(&FaultInjection::Reconfigure);
        FLAGS_chaos_seed.set_on_change(
            &FaultInjection::ReconfigureAndReset);
        FLAGS_chaos_plan.set_on_change(
            &FaultInjection::ReconfigureAndReset);
        FLAGS_chaos_peers.set_on_change(&FaultInjection::Reconfigure);
        // Partition flips (set and heal) keep counters AND the plan's
        // deterministic sequence: a partition layers over a replay.
        FLAGS_chaos_partition_zone.set_on_change(
            &FaultInjection::Reconfigure);
    }
} g_hook_installer;

}  // namespace

bool FaultInjection::ValidatePlan(const std::string& plan) {
    FaultPlan scratch;
    return ParsePlan(plan, &scratch);
}

bool FaultInjection::ValidatePeers(const std::string& peers) {
    std::vector<EndPoint> scratch;
    return ParsePeers(peers, &scratch);
}

void FaultInjection::Reconfigure() {
    Engine& e = engine();
    FaultPlan parsed;
    if (!ParsePlan(FLAGS_chaos_plan.get(), &parsed)) {
        LOG(ERROR) << "chaos_plan unparsable: '" << FLAGS_chaos_plan.get()
                   << "'; fault injection disabled";
        fault_internal::g_chaos_on.store(false, std::memory_order_release);
        return;
    }
    if (!ParsePeers(FLAGS_chaos_peers.get(), &parsed.peers)) {
        LOG(ERROR) << "chaos_peers unparsable: '" << FLAGS_chaos_peers.get()
                   << "'; fault injection disabled";
        fault_internal::g_chaos_on.store(false, std::memory_order_release);
        return;
    }
    parsed.partition_zone = FLAGS_chaos_partition_zone.get();
    parsed.plan_enabled = FLAGS_chaos_enabled.get();
    e.plan.Modify([&](FaultPlan& p) {
        p = parsed;
        return true;
    });
    e.seed.store((uint64_t)FLAGS_chaos_seed.get(),
                 std::memory_order_release);
    // Enable LAST so no decision runs against a half-applied plan. A
    // zone partition keeps the seams consulting Decide even when the
    // probability plan is off.
    fault_internal::g_chaos_on.store(
        FLAGS_chaos_enabled.get() || !parsed.partition_zone.empty(),
        std::memory_order_release);
}

void FaultInjection::ReconfigureAndReset() {
    // Disable while swapping so no decision interleaves between the
    // counter reset and the re-apply (a tick against the old sequence
    // would break seed replay).
    fault_internal::g_chaos_on.store(false, std::memory_order_release);
    Engine& e = engine();
    // Quiesce in-flight Decide calls: each one holds a DoublyBufferedData
    // read scope for its whole body (including the seq tick), and Modify
    // serializes with every reader — after this no-op barrier, a fiber
    // that slipped past the enabled gate has finished its tick, so the
    // fresh sequence really does start at decision 0.
    e.plan.Modify([](FaultPlan&) { return true; });
    e.seq.store(0, std::memory_order_release);
    ResetCounters();
    Reconfigure();
}

// The crash action's wild store is DELIBERATE undefined behavior (the
// black-box dump must come from the fatal-signal handler, exactly as a
// production crash would) — keep sanitizers from turning it into a
// UBSan abort before the real SIGSEGV fires.
#if defined(__clang__) || defined(__GNUC__)
__attribute__((no_sanitize("undefined")))
#endif
static void CrashWithRealSegv() {
    *(volatile int*)0 = 0;
}

FaultAction FaultInjection::Decide(FaultOp op, const EndPoint& peer,
                                   size_t len) {
    FaultAction action;
    Engine& e = engine();
    DoublyBufferedData<FaultPlan>::ScopedPtr p;
    if (e.plan.Read(&p) != 0) return action;
    // Zone partition (ISSUE 14), checked BEFORE the probability plan
    // and WITHOUT consuming a decision tick: cutting a pod must not
    // shift a replayed seed's sequence. Applies to the byte/connection
    // seams only — the pool/ring seams are local-machine affairs.
    if (!p->partition_zone.empty() &&
        (op == FaultOp::kWrite || op == FaultOp::kRead ||
         op == FaultOp::kAccept || op == FaultOp::kConnect)) {
        ZoneRegistry& z = zone_registry();
        std::lock_guard<std::mutex> g(z.mu);
        auto it = z.zones.find(peer);
        if (it != z.zones.end() && it->second == p->partition_zone) {
            action.kind =
                (op == FaultOp::kAccept || op == FaultOp::kConnect)
                    ? FaultAction::kRefuse
                    : FaultAction::kReset;
            e.zone_cuts << 1;
            e.injected[action.kind] << 1;
            return action;
        }
    }
    // Partition-only mode (chaos_enabled=0 but a zone is cut): the
    // probability plan stays healed — and consumes no ticks, so the
    // replayed sequence resumes intact when re-enabled.
    if (!p->plan_enabled) return action;
    // Scope check BEFORE consuming a tick: unrelated traffic must not
    // shift the replayed sequence. The staging ring has NO peer (its
    // completions come from the local device stream), so a per-peer
    // plan must not silently disable ring_delay/ring_drop — ring
    // decisions bypass the filter. The verb plane is keyed by socket/
    // window ids, not endpoints (posts carry no EndPoint), so verb and
    // doorbell decisions bypass it too.
    // The handler seam bypasses it as well: the grey-node plan is
    // applied ON the degraded server, whose peers at dispatch time are
    // clients — not the targets a chaos_peers list names.
    if (op != FaultOp::kRingComplete && op != FaultOp::kVerbPost &&
        op != FaultOp::kCqComplete && op != FaultOp::kHandler &&
        !p->Matches(peer)) {
        return action;
    }
    const uint64_t n = e.seq.fetch_add(1, std::memory_order_relaxed);
    const uint64_t seed = e.seed.load(std::memory_order_relaxed);
    const uint64_t r = splitmix64(seed + n * 0x9e3779b97f4a7c15ull);
    const double u = to_unit(r);
    e.ndecisions << 1;
    // Flight-recorder stamp for chaos decisions: a = decision index, b
    // packs (seed_low32, op, action kind) so a seed replay aligns
    // decision-for-decision with the merged timeline.
    const auto chaos_stamp = [&](FaultAction::Kind kind) {
        flight::Record(flight::kChaosInject, n,
                       ((uint64_t)(uint32_t)seed << 32) |
                           ((uint64_t)(uint32_t)op << 8) | (uint64_t)kind);
    };
    if (p->crash > 0.0 && u < p->crash) {
        e.injected[FaultAction::kCrash] << 1;
        chaos_stamp(FaultAction::kCrash);
        // A real SIGSEGV, not exit(): the black-box dump must come from
        // the fatal-signal handler, exactly as a production crash would.
        CrashWithRealSegv();
    }
    if (op == FaultOp::kAccept || op == FaultOp::kConnect) {
        if (u < p->refuse) action.kind = FaultAction::kRefuse;
    } else if (op == FaultOp::kPoolResolve) {
        // Descriptor resolve: corrupt the crc verdict or inject a stale
        // pool epoch — both must fail ONLY the call (TERR_REQUEST /
        // TERR_STALE_EPOCH), never the connection. (No aux byte
        // position: the peer pool is mapped read-only, so "corrupt"
        // means the verdict, not the bytes.)
        double acc = 0.0;
        if (u < (acc += p->pool_corrupt)) {
            action.kind = FaultAction::kCorrupt;
        } else if (u < (acc += p->pool_stale)) {
            action.kind = FaultAction::kStaleEpoch;
        }
    } else if (op == FaultOp::kRingComplete) {
        double acc = 0.0;
        if (u < (acc += p->ring_drop)) {
            action.kind = FaultAction::kDrop;
        } else if (u < (acc += p->ring_delay)) {
            action.kind = FaultAction::kDelay;
            action.delay_us = p->ring_delay_us;
        }
    } else if (op == FaultOp::kLeaseRelease) {
        // Leaked-pin simulation: EndRPC "forgets" the release; the
        // expiry reaper must reclaim it (rpc_pool_reaped > 0).
        if (u < p->pool_leak) action.kind = FaultAction::kDrop;
    } else if (op == FaultOp::kCostMeasure) {
        // Cost inflation (ISSUE 15): the QoS cost model multiplies this
        // completion's measured cost by aux before the EWMA fold —
        // work-priced shedding without moving real bytes.
        if (u < p->cost_inflate) {
            action.kind = FaultAction::kInflate;
            action.aux = (uint64_t)p->cost_inflate_mult;
        }
    } else if (op == FaultOp::kStreamWrite) {
        // Server-push chunk send (ISSUE 17): a stalled send simulates a
        // slow consumer parking the writer; a dropped chunk stays in the
        // replay ring and must come back via the receiver's dup-ack
        // retransmit — both fail only the stream's timing, never the
        // connection.
        double acc = 0.0;
        if (u < (acc += p->stream_drop_chunk)) {
            action.kind = FaultAction::kDrop;
        } else if (u < (acc += p->stream_stall)) {
            action.kind = FaultAction::kDelay;
            action.delay_us = p->stream_stall_us;
        }
    } else if (op == FaultOp::kVerbPost) {
        // A dropped post vanishes in flight: no completion arrives, the
        // initiator's pending-wr deadline reaps and retries it — the
        // retransmit path the verbs soak proves.
        if (u < p->verb_drop) action.kind = FaultAction::kDrop;
    } else if (op == FaultOp::kCqComplete) {
        // The doorbell rings late: the completion is delivered after
        // the delay, parking CQ pollers (rpc_verbs_cq_parks climbs).
        if (u < p->doorbell_delay) {
            action.kind = FaultAction::kDelay;
            action.delay_us = p->doorbell_delay_us;
        }
    } else if (op == FaultOp::kHandler) {
        // Grey-failure dispatch seam (ISSUE 20). error_rate FIRST in the
        // cumulative draw: a soak's slow_node=1 (every call slow) must
        // not absorb the error slice — 'error_rate=0.05,slow_node=1:80'
        // means 5% fail, 95% slow, exactly as written.
        double acc = 0.0;
        if (u < (acc += p->error_rate)) {
            action.kind = FaultAction::kFail;
        } else if (u < (acc += p->slow_node)) {
            action.kind = FaultAction::kDelay;
            action.delay_us = p->slow_node_us;
        }
    } else {
        double acc = 0.0;
        if (u < (acc += p->drop)) {
            action.kind = FaultAction::kDrop;
        } else if (u < (acc += p->delay)) {
            action.kind = FaultAction::kDelay;
            action.delay_us = p->delay_us;
        } else if (u < (acc += p->short_io)) {
            action.kind = FaultAction::kShort;
            // Cap to a deterministic fraction of the operation (at least
            // one byte so progress invariants hold).
            const uint64_t r2 = splitmix64(r);
            action.max_bytes = len > 1 ? 1 + (size_t)(r2 % (len - 1)) : 1;
        } else if (u < (acc += p->corrupt)) {
            action.kind = FaultAction::kCorrupt;
            action.aux = splitmix64(r ^ 0xc0ffee);
        } else if (u < (acc += p->reset)) {
            action.kind = FaultAction::kReset;
        }
    }
    if (action.kind != FaultAction::kNone) {
        e.injected[action.kind] << 1;
        chaos_stamp(action.kind);
    }
    return action;
}

int64_t FaultInjection::injected_count(FaultAction::Kind k) {
    if (k <= FaultAction::kNone || k >= FaultAction::kKindCount) return 0;
    return engine().injected[k].get_value();
}

int64_t FaultInjection::decisions() { return engine().ndecisions.get_value(); }

void FaultInjection::SetPeerZone(const EndPoint& peer,
                                 const std::string& zone) {
    ZoneRegistry& z = zone_registry();
    std::lock_guard<std::mutex> g(z.mu);
    if (zone.empty()) {
        z.zones.erase(peer);
    } else {
        z.zones[peer] = zone;
    }
}

std::string FaultInjection::PeerZone(const EndPoint& peer) {
    ZoneRegistry& z = zone_registry();
    std::lock_guard<std::mutex> g(z.mu);
    auto it = z.zones.find(peer);
    return it != z.zones.end() ? it->second : "";
}

int64_t FaultInjection::zone_partition_cuts() {
    return engine().zone_cuts.get_value();
}

void FaultInjection::ResetCounters() {
    Engine& e = engine();
    for (int k = FaultAction::kDelay; k < FaultAction::kKindCount; ++k) {
        e.injected[k].reset();
    }
    e.ndecisions.reset();
    e.zone_cuts.reset();
}

std::string FaultInjection::DebugString() {
    Engine& e = engine();
    std::string out;
    char line[256];
    snprintf(line, sizeof(line), "enabled %d\n",
             fault_injection_enabled() ? 1 : 0);
    out += line;
    snprintf(line, sizeof(line), "seed %lld\n",
             (long long)e.seed.load(std::memory_order_relaxed));
    out += line;
    out += "plan " + FLAGS_chaos_plan.get() + "\n";
    out += "peers " + FLAGS_chaos_peers.get() + "\n";
    out += "partition_zone " + FLAGS_chaos_partition_zone.get() + "\n";
    snprintf(line, sizeof(line), "zone_partition_cuts %lld\n",
             (long long)engine().zone_cuts.get_value());
    out += line;
    snprintf(line, sizeof(line), "decisions %lld\n",
             (long long)e.ndecisions.get_value());
    out += line;
    for (int k = FaultAction::kDelay; k < FaultAction::kKindCount; ++k) {
        snprintf(line, sizeof(line), "injected_%s %lld\n", kKindNames[k],
                 (long long)e.injected[k].get_value());
        out += line;
    }
    return out;
}

}  // namespace tpurpc
