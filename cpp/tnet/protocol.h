// Protocol: the function-pointer table every wire protocol implements, plus
// the global registry.
//
// Modeled on reference src/brpc/protocol.h:77-172 (struct Protocol {parse,
// serialize_request, pack_request, process_request, process_response,
// verify}) and RegisterProtocol/FindProtocol (protocol.h:186-193). The
// InputMessenger sniffs protocols per connection and remembers the winner
// (socket->preferred_protocol_index).
#pragma once

#include <cstdint>
#include <functional>
#include <string>

#include "tbase/iobuf.h"

namespace tpurpc {

class Socket;
class InputMessageBase;

enum class ParseError {
    OK = 0,
    NOT_ENOUGH_DATA,  // keep bytes, wait for more
    TRY_OTHERS,       // not this protocol; let another parser sniff
    ERROR,            // corrupt stream: fail the connection
};

struct ParseResult {
    ParseError error = ParseError::TRY_OTHERS;
    InputMessageBase* msg = nullptr;

    static ParseResult make_ok(InputMessageBase* m) {
        return ParseResult{ParseError::OK, m};
    }
    static ParseResult make(ParseError e) { return ParseResult{e, nullptr}; }
};

// Base of every cut message flowing from parse() to process().
class InputMessageBase {
public:
    virtual ~InputMessageBase() = default;
    // Socket the message arrived on (id; Address() to use).
    uint64_t socket_id = 0;
    int protocol_index = -1;
};

struct Protocol {
    // Cut one message from `source` (bytes already read from the socket).
    ParseResult (*parse)(IOBuf* source, Socket* socket, bool read_eof,
                         const void* arg) = nullptr;
    // Handle a cut message (request on servers, response on clients). Runs
    // on a fiber. Owns `msg` (must delete).
    void (*process)(InputMessageBase* msg) = nullptr;
    // Human name (diagnostics + /connections).
    const char* name = "unknown";
    // Opaque arg passed to parse (e.g. the Server*).
    const void* parse_arg = nullptr;
    // Process every message inline on the input fiber, in cut order.
    // Required by protocols without correlation ids (HTTP): spawning
    // earlier burst messages onto fibers would let responses overtake
    // each other on one connection.
    bool process_in_order = false;
};

// Global registry (reference global.cpp:416-601 registers all protocols at
// init). Index is stable after registration.
int RegisterProtocol(const Protocol& p);
const Protocol* GetProtocol(int index);
int ProtocolCount();

}  // namespace tpurpc
