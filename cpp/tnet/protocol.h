// Protocol: the function-pointer table every wire protocol implements, plus
// the global registry.
//
// Modeled on reference src/brpc/protocol.h:77-172 (struct Protocol {parse,
// serialize_request, pack_request, process_request, process_response,
// verify}) and RegisterProtocol/FindProtocol (protocol.h:186-193). The
// InputMessenger sniffs protocols per connection and remembers the winner
// (socket->preferred_protocol_index).
#pragma once

#include <cstdint>
#include <functional>
#include <string>

#include "tbase/iobuf.h"

namespace tpurpc {

class Socket;
class InputMessageBase;

enum class ParseError {
    OK = 0,
    NOT_ENOUGH_DATA,  // keep bytes, wait for more
    TRY_OTHERS,       // not this protocol; let another parser sniff
    ERROR,            // corrupt stream: fail the connection
};

struct ParseResult {
    ParseError error = ParseError::TRY_OTHERS;
    InputMessageBase* msg = nullptr;

    static ParseResult make_ok(InputMessageBase* m) {
        return ParseResult{ParseError::OK, m};
    }
    static ParseResult make(ParseError e) { return ParseResult{e, nullptr}; }
};

// Base of every cut message flowing from parse() to process().
class InputMessageBase {
public:
    virtual ~InputMessageBase() = default;
    // Socket the message arrived on (id; Address() to use).
    uint64_t socket_id = 0;
    int protocol_index = -1;
    // Wire size of this message (header + body), set by parse(). The
    // run-to-completion dispatcher uses it as the "small message" gate
    // (-inline_dispatch_max_bytes); 0 = unknown (never inlined).
    size_t byte_size = 0;
};

struct Protocol {
    // Cut one message from `source` (bytes already read from the socket).
    ParseResult (*parse)(IOBuf* source, Socket* socket, bool read_eof,
                         const void* arg) = nullptr;
    // Handle a cut message (request on servers, response on clients). Runs
    // on a fiber. Owns `msg` (must delete).
    void (*process)(InputMessageBase* msg) = nullptr;
    // Human name (diagnostics + /connections).
    const char* name = "unknown";
    // Opaque arg passed to parse (e.g. the Server*).
    const void* parse_arg = nullptr;
    // Process every message inline on the input fiber, in cut order.
    // Required by protocols without correlation ids (HTTP): spawning
    // earlier burst messages onto fibers would let responses overtake
    // each other on one connection.
    bool process_in_order = false;
    // Run-to-completion hint (ISSUE 7): process() is cheap and does not
    // block, so small messages may run inline on the input fiber instead
    // of spawning a processing fiber — subject to the per-wake
    // -inline_dispatch_budget (input_messenger.h). Server-side handlers
    // additionally gate on their method's inline-safe flag
    // (Server::SetMethodInlineSafe).
    bool inline_safe = false;

    // ---- zero-cut parse fast path (optional, ISSUE 7) ----
    // Fixed header length `peek` wants to inspect; 0 disables the fast
    // path for this protocol.
    uint32_t peek_len = 0;
    // Classify a sticky connection's next frame from its first peek_len
    // contiguous bytes WITHOUT consuming anything. Returns the total
    // frame size in bytes (>= peek_len; the messenger then waits for the
    // whole frame and calls parse exactly once), 0 when the header is
    // not this protocol's (re-sniff / TRY_OTHERS), or -1 when the header
    // is corrupt (fail the connection). Skips the cutn + re-parse loop
    // the slow path pays on every partial read.
    int64_t (*peek)(const char* hdr, Socket* socket) = nullptr;
};

// Global registry (reference global.cpp:416-601 registers all protocols at
// init). Index is stable after registration.
int RegisterProtocol(const Protocol& p);
const Protocol* GetProtocol(int index);
int ProtocolCount();

}  // namespace tpurpc
