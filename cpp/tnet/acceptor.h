// Acceptor: the listen-socket loop creating per-connection Sockets bound to
// a messenger. Modeled on reference src/brpc/acceptor.{h,cpp} (accept() as
// an InputMessenger subclass; per-connection Socket::Create; Join() waits
// for every accepted connection's Socket to be *recycled* before returning
// so no in-flight event fiber can outlive the owning Server).
#pragma once

#include <atomic>
#include <mutex>
#include <set>
#include <vector>

#include "tbase/endpoint.h"
#include "tfiber/butex.h"
#include "tnet/input_messenger.h"
#include "tnet/socket.h"

namespace tpurpc {

class Acceptor {
public:
    explicit Acceptor(InputMessenger* messenger) : messenger_(messenger) {
        quiesce_butex_ = butex_create();
    }
    ~Acceptor() {
        StopAccept();
        butex_destroy(quiesce_butex_);
    }

    // Listen on `ep` (port 0 picks one; see listened_port()). Returns 0.
    int StartAccept(const EndPoint& ep);
    // Stops listening, fails all accepted connections, then BLOCKS until
    // the listen socket and every accepted socket have been recycled —
    // i.e. no event/processing fiber still holds a pointer into this
    // Acceptor or its messenger/Server. Without this wait, destroying a
    // Server races in-flight fibers (the reference's Acceptor::Join,
    // acceptor.cpp, exists for exactly this).
    void StopAccept();
    int listened_port() const { return listened_port_; }

    // Graceful-drain accept gate: stop ACCEPTING without closing the
    // listening fd — the port stays bound (no thundering re-bind race on
    // restart) and TCP handshakes still land in the kernel backlog, so a
    // connect-probe health check keeps passing while the process drains.
    // Resume kicks the accept loop once so backlogged connections queued
    // while paused are picked up (edge-triggered epoll would otherwise
    // strand them until the NEXT connection arrives).
    void PauseAccept() { paused_.store(true, std::memory_order_release); }
    void ResumeAccept();
    bool accept_paused() const {
        return paused_.load(std::memory_order_acquire);
    }

    // # connections accepted (metrics / tests).
    int64_t accepted_count() const {
        return accepted_.load(std::memory_order_relaxed);
    }

    // Wrap every accepted connection in a server-side TLS transport
    // (requires TlsServerInit first; set before StartAccept).
    void set_tls(bool on) { tls_ = on; }
    // Live accepted connections (for /connections).
    std::vector<SocketId> connections();

private:
    static void OnNewConnections(Socket* listen_socket);
    static void ConnRecycled(void* arg, SocketId id);
    static void ListenRecycled(void* arg, SocketId id);

    InputMessenger* messenger_;
    SocketId listen_id_ = INVALID_VREF_ID;
    int listened_port_ = 0;
    std::atomic<int64_t> accepted_{0};
    std::mutex conn_mu_;
    std::set<SocketId> conn_ids_;
    // Quiesce accounting: +1 per accepted socket (before Create), -1 from
    // the recycle callback; listen_live_ covers the listen socket itself.
    std::atomic<int64_t> live_conns_{0};
    std::atomic<bool> listen_live_{false};
    std::atomic<bool> paused_{false};
    bool tls_ = false;
    void* quiesce_butex_ = nullptr;
};

}  // namespace tpurpc
