// Acceptor: the listen-socket loop creating per-connection Sockets bound to
// a messenger. Modeled on reference src/brpc/acceptor.{h,cpp} (accept() as
// an InputMessenger subclass; per-connection Socket::Create).
#pragma once

#include <atomic>

#include "tbase/endpoint.h"
#include "tnet/input_messenger.h"
#include "tnet/socket.h"

namespace tpurpc {

class Acceptor {
public:
    explicit Acceptor(InputMessenger* messenger) : messenger_(messenger) {}
    ~Acceptor() { StopAccept(); }

    // Listen on `ep` (port 0 picks one; see listened_port()). Returns 0.
    int StartAccept(const EndPoint& ep);
    void StopAccept();
    int listened_port() const { return listened_port_; }

    // # connections accepted (metrics / tests).
    int64_t accepted_count() const {
        return accepted_.load(std::memory_order_relaxed);
    }

private:
    static void OnNewConnections(Socket* listen_socket);

    InputMessenger* messenger_;
    SocketId listen_id_ = INVALID_VREF_ID;
    int listened_port_ = 0;
    std::atomic<int64_t> accepted_{0};
};

}  // namespace tpurpc
