// Acceptor: the listen-socket loop creating per-connection Sockets bound to
// a messenger. Modeled on reference src/brpc/acceptor.{h,cpp} (accept() as
// an InputMessenger subclass; per-connection Socket::Create).
#pragma once

#include <atomic>
#include <mutex>
#include <set>
#include <vector>

#include "tbase/endpoint.h"
#include "tnet/input_messenger.h"
#include "tnet/socket.h"

namespace tpurpc {

class Acceptor {
public:
    explicit Acceptor(InputMessenger* messenger) : messenger_(messenger) {}
    ~Acceptor() { StopAccept(); }

    // Listen on `ep` (port 0 picks one; see listened_port()). Returns 0.
    int StartAccept(const EndPoint& ep);
    // Stops listening AND fails all accepted connections — their sockets
    // hold pointers into the owning server, which may be destroyed next
    // (reference Acceptor keeps the connection list for the same reason,
    // acceptor.h + /connections).
    void StopAccept();
    int listened_port() const { return listened_port_; }

    // # connections accepted (metrics / tests).
    int64_t accepted_count() const {
        return accepted_.load(std::memory_order_relaxed);
    }
    // Live accepted connections (for /connections later).
    std::vector<SocketId> connections();

private:
    static void OnNewConnections(Socket* listen_socket);
    void record_connection(SocketId id);

    InputMessenger* messenger_;
    SocketId listen_id_ = INVALID_VREF_ID;
    int listened_port_ = 0;
    std::atomic<int64_t> accepted_{0};
    std::mutex conn_mu_;
    std::set<SocketId> conn_ids_;
};

}  // namespace tpurpc
