#include "tnet/protocol.h"

#include <mutex>
#include <vector>

namespace tpurpc {

namespace {
struct Registry {
    std::mutex mu;
    std::vector<Protocol> protocols;
};
Registry* registry() {
    static Registry* r = [] {
        auto* rr = new Registry;
        // Pointers returned by GetProtocol must stay stable.
        rr->protocols.reserve(64);
        return rr;
    }();
    return r;
}
}  // namespace

int RegisterProtocol(const Protocol& p) {
    Registry* r = registry();
    std::lock_guard<std::mutex> g(r->mu);
    r->protocols.push_back(p);
    return (int)r->protocols.size() - 1;
}

const Protocol* GetProtocol(int index) {
    Registry* r = registry();
    std::lock_guard<std::mutex> g(r->mu);
    if (index < 0 || index >= (int)r->protocols.size()) return nullptr;
    return &r->protocols[(size_t)index];
}

int ProtocolCount() {
    Registry* r = registry();
    std::lock_guard<std::mutex> g(r->mu);
    return (int)r->protocols.size();
}

}  // namespace tpurpc
