// Socket: the central connection object — versioned-id addressed, wait-free
// write queue, edge-triggered read dispatch.
//
// Modeled on reference src/brpc/socket.h:294 / socket.cpp:
//  - SocketId addressing + SetFailed/recycle via VersionedRefWithId
//  - write path: wait-free MPSC stack `_write_head` (socket.cpp:488,1695),
//    first writer writes inline once (socket.cpp:1615), leftovers go to a
//    KeepWrite fiber (socket.cpp:1800) batching via DoWrite (:1920);
//    back-pressure via EOVERCROWDED
//  - read path: OnInputEvent's atomic `_nevent` starts exactly one
//    processing fiber per readiness burst (socket.cpp:2229,2256)
//  - connect-on-first-write (ConnectIfNot socket.cpp:1409)
// The transport is pluggable: a TransportEndpoint (ICI/shm, see
// tnet/transport.h) can take over the data plane while this Socket keeps
// the id/lifecycle/queue semantics — the RdmaEndpoint pattern
// (reference src/brpc/rdma/rdma_endpoint.h).
#pragma once

#include <atomic>
#include <cstdint>
#include <deque>
#include <mutex>
#include <string>
#include <vector>

#include "tbase/endpoint.h"
#include "tbase/iobuf.h"
#include "tbase/time.h"
#include "tbase/versioned_ref.h"
#include "tnet/circuit_breaker.h"
#include "tnet/transport.h"
#include "tfiber/butex.h"
#include "tfiber/fiber.h"

namespace tpurpc {

class Socket;
using SocketId = VRefId;
using SocketUniquePtr = VRefPtr<Socket>;

struct SocketOptions {
    int fd = -1;  // may be -1: connect-on-first-write to remote_side
    EndPoint remote_side;
    // Edge-triggered readable callback (InputMessenger::OnNewMessages or
    // Acceptor::OnNewConnections). Runs on a fiber.
    void (*on_edge_triggered_events)(Socket*) = nullptr;
    void* user = nullptr;  // InputMessenger* / Acceptor* / Server*
    // Optional transport endpoint taking over the data plane (ICI).
    TransportEndpoint* transport = nullptr;
    // Registry tier of a plain-fd connection when it is NOT the default
    // tcp tier (ISSUE 14): a cross-pod peer's socket is created with
    // TierDcn() so descriptor eligibility, byte attribution and the
    // -dcn_emu_* shaping all key off the tier without a second data
    // plane. Ignored when `transport` is set (the endpoint knows its
    // own tier). -1 = default (tcp).
    int forced_transport_tier = -1;
    // If set, the socket Release()s the endpoint at recycle time (the
    // link frees itself once both sides' sockets are gone).
    bool owns_transport = false;
    // >0: on SetFailed, keep probing the remote every this-many ms and
    // Revive the SAME SocketId on success (reference
    // src/brpc/details/health_check.cpp — ids held by load balancers stay
    // valid across failures). 0 disables.
    int health_check_interval_ms = 0;
    // Client-side TLS: after connect, wrap the fd in a TLS transport
    // (tnet/tls.h) negotiating `tls_alpn` (e.g. "h2") with SNI
    // `tls_sni`. Requires libssl at runtime.
    bool tls = false;
    std::string tls_alpn;
    std::string tls_sni;
    // Invoked exactly once when the socket's last ref drops and the slot
    // recycles (reference SocketUser::BeforeRecycled). This is how an
    // Acceptor learns no event/processing fiber can still be touching a
    // connection — the quiesce signal Server teardown waits on. Must be
    // cheap and lock-light (runs on whatever fiber dropped the last ref).
    // Guarantee: if set, it fires even when Create() itself fails.
    void (*on_recycle)(void* arg, SocketId id) = nullptr;
    void* recycle_arg = nullptr;
};

class Socket : public VersionedRefWithId<Socket> {
public:
    // ---- creation / addressing ----
    static int Create(const SocketOptions& options, SocketId* id);
    static int AddressSocket(SocketId id, SocketUniquePtr* out) {
        out->reset();
        Socket* s = Address(id);
        if (s == nullptr) return -1;
        *out = SocketUniquePtr(s);
        return 0;
    }

    SocketId id() const { return vref_id(); }
    int fd() const { return fd_.load(std::memory_order_acquire); }
    const EndPoint& remote_side() const { return remote_side_; }
    const EndPoint& local_side() const { return local_side_; }
    void* user() const { return user_; }

    // ---- write path ----
    // Queue `data` (zero-copy moved) for ordered write. Returns 0, or -1
    // with errno (EOVERCROWDED when the unwritten backlog is too large,
    // or the socket is failed). Never blocks. `notify_id` (a CallId value)
    // is error-notified if the request is dropped by a write failure —
    // how in-flight RPCs learn their connection died (the reference passes
    // Controller ids through WriteRequest, socket.cpp Write w/ id_wait).
    int Write(IOBuf* data, uint64_t notify_id = 0);

    // ---- read path (called by EventDispatcher) ----
    static void OnInputEventById(SocketId id);
    static void OnOutputEventById(SocketId id);

    // ---- connect ----
    // Ensure connected (used by client sockets created with fd == -1);
    // blocks the calling fiber until connected or error. Returns 0 / -1.
    int ConnectIfNot();

    // ---- failure / health check ----
    int SetFailedWithError(int error_code);
    int error_code() const { return error_code_.load(std::memory_order_acquire); }
    // Process-wide failure observer, invoked once per socket from
    // OnFailed (the winning SetFailed). Lets upper layers react to
    // connection death without tnet depending on them (the RPC layer
    // cancels in-flight server calls here). The observer may run under
    // arbitrary locks — it must not run user code inline.
    using FailureObserver = void (*)(SocketId);
    static void set_failure_observer(FailureObserver ob);
    // Process-wide revive observer, invoked from ReviveAfterHealthCheck
    // after the socket is usable again (draining cleared, breaker reset).
    // Lets the outlier tier re-enter a revived-but-previously-ejected
    // backend through its probe ramp instead of at full weight: the
    // health probe only proves the process answers, not that it is fast.
    using ReviveObserver = void (*)(SocketId);
    static void set_revive_observer(ReviveObserver ob);
    // Stop the revive loop (set when the naming layer removes this server
    // for good; the health-check fiber then drops its ref and the socket
    // recycles).
    void StopHealthCheck() {
        hc_stop_.store(true, std::memory_order_release);
    }
    int health_check_interval_ms() const { return health_check_interval_ms_; }
    // Per-connection breaker (reference keeps one per Socket too); fed by
    // the client stack after each call, isolation = SetFailed + revive.
    CircuitBreaker& circuit_breaker() { return circuit_breaker_; }

    // ---- draining (zero-downtime lifecycle) ----
    // The peer announced a planned shutdown (tpu_std GOAWAY meta / h2
    // GOAWAY): the connection stays LIVE — in-flight calls complete
    // normally — but new calls must steer away (load balancers skip
    // draining nodes; pinned channels re-create their connection).
    // Cleared on slot reuse (Create) and on health-check revive: the
    // restarted process serves anew.
    void SetDraining() { draining_.store(true, std::memory_order_release); }
    bool Draining() const {
        return draining_.load(std::memory_order_acquire);
    }

    // Plugged data-plane transport (ICI), or null for the fd path.
    TransportEndpoint* transport() const { return transport_; }
    // The registry tier of this connection's data plane (tnet/transport.h):
    // TierTcp() for the plain-fd/TLS path, the endpoint's own tier
    // otherwise. Descriptor eligibility, credit accounting, and byte
    // attribution key off this — one seam, no per-transport special
    // cases.
    int transport_tier() const {
        if (transport_ != nullptr) return transport_->tier();
        return forced_tier_ >= 0 ? forced_tier_ : TierTcp();
    }
    // The raw SocketOptions::forced_transport_tier this socket was
    // created with (-1 = default tcp): the (endpoint, tier) key half the
    // SocketMap/SocketPool registries re-derive at Return/Remove time.
    int forced_transport_tier() const { return forced_tier_; }
    // Upgrade a live connection to a transport data plane (server side of
    // the ICI handshake). Must be called from the socket's input fiber
    // with no concurrent writers — i.e. before the peer can have sent any
    // post-handshake request (the handshake protocol guarantees this).
    // The socket takes ownership (Release()d at recycle).
    void InstallTransport(TransportEndpoint* t) {
        transport_ = t;
        owns_transport_ = true;
    }

    // ---- per-connection parsing state (owned by InputMessenger) ----
    IOPortal read_buf;
    int preferred_protocol_index = -1;
    // Zero-cut fast path (Protocol::peek): total bytes of the frame the
    // peeked header announced, 0 when no peek is outstanding. While set,
    // the messenger skips parse entirely until the whole frame arrived —
    // no re-peek, no re-parse per partial read. Input-fiber-only.
    int64_t pending_frame_bytes = 0;
    // Protocol-private per-connection state (e.g. the HTTP/2 session:
    // HPACK context + stream table). Owned by the socket once set; the
    // deleter runs at recycle. Set from the input fiber only.
    void set_conn_data(void* data, void (*deleter)(void*)) {
        conn_data_ = data;
        conn_data_deleter_ = deleter;
    }
    void* conn_data() const { return conn_data_; }

    // ---- pipelined-response correlation ----
    // For protocols without correlation ids on the wire (redis,
    // memcache): each sender pushes {expected reply count, its CallId}
    // BEFORE writing, in write order; the response parser pops FIFO to
    // know whose replies it is reading (reference socket.h:532
    // PushPipelinedInfo / PopPipelinedInfo / GivebackPipelinedInfo).
    struct PipelinedInfo {
        uint32_t count = 0;    // replies this request expects
        uint64_t id_wait = 0;  // CallId to complete
    };
    void PushPipelinedInfo(const PipelinedInfo& pi) {
        std::lock_guard<std::mutex> g(pipeline_mu_);
        pipeline_q_.push_back(pi);
    }
    bool PopPipelinedInfo(PipelinedInfo* pi) {
        std::lock_guard<std::mutex> g(pipeline_mu_);
        if (pipeline_q_.empty()) return false;
        *pi = pipeline_q_.front();
        pipeline_q_.pop_front();
        return true;
    }
    // ---- auth fight (reference socket.h:515 FightAuthentication) ----
    // First caller on a fresh connection wins the right to attach the
    // credential; everyone else waits for its outcome. States: 0 none,
    // 1 in progress (one writer is authenticating), 2 done.
    // Returns: 0 = caller must attach the credential, 1 = already done.
    int FightAuthentication() {
        int expect = 0;
        if (auth_state_.compare_exchange_strong(
                expect, 1, std::memory_order_acq_rel)) {
            return 0;
        }
        return 1;
    }
    // Park until the in-flight authentication RESOLVES: done (state 2),
    // or aborted back to none (state 0 — the caller should re-fight).
    // Returns 0 on resolution, -1 on socket failure or timeout.
    int WaitAuthenticated(int64_t abstime_us);
    // The fight winner's call died without a processed response
    // (credential generation failed, timeout, retry): release the fight
    // so another caller can authenticate — otherwise the shared
    // connection wedges with every later call parked behind state 1.
    // No-op unless authentication is still in progress.
    void AbortAuthentication() {
        int expect = 1;
        if (auth_state_.compare_exchange_strong(
                expect, 0, std::memory_order_acq_rel)) {
            butex_word(auth_butex_)->fetch_add(1,
                                               std::memory_order_release);
            butex_wake_all(auth_butex_);
        }
    }
    // The authenticating call's response arrived: connection is trusted.
    // Exactly one caller transitions (via the transient publishing state
    // 3) and writes the user; races (e.g. two client response fibers)
    // collapse to the first winner.
    void SetAuthenticated(const std::string& user) {
        for (int from : {1, 0}) {
            int expect = from;
            if (auth_state_.compare_exchange_strong(
                    expect, 3, std::memory_order_acq_rel)) {
                auth_user_ = user;
                auth_state_.store(2, std::memory_order_release);
                butex_word(auth_butex_)->fetch_add(
                    1, std::memory_order_release);
                butex_wake_all(auth_butex_);
                return;
            }
        }
    }
    bool authenticated() const {
        return auth_state_.load(std::memory_order_acquire) == 2;
    }
    // Server side: the verified peer identity ("" before verification).
    const std::string& auth_user() const { return auth_user_; }

    // Un-push after a failed write (the entry must not shift correlation
    // for later callers). True if it was still queued.
    bool RemovePipelinedInfo(uint64_t id_wait) {
        std::lock_guard<std::mutex> g(pipeline_mu_);
        for (auto it = pipeline_q_.begin(); it != pipeline_q_.end(); ++it) {
            if (it->id_wait == id_wait) {
                pipeline_q_.erase(it);
                return true;
            }
        }
        return false;
    }
    // Fail every queued pipelined call (connection died) and clear.
    std::vector<PipelinedInfo> ResetPipelinedInfo() {
        std::lock_guard<std::mutex> g(pipeline_mu_);
        std::vector<PipelinedInfo> out(pipeline_q_.begin(),
                                       pipeline_q_.end());
        pipeline_q_.clear();
        return out;
    }

    // Bytes queued but not yet written (back-pressure signal).
    int64_t unwritten_bytes() const {
        return unwritten_bytes_.load(std::memory_order_relaxed);
    }

    // ---- per-socket stats (reference socket.h:127 SocketStat) ----
    void add_bytes_read(int64_t n) {
        bytes_read_.fetch_add(n, std::memory_order_relaxed);
        last_active_us_.store(monotonic_time_us(),
                              std::memory_order_relaxed);
    }
    void add_bytes_written(int64_t n) {
        bytes_written_.fetch_add(n, std::memory_order_relaxed);
        last_active_us_.store(monotonic_time_us(),
                              std::memory_order_relaxed);
    }
    int64_t bytes_read() const {
        return bytes_read_.load(std::memory_order_relaxed);
    }
    int64_t bytes_written() const {
        return bytes_written_.load(std::memory_order_relaxed);
    }
    // One-sided descriptor attribution (ISSUE 9): logical payload bytes
    // this connection delivered by REFERENCE (pool descriptors resolved
    // against a mapped peer pool) — they never crossed the fd/ring, so
    // bytes_read misses them, but they ARE this connection's data-plane
    // throughput. /connections adds them to the in-rate so the device
    // seam's GB/s is visible per connection.
    void add_descriptor_bytes_read(int64_t n) {
        descriptor_bytes_read_.fetch_add(n, std::memory_order_relaxed);
    }
    int64_t descriptor_bytes_read() const {
        return descriptor_bytes_read_.load(std::memory_order_relaxed);
    }
    // The ONE peer pool this connection's ICI handshake mapped (0 =
    // none). Descriptor resolution is bound to it: a request on this
    // connection may only reference the pool its handshake registered
    // (or, on an in-process link, this process's own pool) — a global
    // registry hit alone must never be enough, or any connection could
    // read any mapped tenant's pool memory.
    void set_peer_pool_id(uint64_t id) {
        peer_pool_id_.store(id, std::memory_order_relaxed);
    }
    uint64_t peer_pool_id() const {
        return peer_pool_id_.load(std::memory_order_relaxed);
    }
    int64_t created_us() const { return created_us_; }
    int64_t last_active_us() const {
        return last_active_us_.load(std::memory_order_relaxed);
    }

    // ---- per-connection I/O attribution (ISSUE 6; /connections) ----
    int64_t write_batches() const {
        return nwrite_batches_.load(std::memory_order_relaxed);
    }
    int64_t max_write_batch_bytes() const {
        return max_write_batch_.load(std::memory_order_relaxed);
    }
    int64_t queued_write_highwater() const {
        return queued_highwater_.load(std::memory_order_relaxed);
    }
    int64_t overcrowded_incidents() const {
        return novercrowded_.load(std::memory_order_relaxed);
    }
    // In/out bytes-per-second since the PREVIOUS call (or since creation
    // on the first): /connections computes scrape-to-scrape rates with
    // no per-socket sampler thread. Concurrent scrapes race benignly
    // (one of them sees a shorter window).
    struct IoRates {
        double in_bps = 0;
        double out_bps = 0;
    };
    IoRates ScrapeIoRates(int64_t now_us) {
        // Logical in-bytes: fd/ring bytes PLUS descriptor-referenced
        // bytes delivered in place (ISSUE 9) — the connection's true
        // data-plane rate.
        const int64_t in = bytes_read() + descriptor_bytes_read();
        const int64_t out = bytes_written();
        const int64_t prev_us = rate_scrape_us_.exchange(
            now_us, std::memory_order_relaxed);
        const int64_t prev_in =
            rate_scrape_in_.exchange(in, std::memory_order_relaxed);
        const int64_t prev_out =
            rate_scrape_out_.exchange(out, std::memory_order_relaxed);
        const int64_t base_us = prev_us != 0 ? prev_us : created_us_;
        const double dt = (double)(now_us - base_us) / 1e6;
        IoRates r;
        if (dt > 0) {
            r.in_bps = (double)(in - (prev_us != 0 ? prev_in : 0)) / dt;
            r.out_bps = (double)(out - (prev_us != 0 ? prev_out : 0)) / dt;
            if (r.in_bps < 0) r.in_bps = 0;    // slot-reuse race
            if (r.out_bps < 0) r.out_bps = 0;
        }
        return r;
    }

    // VersionedRefWithId hooks.
    void OnFailed();
    void OnRecycle();

private:
    friend class VersionedRefWithId<Socket>;
    friend class EventDispatcher;
    friend class WriteCoalesceScope;

    struct WriteRequest {
        std::atomic<WriteRequest*> next{nullptr};
        IOBuf data;
        uint64_t notify_id = 0;
        static WriteRequest* unlinked() { return (WriteRequest*)0x1; }
    };

    static void DropWriteRequest(WriteRequest* req);
    void CloseFdAndDropQueued();
    static void* HealthCheckThunk(void* arg);  // arg = Socket* (ref held)
    void HealthCheckLoop();
    // Reset connection state and un-fail (health-check fiber only, with
    // every other ref gone so no writer/reader is concurrent).
    int ReviveAfterHealthCheck();
    void StartKeepWriteIfNeeded();
    static void* KeepWriteThunk(void* arg);  // arg = SocketId
    void KeepWrite();
    // Drain pending write requests once; returns false on fatal error.
    bool FlushOnce(bool allow_block);
    // Drop every queued write request, error-notifying their CallIds. Only
    // the elected writer may call this (owns batch state). Needed at
    // failure time: recycle-time cleanup is too late for health-checked
    // sockets whose slot stays pinned while failed.
    void DrainWriteQueue();
    // Wait (fiber) until the fd is writable.
    int WaitEpollOut();
    static void* ProcessEventThunk(void* arg);  // arg = SocketId

    std::atomic<int> fd_{-1};
    EndPoint remote_side_;
    EndPoint local_side_;
    void (*on_edge_triggered_events_)(Socket*) = nullptr;
    void* user_ = nullptr;
    TransportEndpoint* transport_ = nullptr;
    bool owns_transport_ = false;
    int forced_tier_ = -1;  // SocketOptions::forced_transport_tier

    std::atomic<WriteRequest*> write_head_{nullptr};
    std::atomic<int64_t> write_pending_{0};
    std::atomic<int64_t> unwritten_bytes_{0};
    // In-progress batch owned by the single active writer. writer_consumed_
    // counts fully-written requests not yet subtracted from write_pending_;
    // it must survive the inline-flush -> KeepWrite handoff or the writer
    // election count drifts and the queue wedges.
    std::vector<WriteRequest*> inflight_batch_;
    size_t inflight_index_ = 0;
    int64_t writer_consumed_ = 0;

    std::atomic<int> nevent_{0};
    void* epollout_butex_ = nullptr;
    std::atomic<int> error_code_{0};
    std::atomic<bool> connecting_{false};
    void* connect_butex_ = nullptr;
    void* auth_butex_ = nullptr;
    std::atomic<int> auth_state_{0};
    std::string auth_user_;
    int health_check_interval_ms_ = 0;
    bool tls_ = false;
    std::string tls_alpn_;
    std::string tls_sni_;
    std::atomic<bool> hc_stop_{false};
    std::atomic<bool> draining_{false};
    CircuitBreaker circuit_breaker_;
    void (*on_recycle_)(void*, SocketId) = nullptr;
    void* recycle_arg_ = nullptr;
    std::atomic<int64_t> bytes_read_{0};
    std::atomic<int64_t> bytes_written_{0};
    std::atomic<int64_t> descriptor_bytes_read_{0};
    std::atomic<uint64_t> peer_pool_id_{0};
    int64_t created_us_ = 0;
    std::atomic<int64_t> last_active_us_{0};
    // I/O attribution (reset on slot reuse, like the byte counters).
    std::atomic<int64_t> nwrite_batches_{0};
    std::atomic<int64_t> max_write_batch_{0};
    std::atomic<int64_t> queued_highwater_{0};
    std::atomic<int64_t> novercrowded_{0};
    std::atomic<int64_t> rate_scrape_us_{0};
    std::atomic<int64_t> rate_scrape_in_{0};
    std::atomic<int64_t> rate_scrape_out_{0};
    void* conn_data_ = nullptr;
    void (*conn_data_deleter_)(void*) = nullptr;
    std::mutex pipeline_mu_;
    std::deque<PipelinedInfo> pipeline_q_;
};

// Process-wide count of write elections deferred into a coalescing scope
// (the rpc_socket_coalesced_writes tvar; /loops + tests read it here).
int64_t SocketCoalescedWrites();

// Write coalescing across one dispatch round (ISSUE 7): while a scope is
// armed on the current thread, a Socket::Write that wins the writer
// election DEFERS its flush — the request sits in the wait-free queue and
// the elected-writer role transfers to the scope. FlushDeferred() (called
// at the end of each messenger cut round, and by the scope destructor)
// then flushes each deferred socket once, so every response queued on the
// same connection during the round leaves in a single writev
// (rpc_socket_write_batch_bytes grows; rpc_socket_coalesced_writes counts
// deferred elections). Cross-request coalescing on pooled connections
// works the same way: the round's scope spans all sockets it wrote to.
//
// Safety: the scope is registered in a thread-local; TaskGroup::sched_park
// flushes-and-detaches it before any fiber switch, so a handler that
// (illegally, per the inline-safe contract) parks mid-round can never
// strand deferred writes on the old thread or leave a dangling pointer.
class WriteCoalesceScope {
public:
    WriteCoalesceScope();   // arms on this thread (no-op when nested)
    ~WriteCoalesceScope();  // FlushDeferred + disarm
    WriteCoalesceScope(const WriteCoalesceScope&) = delete;
    WriteCoalesceScope& operator=(const WriteCoalesceScope&) = delete;

    // Flush every deferred socket now; the scope stays armed for the
    // next round.
    void FlushDeferred();

    // Called by the elected writer in Socket::Write: true = the flush
    // was deferred into the active scope (a reference is held until the
    // flush). False when no scope is armed or it is full.
    static bool TryDefer(Socket* s);
    // sched_park hook: flush + detach whatever scope is armed on this
    // thread (the parking fiber may resume on another thread).
    static void FlushCurrent();

private:
    static constexpr int kMaxSockets = 8;
    Socket* sockets_[kMaxSockets];  // AddRef'd until flushed
    int nsockets_ = 0;
    bool armed_ = false;  // this instance owns the thread slot
};

}  // namespace tpurpc
