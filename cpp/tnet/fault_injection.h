// Deterministic, seeded fault-injection seam for the transport layer.
//
// The robustness stack (circuit breaker, health check, retries, backup
// requests, EOVERCROWDED, crc32c body checksums) is only proven if it is
// exercised adversarially. This layer lets the transport seams — fd
// read/write (tnet/socket.cc, tnet/input_messenger.cc), TLS
// (tnet/tls.cc), shared-memory links (tici/shm_link.cc) and accept/
// connect time — consult one process-wide fault plan and inject drops,
// delays, short reads/writes, payload corruption, connection resets and
// refusals.
//
// Design rules:
//  - Zero overhead when disabled: seams gate on `fault_injection_enabled()`,
//    a single relaxed atomic load; nothing else runs.
//  - Deterministic: decision n of a (seed, plan) pair is a pure function
//    of n (splitmix64 over a monotone counter). Replaying the same seed
//    against the same call sequence reproduces the same injection
//    sequence — asserted by ttest FaultInjection.DeterministicReplay.
//  - Per-peer scoping: the plan may name remote endpoints; traffic to
//    other peers neither injects nor consumes a decision tick, so
//    unrelated connections do not perturb the replayed sequence.
//  - Live toggling: the chaos_* flags (tbase/flags) re-apply on every
//    set (on-change hook), and the /chaos portal page
//    (thttp/builtin_services.cc) drives them over HTTP.
//  - Observable: every injection bumps a tvar Adder exported as
//    chaos_injected_<kind> (visible in /vars, /metrics and /chaos).
#pragma once

#include <atomic>
#include <cstddef>
#include <cstdint>
#include <string>

#include "tbase/endpoint.h"

namespace tpurpc {

// Where in the transport a decision is being made.
enum class FaultOp {
    kWrite = 0,    // outbound bytes (fd writev / TLS write / shm post)
    kRead = 1,     // inbound bytes (fd read / TLS read / shm pump)
    kAccept = 2,   // server accept time
    kConnect = 3,  // client connect time
    // Zero-copy data-path seams (ISSUE 10d) — the pool/ring layer the
    // PR-1 chaos plan never reached:
    kPoolResolve = 4,   // server-side descriptor resolve (crc / epoch)
    kRingComplete = 5,  // device staging-ring completion
    kLeaseRelease = 6,  // pinned-block release at EndRPC (leak sim)
    // Work-priced admission seam (ISSUE 15): consulted when a handler
    // completion feeds its measured cost into the QoS cost model, so a
    // soak can inflate a method's price without moving real bytes.
    kCostMeasure = 7,
    // Server-push stream chunk send (ISSUE 17): consulted per
    // STREAM_DATA chunk so a soak can inject slow consumers
    // (stream_stall=prob[:ms] -> kDelay) and lost chunks
    // (stream_drop_chunk=prob -> kDrop, recovered by the receiver's
    // dup-ack retransmit path) deterministically.
    kStreamWrite = 8,
    // One-sided verb plane (ISSUE 18). kVerbPost: consulted when a
    // REMOTE_READ/REMOTE_WRITE is posted (verb_drop=prob -> kDrop: the
    // post vanishes in flight; the initiator's pending-wr deadline
    // reaps and retries it). kCqComplete: consulted when a completion
    // is delivered into a doorbell CQ (doorbell_delay=prob[:us] ->
    // kDelay: the doorbell rings late, parking pollers). Neither is
    // peer-filtered — verbs are keyed by socket/window ids, not
    // endpoints.
    kVerbPost = 9,
    kCqComplete = 10,
    // Grey-failure seam (ISSUE 20): consulted at handler dispatch, after
    // admission, before the user method runs. slow_node=prob[:ms] ->
    // kDelay inflates service time (the node is SLOW, not dead: connect
    // probes still pass, health checks stay green — only the outlier
    // tier can see it). error_rate=prob -> kFail answers the call with a
    // synthetic failure without running the handler. Not peer-filtered:
    // the plan is applied ON the degraded server itself, and its peers
    // at this seam are clients, not the targets a chaos_peers list
    // names.
    kHandler = 11,
};

// What the consulting seam should do.
struct FaultAction {
    enum Kind {
        kNone = 0,
        kDelay,    // sleep delay_us, then proceed normally
        kShort,    // cap this I/O to max_bytes (short read/write)
        kDrop,     // claim success but discard the bytes
        kCorrupt,  // flip one byte of the payload (crc32c's job to catch)
        kReset,    // fail the operation with ECONNRESET
        kRefuse,   // refuse the connection (accept/connect only)
        // Pool-descriptor staleness (kPoolResolve only): resolve as if
        // the descriptor's pool_epoch predated the mapping — the call
        // must fail retriable (TERR_STALE_EPOCH), never the connection.
        kStaleEpoch,
        // Cost inflation (kCostMeasure only, ISSUE 15): multiply the
        // measured handler cost by `aux` before it feeds the admission
        // cost model — drives work-priced shedding in soaks.
        kInflate,
        // Process crash (ISSUE 19): Decide itself dies on a genuine
        // SIGSEGV (null write) after recording the chaos event — the
        // flight recorder's signal path must produce the black-box dump.
        // Never returned to a seam; the sentinel below stays the counter
        // array size.
        kCrash,
        // Synthetic handler failure (kHandler only, ISSUE 20): the call
        // is answered with a retriable error without running the user
        // method — a grey node that computes wrong/errors, yet whose
        // connection-level health stays perfect.
        kFail,
        kKindCount  // sentinel (counter array size)
    };
    Kind kind = kNone;
    int64_t delay_us = 0;   // kDelay
    size_t max_bytes = 0;   // kShort: cap for this operation
    uint64_t aux = 0;       // kCorrupt: byte-position seed; kInflate: mult
};

namespace fault_internal {
// The one hot-path word. Seams read it inline; everything behind it is
// out-of-line in fault_injection.cc.
extern std::atomic<bool> g_chaos_on;
}  // namespace fault_internal

// Hot-path gate: one relaxed load, no function call when disabled.
inline bool fault_injection_enabled() {
    return fault_internal::g_chaos_on.load(std::memory_order_relaxed);
}

class FaultInjection {
public:
    // Decide the fault (if any) for one operation of `len` bytes against
    // `peer`. Only call when fault_injection_enabled().
    static FaultAction Decide(FaultOp op, const EndPoint& peer, size_t len);

    // Re-read the chaos_* flags into the live plan (the chaos_enabled /
    // chaos_peers on-change hook). Does NOT touch the decision counter
    // or the injection counters — disabling after a run must leave the
    // counters readable for the replay-diff workflow.
    static void Reconfigure();

    // Reconfigure() plus a fresh deterministic sequence: resets the
    // decision counter AND the injection counters (the chaos_seed /
    // chaos_plan on-change hook — re-applying a seed replays from
    // decision 0, and two runs of the same seed are directly
    // comparable).
    static void ReconfigureAndReset();

    // True when the strings would parse (Reconfigure fails closed —
    // disables injection — on unparsable input; callers that want to
    // REJECT instead, like the /chaos page, validate first).
    static bool ValidatePlan(const std::string& plan);
    static bool ValidatePeers(const std::string& peers);

    // ---- zone partition (ISSUE 14) ----
    // Register the locality zone of a peer endpoint (mesh tools and the
    // naming layer feed this from their zone tags). With the
    // -chaos_partition_zone flag set to a zone name, EVERY read/write/
    // connect against a peer registered in that zone fails (kReset /
    // kRefuse) — one command partitions an entire pod. Partition
    // matching neither consumes a decision tick nor touches the
    // deterministic plan sequence, so a partition can be layered over a
    // replayed seed. Cuts are counted in chaos_zone_partition_cuts.
    static void SetPeerZone(const EndPoint& peer, const std::string& zone);
    static std::string PeerZone(const EndPoint& peer);
    static int64_t zone_partition_cuts();

    // Current config + counters, one "key value" pair per line (the
    // /chaos page body; also convenient for tests).
    static std::string DebugString();

    // Counters (injected_count is also exported via the
    // chaos_injected_<kind> tvars).
    static int64_t injected_count(FaultAction::Kind k);
    static int64_t decisions();
    static void ResetCounters();
};

}  // namespace tpurpc
