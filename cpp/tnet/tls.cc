#include "tnet/tls.h"

#include <dlfcn.h>
#include <netinet/in.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <cerrno>
#include <cstring>
#include <mutex>

#include "tbase/endpoint.h"
#include "tbase/logging.h"
#include "tbase/time.h"
#include "tfiber/fiber.h"
#include "tnet/fault_injection.h"

namespace tpurpc {

namespace {

// ---- OpenSSL 3 ABI surface (hand-declared; resolved via dlsym) ----

typedef struct ssl_ctx_st SSL_CTX;
typedef struct ssl_st SSL;
typedef struct ssl_method_st SSL_METHOD;

constexpr int kSslFiletypePem = 1;       // SSL_FILETYPE_PEM
constexpr int kSslErrorWantRead = 2;     // SSL_ERROR_WANT_READ
constexpr int kSslErrorWantWrite = 3;    // SSL_ERROR_WANT_WRITE
constexpr int kSslErrorZeroReturn = 6;   // SSL_ERROR_ZERO_RETURN
constexpr int kSslCtrlMode = 33;         // SSL_CTRL_MODE
constexpr long kModePartialWrite = 0x1;  // SSL_MODE_ENABLE_PARTIAL_WRITE
constexpr long kModeMovingBuffer = 0x2;  // SSL_MODE_ACCEPT_MOVING_WRITE_BUFFER
constexpr int kCtrlSetTlsextHostname = 55;  // SSL_CTRL_SET_TLSEXT_HOSTNAME
constexpr int kTlsextNametypeHost = 0;      // TLSEXT_NAMETYPE_host_name

struct SslApi {
    void* handle = nullptr;
    int (*init_ssl)(uint64_t, const void*);
    const SSL_METHOD* (*tls_method)();
    SSL_CTX* (*ctx_new)(const SSL_METHOD*);
    void (*ctx_free)(SSL_CTX*);
    int (*use_cert_chain)(SSL_CTX*, const char*);
    int (*use_privkey)(SSL_CTX*, const char*, int);
    long (*ctx_ctrl)(SSL_CTX*, int, long, void*);
    int (*set_alpn_protos)(SSL*, const unsigned char*, unsigned);
    void (*ctx_set_alpn_select_cb)(
        SSL_CTX*,
        int (*)(SSL*, const unsigned char**, unsigned char*,
                const unsigned char*, unsigned, void*),
        void*);
    SSL* (*ssl_new)(SSL_CTX*);
    void (*ssl_free)(SSL*);
    int (*set_fd)(SSL*, int);
    void (*set_connect_state)(SSL*);
    void (*set_accept_state)(SSL*);
    int (*do_handshake)(SSL*);
    int (*ssl_read)(SSL*, void*, int);
    int (*ssl_write)(SSL*, const void*, int);
    int (*get_error)(const SSL*, int);
    int (*ssl_shutdown)(SSL*);
    long (*ssl_ctrl)(SSL*, int, long, void*);
    void (*get0_alpn_selected)(const SSL*, const unsigned char**,
                               unsigned*);
    void (*err_clear)();
};

SslApi* ssl_api() {
    static SslApi* api = []() -> SslApi* {
        void* h = dlopen("libssl.so.3", RTLD_NOW | RTLD_GLOBAL);
        if (h == nullptr) h = dlopen("libssl.so", RTLD_NOW | RTLD_GLOBAL);
        if (h == nullptr) return nullptr;
        auto* a = new SslApi;
        a->handle = h;
        bool ok = true;
        auto sym = [&](const char* name) -> void* {
            void* p = dlsym(h, name);
            if (p == nullptr) ok = false;
            return p;
        };
        a->init_ssl = (decltype(a->init_ssl))sym("OPENSSL_init_ssl");
        a->tls_method = (decltype(a->tls_method))sym("TLS_method");
        a->ctx_new = (decltype(a->ctx_new))sym("SSL_CTX_new");
        a->ctx_free = (decltype(a->ctx_free))sym("SSL_CTX_free");
        a->use_cert_chain = (decltype(a->use_cert_chain))sym(
            "SSL_CTX_use_certificate_chain_file");
        a->use_privkey =
            (decltype(a->use_privkey))sym("SSL_CTX_use_PrivateKey_file");
        a->ctx_ctrl = (decltype(a->ctx_ctrl))sym("SSL_CTX_ctrl");
        a->set_alpn_protos =
            (decltype(a->set_alpn_protos))sym("SSL_set_alpn_protos");
        a->ctx_set_alpn_select_cb = (decltype(a->ctx_set_alpn_select_cb))sym(
            "SSL_CTX_set_alpn_select_cb");
        a->ssl_new = (decltype(a->ssl_new))sym("SSL_new");
        a->ssl_free = (decltype(a->ssl_free))sym("SSL_free");
        a->set_fd = (decltype(a->set_fd))sym("SSL_set_fd");
        a->set_connect_state =
            (decltype(a->set_connect_state))sym("SSL_set_connect_state");
        a->set_accept_state =
            (decltype(a->set_accept_state))sym("SSL_set_accept_state");
        a->do_handshake = (decltype(a->do_handshake))sym("SSL_do_handshake");
        a->ssl_read = (decltype(a->ssl_read))sym("SSL_read");
        a->ssl_write = (decltype(a->ssl_write))sym("SSL_write");
        a->get_error = (decltype(a->get_error))sym("SSL_get_error");
        a->ssl_shutdown = (decltype(a->ssl_shutdown))sym("SSL_shutdown");
        a->ssl_ctrl = (decltype(a->ssl_ctrl))sym("SSL_ctrl");
        a->get0_alpn_selected = (decltype(a->get0_alpn_selected))sym(
            "SSL_get0_alpn_selected");
        a->err_clear = (decltype(a->err_clear))sym("ERR_clear_error");
        if (!ok) {
            dlclose(h);
            delete a;
            return nullptr;
        }
        a->init_ssl(0, nullptr);
        return a;
    }();
    return api;
}

// ALPN select callback: prefer h2, accept http/1.1.
int AlpnSelect(SSL*, const unsigned char** out, unsigned char* outlen,
               const unsigned char* in, unsigned inlen, void*) {
    const unsigned char* http11 = nullptr;
    unsigned char http11_len = 0;
    for (unsigned i = 0; i + 1 <= inlen;) {
        const unsigned char len = in[i];
        if (i + 1 + len > inlen) break;
        if (len == 2 && memcmp(in + i + 1, "h2", 2) == 0) {
            *out = in + i + 1;
            *outlen = len;
            return 0;  // SSL_TLSEXT_ERR_OK
        }
        if (len == 8 && memcmp(in + i + 1, "http/1.1", 8) == 0) {
            http11 = in + i + 1;
            http11_len = len;
        }
        i += 1 + len;
    }
    if (http11 != nullptr) {
        *out = http11;
        *outlen = http11_len;
        return 0;
    }
    return 3;  // SSL_TLSEXT_ERR_NOACK: proceed without ALPN
}

SSL_CTX* g_server_ctx = nullptr;
SSL_CTX* client_ctx() {
    static SSL_CTX* ctx = [] {
        SslApi* a = ssl_api();
        if (a == nullptr) return (SSL_CTX*)nullptr;
        SSL_CTX* c = a->ctx_new(a->tls_method());
        if (c != nullptr) {
            a->ctx_ctrl(c, kSslCtrlMode,
                        kModePartialWrite | kModeMovingBuffer, nullptr);
        }
        return c;
    }();
    return ctx;
}

// ---- the transport ----

class TlsTransport : public TransportEndpoint {
public:
    TlsTransport(SSL* ssl, int fd, SslApi* api)
        : ssl_(ssl), fd_(fd), api_(api) {
        // Remote identity for per-peer fault-injection scoping; best
        // effort (an unconnected fd leaves it empty = matches only
        // unscoped plans).
        sockaddr_in peer;
        socklen_t plen = sizeof(peer);
        if (getpeername(fd, (sockaddr*)&peer, &plen) == 0) {
            remote_ = sockaddr2endpoint(peer);
        }
    }

    ~TlsTransport() override {
        if (ssl_ != nullptr) api_->ssl_free(ssl_);
        // The Socket never closes a transport's fd (ICI links own their
        // event fds); the raw TCP fd under TLS is ours.
        if (fd_ >= 0) ::close(fd_);
    }

    int event_fd() const override { return fd_; }
    bool Established() const override { return established_; }

    std::string alpn() const {
        const unsigned char* p = nullptr;
        unsigned len = 0;
        api_->get0_alpn_selected(ssl_, &p, &len);
        return p != nullptr ? std::string((const char*)p, len)
                            : std::string();
    }

    ssize_t CutFromIOBufList(IOBuf* const* pieces, size_t count) override {
        // Chaos: faults on the PLAINTEXT side of the record layer, so a
        // corrupt byte arrives MAC-valid and only the application-level
        // crc32c can catch it (exactly the property under test).
        // Decided (and slept) BEFORE taking ssl_mu_: fiber_usleep may
        // resume on another worker thread, and unlocking a std::mutex
        // from a non-owner thread is UB (pieces are owned by the single
        // elected writer, so touching them here is safe).
        FaultAction fault;
        size_t fault_budget = 0;  // kShort: plaintext bytes still allowed
        if (__builtin_expect(fault_injection_enabled(), 0)) {
            size_t total_len = 0;
            for (size_t i = 0; i < count; ++i) total_len += pieces[i]->size();
            fault = FaultInjection::Decide(FaultOp::kWrite, remote_,
                                           total_len);
            switch (fault.kind) {
                case FaultAction::kReset:
                    errno = ECONNRESET;
                    return -1;
                case FaultAction::kDelay:
                    // Safe to park: with chaos enabled, Socket::FlushOnce
                    // routes every write through the KeepWrite fiber
                    // (no caller locks on that stack).
                    fiber_usleep(fault.delay_us);
                    break;
                case FaultAction::kDrop: {
                    for (size_t i = 0; i < count; ++i) {
                        pieces[i]->pop_front(pieces[i]->size());
                    }
                    return (ssize_t)total_len;
                }
                case FaultAction::kShort:
                    fault_budget = fault.max_bytes > 0 ? fault.max_bytes : 1;
                    break;
                default:
                    break;
            }
        }
        // SSL* is not thread-safe; the KeepWrite fiber and the input
        // fiber (Pump) can run concurrently.
        std::lock_guard<std::mutex> g(ssl_mu_);
        if (!DriveHandshake()) return -1;  // errno set
        ssize_t total = 0;
        char chunk[16384];
        for (size_t i = 0; i < count; ++i) {
            IOBuf* piece = pieces[i];
            while (!piece->empty()) {
                size_t n = piece->copy_to(chunk, sizeof(chunk));
                if (fault.kind == FaultAction::kShort) {
                    if (fault_budget == 0) {
                        // Short write: report what went through (or
                        // EAGAIN so the writer parks and retries).
                        if (total > 0) return total;
                        errno = EAGAIN;
                        return -1;
                    }
                    n = std::min(n, fault_budget);
                }
                if (fault.kind == FaultAction::kCorrupt && total == 0) {
                    chunk[fault.aux % n] ^= 0x20;
                }
                api_->err_clear();  // see WantMore()
                const int w = api_->ssl_write(ssl_, chunk, (int)n);
                if (w <= 0) {
                    if (WantMore(w)) {
                        errno = EAGAIN;
                        return total > 0 ? total : -1;
                    }
                    errno = EIO;
                    return total > 0 ? total : -1;
                }
                piece->pop_front((size_t)w);
                total += w;
                if (fault.kind == FaultAction::kShort) {
                    fault_budget -= std::min(fault_budget, (size_t)w);
                }
            }
        }
        return total;
    }

    int WaitWritable(int64_t abstime_us) override {
        // Wait for the direction the last SSL op actually needed: a
        // handshake stalled on WANT_READ must NOT poll POLLOUT (a TCP
        // socket is almost always write-ready — that poll returns
        // immediately and the KeepWrite loop busy-spins for the whole
        // handshake RTT).
        const short ev = want_events_.load(std::memory_order_acquire);
        pollfd p{fd_, ev != 0 ? ev : (short)(POLLIN | POLLOUT), 0};
        int timeout_ms = 100;
        if (abstime_us > 0) {
            const int64_t remain_ms =
                (abstime_us - monotonic_time_us()) / 1000;
            if (remain_ms <= 0) return -1;
            timeout_ms = (int)std::min<int64_t>(remain_ms, 100);
        }
        return ::poll(&p, 1, timeout_ms) >= 0 ? 0 : -1;
    }

    ssize_t Pump(IOPortal* dst) override {
        // Chaos: inbound faults on the decrypted plaintext. Decided (and
        // slept) BEFORE ssl_mu_ — see CutFromIOBufList.
        FaultAction fault;
        if (__builtin_expect(fault_injection_enabled(), 0)) {
            fault = FaultInjection::Decide(FaultOp::kRead, remote_, 16384);
            if (fault.kind == FaultAction::kReset) {
                errno = ECONNRESET;
                return -1;
            }
            if (fault.kind == FaultAction::kDelay) {
                fiber_usleep(fault.delay_us);
            }
        }
        std::lock_guard<std::mutex> g(ssl_mu_);
        if (!DriveHandshake()) return -1;
        ssize_t total = 0;
        char buf[16384];
        while (true) {
            api_->err_clear();  // see WantMore()
            int want = sizeof(buf);
            if (fault.kind == FaultAction::kShort) {
                want = (int)std::min<size_t>(
                    sizeof(buf), fault.max_bytes > 0 ? fault.max_bytes : 1);
            }
            const int r = api_->ssl_read(ssl_, buf, want);
            if (r > 0) {
                if (fault.kind == FaultAction::kCorrupt && total == 0) {
                    buf[fault.aux % (uint64_t)r] ^= 0x20;
                }
                if (fault.kind != FaultAction::kDrop) {
                    dst->append(buf, (size_t)r);
                }
                total += r;
                if (fault.kind == FaultAction::kShort) return total;
                continue;
            }
            const int err = api_->get_error(ssl_, r);
            if (err == kSslErrorZeroReturn) {
                return total > 0 ? total : 0;  // clean TLS shutdown
            }
            if (err == kSslErrorWantRead || err == kSslErrorWantWrite) {
                if (total > 0) return total;
                errno = EAGAIN;
                return -1;
            }
            // Transport error; a half-read burst still delivers.
            if (total > 0) return total;
            return 0;  // treat as EOF: the socket fails via TERR_EOF
        }
    }

    void Close() override {
        std::lock_guard<std::mutex> g(ssl_mu_);
        if (!closed_) {
            closed_ = true;
            api_->err_clear();
            api_->ssl_shutdown(ssl_);
            // Leave the queue clean: shutdown of an in-handshake session
            // records an error the next connection on this thread must
            // not inherit.
            api_->err_clear();
        }
    }

    void Release() override { delete this; }

private:
    // SSL_get_error consults the THREAD-LOCAL OpenSSL error queue: a
    // stale entry left by another connection on this thread (e.g. its
    // teardown SSL_shutdown) makes an innocent EAGAIN read classify as
    // fatal SSL_ERROR_SSL. Every SSL op here is preceded by
    // ERR_clear_error() so the queue only ever holds THIS call's errors.
    bool WantMore(int rc) {
        const int err = api_->get_error(ssl_, rc);
        if (err == kSslErrorWantRead) {
            want_events_.store(POLLIN, std::memory_order_release);
            return true;
        }
        if (err == kSslErrorWantWrite) {
            want_events_.store(POLLOUT, std::memory_order_release);
            return true;
        }
        return false;
    }

    // Returns true once established; false with errno=EAGAIN while the
    // handshake still needs bytes, errno=EIO on fatal failure.
    bool DriveHandshake() {
        if (established_) return true;
        api_->err_clear();
        const int rc = api_->do_handshake(ssl_);
        if (rc == 1) {
            established_ = true;
            return true;
        }
        errno = WantMore(rc) ? EAGAIN : EIO;
        return false;
    }

    SSL* ssl_;
    int fd_;
    SslApi* api_;
    EndPoint remote_;  // per-peer fault-injection scoping
    std::mutex ssl_mu_;
    std::atomic<short> want_events_{0};  // POLLIN/POLLOUT of last WANT_*
    bool established_ = false;
    bool closed_ = false;
};

}  // namespace

bool TlsAvailable() { return ssl_api() != nullptr; }

int TlsServerInit(const std::string& cert_pem_path,
                  const std::string& key_pem_path) {
    SslApi* a = ssl_api();
    if (a == nullptr) {
        LOG(ERROR) << "TLS requested but libssl is not available";
        return -1;
    }
    static std::mutex mu;
    std::lock_guard<std::mutex> g(mu);
    if (g_server_ctx != nullptr) return 0;
    SSL_CTX* ctx = a->ctx_new(a->tls_method());
    if (ctx == nullptr) return -1;
    if (a->use_cert_chain(ctx, cert_pem_path.c_str()) != 1 ||
        a->use_privkey(ctx, key_pem_path.c_str(), kSslFiletypePem) != 1) {
        LOG(ERROR) << "TLS: failed to load cert/key from "
                   << cert_pem_path << " / " << key_pem_path;
        a->ctx_free(ctx);
        return -1;
    }
    a->ctx_ctrl(ctx, kSslCtrlMode, kModePartialWrite | kModeMovingBuffer,
                nullptr);
    a->ctx_set_alpn_select_cb(ctx, AlpnSelect, nullptr);
    g_server_ctx = ctx;
    return 0;
}

TransportEndpoint* NewTlsServerTransport(int fd) {
    SslApi* a = ssl_api();
    if (a == nullptr || g_server_ctx == nullptr) return nullptr;
    SSL* ssl = a->ssl_new(g_server_ctx);
    if (ssl == nullptr) return nullptr;
    a->set_fd(ssl, fd);
    a->set_accept_state(ssl);
    return new TlsTransport(ssl, fd, a);
}

TransportEndpoint* NewTlsClientTransport(int fd, const std::string& alpn,
                                         const std::string& sni) {
    SslApi* a = ssl_api();
    SSL_CTX* ctx = client_ctx();
    if (a == nullptr || ctx == nullptr) return nullptr;
    SSL* ssl = a->ssl_new(ctx);
    if (ssl == nullptr) return nullptr;
    a->set_fd(ssl, fd);
    a->set_connect_state(ssl);
    if (!alpn.empty()) {
        // ALPN wire format: length-prefixed protocol list.
        std::string wire;
        wire.push_back((char)alpn.size());
        wire += alpn;
        a->set_alpn_protos(ssl, (const unsigned char*)wire.data(),
                           (unsigned)wire.size());
    }
    if (!sni.empty()) {
        a->ssl_ctrl(ssl, kCtrlSetTlsextHostname, kTlsextNametypeHost,
                    (void*)sni.c_str());
    }
    return new TlsTransport(ssl, fd, a);
}

}  // namespace tpurpc
