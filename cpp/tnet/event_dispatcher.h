// EventDispatcher: epoll loops delivering readiness events to Sockets by id.
//
// Modeled on reference src/brpc/event_dispatcher.h:92-143 +
// event_dispatcher_epoll.cpp (epoll_wait loop :196-209, edge-triggered, fds
// registered with versioned ids so stale events on recycled sockets are
// ignored). Sharded by fd across `event_dispatcher_num` loops. Each loop
// runs on a dedicated pthread (the reference wraps it in a bthread; the
// callbacks here immediately hand off to fibers, which is what matters).
#pragma once

#include <atomic>
#include <thread>
#include <vector>

#include "tnet/socket.h"

namespace tpurpc {

class EventDispatcher {
public:
    // Register fd for edge-triggered EPOLLIN events delivered to socket id.
    int AddConsumer(SocketId id, int fd);
    // ADD with EPOLLIN|EPOLLOUT (async connect in flight).
    int AddConsumerWithEpollOut(SocketId id, int fd);
    // Also wait for EPOLLOUT once (connect / blocked write). `pollin` keeps
    // the read registration alive.
    int RegisterEpollOut(SocketId id, int fd, bool pollin);
    int UnregisterEpollOut(SocketId id, int fd, bool pollin);
    int RemoveConsumer(int fd);

    static EventDispatcher& GetGlobalDispatcher(int fd);
    static void StopAll();

private:
    EventDispatcher();
    ~EventDispatcher();
    void Run();

    int epfd_ = -1;
    std::atomic<bool> stop_{false};
    std::thread thread_;

    friend EventDispatcher* global_dispatchers();
};

}  // namespace tpurpc
