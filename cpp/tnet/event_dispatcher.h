// EventDispatcher: epoll loops delivering readiness events to Sockets by id.
//
// Modeled on reference src/brpc/event_dispatcher.h:92-143 +
// event_dispatcher_epoll.cpp (epoll_wait loop :196-209, edge-triggered, fds
// registered with versioned ids so stale events on recycled sockets are
// ignored). Sharded by fd across `event_dispatcher_num` loops. Each loop
// runs on a dedicated pthread (the reference wraps it in a bthread; the
// callbacks here immediately hand off to fibers, which is what matters).
//
// Raw-speed round (ISSUE 7):
//  - loops block in epoll_wait with NO timeout: an eventfd registered in
//    the epoll set delivers stop/wake (the old implementation closed the
//    epoll fd and relied on EBADF, and woke every 100 ms even when idle);
//  - optional CPU pinning via -event_dispatcher_affinity so a loop's
//    cache footprint stays on one core (run-to-completion sharding);
//  - the event batch grows adaptively (64 -> 4096) when a wake saturates
//    it, so bursty sockets drain in one epoll_wait round.
//
// Telemetry (ISSUE 6): every loop exports labelled families —
// rpc_dispatcher_epoll_waits / _events / _wakeups (counters, {loop=N}),
// rpc_dispatcher_events_per_wake and _wake_to_dispatch_us (summaries) —
// rendered on /loops and fed into the /vars?series= rings.
#pragma once

#include <atomic>
#include <thread>
#include <vector>

#include "tnet/socket.h"
#include "tvar/latency_recorder.h"
#include "tvar/reducer.h"

namespace tpurpc {

class EventDispatcher {
public:
    // Register fd for edge-triggered EPOLLIN events delivered to socket id.
    int AddConsumer(SocketId id, int fd);
    // ADD with EPOLLIN|EPOLLOUT (async connect in flight).
    int AddConsumerWithEpollOut(SocketId id, int fd);
    // Also wait for EPOLLOUT once (connect / blocked write). `pollin` keeps
    // the read registration alive.
    int RegisterEpollOut(SocketId id, int fd, bool pollin);
    int UnregisterEpollOut(SocketId id, int fd, bool pollin);
    int RemoveConsumer(int fd);

    static EventDispatcher& GetGlobalDispatcher(int fd);
    static void StopAll();

    // ---- per-loop telemetry (the /loops builtin) ----
    struct LoopStats {
        int64_t epoll_waits = 0;  // epoll_wait returns (blocking waits)
        int64_t events = 0;       // readiness events delivered
        int64_t wakeups = 0;      // eventfd wakes (stop/cross-thread kicks)
        int64_t batch_capacity = 0;  // current adaptive event-array size
        int cpu = -1;                // pinned CPU, -1 = unpinned
        const LatencyRecorder* events_per_wake = nullptr;
        const LatencyRecorder* wake_to_dispatch_us = nullptr;
    };
    // Visits every live loop in index order; no-op before the first
    // dispatcher exists.
    static void ForEachLoop(void (*fn)(int index, const LoopStats&,
                                       void* arg),
                            void* arg);
    // Sum of epoll_waits across loops (tests).
    static int64_t TotalEpollWaits();

private:
    explicit EventDispatcher(int index);
    ~EventDispatcher();
    void Run();
    // Write the eventfd so a blocking epoll_wait returns promptly.
    void Wakeup();

    int epfd_ = -1;
    int wakeup_fd_ = -1;  // eventfd registered in epfd_ (sentinel data)
    int index_ = 0;
    int pinned_cpu_ = -1;
    std::atomic<bool> stop_{false};
    // Adaptive batch size, written by the loop thread only; atomic so
    // ForEachLoop can read it racily for /loops.
    std::atomic<int64_t> batch_capacity_{64};
    // Telemetry cells live in process-lifetime labelled families; the
    // loop updates through raw pointers (relaxed atomics / recorder
    // adds) so the hot path never touches the family mutex.
    IntCell* waits_cell_ = nullptr;
    IntCell* events_cell_ = nullptr;
    IntCell* wakeups_cell_ = nullptr;
    LatencyRecorder* events_per_wake_ = nullptr;
    LatencyRecorder* wake_us_ = nullptr;
    std::thread thread_;

    friend EventDispatcher* global_dispatchers();
};

}  // namespace tpurpc
