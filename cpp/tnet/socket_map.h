// SocketMap: the client-side connection registry — one shared connection
// per remote endpoint ("single" connection mode). Modeled on reference
// src/brpc/socket_map.h:82-150 (SocketMapInsert/Remove keyed by endpoint).
#pragma once

#include <deque>
#include <map>
#include <mutex>
#include <vector>

#include "tbase/endpoint.h"
#include "tnet/socket.h"

namespace tpurpc {

class InputMessenger;

// Create a fresh client connection (connect-on-first-write) to `remote`
// fed into `messenger` — the one place client SocketOptions are built
// (SocketMap, SocketPool and short-lived connections all use it).
// `tier` (tnet/transport.h registry id; -1 = default tcp) stamps the
// socket's forced transport tier — how a dcn-class connection differs
// from a tcp one to the same address (ISSUE 14).
int CreateClientSocket(const EndPoint& remote, InputMessenger* messenger,
                       SocketId* id, int tier = -1);

class SocketMap {
public:
    static SocketMap* singleton();

    // Get (or create, connect-on-first-write) the shared socket to `remote`
    // whose input is handled by `messenger`. Returns 0 and sets *id.
    // Keyed by (endpoint, tier) — a tcp and a dcn endpoint at the same
    // address NEVER share a connection or its health/breaker state: a
    // WAN-shaped dcn socket tripping its breaker must not poison the
    // LAN path, and vice versa.
    int GetOrCreate(const EndPoint& remote, InputMessenger* messenger,
                    SocketId* id, int tier = -1);
    // Drop the cached socket (e.g. after SetFailed).
    void Remove(const EndPoint& remote, SocketId expected_id,
                int tier = -1);

    // Every remote this process holds a shared client connection to —
    // the rpcz stitcher's peer discovery (these are real serving ports,
    // unlike accepted connections' ephemeral remote ports).
    std::vector<EndPoint> endpoints();

private:
    // -1 ("default tcp") and an explicit TierTcp() are distinct keys on
    // purpose: normalizing would need the registry initialized before
    // any map use, and nothing creates explicit-tcp entries today.
    using Key = std::pair<EndPoint, int>;
    std::mutex mu_;
    std::map<Key, SocketId> map_;
};

// Pooled ("pooled" connection mode) client sockets: one in-flight RPC per
// connection at a time, returned to the per-remote idle pool after its
// response arrives (reference src/brpc/socket.cpp SocketPool::GetSocket /
// ReturnSocket; controller.cpp: a call that failed without a response
// never reuses its pooled connection). An idle-close sweep fails pooled
// connections unused for -pooled_idle_close_s (reference socket_map.h:204
// idle-close thread).
//
// Selection is FIFO (pop-front / return-push-back), so consecutive calls
// ROUND-ROBIN through the pool members instead of convoying on the most
// recently returned socket: sockets shard across the epoll loops by fd,
// and the old LIFO stack kept re-dispatching the whole pooled load onto
// the one or two hottest fds — the direct cause of pooled-TCP QPS
// landing below single-connection in BENCH_r05 (ISSUE 7).
class SocketPool {
public:
    static SocketPool* singleton();

    // Pop the least-recently-used idle healthy connection to `remote` or
    // create a fresh one (connect-on-first-write). Returns 0 and sets
    // *id. Pools are keyed by (endpoint, tier) like the SocketMap — a
    // pooled dcn connection is never handed to a tcp caller.
    int Get(const EndPoint& remote, InputMessenger* messenger, SocketId* id,
            int tier = -1);
    // Return a connection whose RPC received its response. Over-capacity
    // or failed sockets are closed instead of pooled.
    void Return(SocketId id);

    // Test/portal introspection: idle connections pooled for `remote`.
    size_t idle_count(const EndPoint& remote, int tier = -1);

private:
    SocketPool() = default;
    void SweepLoop();  // idle-close fiber

    struct IdleConn {
        SocketId id;
        int64_t returned_us;
    };
    using Key = std::pair<EndPoint, int>;
    std::mutex mu_;
    std::map<Key, std::deque<IdleConn>> pools_;
    bool sweeping_ = false;
};

}  // namespace tpurpc
