// SocketMap: the client-side connection registry — one shared connection
// per remote endpoint ("single" connection mode). Modeled on reference
// src/brpc/socket_map.h:82-150 (SocketMapInsert/Remove keyed by endpoint).
#pragma once

#include <map>
#include <mutex>

#include "tbase/endpoint.h"
#include "tnet/socket.h"

namespace tpurpc {

class InputMessenger;

class SocketMap {
public:
    static SocketMap* singleton();

    // Get (or create, connect-on-first-write) the shared socket to `remote`
    // whose input is handled by `messenger`. Returns 0 and sets *id.
    int GetOrCreate(const EndPoint& remote, InputMessenger* messenger,
                    SocketId* id);
    // Drop the cached socket (e.g. after SetFailed).
    void Remove(const EndPoint& remote, SocketId expected_id);

private:
    std::mutex mu_;
    std::map<EndPoint, SocketId> map_;
};

}  // namespace tpurpc
