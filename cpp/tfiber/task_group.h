// TaskGroup: one per worker pthread — local run queue + scheduling loop.
// TaskControl: the global scheduler owning all groups.
//
// Modeled on reference src/bthread/task_group.{h,cpp} (run_main_task
// task_group.cpp:199, sched_to :703, ready_to_run[_remote] task_group.h:184)
// and src/bthread/task_control.{h,cpp} (steal_task :528, signal_task :564).
//
// Scheduling model (simplified vs the reference, same semantics): every
// worker has a "main context" (the pthread stack). Fibers always switch
// back to the main context when they yield/park/end; the main loop then runs
// the pending `remained` closure (the publish-after-switch hook that makes
// butex parking race-free) and picks the next fiber.
#pragma once

#include <atomic>
#include <deque>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

#include "tfiber/context.h"
#include "tbase/mpmc_queue.h"
#include "tfiber/parking_lot.h"
#include "tfiber/task_meta.h"
#include "tfiber/work_stealing_queue.h"

namespace tpurpc {

class TaskControl;
class IntCell;

class TaskGroup {
public:
    explicit TaskGroup(TaskControl* control, int index);

    // The worker pthread body.
    void run_main_task();

    // Called from fibers running on this group's worker:
    void yield();                      // requeue self, run others
    void sched_park();                 // switch out; `remained` publishes us
    // The publish-after-switch hook. Raw fn+arg (not std::function) so the
    // scheduler's hottest path never heap-allocates; `arg` typically lives
    // on the parked fiber's stack, which outlives the hook by construction
    // (reference task_group.h set_remained has the same shape).
    void set_remained(void (*fn)(void*), void* arg) {
        remained_fn_ = fn;
        remained_arg_ = arg;
    }
    void exit_current();               // current fiber is done (never returns)

    // Enqueue a ready fiber from this worker thread.
    void ready_to_run(TaskMeta* m);

    // Run `m` IMMEDIATELY on this worker and requeue the calling fiber
    // (the reference's run-new-bthread-now start_foreground path,
    // src/bthread/task_group.cpp sched_to) — must be called from the
    // currently running fiber of this group.
    void run_urgent(TaskMeta* m);

    TaskMeta* current() const { return cur_meta_; }
    int index() const { return index_; }
    TaskControl* control() const { return control_; }

    // Steal interface for other groups.
    bool steal(TaskMeta** m) { return rq_.steal(m); }

    static TaskGroup* tls_group();

    // Entry point of every fiber stack (public: stack.cc needs its address).
    static void fiber_entry(void* arg);

private:
    friend class TaskControl;

    TaskMeta* wait_task();             // pop/steal/park until a task or stop
    void sched_to(TaskMeta* next);     // main context -> fiber

    TaskControl* control_;
    int index_;
    WorkStealingQueue<TaskMeta*> rq_;
    fcontext_t main_ctx_ = nullptr;
    TaskMeta* next_meta_ = nullptr;  // urgent handoff: run before queues
    TaskMeta* cur_meta_ = nullptr;
    void (*remained_fn_)(void*) = nullptr;
    void* remained_arg_ = nullptr;
    bool cur_ended_ = false;
    uint64_t steal_seed_;
    ParkingLot::State park_state_{0};
    // Worker pthread stack bounds + fake-stack handle (ASan fiber-switch
    // annotations).
    const void* worker_stack_base_ = nullptr;
    size_t worker_stack_size_ = 0;
    void* worker_asan_fake_ = nullptr;
};

class TaskControl {
public:
    static TaskControl* singleton();
    // Worker tags (reference bthread_tag_t, types.h:37-39): tag 0 is the
    // default pool above; nonzero tags get their OWN isolated worker
    // pool (queues, parking lot, workers) so latency-critical traffic
    // cannot be starved by bulk work sharing the default pool. Pools are
    // created on first use and live for the process.
    static TaskControl* of_tag(int tag);
    // Enumerate all live pools (default + tagged) for introspection.
    static void ForEachPool(void (*fn)(int tag, TaskControl* c, void* arg),
                            void* arg);

    // Idempotent; starts `concurrency` workers on first call.
    void ensure_started();
    // Before start: sets the initial worker count. After start: grows the
    // pool by starting additional workers (shrinking is not supported,
    // matching the reference's add_workers-only semantics).
    void set_concurrency(int n);
    int concurrency() const {
        return (int)ngroup_.load(std::memory_order_acquire);
    }

    // Enqueue from any thread (worker: local queue; other: remote queue).
    void ready_to_run(TaskMeta* m);
    // Push to the shared remote queue (non-worker producers).
    void ready_to_run_remote(TaskMeta* m);

    bool steal_task(TaskMeta** m, uint64_t* seed, int exclude_index);
    bool pop_remote(TaskMeta** m);

    // Currently-running fibers of this pool (racy snapshot; TaskTracer
    // diagnostics only).
    void CollectRunning(std::vector<const TaskMeta*>* out) const {
        const size_t n = ngroup_.load(std::memory_order_acquire);
        for (size_t i = 0; i < n; ++i) {
            const TaskMeta* m = groups_[i]->current();
            if (m != nullptr) out->push_back(m);
        }
    }

    ParkingLot& parking_lot() { return parking_lot_; }
    bool stopped() const { return stopped_.load(std::memory_order_acquire); }
    void stop_and_join();

    // ---- scheduler telemetry (ISSUE 6; the /loops builtin) ----
    // Labelled families rpc_scheduler_{steals,remote_overflows,
    // urgent_handoffs,runqueue_highwater}{pool="tag"}. Cells are created
    // at pool start; the hot paths update through raw pointers (relaxed
    // atomics) and are no-ops before then.
    int64_t steals() const;
    int64_t remote_overflows() const;
    int64_t urgent_handoffs() const;
    int64_t runqueue_highwater() const;
    void reset_runqueue_highwater();  // /loops?reset=1

    std::atomic<int64_t> nfibers{0};  // live fibers (metrics)

private:
    TaskControl();

    // Post-start growth: groups_ is a fixed array so steal_task can scan
    // it lock-free while add_workers appends; ngroup_ is bumped (release)
    // only after the new group is fully constructed.
    static constexpr size_t kMaxGroups = 128;

    void add_workers_locked(int n);  // start_mu_ held

    std::atomic<bool> started_{false};
    std::atomic<bool> stopped_{false};
    std::mutex start_mu_;
    TaskGroup* groups_[kMaxGroups] = {};
    std::atomic<size_t> ngroup_{0};
    std::vector<std::thread> workers_;
    // Remote queue: lock-free ring; overflow spills to a mutexed list
    // (overflow_size_ lets consumers skip the lock when empty).
    MpmcBoundedQueue<TaskMeta*> remote_ring_;
    std::mutex overflow_mu_;
    std::deque<TaskMeta*> overflow_q_;
    std::atomic<size_t> overflow_size_{0};
    ParkingLot parking_lot_;
    int tag_ = 0;  // worker tag of this pool
    // Telemetry cells (null until ensure_started creates this pool's
    // label tuple).
    IntCell* steals_cell_ = nullptr;
    IntCell* remote_overflow_cell_ = nullptr;
    IntCell* urgent_cell_ = nullptr;
    IntCell* rq_highwater_cell_ = nullptr;

    friend class TaskGroup;
};

// ---- internal helpers shared with butex/fiber impl ----
TaskMeta* fiber_meta_of(fiber_t tid);         // nullptr if stale
void fiber_requeue(fiber_t tid);              // ready_to_run if still alive
void fiber_requeue_meta(TaskMeta* m);

// Park hooks (ISSUE 7): run by sched_park on the parking fiber, BEFORE
// the context switch. Upper layers (tnet) that keep thread-local
// batching state across a dispatch round register a flush here so a
// fiber that parks mid-round can never strand that state on the old
// thread. Registration is idempotent per fn and must happen before the
// state is first armed; hooks are process-lifetime.
void register_park_hook(void (*fn)());
void run_park_hooks();

// Batched parking-lot signals (ISSUE 7): while a batcher is armed on the
// current thread, every ready_to_run defers its futex wake into the
// batcher; Flush() issues ONE signal(n) per pool. The input messenger
// arms one per readiness burst, so completing 64 RPC responses costs one
// futex syscall instead of 64. Queues are pushed eagerly — only the
// *wakeup* of parked workers is batched, so running workers still steal
// mid-round; a flush is bounded by one cut round.
//
// Safety: TaskGroup::sched_park flushes-and-detaches the armed batcher
// before any fiber switch — a park mid-round can never strand deferred
// signals on the old thread.
class WakeBatcher {
public:
    WakeBatcher();   // arms on this thread (no-op when nested)
    ~WakeBatcher();  // Flush + disarm
    WakeBatcher(const WakeBatcher&) = delete;
    WakeBatcher& operator=(const WakeBatcher&) = delete;

    // Signal everything accumulated; stays armed for the next round.
    void Flush();

    // Called by the scheduler's wake paths: true = the signal was
    // absorbed into the active batcher; false = caller must signal now.
    static bool TryBatch(TaskControl* c, int n);
    // sched_park hook: flush + detach the batcher armed on this thread.
    static void FlushCurrent();

private:
    static constexpr int kMaxPools = 4;
    TaskControl* pools_[kMaxPools];
    int counts_[kMaxPools];
    int npools_ = 0;
    bool armed_ = false;
};

}  // namespace tpurpc
