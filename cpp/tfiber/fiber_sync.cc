#include "tfiber/fiber_sync.h"

#include <cerrno>

#include "tbase/time.h"
#include "tfiber/contention_profiler.h"

namespace tpurpc {

// ---------------- FiberMutex ----------------

FiberMutex::FiberMutex() { butex_ = butex_create(); }
FiberMutex::~FiberMutex() { butex_destroy(butex_); }

bool FiberMutex::try_lock() {
    std::atomic<int>* w = butex_word(butex_);
    int expected = 0;
    return w->compare_exchange_strong(expected, 1, std::memory_order_acquire,
                                      std::memory_order_relaxed);
}

void FiberMutex::lock() {
    std::atomic<int>* w = butex_word(butex_);
    int expected = 0;
    if (w->compare_exchange_strong(expected, 1, std::memory_order_acquire,
                                   std::memory_order_relaxed)) {
        return;
    }
    // Contended: advertise waiters (state 2) and park. The wait is
    // charged to the caller's PC for /hotspots/contention (reference
    // bthread/mutex.cpp contention hooks) — only this slow path pays.
    const int64_t t0 = monotonic_time_us();
    while (w->exchange(2, std::memory_order_acquire) != 0) {
        butex_wait(butex_, 2, nullptr);
    }
    RecordContention((uintptr_t)__builtin_return_address(0),
                     monotonic_time_us() - t0);
}

void FiberMutex::unlock() {
    std::atomic<int>* w = butex_word(butex_);
    const int prev = w->exchange(0, std::memory_order_release);
    if (prev == 2) {
        butex_wake(butex_);
    }
}

// ---------------- FiberCond ----------------

FiberCond::FiberCond() { butex_ = butex_create(); }
FiberCond::~FiberCond() { butex_destroy(butex_); }

void FiberCond::wait(FiberMutex& mu) { wait_until(mu, 0); }

int FiberCond::wait_until(FiberMutex& mu, int64_t abstime_us) {
    std::atomic<int>* seq = butex_word(butex_);
    const int expected = seq->load(std::memory_order_acquire);
    mu.unlock();
    int rc = 0;
    const int64_t* abs_ptr = abstime_us > 0 ? &abstime_us : nullptr;
    if (butex_wait(butex_, expected, abs_ptr) == ETIMEDOUT) {
        rc = ETIMEDOUT;
    }
    mu.lock();
    return rc;
}

void FiberCond::notify_one() {
    butex_word(butex_)->fetch_add(1, std::memory_order_release);
    butex_wake(butex_);
}

void FiberCond::notify_all() {
    butex_word(butex_)->fetch_add(1, std::memory_order_release);
    butex_wake_all(butex_);
}

// ---------------- CountdownEvent ----------------

CountdownEvent::CountdownEvent(int initial) {
    butex_ = butex_create();
    butex_word(butex_)->store(initial, std::memory_order_relaxed);
}

CountdownEvent::~CountdownEvent() { butex_destroy(butex_); }

void CountdownEvent::signal(int n) {
    std::atomic<int>* w = butex_word(butex_);
    const int prev = w->fetch_sub(n, std::memory_order_release);
    if (prev - n <= 0) {
        butex_wake_all(butex_);
    }
}

void CountdownEvent::add_count(int n) {
    butex_word(butex_)->fetch_add(n, std::memory_order_release);
}

void CountdownEvent::reset(int n) {
    butex_word(butex_)->store(n, std::memory_order_release);
}

int CountdownEvent::wait(const int64_t* abstime_us) {
    std::atomic<int>* w = butex_word(butex_);
    while (true) {
        const int v = w->load(std::memory_order_acquire);
        if (v <= 0) return 0;
        if (butex_wait(butex_, v, abstime_us) == ETIMEDOUT) {
            return ETIMEDOUT;
        }
    }
}


// ---------------- FiberRWLock ----------------

FiberRWLock::FiberRWLock() { state_butex_ = butex_create(); }
FiberRWLock::~FiberRWLock() { butex_destroy(state_butex_); }

void FiberRWLock::rdlock() {
    // New readers funnel through writer_mu_: while a writer holds or
    // waits on it, readers queue behind — writer preference.
    writer_mu_.lock();
    std::atomic<int>* w = butex_word(state_butex_);
    while (true) {
        int v = w->load(std::memory_order_acquire);
        if (v >= 0) {
            if (w->compare_exchange_weak(v, v + 1,
                                         std::memory_order_acquire)) {
                break;
            }
        } else {
            butex_wait(state_butex_, v, nullptr);
        }
    }
    writer_mu_.unlock();
}

void FiberRWLock::rdunlock() {
    std::atomic<int>* w = butex_word(state_butex_);
    if (w->fetch_sub(1, std::memory_order_release) == 1) {
        butex_wake_all(state_butex_);  // last reader: wake a parked writer
    }
}

void FiberRWLock::wrlock() {
    writer_mu_.lock();  // serialize writers AND stop new readers
    std::atomic<int>* w = butex_word(state_butex_);
    while (true) {
        int expected = 0;
        if (w->compare_exchange_weak(expected, -1,
                                     std::memory_order_acquire)) {
            return;  // writer_mu_ stays held until wrunlock
        }
        butex_wait(state_butex_, expected, nullptr);
    }
}

void FiberRWLock::wrunlock() {
    butex_word(state_butex_)->store(0, std::memory_order_release);
    butex_wake_all(state_butex_);
    writer_mu_.unlock();
}

// ---------------- FiberOnce ----------------

FiberOnce::FiberOnce() { butex_ = butex_create(); }
FiberOnce::~FiberOnce() { butex_destroy(butex_); }

void FiberOnce::call(void (*fn)()) {
    std::atomic<int>* w = butex_word(butex_);
    while (true) {
        int v = w->load(std::memory_order_acquire);
        if (v == 2) return;  // done
        if (v == 0 &&
            w->compare_exchange_strong(v, 1, std::memory_order_acq_rel)) {
            fn();
            w->store(2, std::memory_order_release);
            butex_wake_all(butex_);
            return;
        }
        if (v == 1) butex_wait(butex_, 1, nullptr);
    }
}

}  // namespace tpurpc
