// Guard-paged, pooled fiber stacks.
// Modeled on reference src/bthread/stack.h:56-75: SMALL/NORMAL/LARGE mmap'd
// stacks with a guard page, pooled for reuse (stack allocation dominates
// fiber-start cost otherwise).
#pragma once

#include <cstddef>

#include "tfiber/context.h"

namespace tpurpc {

enum StackType {
    STACK_TYPE_SMALL = 0,   // 32KB
    STACK_TYPE_NORMAL = 1,  // 256KB (default)
    STACK_TYPE_LARGE = 2,   // 1MB
};

struct StackStorage {
    void* base = nullptr;   // usable low address (above guard page)
    size_t size = 0;        // usable bytes
    int type = STACK_TYPE_NORMAL;
    fcontext_t context = nullptr;  // saved context when suspended
};

// Get a pooled stack of `type`, with its entry context built for `entry`.
// Returns false on mmap failure.
bool get_stack(StackStorage* s, int type, void (*entry)(void*));
void return_stack(StackStorage* s);

size_t stack_size_of(int type);

}  // namespace tpurpc
