// Chase-Lev work-stealing deque: owner pushes/pops at the bottom, thieves
// steal from the top. Modeled on reference
// src/bthread/work_stealing_queue.h:32 (same algorithm, bounded ring).
#pragma once

#include <atomic>
#include <cstddef>

#include "tbase/logging.h"

namespace tpurpc {

template <typename T>
class WorkStealingQueue {
public:
    WorkStealingQueue() : buffer_(nullptr), cap_(0) {}
    ~WorkStealingQueue() { delete[] buffer_; }

    int init(size_t capacity) {
        CHECK((capacity & (capacity - 1)) == 0) << "capacity must be 2^n";
        buffer_ = new T[capacity];
        cap_ = capacity;
        return 0;
    }

    // Owner only. Returns false when full.
    bool push(const T& v) {
        const size_t b = bottom_.load(std::memory_order_relaxed);
        const size_t t = top_.load(std::memory_order_acquire);
        if (b >= t + cap_) return false;
        buffer_[b & (cap_ - 1)] = v;
        bottom_.store(b + 1, std::memory_order_release);
        return true;
    }

    // Owner only.
    bool pop(T* v) {
        const size_t b = bottom_.load(std::memory_order_relaxed);
        size_t t = top_.load(std::memory_order_relaxed);
        if (t >= b) return false;  // empty
        const size_t new_b = b - 1;
        bottom_.store(new_b, std::memory_order_relaxed);
        std::atomic_thread_fence(std::memory_order_seq_cst);
        t = top_.load(std::memory_order_relaxed);
        if (t > new_b) {
            bottom_.store(b, std::memory_order_relaxed);
            return false;
        }
        *v = buffer_[new_b & (cap_ - 1)];
        if (t != new_b) return true;  // more than one item left
        // Last item: race with stealers via CAS on top.
        const bool won = top_.compare_exchange_strong(
            t, t + 1, std::memory_order_seq_cst, std::memory_order_relaxed);
        bottom_.store(b, std::memory_order_relaxed);
        return won;
    }

    // Any thread. The seq_cst fence before (re)reading bottom_ pairs with
    // the fence in pop(): without it a thief can act on a stale bottom and
    // take the element the owner is popping without a CAS (the reference
    // has the same fence, src/bthread/work_stealing_queue.h:115-125).
    bool steal(T* v) {
        size_t t = top_.load(std::memory_order_acquire);
        std::atomic_thread_fence(std::memory_order_seq_cst);
        size_t b = bottom_.load(std::memory_order_acquire);
        while (t < b) {
            *v = buffer_[t & (cap_ - 1)];
            if (top_.compare_exchange_strong(t, t + 1,
                                             std::memory_order_seq_cst,
                                             std::memory_order_relaxed)) {
                return true;
            }
            std::atomic_thread_fence(std::memory_order_seq_cst);
            b = bottom_.load(std::memory_order_acquire);
        }
        return false;
    }

    size_t volatile_size() const {
        const size_t b = bottom_.load(std::memory_order_relaxed);
        const size_t t = top_.load(std::memory_order_relaxed);
        return b > t ? b - t : 0;
    }

    size_t capacity() const { return cap_; }

private:
    std::atomic<size_t> bottom_{1};
    std::atomic<size_t> top_{1};
    T* buffer_;
    size_t cap_;
};

}  // namespace tpurpc
