#include "tfiber/stack.h"

#include <sys/mman.h>
#include <unistd.h>

#include <mutex>
#include <vector>

#include "tbase/logging.h"

// A fiber that finishes leaves via a context switch, so its frames'
// shadow-poisoning epilogues never run; a recycled stack then carries
// stale ASan redzones that flag the next fiber's perfectly valid frames.
// Unpoison the whole usable range on recycle (reference keeps the same
// annotation in src/bthread/stack_inl.h).
#ifndef __has_feature
#define __has_feature(x) 0
#endif
#if defined(__SANITIZE_ADDRESS__) || __has_feature(address_sanitizer)
extern "C" void __asan_unpoison_memory_region(void const volatile* addr,
                                              size_t size);
#define TF_UNPOISON_STACK(base, size) __asan_unpoison_memory_region(base, size)
#else
#define TF_UNPOISON_STACK(base, size) ((void)0)
#endif

namespace tpurpc {

size_t stack_size_of(int type) {
    // ASan redzones inflate every frame several-fold, and its fatal-error
    // reporter runs on the faulting (fiber) stack — undersized stacks turn
    // any report into a nested guard-page fault that truncates it.
    // (Same gcc+clang detection idiom as TF_UNPOISON_STACK above.)
#if defined(__SANITIZE_ADDRESS__) || __has_feature(address_sanitizer)
    constexpr size_t kScale = 8;
#else
    constexpr size_t kScale = 1;
#endif
    switch (type) {
        case STACK_TYPE_SMALL: return kScale * 32 * 1024;
        case STACK_TYPE_LARGE: return kScale * 1024 * 1024;
        default: return kScale * 256 * 1024;
    }
}

namespace {

struct StackPool {
    std::mutex mu;
    std::vector<void*> free_bases;  // low addresses incl. guard page
};

// Intentionally leaked: this was the ONLY static destructor in the whole
// library, and it freed the free_bases vectors at process exit while
// worker/dispatcher/timer threads still start and finish fibers — whose
// return_stack() then pushed into the freed vector buffer (an exit-time
// heap-use-after-free observed under ASan). Process-lifetime threads
// require process-lifetime pools (same rule as every other singleton).
StackPool* const g_pools = new StackPool[3];

constexpr size_t kGuard = 4096;

void* allocate_raw(int type) {
    StackPool& pool = g_pools[type];
    {
        std::lock_guard<std::mutex> g(pool.mu);
        if (!pool.free_bases.empty()) {
            void* base = pool.free_bases.back();
            pool.free_bases.pop_back();
            return base;
        }
    }
    const size_t total = stack_size_of(type) + kGuard;
    void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE,
                     MAP_PRIVATE | MAP_ANONYMOUS | MAP_STACK, -1, 0);
    if (mem == MAP_FAILED) return nullptr;
    // Guard page at the low end (stacks grow down into it -> SIGSEGV
    // instead of silent corruption).
    if (mprotect(mem, kGuard, PROT_NONE) != 0) {
        munmap(mem, total);
        return nullptr;
    }
    return mem;
}

}  // namespace

bool get_stack(StackStorage* s, int type, void (*entry)(void*)) {
    void* raw = allocate_raw(type);
    if (raw == nullptr) return false;
    s->base = (char*)raw + kGuard;
    s->size = stack_size_of(type);
    s->type = type;
    s->context = tf_make_fcontext(s->base, s->size, entry);
    return true;
}

void return_stack(StackStorage* s) {
    if (s->base == nullptr) return;
    TF_UNPOISON_STACK(s->base, s->size);
    void* raw = (char*)s->base - kGuard;
    StackPool& pool = g_pools[s->type];
    std::lock_guard<std::mutex> g(pool.mu);
    if (pool.free_bases.size() < 64) {
        pool.free_bases.push_back(raw);
    } else {
        munmap(raw, stack_size_of(s->type) + kGuard);
    }
    s->base = nullptr;
    s->context = nullptr;
}

}  // namespace tpurpc
