// TimerThread: one dedicated pthread running scheduled callbacks (RPC
// timeouts, backup-request timers, fiber sleeps).
// Modeled on reference src/bthread/timer_thread.h:53-82 (schedule /
// unschedule); unschedule guarantees that on return the callback is either
// cancelled or has finished running — the property butex timed-wait relies
// on to keep stack-allocated waiters safe.
#pragma once

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <map>
#include <mutex>
#include <thread>

namespace tpurpc {

using TimerId = uint64_t;
constexpr TimerId INVALID_TIMER_ID = 0;

class TimerThread {
public:
    static TimerThread* singleton();

    // Run fn(arg) at absolute microsecond time `abstime_us`
    // (monotonic_time_us clock). Returns a TimerId.
    TimerId schedule(void (*fn)(void*), void* arg, int64_t abstime_us);

    // Cancel. Returns 0 if cancelled before running; 1 if it already ran or
    // was running; -1 if unknown. With wait_running (the default) a call
    // BLOCKS until an in-flight callback completes — the guarantee butex
    // timed-waits need for stack-allocated waiters. Pass false for
    // fire-and-forget cancels whose callbacks hold only values (RPC
    // timeout timers carry CallId values, never pointers).
    int unschedule(TimerId id, bool wait_running = true);

    void stop_and_join();

private:
    TimerThread();
    ~TimerThread() = default;
    void Run();

    struct Task {
        void (*fn)(void*);
        void* arg;
        TimerId id;
    };

    std::mutex mu_;
    std::condition_variable cv_;
    std::condition_variable run_done_cv_;
    std::multimap<int64_t, Task> tasks_;
    // id -> position, so unschedule is O(log n) instead of a full scan
    // (every timed wait that completes early cancels its timer).
    std::map<TimerId, std::multimap<int64_t, Task>::iterator> by_id_;
    TimerId next_id_ = 1;
    TimerId running_id_ = 0;
    bool stopped_ = false;
    std::thread thread_;
};

}  // namespace tpurpc
