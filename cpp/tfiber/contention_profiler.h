// Fiber-mutex contention profiler: every CONTENDED FiberMutex::lock
// records its call site + wait time into a fixed lock-free table; the
// /hotspots/contention portal page renders the symbolized top sites.
//
// Reference parity: the bthread mutex contention profiler
// (src/bthread/mutex.cpp contention hooks feeding
// builtin/hotspots_service.cpp's contention view). Recording costs one
// hash probe + two atomic adds, and only on the already-slow contended
// path — uncontended locks never touch it.
#pragma once

#include <cstddef>
#include <cstdint>
#include <string>

namespace tpurpc {

// Called by FiberMutex::lock after a contended acquisition.
void RecordContention(uintptr_t site_pc, int64_t wait_us);

// Symbolized text report of the top-N wait sites (plus totals).
std::string ContentionProfileText(size_t topn = 30);

// Same data as JSON (the /hotspots/contention?format=json view):
// {"total_count":N,"total_wait_us":N,"other_count":N,
//  "sites":[{"site":"sym","count":N,"wait_us":N},...]}.
std::string ContentionProfileJson(size_t topn = 30);

// Zero all counters (each /hotspots/contention view starts a fresh
// observation window).
void ResetContentionProfile();

}  // namespace tpurpc
