// ParkingLot: where idle workers sleep and task submitters wake them.
// Modeled on reference src/bthread/parking_lot.h — a futex word whose value
// changes on every signal, so a worker that re-checks queues between
// reading the word and parking can never miss a wakeup.
#pragma once

#include "tfiber/sys_futex.h"

namespace tpurpc {

class ParkingLot {
public:
    struct State {
        int val;
    };

    // Read current state; pass to wait() so an intervening signal aborts
    // the park.
    State get_state() {
        return State{pending_signal_.load(std::memory_order_acquire)};
    }

    void signal(int num_task) {
        pending_signal_.fetch_add((num_task << 1), std::memory_order_release);
        futex_wake_private(&pending_signal_, num_task);
    }

    // Park until signalled (or 100ms safety timeout).
    void wait(const State& expected) {
        timespec ts{0, 100 * 1000 * 1000};
        futex_wait_private(&pending_signal_, expected.val, &ts);
    }

    void stop() {
        pending_signal_.fetch_or(1, std::memory_order_release);
        futex_wake_private(&pending_signal_, 1 << 30);
    }

private:
    // Bit 0: stopped flag; upper bits: signal counter.
    std::atomic<int> pending_signal_{0};
};

}  // namespace tpurpc
