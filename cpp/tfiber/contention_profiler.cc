#include "tfiber/contention_profiler.h"

#include <algorithm>
#include <atomic>
#include <cstdio>
#include <vector>

#include "tbase/symbolize.h"

namespace tpurpc {

namespace {

// Open-addressed fixed table keyed by call-site PC. Collisions past the
// probe limit fall into the overflow slot (reported as "other").
constexpr size_t kSlots = 512;  // power of two
constexpr size_t kProbes = 8;

struct Slot {
    std::atomic<uintptr_t> pc{0};
    std::atomic<int64_t> count{0};
    std::atomic<int64_t> wait_us{0};
};

Slot g_slots[kSlots];
Slot g_overflow;

}  // namespace

void RecordContention(uintptr_t site_pc, int64_t wait_us) {
    size_t h = (site_pc >> 2) * 0x9E3779B97F4A7C15ull;
    for (size_t i = 0; i < kProbes; ++i) {
        Slot& s = g_slots[(h + i) & (kSlots - 1)];
        uintptr_t cur = s.pc.load(std::memory_order_acquire);
        if (cur == 0) {
            // Claim; a racer claiming the same slot for a different pc
            // just moves on to the next probe.
            if (!s.pc.compare_exchange_strong(cur, site_pc,
                                              std::memory_order_acq_rel)) {
                if (cur != site_pc) continue;
            }
            cur = site_pc;
        }
        if (cur == site_pc) {
            s.count.fetch_add(1, std::memory_order_relaxed);
            s.wait_us.fetch_add(wait_us, std::memory_order_relaxed);
            return;
        }
    }
    g_overflow.count.fetch_add(1, std::memory_order_relaxed);
    g_overflow.wait_us.fetch_add(wait_us, std::memory_order_relaxed);
}

std::string ContentionProfileText(size_t topn) {
    struct Row {
        uintptr_t pc;
        int64_t count;
        int64_t wait_us;
    };
    std::vector<Row> rows;
    int64_t total_count = 0, total_wait = 0;
    for (Slot& s : g_slots) {
        const uintptr_t pc = s.pc.load(std::memory_order_acquire);
        if (pc == 0) continue;
        const int64_t c = s.count.load(std::memory_order_relaxed);
        const int64_t w = s.wait_us.load(std::memory_order_relaxed);
        if (c == 0) continue;
        rows.push_back({pc, c, w});
        total_count += c;
        total_wait += w;
    }
    const int64_t oc = g_overflow.count.load(std::memory_order_relaxed);
    total_count += oc;
    total_wait += g_overflow.wait_us.load(std::memory_order_relaxed);
    std::sort(rows.begin(), rows.end(), [](const Row& a, const Row& b) {
        return a.wait_us > b.wait_us;
    });
    if (rows.size() > topn) rows.resize(topn);
    std::string out;
    char line[512];
    snprintf(line, sizeof(line),
             "fiber-mutex contention: %lld contended acquisitions, "
             "%lld us total wait\n\n%12s %14s  %s\n",
             (long long)total_count, (long long)total_wait, "count",
             "wait_us", "lock call site");
    out += line;
    for (const Row& r : rows) {
        snprintf(line, sizeof(line), "%12lld %14lld  %s\n",
                 (long long)r.count, (long long)r.wait_us,
                 SymbolizePc(r.pc).c_str());
        out += line;
    }
    if (oc > 0) {
        snprintf(line, sizeof(line), "%12lld %14s  (other sites)\n",
                 (long long)oc, "-");
        out += line;
    }
    return out;
}

std::string ContentionProfileJson(size_t topn) {
    struct Row {
        uintptr_t pc;
        int64_t count;
        int64_t wait_us;
    };
    std::vector<Row> rows;
    int64_t total_count = 0, total_wait = 0;
    for (Slot& s : g_slots) {
        const uintptr_t pc = s.pc.load(std::memory_order_acquire);
        if (pc == 0) continue;
        const int64_t c = s.count.load(std::memory_order_relaxed);
        const int64_t w = s.wait_us.load(std::memory_order_relaxed);
        if (c == 0) continue;
        rows.push_back({pc, c, w});
        total_count += c;
        total_wait += w;
    }
    const int64_t oc = g_overflow.count.load(std::memory_order_relaxed);
    total_count += oc;
    total_wait += g_overflow.wait_us.load(std::memory_order_relaxed);
    std::sort(rows.begin(), rows.end(), [](const Row& a, const Row& b) {
        return a.wait_us > b.wait_us;
    });
    if (rows.size() > topn) rows.resize(topn);
    std::string out;
    char line[512];
    snprintf(line, sizeof(line),
             "{\"total_count\": %lld, \"total_wait_us\": %lld, "
             "\"other_count\": %lld, \"sites\": [",
             (long long)total_count, (long long)total_wait, (long long)oc);
    out += line;
    bool first = true;
    for (const Row& r : rows) {
        std::string sym = SymbolizePc(r.pc);
        // Symbol names may carry quotes/backslashes in pathological
        // cases; escape minimally so the document stays valid JSON.
        std::string esc;
        for (char c : sym) {
            if (c == '"' || c == '\\') esc.push_back('\\');
            if ((unsigned char)c >= 0x20) esc.push_back(c);
        }
        snprintf(line, sizeof(line),
                 "%s{\"site\": \"%s\", \"count\": %lld, \"wait_us\": %lld}",
                 first ? "" : ", ", esc.c_str(), (long long)r.count,
                 (long long)r.wait_us);
        out += line;
        first = false;
    }
    out += "]}";
    return out;
}

void ResetContentionProfile() {
    // Counters only — the pc claims stay. Zeroing pc would let a racing
    // recorder (which already matched this slot) add its wait to a slot
    // a DIFFERENT call site then claims, misattributing the time. Sites
    // are bounded (kSlots) and long-lived by nature, so keeping claims
    // costs nothing.
    for (Slot& s : g_slots) {
        s.count.store(0, std::memory_order_relaxed);
        s.wait_us.store(0, std::memory_order_relaxed);
    }
    g_overflow.count.store(0, std::memory_order_relaxed);
    g_overflow.wait_us.store(0, std::memory_order_relaxed);
}

}  // namespace tpurpc
