#include "tfiber/fiber_key.h"
#include "tfiber/task_group.h"

#include <pthread.h>
#include <unistd.h>

#include <algorithm>
#include <map>
#include <cerrno>
#include <thread>

#include "tbase/fast_rand.h"
#include "tbase/time.h"
#include "tbase/flags.h"
#include "tbase/flight_recorder.h"
#include "tbase/logging.h"
#include "tbase/resource_pool.h"
#include "tfiber/butex.h"
#include "tfiber/timer_thread.h"
#include "tvar/multi_dimension.h"
#include "tvar/reducer.h"

// 0 = auto: hardware_concurrency + 1, min 4 (the reference defaults to
// cores+1 via FLAGS_bthread_concurrency; a fixed count would cap
// throughput on many-core TPU-VM hosts).
DEFINE_int32(fiber_worker_count, 0, "number of fiber worker pthreads");
DEFINE_int32(fiber_tagged_worker_count, 2,
             "worker pthreads per nonzero worker tag pool");

namespace tpurpc {

namespace {
thread_local TaskGroup* tls_task_group = nullptr;

// Scheduler telemetry families, one series per worker pool
// ({pool="tag"}). Created on first pool start (runtime, never
// static-init); the /loops builtin and the series rings read them.
LabelledMetric<IntCell>* sched_steals() {
    static auto* m =
        new LabelledMetric<IntCell>("rpc_scheduler_steals", {"pool"});
    return m;
}
LabelledMetric<IntCell>* sched_remote_overflows() {
    static auto* m = new LabelledMetric<IntCell>(
        "rpc_scheduler_remote_overflows", {"pool"});
    return m;
}
LabelledMetric<IntCell>* sched_urgent() {
    static auto* m = new LabelledMetric<IntCell>(
        "rpc_scheduler_urgent_handoffs", {"pool"});
    return m;
}
LabelledMetric<IntCell>* sched_rq_highwater() {
    static auto* m = new LabelledMetric<IntCell>(
        "rpc_scheduler_runqueue_highwater", {"pool"});
    return m;
}
}  // namespace

TaskGroup* TaskGroup::tls_group() { return tls_task_group; }

bool is_running_on_fiber_worker() {
    TaskGroup* g = tls_task_group;
    return g != nullptr && g->current() != nullptr;
}

// ---------------- ASan fiber-switch annotations ----------------
// Without these, ASan keeps using the OLD stack's bounds after a context
// switch and reports wild stack-buffer-underflow/overflow (the reference
// carries the same annotations in src/bthread/stack_inl.h).
// The fake-stack handle of each context must be saved at switch-out and
// handed back at switch-in (a null save tells ASan the context is DYING
// and frees its fake frames — only exit_current may pass null).
#ifndef __has_feature
#define __has_feature(x) 0  // gcc signals ASan via __SANITIZE_ADDRESS__
#endif
#if defined(__SANITIZE_ADDRESS__) || __has_feature(address_sanitizer)
extern "C" {
void __sanitizer_start_switch_fiber(void** fake_stack_save,
                                    const void* bottom, size_t size);
void __sanitizer_finish_switch_fiber(void* fake_stack_save,
                                     const void** bottom_old,
                                     size_t* size_old);
}
static void asan_before_jump(void** fake_save, const void* bottom,
                             size_t size) {
    __sanitizer_start_switch_fiber(fake_save, bottom, size);
}
static void asan_after_jump(void* fake_restore) {
    __sanitizer_finish_switch_fiber(fake_restore, nullptr, nullptr);
}
#else
static void asan_before_jump(void**, const void*, size_t) {}
static void asan_after_jump(void*) {}
#endif

// ---------------- TaskGroup ----------------

TaskGroup::TaskGroup(TaskControl* control, int index)
    : control_(control), index_(index), steal_seed_(fast_rand() | 1) {
    CHECK_EQ(rq_.init(1024), 0);
}

void TaskGroup::run_main_task() {
    tls_task_group = this;
    {
        pthread_attr_t attr;
        if (pthread_getattr_np(pthread_self(), &attr) == 0) {
            void* base = nullptr;
            size_t size = 0;
            pthread_attr_getstack(&attr, &base, &size);
            worker_stack_base_ = base;
            worker_stack_size_ = size;
            pthread_attr_destroy(&attr);
        }
    }
    while (true) {
        TaskMeta* m = wait_task();
        if (m == nullptr) break;  // stopped
        sched_to(m);
        // Back on the main context: first run the publish-after-switch
        // hook of the fiber that just switched out (butex parking, yield
        // requeue) — it must run before we pick another task.
        if (remained_fn_ != nullptr) {
            void (*fn)(void*) = remained_fn_;
            void* arg = remained_arg_;
            remained_fn_ = nullptr;
            remained_arg_ = nullptr;
            fn(arg);
        }
        if (cur_ended_) {
            // The fiber finished: recycle stack + slot, wake joiners.
            TaskMeta* dead = cur_meta_;
            cur_meta_ = nullptr;
            cur_ended_ = false;
            return_stack(&dead->stack);
            std::atomic<int>* vb = butex_word(dead->version_butex);
            const fiber_t dead_tid = dead->tid;
            vb->fetch_add(1, std::memory_order_release);
            butex_wake_all(dead->version_butex);
            control_->nfibers.fetch_sub(1, std::memory_order_relaxed);
            return_resource<TaskMeta>((ResourceId)((dead_tid & 0xffffffff) - 1));
        } else {
            cur_meta_ = nullptr;
        }
    }
}

TaskMeta* TaskGroup::wait_task() {
    while (true) {
        // Urgent handoff runs before any queue: run_urgent parked its
        // caller with `next_meta_` armed; the requeue hook has already
        // republished the caller by the time we get here.
        if (next_meta_ != nullptr) {
            TaskMeta* m = next_meta_;
            next_meta_ = nullptr;
            return m;
        }
        if (control_->stopped()) return nullptr;
        TaskMeta* m = nullptr;
        if (rq_.pop(&m)) return m;
        if (control_->pop_remote(&m)) return m;
        if (control_->steal_task(&m, &steal_seed_, index_)) return m;
        const ParkingLot::State st = control_->parking_lot().get_state();
        // Re-check after reading the state so a concurrent signal is never
        // missed (the futex value would have changed).
        if (rq_.pop(&m) || control_->pop_remote(&m) ||
            control_->steal_task(&m, &steal_seed_, index_)) {
            return m;
        }
        control_->parking_lot().wait(st);
    }
}

void TaskGroup::sched_to(TaskMeta* next) {
    cur_meta_ = next;
    cur_ended_ = false;
    asan_before_jump(&worker_asan_fake_, next->stack.base,
                     next->stack.size);
    tf_jump_fcontext(&main_ctx_, next->stack.context, next);
    asan_after_jump(worker_asan_fake_);
}

void TaskGroup::fiber_entry(void* arg) {
    TaskMeta* m = (TaskMeta*)arg;
    asan_after_jump(m->asan_fake);
    m->ret = m->fn(m->arg);
    // Fiber-local storage: run dtors + recycle the keytable (reference
    // key.cpp return_keytable at task_runner end).
    if (m->local_storage != nullptr) {
        fiber_internal::return_keytable(m->local_storage);
        m->local_storage = nullptr;
    }
    TaskGroup::tls_group()->exit_current();
}

void TaskGroup::exit_current() {
    cur_ended_ = true;
    TaskMeta* m = cur_meta_;
    // null save: the fiber context dies here; ASan frees its fake frames.
    asan_before_jump(nullptr, worker_stack_base_, worker_stack_size_);
    tf_jump_fcontext(&m->stack.context, main_ctx_, nullptr);
    CHECK(false) << "dead fiber resumed";
}

// errno is thread-local, but a parked fiber can resume on a DIFFERENT
// worker — and the compiler may legally CSE __errno_location() (declared
// const) across the context switch, reading/writing the OLD worker's
// errno after resume. Make errno effectively fiber-local by saving it
// around the switch (reference task_group.cpp:711-712,794-795 "Save errno
// so that errno is bthread-specific"), through noinline helpers so the
// location is recomputed on the resuming thread.
__attribute__((noinline)) static int read_errno_here() { return errno; }
__attribute__((noinline)) static void write_errno_here(int v) { errno = v; }

void TaskGroup::sched_park() {
    TaskMeta* m = cur_meta_;
    // A parked fiber may resume on a DIFFERENT pthread: flush + detach
    // the thread-local batching scopes (park hooks first — the write-
    // coalescing flush may spawn fibers whose wake signals then ride the
    // batcher's own flush). Without this, a mid-round park would strand
    // deferred work on the old thread and dangle its thread-local
    // pointers.
    run_park_hooks();
    WakeBatcher::FlushCurrent();
    flight::Record(flight::kSchedPark, (uint64_t)m->tid, 0);
    const int saved_errno = read_errno_here();
    asan_before_jump(&m->asan_fake, worker_stack_base_,
                     worker_stack_size_);
    tf_jump_fcontext(&m->stack.context, main_ctx_, nullptr);
    // Resumed later on possibly a DIFFERENT worker; re-read tls_group —
    // callers must not cache `this` across sched_park (they don't: all
    // callers go through TaskGroup::tls_group()). `m` lives on this fiber
    // stack and is still our own meta.
    asan_after_jump(m->asan_fake);
    write_errno_here(saved_errno);
}

// ---------------- park hooks + wake batching (ISSUE 7) ----------------

namespace {
constexpr int kMaxParkHooks = 4;
std::atomic<void (*)()> g_park_hooks[kMaxParkHooks];
std::atomic<int> g_npark_hooks{0};

thread_local WakeBatcher* g_wake_batcher = nullptr;
}  // namespace

void register_park_hook(void (*fn)()) {
    const int n = g_npark_hooks.load(std::memory_order_acquire);
    for (int i = 0; i < n; ++i) {
        if (g_park_hooks[i].load(std::memory_order_relaxed) == fn) return;
    }
    static std::mutex* mu = new std::mutex;
    std::lock_guard<std::mutex> g(*mu);
    const int cur = g_npark_hooks.load(std::memory_order_relaxed);
    for (int i = 0; i < cur; ++i) {
        if (g_park_hooks[i].load(std::memory_order_relaxed) == fn) return;
    }
    CHECK_LT(cur, kMaxParkHooks) << "too many park hooks";
    g_park_hooks[cur].store(fn, std::memory_order_relaxed);
    g_npark_hooks.store(cur + 1, std::memory_order_release);
}

void run_park_hooks() {
    const int n = g_npark_hooks.load(std::memory_order_acquire);
    for (int i = 0; i < n; ++i) {
        g_park_hooks[i].load(std::memory_order_relaxed)();
    }
}

WakeBatcher::WakeBatcher() {
    if (g_wake_batcher == nullptr) {
        g_wake_batcher = this;
        armed_ = true;
    }
}

WakeBatcher::~WakeBatcher() {
    if (!armed_) return;
    Flush();
    if (g_wake_batcher == this) g_wake_batcher = nullptr;
}

void WakeBatcher::Flush() {
    for (int i = 0; i < npools_; ++i) {
        pools_[i]->parking_lot().signal(counts_[i]);
    }
    npools_ = 0;
}

bool WakeBatcher::TryBatch(TaskControl* c, int n) {
    WakeBatcher* b = g_wake_batcher;
    if (b == nullptr) return false;
    for (int i = 0; i < b->npools_; ++i) {
        if (b->pools_[i] == c) {
            b->counts_[i] += n;
            return true;
        }
    }
    if (b->npools_ >= kMaxPools) return false;
    b->pools_[b->npools_] = c;
    b->counts_[b->npools_] = n;
    ++b->npools_;
    return true;
}

void WakeBatcher::FlushCurrent() {
    WakeBatcher* b = g_wake_batcher;
    if (b == nullptr) return;
    b->Flush();
    b->armed_ = false;
    g_wake_batcher = nullptr;
}

namespace {
void requeue_meta_cb(void* arg) {
    fiber_requeue_meta((TaskMeta*)arg);
}
}  // namespace

void TaskGroup::yield() {
    TaskMeta* m = cur_meta_;
    set_remained(requeue_meta_cb, m);
    sched_park();
}

void TaskGroup::ready_to_run(TaskMeta* m) {
    if (!rq_.push(m)) {
        control_->ready_to_run_remote(m);
        return;
    }
    // Run-queue depth high-water: a sustained climb means admission
    // outruns dispatch (the ROADMAP item-4 signature). One relaxed load
    // + compare in the common (not-a-new-max) case.
    if (control_->rq_highwater_cell_ != nullptr) {
        control_->rq_highwater_cell_->update_max(
            (int64_t)rq_.volatile_size());
    }
    if (!WakeBatcher::TryBatch(control_, 1)) {
        control_->parking_lot().signal(1);
    }
}

void TaskGroup::run_urgent(TaskMeta* m) {
    TaskMeta* self = cur_meta_;
    next_meta_ = m;
    if (control_->urgent_cell_ != nullptr) control_->urgent_cell_->add(1);
    set_remained(requeue_meta_cb, self);
    sched_park();
}

// ---------------- TaskControl ----------------

TaskControl::TaskControl() {
    CHECK_EQ(remote_ring_.init(4096), 0);
}

TaskControl* TaskControl::singleton() {
    static TaskControl* c = new TaskControl;
    return c;
}

// Tags are bounded (reference validates against task_group_ntags the
// same way): each pool is 2+ permanent pthreads, so an unvalidated
// dynamic tag would leak threads without bound. Lock-free fast path via
// a fixed atomic array — spawns on hot tagged pools must not contend on
// a registry mutex.
static constexpr int kMaxWorkerTag = 64;
static std::atomic<TaskControl*> g_tag_pools[kMaxWorkerTag];

TaskControl* TaskControl::of_tag(int tag) {
    if (tag <= 0) {
        LOG_IF(ERROR, tag < 0) << "invalid worker tag " << tag
                               << "; using the default pool";
        return singleton();
    }
    if (tag >= kMaxWorkerTag) {
        LOG(ERROR) << "worker tag " << tag << " out of range (max "
                   << kMaxWorkerTag - 1 << "); using the default pool";
        return singleton();
    }
    TaskControl* c = g_tag_pools[tag].load(std::memory_order_acquire);
    if (c != nullptr) return c;
    static std::mutex* mu = new std::mutex;
    std::lock_guard<std::mutex> g(*mu);
    c = g_tag_pools[tag].load(std::memory_order_relaxed);
    if (c != nullptr) return c;
    c = new TaskControl;
    c->tag_ = tag;
    g_tag_pools[tag].store(c, std::memory_order_release);
    return c;
}

void TaskControl::ForEachPool(void (*fn)(int tag, TaskControl* c,
                                         void* arg),
                              void* arg) {
    fn(0, singleton(), arg);
    for (int t = 1; t < kMaxWorkerTag; ++t) {
        TaskControl* c = g_tag_pools[t].load(std::memory_order_acquire);
        if (c != nullptr) fn(t, c, arg);
    }
}

void TaskControl::ensure_started() {
    if (started_.load(std::memory_order_acquire)) return;
    std::lock_guard<std::mutex> g(start_mu_);
    if (started_.load(std::memory_order_relaxed)) return;
    int concurrency;
    if (tag_ != 0) {
        concurrency = std::max(1, FLAGS_fiber_tagged_worker_count.get());
    } else {
        concurrency = FLAGS_fiber_worker_count.get();
        if (concurrency <= 0) {
            const unsigned hc = std::thread::hardware_concurrency();
            concurrency = (int)std::max(4u, hc + 1);
        }
    }
    // Telemetry cells before the first worker runs: the hot paths
    // null-check but never lock the family mutex.
    const std::string pool = std::to_string(tag_);
    steals_cell_ = sched_steals()->get_stats({pool});
    remote_overflow_cell_ = sched_remote_overflows()->get_stats({pool});
    urgent_cell_ = sched_urgent()->get_stats({pool});
    rq_highwater_cell_ = sched_rq_highwater()->get_stats({pool});
    add_workers_locked(concurrency);
    started_.store(true, std::memory_order_release);
}

int64_t TaskControl::steals() const {
    return steals_cell_ != nullptr ? steals_cell_->get() : 0;
}
int64_t TaskControl::remote_overflows() const {
    return remote_overflow_cell_ != nullptr ? remote_overflow_cell_->get()
                                            : 0;
}
int64_t TaskControl::urgent_handoffs() const {
    return urgent_cell_ != nullptr ? urgent_cell_->get() : 0;
}
int64_t TaskControl::runqueue_highwater() const {
    return rq_highwater_cell_ != nullptr ? rq_highwater_cell_->get() : 0;
}
void TaskControl::reset_runqueue_highwater() {
    if (rq_highwater_cell_ != nullptr) rq_highwater_cell_->set(0);
}

void TaskControl::add_workers_locked(int n) {
    if (stopped_.load(std::memory_order_relaxed)) return;
    for (int i = 0; i < n; ++i) {
        const size_t idx = ngroup_.load(std::memory_order_relaxed);
        if (idx >= kMaxGroups) {
            LOG(ERROR) << "worker pool is at its " << kMaxGroups
                       << "-group capacity";
            return;
        }
        TaskGroup* tg = new TaskGroup(this, (int)idx);
        groups_[idx] = tg;
        // Publish before the worker runs (steal_task scans [0, ngroup)).
        ngroup_.store(idx + 1, std::memory_order_release);
        workers_.emplace_back([tg] { tg->run_main_task(); });
    }
}

void TaskControl::set_concurrency(int n) {
    std::lock_guard<std::mutex> g(start_mu_);
    if (!started_.load(std::memory_order_relaxed)) {
        FLAGS_fiber_worker_count.set(n);
        return;
    }
    // Live growth (reference TaskControl::add_workers): a long-running
    // server can scale its pool up; shrinking is not supported.
    const int cur = (int)ngroup_.load(std::memory_order_relaxed);
    if (n > cur) add_workers_locked(n - cur);
}

void TaskControl::ready_to_run(TaskMeta* m) {
    TaskGroup* g = tls_task_group;
    // The local-queue shortcut is only valid on a worker of THIS pool: a
    // tagged fiber woken from another pool's worker (or a plain pthread)
    // must go through the remote queue of its own pool.
    if (g != nullptr && g->control() == this) {
        g->ready_to_run(m);
    } else {
        ready_to_run_remote(m);
    }
}

void TaskControl::ready_to_run_remote(TaskMeta* m) {
    if (!remote_ring_.push(m)) {
        // Ring full: spill to the mutexed overflow list rather than
        // spinning — fiber spawns must never be dropped or block.
        {
            std::lock_guard<std::mutex> g(overflow_mu_);
            overflow_q_.push_back(m);
            overflow_size_.fetch_add(1, std::memory_order_release);
        }
        if (remote_overflow_cell_ != nullptr) {
            remote_overflow_cell_->add(1);
        }
    }
    if (!WakeBatcher::TryBatch(this, 1)) {
        parking_lot_.signal(1);
    }
}

bool TaskControl::pop_remote(TaskMeta** m) {
    // Ring first: ring entries are OLDER than anything spilled (spills
    // only happen when the ring is full). To keep the spill from
    // starving while the ring stays busy, each successful pop migrates a
    // bounded batch of spilled fibers into the freed ring slots — they
    // land BEHIND the remaining ring entries, preserving rough FIFO,
    // and both queues make progress under sustained saturation.
    if (remote_ring_.pop(m)) {
        if (overflow_size_.load(std::memory_order_acquire) != 0) {
            std::lock_guard<std::mutex> g(overflow_mu_);
            for (int i = 0; i < 64 && !overflow_q_.empty(); ++i) {
                if (!remote_ring_.push(overflow_q_.front())) break;
                overflow_q_.pop_front();
                overflow_size_.fetch_sub(1, std::memory_order_release);
            }
        }
        return true;
    }
    if (overflow_size_.load(std::memory_order_acquire) == 0) return false;
    std::lock_guard<std::mutex> g(overflow_mu_);
    if (overflow_q_.empty()) return false;
    *m = overflow_q_.front();
    overflow_q_.pop_front();
    overflow_size_.fetch_sub(1, std::memory_order_release);
    return true;
}

bool TaskControl::steal_task(TaskMeta** m, uint64_t* seed, int exclude) {
    const size_t n = ngroup_.load(std::memory_order_acquire);
    if (n <= 1) return false;
    // xorshift over group indices, starting at a pseudo-random offset.
    uint64_t s = *seed;
    s ^= s << 13;
    s ^= s >> 7;
    s ^= s << 17;
    *seed = s;
    const size_t start = (size_t)(s % n);
    for (size_t i = 0; i < n; ++i) {
        const size_t idx = (start + i) % n;
        if ((int)idx == exclude) continue;
        if (groups_[idx]->steal(m)) {
            if (steals_cell_ != nullptr) steals_cell_->add(1);
            return true;
        }
    }
    return false;
}

void TaskControl::stop_and_join() {
    // Snapshot the workers under start_mu_ (serializing against
    // set_concurrency growth), but JOIN outside it: a fiber on a worker
    // may itself be blocked in set_concurrency on start_mu_, and joining
    // that worker while holding the lock would deadlock. Once stopped_
    // is set, add_workers_locked refuses to grow, so the snapshot is
    // complete.
    std::vector<std::thread> to_join;
    {
        std::lock_guard<std::mutex> g(start_mu_);
        stopped_.store(true, std::memory_order_release);
        parking_lot_.stop();
        to_join = std::move(workers_);
        workers_.clear();
    }
    for (auto& t : to_join) {
        if (t.joinable()) t.join();
    }
}

// ---------------- fiber API ----------------

TaskMeta* fiber_meta_of(fiber_t tid) {
    if (tid == INVALID_FIBER) return nullptr;
    const ResourceId slot = (ResourceId)((tid & 0xffffffff) - 1);
    TaskMeta* m = address_resource<TaskMeta>(slot);
    if (m == nullptr || m->version_butex == nullptr) return nullptr;
    const uint32_t expect_version = (uint32_t)(tid >> 32);
    if ((uint32_t)butex_word(m->version_butex)
            ->load(std::memory_order_acquire) != expect_version) {
        return nullptr;
    }
    return m;
}

void fiber_requeue_meta(TaskMeta* m) {
    (m->control != nullptr ? m->control : TaskControl::singleton())
        ->ready_to_run(m);
}

void fiber_requeue(fiber_t tid) {
    TaskMeta* m = fiber_meta_of(tid);
    if (m != nullptr) fiber_requeue_meta(m);
}

static int start_fiber_impl(fiber_t* tid, const FiberAttr* attr,
                            void* (*fn)(void*), void* arg,
                            bool urgent = false) {
    TaskControl* c = TaskControl::of_tag(attr != nullptr ? attr->tag : 0);
    c->ensure_started();
    ResourceId slot;
    TaskMeta* m = get_resource<TaskMeta>(&slot);
    if (m == nullptr) return -1;
    if (m->version_butex == nullptr) {
        m->version_butex = butex_create();
    }
    m->version =
        (uint32_t)butex_word(m->version_butex)->load(std::memory_order_relaxed);
    m->fn = fn;
    m->arg = arg;
    m->ret = nullptr;
    m->local_storage = nullptr;  // fresh fiber: no inherited fiber-locals
    // Stale handle from the slot's previous tenant would hand ASan a freed
    // fake stack on this fiber's first switch-in.
    m->asan_fake = nullptr;
    m->stack_type = attr ? attr->stack_type : STACK_TYPE_NORMAL;
    m->control = c;
    m->tid = ((fiber_t)m->version << 32) | (fiber_t)(slot + 1);
    if (!get_stack(&m->stack, m->stack_type, TaskGroup::fiber_entry)) {
        return_resource<TaskMeta>(slot);
        return -1;
    }
    if (tid) *tid = m->tid;
    c->nfibers.fetch_add(1, std::memory_order_relaxed);
    TaskGroup* g = tls_task_group;
    if (urgent && g != nullptr && g->current() != nullptr &&
        g->control() == c) {
        g->run_urgent(m);  // runs m NOW; caller resumes via the queues
    } else {
        c->ready_to_run(m);
    }
    return 0;
}

int fiber_start_background(fiber_t* tid, const FiberAttr* attr,
                           void* (*fn)(void*), void* arg) {
    return start_fiber_impl(tid, attr, fn, arg);
}

int fiber_start_urgent(fiber_t* tid, const FiberAttr* attr, void* (*fn)(void*),
                       void* arg) {
    // Run-new-fiber-immediately (reference task_group.cpp
    // start_foreground → sched_to): the new fiber takes this worker right
    // away and the caller is requeued — the core latency trick for
    // dispatching a just-parsed request before the parser fiber resumes.
    return start_fiber_impl(tid, attr, fn, arg, /*urgent=*/true);
}

int fiber_join(fiber_t tid, void** ret) {
    if (ret) *ret = nullptr;
    if (tid == INVALID_FIBER) return 0;
    if (tid == fiber_self()) return EINVAL;  // self-join would park forever
    const ResourceId slot = (ResourceId)((tid & 0xffffffff) - 1);
    TaskMeta* m = address_resource<TaskMeta>(slot);
    if (m == nullptr || m->version_butex == nullptr) return 0;
    const uint32_t expect_version = (uint32_t)(tid >> 32);
    std::atomic<int>* word = butex_word(m->version_butex);
    while ((uint32_t)word->load(std::memory_order_acquire) == expect_version) {
        butex_wait(m->version_butex, (int)expect_version, nullptr);
    }
    return 0;
}

bool fiber_exists(fiber_t tid) { return fiber_meta_of(tid) != nullptr; }

fiber_t fiber_self() {
    TaskGroup* g = tls_task_group;
    if (g == nullptr || g->current() == nullptr) return INVALID_FIBER;
    return g->current()->tid;
}

void fiber_yield() {
    TaskGroup* g = tls_task_group;
    if (g == nullptr || g->current() == nullptr) {
        std::this_thread::yield();
        return;
    }
    g->yield();
}

namespace {
void usleep_timer_cb(void* arg) { fiber_requeue((fiber_t)(uintptr_t)arg); }

struct SleepArgs {
    fiber_t tid;
    int64_t abstime;
};

void usleep_remained_cb(void* raw) {
    SleepArgs* sa = (SleepArgs*)raw;  // lives on the parked fiber's stack
    TimerThread::singleton()->schedule(usleep_timer_cb,
                                       (void*)(uintptr_t)sa->tid, sa->abstime);
}
}  // namespace

int fiber_usleep(int64_t us) {
    TaskGroup* g = tls_task_group;
    if (g == nullptr || g->current() == nullptr) {
        ::usleep((useconds_t)us);
        return 0;
    }
    TaskMeta* m = g->current();
    SleepArgs sa{m->tid, monotonic_time_us() + us};
    g->set_remained(usleep_remained_cb, &sa);
    g->sched_park();
    return 0;
}

void fiber_set_worker_count(int n) {
    TaskControl::singleton()->set_concurrency(n);
}
int fiber_get_worker_count() {
    return TaskControl::singleton()->concurrency();
}

}  // namespace tpurpc
