#include "tfiber/context.h"

#include <cstdlib>
#include <cstring>

namespace tpurpc {

namespace {
// Safety net: a fresh context's entry function must never return; if it
// does, `ret` lands here.
void fiber_entry_returned() { abort(); }
}  // namespace

#ifndef __has_feature
#define __has_feature(x) 0  // gcc signals ASan via __SANITIZE_ADDRESS__
#endif
#if defined(__SANITIZE_ADDRESS__) || __has_feature(address_sanitizer)
// Writes into a freshly mmap'd fiber stack; ASan misreads it as a stack
// overflow (the switch annotations live in task_group.cc, not here).
__attribute__((no_sanitize_address))
#endif
fcontext_t
tf_make_fcontext(void* stack_base, size_t size, void (*fn)(void*)) {
#if defined(__x86_64__)
    // Stack grows down. Align the top to 16 bytes.
    uintptr_t top = ((uintptr_t)stack_base + size) & ~(uintptr_t)15;
    // Reserve the saved-register frame (0x40 bytes, layout in context.S)
    // plus one slot above rip for the safety-net return address.
    uintptr_t sp = top - 0x48;
    uint64_t* slots = (uint64_t*)sp;
    // mxcsr/x87cw: capture the current thread's control words.
    uint32_t mxcsr;
    uint16_t fcw;
    __asm__ volatile("stmxcsr %0" : "=m"(mxcsr));
    __asm__ volatile("fnstcw %0" : "=m"(fcw));
    slots[0] = (uint64_t)mxcsr | ((uint64_t)fcw << 32);
    slots[1] = 0;  // r12
    slots[2] = 0;  // r13
    slots[3] = 0;  // r14
    slots[4] = 0;  // r15
    slots[5] = 0;  // rbx
    slots[6] = 0;  // rbp
    slots[7] = (uint64_t)(void*)fn;  // rip
    slots[8] = (uint64_t)(void*)fiber_entry_returned;
    return (fcontext_t)sp;
#elif defined(__aarch64__)
    // Layout in context_aarch64.S: 0xa0-byte frame, x30 (resume pc) at
    // +0x98. The entry fn receives the jump's arg in x0 and must never
    // return (x29=0 terminates unwinds; a stray ret jumps to 0 and
    // faults loudly rather than corrupting).
    (void)fiber_entry_returned;
    uintptr_t top = ((uintptr_t)stack_base + size) & ~(uintptr_t)15;
    uintptr_t sp = top - 0xa0;
    uint64_t* slots = (uint64_t*)sp;
    for (int i = 0; i < 0xa0 / 8; ++i) slots[i] = 0;
    slots[0x98 / 8] = (uint64_t)(void*)fn;  // x30: first jump enters fn
    return (fcontext_t)sp;
#else
#error "unsupported architecture: add a context_<arch>.S variant"
#endif
}

}  // namespace tpurpc
