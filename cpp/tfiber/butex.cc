#include "tfiber/butex.h"

#include <cerrno>
#include <mutex>

#include "tbase/logging.h"
#include "tbase/resource_pool.h"
#include "tbase/time.h"
#include "tfiber/sys_futex.h"
#include "tfiber/task_group.h"
#include "tfiber/timer_thread.h"

namespace tpurpc {

namespace {

enum WaiterState : int {
    WAITER_PARKED = 0,
    WAITER_WOKEN = 1,
    WAITER_TIMEDOUT = 2,
    WAITER_CANCELLED = 3,  // value mismatch discovered at publish time
};

struct Butex;

// Lives on the waiting fiber's / pthread's stack. Lifetime: from enqueue
// until the owner resumes; the owner guarantees (via TimerThread::unschedule
// blocking semantics) that no timer callback can still touch it after
// butex_wait returns.
struct ButexWaiter {
    ButexWaiter* next = nullptr;
    ButexWaiter* prev = nullptr;
    Butex* container = nullptr;
    bool is_fiber = false;
    fiber_t tid = INVALID_FIBER;
    TaskMeta* meta = nullptr;
    std::atomic<int> state{WAITER_PARKED};
    std::atomic<int> pthread_word{0};
    TimerId timer_id = INVALID_TIMER_ID;
};

struct Butex {
    std::atomic<int> value{0};
    std::mutex mu;
    // Intrusive doubly-linked list, FIFO wake order.
    ButexWaiter* head = nullptr;
    ButexWaiter* tail = nullptr;
    ResourceId pool_id = 0;  // slot in the butex pool (never unmapped)

    void enqueue(ButexWaiter* w) {
        w->container = this;
        w->next = nullptr;
        w->prev = tail;
        if (tail) {
            tail->next = w;
        } else {
            head = w;
        }
        tail = w;
    }

    // Returns true if w was in the list.
    bool erase(ButexWaiter* w) {
        if (w->container != this) return false;
        if (w->prev) {
            w->prev->next = w->next;
        } else {
            head = w->next;
        }
        if (w->next) {
            w->next->prev = w->prev;
        } else {
            tail = w->prev;
        }
        w->container = nullptr;
        w->next = w->prev = nullptr;
        return true;
    }

    ButexWaiter* pop_front() {
        ButexWaiter* w = head;
        if (w) erase(w);
        return w;
    }
};

void wake_waiter_locked_popped(ButexWaiter* w) {
    // w is already off the list; caller dropped the butex lock.
    if (w->is_fiber) {
        // Set state BEFORE requeue: the fiber may resume instantly on
        // another worker and inspect it.
        w->state.store(WAITER_WOKEN, std::memory_order_release);
        fiber_requeue_meta(w->meta);
    } else {
        w->state.store(WAITER_WOKEN, std::memory_order_release);
        w->pthread_word.store(1, std::memory_order_release);
        futex_wake_private(&w->pthread_word, 1);
    }
}

// Timer callback for timed waits: if the waiter is still enqueued, remove
// and wake it with TIMEDOUT. Runs on the timer thread; synchronized with
// wakers via the butex mutex, and with the waiter's stack lifetime via
// TimerThread::unschedule's blocking guarantee.
struct TimeoutArg {
    Butex* b;
    ButexWaiter* w;
};

void butex_timeout_cb(void* raw) {
    TimeoutArg* ta = (TimeoutArg*)raw;
    Butex* b = ta->b;
    ButexWaiter* w = ta->w;
    {
        std::lock_guard<std::mutex> g(b->mu);
        if (!b->erase(w)) return;  // already woken
        w->state.store(WAITER_TIMEDOUT, std::memory_order_release);
    }
    if (w->is_fiber) {
        fiber_requeue_meta(w->meta);
    } else {
        w->pthread_word.store(1, std::memory_order_release);
        futex_wake_private(&w->pthread_word, 1);
    }
}

// The publish-after-switch hook of the fiber wait path: runs on the main
// context after the fiber has switched out; only then does the waiter become
// visible to wakers.
struct PublishArgs {
    Butex* b;
    ButexWaiter* w;
    TimeoutArg ta;
    bool timed;
    int64_t abstime;
    int expected_value;
};

void publish_waiter_cb(void* raw) {
    PublishArgs* pa = (PublishArgs*)raw;
    Butex* b = pa->b;
    ButexWaiter* w = pa->w;
    std::lock_guard<std::mutex> lk(b->mu);
    if (b->value.load(std::memory_order_relaxed) != pa->expected_value) {
        w->state.store(WAITER_CANCELLED, std::memory_order_release);
        fiber_requeue_meta(w->meta);
        return;
    }
    // Arm the timer BEFORE enqueueing, all under the butex lock: once a
    // waker can pop w, w->timer_id is already set (the resumed fiber reads
    // it), and the timeout callback blocks on this same lock so it cannot
    // run before the enqueue either.
    if (pa->timed) {
        w->timer_id = TimerThread::singleton()->schedule(butex_timeout_cb,
                                                         &pa->ta, pa->abstime);
    }
    b->enqueue(w);
}

int wait_pthread(Butex* b, int expected, const int64_t* abstime_us) {
    ButexWaiter w;
    w.is_fiber = false;
    {
        std::lock_guard<std::mutex> g(b->mu);
        if (b->value.load(std::memory_order_relaxed) != expected) {
            errno = EWOULDBLOCK;
            return EWOULDBLOCK;
        }
        b->enqueue(&w);
    }
    while (w.pthread_word.load(std::memory_order_acquire) == 0) {
        timespec ts;
        timespec* ts_ptr = nullptr;
        if (abstime_us != nullptr) {
            const int64_t now = monotonic_time_us();
            int64_t left = *abstime_us - now;
            if (left <= 0) {
                // Timed out: remove ourselves unless a waker got us first.
                std::unique_lock<std::mutex> g(b->mu);
                if (b->erase(&w)) {
                    errno = ETIMEDOUT;
                    return ETIMEDOUT;
                }
                g.unlock();
                // A waker popped us: it WILL set pthread_word shortly; spin
                // on the futex until it does (keeps &w alive meanwhile).
                while (w.pthread_word.load(std::memory_order_acquire) == 0) {
                    futex_wait_private(&w.pthread_word, 0, nullptr);
                }
                return 0;
            }
            ts.tv_sec = left / 1000000;
            ts.tv_nsec = (left % 1000000) * 1000;
            ts_ptr = &ts;
        }
        futex_wait_private(&w.pthread_word, 0, ts_ptr);
    }
    return 0;
}

}  // namespace

// Butexes live in a ResourcePool whose slots are NEVER unmapped
// (reference butex.cpp uses the same scheme): a waker that lost the race
// with butex_destroy touches a still-mapped, possibly-recycled Butex and
// produces at most a spurious wake (waiters re-check their condition in a
// loop), never a use-after-free. This is what makes the
// "signal() then waiter frees the event" idiom of CountdownEvent and the
// RPC sync paths safe.
void* butex_create() {
    ResourceId id;
    Butex* b = get_resource<Butex>(&id);
    if (b == nullptr) return nullptr;
    b->pool_id = id;
    b->value.store(0, std::memory_order_relaxed);
    return b;
}

void butex_destroy(void* butex) {
    if (butex == nullptr) return;
    Butex* b = (Butex*)butex;
    // Waiter list must already be empty (callers own that invariant: no
    // destroy with parked waiters).
    return_resource<Butex>(b->pool_id);
}

std::atomic<int>* butex_word(void* butex) { return &((Butex*)butex)->value; }

int butex_wait(void* butex, int expected_value, const int64_t* abstime_us) {
    Butex* b = (Butex*)butex;
    if (b->value.load(std::memory_order_acquire) != expected_value) {
        errno = EWOULDBLOCK;
        return EWOULDBLOCK;
    }
    TaskGroup* g = TaskGroup::tls_group();
    if (g == nullptr || g->current() == nullptr) {
        return wait_pthread(b, expected_value, abstime_us);
    }

    // Fiber path. The waiter is published to the butex list only AFTER the
    // fiber has switched off its stack (the `remained` hook runs on the
    // main context) — so a waker can never requeue a fiber that is still
    // running (reference butex.cpp wait_for_butex via set_remained).
    TaskMeta* m = g->current();
    ButexWaiter w;
    w.is_fiber = true;
    w.tid = m->tid;
    w.meta = m;
    // All publish-hook state lives on this (parked) fiber's stack.
    PublishArgs pa;
    pa.b = b;
    pa.w = &w;
    pa.ta = TimeoutArg{b, &w};
    pa.timed = abstime_us != nullptr;
    pa.abstime = abstime_us ? *abstime_us : 0;
    pa.expected_value = expected_value;
    g->set_remained(publish_waiter_cb, &pa);
    g->sched_park();

    // Resumed. If a timer was armed, make sure its callback is not running
    // before the stack-allocated waiter state goes out of scope.
    if (pa.timed && w.timer_id != INVALID_TIMER_ID) {
        TimerThread::singleton()->unschedule(w.timer_id);
    }
    const int st = w.state.load(std::memory_order_acquire);
    if (st == WAITER_TIMEDOUT) {
        errno = ETIMEDOUT;
        return ETIMEDOUT;
    }
    if (st == WAITER_CANCELLED) {
        errno = EWOULDBLOCK;
        return EWOULDBLOCK;
    }
    return 0;
}

int butex_wake(void* butex) {
    Butex* b = (Butex*)butex;
    ButexWaiter* w;
    {
        std::lock_guard<std::mutex> g(b->mu);
        w = b->pop_front();
    }
    if (w == nullptr) return 0;
    wake_waiter_locked_popped(w);
    return 1;
}

int butex_wake_all(void* butex) {
    Butex* b = (Butex*)butex;
    int n = 0;
    while (true) {
        ButexWaiter* w;
        {
            std::lock_guard<std::mutex> g(b->mu);
            w = b->pop_front();
        }
        if (w == nullptr) break;
        wake_waiter_locked_popped(w);
        ++n;
    }
    return n;
}

}  // namespace tpurpc
