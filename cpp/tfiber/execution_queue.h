// ExecutionQueue: MPSC serialized executor — wait-free submission from any
// thread, one consumer fiber that processes items in batches.
//
// Modeled on reference src/bthread/execution_queue.h:31-112
// (execution_queue_start/execute, TaskIterator batching). Used by the
// locality-aware load balancer and streaming RPC's ordered delivery; also a
// public building block.
//
// Implementation: lock-free LIFO stack (single-exchange push) grabbed whole
// by the consumer and reversed to FIFO — the same pattern as Socket's
// wait-free write queue (reference socket.cpp:488,1695). A pending-count
// elects exactly one consumer-fiber run per burst.
#pragma once

#include <atomic>
#include <vector>

#include "tbase/logging.h"
#include "tfiber/fiber.h"
#include "tfiber/fiber_sync.h"

namespace tpurpc {

template <typename T>
class ExecutionQueue {
public:
    class TaskIterator {
    public:
        explicit TaskIterator(std::vector<T>* batch) : batch_(batch), i_(0) {}
        explicit operator bool() const { return i_ < batch_->size(); }
        T& operator*() const { return (*batch_)[i_]; }
        T* operator->() const { return &(*batch_)[i_]; }
        TaskIterator& operator++() {
            ++i_;
            return *this;
        }
        bool is_queue_stopped() const { return stopped_; }

    private:
        friend class ExecutionQueue;
        std::vector<T>* batch_;
        size_t i_;
        bool stopped_ = false;
    };

    // fn(meta, iter): consume the batch; called on a fiber.
    using ExecuteFn = int (*)(void* meta, TaskIterator& iter);

    ExecutionQueue() = default;

    int start(ExecuteFn fn, void* meta) {
        fn_ = fn;
        meta_ = meta;
        return 0;
    }


    // Wait-free-ish from any thread (one atomic exchange + one fetch_add).
    // Returns -1 if stopped.
    int execute(const T& value) {
        if (stopping_.load(std::memory_order_acquire)) return -1;
        Node* n = new Node;
        n->value = value;
        push_node(n);
        if (pending_.fetch_add(1, std::memory_order_acq_rel) == 0) {
            start_consumer();
        }
        return 0;
    }

    // Stop accepting new items; queued items are drained, then an iteration
    // with is_queue_stopped() is delivered.
    int stop() {
        bool expected = false;
        if (!stopping_.compare_exchange_strong(expected, true)) return -1;
        Node* n = new Node;
        n->is_stop_marker = true;
        push_node(n);
        if (pending_.fetch_add(1, std::memory_order_acq_rel) == 0) {
            start_consumer();
        }
        return 0;
    }

    int join() {
        join_event_.wait();
        return 0;
    }

    // Opt-in self-deletion for heap-allocated queues with two owners (the
    // producer-side holder and the consumer run that delivers the stop
    // iteration): each calls release() when done; the second delete()s.
    // Solves the "who frees the queue" problem when the stop-delivered
    // callback may destroy the producer-side holder while the consumer
    // still touches queue members to retire (streaming RPC's rx queue).
    void enable_self_release() { self_release_ = true; }
    void release() {
        if (owners_.fetch_sub(1, std::memory_order_acq_rel) == 1) {
            delete this;
        }
    }

private:
    struct Node {
        std::atomic<Node*> next{unlinked()};
        T value{};
        bool is_stop_marker = false;
    };

    static Node* unlinked() { return (Node*)0x1; }

    void push_node(Node* n) {
        Node* old = head_.exchange(n, std::memory_order_acq_rel);
        // Link after the exchange; traversers spin past the sentinel.
        n->next.store(old, std::memory_order_release);
    }

    static void* consumer_thunk(void* arg) {
        ((ExecutionQueue*)arg)->consume();
        return nullptr;
    }

    void start_consumer() {
        fiber_t tid;
        if (fiber_start_background(&tid, nullptr, consumer_thunk, this) != 0) {
            consume();  // degrade: run inline
        }
    }

    void consume() {
        bool saw_stop = false;
        bool stop_delivered = false;
        while (true) {
            Node* list = head_.exchange(nullptr, std::memory_order_acq_rel);
            // Reverse LIFO to FIFO, spinning past in-flight links.
            std::vector<Node*> nodes;
            for (Node* cur = list; cur != nullptr;) {
                Node* next = cur->next.load(std::memory_order_acquire);
                while (next == unlinked()) {
                    next = cur->next.load(std::memory_order_acquire);
                }
                nodes.push_back(cur);
                cur = next;
            }
            const int64_t k = (int64_t)nodes.size();
            std::vector<T> batch;
            batch.reserve(nodes.size());
            for (auto it = nodes.rbegin(); it != nodes.rend(); ++it) {
                if ((*it)->is_stop_marker) {
                    saw_stop = true;
                } else {
                    batch.push_back(std::move((*it)->value));
                }
                delete *it;
            }
            // The stopped iteration is delivered exactly once (a callback
            // may release `meta` on it) — and NOTHING is delivered after
            // it: a racing execute() that slipped past the stopping_ check
            // must not reach fn_ once meta may be gone. stop_delivered_ is
            // an object member because that late push can spawn a fresh
            // consumer run with fresh locals.
            const bool delivered_already =
                stop_delivered_.load(std::memory_order_acquire);
            if (!delivered_already &&
                (!batch.empty() || (saw_stop && !stop_delivered))) {
                TaskIterator iter(&batch);
                iter.stopped_ = saw_stop;
                stop_delivered |= saw_stop;
                if (saw_stop) {
                    stop_delivered_.store(true, std::memory_order_release);
                }
                fn_(meta_, iter);
            }
            // Retire when the count we processed matches all submissions;
            // a transiently-negative count (we consumed a pushed-but-not-
            // yet-counted node) keeps us looping until the count lands.
            if (pending_.fetch_sub(k, std::memory_order_acq_rel) == k) {
                break;
            }
        }
        if (saw_stop) {
            // Capture BEFORE signaling: in join()-managed mode the joiner
            // may destroy this queue the moment signal lands, so signal
            // must be the consumer's last member touch. In self-release
            // mode nobody joins-and-frees; release() (the consumer-side
            // ownership drop) is then safe after the signal and runs once
            // (the stop marker is consumed by exactly one run).
            const bool self_rel = self_release_;
            join_event_.signal();
            if (self_rel) release();
        }
    }

    ExecuteFn fn_ = nullptr;
    void* meta_ = nullptr;
    std::atomic<Node*> head_{nullptr};
    std::atomic<int64_t> pending_{0};
    std::atomic<bool> stopping_{false};
    std::atomic<bool> stop_delivered_{false};
    CountdownEvent join_event_{1};
    bool self_release_ = false;
    std::atomic<int> owners_{2};
};

}  // namespace tpurpc
