// TaskMeta: the fiber descriptor, pooled in a ResourcePool and addressed by
// fiber_t = (version<<32)|slot. Modeled on reference src/bthread/task_meta.h.
#pragma once

#include <atomic>
#include <cstdint>
#include <functional>

#include "tfiber/fiber.h"
#include "tfiber/stack.h"

namespace tpurpc {

class TaskControl;
class TaskGroup;

struct TaskMeta {
    // Entry + result.
    void* (*fn)(void*) = nullptr;
    void* arg = nullptr;
    void* ret = nullptr;

    // Join/versioning: `version_butex` points to a pooled butex word whose
    // value is the current version of this slot. fiber_join waits for it to
    // move past the version embedded in the tid (reference task_meta.h
    // version_butex; controller retries rely on the same scheme for ids).
    uint32_t version = 0;
    void* version_butex = nullptr;

    StackStorage stack;
    int stack_type = STACK_TYPE_NORMAL;
    fiber_t tid = INVALID_FIBER;

    // Fiber-local storage (lazily created; reference bthread keytables).
    void* local_storage = nullptr;

    // The worker pool this fiber belongs to (tag routing: a parked fiber
    // must requeue to ITS pool, and cross-pool wakeups must not land on
    // the waker's local queue).
    TaskControl* control = nullptr;

    bool about_to_quit = false;

    // ASan fake-stack handle saved when this fiber switches out (fiber
    // annotations in task_group.cc; unused in non-ASan builds).
    void* asan_fake = nullptr;
};

}  // namespace tpurpc
