// CallId: lockable, versioned 64-bit handle with error propagation — the
// RPC correlation-id mechanism.
//
// Modeled on reference src/bthread/id.h:34-100 (bthread_id_create/lock/
// unlock/unlock_and_destroy/error/join): one RPC's Controller is locked by
// its CallId; the response path and the error path (timeout, socket
// failure) both contend for the lock, and retries bump the version so
// stale responses from earlier tries fail to lock.
//
// Simplifications vs the reference: the internal lock is a small mutex +
// condition (the reference queues lockers on a butex); version ranges are a
// single live version bumped by next_version().
#pragma once

#include <cstdint>

namespace tpurpc {

using CallId = uint64_t;
constexpr CallId INVALID_CALL_ID = 0;

// on_error runs with the id LOCKED; it must eventually call
// id_unlock (retry path) or id_unlock_and_destroy (final failure).
using IdOnError = int (*)(CallId id, void* data, int error_code);

int id_create(CallId* id, void* data, IdOnError on_error);

// Lock the id; fails (-1) if the id/version is stale or destroyed. Blocks
// (fiber- and pthread-aware) while another holder has the lock.
int id_lock(CallId id, void** data_out);
// Like id_lock but accepts ANY version in [first_ver, live_ver] — the
// ranged lock of reference bthread_id_create_ranged (id.h:56). Backup
// requests need it: the original call and the backup are BOTH live, and
// whichever response arrives first must be able to lock; the caller
// decides staleness by comparing the version against its in-flight calls.
int id_lock_range(CallId id, void** data_out);
int id_unlock(CallId id);
// Unlock and destroy: wakes all joiners; further locks fail.
int id_unlock_and_destroy(CallId id);

// Deliver an error: locks the id and invokes on_error(data, error_code).
// Returns -1 if the id is stale/destroyed.
int id_error(CallId id, int error_code);

// Block until the id is destroyed (returns immediately if stale).
int id_join(CallId id);

// Invalidate the current version and return the next one (retries). Caller
// must hold the lock; the returned id replaces the old one on the wire.
CallId id_next_version(CallId id);

// True while the id (this version) is live.
bool id_exists(CallId id);
// True while ANY version of the id's RPC is live — the existence analog
// of id_lock_range / id_error (a retried call's ORIGINAL id value stays
// range-live, and range-valid errors still reach it).
bool id_exists_range(CallId id);

}  // namespace tpurpc
