// butex: a futex for fibers — a 32-bit word plus a waiter list. Fibers park
// on it without blocking their worker pthread; plain pthreads can wait on
// the same butex (they fall back to a real futex), so sync primitives work
// identically inside and outside workers.
//
// Modeled on reference src/bthread/butex.h:41-84 / butex.cpp (pthread
// waiters butex.cpp:81-143). ALL higher synchronization in this framework —
// FiberMutex, cond, countdown, fiber join, CallId, Socket waits — builds on
// these four calls.
#pragma once

#include <atomic>
#include <ctime>

namespace tpurpc {

// Create/destroy a butex (the returned handle owns a 32-bit word).
void* butex_create();
void butex_destroy(void* butex);

// The 32-bit word (value is user-controlled).
std::atomic<int>* butex_word(void* butex);

// Park until woken, the value changes, or `abstime_us` (absolute
// monotonic; null = forever). Returns 0 when woken, else the POSITIVE
// error code: ETIMEDOUT or EWOULDBLOCK (value already != expected).
// errno is also set, but ONLY the return value is reliable: a fiber can
// resume on a different worker thread, and compilers may cache the
// (const) __errno_location() across the switch, making caller-side errno
// reads address the old thread (same reasoning as the reference saving
// errno across context switches, task_group.cpp:711).
int butex_wait(void* butex, int expected_value, const int64_t* abstime_us);

// Wake up to one / all waiters. Returns the number woken.
int butex_wake(void* butex);
int butex_wake_all(void* butex);

}  // namespace tpurpc
