#include "tfiber/fiber_key.h"

#include <pthread.h>

#include <atomic>
#include <cerrno>
#include <mutex>
#include <vector>

#include "tfiber/task_group.h"
#include "tfiber/task_meta.h"

namespace tpurpc {

namespace {

constexpr uint32_t kMaxKeys = 256;

// Global key registry: per-slot version (odd = in use) + dtor. Versions
// are atomic so get/setspecific can validate a key handle against the
// current generation without taking mu (mu guards create/delete only).
struct KeyRegistry {
    std::mutex mu;
    std::atomic<uint32_t> versions[kMaxKeys] = {};  // even = free, odd = live
    void (*dtors[kMaxKeys])(void*) = {};
    std::vector<uint32_t> free_slots;
    uint32_t next_unused = 0;
};
KeyRegistry* registry() {
    static KeyRegistry* r = new KeyRegistry;
    return r;
}

// Per-fiber table: value + the key version it was written under.
struct KeyTable {
    struct Entry {
        void* data = nullptr;
        uint32_t version = 0;
    };
    std::vector<Entry> entries;
};

// Pool of recycled keytables (reference key.cpp:328 borrow_keytable /
// return_keytable — reusing tables avoids an allocation per session).
struct KeyTablePool {
    std::mutex mu;
    std::vector<KeyTable*> free_list;
};
KeyTablePool* kt_pool() {
    static KeyTablePool* p = new KeyTablePool;
    return p;
}

KeyTable* borrow_keytable() {
    {
        std::lock_guard<std::mutex> g(kt_pool()->mu);
        if (!kt_pool()->free_list.empty()) {
            KeyTable* kt = kt_pool()->free_list.back();
            kt_pool()->free_list.pop_back();
            return kt;
        }
    }
    return new KeyTable;
}

// Pthread fallback cleanup: a real pthread TLS destructor runs the
// keytable dtors when a NON-worker thread using FLS exits (the reference
// installs the same for its pthread fallback; without it every
// short-lived user thread would leak its table + values).
pthread_key_t g_pthread_cleanup_key;
pthread_once_t g_pthread_cleanup_once = PTHREAD_ONCE_INIT;
void pthread_kt_cleanup(void* kt);
void init_pthread_cleanup_key() {
    pthread_key_create(&g_pthread_cleanup_key, pthread_kt_cleanup);
}

// The current execution context's keytable slot: the running fiber's
// TaskMeta::local_storage, or a thread-local for plain pthreads.
void** current_kt_slot() {
    TaskGroup* g = TaskGroup::tls_group();
    if (g != nullptr && g->current() != nullptr) {
        return &g->current()->local_storage;
    }
    thread_local void* pthread_kt = nullptr;
    return &pthread_kt;
}

bool on_fiber_worker_here() {
    TaskGroup* g = TaskGroup::tls_group();
    return g != nullptr && g->current() != nullptr;
}

}  // namespace

int fiber_key_create(fiber_key_t* key, void (*dtor)(void*)) {
    KeyRegistry* r = registry();
    std::lock_guard<std::mutex> g(r->mu);
    uint32_t slot;
    if (!r->free_slots.empty()) {
        slot = r->free_slots.back();
        r->free_slots.pop_back();
    } else if (r->next_unused < kMaxKeys) {
        slot = r->next_unused++;
    } else {
        errno = ENOMEM;
        return ENOMEM;
    }
    r->versions[slot] |= 1;  // live (odd)
    r->dtors[slot] = dtor;
    key->index = slot;
    key->version = r->versions[slot];
    return 0;
}

int fiber_key_delete(fiber_key_t key) {
    KeyRegistry* r = registry();
    std::lock_guard<std::mutex> g(r->mu);
    if (key.index >= kMaxKeys || r->versions[key.index] != key.version) {
        errno = EINVAL;
        return EINVAL;
    }
    r->versions[key.index] += 1;  // even: free; stale reads fail
    r->dtors[key.index] = nullptr;
    r->free_slots.push_back(key.index);
    return 0;
}

int fiber_setspecific(fiber_key_t key, void* data) {
    if (key.index >= kMaxKeys || (key.version & 1) == 0 ||
        registry()->versions[key.index].load(std::memory_order_acquire) !=
            key.version) {
        errno = EINVAL;
        return EINVAL;
    }
    void** slot = current_kt_slot();
    if (*slot == nullptr) {
        *slot = borrow_keytable();
        if (!on_fiber_worker_here()) {
            // Register exit cleanup for this plain pthread's table.
            pthread_once(&g_pthread_cleanup_once, init_pthread_cleanup_key);
            pthread_setspecific(g_pthread_cleanup_key, *slot);
        }
    }
    KeyTable* kt = (KeyTable*)*slot;
    if (kt->entries.size() <= key.index) {
        kt->entries.resize(key.index + 1);
    }
    kt->entries[key.index].data = data;
    kt->entries[key.index].version = key.version;
    return 0;
}

void* fiber_getspecific(fiber_key_t key) {
    if (key.index >= kMaxKeys ||
        registry()->versions[key.index].load(std::memory_order_acquire) !=
            key.version) {
        // Deleted key handle: reads after fiber_key_delete see null even
        // though this fiber's entry still carries the old generation.
        return nullptr;
    }
    void** slot = current_kt_slot();
    if (*slot == nullptr) return nullptr;
    KeyTable* kt = (KeyTable*)*slot;
    if (kt->entries.size() <= key.index) return nullptr;
    const KeyTable::Entry& e = kt->entries[key.index];
    // Stale entry (deleted/recreated): this fiber's value was written
    // under another key generation.
    return e.version == key.version ? e.data : nullptr;
}

namespace fiber_internal {

void return_keytable(void* raw) {
    if (raw == nullptr) return;
    KeyTable* kt = (KeyTable*)raw;
    KeyRegistry* r = registry();
    // Run destructors for values whose key is still live. Re-loop: a
    // destructor may itself setspecific at an already-visited index
    // (pthread_key semantics: up to PTHREAD_DESTRUCTOR_ITERATIONS
    // passes; the reference keytable does the same).
    for (int pass = 0; pass < 4; ++pass) {
        bool any = false;
        for (uint32_t i = 0; i < kt->entries.size(); ++i) {
            KeyTable::Entry& e = kt->entries[i];
            if (e.data == nullptr) continue;
            void (*dtor)(void*) = nullptr;
            {
                std::lock_guard<std::mutex> g(r->mu);
                if (i < kMaxKeys && r->versions[i] == e.version) {
                    dtor = r->dtors[i];
                }
            }
            void* data = e.data;
            e.data = nullptr;
            e.version = 0;
            any = true;
            if (dtor != nullptr) dtor(data);
        }
        if (!any) break;
    }
    kt->entries.clear();
    std::lock_guard<std::mutex> g(kt_pool()->mu);
    if (kt_pool()->free_list.size() < 1024) {
        kt_pool()->free_list.push_back(kt);
    } else {
        delete kt;
    }
}

}  // namespace fiber_internal

namespace {
void pthread_kt_cleanup(void* kt) { fiber_internal::return_keytable(kt); }
}  // namespace

}  // namespace tpurpc
