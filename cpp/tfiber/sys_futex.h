// Raw futex syscall wrappers (reference src/bthread/sys_futex.h).
#pragma once

#include <linux/futex.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <atomic>
#include <cerrno>
#include <ctime>

namespace tpurpc {

inline int futex_wait_private(std::atomic<int>* addr, int expected,
                              const timespec* timeout) {
    return (int)syscall(SYS_futex, addr, FUTEX_WAIT_PRIVATE, expected, timeout,
                        nullptr, 0);
}

inline int futex_wake_private(std::atomic<int>* addr, int nwake) {
    return (int)syscall(SYS_futex, addr, FUTEX_WAKE_PRIVATE, nwake, nullptr,
                        nullptr, 0);
}

}  // namespace tpurpc
