#include "tfiber/timer_thread.h"

#include "tbase/time.h"

namespace tpurpc {

TimerThread* TimerThread::singleton() {
    static TimerThread* t = new TimerThread;
    return t;
}

TimerThread::TimerThread() { thread_ = std::thread([this] { Run(); }); }

TimerId TimerThread::schedule(void (*fn)(void*), void* arg,
                              int64_t abstime_us) {
    std::unique_lock<std::mutex> lk(mu_);
    if (stopped_) return INVALID_TIMER_ID;
    const TimerId id = next_id_++;
    const bool need_wake =
        tasks_.empty() || abstime_us < tasks_.begin()->first;
    auto it = tasks_.emplace(abstime_us, Task{fn, arg, id});
    by_id_[id] = it;
    if (need_wake) cv_.notify_one();
    return id;
}

int TimerThread::unschedule(TimerId id, bool wait_running) {
    std::unique_lock<std::mutex> lk(mu_);
    auto idx = by_id_.find(id);
    if (idx != by_id_.end()) {
        tasks_.erase(idx->second);
        by_id_.erase(idx);
        return 0;
    }
    if (running_id_ == id) {
        if (wait_running) {
            // Block until the in-flight callback finishes (butex timed-wait
            // safety depends on this).
            run_done_cv_.wait(lk, [this, id] { return running_id_ != id; });
        }
        return 1;
    }
    return -1;  // already ran (or never existed)
}

void TimerThread::Run() {
    std::unique_lock<std::mutex> lk(mu_);
    while (!stopped_) {
        if (tasks_.empty()) {
            cv_.wait(lk);
            continue;
        }
        const int64_t now = monotonic_time_us();
        auto it = tasks_.begin();
        if (it->first > now) {
            cv_.wait_for(lk, std::chrono::microseconds(it->first - now));
            continue;
        }
        Task task = it->second;
        by_id_.erase(task.id);
        tasks_.erase(it);
        running_id_ = task.id;
        lk.unlock();
        task.fn(task.arg);
        lk.lock();
        running_id_ = 0;
        run_done_cv_.notify_all();
    }
}

void TimerThread::stop_and_join() {
    {
        std::lock_guard<std::mutex> g(mu_);
        stopped_ = true;
        cv_.notify_all();
    }
    if (thread_.joinable()) thread_.join();
}

}  // namespace tpurpc
