// Fiber-aware synchronization primitives built on butex: mutex, condition
// variable, countdown event, semaphore. Usable from fibers AND plain
// pthreads (butex handles both waiter kinds), matching the reference's
// bthread_mutex/bthread_cond/CountdownEvent (src/bthread/mutex.cpp,
// condition_variable.cpp, countdown_event.cpp).
#pragma once

#include <cstdint>

#include "tfiber/butex.h"

namespace tpurpc {

class FiberMutex {
public:
    FiberMutex();
    ~FiberMutex();
    FiberMutex(const FiberMutex&) = delete;
    FiberMutex& operator=(const FiberMutex&) = delete;

    void lock();
    void unlock();
    bool try_lock();

    void* butex() { return butex_; }

private:
    // value: 0 unlocked, 1 locked no waiters, 2 locked with (possible)
    // waiters — the classic futex mutex protocol.
    void* butex_;
};

class FiberMutexGuard {
public:
    explicit FiberMutexGuard(FiberMutex& mu) : mu_(mu) { mu_.lock(); }
    ~FiberMutexGuard() { mu_.unlock(); }

private:
    FiberMutex& mu_;
};

class FiberCond {
public:
    FiberCond();
    ~FiberCond();

    // mu must be held; atomically releases it while waiting.
    void wait(FiberMutex& mu);
    // Returns 0, or ETIMEDOUT.
    int wait_until(FiberMutex& mu, int64_t abstime_us);
    void notify_one();
    void notify_all();

private:
    void* butex_;  // value = notification sequence number
};

// Writer-preferring reader/writer lock (reference src/bthread/rwlock.cpp):
// readers share; a waiting writer blocks NEW readers so it can't starve.
class FiberRWLock {
public:
    FiberRWLock();
    ~FiberRWLock();
    FiberRWLock(const FiberRWLock&) = delete;
    FiberRWLock& operator=(const FiberRWLock&) = delete;

    void rdlock();
    void rdunlock();
    void wrlock();
    void wrunlock();

private:
    // state butex value: number of active readers; -1 = writer holds.
    void* state_butex_;
    // serializes writers and blocks new readers while a writer waits.
    FiberMutex writer_mu_;
};

// One-time initialization usable from fibers (reference bthread_once):
// concurrent callers block until the first caller's fn completes.
class FiberOnce {
public:
    FiberOnce();
    ~FiberOnce();
    void call(void (*fn)());

private:
    void* butex_;  // 0 = not run, 1 = running, 2 = done
};

class CountdownEvent {
public:
    explicit CountdownEvent(int initial = 1);
    ~CountdownEvent();

    void signal(int n = 1);
    void add_count(int n = 1);
    void reset(int n);
    // Block until the count reaches zero. Returns 0, or ETIMEDOUT when
    // abstime_us (monotonic) passes first.
    int wait(const int64_t* abstime_us = nullptr);

private:
    void* butex_;
};

}  // namespace tpurpc
