// Fiber context switching (x86_64 SysV).
//
// The reference uses boost.context-derived per-arch assembly
// (src/bthread/context.cpp:17-148, bthread_jump_fcontext /
// bthread_make_fcontext). We implement our own minimal variant for x86_64
// (TPU-VM hosts are x86_64/aarch64; this image is x86_64): a context is just
// a stack pointer; switching saves the 6 callee-saved GPRs + return address
// on the old stack and restores them from the new stack.
//
// FP/SSE state: per SysV ABI all xmm registers are caller-saved and the
// x87/mxcsr control words are rarely changed; like boost's fcontext we also
// save/restore mxcsr + x87cw to be safe in code that toggles rounding modes.
#pragma once

#include <cstddef>
#include <cstdint>

namespace tpurpc {

// Opaque context: points into the fiber's stack where registers are saved.
using fcontext_t = void*;

extern "C" {
// Switch from the current context (saved to *from) to `to`. `arg` appears as
// the return value in the resumed context / first argument of a fresh one.
void* tf_jump_fcontext(fcontext_t* from, fcontext_t to, void* arg);
}

// Build a fresh context on [stack_base, stack_base+size) that will call
// fn(arg_from_first_jump) when first jumped to. fn must never return.
fcontext_t tf_make_fcontext(void* stack_base, size_t size, void (*fn)(void*));

}  // namespace tpurpc
