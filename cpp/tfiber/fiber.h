// Public fiber API: M:N user-space threads over a work-stealing scheduler.
//
// Mirrors the reference's bthread C API (src/bthread/bthread.h:
// bthread_start_urgent / bthread_start_background / bthread_join /
// bthread_yield / bthread_usleep / bthread_self) with tpurpc naming. Every
// I/O callback and user service method in this framework runs on a fiber.
#pragma once

#include <cstdint>

namespace tpurpc {

// fiber_t = (version << 32) | resource-pool slot; 0 = invalid.
using fiber_t = uint64_t;
constexpr fiber_t INVALID_FIBER = 0;

struct FiberAttr {
    int stack_type = 1;  // STACK_TYPE_NORMAL
    // Worker tag (reference bthread_tag_t): 0 = the default pool;
    // nonzero tags run on their own isolated worker pool, so tagged
    // workloads cannot starve (or be starved by) the default pool.
    int tag = 0;
};

constexpr FiberAttr FIBER_ATTR_NORMAL = {1, 0};
constexpr FiberAttr FIBER_ATTR_SMALL = {0, 0};
constexpr FiberAttr FIBER_ATTR_LARGE = {2, 0};

// Start a fiber. `urgent` hints the scheduler to run it ASAP (the caller of
// start_background keeps running; reference bthread.h start_urgent vs
// start_background).
int fiber_start_background(fiber_t* tid, const FiberAttr* attr,
                           void* (*fn)(void*), void* arg);
int fiber_start_urgent(fiber_t* tid, const FiberAttr* attr,
                       void* (*fn)(void*), void* arg);

// Wait for fiber termination. Returns 0; joining a dead/invalid tid
// returns 0 immediately (same contract as bthread_join).
int fiber_join(fiber_t tid, void** ret);

// True while the fiber exists and has not finished.
bool fiber_exists(fiber_t tid);

// Current fiber id; INVALID_FIBER when called outside a worker.
fiber_t fiber_self();

// Cooperative reschedule.
void fiber_yield();

// Sleep without blocking the worker thread.
int fiber_usleep(int64_t us);

// True if the calling thread is a fiber worker (i.e. fiber context).
bool is_running_on_fiber_worker();

// Scheduler control.
// Start the scheduler with `num_workers` worker pthreads (idempotent;
// auto-started on first fiber_start with a default from flag
// fiber_worker_count).
void fiber_set_worker_count(int num_workers);
int fiber_get_worker_count();

}  // namespace tpurpc
