#include "tfiber/task_tracer.h"

#include <sys/uio.h>
#include <unistd.h>

#include <cstdio>
#include <vector>

#include "tbase/resource_pool.h"
#include "tbase/symbolize.h"
#include "tfiber/butex.h"
#include "tfiber/task_group.h"
#include "tfiber/task_meta.h"

namespace tpurpc {

namespace {

// Frame-pointer / resume-pc offsets at a saved context SP, per arch:
// x86-64 (context.S, 0x40-byte frame): rbp at 0x30, rip at 0x38.
// aarch64 (context_aarch64.S, 0xa0-byte frame): x29 at 0x90, x30 at
// 0x98 — the x86 offsets would read d12/d13 (callee-saved FP regs) as
// fp/pc and make every /fibers?st=1 walk garbage.
#if defined(__aarch64__)
constexpr size_t kSavedRbpOff = 0x90;  // x29
constexpr size_t kSavedRipOff = 0x98;  // x30 (resume pc)
#else
constexpr size_t kSavedRbpOff = 0x30;  // rbp
constexpr size_t kSavedRipOff = 0x38;  // rip
#endif

// Fault-safe read of a word from our own address space: a stack being
// concurrently recycled/unmapped returns false instead of SIGSEGV.
bool SafeReadWord(uintptr_t addr, uintptr_t* out) {
    if (addr == 0 || (addr & 7) != 0) return false;
    iovec local{out, sizeof(*out)};
    iovec remote{(void*)addr, sizeof(*out)};
    return process_vm_readv(getpid(), &local, 1, &remote, 1, 0) ==
           (ssize_t)sizeof(*out);
}

bool InStack(uintptr_t p, uintptr_t lo, uintptr_t hi) {
    return p >= lo && p + 16 <= hi;
}

}  // namespace

std::string DumpFiberStacks(size_t max_frames_per_fiber) {
    // Fibers on a CPU right now: their saved context is stale garbage.
    std::vector<const TaskMeta*> running;
    TaskControl::ForEachPool(
        [](int, TaskControl* c, void* arg) {
            c->CollectRunning((std::vector<const TaskMeta*>*)arg);
        },
        &running);

    std::string out;
    char line[256];
    auto* pool = ResourcePool<TaskMeta>::singleton();
    const size_t nslots = pool->size();
    size_t nlive = 0;
    for (size_t slot = 0; slot < nslots; ++slot) {
        TaskMeta* m = address_resource<TaskMeta>((ResourceId)slot);
        if (m == nullptr || m->version_butex == nullptr ||
            m->tid == INVALID_FIBER) {
            continue;
        }
        // Live = the slot's current version matches the tid's version
        // (a recycled slot moved past it).
        const uint32_t tid_version = (uint32_t)(m->tid >> 32);
        if ((uint32_t)butex_word(m->version_butex)
                ->load(std::memory_order_acquire) != tid_version) {
            continue;
        }
        ++nlive;
        bool is_running = false;
        for (const TaskMeta* r : running) {
            if (r == m) {
                is_running = true;
                break;
            }
        }
        snprintf(line, sizeof(line), "fiber %llu  %s\n",
                 (unsigned long long)m->tid,
                 is_running ? "[running]" : "[suspended]");
        out += line;
        if (is_running) continue;
        // Snapshot the racy fields once; bounds-check everything.
        const uintptr_t lo = (uintptr_t)m->stack.base;
        const uintptr_t hi = lo + m->stack.size;
        const uintptr_t ctx = (uintptr_t)m->stack.context;
        if (!InStack(ctx, lo, hi)) {
            out += "    <no saved context>\n";
            continue;
        }
        uintptr_t rip = 0, rbp = 0;
        if (!SafeReadWord(ctx + kSavedRipOff, &rip) ||
            !SafeReadWord(ctx + kSavedRbpOff, &rbp)) {
            out += "    <stack read failed>\n";
            continue;
        }
        size_t depth = 0;
        while (rip != 0 && depth < max_frames_per_fiber) {
            snprintf(line, sizeof(line), "    #%zu 0x%llx %s\n", depth,
                     (unsigned long long)rip, SymbolizePc(rip).c_str());
            out += line;
            ++depth;
            // Frame-pointer chain: [rbp]=caller rbp, [rbp+8]=return pc.
            if (!InStack(rbp, lo, hi)) break;
            uintptr_t next_rbp = 0, next_rip = 0;
            if (!SafeReadWord(rbp, &next_rbp) ||
                !SafeReadWord(rbp + 8, &next_rip)) {
                break;
            }
            // The chain must move UP the stack or it's garbage/looping.
            if (next_rbp <= rbp && next_rbp != 0) break;
            rip = next_rip;
            rbp = next_rbp;
        }
        if (depth == 0) out += "    <unwalkable>\n";
    }
    snprintf(line, sizeof(line), "%zu live fiber(s)\n", nlive);
    return line + out;
}

}  // namespace tpurpc
