#include "tfiber/call_id.h"

#include <mutex>
#include <vector>

#include "tbase/resource_pool.h"
#include "tfiber/butex.h"

namespace tpurpc {

namespace {

struct IdSlot {
    std::mutex mu;
    // The create-time version and the current wire version: retries bump
    // live_ver (stale responses fail the strict lock check) but errors and
    // joins stay valid across the whole [first_ver, live_ver] range — the
    // reference's ranged bthread_id (id.h:56 bthread_id_create_ranged).
    uint32_t first_ver = 0;
    uint32_t live_ver = 0;
    bool destroyed = true;
    bool locked = false;
    void* data = nullptr;
    IdOnError on_error = nullptr;
    void* lock_butex = nullptr;  // word: lock release sequence
    void* join_butex = nullptr;  // word: destroy sequence
    std::vector<int> pending_errors;
};

inline ResourceId slot_of(CallId id) {
    return (ResourceId)((id & 0xffffffffu) - 1);
}
inline uint32_t ver_of(CallId id) { return (uint32_t)(id >> 32); }
inline CallId make_id(uint32_t ver, ResourceId slot) {
    return ((CallId)ver << 32) | (CallId)(slot + 1);
}

IdSlot* resolve(CallId id) {
    if (id == INVALID_CALL_ID) return nullptr;
    return address_resource<IdSlot>(slot_of(id));
}

// Strict: only the current wire version may lock (stale responses drop).
bool valid_locked(IdSlot* s, CallId id) {
    return !s->destroyed && s->live_ver == ver_of(id);
}
// Range: any version of this RPC may deliver errors / join.
bool valid_range(IdSlot* s, CallId id) {
    const uint32_t v = ver_of(id);
    return !s->destroyed && v >= s->first_ver && v <= s->live_ver;
}

}  // namespace

int id_create(CallId* id, void* data, IdOnError on_error) {
    ResourceId slot;
    IdSlot* s = get_resource<IdSlot>(&slot);
    if (s == nullptr) return -1;
    std::lock_guard<std::mutex> g(s->mu);
    if (s->lock_butex == nullptr) s->lock_butex = butex_create();
    if (s->join_butex == nullptr) s->join_butex = butex_create();
    s->first_ver = s->live_ver;
    s->destroyed = false;
    s->locked = false;
    s->data = data;
    s->on_error = on_error;
    s->pending_errors.clear();
    *id = make_id(s->live_ver, slot);
    return 0;
}

namespace {
int id_lock_impl(CallId id, void** data_out, bool range) {
    IdSlot* s = resolve(id);
    if (s == nullptr) return -1;
    while (true) {
        int seq;
        {
            std::lock_guard<std::mutex> g(s->mu);
            if (!(range ? valid_range(s, id) : valid_locked(s, id))) {
                return -1;
            }
            if (!s->locked) {
                s->locked = true;
                if (data_out) *data_out = s->data;
                return 0;
            }
            seq = butex_word(s->lock_butex)->load(std::memory_order_relaxed);
        }
        butex_wait(s->lock_butex, seq, nullptr);
    }
}
}  // namespace

int id_lock(CallId id, void** data_out) {
    return id_lock_impl(id, data_out, false);
}

int id_lock_range(CallId id, void** data_out) {
    return id_lock_impl(id, data_out, true);
}

int id_unlock(CallId id) {
    IdSlot* s = resolve(id);
    if (s == nullptr) return -1;
    int deferred_error = 0;
    bool run_error = false;
    {
        std::lock_guard<std::mutex> g(s->mu);
        if (!s->locked) return -1;
        if (!s->pending_errors.empty() && valid_range(s, id)) {
            // Keep the lock and deliver the queued error to on_error.
            deferred_error = s->pending_errors.front();
            s->pending_errors.erase(s->pending_errors.begin());
            run_error = true;
        } else {
            s->locked = false;
            butex_word(s->lock_butex)
                ->fetch_add(1, std::memory_order_release);
        }
    }
    if (run_error) {
        IdOnError cb = s->on_error;
        void* data = s->data;
        if (cb != nullptr) {
            return cb(id, data, deferred_error);
        }
        return id_unlock_and_destroy(id);
    }
    butex_wake(s->lock_butex);
    return 0;
}

int id_unlock_and_destroy(CallId id) {
    IdSlot* s = resolve(id);
    if (s == nullptr) return -1;
    {
        std::lock_guard<std::mutex> g(s->mu);
        if (s->destroyed) return -1;
        s->destroyed = true;
        s->locked = false;
        ++s->live_ver;  // all outstanding versions go stale
        s->pending_errors.clear();
        butex_word(s->lock_butex)->fetch_add(1, std::memory_order_release);
        butex_word(s->join_butex)->fetch_add(1, std::memory_order_release);
    }
    butex_wake_all(s->lock_butex);
    butex_wake_all(s->join_butex);
    return_resource<IdSlot>(slot_of(id));
    return 0;
}

int id_error(CallId id, int error_code) {
    IdSlot* s = resolve(id);
    if (s == nullptr) return -1;
    {
        std::lock_guard<std::mutex> g(s->mu);
        if (!valid_range(s, id)) return -1;
        if (s->locked) {
            s->pending_errors.push_back(error_code);
            return 0;
        }
        s->locked = true;
    }
    IdOnError cb = s->on_error;
    if (cb != nullptr) {
        return cb(id, s->data, error_code);
    }
    return id_unlock_and_destroy(id);
}

int id_join(CallId id) {
    IdSlot* s = resolve(id);
    if (s == nullptr) return 0;
    while (true) {
        int seq;
        {
            std::lock_guard<std::mutex> g(s->mu);
            if (!valid_range(s, id)) return 0;
            seq = butex_word(s->join_butex)->load(std::memory_order_relaxed);
        }
        butex_wait(s->join_butex, seq, nullptr);
    }
}

CallId id_next_version(CallId id) {
    IdSlot* s = resolve(id);
    if (s == nullptr) return INVALID_CALL_ID;
    std::lock_guard<std::mutex> g(s->mu);
    if (!valid_locked(s, id)) return INVALID_CALL_ID;
    ++s->live_ver;
    return make_id(s->live_ver, slot_of(id));
}

bool id_exists(CallId id) {
    IdSlot* s = resolve(id);
    if (s == nullptr) return false;
    std::lock_guard<std::mutex> g(s->mu);
    return valid_locked(s, id);
}

bool id_exists_range(CallId id) {
    IdSlot* s = resolve(id);
    if (s == nullptr) return false;
    std::lock_guard<std::mutex> g(s->mu);
    return valid_range(s, id);
}

}  // namespace tpurpc
