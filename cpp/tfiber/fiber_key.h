// Fiber-local storage: versioned keys + per-fiber keytables.
//
// Modeled on reference src/bthread/key.cpp (bthread_key_create /
// bthread_setspecific / bthread_getspecific; KeyTable :328-373 with
// borrow/return pooling so session data is reused across requests).
// A key is (index, version): deleting a key bumps the slot's version so
// stale keys read null instead of another user's data. Keytables are
// created lazily on first setspecific, run destructors at fiber exit,
// then return to a pool for reuse by later fibers.
#pragma once

#include <cstdint>

namespace tpurpc {

struct fiber_key_t {
    uint32_t index = 0;
    uint32_t version = 0;
    bool operator==(const fiber_key_t& o) const {
        return index == o.index && version == o.version;
    }
};
constexpr fiber_key_t INVALID_FIBER_KEY = {0, 0};

// Create a key; `dtor` (may be null) runs at fiber exit on each fiber's
// non-null value. Returns 0, or ENOMEM when out of key slots.
int fiber_key_create(fiber_key_t* key, void (*dtor)(void*));

// Delete the key: values become unreachable immediately (getspecific on
// the stale key returns null); their destructors do NOT run (same
// contract as the reference bthread_key_delete / pthread_key_delete).
int fiber_key_delete(fiber_key_t key);

// Set/get this fiber's value for `key`. Outside a fiber worker, a
// process-wide per-pthread fallback table is used (like the reference's
// pthread fallback in bthread_setspecific).
int fiber_setspecific(fiber_key_t key, void* data);
void* fiber_getspecific(fiber_key_t key);

namespace fiber_internal {
// Run dtors + recycle the current fiber's keytable (fiber exit path).
void return_keytable(void* kt);
}  // namespace fiber_internal

}  // namespace tpurpc
