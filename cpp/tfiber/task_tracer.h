// TaskTracer: live stack dumps of suspended fibers for /fibers?st=1.
//
// Reference parity: src/bthread/task_tracer.h:36-108 (signal+libunwind
// stack capture of live bthreads). This tracer walks the SAVED context
// of parked fibers instead: every switch-out stores the fiber's SP
// (context.S documents the register layout at that SP), the build keeps
// frame pointers (-fno-omit-frame-pointer), and all memory reads go
// through process_vm_readv so racing resumes/stack recycling can never
// fault the server — a torn read just ends that fiber's walk early.
// Fibers currently ON a CPU are reported as running, without frames
// (their saved context is stale by definition).
#pragma once

#include <cstddef>
#include <string>

namespace tpurpc {

// Text dump: one block per live fiber — tid, state, symbolized frames.
std::string DumpFiberStacks(size_t max_frames_per_fiber = 16);

}  // namespace tpurpc
