#include "tvar/variable.h"

#include <map>

namespace tpurpc {

namespace {
struct Registry {
    std::mutex mu;
    std::map<std::string, Variable*> vars;
};
Registry* registry() {
    static Registry* r = new Registry;
    return r;
}
}  // namespace

Variable::~Variable() { hide(); }

int Variable::expose(const std::string& name) {
    Registry* r = registry();
    std::lock_guard<std::mutex> g(r->mu);
    if (!name_.empty()) r->vars.erase(name_);
    name_ = name;
    if (!name.empty()) {
        // Last expose wins (same as reference semantics with a warning).
        r->vars[name] = this;
    }
    return 0;
}

void Variable::hide() {
    if (name_.empty()) return;
    Registry* r = registry();
    std::lock_guard<std::mutex> g(r->mu);
    auto it = r->vars.find(name_);
    if (it != r->vars.end() && it->second == this) r->vars.erase(it);
    name_.clear();
}

std::vector<std::string> Variable::list_exposed() {
    Registry* r = registry();
    std::lock_guard<std::mutex> g(r->mu);
    std::vector<std::string> out;
    out.reserve(r->vars.size());
    for (auto& kv : r->vars) out.push_back(kv.first);
    return out;
}

bool Variable::describe_exposed(const std::string& name, std::string* out) {
    Registry* r = registry();
    std::lock_guard<std::mutex> g(r->mu);
    auto it = r->vars.find(name);
    if (it == r->vars.end()) return false;
    *out = it->second->get_description();
    return true;
}

std::vector<std::pair<std::string, std::string>> Variable::dump_exposed() {
    Registry* r = registry();
    std::lock_guard<std::mutex> g(r->mu);
    std::vector<std::pair<std::string, std::string>> out;
    out.reserve(r->vars.size());
    for (auto& kv : r->vars) {
        out.emplace_back(kv.first, kv.second->get_description());
    }
    return out;
}

}  // namespace tpurpc
