#include "tvar/variable.h"

#include <cctype>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <map>

namespace tpurpc {

namespace {
struct Registry {
    std::mutex mu;
    std::map<std::string, Variable*> vars;
};
Registry* registry() {
    static Registry* r = new Registry;
    return r;
}
}  // namespace

Variable::~Variable() { hide(); }

int Variable::expose(const std::string& name) {
    Registry* r = registry();
    std::lock_guard<std::mutex> g(r->mu);
    if (!name_.empty()) r->vars.erase(name_);
    name_ = name;
    if (!name.empty()) {
        // Last expose wins (same as reference semantics with a warning).
        r->vars[name] = this;
    }
    return 0;
}

void Variable::hide() {
    if (name_.empty()) return;
    Registry* r = registry();
    std::lock_guard<std::mutex> g(r->mu);
    auto it = r->vars.find(name_);
    if (it != r->vars.end() && it->second == this) r->vars.erase(it);
    name_.clear();
}

std::vector<std::string> Variable::list_exposed() {
    Registry* r = registry();
    std::lock_guard<std::mutex> g(r->mu);
    std::vector<std::string> out;
    out.reserve(r->vars.size());
    for (auto& kv : r->vars) out.push_back(kv.first);
    return out;
}

bool Variable::describe_exposed(const std::string& name, std::string* out) {
    Registry* r = registry();
    std::lock_guard<std::mutex> g(r->mu);
    auto it = r->vars.find(name);
    if (it == r->vars.end()) return false;
    *out = it->second->get_description();
    return true;
}

std::vector<std::pair<std::string, std::string>> Variable::dump_exposed() {
    Registry* r = registry();
    std::lock_guard<std::mutex> g(r->mu);
    std::vector<std::pair<std::string, std::string>> out;
    out.reserve(r->vars.size());
    for (auto& kv : r->vars) {
        out.emplace_back(kv.first, kv.second->get_description());
    }
    return out;
}

void Variable::for_each_exposed(
    const std::function<void(const std::string&, const Variable*)>& fn) {
    Registry* r = registry();
    std::lock_guard<std::mutex> g(r->mu);
    for (auto& kv : r->vars) fn(kv.first, kv.second);
}

std::vector<std::pair<std::string, double>> Variable::numeric_fields() const {
    std::vector<std::pair<std::string, double>> out;
    const std::string desc = get_description();
    if (IsNumericLiteral(desc)) {
        out.emplace_back("", strtod(desc.c_str(), nullptr));
    }
    return out;
}

void Variable::prometheus_text(const std::string& name,
                               std::string* out) const {
    for (const auto& f : numeric_fields()) {
        const std::string mname =
            f.first.empty() ? name : name + SanitizeMetricName(f.first);
        *out += "# TYPE " + mname + " gauge\n";
        *out += mname + " " + FormatMetricValue(f.second) + "\n";
    }
}

const char* Variable::prometheus_labelled_samples(const std::string& name,
                                                  const std::string& labels,
                                                  std::string* out) const {
    for (const auto& f : numeric_fields()) {
        const std::string mname =
            f.first.empty() ? name : name + SanitizeMetricName(f.first);
        *out += mname + "{" + labels + "} " + FormatMetricValue(f.second) +
                "\n";
    }
    return "gauge";
}

std::string Variable::dump_prometheus() {
    std::string out;
    for_each_exposed([&out](const std::string& name, const Variable* v) {
        v->prometheus_text(SanitizeMetricName(name), &out);
    });
    return out;
}

std::string SanitizeMetricName(std::string name) {
    for (char& c : name) {
        if (!isalnum((unsigned char)c) && c != '_' && c != ':') c = '_';
    }
    if (!name.empty() && isdigit((unsigned char)name[0])) {
        name.insert(name.begin(), '_');
    }
    return name;
}

bool IsNumericLiteral(const std::string& s) {
    char* end = nullptr;
    strtod(s.c_str(), &end);
    return end != s.c_str() && *end == '\0' && !s.empty();
}

std::string FormatMetricValue(double v) {
    // Range-check BEFORE the cast (double->long long outside the
    // representable range is UB), and map non-finite values to the
    // prometheus canonical spellings instead of printf's "inf"/"nan".
    if (!std::isfinite(v)) {
        return v != v ? "NaN" : (v > 0 ? "+Inf" : "-Inf");
    }
    char buf[64];
    if (v > -9.0e15 && v < 9.0e15 && v == (double)(long long)v) {
        snprintf(buf, sizeof(buf), "%lld", (long long)v);
    } else {
        snprintf(buf, sizeof(buf), "%.17g", v);
    }
    return buf;
}

}  // namespace tpurpc
