// Reducer: write-mostly combiners whose write path touches only a
// thread-local cell; reads combine all thread agents.
//
// Modeled on reference src/bvar/reducer.h + detail/agent_group.h: Adder,
// Maxer, Miner, and the general Reducer<T, Op>. Each TLS cell is guarded by
// its own mutex that is uncontended except for the brief moment a reader
// combines — so a write is one uncontended lock + op (~15-20ns), not a
// shared-counter cache-line fight. (The reference's raw TLS add is ~2ns; an
// atomic fast path for arithmetic T is a known follow-up.)
//
// Lifetime contract (same spirit as bvar): a Reducer must not be destroyed
// while other threads may still be writing to it — destroy after writer
// threads quiesce. Reducers are typically process-lifetime globals. Note
// that destroying a reducer orphans its per-thread cells until each writer
// thread exits (one allocation + one TLS map entry per destroyed reducer
// per thread) — don't create/destroy reducers in a hot loop.
#pragma once

#include <atomic>
#include <limits>
#include <mutex>
#include <sstream>
#include <type_traits>
#include <vector>

#include "tvar/variable.h"

namespace tpurpc {

namespace tvar_detail {

// One agent per (thread, reducer). Registered with its owner on first use;
// on thread exit the value folds into the owner's residual.
template <typename T>
struct AgentCell {
    std::mutex mu;
    T value{};
    void* owner = nullptr;
};

}  // namespace tvar_detail

template <typename T, typename Op>
class Reducer : public Variable {
public:
    using Cell = tvar_detail::AgentCell<T>;

    explicit Reducer(T identity = T())
        : identity_(identity), residual_(identity) {}

    ~Reducer() override {
        hide();
        std::lock_guard<std::mutex> g(cells_mu_);
        for (Cell* c : cells_) {
            std::lock_guard<std::mutex> cg(c->mu);
            c->owner = nullptr;  // orphan: thread-exit won't fold into us
        }
    }

    // The hot path: mutate this thread's cell.
    template <typename Fn>
    void modify(Fn&& fn) {
        Cell* c = tls_cell();
        std::lock_guard<std::mutex> g(c->mu);
        fn(c->value);
    }

    Reducer& operator<<(const T& v) {
        modify([&](T& cur) { Op()(cur, v); });
        return *this;
    }

    T get_value() const {
        T result = residual_load();
        std::lock_guard<std::mutex> g(cells_mu_);
        for (Cell* c : cells_) {
            std::lock_guard<std::mutex> cg(c->mu);
            Op()(result, c->value);
        }
        return result;
    }

    // Reset all agents to identity and return the combined pre-reset value
    // (used by Window sampling).
    T reset() {
        T result;
        {
            std::lock_guard<std::mutex> rg(residual_mu_);
            result = residual_;
            residual_ = identity_;
        }
        std::lock_guard<std::mutex> g(cells_mu_);
        for (Cell* c : cells_) {
            std::lock_guard<std::mutex> cg(c->mu);
            Op()(result, c->value);
            c->value = identity_;
        }
        return result;
    }

    std::string get_description() const override {
        std::ostringstream os;
        os << get_value();
        return os.str();
    }

private:
    struct TlsRegistry;

    // Keyed by a never-reused uid, not `this`: a new reducer allocated at a
    // destroyed one's address must not inherit its orphaned cells.
    Cell* tls_cell() {
        thread_local std::vector<std::pair<uint64_t, Cell*>> map;
        for (auto& p : map) {
            if (p.first == uid_) return p.second;
        }
        Cell* c = new Cell;
        c->value = identity_;
        c->owner = this;
        {
            std::lock_guard<std::mutex> g(cells_mu_);
            cells_.push_back(c);
        }
        map.emplace_back(uid_, c);
        tls_cleanup().cells.push_back(c);
        return c;
    }

    static uint64_t next_uid() {
        static std::atomic<uint64_t> counter{1};
        return counter.fetch_add(1, std::memory_order_relaxed);
    }

    // Per-thread cleanup: folds cells into owners at thread exit.
    struct Cleanup {
        std::vector<Cell*> cells;
        ~Cleanup() {
            for (Cell* c : cells) {
                Reducer* owner;
                {
                    std::lock_guard<std::mutex> g(c->mu);
                    owner = (Reducer*)c->owner;
                }
                if (owner != nullptr) {
                    owner->fold_and_remove(c);
                } else {
                    delete c;
                }
            }
        }
    };
    static Cleanup& tls_cleanup() {
        thread_local Cleanup cl;
        return cl;
    }

    void fold_and_remove(Cell* c) {
        {
            std::lock_guard<std::mutex> rg(residual_mu_);
            std::lock_guard<std::mutex> cg(c->mu);
            Op()(residual_, c->value);
        }
        {
            std::lock_guard<std::mutex> g(cells_mu_);
            for (size_t i = 0; i < cells_.size(); ++i) {
                if (cells_[i] == c) {
                    cells_[i] = cells_.back();
                    cells_.pop_back();
                    break;
                }
            }
        }
        delete c;
    }

    T residual_load() const {
        std::lock_guard<std::mutex> g(residual_mu_);
        return residual_;
    }

    const uint64_t uid_ = next_uid();
    T identity_;
    mutable std::mutex residual_mu_;
    T residual_{};
    mutable std::mutex cells_mu_;
    std::vector<Cell*> cells_;
};

// ---- concrete ops ----

struct AddOp {
    template <typename T>
    void operator()(T& a, const T& b) const {
        a += b;
    }
};
struct MaxOp {
    template <typename T>
    void operator()(T& a, const T& b) const {
        if (b > a) a = b;
    }
};
struct MinOp {
    template <typename T>
    void operator()(T& a, const T& b) const {
        if (b < a) a = b;
    }
};

template <typename T>
class Adder : public Reducer<T, AddOp> {
public:
    Adder() : Reducer<T, AddOp>(T()) {}
};

template <typename T>
class Maxer : public Reducer<T, MaxOp> {
public:
    Maxer() : Reducer<T, MaxOp>(std::numeric_limits<T>::lowest()) {}
};

template <typename T>
class Miner : public Reducer<T, MinOp> {
public:
    Miner() : Reducer<T, MinOp>(std::numeric_limits<T>::max()) {}
};

// A process-lifetime counter whose tvar registration happens on FIRST
// USE, never at static-init time (the variable registry must not be
// entered from static constructors), and whose storage is leaked so
// static-teardown-time increments stay safe. Declare at namespace
// scope: `static LazyAdder g_foo("my_counter");  *g_foo << 1;`.
class LazyAdder {
public:
    constexpr explicit LazyAdder(const char* name) : name_(name) {}

    Adder<int64_t>& operator*() {
        Adder<int64_t>* a = adder_.load(std::memory_order_acquire);
        if (__builtin_expect(a == nullptr, 0)) {
            auto* fresh = new Adder<int64_t>;
            Adder<int64_t>* expected = nullptr;
            if (adder_.compare_exchange_strong(expected, fresh,
                                               std::memory_order_acq_rel)) {
                fresh->expose(name_);
                a = fresh;
            } else {
                delete fresh;  // lost the race; expected holds the winner
                a = expected;
            }
        }
        return *a;
    }

private:
    const char* name_;
    std::atomic<Adder<int64_t>*> adder_{nullptr};
};

// IntCell: one lock-free atomic int64 behind the Variable interface —
// default-constructible (usable as the T of a MultiDimension family)
// and cheap enough to update from scheduler/event-loop hot paths where
// even a Reducer's uncontended TLS-cell lock is too much. The writer
// holds the cell pointer (get_stats once, then relaxed atomics).
class IntCell : public Variable {
public:
    IntCell() = default;
    ~IntCell() override { hide(); }
    void add(int64_t d) { v_.fetch_add(d, std::memory_order_relaxed); }
    void set(int64_t x) { v_.store(x, std::memory_order_relaxed); }
    // Monotonic high-water update (run-queue depth, queued-write bytes).
    void update_max(int64_t x) {
        int64_t cur = v_.load(std::memory_order_relaxed);
        while (x > cur &&
               !v_.compare_exchange_weak(cur, x, std::memory_order_relaxed)) {
        }
    }
    int64_t get() const { return v_.load(std::memory_order_relaxed); }
    std::string get_description() const override {
        std::ostringstream os;
        os << get();
        return os.str();
    }

private:
    std::atomic<int64_t> v_{0};
};

// PassiveStatus: value computed on read (reference src/bvar/passive_status.h).
template <typename T>
class PassiveStatus : public Variable {
public:
    using Getter = T (*)(void*);
    PassiveStatus(Getter getter, void* arg) : getter_(getter), arg_(arg) {}
    ~PassiveStatus() override { hide(); }
    T get_value() const { return getter_(arg_); }
    std::string get_description() const override {
        std::ostringstream os;
        os << get_value();
        return os.str();
    }

private:
    Getter getter_;
    void* arg_;
};

// Status: directly-set value (reference src/bvar/status.h).
template <typename T>
class Status : public Variable {
public:
    explicit Status(T v = T()) : value_(v) {}
    // Unregister BEFORE members are destroyed: a /vars scrape between
    // ~Status and ~Variable would virtual-dispatch into a half-dead object.
    ~Status() override { hide(); }
    void set_value(const T& v) {
        std::lock_guard<std::mutex> g(mu_);
        value_ = v;
    }
    T get_value() const {
        std::lock_guard<std::mutex> g(mu_);
        return value_;
    }
    std::string get_description() const override {
        std::ostringstream os;
        os << get_value();
        return os.str();
    }

private:
    mutable std::mutex mu_;
    T value_;
};

}  // namespace tpurpc
