// Process-level metrics (cpu/memory/fds/threads/io) — reference
// src/bvar/default_variables.cpp. Idempotent; called at server startup so
// /vars and /metrics are scrape-worthy out of the box.
#pragma once

namespace tpurpc {

void ExposeProcessVariables();

// Flag→var bridge: every registered runtime flag becomes a
// `flag_<name>` PassiveStatus in /vars (bools render 0/1, numerics pass
// through — both scrape-able at /metrics; strings stay /vars-only), so a
// live flag flip is visible alongside the metrics it changes. Idempotent
// and re-runnable (later registrations picked up on the next call).
void ExposeFlagVariables();

}  // namespace tpurpc
