// Process-level metrics (cpu/memory/fds/threads/io) — reference
// src/bvar/default_variables.cpp. Idempotent; called at server startup so
// /vars and /metrics are scrape-worthy out of the box.
#pragma once

namespace tpurpc {

void ExposeProcessVariables();

}  // namespace tpurpc
