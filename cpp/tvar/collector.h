// Collector: the sampling pipeline for OBJECTS (not counters) — the
// backbone of rpcz spans and rpc_dump.
//
// Modeled on reference src/bvar/collector.h:46-123 + collector.cpp:38 (a
// global speed limit of ~N samples/second decides up-front whether an
// expensive record is created at all; created records are pushed onto a
// wait-free MPSC list and a background thread dispatches them out of the
// request path). Here: sample() is the token gate, submit() the wait-free
// push, and each Collected subclass implements dispatch() (runs on the
// collector thread, which then deletes the object).
#pragma once

#include <atomic>
#include <cstdint>

namespace tpurpc {

class Collected {
public:
    virtual ~Collected() = default;
    // Runs on the collector background thread; the object is deleted
    // right after.
    virtual void dispatch() = 0;

private:
    friend class Collector;
    Collected* next_ = nullptr;
};

class Collector {
public:
    // Intentionally leaked (process-lifetime background thread).
    static Collector* singleton();

    // Global speed gate: true at most max_samples_per_second() times per
    // second (reference bvar_collector_max_pending_samples spirit).
    // Callers create the expensive record only when this returns true.
    bool sample();

    // Hand off a record to the background dispatcher (wait-free push).
    void submit(Collected* obj);

    int64_t max_samples_per_second() const { return max_per_second_; }
    int64_t ndispatched() const {
        return ndispatched_.load(std::memory_order_relaxed);
    }

private:
    Collector();
    void Run();

    std::atomic<Collected*> head_{nullptr};
    std::atomic<int64_t> window_start_us_{0};
    std::atomic<int64_t> window_count_{0};
    std::atomic<int64_t> ndispatched_{0};
    const int64_t max_per_second_ = 1000;
};

}  // namespace tpurpc
