#include "tvar/multi_dimension.h"

#include <cstdlib>

namespace tpurpc {

namespace multi_dim_detail {

bool numeric(const std::string& s) {
    char* end = nullptr;
    strtod(s.c_str(), &end);
    return end != s.c_str() && *end == '\0' && !s.empty();
}

}  // namespace multi_dim_detail

namespace {

struct LabelledRegistry {
    std::mutex mu;
    std::map<std::string, MultiDimensionBase*> metrics;
};
LabelledRegistry* lreg() {
    static LabelledRegistry* r = new LabelledRegistry;
    return r;
}

}  // namespace

void RegisterLabelledMetric(const std::string& name,
                            MultiDimensionBase* m) {
    std::lock_guard<std::mutex> g(lreg()->mu);
    lreg()->metrics[name] = m;
}

void UnregisterLabelledMetric(const std::string& name) {
    std::lock_guard<std::mutex> g(lreg()->mu);
    lreg()->metrics.erase(name);
}

std::string DumpLabelledMetrics() {
    std::map<std::string, MultiDimensionBase*> snapshot;
    {
        std::lock_guard<std::mutex> g(lreg()->mu);
        snapshot = lreg()->metrics;
    }
    std::string out;
    for (const auto& kv : snapshot) {
        out += kv.second->prometheus_text(kv.first);
    }
    return out;
}

}  // namespace tpurpc
