#include "tvar/multi_dimension.h"

namespace tpurpc {

namespace {

struct LabelledRegistry {
    std::mutex mu;
    std::map<std::string, MultiDimensionBase*> metrics;
};
LabelledRegistry* lreg() {
    static LabelledRegistry* r = new LabelledRegistry;
    return r;
}

}  // namespace

void RegisterLabelledMetric(const std::string& name,
                            MultiDimensionBase* m) {
    std::lock_guard<std::mutex> g(lreg()->mu);
    lreg()->metrics[name] = m;
}

void UnregisterLabelledMetric(const std::string& name) {
    std::lock_guard<std::mutex> g(lreg()->mu);
    lreg()->metrics.erase(name);
}

std::string DumpLabelledMetrics() {
    std::map<std::string, MultiDimensionBase*> snapshot;
    {
        std::lock_guard<std::mutex> g(lreg()->mu);
        snapshot = lreg()->metrics;
    }
    std::string out;
    for (const auto& kv : snapshot) {
        out += kv.second->prometheus_text(kv.first);
    }
    return out;
}

}  // namespace tpurpc
