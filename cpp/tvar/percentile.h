// Percentile: quantile estimation for latency values.
//
// The reference uses per-thread reservoir buckets combined on sample
// (src/bvar/detail/percentile.h). We use a log-scale histogram instead:
// fixed 256-bucket layout (32 octaves x 8 sub-buckets covering 1us..2^32us)
// with relaxed atomic counters — O(1) contention-free writes, O(256) reads,
// exact below 16, ~7% worst-case relative error above, and histograms merge
// trivially across
// threads and windows (prometheus-style). This trades the reference's exact
// small-sample quantiles for simpler, faster, mergeable state.
#pragma once

#include <atomic>
#include <cstdint>
#include <cstring>

namespace tpurpc {

class PercentileHistogram {
public:
    static constexpr int kOctaves = 32;
    static constexpr int kSub = 8;
    static constexpr int kBuckets = kOctaves * kSub;

    void add(int64_t value) {
        buckets_[bucket_of(value)].fetch_add(1, std::memory_order_relaxed);
    }

    // Copy counters out (for window snapshots).
    void snapshot(uint64_t out[kBuckets]) const {
        for (int i = 0; i < kBuckets; ++i) {
            out[i] = buckets_[i].load(std::memory_order_relaxed);
        }
    }

    static int bucket_of(int64_t value) {
        if (value < 0) value = 0;
        uint64_t v = (uint64_t)value;
        if (v < kSub) return (int)v;  // exact for tiny values
        const int msb = 63 - __builtin_clzll(v);
        const int octave = msb;
        const int sub = (int)((v >> (msb - 3)) & 7);  // top 3 bits after msb
        int idx = octave * kSub + sub;
        return idx < kBuckets ? idx : kBuckets - 1;
    }

    // Representative value of a bucket: exact for values < 16 (octaves 0-3
    // store them exactly), geometric midpoint above.
    static int64_t bucket_value(int idx) {
        if (idx < kSub) return idx;  // exact 0..7
        const int octave = idx / kSub;
        const int sub = idx % kSub;
        const uint64_t base = (uint64_t)1 << octave;
        // octave 3: base/8 == 1, base/16 == 0 -> exact 8..15.
        return (int64_t)(base + (base / 8) * (uint64_t)sub + base / 16);
    }

private:
    std::atomic<uint64_t> buckets_[kBuckets] = {};
};

// A plain (non-atomic) histogram snapshot with quantile math.
struct HistogramSnapshot {
    uint64_t buckets[PercentileHistogram::kBuckets] = {};

    void add_from(const PercentileHistogram& h) {
        uint64_t tmp[PercentileHistogram::kBuckets];
        h.snapshot(tmp);
        for (int i = 0; i < PercentileHistogram::kBuckets; ++i) {
            buckets[i] += tmp[i];
        }
    }
    void subtract(const HistogramSnapshot& other) {
        for (int i = 0; i < PercentileHistogram::kBuckets; ++i) {
            buckets[i] -= other.buckets[i];
        }
    }
    uint64_t total() const {
        uint64_t t = 0;
        for (uint64_t b : buckets) t += b;
        return t;
    }
    // q in (0,1]; returns representative latency value.
    int64_t quantile(double q) const {
        const uint64_t t = total();
        if (t == 0) return 0;
        uint64_t target = (uint64_t)(q * (double)t);
        if (target >= t) target = t - 1;
        uint64_t seen = 0;
        for (int i = 0; i < PercentileHistogram::kBuckets; ++i) {
            seen += buckets[i];
            if (seen > target) return PercentileHistogram::bucket_value(i);
        }
        return PercentileHistogram::bucket_value(
            PercentileHistogram::kBuckets - 1);
    }
};

}  // namespace tpurpc
