#include "tvar/collector.h"

#include <thread>

#include "tbase/time.h"

namespace tpurpc {

Collector* Collector::singleton() {
    static Collector* c = new Collector;
    return c;
}

Collector::Collector() {
    std::thread([this] { Run(); }).detach();
}

bool Collector::sample() {
    const int64_t now = monotonic_time_us();
    const int64_t ws = window_start_us_.load(std::memory_order_relaxed);
    if (now - ws >= 1000 * 1000) {
        // New one-second window (benign race: worst case two resetters
        // both zero the count — a few extra samples, never unbounded).
        window_start_us_.store(now, std::memory_order_relaxed);
        window_count_.store(0, std::memory_order_relaxed);
    }
    return window_count_.fetch_add(1, std::memory_order_relaxed) <
           max_per_second_;
}

void Collector::submit(Collected* obj) {
    Collected* old = head_.load(std::memory_order_relaxed);
    do {
        obj->next_ = old;
    } while (!head_.compare_exchange_weak(old, obj,
                                          std::memory_order_release,
                                          std::memory_order_relaxed));
}

void Collector::Run() {
    while (true) {
        std::this_thread::sleep_for(std::chrono::milliseconds(50));
        Collected* grabbed =
            head_.exchange(nullptr, std::memory_order_acquire);
        // Reverse to submission order.
        Collected* rev = nullptr;
        while (grabbed != nullptr) {
            Collected* next = grabbed->next_;
            grabbed->next_ = rev;
            rev = grabbed;
            grabbed = next;
        }
        while (rev != nullptr) {
            Collected* next = rev->next_;
            rev->dispatch();
            delete rev;
            ndispatched_.fetch_add(1, std::memory_order_relaxed);
            rev = next;
        }
    }
}

}  // namespace tpurpc
