// Time-series rings for every exposed variable — bvar "detail" series.
//
// Modeled on reference src/bvar/detail/series.h (Series<T>: per-second
// ring of 60, rolling into per-minute 60 and per-hour 24, appended by the
// 1Hz sampler thread). Instantaneous /vars values answer "what is it
// NOW"; these rings answer "what was it over the last minute/hour/day",
// which is what post-hoc debugging of a soak actually needs. Rendered as
// /vars?series=<name> JSON and as inline sparklines on the /vars page.
//
// The ring itself is tick-driven — append() IS the clock (one call = one
// second) — so boundary rollover is testable under a fake clock by just
// calling append() N times.
#pragma once

#include <cstdint>
#include <map>
#include <mutex>
#include <string>
#include <vector>

namespace tpurpc {

class SeriesRing {
public:
    static constexpr int kSeconds = 60;
    static constexpr int kMinutes = 60;
    static constexpr int kHours = 24;

    // One per-second observation. Every 60th append folds the mean of the
    // last 60 seconds into the minute ring; every 60th minute entry folds
    // the mean of the last 60 minutes into the hour ring.
    void append(double v);

    int64_t ticks() const { return nsecond_; }

    // Oldest-first, zero-padded to the full ring length (a scrape always
    // sees exactly 60/60/24 points).
    std::vector<double> seconds() const { return unroll(second_, kSeconds, nsecond_); }
    std::vector<double> minutes() const { return unroll(minute_, kMinutes, nminute_); }
    std::vector<double> hours() const { return unroll(hour_, kHours, nhour_); }

    // {"name":..., "ticks":N, "second":[...], "minute":[...], "hour":[...]}
    std::string ToJson(const std::string& name) const;

    // Unicode sparkline of the last `n` seconds (portal inline rendering).
    std::string Sparkline(int n = kSeconds) const;

private:
    static std::vector<double> unroll(const double* ring, int cap,
                                      int64_t n);

    double second_[kSeconds] = {};
    double minute_[kMinutes] = {};
    double hour_[kHours] = {};
    int64_t nsecond_ = 0;  // total appends; position = nsecond_ % 60
    int64_t nminute_ = 0;
    int64_t nhour_ = 0;
};

// Global per-variable series store, fed once per second by the
// SamplerCollector: every exposed variable's numeric_fields() land in a
// ring named <var><suffix>. Gated by -tvar_save_series (live-togglable).
class SeriesCollector {
public:
    static SeriesCollector* singleton();

    // Idempotent: registers the 1Hz tick with the SamplerCollector.
    void Enable();

    // One sampling tick (normally driven by the sampler thread; tests
    // drive it directly). Skips work when -tvar_save_series is false.
    void Tick();

    // JSON for one series, or empty when unknown.
    std::string SeriesJson(const std::string& name) const;
    // Sparkline for the ring exactly named `name` ("" when absent) —
    // the /vars page decorates plain numeric vars with this.
    std::string SparklineFor(const std::string& name) const;
    // All known series names (the /vars?series= index).
    std::vector<std::string> Names() const;

private:
    SeriesCollector() = default;
    // Bounded: a runaway label cardinality must not eat the heap.
    static constexpr size_t kMaxSeries = 1024;

    mutable std::mutex mu_;
    std::map<std::string, SeriesRing> rings_;
    bool enabled_ = false;
};

}  // namespace tpurpc
