// tvar: low-contention write-mostly metrics. Base Variable + named registry.
//
// Modeled on the reference's bvar (src/bvar/variable.h:118-197: expose /
// dump_exposed / list_exposed). Every subsystem of the framework exposes
// counters through this registry; the /vars builtin service and the
// prometheus exporter render it.
#pragma once

#include <mutex>
#include <string>
#include <vector>

namespace tpurpc {

class Variable {
public:
    Variable() = default;
    virtual ~Variable();
    Variable(const Variable&) = delete;
    Variable& operator=(const Variable&) = delete;

    // Register under `name` (empty hides it). Re-exposing renames.
    int expose(const std::string& name);
    void hide();
    const std::string& name() const { return name_; }
    bool is_exposed() const { return !name_.empty(); }

    // Render current value as text (the /vars format).
    virtual std::string get_description() const = 0;

    // Registry queries.
    static std::vector<std::string> list_exposed();
    // Returns false if no such variable.
    static bool describe_exposed(const std::string& name, std::string* out);
    // name -> description for every exposed variable.
    static std::vector<std::pair<std::string, std::string>> dump_exposed();

private:
    std::string name_;
};

}  // namespace tpurpc
