// tvar: low-contention write-mostly metrics. Base Variable + named registry.
//
// Modeled on the reference's bvar (src/bvar/variable.h:118-197: expose /
// dump_exposed / list_exposed). Every subsystem of the framework exposes
// counters through this registry; the /vars builtin service and the
// prometheus exporter render it.
#pragma once

#include <functional>
#include <mutex>
#include <string>
#include <utility>
#include <vector>

namespace tpurpc {

class Variable {
public:
    Variable() = default;
    virtual ~Variable();
    Variable(const Variable&) = delete;
    Variable& operator=(const Variable&) = delete;

    // Register under `name` (empty hides it). Re-exposing renames.
    int expose(const std::string& name);
    void hide();
    const std::string& name() const { return name_; }
    bool is_exposed() const { return !name_.empty(); }

    // Render current value as text (the /vars format).
    virtual std::string get_description() const = 0;

    // Numeric sub-values of this variable, as (suffix, value) pairs —
    // the time-series sampler and the default prometheus exposition both
    // consume this. Default: {("", v)} when get_description() is a plain
    // number, empty otherwise. Composite variables (LatencyRecorder)
    // override to yield one entry per field ({"_qps", ...}, ...).
    virtual std::vector<std::pair<std::string, double>> numeric_fields()
        const;

    // Prometheus text exposition of this variable under (sanitized)
    // `name`, appended to *out — TYPE line(s) included. Default: one
    // gauge per numeric field. LatencyRecorder overrides to emit a real
    // summary family; MultiDimension emits one sample per label tuple.
    virtual void prometheus_text(const std::string& name,
                                 std::string* out) const;

    // One labelled series of family `name`: append sample lines only (no
    // TYPE line), merging `labels` (`k="v",...`) into each sample's label
    // set; returns the family type for the caller's single TYPE line.
    // Default: one gauge sample per numeric field. Used by MultiDimension
    // so a labelled LatencyRecorder stays a well-formed summary.
    virtual const char* prometheus_labelled_samples(const std::string& name,
                                                    const std::string& labels,
                                                    std::string* out) const;

    // Registry queries.
    static std::vector<std::string> list_exposed();
    // Returns false if no such variable.
    static bool describe_exposed(const std::string& name, std::string* out);
    // name -> description for every exposed variable.
    static std::vector<std::pair<std::string, std::string>> dump_exposed();
    // Visit every exposed variable under the registry lock (callbacks
    // must not re-enter the registry).
    static void for_each_exposed(
        const std::function<void(const std::string&, const Variable*)>& fn);
    // The whole registry in prometheus text exposition format — the ONE
    // sanitize + render path behind /metrics.
    static std::string dump_prometheus();

private:
    std::string name_;
};

// Central metric-name sanitization: prometheus names must match
// [a-zA-Z_:][a-zA-Z0-9_:]* — every exporter path goes through here.
std::string SanitizeMetricName(std::string name);
// True when `s` parses fully as a number.
bool IsNumericLiteral(const std::string& s);
// Render a sample value: integral doubles print without an exponent
// (counters stay "1000000", not "1e+06"), the rest as %.17g.
std::string FormatMetricValue(double v);

}  // namespace tpurpc
