#include "tvar/series.h"

#include <cmath>
#include <sstream>

#include "tbase/flags.h"
#include "tvar/variable.h"
#include "tvar/window.h"

// Live-togglable (reference -bvar_save_series, on by default): the rings
// cost one dump of every exposed variable per second.
DEFINE_bool(tvar_save_series, true,
            "sample every exposed variable into 60s/60min/24h rings");

namespace tpurpc {

void SeriesRing::append(double v) {
    second_[nsecond_ % kSeconds] = v;
    ++nsecond_;
    if (nsecond_ % kSeconds == 0) {
        double sum = 0;
        for (double s : second_) sum += s;
        minute_[nminute_ % kMinutes] = sum / kSeconds;
        ++nminute_;
        if (nminute_ % kMinutes == 0) {
            sum = 0;
            for (double m : minute_) sum += m;
            hour_[nhour_ % kHours] = sum / kMinutes;
            ++nhour_;
        }
    }
}

std::vector<double> SeriesRing::unroll(const double* ring, int cap,
                                       int64_t n) {
    std::vector<double> out((size_t)cap, 0.0);
    // Oldest-first: when the ring wrapped, the entry at n % cap is the
    // oldest; before that, entries [0, n) are already in order.
    const int64_t start = n >= cap ? n % cap : 0;
    const int64_t filled = n >= cap ? cap : n;
    const int64_t pad = cap - filled;
    for (int64_t i = 0; i < filled; ++i) {
        out[(size_t)(pad + i)] = ring[(start + i) % cap];
    }
    return out;
}

namespace {
void AppendJsonArray(std::ostringstream& os, const std::vector<double>& v) {
    os << "[";
    for (size_t i = 0; i < v.size(); ++i) {
        if (i > 0) os << ",";
        // JSON has no Inf/NaN literal — a non-finite sample (e.g. a
        // 0/0-ratio PassiveStatus) must not make the whole ring
        // unparseable; 0 keeps the trend readable.
        os << (std::isfinite(v[i]) ? FormatMetricValue(v[i]) : "0");
    }
    os << "]";
}
}  // namespace

std::string SeriesRing::ToJson(const std::string& name) const {
    std::ostringstream os;
    os << "{\"name\":\"" << name << "\",\"ticks\":" << nsecond_
       << ",\"second\":";
    AppendJsonArray(os, seconds());
    os << ",\"minute\":";
    AppendJsonArray(os, minutes());
    os << ",\"hour\":";
    AppendJsonArray(os, hours());
    os << "}";
    return os.str();
}

std::string SeriesRing::Sparkline(int n) const {
    static const char* kBlocks[] = {"▁", "▂", "▃", "▄",
                                    "▅", "▆", "▇", "█"};
    if (n > kSeconds) n = kSeconds;
    const std::vector<double> all = seconds();
    const std::vector<double> tail(all.end() - n, all.end());
    double lo = tail[0], hi = tail[0];
    for (double v : tail) {
        if (v < lo) lo = v;
        if (v > hi) hi = v;
    }
    std::string out;
    for (double v : tail) {
        const int idx =
            hi > lo ? (int)((v - lo) / (hi - lo) * 7.0 + 0.5) : 0;
        out += kBlocks[idx < 0 ? 0 : (idx > 7 ? 7 : idx)];
    }
    return out;
}

SeriesCollector* SeriesCollector::singleton() {
    static SeriesCollector* c = new SeriesCollector;
    return c;
}

void SeriesCollector::Enable() {
    {
        std::lock_guard<std::mutex> g(mu_);
        if (enabled_) return;
        enabled_ = true;
    }
    SamplerCollector::singleton()->add(
        [this] { Tick(); });  // process-lifetime: never removed
}

void SeriesCollector::Tick() {
    if (!FLAGS_tvar_save_series.get()) return;
    // Read all variables first (under the registry lock, like any /vars
    // dump), then update rings_ under mu_ only — the two locks are never
    // held together.
    std::vector<std::pair<std::string, double>> obs;
    Variable::for_each_exposed(
        [&obs](const std::string& name, const Variable* v) {
            for (const auto& f : v->numeric_fields()) {
                obs.emplace_back(name + f.first, f.second);
            }
        });
    std::lock_guard<std::mutex> g(mu_);
    for (const auto& o : obs) {
        auto it = rings_.find(o.first);
        if (it == rings_.end()) {
            if (rings_.size() >= kMaxSeries) continue;  // cardinality cap
            it = rings_.emplace(o.first, SeriesRing()).first;
        }
        it->second.append(o.second);
    }
}

std::string SeriesCollector::SeriesJson(const std::string& name) const {
    std::lock_guard<std::mutex> g(mu_);
    auto it = rings_.find(name);
    return it == rings_.end() ? "" : it->second.ToJson(name);
}

std::string SeriesCollector::SparklineFor(const std::string& name) const {
    std::lock_guard<std::mutex> g(mu_);
    auto it = rings_.find(name);
    return it == rings_.end() ? "" : it->second.Sparkline();
}

std::vector<std::string> SeriesCollector::Names() const {
    std::lock_guard<std::mutex> g(mu_);
    std::vector<std::string> out;
    out.reserve(rings_.size());
    for (const auto& kv : rings_) out.push_back(kv.first);
    return out;
}

}  // namespace tpurpc
