// Process-level variables: cpu, memory, fds, threads, io — exposed at
// /vars and scraped at /metrics.
//
// Modeled on reference src/bvar/default_variables.cpp:878 (PassiveStatus
// readers over /proc/self). Registered once by ExposeProcessVariables()
// (called from server startup); values are read lazily per scrape.
#include "tvar/default_variables.h"

#include <dirent.h>
#include <unistd.h>

#include <cinttypes>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <set>

#include "tbase/flags.h"
#include "tbase/time.h"
#include "tvar/reducer.h"

namespace tpurpc {

namespace {

struct ProcStat {
    int64_t utime_ticks = 0;
    int64_t stime_ticks = 0;
    int64_t num_threads = 0;
    int64_t vsize_bytes = 0;
    int64_t rss_bytes = 0;
};

bool ReadProcStat(ProcStat* out) {
    FILE* f = fopen("/proc/self/stat", "r");
    if (f == nullptr) return false;
    char buf[1024];
    const size_t n = fread(buf, 1, sizeof(buf) - 1, f);
    fclose(f);
    if (n == 0) return false;
    buf[n] = '\0';
    // Field 2 (comm) may contain spaces: skip past the closing paren.
    const char* p = strrchr(buf, ')');
    if (p == nullptr) return false;
    p += 2;  // skip ") "
    // Fields from 3 (state) onward; utime=14 stime=15 num_threads=20
    // vsize=23 rss=24 (1-based).
    long long utime = 0, stime = 0, nthreads = 0, vsize = 0, rss = 0;
    // state(3) + 10 ints to reach field 14.
    int field = 3;
    const char* q = p;
    while (*q && field < 14) {
        if (*q == ' ') ++field;
        ++q;
    }
    if (sscanf(q, "%lld %lld", &utime, &stime) != 2) return false;
    while (*q && field < 20) {
        if (*q == ' ') ++field;
        ++q;
    }
    if (sscanf(q, "%lld", &nthreads) != 1) return false;
    while (*q && field < 23) {
        if (*q == ' ') ++field;
        ++q;
    }
    if (sscanf(q, "%lld %lld", &vsize, &rss) != 2) return false;
    out->utime_ticks = utime;
    out->stime_ticks = stime;
    out->num_threads = nthreads;
    out->vsize_bytes = vsize;
    out->rss_bytes = rss * sysconf(_SC_PAGESIZE);
    return true;
}

int64_t CountFds() {
    DIR* d = opendir("/proc/self/fd");
    if (d == nullptr) return -1;
    int64_t n = 0;
    while (readdir(d) != nullptr) ++n;
    closedir(d);
    // Drop ".", ".." and the opendir() handle itself.
    return n > 3 ? n - 3 : 0;
}

bool ReadProcIo(int64_t* read_bytes, int64_t* write_bytes) {
    FILE* f = fopen("/proc/self/io", "r");
    if (f == nullptr) return false;
    char line[128];
    long long rb = -1, wb = -1;
    while (fgets(line, sizeof(line), f) != nullptr) {
        if (sscanf(line, "read_bytes: %lld", &rb) == 1) continue;
        if (sscanf(line, "write_bytes: %lld", &wb) == 1) continue;
    }
    fclose(f);
    *read_bytes = rb;
    *write_bytes = wb;
    return rb >= 0 && wb >= 0;
}

const int64_t g_start_us = monotonic_time_us();

int64_t ticks_to_ms(int64_t ticks) {
    static const long hz = sysconf(_SC_CLK_TCK);
    return hz > 0 ? ticks * 1000 / hz : 0;
}

// One PassiveStatus per metric, all sharing the /proc readers.
template <int64_t (*Fn)()>
struct Gauge : public Variable {
    std::string get_description() const override {
        char buf[32];
        snprintf(buf, sizeof(buf), "%" PRId64, Fn());
        return buf;
    }
};

// One /proc read shared by all gauges of a scrape (reference
// CachedReader): values within a dump stay mutually consistent and a
// 9-gauge scrape does 2 file opens, not 7.
ProcStat cached_stat() {
    static std::mutex mu;
    static ProcStat cached;
    static int64_t read_at_us = -1;
    std::lock_guard<std::mutex> g(mu);
    const int64_t now = monotonic_time_us();
    if (read_at_us < 0 || now - read_at_us > 100 * 1000) {
        ProcStat s;
        if (ReadProcStat(&s)) cached = s;
        read_at_us = now;
    }
    return cached;
}

struct ProcIo {
    int64_t read_bytes = 0;
    int64_t write_bytes = 0;
};
ProcIo cached_io() {
    static std::mutex mu;
    static ProcIo cached;
    static int64_t read_at_us = -1;
    std::lock_guard<std::mutex> g(mu);
    const int64_t now = monotonic_time_us();
    if (read_at_us < 0 || now - read_at_us > 100 * 1000) {
        int64_t r = 0, w = 0;
        if (ReadProcIo(&r, &w)) cached = ProcIo{r, w};
        read_at_us = now;
    }
    return cached;
}

int64_t cpu_user_ms() { return ticks_to_ms(cached_stat().utime_ticks); }
int64_t cpu_system_ms() { return ticks_to_ms(cached_stat().stime_ticks); }
int64_t mem_resident() { return cached_stat().rss_bytes; }
int64_t mem_virtual() { return cached_stat().vsize_bytes; }
int64_t thread_count() { return cached_stat().num_threads; }
int64_t fd_count() { return CountFds(); }
int64_t uptime_s() { return (monotonic_time_us() - g_start_us) / 1000000; }
int64_t io_read() { return cached_io().read_bytes; }
int64_t io_write() { return cached_io().write_bytes; }

}  // namespace

namespace {

// One bridge variable per flag (VERDICT gap: flag flips were invisible
// to scrapes). Bools render 0/1 so prometheus picks them up; numeric
// flags pass through; string flags stay /vars-only (non-numeric
// descriptions are skipped by the exporter).
struct FlagVariable : public Variable {
    explicit FlagVariable(FlagBase* f) : flag(f) {}
    std::string get_description() const override {
        const std::string v = flag->GetString();
        if (strcmp(flag->type(), "bool") == 0) {
            return v == "true" ? "1" : "0";
        }
        return v;
    }
    FlagBase* flag;
};

}  // namespace

void ExposeFlagVariables() {
    // Tracks what is already bridged so restarts / late-registered flags
    // are handled without duplicates (expose() would retake the name
    // anyway, but the old bridge object would leak its registry slot).
    static std::mutex mu;
    static std::set<std::string>* bridged = new std::set<std::string>;
    std::lock_guard<std::mutex> g(mu);
    for (FlagBase* f : ListFlags()) {
        if (!bridged->insert(f->name()).second) continue;
        // Intentionally leaked: flags are process-lifetime.
        (new FlagVariable(f))->expose(std::string("flag_") + f->name());
    }
}

void ExposeProcessVariables() {
    static std::once_flag once;
    std::call_once(once, [] {
        // Intentionally leaked: process-lifetime variables.
        (new Gauge<cpu_user_ms>())->expose("process_cpu_user_ms");
        (new Gauge<cpu_system_ms>())->expose("process_cpu_system_ms");
        (new Gauge<mem_resident>())->expose("process_memory_resident_bytes");
        (new Gauge<mem_virtual>())->expose("process_memory_virtual_bytes");
        (new Gauge<thread_count>())->expose("process_thread_count");
        (new Gauge<fd_count>())->expose("process_fd_count");
        (new Gauge<uptime_s>())->expose("process_uptime_seconds");
        (new Gauge<io_read>())->expose("process_io_read_bytes");
        (new Gauge<io_write>())->expose("process_io_write_bytes");
    });
}

}  // namespace tpurpc
