// MultiDimension<T>: labelled metrics — a map of label-value tuples to an
// underlying variable, exported to prometheus with label sets.
//
// Reference: src/bvar/multi_dimension{.h,_inl.h} (MultiDimension<bvar::T>
// keyed by a label list, exposed through /brpc_metrics with
// {label="value"} series). T is any Variable-like with get_description()
// returning a number (Adder<int64_t>, LatencyRecorder, ...).
#pragma once

#include <map>
#include <memory>
#include <mutex>
#include <sstream>
#include <string>
#include <vector>

#include "tvar/variable.h"

namespace tpurpc {

template <typename T>
class MultiDimension : public Variable {
public:
    // labels: the dimension NAMES, fixed at construction
    // (e.g. {"method", "peer"}).
    explicit MultiDimension(std::vector<std::string> labels)
        : labels_(std::move(labels)) {}
    ~MultiDimension() override { hide(); }

    // The stat for one label-value tuple (created on first use). The
    // returned pointer lives as long as this MultiDimension.
    T* get_stats(const std::vector<std::string>& values) {
        std::lock_guard<std::mutex> g(mu_);
        auto it = stats_.find(values);
        if (it == stats_.end()) {
            it = stats_.emplace(values, std::make_unique<T>()).first;
        }
        return it->second.get();
    }

    // Remove one series (e.g. a departed peer).
    void delete_stats(const std::vector<std::string>& values) {
        std::lock_guard<std::mutex> g(mu_);
        stats_.erase(values);
    }

    size_t count_stats() const {
        std::lock_guard<std::mutex> g(mu_);
        return stats_.size();
    }

    const std::vector<std::string>& labels() const { return labels_; }

    // /vars rendering: one line per series.
    std::string get_description() const override {
        std::ostringstream os;
        std::lock_guard<std::mutex> g(mu_);
        os << stats_.size() << " series";
        for (const auto& kv : stats_) {
            os << "\n  {" << label_pairs(kv.first)
               << "} : " << kv.second->get_description();
        }
        return os.str();
    }

    // Prometheus exposition: one TYPE line for the family, then each
    // label tuple's samples via the stat's own labelled-sample hook — a
    // labelled Adder stays a gauge, a labelled LatencyRecorder a proper
    // summary (no JSON re-parsing).
    std::string prometheus_text(const std::string& name) const {
        std::lock_guard<std::mutex> g(mu_);
        std::string samples;
        const char* type = nullptr;
        for (const auto& kv : stats_) {
            type = kv.second->prometheus_labelled_samples(
                name, label_pairs(kv.first), &samples);
        }
        if (samples.empty() || type == nullptr) return "";
        return "# TYPE " + name + " " + type + "\n" + samples;
    }

    // Exported through the registry-wide /metrics dump too (a
    // MultiDimension is itself an exposed Variable).
    void prometheus_text(const std::string& name,
                         std::string* out) const override {
        *out += prometheus_text(name);
    }

    // Per-tuple series: each label tuple becomes a "_<label>_<value>"
    // suffix so labelled families feed the 60s/60min/24h rings —
    // /vars?series=rpc_dispatcher_epoll_waits_loop_0 answers "what did
    // loop 0 do over the last minute". Bounded at kMaxSeriesTuples
    // tuples (the dispatcher/scheduler/connection families this exists
    // for are low-cardinality by construction; a runaway peer-labelled
    // family must not flood the SeriesCollector, which caps globally
    // too).
    std::vector<std::pair<std::string, double>> numeric_fields()
        const override {
        std::vector<std::pair<std::string, double>> out;
        std::lock_guard<std::mutex> g(mu_);
        size_t ntuples = 0;
        for (const auto& kv : stats_) {
            if (++ntuples > kMaxSeriesTuples) break;
            std::string suffix;
            for (size_t i = 0; i < labels_.size() && i < kv.first.size();
                 ++i) {
                suffix += "_" + labels_[i] + "_" + kv.first[i];
            }
            suffix = SanitizeMetricName(suffix);
            for (const auto& f : kv.second->numeric_fields()) {
                out.emplace_back(suffix + f.first, f.second);
            }
        }
        return out;
    }

private:
    static constexpr size_t kMaxSeriesTuples = 16;

    std::string label_pairs(const std::vector<std::string>& values) const {
        std::ostringstream os;
        for (size_t i = 0; i < labels_.size() && i < values.size(); ++i) {
            if (i > 0) os << ",";
            os << labels_[i] << "=\"" << values[i] << "\"";
        }
        return os.str();
    }

    std::vector<std::string> labels_;
    mutable std::mutex mu_;
    std::map<std::vector<std::string>, std::unique_ptr<T>> stats_;
};

// Registry of MultiDimension instances for the /metrics exporter (plain
// Variables render through get_description; labelled ones need the
// per-series exposition).
class MultiDimensionBase {
public:
    virtual ~MultiDimensionBase() = default;
    virtual std::string prometheus_text(const std::string& name) const = 0;
};

void RegisterLabelledMetric(const std::string& name, MultiDimensionBase* m);
void UnregisterLabelledMetric(const std::string& name);
// All registered labelled metrics rendered for /metrics.
std::string DumpLabelledMetrics();

template <typename T>
class LabelledMetric : public MultiDimension<T>, public MultiDimensionBase {
public:
    LabelledMetric(const std::string& name, std::vector<std::string> labels)
        : MultiDimension<T>(std::move(labels)), name_(name) {
        this->expose(name);
        RegisterLabelledMetric(name, this);
    }
    ~LabelledMetric() override { UnregisterLabelledMetric(name_); }

    std::string prometheus_text(const std::string& name) const override {
        return MultiDimension<T>::prometheus_text(name);
    }

private:
    std::string name_;
};

}  // namespace tpurpc
