// LatencyRecorder: the one-liner latency metric — qps + avg + percentiles
// (p50/p90/p99/p999) + max over a sliding window.
//
// Modeled on reference src/bvar/latency_recorder.h (LatencyRecorder
// composes IntRecorder + Percentile + Maxer + qps windows). Ours composes
// an Adder<count>, Adder<sum>, the log-histogram PercentileHistogram (see
// percentile.h for the design tradeoff vs the reference's reservoirs), and
// a windowed max.
#pragma once

#include <cstdint>
#include <deque>
#include <mutex>
#include <sstream>

#include "tbase/time.h"
#include "tvar/percentile.h"
#include "tvar/reducer.h"
#include "tvar/window.h"

namespace tpurpc {

class LatencyRecorder : public Variable {
public:
    explicit LatencyRecorder(int window_size = 10)
        : window_size_(window_size) {
        sampler_id_ = SamplerCollector::singleton()->add([this] { take_sample(); });
    }
    ~LatencyRecorder() override {
        SamplerCollector::singleton()->remove(sampler_id_);
        hide();
    }

    // Record one latency (microseconds).
    LatencyRecorder& operator<<(int64_t latency_us) {
        count_.fetch_add(1, std::memory_order_relaxed);
        sum_.fetch_add(latency_us, std::memory_order_relaxed);
        hist_.add(latency_us);
        // Windowed max: racy update is fine (metrics).
        int64_t cur = live_max_.load(std::memory_order_relaxed);
        while (latency_us > cur &&
               !live_max_.compare_exchange_weak(cur, latency_us)) {
        }
        return *this;
    }

    int64_t count() const { return count_.load(std::memory_order_relaxed); }
    // Cumulative sum of recorded latencies (us) — the prometheus summary
    // `_sum` (monotonic, like `_count`; quantiles stay windowed).
    int64_t sum() const { return sum_.load(std::memory_order_relaxed); }

    // Window stats (over the last window_size seconds).
    int64_t qps() const;
    int64_t latency() const;  // avg us
    int64_t latency_percentile(double q) const;
    int64_t max_latency() const;

    // One window_delta() snapshot for all fields: 1/6 the cost of deriving
    // each independently, and the JSON is internally consistent.
    std::string get_description() const override;

    // Per-field values for time-series sampling (name_qps, name_p99, ...)
    // without re-parsing the JSON description.
    std::vector<std::pair<std::string, double>> numeric_fields()
        const override;

    // A real prometheus summary family: quantile-labelled samples +
    // cumulative `_sum`/`_count` — replaces the flat `_field` gauges the
    // exporter used to parse out of the JSON description.
    void prometheus_text(const std::string& name,
                         std::string* out) const override;
    const char* prometheus_labelled_samples(const std::string& name,
                                            const std::string& labels,
                                            std::string* out) const override;

    // Expose under a family name (like the reference's
    // LatencyRecorder::expose creating name_latency, name_qps, ...).
    int expose(const std::string& prefix) { return Variable::expose(prefix); }

private:
    void take_sample();

    struct Snap {
        int64_t count = 0;
        int64_t sum = 0;
        int64_t max = 0;
        HistogramSnapshot hist;
    };
    Snap window_delta() const;

    int window_size_;
    uint64_t sampler_id_ = 0;
    std::atomic<int64_t> count_{0};
    std::atomic<int64_t> sum_{0};
    std::atomic<int64_t> live_max_{0};
    PercentileHistogram hist_;
    mutable std::mutex mu_;
    std::deque<Snap> samples_;
};

}  // namespace tpurpc
