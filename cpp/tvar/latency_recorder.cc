#include "tvar/latency_recorder.h"

namespace tpurpc {

void LatencyRecorder::take_sample() {
    Snap s;
    s.count = count_.load(std::memory_order_relaxed);
    s.sum = sum_.load(std::memory_order_relaxed);
    s.max = live_max_.exchange(0, std::memory_order_relaxed);
    s.hist.add_from(hist_);
    std::lock_guard<std::mutex> g(mu_);
    samples_.push_back(s);
    while ((int)samples_.size() > window_size_ + 1) samples_.pop_front();
}

LatencyRecorder::Snap LatencyRecorder::window_delta() const {
    std::lock_guard<std::mutex> g(mu_);
    Snap d;
    if (samples_.size() < 2) {
        // Window not warmed up: report live totals so early reads show data.
        d.count = count_.load(std::memory_order_relaxed);
        d.sum = sum_.load(std::memory_order_relaxed);
        d.max = live_max_.load(std::memory_order_relaxed);
        // A sampler tick may already have folded the max into samples_.
        for (const Snap& s : samples_) {
            if (s.max > d.max) d.max = s.max;
        }
        d.hist.add_from(hist_);
        return d;
    }
    const Snap& newest = samples_.back();
    const Snap& oldest = samples_.front();
    d.count = newest.count - oldest.count;
    d.sum = newest.sum - oldest.sum;
    d.hist = newest.hist;
    d.hist.subtract(oldest.hist);
    // Skip front(): its interval precedes the window start.
    for (size_t i = 1; i < samples_.size(); ++i) {
        if (samples_[i].max > d.max) d.max = samples_[i].max;
    }
    const int64_t live = live_max_.load(std::memory_order_relaxed);
    if (live > d.max) d.max = live;
    return d;
}

int64_t LatencyRecorder::qps() const {
    std::unique_lock<std::mutex> g(mu_);
    if (samples_.size() < 2) return 0;
    const int64_t dc = samples_.back().count - samples_.front().count;
    const int64_t secs = (int64_t)samples_.size() - 1;
    return dc / (secs > 0 ? secs : 1);
}

int64_t LatencyRecorder::latency() const {
    Snap d = window_delta();
    return d.count > 0 ? d.sum / d.count : 0;
}

int64_t LatencyRecorder::latency_percentile(double q) const {
    return window_delta().hist.quantile(q);
}

int64_t LatencyRecorder::max_latency() const { return window_delta().max; }

std::vector<std::pair<std::string, double>> LatencyRecorder::numeric_fields()
    const {
    const Snap d = window_delta();
    return {
        {"_qps", (double)qps()},
        {"_avg_us", (double)(d.count > 0 ? d.sum / d.count : 0)},
        {"_p50", (double)d.hist.quantile(0.5)},
        {"_p90", (double)d.hist.quantile(0.9)},
        {"_p99", (double)d.hist.quantile(0.99)},
        {"_p999", (double)d.hist.quantile(0.999)},
        {"_max", (double)d.max},
        {"_count", (double)count()},
    };
}

void LatencyRecorder::prometheus_text(const std::string& name,
                                      std::string* out) const {
    const Snap d = window_delta();
    std::ostringstream os;
    os << "# TYPE " << name << " summary\n";
    const double qs[] = {0.5, 0.9, 0.99, 0.999};
    const char* qlabels[] = {"0.5", "0.9", "0.99", "0.999"};
    for (int i = 0; i < 4; ++i) {
        os << name << "{quantile=\"" << qlabels[i] << "\"} "
           << d.hist.quantile(qs[i]) << "\n";
    }
    os << name << "_sum " << sum() << "\n";
    os << name << "_count " << count() << "\n";
    *out += os.str();
}

const char* LatencyRecorder::prometheus_labelled_samples(
    const std::string& name, const std::string& labels,
    std::string* out) const {
    const Snap d = window_delta();
    std::ostringstream os;
    const double qs[] = {0.5, 0.9, 0.99, 0.999};
    const char* qlabels[] = {"0.5", "0.9", "0.99", "0.999"};
    for (int i = 0; i < 4; ++i) {
        os << name << "{" << labels << ",quantile=\"" << qlabels[i] << "\"} "
           << d.hist.quantile(qs[i]) << "\n";
    }
    os << name << "_sum{" << labels << "} " << sum() << "\n";
    os << name << "_count{" << labels << "} " << count() << "\n";
    *out += os.str();
    return "summary";
}

std::string LatencyRecorder::get_description() const {
    const Snap d = window_delta();
    std::ostringstream os;
    os << "{\"qps\":" << qps()
       << ",\"avg_us\":" << (d.count > 0 ? d.sum / d.count : 0)
       << ",\"p50\":" << d.hist.quantile(0.5)
       << ",\"p90\":" << d.hist.quantile(0.9)
       << ",\"p99\":" << d.hist.quantile(0.99)
       << ",\"p999\":" << d.hist.quantile(0.999) << ",\"max\":" << d.max
       << ",\"count\":" << count() << "}";
    return os.str();
}

}  // namespace tpurpc
