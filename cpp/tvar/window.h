// Sampler + Window: time-windowed views over reducers.
//
// Modeled on reference src/bvar/detail/sampler.h:44-51 (a background thread
// samples every windowed variable once per second) and src/bvar/window.h.
// Window<R> shows the delta of reducer R over the last N seconds.
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <sstream>
#include <thread>

#include "tvar/variable.h"

namespace tpurpc {

// Background 1Hz sampling service. Samplers run OFF the registry lock
// (reference sampler.cpp keeps its linked samplers unlocked the same
// way): a slow PassiveStatus callback must not stall registration or the
// other windows. remove() blocks until the removed sampler is not (and
// will never again be) running, so Window destruction stays safe.
class SamplerCollector {
public:
    static SamplerCollector* singleton();
    using SampleFn = std::function<void()>;
    // Returns a registration id.
    uint64_t add(SampleFn fn);
    void remove(uint64_t id);

private:
    struct Entry {
        std::atomic<bool> alive{true};
        SampleFn fn;
    };

    SamplerCollector();
    void Run();
    std::mutex mu_;
    std::condition_variable cv_;
    std::vector<std::pair<uint64_t, std::shared_ptr<Entry>>> fns_;
    uint64_t next_id_ = 1;
    uint64_t running_id_ = 0;  // sampler currently executing off-lock
    std::thread::id collector_tid_;  // set once by Run()
};

// Window over a reducer-like R (requires R::get_value() returning T and
// operator semantics where the windowed value = now - value_at(now - N)).
template <typename R, typename T>
class WindowBase : public Variable {
public:
    explicit WindowBase(R* reducer, int window_size = 10)
        : reducer_(reducer), window_size_(window_size) {
        sampler_id_ = SamplerCollector::singleton()->add([this] { take_sample(); });
    }
    ~WindowBase() override {
        SamplerCollector::singleton()->remove(sampler_id_);
        hide();
    }

    T get_value() const {
        std::lock_guard<std::mutex> g(mu_);
        if (samples_.empty()) return T();
        return samples_.back().value - samples_.front().value;
    }

    // Value change per second over the window.
    double get_qps() const {
        std::lock_guard<std::mutex> g(mu_);
        if (samples_.size() < 2) return 0.0;
        const double dv =
            (double)(samples_.back().value - samples_.front().value);
        const double dt = (double)(samples_.size() - 1);
        return dv / dt;
    }

    std::string get_description() const override {
        std::ostringstream os;
        os << get_value();
        return os.str();
    }

private:
    void take_sample() {
        const T v = reducer_->get_value();
        std::lock_guard<std::mutex> g(mu_);
        samples_.push_back(Sample{v});
        while ((int)samples_.size() > window_size_ + 1) {
            samples_.pop_front();
        }
    }

    struct Sample {
        T value;
    };
    R* reducer_;
    int window_size_;
    uint64_t sampler_id_;
    mutable std::mutex mu_;
    std::deque<Sample> samples_;
};

}  // namespace tpurpc
