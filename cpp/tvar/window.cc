#include "tvar/window.h"

#include <thread>

namespace tpurpc {

SamplerCollector* SamplerCollector::singleton() {
    static SamplerCollector* s = new SamplerCollector;
    return s;
}

SamplerCollector::SamplerCollector() {
    std::thread([this] { Run(); }).detach();
}

uint64_t SamplerCollector::add(SampleFn fn) {
    auto e = std::make_shared<Entry>();
    e->fn = std::move(fn);
    std::lock_guard<std::mutex> g(mu_);
    const uint64_t id = next_id_++;
    fns_.emplace_back(id, std::move(e));
    return id;
}

void SamplerCollector::remove(uint64_t id) {
    std::unique_lock<std::mutex> g(mu_);
    for (size_t i = 0; i < fns_.size(); ++i) {
        if (fns_[i].first == id) {
            fns_[i].second->alive.store(false, std::memory_order_release);
            fns_[i] = std::move(fns_.back());
            fns_.pop_back();
            break;
        }
    }
    // remove() from INSIDE a sampler callback (a Window destroyed on the
    // collector thread itself): waiting would self-deadlock, and it's
    // already safe — this call can only be the running sampler's own
    // frame, which won't run again after the erase above.
    if (std::this_thread::get_id() == collector_tid_) return;
    // The sampler may be mid-execution off-lock; its owner is about to be
    // destroyed, so wait it out (Run() re-checks liveness under mu_
    // before each call, so after this wait it can never start again).
    cv_.wait(g, [&] { return running_id_ != id; });
}

void SamplerCollector::Run() {
    {
        std::lock_guard<std::mutex> g(mu_);
        collector_tid_ = std::this_thread::get_id();
    }
    while (true) {
        std::this_thread::sleep_for(std::chrono::seconds(1));
        std::vector<std::pair<uint64_t, std::shared_ptr<Entry>>> snap;
        {
            std::lock_guard<std::mutex> g(mu_);
            snap = fns_;  // shared_ptr copies: entries stay alive off-lock
        }
        for (auto& p : snap) {
            {
                // alive + running_id_ flip under ONE mu hold so remove()
                // can't slip between them; the O(1) atomic replaces a
                // registry scan per sampler.
                std::lock_guard<std::mutex> g(mu_);
                if (!p.second->alive.load(std::memory_order_acquire)) {
                    continue;  // removed since the snapshot
                }
                running_id_ = p.first;
            }
            p.second->fn();
            {
                std::lock_guard<std::mutex> g(mu_);
                running_id_ = 0;
            }
            cv_.notify_all();
        }
    }
}

}  // namespace tpurpc
