#include "tvar/window.h"

#include <thread>

namespace tpurpc {

SamplerCollector* SamplerCollector::singleton() {
    static SamplerCollector* s = new SamplerCollector;
    return s;
}

SamplerCollector::SamplerCollector() {
    std::thread([this] { Run(); }).detach();
}

uint64_t SamplerCollector::add(SampleFn fn) {
    std::lock_guard<std::mutex> g(mu_);
    const uint64_t id = next_id_++;
    fns_.emplace_back(id, std::move(fn));
    return id;
}

void SamplerCollector::remove(uint64_t id) {
    std::lock_guard<std::mutex> g(mu_);
    for (size_t i = 0; i < fns_.size(); ++i) {
        if (fns_[i].first == id) {
            fns_[i] = std::move(fns_.back());
            fns_.pop_back();
            return;
        }
    }
}

void SamplerCollector::Run() {
    while (true) {
        std::this_thread::sleep_for(std::chrono::seconds(1));
        std::lock_guard<std::mutex> g(mu_);
        for (auto& p : fns_) p.second();
    }
}

}  // namespace tpurpc
