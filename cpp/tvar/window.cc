#include "tvar/window.h"

#include <thread>

namespace tpurpc {

SamplerCollector* SamplerCollector::singleton() {
    static SamplerCollector* s = new SamplerCollector;
    return s;
}

SamplerCollector::SamplerCollector() {
    std::thread([this] { Run(); }).detach();
}

uint64_t SamplerCollector::add(SampleFn fn) {
    std::lock_guard<std::mutex> g(mu_);
    const uint64_t id = next_id_++;
    fns_.emplace_back(id, std::make_shared<SampleFn>(std::move(fn)));
    return id;
}

void SamplerCollector::remove(uint64_t id) {
    std::unique_lock<std::mutex> g(mu_);
    for (size_t i = 0; i < fns_.size(); ++i) {
        if (fns_[i].first == id) {
            fns_[i] = std::move(fns_.back());
            fns_.pop_back();
            break;
        }
    }
    // remove() from INSIDE a sampler callback (a Window destroyed on the
    // collector thread itself): waiting would self-deadlock, and it's
    // already safe — this call can only be the running sampler's own
    // frame, which won't run again after the erase above.
    if (std::this_thread::get_id() == collector_tid_) return;
    // The sampler may be mid-execution off-lock; its owner is about to be
    // destroyed, so wait it out (Run() re-checks liveness under mu_
    // before each call, so after this wait it can never start again).
    cv_.wait(g, [&] { return running_id_ != id; });
}

void SamplerCollector::Run() {
    {
        std::lock_guard<std::mutex> g(mu_);
        collector_tid_ = std::this_thread::get_id();
    }
    while (true) {
        std::this_thread::sleep_for(std::chrono::seconds(1));
        std::vector<std::pair<uint64_t, std::shared_ptr<SampleFn>>> snap;
        {
            std::lock_guard<std::mutex> g(mu_);
            snap = fns_;  // shared_ptr copies: fns stay alive off-lock
        }
        for (auto& p : snap) {
            {
                std::lock_guard<std::mutex> g(mu_);
                bool alive = false;
                for (auto& f : fns_) {
                    if (f.first == p.first) {
                        alive = true;
                        break;
                    }
                }
                if (!alive) continue;  // removed since the snapshot
                running_id_ = p.first;
            }
            (*p.second)();
            {
                std::lock_guard<std::mutex> g(mu_);
                running_id_ = 0;
            }
            cv_.notify_all();
        }
    }
}

}  // namespace tpurpc
