#include "tbase/flags.h"

#include <cstdio>
#include <cstdlib>

namespace tpurpc {

namespace {
struct Registry {
    std::mutex mu;
    std::vector<FlagBase*> flags;
};
Registry* registry() {
    static Registry* r = new Registry;
    return r;
}
}  // namespace

void RegisterFlag(FlagBase* flag) {
    Registry* r = registry();
    std::lock_guard<std::mutex> g(r->mu);
    r->flags.push_back(flag);
}

FlagBase* FindFlag(const std::string& name) {
    Registry* r = registry();
    std::lock_guard<std::mutex> g(r->mu);
    for (FlagBase* f : r->flags) {
        if (name == f->name()) return f;
    }
    return nullptr;
}

std::vector<FlagBase*> ListFlags() {
    Registry* r = registry();
    std::lock_guard<std::mutex> g(r->mu);
    return r->flags;
}

bool SetFlagValue(const std::string& name, const std::string& value) {
    FlagBase* f = FindFlag(name);
    if (f == nullptr) return false;
    return f->SetString(value);
}

template <>
bool Flag<int32_t>::SetString(const std::string& s) {
    char* end = nullptr;
    long v = strtol(s.c_str(), &end, 10);
    if (end == s.c_str() || *end != '\0') return false;
    if (validator_ && !validator_((int32_t)v)) return false;
    value_.store((int32_t)v, std::memory_order_relaxed);
    NotifyChanged();
    return true;
}

template <>
bool Flag<int64_t>::SetString(const std::string& s) {
    char* end = nullptr;
    long long v = strtoll(s.c_str(), &end, 10);
    if (end == s.c_str() || *end != '\0') return false;
    if (validator_ && !validator_((int64_t)v)) return false;
    value_.store((int64_t)v, std::memory_order_relaxed);
    NotifyChanged();
    return true;
}

template <>
bool Flag<bool>::SetString(const std::string& s) {
    bool v;
    if (s == "true" || s == "1") {
        v = true;
    } else if (s == "false" || s == "0") {
        v = false;
    } else {
        return false;
    }
    if (validator_ && !validator_(v)) return false;
    value_.store(v, std::memory_order_relaxed);
    NotifyChanged();
    return true;
}

template <>
bool Flag<double>::SetString(const std::string& s) {
    char* end = nullptr;
    double v = strtod(s.c_str(), &end);
    if (end == s.c_str() || *end != '\0') return false;
    if (validator_ && !validator_(v)) return false;
    value_.store(v, std::memory_order_relaxed);
    NotifyChanged();
    return true;
}

template <>
std::string Flag<bool>::GetString() const {
    return get() ? "true" : "false";
}

template <>
std::string Flag<double>::GetString() const {
    char buf[64];
    snprintf(buf, sizeof(buf), "%g", get());
    return buf;
}

template <typename T>
std::string Flag<T>::GetString() const {
    return std::to_string(get());
}

template class Flag<int32_t>;
template class Flag<int64_t>;
template class Flag<bool>;
template class Flag<double>;

}  // namespace tpurpc
