#include "tbase/thread_stacks.h"

#include <dirent.h>
#include <signal.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <vector>

#include "tbase/stack_walk.h"
#include "tbase/symbolize.h"
#include "tbase/time.h"

namespace tpurpc {

namespace {

constexpr size_t kMaxFrames = 32;

// One collection at a time. `round` is the stale-handler guard: a
// handler whose delivery outlived its collection window (thread was
// off-CPU past the deadline) sees a bumped round and writes nothing —
// without it, the late handler would race the NEXT thread's capture
// (torn frames, misattributed stacks).
struct Capture {
    std::atomic<uint64_t> round{0};
    std::atomic<int> pending_tid{0};
    std::atomic<bool> done{false};
    uintptr_t frames[kMaxFrames];
    std::atomic<size_t> nframes{0};
};

Capture g_capture;
std::mutex g_dump_mu;

void StackSignalHandler(int, siginfo_t*, void* ucv) {
    const uint64_t my_round =
        g_capture.round.load(std::memory_order_acquire);
    const int me = (int)syscall(SYS_gettid);
    if (g_capture.pending_tid.load(std::memory_order_acquire) != me) {
        return;  // stale/misrouted signal
    }
    uintptr_t local[kMaxFrames];
    const size_t n =
        stack_walk::walk((ucontext_t*)ucv, local, kMaxFrames);
    // Publish only if the collector still waits for THIS round.
    if (g_capture.round.load(std::memory_order_acquire) != my_round ||
        g_capture.pending_tid.load(std::memory_order_acquire) != me) {
        return;
    }
    memcpy(g_capture.frames, local, n * sizeof(uintptr_t));
    g_capture.nframes.store(n, std::memory_order_release);
    g_capture.done.store(true, std::memory_order_release);
}

}  // namespace

std::string DumpThreadStacks(size_t max_frames) {
    std::lock_guard<std::mutex> g(g_dump_mu);

    struct sigaction sa, old_sa;
    memset(&sa, 0, sizeof(sa));
    sa.sa_sigaction = StackSignalHandler;
    sa.sa_flags = SA_SIGINFO | SA_RESTART;
    sigemptyset(&sa.sa_mask);
    if (sigaction(SIGURG, &sa, &old_sa) != 0) {
        return "sigaction failed\n";
    }

    // Snapshot tids first (threads may come and go mid-dump).
    std::vector<int> tids;
    if (DIR* d = opendir("/proc/self/task")) {
        while (dirent* e = readdir(d)) {
            const int tid = atoi(e->d_name);
            if (tid > 0) tids.push_back(tid);
        }
        closedir(d);
    }

    const int self = (int)syscall(SYS_gettid);
    const pid_t pid = getpid();
    // This may run on a fiber whose worker carries other queued work:
    // bound the page's total cost, not just each thread's.
    const int64_t total_deadline = monotonic_time_us() + 1000 * 1000;
    std::string out;
    char line[512];
    snprintf(line, sizeof(line), "%zu thread(s)\n", tids.size());
    out += line;
    for (int tid : tids) {
        snprintf(line, sizeof(line), "--- thread %d%s\n", tid,
                 tid == self ? " (collector)" : "");
        out += line;
        if (tid == self) continue;  // our own stack is this function
        if (monotonic_time_us() >= total_deadline) {
            out += "    <dump budget exhausted>\n";
            continue;
        }
        g_capture.round.fetch_add(1, std::memory_order_acq_rel);
        g_capture.done.store(false, std::memory_order_relaxed);
        g_capture.nframes.store(0, std::memory_order_relaxed);
        g_capture.pending_tid.store(tid, std::memory_order_release);
        if (syscall(SYS_tgkill, pid, tid, SIGURG) != 0) {
            out += "    <gone>\n";
            continue;
        }
        const int64_t deadline = monotonic_time_us() + 100 * 1000;
        while (!g_capture.done.load(std::memory_order_acquire) &&
               monotonic_time_us() < deadline) {
            usleep(200);
        }
        g_capture.pending_tid.store(0, std::memory_order_release);
        if (!g_capture.done.load(std::memory_order_acquire)) {
            // Invalidate the round so a late handler writes nothing.
            g_capture.round.fetch_add(1, std::memory_order_acq_rel);
            out += "    <no response (uninterruptible?)>\n";
            continue;
        }
        const size_t captured =
            g_capture.nframes.load(std::memory_order_acquire);
        const size_t n = captured < max_frames ? captured : max_frames;
        for (size_t i = 0; i < n; ++i) {
            snprintf(line, sizeof(line), "    #%zu 0x%llx %s\n", i,
                     (unsigned long long)g_capture.frames[i],
                     SymbolizePc(g_capture.frames[i]).c_str());
            out += line;
        }
        if (n == 0) out += "    <unwalkable>\n";
    }
    sigaction(SIGURG, &old_sa, nullptr);
    return out;
}

}  // namespace tpurpc
