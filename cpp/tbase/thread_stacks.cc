#include "tbase/thread_stacks.h"

#include <dirent.h>
#include <signal.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <vector>

#include "tbase/stack_walk.h"
#include "tbase/symbolize.h"
#include "tbase/time.h"

namespace tpurpc {

namespace {

constexpr size_t kMaxFrames = 32;

// One collection at a time. Stale-handler protocol (a handler whose
// delivery outlived its 100ms window — thread off-CPU — must not tear a
// LATER round's capture):
//  1. CLAIM: the handler CASes pending_tid from its own tid to the
//     negated value. The collector publishes each round's tid exactly
//     once, so exactly one handler can claim a round; a handler whose
//     round already ended sees a different pending_tid and bows out
//     before touching shared frames. (The earlier check-then-write had a
//     TOCTOU hole between the re-check and the memcpy.)
//  2. Per-round buffer slot: frames go into slots[round & 1], so a
//     claimed writer suspended across ONE round boundary scribbles on
//     the previous slot, not the one the next round reads. (Parity
//     repeats every two rounds — the seqlock below covers the rest.)
//  3. Round-stamped publication: completion is `done_round == round`
//     (not a bool reset each round), so a late store can never signal a
//     round it didn't capture.
//  4. Per-slot seqlock: the handler brackets its write with gen
//     increments (odd = writing); the collector copies the frames and
//     accepts them only if gen was even and unchanged across the copy.
//     A stale writer suspended PAST two rounds (same slot parity) can
//     therefore still collide, but the collector detects the tear and
//     reports <no response> instead of printing garbage.
struct Capture {
    std::atomic<uint64_t> round{0};
    std::atomic<int> pending_tid{0};
    std::atomic<uint64_t> done_round{0};  // last round fully published
    struct Slot {
        std::atomic<uint32_t> gen{0};  // seqlock: odd while being written
        uintptr_t frames[kMaxFrames];
        std::atomic<size_t> nframes{0};
    } slots[2];
};

Capture g_capture;
std::mutex g_dump_mu;

void StackSignalHandler(int, siginfo_t*, void* ucv) {
    const uint64_t my_round =
        g_capture.round.load(std::memory_order_acquire);
    const int me = (int)syscall(SYS_gettid);
    // CLAIM this round (step 1 above).
    int expect = me;
    if (!g_capture.pending_tid.compare_exchange_strong(
            expect, -me, std::memory_order_acq_rel)) {
        return;  // stale/misrouted signal: another round owns the buffer
    }
    uintptr_t local[kMaxFrames];
    const size_t n =
        stack_walk::walk((ucontext_t*)ucv, local, kMaxFrames);
    Capture::Slot& slot = g_capture.slots[my_round & 1];
    slot.gen.fetch_add(1, std::memory_order_acq_rel);  // odd: writing
    memcpy(slot.frames, local, n * sizeof(uintptr_t));
    slot.nframes.store(n, std::memory_order_relaxed);
    slot.gen.fetch_add(1, std::memory_order_acq_rel);  // even: done
    // Publish: only the collector's current round counts (step 3); a
    // stale round number is simply never observed as done.
    g_capture.done_round.store(my_round, std::memory_order_release);
}

}  // namespace

std::string DumpThreadStacks(size_t max_frames) {
    std::lock_guard<std::mutex> g(g_dump_mu);

    struct sigaction sa, old_sa;
    memset(&sa, 0, sizeof(sa));
    sa.sa_sigaction = StackSignalHandler;
    sa.sa_flags = SA_SIGINFO | SA_RESTART;
    sigemptyset(&sa.sa_mask);
    if (sigaction(SIGURG, &sa, &old_sa) != 0) {
        return "sigaction failed\n";
    }

    // Snapshot tids first (threads may come and go mid-dump).
    std::vector<int> tids;
    if (DIR* d = opendir("/proc/self/task")) {
        while (dirent* e = readdir(d)) {
            const int tid = atoi(e->d_name);
            if (tid > 0) tids.push_back(tid);
        }
        closedir(d);
    }

    const int self = (int)syscall(SYS_gettid);
    const pid_t pid = getpid();
    // This may run on a fiber whose worker carries other queued work:
    // bound the page's total cost, not just each thread's.
    const int64_t total_deadline = monotonic_time_us() + 1000 * 1000;
    std::string out;
    char line[512];
    snprintf(line, sizeof(line), "%zu thread(s)\n", tids.size());
    out += line;
    for (int tid : tids) {
        snprintf(line, sizeof(line), "--- thread %d%s\n", tid,
                 tid == self ? " (collector)" : "");
        out += line;
        if (tid == self) continue;  // our own stack is this function
        if (monotonic_time_us() >= total_deadline) {
            out += "    <dump budget exhausted>\n";
            continue;
        }
        const uint64_t round =
            g_capture.round.fetch_add(1, std::memory_order_acq_rel) + 1;
        Capture::Slot& slot = g_capture.slots[round & 1];
        slot.nframes.store(0, std::memory_order_relaxed);
        // Seqlock baseline: this round's ONE legitimate writer must move
        // gen to exactly base+2; any other final value means a stale
        // handler also wrote the slot (before, between or after) and the
        // capture is discarded below.
        const uint32_t gen_base = slot.gen.load(std::memory_order_acquire);
        // Publishing the tid opens the round's single claim slot.
        g_capture.pending_tid.store(tid, std::memory_order_release);
        if (syscall(SYS_tgkill, pid, tid, SIGURG) != 0) {
            g_capture.pending_tid.store(0, std::memory_order_release);
            out += "    <gone>\n";
            continue;
        }
        const int64_t deadline = monotonic_time_us() + 100 * 1000;
        while (g_capture.done_round.load(std::memory_order_acquire) !=
                   round &&
               monotonic_time_us() < deadline) {
            usleep(200);
        }
        // Close the claim window (no-op if the handler already claimed:
        // its CAS flipped pending_tid to -tid).
        g_capture.pending_tid.store(0, std::memory_order_release);
        if (g_capture.done_round.load(std::memory_order_acquire) != round) {
            out += "    <no response (uninterruptible?)>\n";
            continue;
        }
        // Seqlock read: copy out, then verify no (stale) writer touched
        // the slot during the copy.
        const uint32_t g1 = slot.gen.load(std::memory_order_acquire);
        size_t captured = slot.nframes.load(std::memory_order_acquire);
        if (captured > kMaxFrames) captured = kMaxFrames;
        uintptr_t copied[kMaxFrames];
        memcpy(copied, slot.frames, captured * sizeof(uintptr_t));
        const uint32_t g2 = slot.gen.load(std::memory_order_acquire);
        if ((g1 & 1) != 0 || g1 != g2 || g1 != gen_base + 2) {
            out += "    <no response (torn capture discarded)>\n";
            continue;
        }
        const size_t n = captured < max_frames ? captured : max_frames;
        for (size_t i = 0; i < n; ++i) {
            snprintf(line, sizeof(line), "    #%zu 0x%llx %s\n", i,
                     (unsigned long long)copied[i],
                     SymbolizePc(copied[i]).c_str());
            out += line;
        }
        if (n == 0) out += "    <unwalkable>\n";
    }
    sigaction(SIGURG, &old_sa, nullptr);
    return out;
}

}  // namespace tpurpc
