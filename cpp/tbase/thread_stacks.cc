#include "tbase/thread_stacks.h"

#include <dirent.h>
#include <signal.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <ucontext.h>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <vector>

#include "tbase/symbolize.h"
#include "tbase/time.h"

namespace tpurpc {

namespace {

constexpr size_t kMaxFrames = 32;

// One collection at a time; the handler writes into the active slot.
struct Capture {
    std::atomic<int> pending_tid{0};  // tid the handler should serve
    std::atomic<bool> done{false};
    uintptr_t frames[kMaxFrames];
    size_t nframes = 0;
};

Capture g_capture;
std::mutex g_dump_mu;

void StackSignalHandler(int, siginfo_t*, void* ucv) {
    const int me = (int)syscall(SYS_gettid);
    if (g_capture.pending_tid.load(std::memory_order_acquire) != me) {
        return;  // stale/misrouted signal
    }
    // Walk our own frame pointers starting from the signal context.
    size_t n = 0;
#if defined(__x86_64__)
    auto* uc = (ucontext_t*)ucv;
    uintptr_t pc = (uintptr_t)uc->uc_mcontext.gregs[REG_RIP];
    uintptr_t bp = (uintptr_t)uc->uc_mcontext.gregs[REG_RBP];
    while (pc != 0 && n < kMaxFrames) {
        g_capture.frames[n++] = pc;
        if (bp == 0 || (bp & 7) != 0) break;
        const uintptr_t next_bp = *(uintptr_t*)bp;
        const uintptr_t next_pc = *(uintptr_t*)(bp + 8);
        if (next_bp <= bp) break;  // must move up the stack
        bp = next_bp;
        pc = next_pc;
    }
#else
    (void)ucv;
#endif
    g_capture.nframes = n;
    g_capture.done.store(true, std::memory_order_release);
}

}  // namespace

std::string DumpThreadStacks(size_t max_frames) {
    std::lock_guard<std::mutex> g(g_dump_mu);

    struct sigaction sa, old_sa;
    memset(&sa, 0, sizeof(sa));
    sa.sa_sigaction = StackSignalHandler;
    sa.sa_flags = SA_SIGINFO | SA_RESTART;
    sigemptyset(&sa.sa_mask);
    if (sigaction(SIGURG, &sa, &old_sa) != 0) {
        return "sigaction failed\n";
    }

    // Snapshot tids first (threads may come and go mid-dump).
    std::vector<int> tids;
    if (DIR* d = opendir("/proc/self/task")) {
        while (dirent* e = readdir(d)) {
            const int tid = atoi(e->d_name);
            if (tid > 0) tids.push_back(tid);
        }
        closedir(d);
    }

    const int self = (int)syscall(SYS_gettid);
    const pid_t pid = getpid();
    std::string out;
    char line[512];
    snprintf(line, sizeof(line), "%zu thread(s)\n", tids.size());
    out += line;
    for (int tid : tids) {
        snprintf(line, sizeof(line), "--- thread %d%s\n", tid,
                 tid == self ? " (collector)" : "");
        out += line;
        if (tid == self) continue;  // our own stack is this function
        g_capture.done.store(false, std::memory_order_relaxed);
        g_capture.nframes = 0;
        g_capture.pending_tid.store(tid, std::memory_order_release);
        if (syscall(SYS_tgkill, pid, tid, SIGURG) != 0) {
            out += "    <gone>\n";
            continue;
        }
        const int64_t deadline = monotonic_time_us() + 200 * 1000;
        while (!g_capture.done.load(std::memory_order_acquire) &&
               monotonic_time_us() < deadline) {
            usleep(200);
        }
        g_capture.pending_tid.store(0, std::memory_order_release);
        if (!g_capture.done.load(std::memory_order_acquire)) {
            out += "    <no response (uninterruptible?)>\n";
            continue;
        }
        const size_t n =
            g_capture.nframes < max_frames ? g_capture.nframes : max_frames;
        for (size_t i = 0; i < n; ++i) {
            snprintf(line, sizeof(line), "    #%zu 0x%llx %s\n", i,
                     (unsigned long long)g_capture.frames[i],
                     SymbolizePc(g_capture.frames[i]).c_str());
            out += line;
        }
        if (n == 0) out += "    <unwalkable>\n";
    }
    sigaction(SIGURG, &old_sa, nullptr);
    return out;
}

}  // namespace tpurpc
