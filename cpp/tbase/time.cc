#include "tbase/time.h"

#include <unistd.h>

namespace tpurpc {

static double CalibrateTicksPerUs() {
#if defined(__x86_64__)
    const int64_t t0_ns = monotonic_time_ns();
    const uint64_t c0 = cpuwide_ticks();
    usleep(2000);
    const int64_t t1_ns = monotonic_time_ns();
    const uint64_t c1 = cpuwide_ticks();
    const double us = (double)(t1_ns - t0_ns) / 1000.0;
    if (us <= 0) return 1000.0;
    return (double)(c1 - c0) / us;
#else
    return 1000.0;  // ticks == ns
#endif
}

double ticks_per_us() {
    static const double v = CalibrateTicksPerUs();
    return v;
}

}  // namespace tpurpc
