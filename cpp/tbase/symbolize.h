// In-process symbolization for the /hotspots portal: PC -> demangled
// function name via dladdr (the framework is a shared library with
// default visibility, so its functions carry dynamic symbols) with a
// module+offset fallback. Replaces the offline tools/symbolize_prof.py
// step for the portal path (reference hotspots_service.cpp bundles
// pprof's symbolization for the same reason: profiles must be readable
// where they're taken).
#pragma once

#include <cstdint>
#include <string>

namespace tpurpc {

// "Namespace::Function()" | "module.so+0x1234" | "0xdeadbeef".
std::string SymbolizePc(uintptr_t pc);

}  // namespace tpurpc
