#include "tbase/crc32c.h"

#if defined(__x86_64__)
#include <nmmintrin.h>
#endif

namespace tpurpc {

namespace {

// 8 tables of 256 entries, built once (slice-by-8).
struct Tables {
    uint32_t t[8][256];
    Tables() {
        for (uint32_t i = 0; i < 256; ++i) {
            uint32_t c = i;
            for (int k = 0; k < 8; ++k) {
                c = (c & 1) ? (0x82F63B78u ^ (c >> 1)) : (c >> 1);
            }
            t[0][i] = c;
        }
        for (int j = 1; j < 8; ++j) {
            for (uint32_t i = 0; i < 256; ++i) {
                t[j][i] = (t[j - 1][i] >> 8) ^ t[0][t[j - 1][i] & 0xff];
            }
        }
    }
};

const Tables& tables() {
    static const Tables tb;
    return tb;
}

uint32_t crc32c_sw(uint32_t crc, const uint8_t* p, size_t n) {
    const Tables& tb = tables();
    while (n > 0 && ((uintptr_t)p & 7) != 0) {
        crc = tb.t[0][(crc ^ *p++) & 0xff] ^ (crc >> 8);
        --n;
    }
    while (n >= 8) {
        uint64_t w;
        __builtin_memcpy(&w, p, 8);
        w ^= crc;
        crc = tb.t[7][w & 0xff] ^ tb.t[6][(w >> 8) & 0xff] ^
              tb.t[5][(w >> 16) & 0xff] ^ tb.t[4][(w >> 24) & 0xff] ^
              tb.t[3][(w >> 32) & 0xff] ^ tb.t[2][(w >> 40) & 0xff] ^
              tb.t[1][(w >> 48) & 0xff] ^ tb.t[0][(w >> 56) & 0xff];
        p += 8;
        n -= 8;
    }
    while (n > 0) {
        crc = tb.t[0][(crc ^ *p++) & 0xff] ^ (crc >> 8);
        --n;
    }
    return crc;
}

#if defined(__x86_64__)
// Hardware path (ISSUE 9): crc32c IS the Castagnoli polynomial the
// SSE4.2 CRC32 instruction implements — 8 bytes per instruction vs 8
// table lookups. The device data path crc-verifies every chunk, so this
// is directly on the GB/s-gated seam. Detected once at startup;
// non-SSE4.2 x86 and other arches keep the slice-by-8 tables.
__attribute__((target("sse4.2")))
uint32_t crc32c_hw(uint32_t crc, const uint8_t* p, size_t n) {
    while (n > 0 && ((uintptr_t)p & 7) != 0) {
        crc = _mm_crc32_u8(crc, *p++);
        --n;
    }
    uint64_t c64 = crc;
    while (n >= 8) {
        uint64_t w;
        __builtin_memcpy(&w, p, 8);
        c64 = _mm_crc32_u64(c64, w);
        p += 8;
        n -= 8;
    }
    crc = (uint32_t)c64;
    while (n > 0) {
        crc = _mm_crc32_u8(crc, *p++);
        --n;
    }
    return crc;
}

bool has_sse42() {
    static const bool yes = __builtin_cpu_supports("sse4.2");
    return yes;
}
#endif

}  // namespace

uint32_t crc32c_extend(uint32_t crc, const void* data, size_t n) {
    const uint8_t* p = (const uint8_t*)data;
    crc = ~crc;
#if defined(__x86_64__)
    if (has_sse42()) {
        return ~crc32c_hw(crc, p, n);
    }
#endif
    return ~crc32c_sw(crc, p, n);
}

}  // namespace tpurpc
