#include "tbase/endpoint.h"

#include <arpa/inet.h>
#include <netdb.h>

#include <cstdio>
#include <cstring>

namespace tpurpc {

int str2endpoint(const char* ip_str, int port, EndPoint* ep) {
    if (port < 0 || port > 65535) return -1;
    in_addr ip;
    if (strcmp(ip_str, "0.0.0.0") == 0 || ip_str[0] == '\0') {
        ip.s_addr = INADDR_ANY;
    } else if (inet_aton(ip_str, &ip) == 0) {
        return -1;
    }
    ep->ip = ip;
    ep->port = port;
    return 0;
}

int str2endpoint(const char* str, EndPoint* ep) {
    const char* colon = strrchr(str, ':');
    if (colon == nullptr) return -1;
    char ip_buf[64];
    size_t ip_len = (size_t)(colon - str);
    if (ip_len >= sizeof(ip_buf)) return -1;
    memcpy(ip_buf, str, ip_len);
    ip_buf[ip_len] = '\0';
    char* end = nullptr;
    long port = strtol(colon + 1, &end, 10);
    if (end == colon + 1 || *end != '\0') return -1;
    return str2endpoint(ip_buf, (int)port, ep);
}

int hostname2endpoint(const char* str, EndPoint* ep) {
    const char* colon = strrchr(str, ':');
    std::string host = colon ? std::string(str, colon - str) : std::string(str);
    int port = 0;
    if (colon) {
        char* end = nullptr;
        long p = strtol(colon + 1, &end, 10);
        // Same validation as str2endpoint: reject junk and out-of-range
        // ports here too, or "host:99999" would silently truncate via
        // htons later.
        if (end == colon + 1 || *end != '\0' || p < 0 || p > 65535) return -1;
        port = (int)p;
    }
    // Fast path: already an IP literal.
    if (str2endpoint(host.c_str(), port, ep) == 0) return 0;
    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* result = nullptr;
    if (getaddrinfo(host.c_str(), nullptr, &hints, &result) != 0) return -1;
    int rc = -1;
    for (addrinfo* ai = result; ai; ai = ai->ai_next) {
        if (ai->ai_family == AF_INET) {
            ep->ip = ((sockaddr_in*)ai->ai_addr)->sin_addr;
            ep->port = port;
            rc = 0;
            break;
        }
    }
    freeaddrinfo(result);
    return rc;
}

std::string endpoint2str(const EndPoint& ep) {
    char buf[32];
    char ip_buf[INET_ADDRSTRLEN];
    inet_ntop(AF_INET, &ep.ip, ip_buf, sizeof(ip_buf));
    snprintf(buf, sizeof(buf), "%s:%d", ip_buf, ep.port);
    return buf;
}

void endpoint2sockaddr(const EndPoint& ep, sockaddr_in* out) {
    memset(out, 0, sizeof(*out));
    out->sin_family = AF_INET;
    out->sin_addr = ep.ip;
    out->sin_port = htons((uint16_t)ep.port);
}

EndPoint sockaddr2endpoint(const sockaddr_in& in) {
    EndPoint ep;
    ep.ip = in.sin_addr;
    ep.port = ntohs(in.sin_port);
    return ep;
}

}  // namespace tpurpc
