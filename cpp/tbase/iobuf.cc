#include "tbase/iobuf.h"

#include <errno.h>
#include <stdlib.h>
#include <unistd.h>

#include <algorithm>
#include <new>

#include "tbase/flags.h"
#include "tbase/mpmc_queue.h"
#include "tbase/logging.h"

// 512 x 8KB = 4MB per thread: enough that a windowed stream of 1MB
// messages (128 blocks each) recycles through the cache instead of
// malloc/free + arena-trim churn (profiled at ~20% of echo_bench CPU
// with a 16-block cache). Tune down on memory-constrained many-core
// hosts (cost scales with thread count).
DEFINE_int32(iobuf_tls_cache_blocks, 512,
             "max free 8KB blocks cached per thread");

namespace tpurpc {

// ---------------- block allocation ----------------

static void* default_blockmem_allocate(size_t n) { return malloc(n); }
static void default_blockmem_deallocate(void* p) { free(p); }

void* (*IOBuf::blockmem_allocate)(size_t) = default_blockmem_allocate;
void (*IOBuf::blockmem_deallocate)(void*) = default_blockmem_deallocate;
bool (*IOBuf::blockmem_cache_veto)(const void*) = nullptr;

namespace {

// Thread-local cache of fully-free default-sized blocks, and the one block
// this thread is currently appending into (shared by all IOBufs of the
// thread — the scheme of reference iobuf.cpp `share_tls_block`, which is
// what makes tail-extension race-free).
struct TLSData {
    IOBuf::Block* append_block = nullptr;
    IOBuf::Block* cache_head = nullptr;
    size_t num_cached = 0;
    ~TLSData();
};


thread_local TLSData tls_data;

// Cross-thread spillover: network pipelines allocate blocks on one thread
// (parser/worker) and free them on another (writer/dispatcher), so TLS
// caches fill where blocks die and run dry where they're born. A small
// global lock-free ring rebalances; capacity bounds idle memory at
// 1024 x 8KB = 8MB process-wide.
MpmcBoundedQueue<IOBuf::Block*>* global_block_ring() {
    static MpmcBoundedQueue<IOBuf::Block*>* r = [] {
        auto* q = new MpmcBoundedQueue<IOBuf::Block*>;
        CHECK_EQ(q->init(1024), 0);
        return q;
    }();
    return r;
}

}  // namespace

IOBuf::Block* IOBuf::create_block(size_t block_size) {
    // Serve default-sized blocks from the TLS cache first — but only blocks
    // created by the CURRENT allocator pair (the pair may be swapped when a
    // transport installs registered memory; stale malloc'd blocks must not
    // be handed out as registered memory).
    if (block_size == DEFAULT_BLOCK_SIZE && tls_data.cache_head != nullptr &&
        tls_data.cache_head->dealloc == blockmem_deallocate) {
        Block* b = tls_data.cache_head;
        tls_data.cache_head = b->portal_next;
        --tls_data.num_cached;
        b->nshared.store(1, std::memory_order_relaxed);
        b->size = 0;
        b->portal_next = nullptr;
        return b;
    }
    if (block_size == DEFAULT_BLOCK_SIZE) {
        Block* b;
        while (global_block_ring()->pop(&b)) {
            if (b->dealloc != blockmem_deallocate) {
                // Stale allocator generation (transport swapped the
                // allocator): free for real and keep draining.
                b->dealloc(b);
                continue;
            }
            b->nshared.store(1, std::memory_order_relaxed);
            b->size = 0;
            b->portal_next = nullptr;
            return b;
        }
    }
    void* mem = blockmem_allocate(block_size);
    if (mem == nullptr) return nullptr;
    Block* b = new (mem) Block;
    b->nshared.store(1, std::memory_order_relaxed);
    b->size = 0;
    b->cap = (uint32_t)(block_size - offsetof(Block, data));
    b->portal_next = nullptr;
    b->dealloc = blockmem_deallocate;
    return b;
}

void IOBuf::Block::dec_ref() {
    if (nshared.fetch_sub(1, std::memory_order_acq_rel) == 1) {
        const size_t total = cap + offsetof(Block, data);
        // Cache only blocks from the current allocator pair.
        const int32_t cache_cap = FLAGS_iobuf_tls_cache_blocks.get();
        if (total == DEFAULT_BLOCK_SIZE && dealloc == blockmem_deallocate &&
            cache_cap > 0 &&
            (blockmem_cache_veto == nullptr || !blockmem_cache_veto(this))) {
            if (tls_data.num_cached < (size_t)cache_cap) {
                portal_next = tls_data.cache_head;
                tls_data.cache_head = this;
                ++tls_data.num_cached;
                return;
            }
            if (global_block_ring()->push(this)) return;
        }
        dealloc(this);
    }
}

TLSData::~TLSData() {
    if (append_block) {
        append_block->dec_ref();
        append_block = nullptr;
    }
    // The cache itself must be freed for real on thread exit, each block
    // through the deallocator it was created with.
    IOBuf::Block* b = cache_head;
    cache_head = nullptr;
    while (b) {
        IOBuf::Block* next = b->portal_next;
        b->dealloc(b);
        b = next;
    }
}

size_t IOBuf::tls_cached_blocks() { return tls_data.num_cached; }

void IOBuf::flush_tls_cache() {
    IOBuf::Block* b = tls_data.cache_head;
    tls_data.cache_head = nullptr;
    tls_data.num_cached = 0;
    while (b) {
        IOBuf::Block* next = b->portal_next;
        b->dealloc(b);
        b = next;
    }
}

// Returns the thread's current append block (holding a TLS ref), creating a
// fresh one when absent or full.
static IOBuf::Block* share_tls_block() {
    IOBuf::Block* b = tls_data.append_block;
    // The allocator-pair check keeps the registered-memory guarantee: once
    // a transport installs its pool, a pre-install malloc'd append block
    // must not keep receiving payload bytes.
    if (b != nullptr && !b->full() &&
        b->dealloc == IOBuf::blockmem_deallocate) {
        return b;
    }
    if (b != nullptr) b->dec_ref();
    b = IOBuf::create_block();
    tls_data.append_block = b;
    return b;
}

// ---------------- view management ----------------

void IOBuf::push_back_ref_(const BlockRef& r) {
    if (is_small()) {
        // Try merging with the previous ref (same block, contiguous).
        if (small_count_ > 0) {
            BlockRef& last = small_[small_count_ - 1];
            if (last.block == r.block && last.offset + last.length == r.offset) {
                last.length += r.length;
                nbytes_ += r.length;
                r.block->dec_ref();  // merged: we don't keep the extra ref
                return;
            }
        }
        if (small_count_ < kInlineRefs) {
            small_[small_count_++] = r;
            nbytes_ += r.length;
            return;
        }
        // Grow into big view.
        BigView bv;
        bv.cap = 8;
        bv.start = 0;
        bv.count = kInlineRefs;
        bv.refs = (BlockRef*)malloc(bv.cap * sizeof(BlockRef));
        memcpy(bv.refs, small_, kInlineRefs * sizeof(BlockRef));
        big_ = bv;
        is_big_ = true;
    }
    // Big view path.
    if (big_.count > 0) {
        BlockRef& last = big_.refs[(big_.start + big_.count - 1) % big_.cap];
        if (last.block == r.block && last.offset + last.length == r.offset) {
            last.length += r.length;
            nbytes_ += r.length;
            r.block->dec_ref();
            return;
        }
    }
    if (big_.count == big_.cap) {
        const uint32_t new_cap = big_.cap * 2;
        BlockRef* new_refs = (BlockRef*)malloc(new_cap * sizeof(BlockRef));
        for (uint32_t i = 0; i < big_.count; ++i) {
            new_refs[i] = big_.refs[(big_.start + i) % big_.cap];
        }
        free(big_.refs);
        big_.refs = new_refs;
        big_.start = 0;
        big_.cap = new_cap;
    }
    big_.refs[(big_.start + big_.count) % big_.cap] = r;
    ++big_.count;
    nbytes_ += r.length;
}

bool IOBuf::cut_front_ref(BlockRef* out) {
    if (nref_() == 0) return false;
    *out = ref_at(0);
    nbytes_ -= out->length;
    // Remove the ref WITHOUT dec_ref: ownership moves to *out.
    if (is_big_) {
        big_.start = (big_.start + 1) % big_.cap;
        --big_.count;
        if (big_.count == 0) {
            free(big_.refs);
            reset_small();
        }
    } else {
        if (small_count_ == 2) small_[0] = small_[1];
        --small_count_;
    }
    return true;
}

void IOBuf::pop_front_ref_() {
    BlockRef& r = ref_at(0);
    nbytes_ -= r.length;
    r.block->dec_ref();
    if (is_big_) {
        big_.start = (big_.start + 1) % big_.cap;
        --big_.count;
        if (big_.count == 0) {
            free(big_.refs);
            reset_small();
        }
    } else {
        if (small_count_ == 2) small_[0] = small_[1];
        --small_count_;
    }
}

void IOBuf::pop_back_ref_() {
    BlockRef& r = ref_at(nref_() - 1);
    nbytes_ -= r.length;
    r.block->dec_ref();
    if (is_big_) {
        --big_.count;
        if (big_.count == 0) {
            free(big_.refs);
            reset_small();
        }
    } else {
        --small_count_;
    }
}

void IOBuf::clear() {
    while (nref_() > 0) pop_back_ref_();
    if (is_big_) {
        free(big_.refs);
        reset_small();
    }
    nbytes_ = 0;
}

void IOBuf::swap(IOBuf& other) {
    char tmp[sizeof(IOBuf)];
    memcpy(tmp, (void*)this, sizeof(IOBuf));
    memcpy((void*)this, (void*)&other, sizeof(IOBuf));
    memcpy((void*)&other, tmp, sizeof(IOBuf));
}

IOBuf::IOBuf(const IOBuf& rhs) {
    reset_small();
    append(rhs);
}

IOBuf::IOBuf(IOBuf&& rhs) noexcept {
    memcpy((void*)this, (void*)&rhs, sizeof(IOBuf));
    rhs.reset_small();
}

IOBuf& IOBuf::operator=(const IOBuf& rhs) {
    if (this != &rhs) {
        clear();
        append(rhs);
    }
    return *this;
}

IOBuf& IOBuf::operator=(IOBuf&& rhs) noexcept {
    if (this != &rhs) {
        clear();
        memcpy((void*)this, (void*)&rhs, sizeof(IOBuf));
        rhs.reset_small();
    }
    return *this;
}

// ---------------- appending ----------------

int IOBuf::append(const void* data, size_t count) {
    const char* p = (const char*)data;
    size_t left = count;
    while (left > 0) {
        Block* b = share_tls_block();
        if (b == nullptr) return -1;
        const size_t copied = std::min((size_t)b->left_space(), left);
        memcpy(b->data + b->size, p, copied);
        BlockRef r{b->size, (uint32_t)copied, b};
        b->size += (uint32_t)copied;
        b->inc_ref();
        push_back_ref_(r);
        p += copied;
        left -= copied;
    }
    return 0;
}

void IOBuf::append(const IOBuf& other) {
    const uint32_t n = other.nref_();
    for (uint32_t i = 0; i < n; ++i) {
        append_ref(other.ref_at(i));
    }
}

void IOBuf::append(IOBuf&& other) {
    if (empty()) {
        swap(other);
        return;
    }
    const uint32_t n = other.nref_();
    for (uint32_t i = 0; i < n; ++i) {
        BlockRef r = other.ref_at(i);
        r.block->inc_ref();
        push_back_ref_(r);
    }
    other.clear();
}

void IOBuf::append_ref(const BlockRef& ref) {
    ref.block->inc_ref();
    push_back_ref_(ref);
}

// ---------------- cutting ----------------

size_t IOBuf::cutn(IOBuf* out, size_t n) {
    size_t moved = 0;
    while (moved < n && nref_() > 0) {
        BlockRef& r = ref_at(0);
        const size_t want = n - moved;
        if (r.length <= want) {
            // Transfer whole ref: no refcount change, ownership moves.
            BlockRef whole = r;
            nbytes_ -= r.length;
            // Manual pop without dec_ref.
            if (is_big_) {
                big_.start = (big_.start + 1) % big_.cap;
                --big_.count;
                if (big_.count == 0) {
                    free(big_.refs);
                    reset_small();
                }
            } else {
                if (small_count_ == 2) small_[0] = small_[1];
                --small_count_;
            }
            moved += whole.length;
            out->push_back_ref_(whole);
        } else {
            BlockRef part{r.offset, (uint32_t)want, r.block};
            r.block->inc_ref();
            r.offset += (uint32_t)want;
            r.length -= (uint32_t)want;
            nbytes_ -= want;
            moved += want;
            out->push_back_ref_(part);
        }
    }
    return moved;
}

size_t IOBuf::cutn(void* out, size_t n) {
    char* p = (char*)out;
    size_t moved = 0;
    while (moved < n && nref_() > 0) {
        BlockRef& r = ref_at(0);
        const size_t want = std::min((size_t)(n - moved), (size_t)r.length);
        memcpy(p + moved, r.block->data + r.offset, want);
        moved += want;
        if (want == r.length) {
            pop_front_ref_();
        } else {
            r.offset += (uint32_t)want;
            r.length -= (uint32_t)want;
            nbytes_ -= want;
        }
    }
    return moved;
}

size_t IOBuf::cutn(std::string* out, size_t n) {
    n = std::min(n, nbytes_);
    const size_t old = out->size();
    out->resize(old + n);
    return cutn(&(*out)[old], n);
}

int IOBuf::cut1(char* c) {
    if (empty()) return -1;
    return cutn(c, 1) == 1 ? 0 : -1;
}

size_t IOBuf::pop_front(size_t n) {
    size_t popped = 0;
    while (popped < n && nref_() > 0) {
        BlockRef& r = ref_at(0);
        const size_t want = std::min((size_t)(n - popped), (size_t)r.length);
        if (want == r.length) {
            pop_front_ref_();
        } else {
            r.offset += (uint32_t)want;
            r.length -= (uint32_t)want;
            nbytes_ -= want;
        }
        popped += want;
    }
    return popped;
}

size_t IOBuf::pop_back(size_t n) {
    size_t popped = 0;
    while (popped < n && nref_() > 0) {
        BlockRef& r = ref_at(nref_() - 1);
        const size_t want = std::min((size_t)(n - popped), (size_t)r.length);
        if (want == r.length) {
            pop_back_ref_();
        } else {
            r.length -= (uint32_t)want;
            nbytes_ -= want;
        }
        popped += want;
    }
    return popped;
}

// ---------------- reading ----------------

size_t IOBuf::copy_to(void* buf, size_t n, size_t pos) const {
    char* p = (char*)buf;
    size_t copied = 0;
    const uint32_t cnt = nref_();
    for (uint32_t i = 0; i < cnt && copied < n; ++i) {
        const BlockRef& r = ref_at(i);
        if (pos >= r.length) {
            pos -= r.length;
            continue;
        }
        const size_t avail = r.length - pos;
        const size_t want = std::min(n - copied, avail);
        memcpy(p + copied, r.block->data + r.offset + pos, want);
        copied += want;
        pos = 0;
    }
    return copied;
}

size_t IOBuf::copy_to(std::string* s, size_t n, size_t pos) const {
    if (pos >= nbytes_) {
        s->clear();
        return 0;
    }
    n = std::min(n, nbytes_ - pos);
    s->resize(n);
    return copy_to(&(*s)[0], n, pos);
}

std::string IOBuf::to_string() const {
    std::string s;
    copy_to(&s);
    return s;
}

const void* IOBuf::fetch(void* aux, size_t n) const {
    if (n > nbytes_) return nullptr;
    if (n == 0) return aux;
    const BlockRef& r = ref_at(0);
    if (r.length >= n) {
        return r.block->data + r.offset;
    }
    copy_to(aux, n);
    return aux;
}

int IOBuf::front_byte() const {
    if (empty()) return -1;
    const BlockRef& r = ref_at(0);
    return (unsigned char)r.block->data[r.offset];
}

bool IOBuf::equals(const std::string& s) const {
    if (s.size() != nbytes_) return false;
    size_t off = 0;
    const uint32_t cnt = nref_();
    for (uint32_t i = 0; i < cnt; ++i) {
        const BlockRef& r = ref_at(i);
        if (memcmp(s.data() + off, r.block->data + r.offset, r.length) != 0) {
            return false;
        }
        off += r.length;
    }
    return true;
}

const char* IOBuf::backing_block_data(size_t i, size_t* len) const {
    if (i >= nref_()) {
        *len = 0;
        return nullptr;
    }
    const BlockRef& r = ref_at((uint32_t)i);
    *len = r.length;
    return r.block->data + r.offset;
}

// ---------------- fd I/O ----------------

static constexpr size_t kMaxIov = 64;

ssize_t IOBuf::cut_into_file_descriptor(int fd, size_t size_hint) {
    iovec vec[kMaxIov];
    size_t nvec = 0;
    size_t total = 0;
    const uint32_t cnt = nref_();
    for (uint32_t i = 0; i < cnt && nvec < kMaxIov && total < size_hint; ++i) {
        const BlockRef& r = ref_at(i);
        vec[nvec].iov_base = r.block->data + r.offset;
        vec[nvec].iov_len = r.length;
        total += r.length;
        ++nvec;
    }
    if (nvec == 0) return 0;
    ssize_t written = writev(fd, vec, (int)nvec);
    if (written > 0) pop_front((size_t)written);
    return written;
}

ssize_t IOBuf::cut_multiple_into_file_descriptor(int fd, IOBuf* const* pieces,
                                                 size_t count) {
    iovec vec[kMaxIov];
    size_t nvec = 0;
    for (size_t p = 0; p < count && nvec < kMaxIov; ++p) {
        const IOBuf* buf = pieces[p];
        const uint32_t cnt = buf->nref_();
        for (uint32_t i = 0; i < cnt && nvec < kMaxIov; ++i) {
            const BlockRef& r = buf->ref_at(i);
            vec[nvec].iov_base = r.block->data + r.offset;
            vec[nvec].iov_len = r.length;
            ++nvec;
        }
    }
    if (nvec == 0) return 0;
    ssize_t written = writev(fd, vec, (int)nvec);
    if (written > 0) {
        size_t left = (size_t)written;
        for (size_t p = 0; p < count && left > 0; ++p) {
            left -= pieces[p]->pop_front(left);
        }
    }
    return written;
}

// ---------------- IOPortal ----------------

IOPortal::~IOPortal() {
    if (block_) {
        block_->dec_ref();
        block_ = nullptr;
    }
}

void IOPortal::return_cached_blocks() {
    if (block_) {
        block_->dec_ref();
        block_ = nullptr;
    }
}

ssize_t IOPortal::append_from_file_descriptor(int fd, size_t max_count) {
    // Assemble an iovec over [tail of current block] + fresh blocks.
    constexpr size_t kReadVecs = 64;
    iovec vec[kReadVecs];
    Block* blocks[kReadVecs];
    size_t nvec = 0;
    size_t space = 0;
    if (block_ != nullptr && !block_->full()) {
        blocks[nvec] = block_;
        vec[nvec].iov_base = block_->data + block_->size;
        vec[nvec].iov_len = block_->left_space();
        space += block_->left_space();
        ++nvec;
    }
    while (space < max_count && nvec < kReadVecs) {
        Block* b = create_block();
        if (b == nullptr) break;
        blocks[nvec] = b;
        vec[nvec].iov_base = b->data;
        vec[nvec].iov_len = b->cap;
        space += b->cap;
        ++nvec;
    }
    if (nvec == 0) {
        errno = ENOMEM;
        return -1;
    }
    ssize_t nr = readv(fd, vec, (int)nvec);
    if (nr <= 0) {
        // Release blocks we created (index 0 may be the retained block_).
        for (size_t i = 0; i < nvec; ++i) {
            if (blocks[i] != block_) blocks[i]->dec_ref();
        }
        return nr;
    }
    size_t left = (size_t)nr;
    Block* new_current = nullptr;
    for (size_t i = 0; i < nvec; ++i) {
        Block* b = blocks[i];
        const size_t cap_here = vec[i].iov_len;
        const size_t fill = std::min(left, cap_here);
        if (fill > 0) {
            BlockRef r{b->size, (uint32_t)fill, b};
            b->size += (uint32_t)fill;
            b->inc_ref();
            push_back_ref_(r);
            left -= fill;
        }
        if (fill < cap_here && left == 0 && new_current == nullptr && !b->full()) {
            // Keep the first partially-empty block for the next read.
            new_current = b;
            continue;  // retains the ref we hold on it
        }
        if (b != new_current) {
            // Fully used (ref now held by the buf) or untouched: drop our ref
            // unless it's the old block_ that became the new current.
            if (b == block_) {
                // old current: either full (drop) or it became new_current above
                if (b != new_current) {
                    b->dec_ref();
                }
            } else {
                b->dec_ref();
            }
        }
    }
    block_ = new_current;
    return nr;
}

}  // namespace tpurpc
