// SIGPROF sampling CPU profiler — the engine behind the /hotspots/cpu
// builtin (reference: src/brpc/builtin/hotspots_service.cpp drives
// gperftools ProfilerStart; here we own the sampler so the framework has
// no external profiler dependency).
//
// Samples the interrupted PC (and a short frame-pointer backtrace) on
// every ITIMER_PROF tick (all running threads, kernel-selected) into a
// preallocated lock-free buffer. Dump format is text:
//   one "pc fp1 fp2 ..." hex line per sample, then "--- maps ---" and a
//   copy of /proc/self/maps so offline tooling (tools/symbolize_prof.py)
//   can map addresses to functions with addr2line.
#pragma once

#include <cstddef>
#include <string>

namespace tpurpc {

// Starts sampling at `hz` (default 997 to avoid lockstep with timers).
// Returns 0, or -1 if already running.
int StartCpuProfiler(int hz = 997);

// Stops sampling and writes samples + memory map to `path`.
// Returns number of samples written, or -1 on error.
int StopCpuProfiler(const std::string& path);

bool CpuProfilerRunning();

// Stops sampling and returns the profile as a string (same format as the
// file dump) — used by the /hotspots builtin service.
std::string StopCpuProfilerToString();

}  // namespace tpurpc
