// Sampling heap profiler — the engine behind /hotspots/heap and
// /hotspots/growth (reference: gperftools tcmalloc's sampling profiler
// behind brpc's heap_profiler portal; here we own the sampler so the
// framework has no external allocator dependency).
//
// operator new/delete are interposed process-wide (heap_profiler.cc):
// every `-heap_profiler_sample_bytes` allocated bytes, ONE allocation's
// stack is captured with tbase/stack_walk.h and attributed. Two views:
//   live   — sampled bytes currently allocated, by stack (leaks, caches)
//   growth — cumulative sampled bytes allocated since the last reset
//            (churn: who allocates, even if they free promptly)
// Sampling is a deterministic per-thread byte countdown (no RNG): a
// fixed seed + the same allocation sequence reproduce the same sample
// set, which is what makes the profiler testable.
//
// Raw dump format (tools/symbolize_prof.py understands it):
//   heap profile: <stacks> stacks, <bytes> sampled live bytes ...
//   <bytes> <count> @ <pc1> <pc2> ...
//   --- maps ---
//   <copy of /proc/self/maps>
//
// Direct malloc()/free() callers bypass operator new and are NOT
// sampled (IOBuf block pools keep their own accounting in /memory).
// Under ASan the interposers are compiled out (ASan owns the allocator)
// and both views report empty.
#pragma once

#include <cstdint>
#include <string>

namespace tpurpc {

struct HeapProfilerStats {
    int64_t live_bytes = 0;    // sampled bytes still allocated
    int64_t live_count = 0;    // sampled allocations still allocated
    int64_t growth_bytes = 0;  // sampled bytes allocated since reset
    int64_t growth_count = 0;
    int64_t stacks = 0;        // distinct stacks in the table
};

// Sampling is on (interval > 0) and the interposers are compiled in.
bool HeapProfilerActive();

HeapProfilerStats GetHeapProfilerStats();

// Raw pprof-style text (stacks + maps) for offline symbolization.
// growth=false: live bytes by stack; growth=true: cumulative since reset.
std::string HeapProfileRaw(bool growth);

// In-server symbolized rendering (tbase/symbolize.h, like /hotspots/cpu):
// top `top_n` stacks by bytes, one indented frame list each.
std::string HeapProfileSymbolized(bool growth, int top_n = 40);

// Zero the cumulative growth counters (the /hotspots/growth?reset=1
// action); live attribution is untouched.
void ResetHeapGrowth();

// Tests only: drop every table AND restart the calling thread's sample
// countdown so a fixed allocation sequence reproduces exactly.
void ResetHeapProfilerForTest();

}  // namespace tpurpc
