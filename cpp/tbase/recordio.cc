#include "tbase/recordio.h"

#include <arpa/inet.h>

#include <cstring>
#include <vector>

#include "tbase/crc32c.h"

namespace tpurpc {

namespace {
constexpr char kMagic[4] = {'T', 'R', 'E', 'C'};
constexpr size_t kMaxRecord = 256u << 20;
}  // namespace

RecordWriter::RecordWriter(const std::string& path) {
    f_ = fopen(path.c_str(), "ab");
}

RecordWriter::~RecordWriter() {
    if (f_ != nullptr) fclose(f_);
}

bool RecordWriter::Write(const IOBuf& payload) {
    if (f_ == nullptr) return false;
    if (payload.size() > kMaxRecord) {
        // Reject at write time: an oversized record would be accepted
        // here but permanently truncate the stream on read.
        return false;
    }
    char header[12];
    memcpy(header, kMagic, 4);
    const uint32_t len = htonl((uint32_t)payload.size());
    memcpy(header + 4, &len, 4);
    uint32_t crc = 0;
    for (size_t i = 0; i < payload.backing_block_num(); ++i) {
        size_t blen = 0;
        const char* data = payload.backing_block_data(i, &blen);
        crc = crc32c_extend(crc, data, blen);
    }
    crc = htonl(crc);
    memcpy(header + 8, &crc, 4);
    if (fwrite(header, 1, sizeof(header), f_) != sizeof(header)) return false;
    for (size_t i = 0; i < payload.backing_block_num(); ++i) {
        size_t blen = 0;
        const char* data = payload.backing_block_data(i, &blen);
        if (fwrite(data, 1, blen, f_) != blen) return false;
    }
    return true;
}

void RecordWriter::Flush() {
    if (f_ != nullptr) fflush(f_);
}

RecordReader::RecordReader(const std::string& path) {
    f_ = fopen(path.c_str(), "rb");
}

RecordReader::~RecordReader() {
    if (f_ != nullptr) fclose(f_);
}

bool RecordReader::Read(IOBuf* out) {
    out->clear();
    if (f_ == nullptr) return false;
    char header[12];
    if (fread(header, 1, sizeof(header), f_) != sizeof(header)) return false;
    if (memcmp(header, kMagic, 4) != 0) return false;
    uint32_t len, crc;
    memcpy(&len, header + 4, 4);
    memcpy(&crc, header + 8, 4);
    len = ntohl(len);
    crc = ntohl(crc);
    if (len > kMaxRecord) return false;
    std::vector<char> buf(len);
    if (len > 0 && fread(buf.data(), 1, len, f_) != len) return false;
    if (crc32c(buf.data(), len) != crc) return false;
    out->append(buf.data(), len);
    return true;
}

}  // namespace tpurpc
