// VersionedRefWithId: generic 64-bit id = (version<<32)|slot with an atomic
// (version,nref) pair packed in one u64. Address() is wait-free; SetFailed
// flips the version to odd so stale ids fail to resolve.
//
// Modeled on reference src/brpc/versioned_ref_with_id.h:55-207 — the base of
// Socket and IOEventData there; the base of Socket and Stream here.
//
// Lifecycle contract (same as the reference):
//  - Create(): version is even, nref starts at 1 (the "creation ref").
//  - Address(id): succeeds only while version(id) == current even version;
//    bumps nref. Caller must Dereference (use the RAII Ptr).
//  - SetFailed(): flips version to odd exactly once (further Address fails),
//    calls OnFailed(), drops the creation ref.
//  - When nref hits 0, OnRecycle() runs and the slot returns to the pool
//    with version advanced to the next even number.
#pragma once

#include <atomic>
#include <cstdint>

#include "tbase/logging.h"
#include "tbase/resource_pool.h"

namespace tpurpc {

using VRefId = uint64_t;

constexpr VRefId INVALID_VREF_ID = (VRefId)-1;

inline VRefId MakeVRefId(uint32_t version, ResourceId slot) {
    return ((uint64_t)version << 32) | (uint64_t)slot;
}
inline uint32_t VRefVersion(VRefId id) { return (uint32_t)(id >> 32); }
inline ResourceId VRefSlot(VRefId id) { return (ResourceId)(uint32_t)id; }

// T must derive from VersionedRefWithId<T> and provide:
//   void OnFailed();   // called once when SetFailed wins
//   void OnRecycle();  // called when the last ref drops
template <typename T>
class VersionedRefWithId {
public:
    VersionedRefWithId() : versioned_nref_(0), id_(INVALID_VREF_ID) {}

    // Create a new T addressed by *id. Returns 0 on success.
    static int Create(VRefId* id_out, T** out = nullptr) {
        ResourceId slot;
        T* obj = get_resource<T>(&slot);
        if (obj == nullptr) return -1;
        // Current packed state holds the version from the previous life
        // (even) and nref 0.
        uint64_t vn = obj->versioned_nref_.load(std::memory_order_relaxed);
        uint32_t ver = (uint32_t)(vn >> 32);
        CHECK((ver & 1) == 0) << "recycled slot has odd version";
        obj->id_ = MakeVRefId(ver, slot);
        obj->versioned_nref_.store(((uint64_t)ver << 32) | 1,
                                   std::memory_order_release);
        *id_out = obj->id_;
        if (out) *out = obj;
        return 0;
    }

    // Wait-free address: returns nullptr if the id's version is stale.
    static T* Address(VRefId id) {
        T* obj = address_resource<T>(VRefSlot(id));
        if (obj == nullptr) return nullptr;
        const uint32_t expect_ver = VRefVersion(id);
        uint64_t vn = obj->versioned_nref_.load(std::memory_order_acquire);
        while (true) {
            uint32_t ver = (uint32_t)(vn >> 32);
            uint32_t nref = (uint32_t)vn;
            if (ver != expect_ver || nref == 0) return nullptr;
            if (obj->versioned_nref_.compare_exchange_weak(
                    vn, vn + 1, std::memory_order_acquire,
                    std::memory_order_acquire)) {
                return obj;
            }
        }
    }

    VRefId vref_id() const { return id_; }

    // Address WITHOUT taking a ref, resolving even if currently failed
    // (version compared modulo the failed bit). For flag-setting on an
    // object some longer-lived party (e.g. its health-check fiber) keeps
    // alive; must not be used to touch connection state.
    static T* UnsafeAddress(VRefId id) {
        T* obj = address_resource<T>(VRefSlot(id));
        if (obj == nullptr) return nullptr;
        const uint32_t ver = (uint32_t)(
            obj->versioned_nref_.load(std::memory_order_acquire) >> 32);
        if ((ver & ~1u) != (VRefVersion(id) & ~1u)) return nullptr;
        return obj;
    }

    void AddRef() { versioned_nref_.fetch_add(1, std::memory_order_relaxed); }

    void Dereference() {
        uint64_t prev = versioned_nref_.fetch_sub(1, std::memory_order_acq_rel);
        const uint32_t prev_nref = (uint32_t)prev;
        CHECK_GE(prev_nref, 1u);
        if (prev_nref == 1) {
            // Last ref: recycle. Advance version to the next even value so
            // the slot can be reused.
            uint32_t ver = (uint32_t)(prev >> 32);
            uint32_t next_ver = (ver | 1) + 1;  // next even
            static_cast<T*>(this)->OnRecycle();
            versioned_nref_.store((uint64_t)next_ver << 32,
                                  std::memory_order_release);
            return_resource<T>(VRefSlot(id_));
        }
    }

    // Flip version to odd (only the first caller wins), run OnFailed, drop
    // the creation ref. Returns 0 if this call performed the failure.
    int SetFailed() {
        uint64_t vn = versioned_nref_.load(std::memory_order_relaxed);
        while (true) {
            uint32_t ver = (uint32_t)(vn >> 32);
            if (ver & 1) return -1;  // already failed
            uint32_t nref = (uint32_t)vn;
            if (nref == 0) return -1;  // already recycled
            uint64_t next = ((uint64_t)(ver | 1) << 32) | nref;
            if (versioned_nref_.compare_exchange_weak(
                    vn, next, std::memory_order_acq_rel,
                    std::memory_order_relaxed)) {
                static_cast<T*>(this)->OnFailed();
                Dereference();  // drop creation ref
                return 0;
            }
        }
    }

    // Un-fail a failed object: version returns to the original even value
    // so ids minted before SetFailed resolve again, and the creation ref is
    // re-added. Caller must hold a ref (keeping the slot from recycling)
    // and must have reset T's state first. This is how health check revives
    // a Socket without invalidating ids held by load balancers (reference
    // src/brpc/socket.cpp Socket::Revive, health_check.cpp).
    int Revive() {
        uint64_t vn = versioned_nref_.load(std::memory_order_relaxed);
        while (true) {
            uint32_t ver = (uint32_t)(vn >> 32);
            if (!(ver & 1)) return -1;  // not failed
            uint32_t nref = (uint32_t)vn;
            CHECK_GE(nref, 1u) << "Revive without a held ref";
            uint64_t next =
                ((uint64_t)(ver & ~1u) << 32) | (uint64_t)(nref + 1);
            if (versioned_nref_.compare_exchange_weak(
                    vn, next, std::memory_order_acq_rel,
                    std::memory_order_relaxed)) {
                return 0;
            }
        }
    }

    bool Failed() const {
        return (uint32_t)(versioned_nref_.load(std::memory_order_acquire) >>
                          32) &
               1;
    }

    int32_t nref() const {
        return (int32_t)(uint32_t)versioned_nref_.load(
            std::memory_order_acquire);
    }

    static int SetFailedById(VRefId id) {
        T* obj = Address(id);
        if (obj == nullptr) return -1;
        int rc = obj->SetFailed();
        obj->Dereference();
        return rc;
    }

private:
    // high 32: version (odd = failed); low 32: nref.
    std::atomic<uint64_t> versioned_nref_;
    VRefId id_;
};

// RAII reference holder (the SocketUniquePtr pattern).
template <typename T>
class VRefPtr {
public:
    VRefPtr() : obj_(nullptr) {}
    explicit VRefPtr(T* obj) : obj_(obj) {}  // takes over an existing ref
    ~VRefPtr() { reset(); }
    VRefPtr(const VRefPtr&) = delete;
    VRefPtr& operator=(const VRefPtr&) = delete;
    VRefPtr(VRefPtr&& o) noexcept : obj_(o.obj_) { o.obj_ = nullptr; }
    VRefPtr& operator=(VRefPtr&& o) noexcept {
        reset();
        obj_ = o.obj_;
        o.obj_ = nullptr;
        return *this;
    }

    static VRefPtr FromId(VRefId id) { return VRefPtr(T::Address(id)); }

    T* get() const { return obj_; }
    T* operator->() const { return obj_; }
    T& operator*() const { return *obj_; }
    explicit operator bool() const { return obj_ != nullptr; }
    void reset() {
        if (obj_) {
            obj_->Dereference();
            obj_ = nullptr;
        }
    }
    T* release() {
        T* o = obj_;
        obj_ = nullptr;
        return o;
    }

private:
    T* obj_;
};

}  // namespace tpurpc
