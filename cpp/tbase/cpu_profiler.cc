#include "tbase/cpu_profiler.h"

#include "tbase/stack_walk.h"

#include <signal.h>
#include <stdio.h>
#include <string.h>
#include <sys/time.h>
#include <sys/uio.h>
#include <ucontext.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>

namespace tpurpc {

namespace {

constexpr size_t kMaxSamples = 1 << 20;  // 1M samples * 4 slots
constexpr int kDepth = 4;                // pc + 3 caller frames

// Preallocated sample buffer: kDepth slots per sample, 0-terminated rows.
uintptr_t* g_samples = nullptr;
std::atomic<size_t> g_nsamples{0};
std::atomic<bool> g_running{false};
struct sigaction g_old_action;

// Frame capture via the shared hardened walker (tbase/stack_walk.h).
void prof_handler(int, siginfo_t*, void* ucv) {
    if (!g_running.load(std::memory_order_relaxed)) return;
    const size_t i = g_nsamples.fetch_add(1, std::memory_order_relaxed);
    if (i >= kMaxSamples) {
        g_nsamples.store(kMaxSamples, std::memory_order_relaxed);
        return;
    }
    uintptr_t* row = g_samples + i * kDepth;
    const size_t n =
        stack_walk::walk((ucontext_t*)ucv, row, (size_t)kDepth);
    for (size_t d = n; d < (size_t)kDepth; ++d) row[d] = 0;
}

int write_profile(FILE* f) {
    const size_t n = g_nsamples.load(std::memory_order_acquire);
    for (size_t i = 0; i < n; ++i) {
        uintptr_t* row = g_samples + i * kDepth;
        fprintf(f, "%lx", (unsigned long)row[0]);
        for (int d = 1; d < kDepth && row[d] != 0; ++d) {
            fprintf(f, " %lx", (unsigned long)row[d]);
        }
        fputc('\n', f);
    }
    fprintf(f, "--- maps ---\n");
    FILE* maps = fopen("/proc/self/maps", "r");
    if (maps != nullptr) {
        char buf[4096];
        size_t nr;
        while ((nr = fread(buf, 1, sizeof(buf), maps)) > 0) {
            fwrite(buf, 1, nr, f);
        }
        fclose(maps);
    }
    return (int)n;
}

}  // namespace

int StartCpuProfiler(int hz) {
    bool expected = false;
    if (!g_running.compare_exchange_strong(expected, true)) return -1;
    if (g_samples == nullptr) {
        g_samples = new uintptr_t[kMaxSamples * kDepth];
    }
    g_nsamples.store(0, std::memory_order_relaxed);
    struct sigaction sa;
    memset(&sa, 0, sizeof(sa));
    sa.sa_sigaction = prof_handler;
    sa.sa_flags = SA_SIGINFO | SA_RESTART;
    sigemptyset(&sa.sa_mask);
    sigaction(SIGPROF, &sa, &g_old_action);
    itimerval tv;
    tv.it_interval.tv_sec = 0;
    tv.it_interval.tv_usec = 1000000 / (hz > 0 ? hz : 997);
    tv.it_value = tv.it_interval;
    setitimer(ITIMER_PROF, &tv, nullptr);
    return 0;
}

bool CpuProfilerRunning() {
    return g_running.load(std::memory_order_acquire);
}

static void stop_sampling() {
    itimerval tv;
    memset(&tv, 0, sizeof(tv));
    setitimer(ITIMER_PROF, &tv, nullptr);
    g_running.store(false, std::memory_order_release);
    // Keep our (no-op when stopped) handler installed: a tick generated
    // just before the disarm may still be pending, and restoring SIG_DFL
    // here would let that late SIGPROF terminate the process.
}

int StopCpuProfiler(const std::string& path) {
    if (!g_running.load(std::memory_order_acquire)) return -1;
    stop_sampling();
    FILE* f = fopen(path.c_str(), "w");
    if (f == nullptr) return -1;
    const int n = write_profile(f);
    fclose(f);
    return n;
}

std::string StopCpuProfilerToString() {
    if (!g_running.load(std::memory_order_acquire)) return std::string();
    stop_sampling();
    char* buf = nullptr;
    size_t len = 0;
    FILE* f = open_memstream(&buf, &len);
    if (f == nullptr) return std::string();
    write_profile(f);
    fclose(f);
    std::string out(buf, len);
    free(buf);
    return out;
}

}  // namespace tpurpc
