#include "tbase/fast_rand.h"

#include <ctime>

namespace tpurpc {

namespace {
struct SplitMix64 {
    uint64_t x;
    uint64_t next() {
        uint64_t z = (x += 0x9e3779b97f4a7c15ULL);
        z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
        z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
        return z ^ (z >> 31);
    }
};

struct Xoshiro256 {
    uint64_t s[4];
    bool seeded = false;
    static uint64_t rotl(uint64_t x, int k) { return (x << k) | (x >> (64 - k)); }
    void seed() {
        struct timespec ts;
        clock_gettime(CLOCK_MONOTONIC, &ts);
        SplitMix64 sm{(uint64_t)ts.tv_nsec ^ ((uint64_t)ts.tv_sec << 32) ^
                      (uint64_t)(uintptr_t)this};
        for (auto& v : s) v = sm.next();
        seeded = true;
    }
    uint64_t next() {
        if (!seeded) seed();
        const uint64_t result = rotl(s[1] * 5, 7) * 9;
        const uint64_t t = s[1] << 17;
        s[2] ^= s[0];
        s[3] ^= s[1];
        s[1] ^= s[2];
        s[0] ^= s[3];
        s[2] ^= t;
        s[3] = rotl(s[3], 45);
        return result;
    }
};

thread_local Xoshiro256 tls_rng;
}  // namespace

uint64_t fast_rand() { return tls_rng.next(); }

uint64_t fast_rand_less_than(uint64_t range) {
    if (range == 0) return 0;
    // Lemire's multiply-shift rejection-free approximation is fine here.
    return fast_rand() % range;
}

double fast_rand_double() {
    return (double)(fast_rand() >> 11) * (1.0 / 9007199254740992.0);
}

}  // namespace tpurpc
