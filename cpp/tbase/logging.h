// Stream-style logging with CHECK macros.
//
// Modeled on the reference's chromium-derived logger (reference:
// src/butil/logging.h — LOG(x) streams, CHECK/DCHECK macros, severity
// levels, optional glog backend). This implementation is deliberately lean:
// severities, thread-safe line-buffered output to stderr, CHECK* that
// abort with the failed expression, and a pluggable sink so the builtin
// portal can capture recent logs later.
#pragma once

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <functional>
#include <sstream>
#include <string>

namespace tpurpc {

enum LogSeverity : int {
    LOG_TRACE = -1,
    LOG_DEBUG = 0,
    LOG_INFO = 1,
    LOG_WARNING = 2,
    LOG_ERROR = 3,
    LOG_FATAL = 4,
};

// Minimum severity that actually gets emitted (default INFO).
int GetMinLogLevel();
void SetMinLogLevel(int level);

// Optional sink; return true to suppress the default stderr write.
using LogSink = std::function<bool(int severity, const char* file, int line,
                                   const std::string& message)>;
void SetLogSink(LogSink sink);

class LogMessage {
public:
    LogMessage(const char* file, int line, int severity);
    ~LogMessage();
    std::ostream& stream() { return stream_; }

private:
    std::ostringstream stream_;
    const char* file_;
    int line_;
    int severity_;
};

// Swallows the stream when the severity is below the threshold.
class LogMessageVoidify {
public:
    void operator&(std::ostream&) {}
};

namespace logging_internal {
// True at most once per second per call site (stamp = last pass, us).
bool PassEverySecond(std::atomic<int64_t>* last_us);
}  // namespace logging_internal

}  // namespace tpurpc

#define TPURPC_LOG_STREAM(severity)                                       \
    ::tpurpc::LogMessage(__FILE__, __LINE__, ::tpurpc::LOG_##severity)   \
        .stream()

#define LOG(severity)                                                \
    (::tpurpc::LOG_##severity < ::tpurpc::GetMinLogLevel())          \
        ? (void)0                                                    \
        : ::tpurpc::LogMessageVoidify() & TPURPC_LOG_STREAM(severity)

#define LOG_IF(severity, cond) \
    !(cond) ? (void)0 : ::tpurpc::LogMessageVoidify() & TPURPC_LOG_STREAM(severity)

// Rate-limited variants (reference butil/logging.h LOG_EVERY_N /
// LOG_EVERY_SECOND): error storms on hot paths must not become a
// throughput hazard of their own. Each occurrence site gets its own
// static counter/stamp; the check is one relaxed atomic op when
// suppressed.
#define LOG_EVERY_N(severity, n)                                          \
    static ::std::atomic<uint64_t> TPURPC_CAT_(tpurpc_logn_, __LINE__){0}; \
    (TPURPC_CAT_(tpurpc_logn_, __LINE__).fetch_add(                        \
         1, ::std::memory_order_relaxed) %                                 \
         (uint64_t)(n) !=                                                  \
     0)                                                                    \
        ? (void)0                                                          \
        : ::tpurpc::LogMessageVoidify() & TPURPC_LOG_STREAM(severity)

#define LOG_EVERY_SECOND(severity)                                         \
    static ::std::atomic<int64_t> TPURPC_CAT_(tpurpc_logs_, __LINE__){0};  \
    !::tpurpc::logging_internal::PassEverySecond(                          \
        &TPURPC_CAT_(tpurpc_logs_, __LINE__))                              \
        ? (void)0                                                          \
        : ::tpurpc::LogMessageVoidify() & TPURPC_LOG_STREAM(severity)

#define TPURPC_CAT2_(a, b) a##b
#define TPURPC_CAT_(a, b) TPURPC_CAT2_(a, b)

#define CHECK(cond)                                                         \
    (cond) ? (void)0                                                        \
           : ::tpurpc::LogMessageVoidify() &                                \
                 (TPURPC_LOG_STREAM(FATAL) << "Check failed: " #cond " ")

#define CHECK_EQ(a, b) CHECK((a) == (b))
#define CHECK_NE(a, b) CHECK((a) != (b))
#define CHECK_LT(a, b) CHECK((a) < (b))
#define CHECK_LE(a, b) CHECK((a) <= (b))
#define CHECK_GT(a, b) CHECK((a) > (b))
#define CHECK_GE(a, b) CHECK((a) >= (b))

#ifdef NDEBUG
#define DCHECK(cond) CHECK(true || (cond))
#else
#define DCHECK(cond) CHECK(cond)
#endif

// PLOG appends errno text.
#define PLOG(severity) \
    LOG(severity) << "[" << strerror(errno) << "] "
