// Stream-style logging with CHECK macros.
//
// Modeled on the reference's chromium-derived logger (reference:
// src/butil/logging.h — LOG(x) streams, CHECK/DCHECK macros, severity
// levels, optional glog backend). This implementation is deliberately lean:
// severities, thread-safe line-buffered output to stderr, CHECK* that
// abort with the failed expression, and a pluggable sink so the builtin
// portal can capture recent logs later.
#pragma once

#include <cstdint>
#include <cstdlib>
#include <functional>
#include <sstream>
#include <string>

namespace tpurpc {

enum LogSeverity : int {
    LOG_TRACE = -1,
    LOG_DEBUG = 0,
    LOG_INFO = 1,
    LOG_WARNING = 2,
    LOG_ERROR = 3,
    LOG_FATAL = 4,
};

// Minimum severity that actually gets emitted (default INFO).
int GetMinLogLevel();
void SetMinLogLevel(int level);

// Optional sink; return true to suppress the default stderr write.
using LogSink = std::function<bool(int severity, const char* file, int line,
                                   const std::string& message)>;
void SetLogSink(LogSink sink);

class LogMessage {
public:
    LogMessage(const char* file, int line, int severity);
    ~LogMessage();
    std::ostream& stream() { return stream_; }

private:
    std::ostringstream stream_;
    const char* file_;
    int line_;
    int severity_;
};

// Swallows the stream when the severity is below the threshold.
class LogMessageVoidify {
public:
    void operator&(std::ostream&) {}
};

}  // namespace tpurpc

#define TPURPC_LOG_STREAM(severity)                                       \
    ::tpurpc::LogMessage(__FILE__, __LINE__, ::tpurpc::LOG_##severity)   \
        .stream()

#define LOG(severity)                                                \
    (::tpurpc::LOG_##severity < ::tpurpc::GetMinLogLevel())          \
        ? (void)0                                                    \
        : ::tpurpc::LogMessageVoidify() & TPURPC_LOG_STREAM(severity)

#define LOG_IF(severity, cond) \
    !(cond) ? (void)0 : ::tpurpc::LogMessageVoidify() & TPURPC_LOG_STREAM(severity)

#define CHECK(cond)                                                         \
    (cond) ? (void)0                                                        \
           : ::tpurpc::LogMessageVoidify() &                                \
                 (TPURPC_LOG_STREAM(FATAL) << "Check failed: " #cond " ")

#define CHECK_EQ(a, b) CHECK((a) == (b))
#define CHECK_NE(a, b) CHECK((a) != (b))
#define CHECK_LT(a, b) CHECK((a) < (b))
#define CHECK_LE(a, b) CHECK((a) <= (b))
#define CHECK_GT(a, b) CHECK((a) > (b))
#define CHECK_GE(a, b) CHECK((a) >= (b))

#ifdef NDEBUG
#define DCHECK(cond) CHECK(true || (cond))
#else
#define DCHECK(cond) CHECK(cond)
#endif

// PLOG appends errno text.
#define PLOG(severity) \
    LOG(severity) << "[" << strerror(errno) << "] "
