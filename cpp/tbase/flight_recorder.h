// Always-on flight recorder: per-thread lock-free rings of compact binary
// events, dumped as a "black box" on fatal signals or on demand.
//
// The reference debugs live nodes with rpcz/vars; this is the post-mortem
// twin (T3-style step event tracking, arXiv:2401.16677): every load-bearing
// seam records a 32-byte event into a thread-local ring at ~single-digit-ns
// cost, and a crash (SIGSEGV/SIGABRT/LOG(FATAL)) snapshots all rings to a
// file that tools/blackbox_merge.py can correlate across nodes.
//
// Hot-path contract: Record() is one relaxed atomic load when disabled, and
// one rdtsc + four plain stores when enabled. No locks, no allocation after
// the ring is registered (first event on a thread), single writer per ring.
#pragma once

#include <atomic>
#include <cstddef>
#include <cstdint>
#include <string>

namespace tpurpc {
namespace flight {

// Event kinds. Numeric values are a wire format shared with
// tools/blackbox_merge.py — append only, never renumber.
enum EventKind : uint32_t {
    kNone = 0,
    // RPC lifecycle. a=correlation id unless noted.
    kRpcIssue = 1,       // client issues a call        b=trace id
    kRpcDispatch = 2,    // server parsed the request   b=request bytes
    kRpcHandlerIn = 3,   // user handler entered        b=trace id
    kRpcHandlerOut = 4,  // user handler returned       b=error code
    kRpcWrite = 5,       // response queued to socket   b=response bytes
    kRpcRespRecv = 6,    // client received response    b=error code
    // One-sided verbs. a=wr_id.
    kVerbPost = 7,      // verb posted locally          b=verb<<32|bytes
    kVerbWire = 8,      // grantor saw the wire verb    b=verb<<32|bytes
    kVerbComplete = 9,  // completion delivered         b=status
    kVerbReap = 10,     // pending post reaped          b=error code
    // Block leases. a=lease id.
    kLeasePin = 11,       // b=bytes
    kLeaseArm = 12,       // b=call id
    kLeaseRelease = 13,   // b=bytes
    kLeaseExpire = 14,    // b=age_ms
    kLeasePeerDeath = 15, // a=peer key hash  b=leases reclaimed
    // Streams. a=stream id.
    kStreamChunk = 16,        // b=chunk seq
    kStreamCreditStall = 17,  // b=chunk seq at stall
    kStreamResume = 18,       // b=resume-from seq
    // Collectives. a=step/epoch.
    kCollStep = 19,    // b=op<<32|chunk
    kCollReform = 20,  // a=new epoch  b=world size
    // Scheduler.
    kSchedInline = 21,  // inline dispatch on IO thread  a=bytes
    kSchedPark = 22,    // worker parked                 a=signal count
    // Chaos. a=decision index; b packs seed_low32<<32|op<<8|action kind so a
    // seed replay aligns decision-for-decision with the timeline.
    kChaosInject = 23,
    // Outlier ejection (ISSUE 20). a packs the backend's identity
    // (ip4<<16|port — no cid exists for a routing decision); EJECT's b
    // packs reason<<56|detail (detail = ewma/median ratio x100 for
    // latency outliers, the consecutive-error threshold otherwise);
    // REINSTATE's b = probe passes. blackbox_merge decodes both, so a
    // merged timeline shows WHY routing shifted between a grey node's
    // last slow rpc and the first re-routed pick.
    kOutlierEject = 24,
    kOutlierReinstate = 25,

    kKindCount = 26,
};

// Stable names for dumps (indexed by EventKind, length kKindCount).
extern const char* const kKindNames[];

namespace internal {

// One fixed-size ring owned by exactly one writer thread. Kept trivially
// copyable so a signal handler can dump raw memory.
struct Event {
    uint64_t tsc;   // cpuwide_ticks() at record time
    uint32_t kind;  // EventKind
    uint32_t seq;   // low 32 bits of this ring's event counter
    uint64_t a;
    uint64_t b;
};
static_assert(sizeof(Event) == 32, "event must stay compact");

struct ThreadRing {
    Event* slots;
    uint32_t cap;       // power of two
    uint32_t tid;       // kernel tid of the owner
    char name[16];      // thread name at registration
    // Total events ever recorded; slot = next & (cap-1). Only the owner
    // writes it; dumpers read it racily (torn tails are dropped by seq).
    std::atomic<uint64_t> next;
};

constexpr int kMaxRings = 256;

extern std::atomic<bool> g_on;
extern std::atomic<int> g_nrings;
extern ThreadRing* g_rings[kMaxRings];

void RecordSlow(EventKind kind, uint64_t a, uint64_t b);

}  // namespace internal

// Record one event. Safe from any thread at any time (including before
// and after Init); compiles to a relaxed load + branch when disabled.
inline void Record(EventKind kind, uint64_t a, uint64_t b) {
    if (!internal::g_on.load(std::memory_order_relaxed)) return;
    internal::RecordSlow(kind, a, b);
}

// Identity stamped into dumps so the merge tool can label lanes. Safe to
// call once at process start (copies into a static buffer).
void SetNodeName(const std::string& name);

// Dump every registered ring.
//  - DumpToFd: async-signal-safe (write(2) only, preformatted header); this
//    is what the crash handler uses. Returns bytes written or -1.
//  - DumpToFile: open+DumpToFd, bumps rpc_flight_dump_count on success.
//  - DumpJson/DumpText: for the /blackbox portal on a live node.
int64_t DumpToFd(int fd);
bool DumpToFile(const std::string& path);
void DumpJson(std::string* out);
void DumpText(std::string* out);

// Install SIGSEGV/SIGABRT/SIGBUS/SIGFPE/SIGILL handlers that dump all rings
// to `path` and re-raise. LOG(FATAL) aborts, so it is covered via SIGABRT.
// Also mirrors into -flight_blackbox_path for live retargeting.
void InstallCrashHandler(const std::string& path);

// Dump to the crash-handler path if one was installed (unclean-exit paths
// in mesh_node/tpu_router). No-op without a configured path.
bool DumpToConfiguredPath();

// Expose rpc_blackbox_{events,dropped,ring_highwater} + rpc_flight_dump_count.
void ExposeVars();

// Introspection for tests/counters.
uint64_t TotalEvents();     // sum of ring next counters
uint64_t TotalDropped();    // overwritten events + lost-ring events
uint64_t RingHighwater();   // max valid events in any one ring
uint64_t DumpCount();       // successful file dumps

}  // namespace flight
}  // namespace tpurpc
