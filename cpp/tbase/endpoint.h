// EndPoint: ip:port value type with parsing and hostname resolution.
// Modeled on reference src/butil/endpoint.h (str2endpoint/endpoint2str,
// hostname2endpoint). IPv4 + unix-domain ("unix:/path") supported.
#pragma once

#include <netinet/in.h>

#include <cstdint>
#include <string>

namespace tpurpc {

struct EndPoint {
    // Host byte order is never exposed: `ip` is in network byte order as in
    // the reference (butil::ip_t wraps in_addr).
    in_addr ip{};
    int port = 0;

    EndPoint() { ip.s_addr = 0; }
    EndPoint(in_addr i, int p) : ip(i), port(p) {}

    bool operator==(const EndPoint& o) const {
        return ip.s_addr == o.ip.s_addr && port == o.port;
    }
    bool operator!=(const EndPoint& o) const { return !(*this == o); }
    bool operator<(const EndPoint& o) const {
        return ip.s_addr != o.ip.s_addr ? ip.s_addr < o.ip.s_addr
                                        : port < o.port;
    }
};

// "10.0.0.1:8000" -> EndPoint. Returns 0 on success, -1 on failure.
int str2endpoint(const char* str, EndPoint* ep);
int str2endpoint(const char* ip_str, int port, EndPoint* ep);
// "www.foo.com:80" -> EndPoint (blocking getaddrinfo).
int hostname2endpoint(const char* str, EndPoint* ep);
std::string endpoint2str(const EndPoint& ep);

// sockaddr conversion.
void endpoint2sockaddr(const EndPoint& ep, sockaddr_in* out);
EndPoint sockaddr2endpoint(const sockaddr_in& in);

struct EndPointHasher {
    size_t operator()(const EndPoint& ep) const {
        return ((size_t)ep.ip.s_addr * 101) ^ (size_t)ep.port;
    }
};

}  // namespace tpurpc
