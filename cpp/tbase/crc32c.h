// CRC32-C (Castagnoli, polynomial 0x1EDC6F41 reflected 0x82F63B78):
// the frame checksum of the RPC layer.
//
// Reference: src/butil/crc32c.{h,cc} (hardware SSE4.2 path + table
// fallback). Software slice-by-8 here; bulk data rides shared memory on
// the target platform, so the checksum covers control frames where table
// speed (~1-2 GB/s) is ample. An SSE4.2/PMULL fast path slots in behind
// the same signature.
#pragma once

#include <cstddef>
#include <cstdint>

namespace tpurpc {

// Extend a running crc with [data, data+n). Start with crc = 0.
uint32_t crc32c_extend(uint32_t crc, const void* data, size_t n);

inline uint32_t crc32c(const void* data, size_t n) {
    return crc32c_extend(0, data, n);
}

}  // namespace tpurpc
