#include "tbase/logging.h"

#include "tbase/time.h"

#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <mutex>

namespace tpurpc {

static std::atomic<int> g_min_log_level{LOG_INFO};
static LogSink g_sink;
static std::mutex g_sink_mu;

int GetMinLogLevel() { return g_min_log_level.load(std::memory_order_relaxed); }
void SetMinLogLevel(int level) {
    g_min_log_level.store(level, std::memory_order_relaxed);
}
void SetLogSink(LogSink sink) {
    std::lock_guard<std::mutex> g(g_sink_mu);
    g_sink = std::move(sink);
}

static const char* SeverityName(int s) {
    switch (s) {
        case LOG_TRACE: return "T";
        case LOG_DEBUG: return "D";
        case LOG_INFO: return "I";
        case LOG_WARNING: return "W";
        case LOG_ERROR: return "E";
        case LOG_FATAL: return "F";
    }
    return "?";
}

LogMessage::LogMessage(const char* file, int line, int severity)
    : file_(file), line_(line), severity_(severity) {}

LogMessage::~LogMessage() {
    std::string msg = stream_.str();
    {
        std::lock_guard<std::mutex> g(g_sink_mu);
        if (g_sink && g_sink(severity_, file_, line_, msg)) {
            if (severity_ >= LOG_FATAL) abort();
            return;
        }
    }
    // One formatted line, single write() so concurrent logs don't interleave.
    const char* base = strrchr(file_, '/');
    base = base ? base + 1 : file_;
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    struct tm tm_buf;
    localtime_r(&ts.tv_sec, &tm_buf);
    char line_buf[4096];
    int n = snprintf(line_buf, sizeof(line_buf),
                     "%s%02d%02d %02d:%02d:%02d.%06ld %s:%d] %s\n",
                     SeverityName(severity_), tm_buf.tm_mon + 1, tm_buf.tm_mday,
                     tm_buf.tm_hour, tm_buf.tm_min, tm_buf.tm_sec,
                     ts.tv_nsec / 1000, base, line_, msg.c_str());
    if (n > 0) {
        ssize_t unused = write(STDERR_FILENO, line_buf,
                               (size_t)(n < (int)sizeof(line_buf) ? n : (int)sizeof(line_buf)));
        (void)unused;
    }
    if (severity_ >= LOG_FATAL) abort();
}

}  // namespace tpurpc

namespace tpurpc {
namespace logging_internal {

bool PassEverySecond(std::atomic<int64_t>* last_us) {
    const int64_t now = monotonic_time_us();
    int64_t prev = last_us->load(std::memory_order_relaxed);
    if (now - prev < 1000 * 1000) return false;
    // One winner per second; losers stay suppressed.
    return last_us->compare_exchange_strong(prev, now,
                                            std::memory_order_relaxed);
}

}  // namespace logging_internal
}  // namespace tpurpc
