// ResourcePool: slab allocator addressed by 32/64-bit ids with O(1)
// get/address/return — the "weak_ptr as integer" idiom underlying SocketId,
// fiber ids and butex ids.
//
// Modeled on reference src/butil/resource_pool.h:97-118 +
// resource_pool_inl.h (get_resource / address_resource / return_resource
// over PER-THREAD free chunks and a two-level block table). The hot paths
// are thread-local: return_resource pushes onto this thread's free chunk
// and get_resource pops it; only chunk transfer (one op per ~kChunkSize
// recycles) and fresh-slot block growth touch a global mutex. Objects are
// NEVER destructed until process exit; a returned slot is recycled to a
// later get_resource() call, and stale ids are guarded by version schemes
// layered above (versioned_ref.h).
#pragma once

#include <atomic>
#include <cstdint>
#include <mutex>
#include <vector>

namespace tpurpc {

using ResourceId = uint64_t;

template <typename T>
class ResourcePool {
public:
    static constexpr size_t BLOCK_NITEM = 256;
    static constexpr size_t MAX_BLOCKS = 1 << 16;
    // TLS free-chunk sizing: a thread keeps at most kCacheCap recycled ids;
    // above that it ships kChunkSize of them to the global pool in one
    // locked op (amortized locking, reference free_chunk_nitem).
    static constexpr size_t kChunkSize = 64;
    static constexpr size_t kCacheCap = 2 * kChunkSize;

    static ResourcePool* singleton() {
        // Intentionally leaked: slots must outlive all static destructors.
        static ResourcePool* pool = new ResourcePool;
        return pool;
    }

    // Get a free slot; *id receives its address. The object is NOT
    // re-constructed on reuse (same as the reference) — callers re-init.
    T* get_resource(ResourceId* id) {
        LocalCache& tls = local_cache();
        if (!tls.free_ids.empty()) {
            const ResourceId rid = tls.free_ids.back();
            tls.free_ids.pop_back();
            *id = rid;
            return unsafe_address(rid);
        }
        // Refill one chunk from the global free list.
        {
            std::lock_guard<std::mutex> g(free_mu_);
            if (!free_list_.empty()) {
                const size_t take =
                    free_list_.size() < kChunkSize ? free_list_.size()
                                                   : kChunkSize;
                tls.free_ids.assign(free_list_.end() - (long)take,
                                    free_list_.end());
                free_list_.resize(free_list_.size() - take);
            }
        }
        if (!tls.free_ids.empty()) {
            const ResourceId rid = tls.free_ids.back();
            tls.free_ids.pop_back();
            *id = rid;
            return unsafe_address(rid);
        }
        // Allocate a fresh slot (cold once the pool is warmed).
        std::lock_guard<std::mutex> g(grow_mu_);
        size_t n = nitem_.load(std::memory_order_relaxed);
        const size_t block_idx = n / BLOCK_NITEM;
        if (block_idx >= MAX_BLOCKS) return nullptr;
        if (block_idx >= nblock_.load(std::memory_order_acquire)) {
            Block* b = new Block;
            blocks_[block_idx] = b;
            nblock_.store(block_idx + 1, std::memory_order_release);
        }
        nitem_.store(n + 1, std::memory_order_relaxed);
        *id = (ResourceId)n;
        return &blocks_[block_idx]->items[n % BLOCK_NITEM];
    }

    // Wait-free id -> pointer. Never fails for ids previously returned by
    // get_resource (slots are never freed).
    T* address_resource(ResourceId id) const {
        const size_t block_idx = (size_t)id / BLOCK_NITEM;
        if (block_idx >= nblock_.load(std::memory_order_acquire)) {
            return nullptr;
        }
        return &blocks_[block_idx]->items[(size_t)id % BLOCK_NITEM];
    }

    void return_resource(ResourceId id) {
        LocalCache& tls = local_cache();
        tls.free_ids.push_back(id);
        if (tls.free_ids.size() >= kCacheCap) {
            // Ship one chunk to the global list; keep the rest local.
            std::lock_guard<std::mutex> g(free_mu_);
            free_list_.insert(free_list_.end(),
                              tls.free_ids.end() - (long)kChunkSize,
                              tls.free_ids.end());
            tls.free_ids.resize(tls.free_ids.size() - kChunkSize);
        }
    }

    size_t size() const { return nitem_.load(std::memory_order_relaxed); }

private:
    struct Block {
        T items[BLOCK_NITEM];
    };

    // Per-thread free chunk. On thread exit the remainder is flushed to
    // the (leaked) global pool so ids owned by a dying thread are not
    // stranded.
    struct LocalCache {
        std::vector<ResourceId> free_ids;
        ResourcePool* owner = nullptr;
        ~LocalCache() {
            if (owner != nullptr && !free_ids.empty()) {
                std::lock_guard<std::mutex> g(owner->free_mu_);
                owner->free_list_.insert(owner->free_list_.end(),
                                         free_ids.begin(), free_ids.end());
            }
        }
    };

    LocalCache& local_cache() {
        thread_local LocalCache tls;
        if (tls.owner == nullptr) {
            tls.owner = this;
            tls.free_ids.reserve(kCacheCap);
        }
        return tls;
    }

    ResourcePool() : blocks_(MAX_BLOCKS, nullptr) {}

    T* unsafe_address(ResourceId id) const {
        return &blocks_[(size_t)id / BLOCK_NITEM]->items[(size_t)id % BLOCK_NITEM];
    }

    std::mutex free_mu_;
    std::vector<ResourceId> free_list_;
    std::mutex grow_mu_;
    std::atomic<size_t> nitem_{0};
    std::atomic<size_t> nblock_{0};
    mutable std::vector<Block*> blocks_;
};

// Convenience wrappers mirroring the reference's free functions
// (resource_pool.h:97 get_resource / address_resource / return_resource).
template <typename T>
inline T* get_resource(ResourceId* id) {
    return ResourcePool<T>::singleton()->get_resource(id);
}
template <typename T>
inline T* address_resource(ResourceId id) {
    return ResourcePool<T>::singleton()->address_resource(id);
}
template <typename T>
inline void return_resource(ResourceId id) {
    ResourcePool<T>::singleton()->return_resource(id);
}

// ObjectPool: like ResourcePool but addressed by pointer, with TLS free
// lists (reference src/butil/object_pool.h). Used for hot small objects.
template <typename T>
class ObjectPool {
public:
    static T* get() {
        auto& tls = tls_free();
        if (!tls.empty()) {
            T* obj = tls.back();
            tls.pop_back();
            return obj;
        }
        return new T;
    }
    static void put(T* obj) {
        auto& tls = tls_free();
        if (tls.size() < 128) {
            tls.push_back(obj);
        } else {
            delete obj;
        }
    }

private:
    static std::vector<T*>& tls_free() {
        thread_local std::vector<T*> v;
        return v;
    }
};

}  // namespace tpurpc
