#include "tbase/heap_profiler.h"

#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include <algorithm>
#include <atomic>
#include <map>
#include <mutex>
#include <new>
#include <unordered_map>
#include <vector>

#include "tbase/flags.h"
#include "tbase/stack_walk.h"
#include "tbase/symbolize.h"

// Sample one allocation stack every this many operator-new bytes
// (deterministic per-thread countdown). 0 disables sampling; deletes
// then cost one relaxed load. Live-togglable via /flags.
DEFINE_int64(heap_profiler_sample_bytes, 512 * 1024,
             "heap profiler: sample one allocation stack every N "
             "allocated bytes; 0 disables");
// Offsets the FIRST sample of each thread by seed%interval bytes so a
// test can phase-shift the deterministic sample set; 0 = sample after a
// full interval.
DEFINE_int64(heap_profiler_sample_seed, 0,
             "heap profiler: initial countdown offset (bytes)");

namespace tpurpc {
namespace heap_prof {

namespace {

constexpr int kDepth = 8;       // frames kept per sampled stack
constexpr size_t kMaxStacks = 4096;  // distinct-stack table bound
constexpr int kShards = 64;     // live-pointer table sharding

// All hot-path globals are constant-initialized PODs/atomics: the
// interposed operator new runs during OTHER TUs' static init, long
// before this TU's flag objects construct. Until the flag-sync object
// below runs, g_interval is 0 and sampling is off — exactly right for
// early allocations.
std::atomic<int64_t> g_interval{0};
std::atomic<int64_t> g_seed{0};
std::atomic<int64_t> g_nlive{0};  // live sampled pointers, process-wide

// Per-thread state. Trivially-initialized thread_locals only: a ctor
// would recurse through operator new during TLS init.
thread_local int64_t tls_countdown = -1;  // -1: derive from flags
thread_local bool tls_in_hook = false;    // reentrancy guard

struct StackKey {
    uintptr_t pc[kDepth];
    bool operator<(const StackKey& o) const {
        return memcmp(pc, o.pc, sizeof(pc)) < 0;
    }
};

// Atomics so the delete path can decrement without the table lock
// (std::map nodes are address-stable).
struct StackStat {
    std::atomic<int64_t> live_bytes{0};
    std::atomic<int64_t> live_count{0};
    std::atomic<int64_t> growth_bytes{0};
    std::atomic<int64_t> growth_count{0};
};

struct StackTable {
    std::mutex mu;
    std::map<StackKey, StackStat> stacks;
    StackStat overflow;  // everything past kMaxStacks lands here
};

StackTable* stack_table() {
    // First call happens under tls_in_hook (the nested `new` of the
    // table itself must not re-enter sampling).
    static StackTable* t = new StackTable;
    return t;
}

struct LiveRec {
    size_t size;
    StackStat* stat;
};

// Sharded live-pointer table. The per-shard `filter` is a 64-bit mini
// bloom over the shard's live pointers: the delete hot path (every
// operator delete in the process while any sample is live) is one
// relaxed load + bit test in the overwhelmingly common not-sampled
// case. Bits only clear when the shard empties — with a few hundred
// live samples the filter stays sparse.
struct Shard {
    std::mutex mu;
    std::atomic<uint64_t> filter{0};
    std::unordered_map<void*, LiveRec> live;
};

Shard* shards() {
    static Shard* s = new Shard[kShards];
    return s;
}

inline uint64_t ptr_hash(void* p) {
    return (uint64_t)(uintptr_t)p * 0x9E3779B97F4A7C15ull;
}
inline int shard_of(uint64_t h) { return (int)((h >> 8) & (kShards - 1)); }
inline uint64_t filter_bit(uint64_t h) { return 1ull << ((h >> 14) & 63); }

// Capture + record ONE sampled allocation. Runs with tls_in_hook set:
// the map/node allocations below bypass sampling.
// noinline + the always_inline wrappers below pin the frame layout at
// every optimization level: walk_current's caller chain is exactly
// [RecordAlloc, operator new, <real allocation site>...], which is what
// the skip=2 below assumes.
__attribute__((noinline)) void RecordAlloc(void* p, size_t size) {
    uintptr_t frames[kDepth];
    // skip=2 drops RecordAlloc + the operator new wrapper; the leaf of
    // the recorded stack is the real allocation site.
    size_t n = stack_walk::walk_current(frames, (size_t)kDepth, 2);
    StackKey key;
    memset(&key, 0, sizeof(key));
    for (size_t i = 0; i < n; ++i) key.pc[i] = frames[i];

    StackTable* st = stack_table();
    StackStat* stat;
    {
        std::lock_guard<std::mutex> g(st->mu);
        auto it = st->stacks.find(key);
        if (it != st->stacks.end()) {
            stat = &it->second;
        } else if (st->stacks.size() < kMaxStacks) {
            stat = &st->stacks[key];
        } else {
            stat = &st->overflow;
        }
    }
    stat->live_bytes.fetch_add((int64_t)size, std::memory_order_relaxed);
    stat->live_count.fetch_add(1, std::memory_order_relaxed);
    stat->growth_bytes.fetch_add((int64_t)size, std::memory_order_relaxed);
    stat->growth_count.fetch_add(1, std::memory_order_relaxed);

    const uint64_t h = ptr_hash(p);
    Shard& sh = shards()[shard_of(h)];
    {
        std::lock_guard<std::mutex> g(sh.mu);
        sh.live[p] = LiveRec{size, stat};
    }
    sh.filter.fetch_or(filter_bit(h), std::memory_order_relaxed);
    g_nlive.fetch_add(1, std::memory_order_release);
}

__attribute__((always_inline)) inline void MaybeSample(void* p,
                                                       size_t size) {
    const int64_t interval = g_interval.load(std::memory_order_relaxed);
    if (interval <= 0 || p == nullptr) return;
    if (tls_in_hook) return;
    int64_t cd = tls_countdown;
    if (cd < 0) {
        const int64_t seed = g_seed.load(std::memory_order_relaxed);
        cd = interval - (seed > 0 ? seed % interval : 0);
        if (cd <= 0) cd = 1;
    }
    cd -= (int64_t)size;
    if (cd > 0) {
        tls_countdown = cd;
        return;
    }
    tls_countdown = interval;  // deterministic: always a full interval
    tls_in_hook = true;
    RecordAlloc(p, size);
    tls_in_hook = false;
}

inline void MaybeUnsample(void* p) {
    if (p == nullptr) return;
    if (g_nlive.load(std::memory_order_acquire) == 0) return;
    // The bookkeeping below frees unordered_map nodes through operator
    // delete; without the guard that nested delete could hash into the
    // shard whose mutex we hold.
    if (tls_in_hook) return;
    const uint64_t h = ptr_hash(p);
    Shard& sh = shards()[shard_of(h)];
    if ((sh.filter.load(std::memory_order_relaxed) & filter_bit(h)) == 0) {
        return;  // definitely not a sampled pointer
    }
    tls_in_hook = true;
    {
        std::lock_guard<std::mutex> g(sh.mu);
        auto it = sh.live.find(p);
        if (it != sh.live.end()) {
            it->second.stat->live_bytes.fetch_sub(
                (int64_t)it->second.size, std::memory_order_relaxed);
            it->second.stat->live_count.fetch_sub(1,
                                                  std::memory_order_relaxed);
            sh.live.erase(it);
            if (sh.live.empty()) {
                sh.filter.store(0, std::memory_order_relaxed);
            }
            g_nlive.fetch_sub(1, std::memory_order_release);
        }
    }
    tls_in_hook = false;
}

// Mirror the flags into the POD globals at this TU's static init (flags
// above construct first — same TU, in order) and on every live /flags
// mutation.
struct FlagSync {
    FlagSync() {
        g_interval.store(FLAGS_heap_profiler_sample_bytes.get(),
                         std::memory_order_relaxed);
        g_seed.store(FLAGS_heap_profiler_sample_seed.get(),
                     std::memory_order_relaxed);
        FLAGS_heap_profiler_sample_bytes.set_on_change([] {
            g_interval.store(FLAGS_heap_profiler_sample_bytes.get(),
                             std::memory_order_relaxed);
        });
        FLAGS_heap_profiler_sample_seed.set_on_change([] {
            g_seed.store(FLAGS_heap_profiler_sample_seed.get(),
                         std::memory_order_relaxed);
        });
    }
} g_flag_sync;

#ifndef __has_feature
#define __has_feature(x) 0  // gcc signals ASan via __SANITIZE_ADDRESS__
#endif
#if defined(__SANITIZE_ADDRESS__) || __has_feature(address_sanitizer)
constexpr bool kInterposed = false;
#else
constexpr bool kInterposed = true;
#endif

// Public dump/reset APIs allocate (vectors, strings) while holding the
// table/shard locks; without this guard one of those allocations could
// cross the sample threshold and re-enter RecordAlloc on the SAME
// non-recursive mutex. Sampling is suspended for the calling thread.
struct HookGuard {
    bool prev;
    HookGuard() : prev(tls_in_hook) { tls_in_hook = true; }
    ~HookGuard() { tls_in_hook = prev; }
};

struct Row {
    StackKey key;
    int64_t bytes;
    int64_t count;
};

std::vector<Row> SnapshotRows(bool growth) {
    std::vector<Row> rows;
    StackTable* st = stack_table();
    std::lock_guard<std::mutex> g(st->mu);
    rows.reserve(st->stacks.size() + 1);
    auto push = [&](const StackKey& key, const StackStat& s) {
        const int64_t b = growth
                              ? s.growth_bytes.load(std::memory_order_relaxed)
                              : s.live_bytes.load(std::memory_order_relaxed);
        const int64_t c = growth
                              ? s.growth_count.load(std::memory_order_relaxed)
                              : s.live_count.load(std::memory_order_relaxed);
        if (b > 0 || c > 0) rows.push_back(Row{key, b, c});
    };
    for (const auto& kv : st->stacks) push(kv.first, kv.second);
    StackKey zero;
    memset(&zero, 0, sizeof(zero));
    push(zero, st->overflow);
    std::sort(rows.begin(), rows.end(), [](const Row& a, const Row& b) {
        return a.bytes > b.bytes;
    });
    return rows;
}

void AppendMaps(std::string* out) {
    out->append("--- maps ---\n");
    FILE* maps = fopen("/proc/self/maps", "r");
    if (maps != nullptr) {
        char buf[4096];
        size_t nr;
        while ((nr = fread(buf, 1, sizeof(buf), maps)) > 0) {
            out->append(buf, nr);
        }
        fclose(maps);
    }
}

}  // namespace
}  // namespace heap_prof

bool HeapProfilerActive() {
    return heap_prof::kInterposed &&
           heap_prof::g_interval.load(std::memory_order_relaxed) > 0;
}

HeapProfilerStats GetHeapProfilerStats() {
    heap_prof::HookGuard guard;
    HeapProfilerStats out;
    heap_prof::StackTable* st = heap_prof::stack_table();
    std::lock_guard<std::mutex> g(st->mu);
    auto fold = [&](const heap_prof::StackStat& s) {
        out.live_bytes += s.live_bytes.load(std::memory_order_relaxed);
        out.live_count += s.live_count.load(std::memory_order_relaxed);
        out.growth_bytes += s.growth_bytes.load(std::memory_order_relaxed);
        out.growth_count += s.growth_count.load(std::memory_order_relaxed);
    };
    for (const auto& kv : st->stacks) fold(kv.second);
    fold(st->overflow);
    out.stacks = (int64_t)st->stacks.size();
    return out;
}

std::string HeapProfileRaw(bool growth) {
    heap_prof::HookGuard guard;
    const std::vector<heap_prof::Row> rows = heap_prof::SnapshotRows(growth);
    int64_t total = 0;
    for (const auto& r : rows) total += r.bytes;
    std::string out;
    char line[512];
    snprintf(line, sizeof(line),
             "%s profile: %zu stacks, %lld sampled %s bytes "
             "(interval %lld, deterministic countdown)\n",
             growth ? "growth" : "heap", rows.size(), (long long)total,
             growth ? "allocated" : "live",
             (long long)heap_prof::g_interval.load(std::memory_order_relaxed));
    out += line;
    for (const auto& r : rows) {
        snprintf(line, sizeof(line), "%lld %lld @", (long long)r.bytes,
                 (long long)r.count);
        out += line;
        for (int d = 0; d < heap_prof::kDepth && r.key.pc[d] != 0; ++d) {
            snprintf(line, sizeof(line), " %lx", (unsigned long)r.key.pc[d]);
            out += line;
        }
        if (r.key.pc[0] == 0) out += " 0";  // the overflow bucket
        out += "\n";
    }
    heap_prof::AppendMaps(&out);
    return out;
}

std::string HeapProfileSymbolized(bool growth, int top_n) {
    heap_prof::HookGuard guard;
    std::vector<heap_prof::Row> rows = heap_prof::SnapshotRows(growth);
    int64_t total = 0, total_count = 0;
    for (const auto& r : rows) {
        total += r.bytes;
        total_count += r.count;
    }
    std::string out;
    char line[512];
    snprintf(line, sizeof(line),
             "%s profile: %zu stacks, %lld sampled %s bytes in %lld "
             "allocations (sample interval %lld bytes)\n",
             growth ? "growth" : "heap", rows.size(), (long long)total,
             growth ? "allocated" : "live", (long long)total_count,
             (long long)heap_prof::g_interval.load(std::memory_order_relaxed));
    out += line;
    if (!heap_prof::kInterposed) {
        out += "(allocator interposition compiled out under ASan)\n";
        return out;
    }
    if (rows.empty()) {
        out += growth ? "(no sampled allocations since reset)\n"
                      : "(no sampled allocations live)\n";
        return out;
    }
    out += "\n       bytes  allocs  stack (leaf first)\n";
    if ((int)rows.size() > top_n) rows.resize((size_t)top_n);
    for (const auto& r : rows) {
        if (r.key.pc[0] == 0) {
            snprintf(line, sizeof(line), "%12lld %7lld  [stack-table overflow]\n",
                     (long long)r.bytes, (long long)r.count);
            out += line;
            continue;
        }
        snprintf(line, sizeof(line), "%12lld %7lld  %s\n", (long long)r.bytes,
                 (long long)r.count, SymbolizePc(r.key.pc[0]).c_str());
        out += line;
        for (int d = 1; d < heap_prof::kDepth && r.key.pc[d] != 0; ++d) {
            snprintf(line, sizeof(line), "%12s %7s  %s\n", "", "",
                     SymbolizePc(r.key.pc[d]).c_str());
            out += line;
        }
    }
    return out;
}

void ResetHeapGrowth() {
    heap_prof::HookGuard guard;
    heap_prof::StackTable* st = heap_prof::stack_table();
    std::lock_guard<std::mutex> g(st->mu);
    for (auto& kv : st->stacks) {
        kv.second.growth_bytes.store(0, std::memory_order_relaxed);
        kv.second.growth_count.store(0, std::memory_order_relaxed);
    }
    st->overflow.growth_bytes.store(0, std::memory_order_relaxed);
    st->overflow.growth_count.store(0, std::memory_order_relaxed);
}

void ResetHeapProfilerForTest() {
    using namespace heap_prof;
    {
        HookGuard guard;
        // Shards first (drop live records), then ZERO the stack stats in
        // place. The map nodes are never freed: a concurrently-sampling
        // thread may hold a StackStat* it resolved under st->mu before we
        // got here, so clear()ing the map would be a use-after-free. Nodes
        // are address-stable and bounded by kMaxStacks; zeroed rows are
        // filtered out of every dump (b > 0 || c > 0), so the views come
        // back empty all the same.
        Shard* sh = shards();
        for (int i = 0; i < kShards; ++i) {
            std::lock_guard<std::mutex> g(sh[i].mu);
            sh[i].live.clear();
            sh[i].filter.store(0, std::memory_order_relaxed);
        }
        g_nlive.store(0, std::memory_order_release);
        StackTable* st = stack_table();
        std::lock_guard<std::mutex> g(st->mu);
        auto zero = [](StackStat& s) {
            s.live_bytes.store(0, std::memory_order_relaxed);
            s.live_count.store(0, std::memory_order_relaxed);
            s.growth_bytes.store(0, std::memory_order_relaxed);
            s.growth_count.store(0, std::memory_order_relaxed);
        };
        for (auto& kv : st->stacks) zero(kv.second);
        zero(st->overflow);
    }
    tls_countdown = -1;
}

}  // namespace tpurpc

// ---------------- allocator interposition ----------------
// Global operator new/delete replacements, exported from the framework
// shared library and therefore interposed for every C++ allocation in
// the process (the reference relies on tcmalloc linkage for the same
// effect). Compiled out under ASan: its runtime owns these symbols and
// its allocator must not be half-bypassed.

#if !defined(__SANITIZE_ADDRESS__) && !__has_feature(address_sanitizer)

namespace {

__attribute__((always_inline)) inline void* tpurpc_alloc(size_t size) {
    void* p = malloc(size != 0 ? size : 1);
    tpurpc::heap_prof::MaybeSample(p, size);
    return p;
}

__attribute__((always_inline)) inline void* tpurpc_alloc_aligned(
    size_t size, size_t align) {
    if (align < sizeof(void*)) align = sizeof(void*);
    void* p = nullptr;
    if (posix_memalign(&p, align, size != 0 ? size : 1) != 0) return nullptr;
    tpurpc::heap_prof::MaybeSample(p, size);
    return p;
}

inline void tpurpc_free(void* p) {
    tpurpc::heap_prof::MaybeUnsample(p);
    free(p);
}

// Throwing operator new must run the std::new_handler loop ([new.delete
// .single]p4): give an installed handler the chance to release memory
// and retry; only throw once no handler is left.
template <typename Alloc>
__attribute__((always_inline)) inline void* alloc_with_handler(
    Alloc alloc) {
    for (;;) {
        void* p = alloc();
        if (p != nullptr) return p;
        std::new_handler h = std::get_new_handler();
        if (h == nullptr) throw std::bad_alloc();
        h();
    }
}

}  // namespace

void* operator new(size_t size) {
    return alloc_with_handler([size] { return tpurpc_alloc(size); });
}
void* operator new[](size_t size) {
    return alloc_with_handler([size] { return tpurpc_alloc(size); });
}
void* operator new(size_t size, const std::nothrow_t&) noexcept {
    return tpurpc_alloc(size);
}
void* operator new[](size_t size, const std::nothrow_t&) noexcept {
    return tpurpc_alloc(size);
}
void* operator new(size_t size, std::align_val_t al) {
    return alloc_with_handler(
        [size, al] { return tpurpc_alloc_aligned(size, (size_t)al); });
}
void* operator new[](size_t size, std::align_val_t al) {
    return alloc_with_handler(
        [size, al] { return tpurpc_alloc_aligned(size, (size_t)al); });
}
void* operator new(size_t size, std::align_val_t al,
                   const std::nothrow_t&) noexcept {
    return tpurpc_alloc_aligned(size, (size_t)al);
}
void* operator new[](size_t size, std::align_val_t al,
                     const std::nothrow_t&) noexcept {
    return tpurpc_alloc_aligned(size, (size_t)al);
}

void operator delete(void* p) noexcept { tpurpc_free(p); }
void operator delete[](void* p) noexcept { tpurpc_free(p); }
void operator delete(void* p, size_t) noexcept { tpurpc_free(p); }
void operator delete[](void* p, size_t) noexcept { tpurpc_free(p); }
void operator delete(void* p, const std::nothrow_t&) noexcept {
    tpurpc_free(p);
}
void operator delete[](void* p, const std::nothrow_t&) noexcept {
    tpurpc_free(p);
}
void operator delete(void* p, std::align_val_t) noexcept { tpurpc_free(p); }
void operator delete[](void* p, std::align_val_t) noexcept {
    tpurpc_free(p);
}
void operator delete(void* p, size_t, std::align_val_t) noexcept {
    tpurpc_free(p);
}
void operator delete[](void* p, size_t, std::align_val_t) noexcept {
    tpurpc_free(p);
}

#endif  // !ASan
