#include "tbase/flight_recorder.h"

#include <errno.h>
#include <fcntl.h>
#include <signal.h>
#include <string.h>
#include <sys/prctl.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <cstdio>

#include "tbase/flags.h"
#include "tbase/time.h"
#include "tvar/reducer.h"

// Always-on by default: the whole point of a flight recorder is that it is
// already running when the crash happens. -flight_recorder_enabled=0 exists
// for the overhead bench (bench.py blackbox_scrape) and A/B debugging.
DEFINE_bool(flight_recorder_enabled, true,
            "Record flight events into per-thread rings");
DEFINE_int64(flight_recorder_ring, 4096,
             "Events per thread ring (rounded up to a power of two; applies "
             "to rings registered after the change)");
DEFINE_string(flight_blackbox_path, "",
              "If set, fatal signals (and unclean tool exits) dump all "
              "flight rings to this file");

namespace tpurpc {
namespace flight {

const char* const kKindNames[] = {
    "NONE",
    "RPC_ISSUE",
    "RPC_DISPATCH",
    "RPC_HANDLER_IN",
    "RPC_HANDLER_OUT",
    "RPC_WRITE",
    "RPC_RESP_RECV",
    "VERB_POST",
    "VERB_WIRE",
    "VERB_COMPLETE",
    "VERB_REAP",
    "LEASE_PIN",
    "LEASE_ARM",
    "LEASE_RELEASE",
    "LEASE_EXPIRE",
    "LEASE_PEER_DEATH",
    "STREAM_CHUNK",
    "STREAM_CREDIT_STALL",
    "STREAM_RESUME",
    "COLL_STEP",
    "COLL_REFORM",
    "SCHED_INLINE",
    "SCHED_PARK",
    "CHAOS_INJECT",
    "OUTLIER_EJECT",
    "OUTLIER_REINSTATE",
};
static_assert(sizeof(kKindNames) / sizeof(kKindNames[0]) == kKindCount,
              "kKindNames must cover every EventKind");

namespace internal {

std::atomic<bool> g_on{true};
std::atomic<int> g_nrings{0};
ThreadRing* g_rings[kMaxRings] = {};

}  // namespace internal

namespace {

using internal::Event;
using internal::g_nrings;
using internal::g_on;
using internal::g_rings;
using internal::kMaxRings;
using internal::ThreadRing;

// Events recorded on threads that could not get a ring slot (registry full).
std::atomic<uint64_t> g_lost{0};
std::atomic<uint64_t> g_dump_count{0};

// Crash-handler state. The path lives in a fixed buffer (no std::string in
// a signal handler) and is refreshed by the flag's on_change hook.
char g_crash_path[256] = {0};
std::atomic<bool> g_handler_installed{false};
std::atomic<bool> g_dumping{false};

char g_node_name[64] = {0};

// Clock anchors captured when the first ring registers: a (wall, mono, tsc)
// triple lets the merge tool convert any ring's tsc to this node's wall
// clock, and the envelope technique then aligns nodes to each other.
struct Anchors {
    int64_t wall_us;
    int64_t mono_us;
    uint64_t tsc;
    double tpu;
};
Anchors g_anchors = {0, 0, 0, 0.0};
std::atomic<bool> g_anchored{false};

void CaptureAnchorsOnce() {
    bool expected = false;
    if (!g_anchored.compare_exchange_strong(expected, true)) return;
    g_anchors.wall_us = gettimeofday_us();
    g_anchors.mono_us = monotonic_time_us();
    g_anchors.tsc = cpuwide_ticks();
    g_anchors.tpu = ticks_per_us();
}

uint32_t RoundPow2(int64_t v) {
    if (v < 64) v = 64;
    if (v > (1 << 20)) v = 1 << 20;
    uint32_t cap = 64;
    while ((int64_t)cap < v) cap <<= 1;
    return cap;
}

thread_local ThreadRing* t_ring = nullptr;
thread_local bool t_lost = false;

ThreadRing* RegisterRing() {
    int idx = g_nrings.fetch_add(1, std::memory_order_relaxed);
    if (idx >= kMaxRings) {
        // Registry full: keep the counter honest for later arrivals but do
        // not let it run away.
        g_nrings.store(kMaxRings, std::memory_order_relaxed);
        t_lost = true;
        return nullptr;
    }
    CaptureAnchorsOnce();
    uint32_t cap = RoundPow2(FLAGS_flight_recorder_ring.get());
    ThreadRing* r = new ThreadRing();
    r->slots = new Event[cap]();
    r->cap = cap;
    r->tid = (uint32_t)syscall(SYS_gettid);
    memset(r->name, 0, sizeof(r->name));
    prctl(PR_GET_NAME, (unsigned long)r->name, 0, 0, 0);
    r->name[sizeof(r->name) - 1] = '\0';
    r->next.store(0, std::memory_order_relaxed);
    // Publish after the ring is fully initialized: dumpers scan g_rings.
    __atomic_store_n(&g_rings[idx], r, __ATOMIC_RELEASE);
    return r;
}

// Binary dump format (consumed by tools/blackbox_merge.py — versioned).
struct FileHeader {
    char magic[8];  // "TFRBOX1\0"
    uint32_t version;
    uint32_t pid;
    int64_t wall_us;     // anchors captured at recorder init
    int64_t mono_us;
    uint64_t tsc;
    double ticks_per_us;
    int64_t dump_mono_us;  // re-captured at dump time (tsc drift check)
    uint64_t dump_tsc;
    uint32_t nrings;
    uint32_t reserved;
    char node[64];
};

struct RingHeader {
    char magic[8];  // "TFRRING\0"
    uint32_t tid;
    uint32_t cap;
    uint64_t next;
    uint32_t nvalid;
    uint32_t reserved;
    char name[16];
};

// write(2) loop, EINTR-safe, usable from a signal handler.
bool WriteAll(int fd, const void* buf, size_t n) {
    const char* p = (const char*)buf;
    while (n > 0) {
        ssize_t w = write(fd, p, n);
        if (w < 0) {
            if (errno == EINTR) continue;
            return false;
        }
        p += w;
        n -= (size_t)w;
    }
    return true;
}

void CrashHandler(int sig, siginfo_t*, void*) {
    // One dump per process: a second fault while dumping must not recurse.
    bool expected = false;
    if (g_dumping.compare_exchange_strong(expected, true) &&
        g_crash_path[0] != '\0') {
        int fd = open(g_crash_path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
        if (fd >= 0) {
            if (DumpToFd(fd) > 0) {
                g_dump_count.fetch_add(1, std::memory_order_relaxed);
            }
            close(fd);
        }
    }
    // Restore default disposition and re-raise so the exit status still
    // reports the original signal (tests assert -SIGSEGV).
    signal(sig, SIG_DFL);
    raise(sig);
}

int64_t PassiveEvents(void*) { return (int64_t)TotalEvents(); }
int64_t PassiveDropped(void*) { return (int64_t)TotalDropped(); }
int64_t PassiveHighwater(void*) { return (int64_t)RingHighwater(); }
int64_t PassiveDumps(void*) { return (int64_t)DumpCount(); }

// Append one JSON-escaped string (ring/thread names are prctl-limited ASCII,
// but stay defensive).
void AppendJsonString(std::string* out, const char* s) {
    out->push_back('"');
    for (; *s; ++s) {
        unsigned char c = (unsigned char)*s;
        if (c == '"' || c == '\\') {
            out->push_back('\\');
            out->push_back((char)c);
        } else if (c < 0x20) {
            char buf[8];
            snprintf(buf, sizeof(buf), "\\u%04x", c);
            *out += buf;
        } else {
            out->push_back((char)c);
        }
    }
    out->push_back('"');
}

}  // namespace

void internal::RecordSlow(EventKind kind, uint64_t a, uint64_t b) {
    ThreadRing* r = t_ring;
    if (r == nullptr) {
        if (t_lost) {
            g_lost.fetch_add(1, std::memory_order_relaxed);
            return;
        }
        r = RegisterRing();
        if (r == nullptr) {
            g_lost.fetch_add(1, std::memory_order_relaxed);
            return;
        }
        t_ring = r;
    }
    uint64_t next = r->next.load(std::memory_order_relaxed);
    Event& e = r->slots[next & (r->cap - 1)];
    e.tsc = cpuwide_ticks();
    e.kind = kind;
    e.seq = (uint32_t)next;
    e.a = a;
    e.b = b;
    // Release: a dumper that reads `next` sees fully-written slots below it.
    r->next.store(next + 1, std::memory_order_release);
}

void SetNodeName(const std::string& name) {
    strncpy(g_node_name, name.c_str(), sizeof(g_node_name) - 1);
    g_node_name[sizeof(g_node_name) - 1] = '\0';
}

int64_t DumpToFd(int fd) {
    CaptureAnchorsOnce();
    FileHeader h;
    memset(&h, 0, sizeof(h));
    memcpy(h.magic, "TFRBOX1\0", 8);
    h.version = 1;
    h.pid = (uint32_t)getpid();
    h.wall_us = g_anchors.wall_us;
    h.mono_us = g_anchors.mono_us;
    h.tsc = g_anchors.tsc;
    h.ticks_per_us = g_anchors.tpu;
    h.dump_mono_us = monotonic_time_us();
    h.dump_tsc = cpuwide_ticks();
    int n = g_nrings.load(std::memory_order_acquire);
    if (n > kMaxRings) n = kMaxRings;
    int live = 0;
    for (int i = 0; i < n; ++i) {
        if (__atomic_load_n(&g_rings[i], __ATOMIC_ACQUIRE) != nullptr) ++live;
    }
    h.nrings = (uint32_t)live;
    memcpy(h.node, g_node_name, sizeof(h.node));
    int64_t total = 0;
    if (!WriteAll(fd, &h, sizeof(h))) return -1;
    total += (int64_t)sizeof(h);
    for (int i = 0; i < n; ++i) {
        ThreadRing* r = __atomic_load_n(&g_rings[i], __ATOMIC_ACQUIRE);
        if (r == nullptr) continue;
        RingHeader rh;
        memset(&rh, 0, sizeof(rh));
        memcpy(rh.magic, "TFRRING\0", 8);
        rh.tid = r->tid;
        rh.cap = r->cap;
        rh.next = r->next.load(std::memory_order_acquire);
        uint64_t nvalid = rh.next < r->cap ? rh.next : r->cap;
        rh.nvalid = (uint32_t)nvalid;
        memcpy(rh.name, r->name, sizeof(rh.name));
        if (!WriteAll(fd, &rh, sizeof(rh))) return -1;
        // Raw slot order: the merger orders by each event's seq field and
        // drops anything outside [next-cap, next) (torn or stale slots).
        if (nvalid > 0 &&
            !WriteAll(fd, r->slots, nvalid * sizeof(Event))) {
            return -1;
        }
        total += (int64_t)(sizeof(rh) + nvalid * sizeof(Event));
    }
    return total;
}

bool DumpToFile(const std::string& path) {
    int fd = open(path.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (fd < 0) return false;
    int64_t n = DumpToFd(fd);
    close(fd);
    if (n <= 0) return false;
    g_dump_count.fetch_add(1, std::memory_order_relaxed);
    return true;
}

void DumpJson(std::string* out) {
    CaptureAnchorsOnce();
    char buf[256];
    *out += "{\"node\":";
    AppendJsonString(out, g_node_name);
    snprintf(buf, sizeof(buf),
             ",\"pid\":%u,\"wall_us\":%lld,\"mono_us\":%lld,\"tsc\":%llu,"
             "\"ticks_per_us\":%.6f,\"dump_mono_us\":%lld,\"dump_tsc\":%llu,"
             "\"dropped\":%llu,\"rings\":[",
             (unsigned)getpid(), (long long)g_anchors.wall_us,
             (long long)g_anchors.mono_us, (unsigned long long)g_anchors.tsc,
             g_anchors.tpu, (long long)monotonic_time_us(),
             (unsigned long long)cpuwide_ticks(),
             (unsigned long long)TotalDropped());
    *out += buf;
    int n = g_nrings.load(std::memory_order_acquire);
    if (n > kMaxRings) n = kMaxRings;
    bool first_ring = true;
    for (int i = 0; i < n; ++i) {
        ThreadRing* r = __atomic_load_n(&g_rings[i], __ATOMIC_ACQUIRE);
        if (r == nullptr) continue;
        if (!first_ring) out->push_back(',');
        first_ring = false;
        uint64_t next = r->next.load(std::memory_order_acquire);
        uint64_t nvalid = next < r->cap ? next : r->cap;
        snprintf(buf, sizeof(buf), "{\"tid\":%u,\"cap\":%u,\"next\":%llu,",
                 r->tid, r->cap, (unsigned long long)next);
        *out += buf;
        *out += "\"name\":";
        AppendJsonString(out, r->name);
        *out += ",\"events\":[";
        // Oldest-first: walk [next-nvalid, next). The owner may keep
        // recording while we read — drop events whose seq no longer matches
        // their slot (overwritten under us).
        bool first_ev = true;
        for (uint64_t s = next - nvalid; s < next; ++s) {
            const Event& e = r->slots[s & (r->cap - 1)];
            if (e.seq != (uint32_t)s) continue;
            uint32_t kind = e.kind < kKindCount ? e.kind : 0;
            if (!first_ev) out->push_back(',');
            first_ev = false;
            snprintf(buf, sizeof(buf),
                     "{\"tsc\":%llu,\"seq\":%llu,\"k\":%u,\"kind\":\"%s\","
                     "\"a\":%llu,\"b\":%llu}",
                     (unsigned long long)e.tsc, (unsigned long long)s, e.kind,
                     kKindNames[kind], (unsigned long long)e.a,
                     (unsigned long long)e.b);
            *out += buf;
        }
        *out += "]}";
    }
    *out += "]}";
}

void DumpText(std::string* out) {
    CaptureAnchorsOnce();
    char buf[256];
    snprintf(buf, sizeof(buf),
             "flight recorder: node=%s pid=%u enabled=%d events=%llu "
             "dropped=%llu dumps=%llu\n",
             g_node_name[0] ? g_node_name : "?", (unsigned)getpid(),
             (int)g_on.load(std::memory_order_relaxed),
             (unsigned long long)TotalEvents(),
             (unsigned long long)TotalDropped(),
             (unsigned long long)DumpCount());
    *out += buf;
    int n = g_nrings.load(std::memory_order_acquire);
    if (n > kMaxRings) n = kMaxRings;
    const double tpu = g_anchors.tpu > 0 ? g_anchors.tpu : 1.0;
    for (int i = 0; i < n; ++i) {
        ThreadRing* r = __atomic_load_n(&g_rings[i], __ATOMIC_ACQUIRE);
        if (r == nullptr) continue;
        uint64_t next = r->next.load(std::memory_order_acquire);
        uint64_t nvalid = next < r->cap ? next : r->cap;
        snprintf(buf, sizeof(buf), "\n[ring %d] tid=%u name=%s events=%llu\n",
                 i, r->tid, r->name, (unsigned long long)next);
        *out += buf;
        // Show the newest 32 events per ring: the portal page is a glance
        // surface; full history goes through ?format=json or the dump file.
        uint64_t shown = nvalid < 32 ? nvalid : 32;
        for (uint64_t s = next - shown; s < next; ++s) {
            const Event& e = r->slots[s & (r->cap - 1)];
            if (e.seq != (uint32_t)s) continue;
            uint32_t kind = e.kind < kKindCount ? e.kind : 0;
            double rel_us =
                g_anchors.tsc <= e.tsc
                    ? (double)(e.tsc - g_anchors.tsc) / tpu
                    : -(double)(g_anchors.tsc - e.tsc) / tpu;
            snprintf(buf, sizeof(buf),
                     "  +%-12.1f %-20s a=%-20llu b=%llu\n", rel_us,
                     kKindNames[kind], (unsigned long long)e.a,
                     (unsigned long long)e.b);
            *out += buf;
        }
    }
}

void InstallCrashHandler(const std::string& path) {
    if (!path.empty()) {
        // Route through the flag so /flags shows the active path and the
        // on_change hook keeps g_crash_path in sync.
        FLAGS_flight_blackbox_path.set(path);
    }
    bool expected = false;
    if (!g_handler_installed.compare_exchange_strong(expected, true)) return;
    struct sigaction sa;
    memset(&sa, 0, sizeof(sa));
    sa.sa_sigaction = CrashHandler;
    sa.sa_flags = SA_SIGINFO;
    sigemptyset(&sa.sa_mask);
    const int sigs[] = {SIGSEGV, SIGABRT, SIGBUS, SIGFPE, SIGILL};
    for (int sig : sigs) {
        sigaction(sig, &sa, nullptr);
    }
}

bool DumpToConfiguredPath() {
    if (g_crash_path[0] == '\0') return false;
    return DumpToFile(g_crash_path);
}

uint64_t TotalEvents() {
    uint64_t total = 0;
    int n = g_nrings.load(std::memory_order_acquire);
    if (n > kMaxRings) n = kMaxRings;
    for (int i = 0; i < n; ++i) {
        ThreadRing* r = __atomic_load_n(&g_rings[i], __ATOMIC_ACQUIRE);
        if (r != nullptr) total += r->next.load(std::memory_order_relaxed);
    }
    return total;
}

uint64_t TotalDropped() {
    uint64_t dropped = g_lost.load(std::memory_order_relaxed);
    int n = g_nrings.load(std::memory_order_acquire);
    if (n > kMaxRings) n = kMaxRings;
    for (int i = 0; i < n; ++i) {
        ThreadRing* r = __atomic_load_n(&g_rings[i], __ATOMIC_ACQUIRE);
        if (r == nullptr) continue;
        uint64_t next = r->next.load(std::memory_order_relaxed);
        if (next > r->cap) dropped += next - r->cap;
    }
    return dropped;
}

uint64_t RingHighwater() {
    uint64_t hw = 0;
    int n = g_nrings.load(std::memory_order_acquire);
    if (n > kMaxRings) n = kMaxRings;
    for (int i = 0; i < n; ++i) {
        ThreadRing* r = __atomic_load_n(&g_rings[i], __ATOMIC_ACQUIRE);
        if (r == nullptr) continue;
        uint64_t next = r->next.load(std::memory_order_relaxed);
        uint64_t valid = next < r->cap ? next : r->cap;
        if (valid > hw) hw = valid;
    }
    return hw;
}

uint64_t DumpCount() { return g_dump_count.load(std::memory_order_relaxed); }

void ExposeVars() {
    static std::atomic<bool> done{false};
    bool expected = false;
    if (!done.compare_exchange_strong(expected, true)) return;
    static PassiveStatus<int64_t> events(PassiveEvents, nullptr);
    static PassiveStatus<int64_t> dropped(PassiveDropped, nullptr);
    static PassiveStatus<int64_t> highwater(PassiveHighwater, nullptr);
    static PassiveStatus<int64_t> dumps(PassiveDumps, nullptr);
    events.expose("rpc_blackbox_events");
    dropped.expose("rpc_blackbox_dropped");
    highwater.expose("rpc_blackbox_ring_highwater");
    dumps.expose("rpc_flight_dump_count");
}

namespace {

// Keep g_on and g_crash_path in lockstep with their flags, including live
// mutation through the /flags portal. Runs at static init in this TU, after
// the flag objects above are constructed.
struct FlagHooks {
    FlagHooks() {
        g_on.store(FLAGS_flight_recorder_enabled.get(),
                   std::memory_order_relaxed);
        FLAGS_flight_recorder_enabled.set_on_change([] {
            g_on.store(FLAGS_flight_recorder_enabled.get(),
                       std::memory_order_relaxed);
        });
        FLAGS_flight_blackbox_path.set_on_change([] {
            std::string p = FLAGS_flight_blackbox_path.get();
            strncpy(g_crash_path, p.c_str(), sizeof(g_crash_path) - 1);
            g_crash_path[sizeof(g_crash_path) - 1] = '\0';
        });
    }
};
FlagHooks g_flag_hooks;

}  // namespace

}  // namespace flight
}  // namespace tpurpc
