#include "tbase/symbolize.h"

#include <cxxabi.h>
#include <dlfcn.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace tpurpc {

std::string SymbolizePc(uintptr_t pc) {
    // The sampled PC is the RETURN address side for frame entries; keep
    // it as-is (leaf PCs are exact, call sites land inside the caller).
    Dl_info info;
    if (dladdr((void*)pc, &info) != 0) {
        if (info.dli_sname != nullptr) {
            int status = 0;
            char* demangled = abi::__cxa_demangle(info.dli_sname, nullptr,
                                                  nullptr, &status);
            std::string out = status == 0 && demangled != nullptr
                                  ? demangled
                                  : info.dli_sname;
            free(demangled);
            return out;
        }
        if (info.dli_fname != nullptr) {
            const char* base = strrchr(info.dli_fname, '/');
            char buf[256];
            snprintf(buf, sizeof(buf), "%s+0x%lx",
                     base != nullptr ? base + 1 : info.dli_fname,
                     (unsigned long)(pc - (uintptr_t)info.dli_fbase));
            return buf;
        }
    }
    char buf[32];
    snprintf(buf, sizeof(buf), "0x%lx", (unsigned long)pc);
    return buf;
}

}  // namespace tpurpc
