// In-process pthread stack dumps for the /threads portal page.
//
// Reference parity: src/brpc/builtin/threads_service.cpp shells out to
// pstack; this image has no debugger, so we self-inspect: every task in
// /proc/self/task gets SIGURG'd, the handler walks ITS OWN frame-pointer
// chain (the tree builds with -fno-omit-frame-pointer) into a per-thread
// slot, and the collector symbolizes the PCs (tbase/symbolize.h).
#pragma once

#include <cstddef>
#include <string>

namespace tpurpc {

// Symbolized stacks of every thread in the process. Bounded wait;
// threads that don't respond (blocked in uninterruptible syscalls)
// report as such.
std::string DumpThreadStacks(size_t max_frames = 24);

}  // namespace tpurpc
