// DoublyBufferedData: RCU-like read-mostly store. Readers take a
// thread-local mutex (uncontended in steady state = near-free); the writer
// modifies the background copy, flips the index, then serializes with every
// reader by locking each thread-local mutex once.
//
// Modeled on reference src/butil/containers/doubly_buffered_data.h:39-68.
// Backs load-balancer server lists (read on every RPC, written on naming
// updates).
#pragma once

#include <atomic>
#include <cstdint>
#include <memory>
#include <mutex>
#include <vector>

namespace tpurpc {

template <typename T>
class DoublyBufferedData {
    struct Wrapper;

public:
    class ScopedPtr {
    public:
        ScopedPtr() : data_(nullptr), w_(nullptr) {}
        ~ScopedPtr() {
            if (w_) w_->mu.unlock();
        }
        ScopedPtr(const ScopedPtr&) = delete;
        ScopedPtr& operator=(const ScopedPtr&) = delete;
        const T* get() const { return data_; }
        const T& operator*() const { return *data_; }
        const T* operator->() const { return data_; }

    private:
        friend class DoublyBufferedData;
        const T* data_;
        Wrapper* w_;
    };

    DoublyBufferedData() : index_(0) {}

    // Read access; holds the thread-local lock for the scope of *ptr.
    int Read(ScopedPtr* ptr) {
        Wrapper* w = tls_wrapper();
        w->mu.lock();
        ptr->w_ = w;
        ptr->data_ = &data_[index_.load(std::memory_order_acquire)];
        return 0;
    }

    // Modify both copies with fn(T&) -> bool (false aborts before flip).
    template <typename Fn>
    size_t Modify(Fn&& fn) {
        std::lock_guard<std::mutex> g(modify_mu_);
        const int bg = 1 - index_.load(std::memory_order_relaxed);
        if (!fn(data_[bg])) return 0;
        index_.store(bg, std::memory_order_release);
        // Wait for readers of the old foreground: lock each reader mutex
        // once. After this loop no reader can be using the old copy.
        {
            std::lock_guard<std::mutex> wg(wrappers_mu_);
            for (auto& w : wrappers_) {
                w->mu.lock();
                w->mu.unlock();
            }
        }
        // Apply the same change to the (now background) old copy.
        fn(data_[1 - bg]);
        return 1;
    }

private:
    struct Wrapper {
        std::mutex mu;
    };

    // One wrapper per (thread, instance), keyed by a never-reused instance
    // uid rather than `this` — a destroyed instance's address can be reused
    // by a successor, and a raw-pointer key would hand the new instance a
    // dangling Wrapper from the old one's registry.
    Wrapper* tls_wrapper() {
        thread_local std::vector<std::pair<uint64_t, Wrapper*>> map;
        for (auto& p : map) {
            if (p.first == uid_) return p.second;
        }
        auto* nw = new Wrapper;
        {
            std::lock_guard<std::mutex> g(wrappers_mu_);
            wrappers_.emplace_back(nw);
        }
        map.emplace_back(uid_, nw);
        return nw;
    }

    static uint64_t next_uid() {
        static std::atomic<uint64_t> c{1};
        return c.fetch_add(1, std::memory_order_relaxed);
    }

    const uint64_t uid_ = next_uid();
    T data_[2];
    std::atomic<int> index_;
    std::mutex modify_mu_;
    std::mutex wrappers_mu_;
    std::vector<std::unique_ptr<Wrapper>> wrappers_;
};

}  // namespace tpurpc
