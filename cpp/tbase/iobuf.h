// IOBuf: zero-copy, refcounted, non-contiguous buffer — THE payload type of
// the whole framework.
//
// Modeled on the reference's butil::IOBuf (src/butil/iobuf.h:62-84): an IOBuf
// is a tiny queue of BlockRefs over refcounted 8KB Blocks; append/cut move
// pointers, not bytes. The block allocator is pluggable
// (reference src/butil/iobuf.cpp:168 `blockmem_allocate`) which is how the
// RDMA transport takes over allocation so every block lives in registered
// memory (reference src/brpc/rdma/block_pool.h) — our ICI transport uses the
// same hook (cpp/tnet/block_pool.h).
//
// Thread-safety: a Block's refcount is atomic (blocks are shared across
// IOBufs and threads); an individual IOBuf object is NOT thread-safe, same
// contract as the reference.
#pragma once

#include <sys/uio.h>

#include <atomic>
#include <cstddef>
#include <cstdint>
#include <cstring>
#include <string>

namespace tpurpc {

class IOBuf {
public:
    static constexpr size_t DEFAULT_BLOCK_SIZE = 8192;  // incl. header
    static constexpr size_t DEFAULT_PAYLOAD = DEFAULT_BLOCK_SIZE - 32;

    // Pluggable block memory allocator (reference iobuf.cpp:168). The ICI
    // block pool installs its own pair so every IOBuf block is
    // transfer-registered memory.
    static void* (*blockmem_allocate)(size_t);
    static void (*blockmem_deallocate)(void*);
    // Optional cache veto: when set and returning true for a block's
    // memory, dec_ref bypasses the TLS/global block caches and frees
    // through blockmem_deallocate directly. The registered pool installs
    // one so SHARED-region blocks return to its peer-visible freelist
    // under cross-process pressure instead of migrating into per-thread
    // caches where AllocateSharedBlock can't reach them.
    static bool (*blockmem_cache_veto)(const void*);

    // Refcounted block. Lives in memory returned by blockmem_allocate; the
    // header is placed at the front, payload follows. Each block remembers
    // the deallocator that was current at creation, so swapping the
    // allocator pair mid-run (transport init) can never free a block with
    // the wrong deallocator.
    struct Block {
        std::atomic<int32_t> nshared;
        uint32_t size;  // bytes filled; append position shared by writers
        uint32_t cap;   // payload capacity
        Block* portal_next;       // TLS cache list linkage
        void (*dealloc)(void*);   // deallocator captured at creation
        char data[0];

        void inc_ref() { nshared.fetch_add(1, std::memory_order_relaxed); }
        void dec_ref();
        bool full() const { return size >= cap; }
        uint32_t left_space() const { return cap - size; }
    };

    struct BlockRef {
        uint32_t offset;
        uint32_t length;
        Block* block;
    };

    IOBuf() { reset_small(); }
    IOBuf(const IOBuf& rhs);
    IOBuf(IOBuf&& rhs) noexcept;
    IOBuf& operator=(const IOBuf& rhs);
    IOBuf& operator=(IOBuf&& rhs) noexcept;
    ~IOBuf() { clear(); }

    size_t size() const { return nbytes_; }
    bool empty() const { return nbytes_ == 0; }
    void clear();
    void swap(IOBuf& other);

    // ---- appending (copies bytes into blocks) ----
    int append(const void* data, size_t count);
    int append(const char* cstr) { return append(cstr, strlen(cstr)); }
    int append(const std::string& s) { return append(s.data(), s.size()); }
    int push_back(char c) { return append(&c, 1); }

    // ---- appending by reference (zero-copy) ----
    void append(const IOBuf& other);
    void append(IOBuf&& other);
    // Append one BlockRef (takes one reference on ref.block).
    void append_ref(const BlockRef& ref);

    // ---- cutting (zero-copy ref moves) ----
    // Move at most n bytes from the front of *this to the back of *out.
    size_t cutn(IOBuf* out, size_t n);
    size_t cutn(void* out, size_t n);
    size_t cutn(std::string* out, size_t n);
    int cut1(char* c);
    size_t pop_front(size_t n);
    size_t pop_back(size_t n);

    // ---- reading without consuming ----
    size_t copy_to(void* buf, size_t n, size_t pos = 0) const;
    size_t copy_to(std::string* s, size_t n = (size_t)-1, size_t pos = 0) const;
    std::string to_string() const;
    // Contiguous view of the first n bytes WITHOUT consuming: returns a
    // pointer into the first block when it already holds n contiguous
    // bytes (the common case — a readv lands whole headers in one block),
    // else copies them into `aux` (caller-provided, >= n bytes) and
    // returns aux. nullptr when size() < n. The zero-cut header peek of
    // protocol fast paths (reference butil::IOBuf::fetch).
    const void* fetch(void* aux, size_t n) const;
    // First byte, or -1 when empty.
    int front_byte() const;

    // ---- scatter-gather file I/O (reference iobuf.h:163-195) ----
    // writev() refs from the front; pops what was written. Returns bytes
    // written or -1 (errno set).
    ssize_t cut_into_file_descriptor(int fd, size_t size_hint = 1024 * 1024);
    // Multiple IOBufs in one writev (the KeepWrite batching path,
    // reference socket.cpp:1920 DoWrite).
    static ssize_t cut_multiple_into_file_descriptor(int fd, IOBuf* const* pieces,
                                                     size_t count);

    // ---- zero-copy block access (for transports) ----
    size_t backing_block_num() const { return nref_(); }
    // i-th ref's readable span. Valid until the IOBuf is mutated.
    const char* backing_block_data(size_t i, size_t* len) const;
    // Pop the front BlockRef, transferring its block reference to *out
    // (the caller now owns one ref and must dec_ref it). How a transport
    // moves blocks into its send queue without touching refcounts. Returns
    // false when empty.
    bool cut_front_ref(BlockRef* out);

    // Equality by content (test convenience).
    bool equals(const std::string& s) const;

    // Create one block (exposed for IOPortal / appender).
    static Block* create_block(size_t block_size = DEFAULT_BLOCK_SIZE);
    // Thread-local block cache stats (tests).
    static size_t tls_cached_blocks();
    // Return this thread's cached blocks to their deallocators (a pool
    // allocator can then reuse them for region-constrained needs, e.g.
    // cross-process bounce buffers when the shared region ran dry).
    static void flush_tls_cache();

protected:
    friend class IOPortal;
    friend class IOBufAppender;

    // Representation: up to 2 inline refs (small view, covers most RPC
    // payloads: header + body), else a heap-allocated ring (big view) —
    // the same two-view scheme as reference iobuf.h:84.
    static constexpr uint32_t kInlineRefs = 2;

    struct BigView {
        uint32_t start;
        uint32_t count;
        uint32_t cap;
        BlockRef* refs;
    };

    bool is_small() const { return !is_big_; }
    uint32_t nref_() const { return is_big_ ? big_.count : small_count_; }
    BlockRef& ref_at(uint32_t i) {
        return is_big_ ? big_.refs[(big_.start + i) % big_.cap] : small_[i];
    }
    const BlockRef& ref_at(uint32_t i) const {
        return is_big_ ? big_.refs[(big_.start + i) % big_.cap] : small_[i];
    }
    void push_back_ref_(const BlockRef& r);  // no refcount change
    void pop_front_ref_();                   // releases ref
    void pop_back_ref_();                    // releases ref
    void reset_small() {
        is_big_ = false;
        small_count_ = 0;
        nbytes_ = 0;
    }

    union {
        BlockRef small_[kInlineRefs];
        BigView big_;
    };
    uint32_t small_count_;
    bool is_big_;
    size_t nbytes_;
};

// IOPortal: an IOBuf that can read from a file descriptor, keeping a list of
// partially-filled blocks to append into (reference iobuf.h IOPortal).
class IOPortal : public IOBuf {
public:
    IOPortal() : block_(nullptr) {}
    ~IOPortal();
    // readv() up to max_count bytes into blocks appended to *this.
    // Returns bytes read, 0 on EOF, -1 on error.
    ssize_t append_from_file_descriptor(int fd, size_t max_count = 65536);
    void return_cached_blocks();

private:
    Block* block_;  // current partially-filled block
};

// Appender with a cached write pointer (reference IOBufAppender).
class IOBufAppender {
public:
    explicit IOBufAppender(IOBuf* buf) : buf_(buf) {}
    int append(const void* data, size_t n) { return buf_->append(data, n); }
    int push_back(char c) { return buf_->push_back(c); }
    IOBuf* buf() { return buf_; }

private:
    IOBuf* buf_;
};

}  // namespace tpurpc
