#include "tbase/errno.h"

#include <cstring>

namespace tpurpc {

const char* terror(int code) {
    switch (code) {
        case TERR_EOF: return "EOF";
        case TERR_OVERCROWDED: return "The write backlog is overcrowded";
        case TERR_RPC_TIMEDOUT: return "RPC call timed out";
        case TERR_FAILED_SOCKET: return "The socket was failed";
        case TERR_NO_METHOD: return "Method not found";
        case TERR_REQUEST: return "Bad request";
        case TERR_RESPONSE: return "Bad response";
        case TERR_BACKUP_REQUEST: return "Backup request";
        case TERR_LIMIT_EXCEEDED: return "Concurrency limit exceeded";
        case TERR_CLOSE: return "Connection closed";
        case TERR_INTERNAL: return "Internal error";
        case TERR_AUTH: return "Authentication failed";
        case TERR_DRAINING: return "Server draining (planned shutdown)";
        case TERR_OVERLOAD:
            return "Overloaded, shed by priority (retry after backoff)";
        case TERR_STALE_EPOCH:
            return "Stale pool descriptor epoch (remap and retry)";
        default: return strerror(code);
    }
}

}  // namespace tpurpc
