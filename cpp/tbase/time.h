// Time utilities: wall/monotonic microsecond clocks and cpu-wide ticks.
// Modeled on reference src/butil/time.h (gettimeofday_us, cpuwide_time_*).
#pragma once

#include <cstdint>
#include <ctime>

namespace tpurpc {

inline int64_t gettimeofday_us() {
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    return ts.tv_sec * 1000000L + ts.tv_nsec / 1000;
}

inline int64_t monotonic_time_ns() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec * 1000000000L + ts.tv_nsec;
}

inline int64_t monotonic_time_us() { return monotonic_time_ns() / 1000; }
inline int64_t monotonic_time_ms() { return monotonic_time_ns() / 1000000; }

// Raw TSC: the cheapest timestamp on x86_64 (reference uses cpuwide ticks for
// hot-path latency measurements, src/butil/time.h).
inline uint64_t cpuwide_ticks() {
#if defined(__x86_64__)
    uint32_t lo, hi;
    __asm__ __volatile__("rdtsc" : "=a"(lo), "=d"(hi));
    return ((uint64_t)hi << 32) | lo;
#else
    return (uint64_t)monotonic_time_ns();
#endif
}

// Ticks-per-microsecond, calibrated once at startup.
double ticks_per_us();

inline int64_t cpuwide_time_us() {
    return (int64_t)((double)cpuwide_ticks() / ticks_per_us());
}

// Simple stopwatch.
class Timer {
public:
    Timer() : start_(0), stop_(0) {}
    void start() { start_ = monotonic_time_ns(); }
    void stop() { stop_ = monotonic_time_ns(); }
    int64_t n_elapsed() const { return stop_ - start_; }
    int64_t u_elapsed() const { return n_elapsed() / 1000; }
    int64_t m_elapsed() const { return n_elapsed() / 1000000; }

private:
    int64_t start_;
    int64_t stop_;
};

}  // namespace tpurpc
