// Framework error codes beyond the OS errno range.
// Modeled on reference src/brpc/errno.proto (EEOF/EOVERCROWDED/
// ERPCTIMEDOUT/EFAILEDSOCKET/EBACKUPREQUEST...) with the same roles.
#pragma once

namespace tpurpc {

enum RpcErrno {
    TERR_EOF = 4000,          // remote closed the connection
    TERR_OVERCROWDED = 4001,  // write backlog too large (back-pressure)
    TERR_RPC_TIMEDOUT = 4002, // RPC deadline exceeded
    TERR_FAILED_SOCKET = 4003,// the connection was failed mid-RPC
    TERR_NO_METHOD = 4004,    // service/method not found on server
    TERR_REQUEST = 4005,      // malformed request
    TERR_RESPONSE = 4006,     // malformed response
    TERR_BACKUP_REQUEST = 4007,
    TERR_LIMIT_EXCEEDED = 4008,  // concurrency limiter rejected
    TERR_CLOSE = 4009,           // connection closed by user
    TERR_INTERNAL = 4010,
    TERR_AUTH = 4011,            // authentication failed
    // The peer is draining (planned shutdown GOAWAY) and provably did
    // not process the call: retriable on another connection WITHOUT
    // consuming retry budget (re-issuing cannot amplify load on a
    // server that is going away).
    TERR_DRAINING = 4012,
    // Priority-aware overload shed (multi-tenant QoS tier): the server
    // rejected or evicted this request under overload — tenant rate
    // quota dry, fair-queue high-water crossed, or a higher-priority
    // arrival took its place. Retriable, with the server-suggested
    // backoff from the response meta (jittered client-side), and it
    // SPENDS retry budget: overload re-issues amplify load, so they are
    // never free (contrast TERR_DRAINING).
    TERR_OVERLOAD = 4013,
    // Stale zero-copy reference (pool epoch fence, ISSUE 10): a
    // one-sided PoolDescriptor was minted under a pool generation the
    // receiver's mapping no longer matches (peer remapped/restarted, or
    // the pin was reclaimed after its lease expired). Fails ONLY the
    // call — the connection and both processes stay healthy — and is
    // retriable: the re-issue (or the link re-handshake underneath it)
    // re-registers the current generation. Excluded from circuit-
    // breaker error accounting like TERR_OVERLOAD: the server fencing
    // a stale reference is the server working as designed.
    TERR_STALE_EPOCH = 4014,
};

const char* terror(int code);

}  // namespace tpurpc
