// recordio: length-prefixed, crc32c-protected records in a file — the
// storage format of rpc_dump / rpc_replay.
//
// Reference: src/butil/recordio.{h,cc} (record streams used by
// brpc/rpc_dump.cpp and tools/rpc_replay). Format per record:
//   "TREC" u32 length u32 crc32c(payload) payload[length]
// A torn tail (partial final record) or corrupt crc terminates reading
// cleanly rather than erroring mid-stream.
#pragma once

#include <cstdio>
#include <string>

#include "tbase/iobuf.h"

namespace tpurpc {

class RecordWriter {
public:
    // Appends to `path`. valid() false if the file cannot be opened.
    explicit RecordWriter(const std::string& path);
    ~RecordWriter();
    bool valid() const { return f_ != nullptr; }

    // Write one record; returns false on IO error.
    bool Write(const IOBuf& payload);
    void Flush();

private:
    FILE* f_ = nullptr;
};

class RecordReader {
public:
    explicit RecordReader(const std::string& path);
    ~RecordReader();
    bool valid() const { return f_ != nullptr; }

    // Read the next record into *out (cleared first). Returns false at
    // EOF, on a torn tail, or on a corrupt record.
    bool Read(IOBuf* out);

private:
    FILE* f_ = nullptr;
};

}  // namespace tpurpc
