// Thread-local PRNG (xoshiro256**), the fast_rand of this framework.
// Modeled on reference src/butil/fast_rand.h: cheap, non-cryptographic,
// per-thread state so there is never contention.
#pragma once

#include <cstdint>

namespace tpurpc {

// Uniform in [0, 2^64).
uint64_t fast_rand();
// Uniform in [0, range). range == 0 returns 0.
uint64_t fast_rand_less_than(uint64_t range);
// Uniform double in [0, 1).
double fast_rand_double();

}  // namespace tpurpc
