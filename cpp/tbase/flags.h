// Runtime flag registry: DEFINE_*/DECLARE_* macros with a global registry,
// string get/set (for the /flags builtin portal service), and optional
// validators.
//
// The reference uses gflags throughout with live mutation via the /flags
// builtin (reference src/brpc/builtin/flags_service.* and
// src/brpc/reloadable_flags.h). gflags is not in this image, so this is a
// native equivalent with the same capabilities: typed globals, runtime
// set-by-name with validation, enumeration for the portal.
#pragma once

#include <atomic>
#include <cstdint>
#include <functional>
#include <mutex>
#include <string>
#include <vector>

namespace tpurpc {

class FlagBase {
public:
    FlagBase(const char* name, const char* desc, const char* type)
        : name_(name), desc_(desc), type_(type) {}
    virtual ~FlagBase() = default;
    const char* name() const { return name_; }
    const char* description() const { return desc_; }
    const char* type() const { return type_; }
    virtual std::string GetString() const = 0;
    // Returns false if parsing/validation failed.
    virtual bool SetString(const std::string& value) = 0;
    // Invoked after every successful set (typed or by-string): lets a
    // subsystem re-apply derived state on live flag mutation (e.g. the
    // fault-injection plan re-parses when chaos_* flags change).
    void set_on_change(std::function<void()> cb) {
        on_change_ = std::move(cb);
    }

protected:
    void NotifyChanged() {
        if (on_change_) on_change_();
    }

private:
    const char* name_;
    const char* desc_;
    const char* type_;
    std::function<void()> on_change_;
};

// Global registry.
void RegisterFlag(FlagBase* flag);
FlagBase* FindFlag(const std::string& name);
std::vector<FlagBase*> ListFlags();
// Returns false (and leaves the flag unchanged) on parse/validation error.
bool SetFlagValue(const std::string& name, const std::string& value);

template <typename T>
class Flag : public FlagBase {
public:
    Flag(const char* name, T default_value, const char* desc, const char* type)
        : FlagBase(name, desc, type), value_(default_value) {
        RegisterFlag(this);
    }
    T get() const { return value_.load(std::memory_order_relaxed); }
    void set(T v) {
        if (!validator_ || validator_(v)) {
            value_.store(v, std::memory_order_relaxed);
            NotifyChanged();
        }
    }
    void set_validator(std::function<bool(T)> v) { validator_ = std::move(v); }
    operator T() const { return get(); }

    std::string GetString() const override;
    bool SetString(const std::string& s) override;

private:
    std::atomic<T> value_;
    std::function<bool(T)> validator_;
};

class StringFlag : public FlagBase {
public:
    StringFlag(const char* name, const char* default_value, const char* desc)
        : FlagBase(name, desc, "string"), value_(default_value) {
        RegisterFlag(this);
    }
    std::string get() const {
        std::lock_guard<std::mutex> g(mu_);
        return value_;
    }
    void set(const std::string& v) {
        {
            std::lock_guard<std::mutex> g(mu_);
            value_ = v;
        }
        NotifyChanged();  // outside mu_: the hook may read the flag
    }
    std::string GetString() const override { return get(); }
    bool SetString(const std::string& s) override {
        set(s);
        return true;
    }

private:
    mutable std::mutex mu_;
    std::string value_;
};

}  // namespace tpurpc

#define DEFINE_int32(name, default_value, desc) \
    ::tpurpc::Flag<int32_t> FLAGS_##name(#name, default_value, desc, "int32")
#define DEFINE_int64(name, default_value, desc) \
    ::tpurpc::Flag<int64_t> FLAGS_##name(#name, default_value, desc, "int64")
#define DEFINE_bool(name, default_value, desc) \
    ::tpurpc::Flag<bool> FLAGS_##name(#name, default_value, desc, "bool")
#define DEFINE_double(name, default_value, desc) \
    ::tpurpc::Flag<double> FLAGS_##name(#name, default_value, desc, "double")
#define DEFINE_string(name, default_value, desc) \
    ::tpurpc::StringFlag FLAGS_##name(#name, default_value, desc)

#define DECLARE_int32(name) extern ::tpurpc::Flag<int32_t> FLAGS_##name
#define DECLARE_int64(name) extern ::tpurpc::Flag<int64_t> FLAGS_##name
#define DECLARE_bool(name) extern ::tpurpc::Flag<bool> FLAGS_##name
#define DECLARE_double(name) extern ::tpurpc::Flag<double> FLAGS_##name
#define DECLARE_string(name) extern ::tpurpc::StringFlag FLAGS_##name
