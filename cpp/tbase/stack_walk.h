// Shared async-signal-safe frame-pointer walking for the samplers that
// interrupt arbitrary threads (cpu_profiler.cc SIGPROF, thread_stacks.cc
// SIGURG). One hardened implementation: per-arch signal-context
// accessors, process_vm_readv frame reads (a build may omit frame
// pointers anywhere — the register can hold ANYTHING, and a raw
// dereference inside a signal handler would crash the process), and a
// monotonic 1MB span bound against loops/garbage.
#pragma once

#include <signal.h>
#include <sys/uio.h>
#include <ucontext.h>
#include <unistd.h>

#include <cstddef>
#include <cstdint>

namespace tpurpc {
namespace stack_walk {

#if defined(__x86_64__)
inline uintptr_t context_pc(ucontext_t* uc) {
    return (uintptr_t)uc->uc_mcontext.gregs[REG_RIP];
}
inline uintptr_t context_fp(ucontext_t* uc) {
    return (uintptr_t)uc->uc_mcontext.gregs[REG_RBP];
}
#elif defined(__aarch64__)
inline uintptr_t context_pc(ucontext_t* uc) {
    return (uintptr_t)uc->uc_mcontext.pc;
}
inline uintptr_t context_fp(ucontext_t* uc) {
    return (uintptr_t)uc->uc_mcontext.regs[29];
}
#else
inline uintptr_t context_pc(ucontext_t*) { return 0; }
inline uintptr_t context_fp(ucontext_t*) { return 0; }
#endif

// Reads [fp, fp+16) via process_vm_readv — async-signal-safe, fails on
// unmapped addresses instead of faulting.
inline bool safe_read_frame(uintptr_t fp, uintptr_t out[2]) {
    iovec local{out, 2 * sizeof(uintptr_t)};
    iovec remote{(void*)fp, 2 * sizeof(uintptr_t)};
    return process_vm_readv(getpid(), &local, 1, &remote, 1, 0) ==
           (ssize_t)(2 * sizeof(uintptr_t));
}

// Walk the CALLING thread's own frame chain (no signal context) —
// the allocation-site capture path of the heap profiler
// (tbase/heap_profiler.cc). Same hardening as walk(): safe frame reads
// (a sampled allocation can come from foreign code built without frame
// pointers) and the monotonic 1MB span bound. `skip` drops the
// innermost frames (the profiler's own bookkeeping). noinline so the
// first captured frame is a REAL caller, not an inlining artifact.
__attribute__((noinline)) inline size_t walk_current(uintptr_t* frames,
                                                     size_t max,
                                                     size_t skip = 0) {
    if (max == 0) return 0;
    uintptr_t fp = (uintptr_t)__builtin_frame_address(0);
    size_t n = 0;
    const uintptr_t lo = fp;
    const uintptr_t hi = fp + (1u << 20);
    while (n < max && fp >= lo && fp < hi && (fp & 7) == 0 && fp != 0) {
        uintptr_t frame[2];
        if (!safe_read_frame(fp, frame)) break;
        const uintptr_t next_fp = frame[0];
        const uintptr_t ret_pc = frame[1];
        if (ret_pc == 0) break;
        if (skip > 0) {
            --skip;
        } else {
            frames[n++] = ret_pc;
        }
        if (next_fp <= fp) break;
        fp = next_fp;
    }
    return n;
}

// Walk from a signal context into frames[0..max); returns frame count.
// Fibers run on mmap'd stacks, so only monotonically-increasing frame
// pointers within a 1MB span are trusted.
inline size_t walk(ucontext_t* uc, uintptr_t* frames, size_t max) {
    if (max == 0) return 0;
    size_t n = 0;
    frames[n++] = context_pc(uc);
    uintptr_t fp = context_fp(uc);
    const uintptr_t lo = fp;
    const uintptr_t hi = fp + (1u << 20);
    while (n < max && fp >= lo && fp < hi && (fp & 7) == 0 && fp != 0) {
        uintptr_t frame[2];
        if (!safe_read_frame(fp, frame)) break;
        const uintptr_t next_fp = frame[0];
        const uintptr_t ret_pc = frame[1];
        if (ret_pc == 0) break;
        frames[n++] = ret_pc;
        if (next_fp <= fp) break;
        fp = next_fp;
    }
    return n;
}

}  // namespace stack_walk
}  // namespace tpurpc
