// Bounded lock-free MPMC ring (per-cell sequence numbers, the classic
// Vyukov construction). Replaces the mutex+deque remote queue of
// TaskControl: every non-worker fiber spawn and every cross-pool wake
// used to take one global lock (reference keeps its remote queue behind
// the group's own lock but pairs it with per-group sharding,
// src/bthread/remote_task_queue.h — one shared lock-free ring gets the
// same effect with less machinery).
//
// push/pop are wait-free in the common case (one CAS each); a full ring
// returns false so callers can fall back (TaskControl keeps a tiny
// mutexed overflow list — unbounded fiber bursts must never be dropped).
#pragma once

#include <atomic>
#include <cstddef>
#include <cstdint>
#include <memory>

namespace tpurpc {

template <typename T>
class MpmcBoundedQueue {
public:
    MpmcBoundedQueue() = default;
    MpmcBoundedQueue(const MpmcBoundedQueue&) = delete;
    MpmcBoundedQueue& operator=(const MpmcBoundedQueue&) = delete;

    // capacity must be a power of two. Not thread-safe; call before use.
    int init(size_t capacity) {
        if (capacity < 2 || (capacity & (capacity - 1)) != 0) return -1;
        cells_.reset(new Cell[capacity]);
        mask_ = capacity - 1;
        for (size_t i = 0; i < capacity; ++i) {
            cells_[i].seq.store(i, std::memory_order_relaxed);
        }
        enqueue_pos_.store(0, std::memory_order_relaxed);
        dequeue_pos_.store(0, std::memory_order_relaxed);
        return 0;
    }

    bool push(T v) {
        Cell* c;
        size_t pos = enqueue_pos_.load(std::memory_order_relaxed);
        for (;;) {
            c = &cells_[pos & mask_];
            const size_t seq = c->seq.load(std::memory_order_acquire);
            const intptr_t dif = (intptr_t)seq - (intptr_t)pos;
            if (dif == 0) {
                if (enqueue_pos_.compare_exchange_weak(
                        pos, pos + 1, std::memory_order_relaxed)) {
                    break;
                }
            } else if (dif < 0) {
                return false;  // full
            } else {
                pos = enqueue_pos_.load(std::memory_order_relaxed);
            }
        }
        c->data = v;
        c->seq.store(pos + 1, std::memory_order_release);
        return true;
    }

    bool pop(T* v) {
        Cell* c;
        size_t pos = dequeue_pos_.load(std::memory_order_relaxed);
        for (;;) {
            c = &cells_[pos & mask_];
            const size_t seq = c->seq.load(std::memory_order_acquire);
            const intptr_t dif = (intptr_t)seq - (intptr_t)(pos + 1);
            if (dif == 0) {
                if (dequeue_pos_.compare_exchange_weak(
                        pos, pos + 1, std::memory_order_relaxed)) {
                    break;
                }
            } else if (dif < 0) {
                return false;  // empty
            } else {
                pos = dequeue_pos_.load(std::memory_order_relaxed);
            }
        }
        *v = c->data;
        c->seq.store(pos + mask_ + 1, std::memory_order_release);
        return true;
    }

private:
    struct Cell {
        std::atomic<size_t> seq{0};
        T data;
    };
    static constexpr size_t kCacheLine = 64;
    std::unique_ptr<Cell[]> cells_;
    size_t mask_ = 0;
    alignas(kCacheLine) std::atomic<size_t> enqueue_pos_{0};
    alignas(kCacheLine) std::atomic<size_t> dequeue_pos_{0};
};

}  // namespace tpurpc
