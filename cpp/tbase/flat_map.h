// FlatMap: open-addressing hash map optimized for small maps (method maps,
// socket maps). Modeled on reference src/butil/containers/flat_map.h:145 —
// that one uses single-linked buckets; ours uses robin-hood-style linear
// probing which serves the same role (cache-friendly small maps) with less
// code. Iteration order is unspecified.
#pragma once

#include <cstddef>
#include <cstdint>
#include <functional>
#include <string>
#include <utility>
#include <vector>

namespace tpurpc {

// Case-insensitive string hash/eq for HTTP header maps
// (reference CaseIgnoredFlatMap).
struct CaseIgnoredHash {
    size_t operator()(const std::string& s) const {
        size_t h = 14695981039346656037ULL;
        for (char c : s) {
            h ^= (size_t)(c | 0x20);
            h *= 1099511628211ULL;
        }
        return h;
    }
};
struct CaseIgnoredEqual {
    bool operator()(const std::string& a, const std::string& b) const {
        if (a.size() != b.size()) return false;
        for (size_t i = 0; i < a.size(); ++i) {
            if ((a[i] | 0x20) != (b[i] | 0x20)) return false;
        }
        return true;
    }
};

template <typename K, typename V, typename Hash = std::hash<K>,
          typename Equal = std::equal_to<K>>
class FlatMap {
public:
    struct Slot {
        K key;
        V value;
        uint8_t state;  // 0 empty, 1 used, 2 tombstone
        Slot() : state(0) {}
    };

    FlatMap() : size_(0) {}

    V* seek(const K& key) const {
        if (slots_.empty()) return nullptr;
        size_t i = index_of(key);
        size_t probes = 0;
        while (probes < slots_.size()) {
            const Slot& s = slots_[i];
            if (s.state == 0) return nullptr;
            if (s.state == 1 && eq_(s.key, key)) {
                return const_cast<V*>(&s.value);
            }
            i = (i + 1) & mask_;
            ++probes;
        }
        return nullptr;
    }

    V& operator[](const K& key) {
        maybe_grow();
        size_t i = index_of(key);
        size_t first_tomb = (size_t)-1;
        size_t probes = 0;
        while (probes < slots_.size()) {
            Slot& s = slots_[i];
            if (s.state == 0) {
                Slot& dst = (first_tomb != (size_t)-1) ? slots_[first_tomb] : s;
                if (&dst != &s) --tombs_;
                dst.key = key;
                dst.value = V();
                dst.state = 1;
                ++size_;
                return dst.value;
            }
            if (s.state == 2 && first_tomb == (size_t)-1) first_tomb = i;
            if (s.state == 1 && eq_(s.key, key)) return s.value;
            i = (i + 1) & mask_;
            ++probes;
        }
        // Table is all used+tombstones: reuse the first tombstone (one must
        // exist — maybe_grow() bounds used+tombstones below capacity).
        if (first_tomb == (size_t)-1) abort();  // unreachable by invariant
        Slot& dst = slots_[first_tomb];
        --tombs_;
        dst.key = key;
        dst.value = V();
        dst.state = 1;
        ++size_;
        return dst.value;
    }

    bool insert(const K& key, const V& value) {
        V& v = (*this)[key];
        v = value;
        return true;
    }

    size_t erase(const K& key) {
        if (slots_.empty()) return 0;
        size_t i = index_of(key);
        size_t probes = 0;
        while (probes < slots_.size()) {
            Slot& s = slots_[i];
            if (s.state == 0) return 0;
            if (s.state == 1 && eq_(s.key, key)) {
                s.state = 2;
                s.key = K();
                s.value = V();
                --size_;
                ++tombs_;
                return 1;
            }
            i = (i + 1) & mask_;
            ++probes;
        }
        return 0;
    }

    size_t size() const { return size_; }
    bool empty() const { return size_ == 0; }
    void clear() {
        slots_.clear();
        size_ = 0;
        tombs_ = 0;
        mask_ = 0;
    }

    // for_each(fn(key, value)).
    template <typename Fn>
    void for_each(Fn&& fn) const {
        for (const Slot& s : slots_) {
            if (s.state == 1) fn(s.key, s.value);
        }
    }

private:
    size_t index_of(const K& key) const { return hash_(key) & mask_; }

    void maybe_grow() {
        if (slots_.empty()) {
            slots_.resize(16);
            mask_ = 15;
            return;
        }
        // Tombstones count against the load factor, otherwise a table with
        // erase churn fills with tombstones and probes degrade/never end.
        if ((size_ + tombs_) * 4 >= slots_.size() * 3) {  // load factor 0.75
            std::vector<Slot> old;
            old.swap(slots_);
            // Only grow if live entries justify it; otherwise rehash in
            // place to shed tombstones.
            const size_t new_size =
                (size_ * 4 >= old.size() * 2) ? old.size() * 2 : old.size();
            slots_.resize(new_size);
            mask_ = slots_.size() - 1;
            size_ = 0;
            tombs_ = 0;
            for (Slot& s : old) {
                if (s.state == 1) {
                    (*this)[s.key] = std::move(s.value);
                }
            }
        }
    }

    std::vector<Slot> slots_;
    size_t size_;
    size_t tombs_ = 0;
    size_t mask_ = 0;
    Hash hash_;
    Equal eq_;
};

template <typename V>
using CaseIgnoredFlatMap = FlatMap<std::string, V, CaseIgnoredHash, CaseIgnoredEqual>;

}  // namespace tpurpc
