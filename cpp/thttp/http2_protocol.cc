#include "thttp/http2_protocol.h"

#include "thttp/h2_frames.h"

#include <arpa/inet.h>

#include <algorithm>
#include <cctype>
#include <cstring>
#include <mutex>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "tbase/errno.h"
#include "tbase/flags.h"
#include "tbase/logging.h"
#include "tbase/time.h"
#include "tfiber/butex.h"
#include "tfiber/fiber.h"
#include "tfiber/fiber_sync.h"
#include "thttp/hpack.h"
#include "thttp/http_message.h"
#include "tnet/input_messenger.h"
#include "tnet/protocol.h"
#include "tnet/socket.h"
#include "trpc/auth.h"
#include "trpc/controller.h"
#include "trpc/json2pb.h"
#include "trpc/pb_compat.h"
#include "trpc/server.h"
#include "trpc/server_call.h"

// A window-starving client must not pin a response fiber (and its
// concurrency slot) forever; the stream's own grpc-timeout bounds the
// stall further when it is tighter.
DEFINE_int32(h2_server_stall_timeout_ms, 60000,
             "give up on a window-starved h2 response after this stall");

namespace tpurpc {

// Defined in http_protocol.cc (shared with HTTP/1): routes a non-RPC
// request through the registered handlers / json transcoding.
bool DispatchHttpRpc(Server* server, const HttpRequest& req,
                     HttpResponse* res, const EndPoint& remote_side);

using namespace h2;  // frame constants + builders (thttp/h2_frames.h)

namespace {

// Hardening caps on untrusted input (one connection must not be able to
// buffer unbounded memory; same posture as the shm link's hostile-
// descriptor checks and HPACK's kMaxHeaderBytes).
constexpr size_t kMaxBodyBytes = 64u << 20;
constexpr size_t kMaxHeaderBlock = 64u << 10;
constexpr size_t kMaxStreams = 256;

struct H2Stream {
    std::vector<HpackHeader> headers;
    IOBuf body;
    bool has_headers = false;
    bool dispatched = false;
    int64_t send_window = kDefaultWindow;
};

// Per-connection session. Frame processing runs on the input fiber (the
// protocol is in-order); response fibers touch only the window fields and
// stream erasure — both under mu.
struct H2Session {
    HpackDecoder decoder;
    std::map<uint32_t, H2Stream> streams;
    std::mutex mu;
    int64_t conn_send_window = kDefaultWindow;
    int64_t peer_initial_window = kDefaultWindow;
    void* window_butex = butex_create();
    bool goaway = false;
    uint32_t max_stream_id = 0;  // highest client stream ever opened
    // Set (under mu) when WE sent a GOAWAY while draining: streams above
    // goaway_last are never dispatched — the client provably gets no
    // response for them and fails them as retriable-elsewhere, so
    // executing them here would double-run the method.
    bool goaway_sent = false;
    uint32_t goaway_last = 0;
    uint32_t cont_stream = 0;  // nonzero: CONTINUATION expected
    uint8_t cont_flags = 0;
    std::string header_block;

    ~H2Session() { butex_destroy(window_butex); }

    void WakeWindowWaiters() {
        butex_word(window_butex)->fetch_add(1, std::memory_order_release);
        butex_wake_all(window_butex);
    }
};

void DeleteSession(void* s) { delete (H2Session*)s; }

H2Session* session_of(Socket* s) { return (H2Session*)s->conn_data(); }

// ---------------- response writing ----------------

// Write HEADERS (+optional DATA chunks with flow control) + trailers.
// Runs on a response fiber holding a socket ref; parks on the session
// window butex when the send window is exhausted. `deadline_us` (0 =
// none) bounds the stall abort further: past the stream's own deadline
// the client has given up, so parking longer only pins the fiber.
void WriteResponse(
    SocketId sid, uint32_t stream_id,
    const std::vector<std::pair<std::string, std::string>>& headers,
    const std::string& body,
    const std::vector<std::pair<std::string, std::string>>& trailers,
    int64_t deadline_us = 0) {
    SocketUniquePtr s;
    if (Socket::AddressSocket(sid, &s) != 0) return;
    H2Session* sess = session_of(s.get());
    if (sess == nullptr) return;

    std::string out;
    AppendHeadersFrames(&out,
                        trailers.empty() && body.empty()
                            ? (uint8_t)(kFlagEndHeaders | kFlagEndStream)
                            : kFlagEndHeaders,
                        stream_id, EncodeHeaderBlock(headers));
    size_t sent = 0;
    // Give up after a bounded stall and reset the stream (reference h2
    // has the same write-timeout escape); the stream's parsed
    // grpc-timeout caps it when tighter.
    int64_t stall_deadline =
        monotonic_time_us() +
        (int64_t)FLAGS_h2_server_stall_timeout_ms.get() * 1000;
    if (deadline_us > 0 && deadline_us < stall_deadline) {
        stall_deadline = deadline_us;
    }
    while (sent < body.size()) {
        // Flow control: consume min(available conn+stream window, frame
        // cap); park until WINDOW_UPDATE when exhausted.
        // Butex snapshot BEFORE the window check: an update landing
        // between check and wait changes the word, so the wait returns
        // immediately instead of losing the wakeup (checked-then-waited
        // is the classic lost-wakeup race; one miss here stalls the
        // response for the full wait timeout).
        std::atomic<int>* word = butex_word(sess->window_butex);
        const int expected = word->load(std::memory_order_acquire);
        size_t n = 0;
        bool stream_gone = false;
        {
            std::lock_guard<std::mutex> g(sess->mu);
            auto it = sess->streams.find(stream_id);
            if (it == sess->streams.end()) {
                stream_gone = true;  // peer RST mid-response
            } else {
                const int64_t avail = std::min<int64_t>(
                    sess->conn_send_window, it->second.send_window);
                n = (size_t)std::max<int64_t>(
                    0, std::min<int64_t>(
                           avail, (int64_t)std::min<size_t>(
                                      kMaxFrameSize, body.size() - sent)));
                if (n > 0) {
                    sess->conn_send_window -= (int64_t)n;
                    it->second.send_window -= (int64_t)n;
                }
            }
        }
        if (stream_gone) return;
        if (n == 0) {
            // Flush what we have, then wait for a window update.
            if (!out.empty()) {
                IOBuf buf;
                buf.append(out);
                out.clear();
                if (s->Write(&buf) != 0) return;
            }
            if (s->Failed()) return;
            if (monotonic_time_us() >= stall_deadline) {
                // Abort: RST_STREAM CANCEL, drop the stream, skip
                // trailers (the stream is dead).
                uint32_t code = htonl(8);
                IOBuf rst;
                rst.append(BuildFrame(H2_RST_STREAM, 0, stream_id,
                                      std::string((const char*)&code, 4)));
                s->Write(&rst);
                std::lock_guard<std::mutex> g(sess->mu);
                sess->streams.erase(stream_id);
                return;
            }
            // Never park past the stall deadline (a 10s wait quantum
            // would overshoot a tight per-stream deadline by seconds).
            const int64_t abst = std::min(
                monotonic_time_us() + 10 * 1000 * 1000, stall_deadline);
            butex_wait(sess->window_butex, expected, &abst);
            if (s->Failed()) return;
            continue;
        }
        AppendFrame(&out, H2_DATA, 0, stream_id, body.data() + sent, n);
        sent += n;
        if (out.size() > 256 * 1024) {
            IOBuf buf;
            buf.append(out);
            out.clear();
            if (s->Write(&buf) != 0) return;
        }
    }
    if (!trailers.empty()) {
        AppendHeadersFrames(&out,
                            (uint8_t)(kFlagEndHeaders | kFlagEndStream),
                            stream_id, EncodeHeaderBlock(trailers));
    } else if (!body.empty()) {
        out += BuildFrame(H2_DATA, kFlagEndStream, stream_id, "");
    }
    if (!out.empty()) {
        IOBuf buf;
        buf.append(out);
        s->Write(&buf);
    }
    std::lock_guard<std::mutex> g(sess->mu);
    sess->streams.erase(stream_id);
}

// ---------------- request dispatch ----------------

const std::string* FindHeader(const std::vector<HpackHeader>& hs,
                              const char* name) {
    for (const auto& h : hs) {
        if (h.name == name) return &h.value;
    }
    return nullptr;
}

// gRPC "grpc-timeout" header: ASCII digits + one unit suffix. Returns
// the timeout in microseconds, or -1 on parse error (reference
// src/brpc/grpc.cpp ParseH2Timeout).
int64_t ParseGrpcTimeoutUs(const std::string& v) {
    if (v.size() < 2 || v.size() > 9) return -1;  // spec: <= 8 digits
    int64_t num = 0;
    for (size_t i = 0; i + 1 < v.size(); ++i) {
        if (v[i] < '0' || v[i] > '9') return -1;
        num = num * 10 + (v[i] - '0');
    }
    switch (v.back()) {
        case 'H': return num * 3600 * 1000000;
        case 'M': return num * 60 * 1000000;
        case 'S': return num * 1000000;
        case 'm': return num * 1000;
        case 'u': return num;
        case 'n': return num / 1000;
        default: return -1;
    }
}

// gRPC unary call: 5-byte length-prefixed pb in, same out, grpc-status
// trailers (reference src/brpc/grpc.{h,cpp} status mapping).
struct GrpcCallCtx {
    SocketId sid;
    uint32_t stream_id;
    Server::MethodProperty* mp;
    Server::MethodCallGuard* guard;
    std::unique_ptr<google::protobuf::Message> req;
    std::unique_ptr<google::protobuf::Message> res;
    Controller cntl;
    // Multi-tenant accounting (ISSUE 8): x-tpu-tenant/x-tpu-priority
    // identity parsed at dispatch; completion reports to the QoS tier
    // (and teaches the cost model — ISSUE 15).
    QosDispatcher* qos = nullptr;
    QosDispatcher::TenantState* qos_tenant = nullptr;
    int64_t qos_start_us = 0;
    std::string qos_method;    // cost-model key
    int64_t qos_bytes = 0;     // grpc message payload bytes
};

// gRPC spec: grpc-message is percent-encoded (and h2 forbids CR/LF/NUL
// in field values) — a raw multi-line error text would be a protocol
// error that masks the application's failure detail.
std::string PercentEncodeGrpcMessage(const std::string& s) {
    std::string out;
    out.reserve(s.size());
    static const char* hex = "0123456789ABCDEF";
    for (unsigned char ch : s) {
        if (ch >= 0x20 && ch <= 0x7e && ch != '%') {
            out.push_back((char)ch);
        } else {
            out.push_back('%');
            out.push_back(hex[ch >> 4]);
            out.push_back(hex[ch & 0xf]);
        }
    }
    return out;
}

void RespondGrpcError(SocketId sid, uint32_t stream_id, int code,
                      const std::string& msg);

void* RunGrpcCall(void* arg) {
    std::unique_ptr<GrpcCallCtx> c((GrpcCallCtx*)arg);
    // One teardown for every exit path: deregister from the cancel
    // registry, destroy the cancelable id, settle admission accounting.
    const auto finish = [&](int error_code) {
        server_call::Unregister(c->sid, c->stream_id);
        c->cntl.DestroyServerCallId();
        // Per-tenant completion BEFORE Finish (which is the last legal
        // touch of Server memory). Teaches the cost model + the
        // tenant's gradient limiter.
        if (c->qos_tenant != nullptr) {
            QosDispatcher::CompletionInfo ci;
            ci.error_code = error_code;
            ci.method = &c->qos_method;
            ci.logical_bytes = c->qos_bytes;
            ci.peer = c->cntl.remote_side();
            c->qos->OnDone(c->qos_tenant,
                           monotonic_time_us() - c->qos_start_us, ci);
        }
        c->guard->Finish(error_code);
        delete c->guard;
    };
    // Expiry re-check on the handler fiber: the deadline may have passed
    // while this call waited for dispatch (grpc-status 4 =
    // DEADLINE_EXCEEDED).
    if (c->cntl.has_server_deadline() &&
        monotonic_time_us() >= c->cntl.server_deadline_us()) {
        c->mp->status->nexpired.fetch_add(1, std::memory_order_relaxed);
        server_call::CountExpired();
        RespondGrpcError(c->sid, c->stream_id, 4,
                         "deadline expired before handler dispatch");
        finish(TERR_RPC_TIMEDOUT);
        return nullptr;
    }
    struct SyncDone : google::protobuf::Closure {
        CountdownEvent ev{1};
        void Run() override { ev.signal(); }
    } done;
    {
        // Publish the server call for the handler's downstream calls
        // (deadline inheritance + cancel cascade).
        ServerCallScope scope(&c->cntl);
        c->mp->service->CallMethod(c->mp->method, &c->cntl, c->req.get(),
                                   c->res.get(), &done);
    }
    done.ev.wait();
    std::string body;
    std::vector<std::pair<std::string, std::string>> trailers;
    if (c->cntl.Failed()) {
        // grpc-status 2 (UNKNOWN) carries the application error.
        trailers = {{"grpc-status", "2"},
                    {"grpc-message",
                     PercentEncodeGrpcMessage(c->cntl.ErrorText())}};
    } else {
        std::string pb;
        c->res->SerializeToString(&pb);
        body.push_back('\0');  // uncompressed
        const uint32_t len = htonl((uint32_t)pb.size());
        body.append((const char*)&len, 4);
        body += pb;
        trailers = {{"grpc-status", "0"}};
    }
    WriteResponse(c->sid, c->stream_id,
                  {{":status", "200"},
                   {"content-type", "application/grpc"}},
                  body, trailers, c->cntl.server_deadline_us());
    finish(c->cntl.Failed() ? c->cntl.ErrorCode() : 0);
    return nullptr;
}

void RespondGrpcError(SocketId sid, uint32_t stream_id, int code,
                      const std::string& msg) {
    WriteResponse(sid, stream_id,
                  {{":status", "200"},
                   {"content-type", "application/grpc"}},
                  "",
                  {{"grpc-status", std::to_string(code)},
                   {"grpc-message", PercentEncodeGrpcMessage(msg)}});
}

// Plain h2 request -> the shared HTTP handler/json-RPC routing.
struct PlainCallCtx {
    SocketId sid;
    uint32_t stream_id;
    Server* server;
    HttpRequest req;
    EndPoint remote;
};

void* RunPlainCall(void* arg) {
    std::unique_ptr<PlainCallCtx> c((PlainCallCtx*)arg);
    HttpResponse res;
    const HttpHandler* h = c->server->FindHttpHandler(c->req.path);
    if (h != nullptr) {
        (*h)(c->server, c->req, &res);
    } else if (!DispatchHttpRpc(c->server, c->req, &res, c->remote)) {
        res.status = 404;
        res.set_content_type("text/plain");
        res.Append("404 not found: " + c->req.path + "\n");
    }
    std::vector<std::pair<std::string, std::string>> headers;
    headers.push_back({":status", std::to_string(res.status)});
    for (const auto& kv : res.headers) {
        std::string name = kv.first;
        for (char& ch : name) ch = (char)tolower((unsigned char)ch);
        if (name == "connection") continue;  // h2 forbids it
        headers.push_back({name, kv.second});
    }
    WriteResponse(c->sid, c->stream_id, headers, res.body.to_string(), {});
    return nullptr;
}

// Takes the request's headers+body by value (moved out of the stream
// entry under the session mutex): the map entry may be erased by the
// response fiber at any time after dispatch, so no H2Stream pointer may
// be used here.
void DispatchCompleteStream(Socket* s, H2Session* sess, uint32_t stream_id,
                            std::vector<HpackHeader> req_headers,
                            IOBuf req_body) {
    InputMessenger* m = (InputMessenger*)s->user();
    Server* server = m != nullptr ? (Server*)m->context : nullptr;
    const std::string* path = FindHeader(req_headers, ":path");
    const std::string* ct = FindHeader(req_headers, "content-type");
    if (server == nullptr || path == nullptr) {
        RespondGrpcError(s->id(), stream_id, 13, "no server bound");
        return;
    }
    if (ct != nullptr && ct->compare(0, 16, "application/grpc") == 0) {
        // Authentication: gRPC presents the credential per-call in the
        // `authorization` header (trpc/auth.h); mismatch is grpc-status
        // 16 UNAUTHENTICATED.
        if (server->options().auth != nullptr) {
            const std::string* authz =
                FindHeader(req_headers, "authorization");
            AuthContext actx;
            if (authz == nullptr ||
                server->options().auth->VerifyCredential(
                    *authz, s->remote_side(), &actx) != 0) {
                RespondGrpcError(s->id(), stream_id, 16,
                                 "authentication failed");
                return;
            }
        }
        // gRPC: find the pb method, admission, parse, run on a fiber.
        Server::MethodProperty* mp = server->FindMethodByHttpPath(*path);
        if (mp == nullptr) {
            RespondGrpcError(s->id(), stream_id, 12, "unimplemented");
            return;
        }
        // Server-side deadline from grpc-timeout (the h2 analog of the
        // tpu_std timeout_ms meta): shed expired-on-arrival requests
        // before admission with grpc-status 4 (DEADLINE_EXCEEDED).
        const int64_t arrival_us = monotonic_time_us();
        int64_t deadline_us = 0;
        const std::string* gt = FindHeader(req_headers, "grpc-timeout");
        if (gt != nullptr) {
            const int64_t t_us = ParseGrpcTimeoutUs(*gt);
            if (t_us == 0) {
                mp->status->nexpired.fetch_add(1,
                                               std::memory_order_relaxed);
                server_call::CountExpired();
                RespondGrpcError(s->id(), stream_id, 4,
                                 "deadline already expired on arrival");
                return;
            }
            if (t_us > 0) deadline_us = arrival_us + t_us;
        }
        // QoS identity + rate quota (ISSUE 8): the h2 spelling of the
        // tpu_std tenant/priority meta. Quota sheds answer grpc-status 8
        // (RESOURCE_EXHAUSTED) with the suggested backoff in the message
        // — the h2 analog of TERR_OVERLOAD + backoff_ms. The weighted-
        // fair dispatch queue itself is a native-protocol (tpu_std)
        // feature; h2 gets identity, quotas, and per-tenant accounting.
        QosDispatcher* qos = server->qos();
        const std::string* xt = FindHeader(req_headers, "x-tpu-tenant");
        const int priority =
            PriorityFromHeader(FindHeader(req_headers, "x-tpu-priority"));
        QosDispatcher::TenantState* tstate = nullptr;
        // Work-priced admission (ISSUE 15): the h2 door charges the
        // same per-(tenant, method) cost estimate as tpu_std.
        const std::string method_key =
            mp->method->service()->full_name() + "." + mp->method->name();
        int64_t cost_milli = kCostUnitMilli;
        if (qos->enabled()) {
            tstate = qos->Acquire(xt != nullptr ? *xt : "");
            cost_milli = qos->EstimateCostMilli(tstate, method_key);
            int64_t backoff_ms = 0;
            if (!qos->AdmitCost(tstate, arrival_us, cost_milli,
                                &backoff_ms)) {
                RespondGrpcError(s->id(), stream_id, 8,
                                 "tenant over its cost quota; retry after " +
                                     std::to_string(backoff_ms) + "ms");
                return;
            }
        }
        auto* guard = new Server::MethodCallGuard(
            server, mp, deadline_us > 0 ? deadline_us - arrival_us : -1,
            priority);
        if (guard->rejected()) {
            const bool shed = guard->shed();
            delete guard;
            if (shed) {
                server_call::CountShed();
            } else if (tstate != nullptr) {
                qos->CountShed(tstate, cost_milli);
            }
            RespondGrpcError(s->id(), stream_id, 8,
                             shed ? "remaining deadline budget below "
                                    "observed service time"
                                  : "concurrency limit");
            return;
        }
        if (req_body.size() < 5) {
            guard->Finish(TERR_REQUEST);
            delete guard;
            RespondGrpcError(s->id(), stream_id, 3, "truncated message");
            return;
        }
        char prefix[5];
        req_body.cutn(prefix, 5);
        if (prefix[0] != 0) {
            guard->Finish(TERR_REQUEST);
            delete guard;
            RespondGrpcError(s->id(), stream_id, 12,
                             "compressed grpc messages not supported");
            return;
        }
        // Fix the 5-byte framing to the body: a unary call carries
        // exactly ONE length-prefixed message (a second message or a
        // mismatched length is a framing error, not a pb parse error).
        uint32_t msg_len = 0;
        memcpy(&msg_len, prefix + 1, 4);
        msg_len = ntohl(msg_len);
        if ((size_t)msg_len != req_body.size()) {
            guard->Finish(TERR_REQUEST);
            delete guard;
            RespondGrpcError(s->id(), stream_id, 3,
                             "grpc message framing mismatch");
            return;
        }
        auto* ctx = new GrpcCallCtx;
        ctx->sid = s->id();
        ctx->stream_id = stream_id;
        ctx->mp = mp;
        ctx->guard = guard;
        ctx->req.reset(mp->service->GetRequestPrototype(mp->method).New());
        ctx->res.reset(mp->service->GetResponsePrototype(mp->method).New());
        ctx->cntl.InitServerSide(server, s->remote_side());
        ctx->cntl.set_server_deadline_us(deadline_us);
        if (xt != nullptr) ctx->cntl.set_tenant(*xt);
        ctx->cntl.set_priority(priority);
        // Sticky-session identity (ISSUE 16), h2 spelling of the tpu_std
        // meta's session field.
        const std::string* xs = FindHeader(req_headers, "x-tpu-session");
        if (xs != nullptr) ctx->cntl.set_session(*xs);
        if (!ParsePbFromIOBuf(ctx->req.get(), req_body)) {
            guard->Finish(TERR_REQUEST);
            delete guard;
            delete ctx;
            RespondGrpcError(s->id(), stream_id, 3, "bad request pb");
            return;
        }
        // Tenant accounting starts only past the LAST early-return:
        // every BeginServed must reach RunGrpcCall's finish/OnDone, or
        // the tenant's concurrency share leaks and eventually bricks it.
        if (tstate != nullptr) {
            qos->BeginServed(tstate, cost_milli);
            ctx->qos = qos;
            ctx->qos_tenant = tstate;
            ctx->qos_start_us = arrival_us;
            ctx->qos_method = method_key;
            ctx->qos_bytes = (int64_t)msg_len;
        }
        // Cancelable handle keyed by the h2 stream id: RST_STREAM and
        // connection death deliver the cancel; RunGrpcCall tears both
        // down on every exit path.
        CallId scid = INVALID_CALL_ID;
        if (id_create(&scid, &ctx->cntl,
                      &Controller::HandleServerCancelThunk) == 0) {
            ctx->cntl.set_server_call_id(scid);
            server_call::Register(s->id(), stream_id, scid);
        }
        fiber_t tid;
        FiberAttr attr = FIBER_ATTR_NORMAL;
        attr.tag = server->options().fiber_tag;
        if (fiber_start_background(&tid, &attr, RunGrpcCall, ctx) != 0) {
            RunGrpcCall(ctx);  // degrade inline
        }
        return;
    }
    // Plain h2: adapt to the HTTP/1 routing (portal + json RPC).
    auto* ctx = new PlainCallCtx;
    ctx->sid = s->id();
    ctx->stream_id = stream_id;
    ctx->server = server;
    ctx->remote = s->remote_side();
    const std::string* method = FindHeader(req_headers, ":method");
    ctx->req.method = method != nullptr ? *method : "GET";
    const size_t q = path->find('?');
    ctx->req.path = path->substr(0, q);
    if (q != std::string::npos) ctx->req.query = path->substr(q + 1);
    for (const auto& h : req_headers) {
        if (!h.name.empty() && h.name[0] != ':') {
            ctx->req.headers[h.name] = h.value;
        }
    }
    ctx->req.body = std::move(req_body);
    fiber_t tid;
    FiberAttr attr = FIBER_ATTR_NORMAL;
    attr.tag = server->options().fiber_tag;
    if (fiber_start_background(&tid, &attr, RunPlainCall, ctx) != 0) {
        RunPlainCall(ctx);
    }
    (void)sess;
}

// ---------------- frame processing (input fiber, in order) ----------------

struct H2FrameMessage : public InputMessageBase {
    uint8_t type = 0;
    uint8_t flags = 0;
    uint32_t stream_id = 0;
    IOBuf payload;
    bool is_preface = false;
};

void SendRaw(Socket* s, const std::string& bytes) {
    IOBuf buf;
    buf.append(bytes);
    s->Write(&buf);
}

ParseResult ParseH2(IOBuf* source, Socket* s, bool read_eof, const void*) {
    (void)read_eof;
    if (s == nullptr) return ParseResult::make(ParseError::TRY_OTHERS);
    H2Session* sess = session_of(s);
    if (sess == nullptr) {
        // Sniff the client preface.
        char head[kPrefaceLen];
        const size_t n =
            source->copy_to(head, std::min(source->size(), kPrefaceLen));
        if (memcmp(head, kPreface, n) != 0) {
            return ParseResult::make(ParseError::TRY_OTHERS);
        }
        if (n < kPrefaceLen) {
            return ParseResult::make(ParseError::NOT_ENOUGH_DATA);
        }
        source->pop_front(kPrefaceLen);
        auto* msg = new H2FrameMessage;
        msg->is_preface = true;
        return ParseResult::make_ok(msg);
    }
    if (source->size() < kFrameHeaderLen) {
        return ParseResult::make(ParseError::NOT_ENOUGH_DATA);
    }
    char header[kFrameHeaderLen];
    source->copy_to(header, kFrameHeaderLen);
    const uint32_t len = ((uint32_t)(uint8_t)header[0] << 16) |
                         ((uint32_t)(uint8_t)header[1] << 8) |
                         (uint32_t)(uint8_t)header[2];
    if (len > kMaxFrameSize + 255) {
        return ParseResult::make(ParseError::ERROR);  // FRAME_SIZE_ERROR
    }
    if (source->size() < kFrameHeaderLen + len) {
        return ParseResult::make(ParseError::NOT_ENOUGH_DATA);
    }
    source->pop_front(kFrameHeaderLen);
    auto* msg = new H2FrameMessage;
    msg->type = (uint8_t)header[3];
    msg->flags = (uint8_t)header[4];
    uint32_t sid;
    memcpy(&sid, header + 5, 4);
    msg->stream_id = ntohl(sid) & 0x7fffffffu;
    source->cutn(&msg->payload, len);
    return ParseResult::make_ok(msg);
}

// Strip PADDED framing in place. Malformed padding is a connection error
// (RFC 7540 §6.2): for HEADERS, dropping the block would skip its HPACK
// dynamic-table inserts and desynchronize the shared decoder.
bool StripPadding(IOBuf* frag, Socket* s) {
    uint8_t pad;
    if (frag->size() < 1) {
        s->SetFailedWithError(TERR_REQUEST);
        return false;
    }
    frag->cutn(&pad, 1);
    if ((size_t)pad > frag->size()) {
        s->SetFailedWithError(TERR_REQUEST);
        return false;
    }
    IOBuf tmp;
    frag->cutn(&tmp, frag->size() - pad);
    frag->swap(tmp);
    return true;
}

void HandleHeaderBlockDone(Socket* s, H2Session* sess, uint32_t stream_id,
                           uint8_t flags) {
    std::vector<HpackHeader> headers;
    if (!sess->decoder.Decode((const uint8_t*)sess->header_block.data(),
                              sess->header_block.size(), &headers)) {
        s->SetFailedWithError(TERR_REQUEST);  // COMPRESSION_ERROR
        return;
    }
    sess->header_block.clear();
    if (stream_id == 0 || sess->goaway) {
        return;  // stream 0 carries no requests; draining after GOAWAY
    }
    const bool complete = (flags & kFlagEndStream) != 0;
    IOBuf body;
    bool refuse = false;
    {
        std::unique_lock<std::mutex> g(sess->mu);
        if (sess->goaway_sent && stream_id > sess->goaway_last) {
            // Draining: this stream raced our GOAWAY. A peer whose write
            // beat its read of the announcement is NOT covered by its
            // own "fail ids above last-stream-id" rule (it processed the
            // GOAWAY before opening this stream id) — an explicit
            // REFUSED_STREAM tells it promptly that the stream was
            // provably not processed, instead of letting the call burn
            // its whole deadline on a drain-only (SIGUSR2) server that
            // never closes the connection.
            g.unlock();
            uint32_t code = htonl(0x7);  // REFUSED_STREAM
            SendRaw(s, BuildFrame(H2_RST_STREAM, 0, stream_id,
                                  std::string((const char*)&code, 4)));
            return;
        }
        auto it = sess->streams.find(stream_id);
        if (it != sess->streams.end() && it->second.dispatched) {
            // Duplicate HEADERS / request trailers after END_STREAM:
            // already dispatched — dispatching again would double-run
            // the method and interleave two responses on one stream.
            return;
        }
        if (it == sess->streams.end() && stream_id <= sess->max_stream_id) {
            // Reuse of a closed (erased) stream id: connection error per
            // RFC 7540 §5.1.1 — the `dispatched` guard only lives as long
            // as the entry; a hostile peer must not reopen the id after
            // the response fiber erased it.
            s->SetFailedWithError(TERR_REQUEST);
            return;
        }
        if (it == sess->streams.end() &&
            sess->streams.size() >= kMaxStreams) {
            refuse = true;
        } else {
            if (it == sess->streams.end()) {
                // New stream: necessarily > max_stream_id (the reuse
                // guard above failed the connection otherwise).
                sess->max_stream_id = stream_id;
            }
            H2Stream& st = it != sess->streams.end()
                               ? it->second
                               : sess->streams[stream_id];
            if (!st.has_headers) {
                st.send_window = sess->peer_initial_window;
                st.headers = std::move(headers);
                st.has_headers = true;
                if (!complete) return;  // await DATA
            } else {
                // Second header block on an open stream = request
                // trailers (RFC 7540 §8.1: must carry END_STREAM). Keep
                // the original headers and dispatch with the DATA
                // accumulated so far.
                if (!complete) {
                    s->SetFailedWithError(TERR_REQUEST);  // PROTOCOL_ERROR
                    return;
                }
            }
            st.dispatched = true;
            headers = std::move(st.headers);  // move back for dispatch
            body.swap(st.body);
        }
    }
    if (refuse) {
        // Refuse just this stream (we advertised the limit in SETTINGS);
        // killing the connection would fail every in-flight RPC of a
        // legitimately concurrent client.
        uint32_t code = htonl(0x7);  // REFUSED_STREAM
        SendRaw(s, BuildFrame(H2_RST_STREAM, 0, stream_id,
                              std::string((const char*)&code, 4)));
        return;
    }
    DispatchCompleteStream(s, sess, stream_id, std::move(headers),
                           std::move(body));
}

void ProcessH2(InputMessageBase* raw) {
    std::unique_ptr<H2FrameMessage> msg((H2FrameMessage*)raw);
    SocketUniquePtr s = SocketUniquePtr::FromId(msg->socket_id);
    if (!s) return;
    H2Session* sess = session_of(s.get());

    if (msg->is_preface) {
        if (sess != nullptr) return;  // duplicate preface: ignore
        sess = new H2Session;
        s->set_conn_data(sess, DeleteSession);
        // Advertise our concurrent-stream cap so well-behaved clients
        // queue instead of tripping the kMaxStreams refusals.
        uint16_t sid16 = htons(0x3);  // SETTINGS_MAX_CONCURRENT_STREAMS
        uint32_t sval = htonl((uint32_t)kMaxStreams);
        std::string sp;
        sp.append((const char*)&sid16, 2);
        sp.append((const char*)&sval, 4);
        SendRaw(s.get(), BuildFrame(H2_SETTINGS, 0, 0, sp));
        return;
    }
    if (sess == nullptr) return;

    // CONTINUATION discipline: while a header block is open, only
    // CONTINUATION for the same stream is legal.
    if (sess->cont_stream != 0 && (msg->type != H2_CONTINUATION ||
                                   msg->stream_id != sess->cont_stream)) {
        s->SetFailedWithError(TERR_REQUEST);
        return;
    }

    switch (msg->type) {
        case H2_SETTINGS: {
            if (msg->flags & kFlagAck) break;
            const std::string p = msg->payload.to_string();
            for (size_t off = 0; off + 6 <= p.size(); off += 6) {
                uint16_t id;
                uint32_t value;
                memcpy(&id, p.data() + off, 2);
                memcpy(&value, p.data() + off + 2, 4);
                id = ntohs(id);
                value = ntohl(value);
                if (id == 0x4) {  // SETTINGS_INITIAL_WINDOW_SIZE
                    std::lock_guard<std::mutex> g(sess->mu);
                    const int64_t delta =
                        (int64_t)value - sess->peer_initial_window;
                    sess->peer_initial_window = value;
                    for (auto& kv : sess->streams) {
                        kv.second.send_window += delta;
                    }
                    sess->WakeWindowWaiters();
                }
            }
            SendRaw(s.get(), BuildFrame(H2_SETTINGS, kFlagAck, 0, ""));
            break;
        }
        case H2_PING: {
            if (msg->flags & kFlagAck) break;
            SendRaw(s.get(), BuildFrame(H2_PING, kFlagAck, 0,
                                        msg->payload.to_string()));
            break;
        }
        case H2_WINDOW_UPDATE: {
            if (msg->payload.size() != 4) break;
            uint32_t inc;
            msg->payload.copy_to(&inc, 4);
            inc = ntohl(inc) & 0x7fffffffu;
            std::lock_guard<std::mutex> g(sess->mu);
            if (msg->stream_id == 0) {
                sess->conn_send_window += inc;
            } else {
                auto it = sess->streams.find(msg->stream_id);
                if (it != sess->streams.end()) {
                    it->second.send_window += inc;
                }
            }
            sess->WakeWindowWaiters();
            break;
        }
        case H2_HEADERS: {
            IOBuf frag = std::move(msg->payload);
            if ((msg->flags & kFlagPadded) &&
                !StripPadding(&frag, s.get())) {
                return;
            }
            if (msg->flags & kFlagPriority) {
                if (frag.size() < 5) {
                    s->SetFailedWithError(TERR_REQUEST);
                    return;
                }
                IOBuf drop;
                frag.cutn(&drop, 5);
            }
            sess->header_block += frag.to_string();
            if (sess->header_block.size() > kMaxHeaderBlock) {
                s->SetFailedWithError(TERR_REQUEST);
                return;
            }
            if (msg->flags & kFlagEndHeaders) {
                HandleHeaderBlockDone(s.get(), sess, msg->stream_id,
                                      msg->flags);
            } else {
                sess->cont_stream = msg->stream_id;
                sess->cont_flags = msg->flags;
            }
            break;
        }
        case H2_CONTINUATION: {
            if (sess->cont_stream == 0) {
                // CONTINUATION without an open header block: connection
                // error (RFC 7540 §6.10) — accepting it would pollute
                // the shared HPACK state.
                s->SetFailedWithError(TERR_REQUEST);
                return;
            }
            sess->header_block += msg->payload.to_string();
            if (sess->header_block.size() > kMaxHeaderBlock) {
                s->SetFailedWithError(TERR_REQUEST);
                return;
            }
            if (msg->flags & kFlagEndHeaders) {
                const uint8_t hf = sess->cont_flags;
                sess->cont_stream = 0;
                HandleHeaderBlockDone(s.get(), sess, msg->stream_id, hf);
            }
            break;
        }
        case H2_DATA: {
            const size_t sz = msg->payload.size();
            IOBuf frag = std::move(msg->payload);
            if ((msg->flags & kFlagPadded) &&
                !StripPadding(&frag, s.get())) {
                return;
            }
            bool dispatch = false;
            bool known_stream = false;
            std::vector<HpackHeader> req_headers;
            IOBuf req_body;
            {
                std::lock_guard<std::mutex> g(sess->mu);
                auto it = sess->streams.find(msg->stream_id);
                if (it != sess->streams.end() && !it->second.dispatched) {
                    known_stream = true;
                    H2Stream& st = it->second;
                    st.body.append(frag);
                    if (st.body.size() > kMaxBodyBytes) {
                        s->SetFailedWithError(TERR_OVERCROWDED);
                        return;
                    }
                    if (msg->flags & kFlagEndStream) {
                        st.dispatched = true;
                        dispatch = true;
                        req_headers = std::move(st.headers);
                        req_body.swap(st.body);
                    }
                }
            }
            // Receive-side flow control: ALWAYS replenish the connection
            // window (even for unknown/reset streams — dropping those
            // bytes silently shrinks the peer's view of the window until
            // every upload on the connection wedges); the stream window
            // only while the stream still consumes.
            if (sz > 0) {
                uint32_t inc = htonl((uint32_t)sz);
                std::string p((const char*)&inc, 4);
                std::string out = BuildFrame(H2_WINDOW_UPDATE, 0, 0, p);
                if (known_stream && !(msg->flags & kFlagEndStream)) {
                    out += BuildFrame(H2_WINDOW_UPDATE, 0, msg->stream_id,
                                      p);
                }
                SendRaw(s.get(), out);
            }
            if (dispatch) {
                DispatchCompleteStream(s.get(), sess, msg->stream_id,
                                       std::move(req_headers),
                                       std::move(req_body));
            }
            break;
        }
        case H2_RST_STREAM: {
            // The peer abandoned the stream: cancel the in-flight gRPC
            // call so its handler can stop early (cascading into any
            // downstream calls it issued), then drop the stream state.
            server_call::Cancel(s->id(), msg->stream_id);
            std::lock_guard<std::mutex> g(sess->mu);
            sess->streams.erase(msg->stream_id);
            break;
        }
        case H2_GOAWAY:
            sess->goaway = true;
            break;
        case H2_PRIORITY:
        default:
            break;  // ignored
    }
}

int g_h2_index = -1;

}  // namespace

int H2ServerSendGoaway(Socket* s) {
    H2Session* sess = session_of(s);
    if (sess == nullptr) return -1;  // no h2 session on this connection
    uint32_t last;
    {
        // last-stream-id and the dispatch gate flip under ONE mu hold:
        // every stream dispatched before this point has id <= last (and
        // will be answered); every later one is dropped by the gate in
        // HandleHeaderBlockDone — so the client's "fail ids above last"
        // rule never races a stream we actually executed.
        std::lock_guard<std::mutex> g(sess->mu);
        last = sess->max_stream_id;
        sess->goaway_sent = true;
        sess->goaway_last = last;
    }
    uint32_t payload[2] = {htonl(last), htonl(0)};  // NO_ERROR
    SendRaw(s, BuildFrame(H2_GOAWAY, 0, 0,
                          std::string((const char*)payload, 8)));
    return 0;
}

void RegisterHttp2Protocol() {
    if (g_h2_index >= 0) return;
    Protocol p;
    p.parse = ParseH2;
    p.process = ProcessH2;
    p.name = "h2c";
    // Frame handling mutates per-connection session state: must run on
    // the input fiber in frame order (user code is dispatched off it).
    p.process_in_order = true;
    g_h2_index = RegisterProtocol(p);
}

int Http2ProtocolIndex() { return g_h2_index; }

}  // namespace tpurpc
