// HTTP/2 (h2c) server-side protocol + gRPC service dispatch.
//
// Reference: src/brpc/policy/http2_rpc_protocol.cpp:1844 + details/hpack.*
// + src/brpc/grpc.{h,cpp}. Scope here (re-designed, not translated):
// SERVER side over cleartext prior knowledge — the path real gRPC clients
// (grpcio) and `curl --http2-prior-knowledge` use against in-cluster
// services. Covered: connection preface, SETTINGS exchange/ack, HEADERS +
// CONTINUATION with full HPACK decoding, DATA with both-direction flow
// control (WINDOW_UPDATE), PING ack, RST_STREAM, GOAWAY; gRPC unary calls
// (application/grpc content type, 5-byte length-prefixed messages,
// grpc-status trailers) dispatch into the same pb services as tpu_std;
// plain h2 requests route through the HTTP handler/json-RPC paths.
// Client-side h2 and TLS/ALPN are roadmap.
#pragma once

namespace tpurpc {

void RegisterHttp2Protocol();  // idempotent
int Http2ProtocolIndex();

// Graceful drain: send a real GOAWAY (NO_ERROR) on this server-side h2
// connection with last-stream-id = the highest stream ever opened by the
// peer. Streams at or below the advertised id are still served to
// completion; later streams are ignored (the client fails them as
// retriable-on-another-connection without consuming retry budget).
// Returns 0 when the frame was queued, -1 when the socket carries no h2
// session. Called by Server::StartDraining.
int H2ServerSendGoaway(class Socket* s);

}  // namespace tpurpc
