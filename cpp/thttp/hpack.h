// HPACK (RFC 7541): header decoding for the HTTP/2 server path, plus a
// deliberately simple encoder.
//
// Reference: src/brpc/details/hpack.{h,cpp} (~1.7k LoC with an encoding
// Huffman tree). Re-designed smaller: the DECODER is complete (static +
// dynamic table, incremental indexing, table-size updates, canonical
// Huffman via a flat decode walk); the ENCODER emits literal
// never-indexed headers without Huffman — always legal HPACK, trading a
// few bytes per response for zero encoder state to desynchronize.
#pragma once

#include <cstdint>
#include <deque>
#include <string>
#include <vector>

namespace tpurpc {

struct HpackHeader {
    std::string name;   // lowercase on decode (h2 requires lowercase)
    std::string value;
};

class HpackDecoder {
public:
    // `max_dynamic_size` is OUR advertised SETTINGS_HEADER_TABLE_SIZE
    // ceiling; the peer may shrink below it with a table-size update.
    explicit HpackDecoder(size_t max_dynamic_size = 4096)
        : capacity_(max_dynamic_size), max_capacity_(max_dynamic_size) {}

    // Decode one complete header block; append to *out. Returns false on
    // malformed input (connection error per RFC 7541 §5.2/§6).
    bool Decode(const uint8_t* data, size_t len,
                std::vector<HpackHeader>* out);

private:
    bool LookupIndex(uint64_t index, HpackHeader* h) const;
    void InsertDynamic(const HpackHeader& h);
    void EvictToFit();

    size_t capacity_;
    size_t max_capacity_;
    size_t dynamic_size_ = 0;
    std::deque<HpackHeader> dynamic_;  // front = most recent
};

// Literal never-indexed, no Huffman: stateless and always valid.
void HpackEncodeHeader(const std::string& name, const std::string& value,
                       std::string* out);

// Exposed for tests/fuzzing.
bool HpackHuffmanDecode(const uint8_t* data, size_t len, std::string* out);

}  // namespace tpurpc
