#include "thttp/hpack.h"

#include <cctype>

#include "thttp/hpack_tables.h"

namespace tpurpc {

namespace {

constexpr size_t kMaxHeaderBytes = 256 * 1024;  // decoded-size guard

// Huffman decode table built once: for each (state-less) walk we match
// codes MSB-first. A flat map from (nbits,code) would be large; instead
// build a binary trie over the canonical codes — 513 nodes max.
struct HuffTrie {
    struct Node {
        int16_t child[2];
        int16_t sym;  // -1 internal, 0..255 leaf, 256 EOS
    };
    std::vector<Node> nodes;

    HuffTrie() {
        nodes.push_back(Node{{-1, -1}, -1});
        for (int sym = 0; sym <= 256; ++sym) {
            const uint32_t code = hpack::kHuffman[sym].code;
            const int nbits = hpack::kHuffman[sym].nbits;
            int cur = 0;
            for (int b = nbits - 1; b >= 0; --b) {
                const int bit = (code >> b) & 1;
                if (nodes[(size_t)cur].child[bit] < 0) {
                    nodes[(size_t)cur].child[bit] = (int16_t)nodes.size();
                    nodes.push_back(Node{{-1, -1}, -1});
                }
                cur = nodes[(size_t)cur].child[bit];
            }
            nodes[(size_t)cur].sym = (int16_t)sym;
        }
    }
};

const HuffTrie& huff_trie() {
    static const HuffTrie t;
    return t;
}

// Decode an HPACK varint (RFC 7541 §5.1) with `prefix_bits` in *p.
// Advances *p; false on truncation/overflow.
bool DecodeInt(const uint8_t** p, const uint8_t* end, int prefix_bits,
               uint64_t* out) {
    if (*p >= end) return false;
    const uint8_t mask = (uint8_t)((1u << prefix_bits) - 1);
    uint64_t v = (*(*p)++) & mask;
    if (v < mask) {
        *out = v;
        return true;
    }
    int shift = 0;
    while (*p < end) {
        const uint8_t b = *(*p)++;
        if (shift > 56) return false;  // overflow guard
        v += (uint64_t)(b & 0x7f) << shift;
        shift += 7;
        if ((b & 0x80) == 0) {
            *out = v;
            return true;
        }
    }
    return false;  // truncated continuation
}

bool DecodeString(const uint8_t** p, const uint8_t* end, std::string* out) {
    if (*p >= end) return false;
    const bool huffman = (**p & 0x80) != 0;
    uint64_t len = 0;
    if (!DecodeInt(p, end, 7, &len)) return false;
    if (len > (uint64_t)(end - *p) || len > kMaxHeaderBytes) return false;
    if (huffman) {
        if (!HpackHuffmanDecode(*p, (size_t)len, out)) return false;
    } else {
        out->assign((const char*)*p, (size_t)len);
    }
    *p += len;
    return out->size() <= kMaxHeaderBytes;
}

size_t entry_size(const HpackHeader& h) {
    return h.name.size() + h.value.size() + 32;  // RFC 7541 §4.1
}

}  // namespace

bool HpackHuffmanDecode(const uint8_t* data, size_t len, std::string* out) {
    const HuffTrie& t = huff_trie();
    int cur = 0;
    int depth = 0;  // bits consumed since last symbol (for padding check)
    for (size_t i = 0; i < len; ++i) {
        for (int b = 7; b >= 0; --b) {
            const int bit = (data[i] >> b) & 1;
            const int16_t next = t.nodes[(size_t)cur].child[bit];
            if (next < 0) return false;  // not a prefix of any code
            cur = next;
            ++depth;
            const int16_t sym = t.nodes[(size_t)cur].sym;
            if (sym >= 0) {
                if (sym == 256) return false;  // EOS in stream = error
                out->push_back((char)sym);
                if (out->size() > kMaxHeaderBytes) return false;
                cur = 0;
                depth = 0;
            }
        }
    }
    // Padding must be < 8 bits of EOS prefix (all ones). Any node on the
    // all-ones path is fine; a node reachable only via a 0 bit means the
    // padding wasn't EOS bits.
    if (depth >= 8) return false;
    // Walk the all-ones path from root `depth` steps: must equal cur.
    int check = 0;
    for (int i = 0; i < depth; ++i) {
        check = t.nodes[(size_t)check].child[1];
        if (check < 0) return false;
    }
    return check == cur;
}

bool HpackDecoder::LookupIndex(uint64_t index, HpackHeader* h) const {
    if (index == 0) return false;
    if (index <= 61) {
        h->name = hpack::kStaticTable[index - 1].name;
        h->value = hpack::kStaticTable[index - 1].value;
        return true;
    }
    const uint64_t di = index - 62;
    if (di >= dynamic_.size()) return false;
    *h = dynamic_[(size_t)di];
    return true;
}

void HpackDecoder::InsertDynamic(const HpackHeader& h) {
    const size_t sz = entry_size(h);
    if (sz > capacity_) {
        // Larger than the whole table: clears it (RFC 7541 §4.4).
        dynamic_.clear();
        dynamic_size_ = 0;
        return;
    }
    dynamic_.push_front(h);
    dynamic_size_ += sz;
    EvictToFit();
}

void HpackDecoder::EvictToFit() {
    while (dynamic_size_ > capacity_ && !dynamic_.empty()) {
        dynamic_size_ -= entry_size(dynamic_.back());
        dynamic_.pop_back();
    }
}

bool HpackDecoder::Decode(const uint8_t* data, size_t len,
                          std::vector<HpackHeader>* out) {
    const uint8_t* p = data;
    const uint8_t* end = data + len;
    size_t total = 0;
    while (p < end) {
        const uint8_t b = *p;
        if (b & 0x80) {
            // Indexed header field.
            uint64_t index;
            if (!DecodeInt(&p, end, 7, &index)) return false;
            HpackHeader h;
            if (!LookupIndex(index, &h)) return false;
            total += entry_size(h);
            out->push_back(std::move(h));
        } else if (b & 0x40) {
            // Literal with incremental indexing.
            uint64_t index;
            if (!DecodeInt(&p, end, 6, &index)) return false;
            HpackHeader h;
            if (index > 0) {
                if (!LookupIndex(index, &h)) return false;
                h.value.clear();
            } else if (!DecodeString(&p, end, &h.name)) {
                return false;
            }
            if (!DecodeString(&p, end, &h.value)) return false;
            InsertDynamic(h);
            total += entry_size(h);
            out->push_back(std::move(h));
        } else if (b & 0x20) {
            // Dynamic table size update.
            uint64_t size;
            if (!DecodeInt(&p, end, 5, &size)) return false;
            if (size > max_capacity_) return false;
            capacity_ = (size_t)size;
            EvictToFit();
        } else {
            // Literal without indexing (0x00) / never indexed (0x10).
            uint64_t index;
            if (!DecodeInt(&p, end, 4, &index)) return false;
            HpackHeader h;
            if (index > 0) {
                if (!LookupIndex(index, &h)) return false;
                h.value.clear();
            } else if (!DecodeString(&p, end, &h.name)) {
                return false;
            }
            if (!DecodeString(&p, end, &h.value)) return false;
            total += entry_size(h);
            out->push_back(std::move(h));
        }
        if (total > kMaxHeaderBytes) return false;
    }
    return true;
}

void HpackEncodeHeader(const std::string& name, const std::string& value,
                       std::string* out) {
    // Literal never-indexed (0x10), 4-bit length prefixes, no Huffman.
    auto put_len = [out](size_t n, uint8_t first, int prefix_bits) {
        const uint8_t mask = (uint8_t)((1u << prefix_bits) - 1);
        if (n < mask) {
            out->push_back((char)(first | (uint8_t)n));
            return;
        }
        out->push_back((char)(first | mask));
        n -= mask;
        while (n >= 0x80) {
            out->push_back((char)(0x80 | (n & 0x7f)));
            n >>= 7;
        }
        out->push_back((char)n);
    };
    out->push_back((char)0x10);
    put_len(name.size(), 0x00, 7);
    out->append(name);
    put_len(value.size(), 0x00, 7);
    out->append(value);
}

}  // namespace tpurpc
