#include "thttp/http_message.h"

#include <cctype>
#include <cstdio>
#include <cstring>

namespace tpurpc {

namespace {

constexpr size_t kMaxHeaderBytes = 64 * 1024;
constexpr uint64_t kMaxBodyBytes = 64ull << 20;

// Known request verbs (sniffing + validation).
const char* const kMethods[] = {"GET",     "POST",  "HEAD",  "PUT",
                                "DELETE",  "PATCH", "OPTIONS"};

bool ieq(const std::string& a, const char* b) {
    const size_t n = strlen(b);
    if (a.size() != n) return false;
    for (size_t i = 0; i < n; ++i) {
        if (tolower((unsigned char)a[i]) != tolower((unsigned char)b[i])) {
            return false;
        }
    }
    return true;
}

// %xx-decode (path only; '+' is literal in paths).
std::string url_decode(const std::string& in) {
    std::string out;
    out.reserve(in.size());
    for (size_t i = 0; i < in.size(); ++i) {
        if (in[i] == '%' && i + 2 < in.size() && isxdigit((unsigned char)in[i + 1]) &&
            isxdigit((unsigned char)in[i + 2])) {
            const char hex[3] = {in[i + 1], in[i + 2], 0};
            out.push_back((char)strtol(hex, nullptr, 16));
            i += 2;
        } else {
            out.push_back(in[i]);
        }
    }
    return out;
}

}  // namespace

bool CaseLess::operator()(const std::string& a, const std::string& b) const {
    const size_t n = a.size() < b.size() ? a.size() : b.size();
    for (size_t i = 0; i < n; ++i) {
        const int ca = tolower((unsigned char)a[i]);
        const int cb = tolower((unsigned char)b[i]);
        if (ca != cb) return ca < cb;
    }
    return a.size() < b.size();
}

std::string HttpRequest::QueryParam(const std::string& key,
                                    bool* found) const {
    if (found != nullptr) *found = false;
    size_t pos = 0;
    while (pos < query.size()) {
        size_t amp = query.find('&', pos);
        if (amp == std::string::npos) amp = query.size();
        const size_t eq = query.find('=', pos);
        if (eq != std::string::npos && eq < amp && eq - pos == key.size() &&
            query.compare(pos, eq - pos, key) == 0) {
            if (found != nullptr) *found = true;
            return url_decode(query.substr(eq + 1, amp - eq - 1));
        }
        if ((eq == std::string::npos || eq >= amp) &&
            amp - pos == key.size() &&
            query.compare(pos, amp - pos, key) == 0) {
            // bare key (no '=')
            if (found != nullptr) *found = true;
            return "";
        }
        pos = amp + 1;
    }
    return "";
}

HttpParseStatus ParseHttpRequest(IOBuf* source, HttpRequest* out) {
    // Fast sniff on the first bytes: must start with a known verb + SP.
    {
        char probe[8];
        const size_t n = source->copy_to(probe, sizeof(probe), 0);
        bool maybe = false;
        for (const char* m : kMethods) {
            const size_t ml = strlen(m);
            const size_t cmp = n < ml + 1 ? n : ml + 1;
            if (cmp == 0) return HttpParseStatus::kNeedMore;
            char want[9];
            snprintf(want, sizeof(want), "%s ", m);
            if (memcmp(probe, want, cmp) == 0) {
                maybe = true;
                break;
            }
        }
        if (!maybe) return HttpParseStatus::kNotHttp;
        if (n < sizeof(probe) && source->size() == n) {
            // All buffered bytes are a verb prefix: need more to be sure.
            // (kNotHttp was already returned on any mismatch above.)
        }
    }
    // Copy the (bounded) header section out and find CRLFCRLF.
    const size_t scan = source->size() < kMaxHeaderBytes + 4
                            ? source->size()
                            : kMaxHeaderBytes + 4;
    std::string hdr;
    source->copy_to(&hdr, scan, 0);
    const size_t hdr_end = hdr.find("\r\n\r\n");
    if (hdr_end == std::string::npos) {
        if (source->size() > kMaxHeaderBytes) return HttpParseStatus::kError;
        return HttpParseStatus::kNeedMore;
    }
    const size_t header_len = hdr_end + 4;

    HttpRequest req;
    // ---- request line ----
    const size_t line_end = hdr.find("\r\n");
    const std::string line = hdr.substr(0, line_end);
    const size_t sp1 = line.find(' ');
    const size_t sp2 = line.rfind(' ');
    if (sp1 == std::string::npos || sp2 == sp1) return HttpParseStatus::kError;
    req.method = line.substr(0, sp1);
    bool known = false;
    for (const char* m : kMethods) known |= req.method == m;
    if (!known) return HttpParseStatus::kError;
    std::string target = line.substr(sp1 + 1, sp2 - sp1 - 1);
    const std::string proto = line.substr(sp2 + 1);
    if (proto.size() != 8 || proto.compare(0, 5, "HTTP/") != 0 ||
        !isdigit((unsigned char)proto[5]) || proto[6] != '.' ||
        !isdigit((unsigned char)proto[7])) {
        return HttpParseStatus::kError;
    }
    req.version_major = proto[5] - '0';
    req.version_minor = proto[7] - '0';
    if (target.empty()) return HttpParseStatus::kError;
    const size_t q = target.find('?');
    if (q != std::string::npos) {
        req.query = target.substr(q + 1);
        target.resize(q);
    }
    req.path = url_decode(target);

    // ---- headers ----
    size_t pos = line_end + 2;
    while (pos < hdr_end) {
        size_t eol = hdr.find("\r\n", pos);
        if (eol == std::string::npos || eol > hdr_end) eol = hdr_end;
        const std::string hline = hdr.substr(pos, eol - pos);
        pos = eol + 2;
        const size_t colon = hline.find(':');
        if (colon == std::string::npos || colon == 0) {
            return HttpParseStatus::kError;
        }
        std::string name = hline.substr(0, colon);
        // No whitespace allowed in field names (request smuggling guard).
        for (char c : name) {
            if (isspace((unsigned char)c)) return HttpParseStatus::kError;
        }
        size_t vs = colon + 1;
        while (vs < hline.size() && (hline[vs] == ' ' || hline[vs] == '\t')) {
            ++vs;
        }
        size_t ve = hline.size();
        while (ve > vs && (hline[ve - 1] == ' ' || hline[ve - 1] == '\t')) {
            --ve;
        }
        std::string value = hline.substr(vs, ve - vs);
        auto ins = req.headers.emplace(name, value);
        if (!ins.second) {
            // Duplicate header. Differing Content-Length values are the
            // classic request-smuggling vector (RFC 9112 §6.3): reject.
            if (ieq(name, "Content-Length") && ins.first->second != value) {
                return HttpParseStatus::kError;
            }
            ins.first->second = std::move(value);  // otherwise last wins
        }
    }

    // ---- body ----
    uint64_t content_length = 0;
    if (const std::string* te = req.FindHeader("Transfer-Encoding")) {
        (void)te;
        return HttpParseStatus::kError;  // portal requests never chunk
    }
    if (const std::string* cl = req.FindHeader("Content-Length")) {
        char* end = nullptr;
        content_length = strtoull(cl->c_str(), &end, 10);
        if (end == cl->c_str() || *end != '\0' ||
            content_length > kMaxBodyBytes) {
            return HttpParseStatus::kError;
        }
    }
    if (source->size() < header_len + content_length) {
        return HttpParseStatus::kNeedMore;
    }
    source->pop_front(header_len);
    source->cutn(&req.body, content_length);
    *out = std::move(req);
    return HttpParseStatus::kOk;
}

const char* HttpReasonPhrase(int status) {
    switch (status) {
        case 200: return "OK";
        case 204: return "No Content";
        case 301: return "Moved Permanently";
        case 302: return "Found";
        case 400: return "Bad Request";
        case 403: return "Forbidden";
        case 404: return "Not Found";
        case 405: return "Method Not Allowed";
        case 411: return "Length Required";
        case 413: return "Payload Too Large";
        case 431: return "Request Header Fields Too Large";
        case 500: return "Internal Server Error";
        case 501: return "Not Implemented";
        case 503: return "Service Unavailable";
        default: return "Unknown";
    }
}

void SerializeHttpResponse(HttpResponse* res, IOBuf* out) {
    char line[128];
    snprintf(line, sizeof(line), "HTTP/1.1 %d %s\r\n", res->status,
             res->reason.empty() ? HttpReasonPhrase(res->status)
                                 : res->reason.c_str());
    out->append(line);
    if (res->headers.find("Content-Length") == res->headers.end() &&
        res->headers.find("Transfer-Encoding") == res->headers.end()) {
        // Content-Length alongside Transfer-Encoding is illegal (RFC
        // 9112 §6.2); chunked responses carry their own framing.
        snprintf(line, sizeof(line), "Content-Length: %zu\r\n",
                 res->body.size());
        out->append(line);
    }
    if (res->headers.find("Connection") == res->headers.end()) {
        out->append("Connection: keep-alive\r\n");
    }
    for (const auto& kv : res->headers) {
        out->append(kv.first);
        out->append(": ", 2);
        out->append(kv.second);
        out->append("\r\n", 2);
    }
    out->append("\r\n", 2);
    out->append(std::move(res->body));
}

}  // namespace tpurpc
