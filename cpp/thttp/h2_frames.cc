#include "thttp/h2_frames.h"

#include <arpa/inet.h>

#include <algorithm>

#include "thttp/hpack.h"

namespace tpurpc {
namespace h2 {

void AppendFrame(std::string* out, uint8_t type, uint8_t flags,
                 uint32_t stream, const char* payload, size_t len) {
    out->reserve(out->size() + kFrameHeaderLen + len);
    out->push_back((char)((len >> 16) & 0xff));
    out->push_back((char)((len >> 8) & 0xff));
    out->push_back((char)(len & 0xff));
    out->push_back((char)type);
    out->push_back((char)flags);
    const uint32_t sid = htonl(stream & 0x7fffffffu);
    out->append((const char*)&sid, 4);
    out->append(payload, len);
}

std::string BuildFrame(uint8_t type, uint8_t flags, uint32_t stream,
                       const std::string& payload) {
    std::string f;
    AppendFrame(&f, type, flags, stream, payload.data(), payload.size());
    return f;
}

void AppendHeadersFrames(std::string* out, uint8_t flags, uint32_t stream,
                         const std::string& block) {
    if (block.size() <= kMaxFrameSize) {
        AppendFrame(out, H2_HEADERS, flags, stream, block.data(),
                    block.size());
        return;
    }
    const uint8_t end_stream = flags & kFlagEndStream;
    size_t off = 0;
    AppendFrame(out, H2_HEADERS, end_stream, stream, block.data(),
                kMaxFrameSize);
    off += kMaxFrameSize;
    while (off < block.size()) {
        const size_t n =
            std::min<size_t>(kMaxFrameSize, block.size() - off);
        const bool last = off + n >= block.size();
        AppendFrame(out, H2_CONTINUATION, last ? kFlagEndHeaders : 0,
                    stream, block.data() + off, n);
        off += n;
    }
}

std::string EncodeHeaderBlock(
    const std::vector<std::pair<std::string, std::string>>& headers) {
    std::string block;
    for (const auto& kv : headers) {
        HpackEncodeHeader(kv.first, kv.second, &block);
    }
    return block;
}

}  // namespace h2
}  // namespace tpurpc
