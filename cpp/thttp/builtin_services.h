// Builtin observability portal: the HTTP services every server exposes.
//
// Plays the role of reference src/brpc/builtin/ (the ~30 services
// auto-registered by Server::AddBuiltinServices, server.cpp:499-614),
// starting with the operationally load-bearing set:
//   /          index (what's here)
//   /health    liveness probe
//   /status    per-method qps/latency/concurrency/errors (status_service)
//   /vars      every exposed tvar (vars_service); /vars/<name> for one
//   /flags     runtime flags; /flags/<name>?setvalue=v mutates
//              (flags_service + reloadable_flags)
//   /connections  accepted sockets (connections_service)
//   /metrics   Prometheus text exposition
//              (prometheus_metrics_service.cpp:244)
#pragma once

namespace tpurpc {

class Server;

// Install the portal handlers on `server` (called by StartNoListen).
void AddBuiltinHttpServices(Server* server);

}  // namespace tpurpc
