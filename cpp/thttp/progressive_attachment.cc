#include "thttp/progressive_attachment.h"

#include <cstdio>

#include "tbase/errno.h"
#include "tfiber/fiber.h"

namespace tpurpc {

int ProgressiveAttachment::Write(const IOBuf& data) {
    if (closed_.load(std::memory_order_acquire) || data.empty()) {
        return closed_.load(std::memory_order_acquire) ? -1 : 0;
    }
    SocketUniquePtr s;
    if (Socket::AddressSocket(sid_, &s) != 0) return -1;
    char head[32];
    const int n = snprintf(head, sizeof(head), "%zx\r\n", data.size());
    IOBuf chunk;
    chunk.append(head, (size_t)n);
    chunk.append(data);
    chunk.append("\r\n", 2);
    return s->Write(&chunk);
}

void ProgressiveAttachment::Close() {
    bool expect = false;
    if (!closed_.compare_exchange_strong(expect, true,
                                         std::memory_order_acq_rel)) {
        return;
    }
    {
        SocketUniquePtr s;
        if (Socket::AddressSocket(sid_, &s) == 0) {
            IOBuf last;
            last.append("0\r\n\r\n", 5);
            s->Write(&last);
            if (close_conn_) {
                // The header block promised Connection: close — honor it
                // (mirrors the plain-response path in http_protocol.cc):
                // bounded wait for the queued chunks to reach the wire,
                // then fail the socket, which closes the fd.
                for (int i = 0; i < 200 && s->unwritten_bytes() > 0 &&
                                !s->Failed();
                     ++i) {
                    fiber_usleep(1000);
                }
                s->SetFailedWithError(TERR_EOF);
            }
        }
    }
    // Exactly once, AFTER the terminating chunk is queued: the stream no
    // longer holds the server's in-flight count (Join may return and the
    // Server may be torn down right after — last touch discipline).
    if (on_close_ != nullptr) {
        auto cb = on_close_;
        void* arg = on_close_arg_;
        on_close_ = nullptr;
        cb(arg);
    }
}

}  // namespace tpurpc
