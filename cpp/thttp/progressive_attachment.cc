#include "thttp/progressive_attachment.h"

#include <cstdio>

namespace tpurpc {

int ProgressiveAttachment::Write(const IOBuf& data) {
    if (closed_.load(std::memory_order_acquire) || data.empty()) {
        return closed_.load(std::memory_order_acquire) ? -1 : 0;
    }
    SocketUniquePtr s;
    if (Socket::AddressSocket(sid_, &s) != 0) return -1;
    char head[32];
    const int n = snprintf(head, sizeof(head), "%zx\r\n", data.size());
    IOBuf chunk;
    chunk.append(head, (size_t)n);
    chunk.append(data);
    chunk.append("\r\n", 2);
    return s->Write(&chunk);
}

void ProgressiveAttachment::Close() {
    bool expect = false;
    if (!closed_.compare_exchange_strong(expect, true,
                                         std::memory_order_acq_rel)) {
        return;
    }
    SocketUniquePtr s;
    if (Socket::AddressSocket(sid_, &s) != 0) return;
    IOBuf last;
    last.append("0\r\n\r\n", 5);
    s->Write(&last);
}

}  // namespace tpurpc
