// HTTP/1.x server protocol: sniffs HTTP requests on any server
// connection and dispatches them to the server's handler map (builtin
// portal services + user handlers).
//
// Plays the role of reference src/brpc/policy/http_rpc_protocol.cpp's
// server half: ParseHttpMessage feeding ProcessHttpRequest, registered in
// the same protocol registry the native framed protocol uses, so one
// port serves both RPC and the portal (reference server.cpp: builtin
// services are plain services on the same acceptor).
#pragma once

#include <functional>
#include <string>

namespace tpurpc {

class Server;
struct HttpRequest;
struct HttpResponse;

// A handler owns one path (exact) or path prefix (see
// Server::RegisterHttpHandler). Runs on a fiber.
using HttpHandler =
    std::function<void(Server*, const HttpRequest&, HttpResponse*)>;

int HttpProtocolIndex();
void RegisterHttpProtocol();  // idempotent; called from global init

}  // namespace tpurpc
