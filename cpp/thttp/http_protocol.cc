#include "thttp/http_protocol.h"

#include <memory>

#include "tbase/errno.h"
#include "tbase/logging.h"
#include "thttp/http_message.h"
#include "tnet/input_messenger.h"
#include "tnet/protocol.h"
#include "tnet/socket.h"
#include "trpc/server.h"

namespace tpurpc {

namespace {

struct HttpInputMessage : public InputMessageBase {
    HttpRequest req;
    Server* server = nullptr;
};

ParseResult ParseHttp(IOBuf* source, Socket* s, bool read_eof, const void*) {
    (void)read_eof;
    HttpRequest req;
    switch (ParseHttpRequest(source, &req)) {
        case HttpParseStatus::kNotHttp:
            return ParseResult::make(ParseError::TRY_OTHERS);
        case HttpParseStatus::kNeedMore:
            return ParseResult::make(ParseError::NOT_ENOUGH_DATA);
        case HttpParseStatus::kError:
            return ParseResult::make(ParseError::ERROR);
        case HttpParseStatus::kOk:
            break;
    }
    auto* msg = new HttpInputMessage;
    msg->req = std::move(req);
    InputMessenger* m = (InputMessenger*)s->user();
    msg->server = m != nullptr ? (Server*)m->context : nullptr;
    return ParseResult::make_ok(msg);
}

void ProcessHttp(InputMessageBase* msg_base) {
    std::unique_ptr<HttpInputMessage> msg((HttpInputMessage*)msg_base);
    SocketUniquePtr s = SocketUniquePtr::FromId(msg->socket_id);
    if (!s) return;
    HttpResponse res;
    const bool close_conn = [&] {
        const std::string* conn = msg->req.FindHeader("Connection");
        if (conn != nullptr) {
            return conn->find("close") != std::string::npos;
        }
        return msg->req.version_minor == 0;  // HTTP/1.0 default
    }();
    if (msg->server == nullptr) {
        res.status = 503;
        res.Append("no server bound to this port\n");
    } else {
        const HttpHandler* h = msg->server->FindHttpHandler(msg->req.path);
        if (h == nullptr) {
            res.status = 404;
            res.set_content_type("text/plain");
            res.Append("404 not found: " + msg->req.path + "\n");
        } else {
            (*h)(msg->server, msg->req, &res);
        }
    }
    if (close_conn) res.SetHeader("Connection", "close");
    // HEAD: headers (incl. the Content-Length the body WOULD have), no
    // body bytes (RFC 9110 §9.3.2 — sending them desyncs keep-alive).
    if (msg->req.method == "HEAD") {
        char cl[32];
        snprintf(cl, sizeof(cl), "%zu", res.body.size());
        res.SetHeader("Content-Length", cl);
        res.body.clear();
    }
    IOBuf out;
    SerializeHttpResponse(&res, &out);
    s->Write(&out);
    if (close_conn) {
        // Honor the advertised close ourselves: read-until-EOF clients
        // (HTTP/1.0, simple scripts) block forever otherwise. Wait for
        // the write queue to drain (bounded), then fail the socket —
        // which closes the fd.
        for (int i = 0; i < 200 && s->unwritten_bytes() > 0; ++i) {
            fiber_usleep(1000);
        }
        s->SetFailedWithError(TERR_EOF);
    }
}

int g_http_index = -1;

}  // namespace

void RegisterHttpProtocol() {
    if (g_http_index >= 0) return;
    Protocol p;
    p.parse = ParseHttp;
    p.process = ProcessHttp;
    p.name = "http";
    p.process_in_order = true;  // no correlation ids: FIFO responses
    g_http_index = RegisterProtocol(p);
}

int HttpProtocolIndex() { return g_http_index; }

}  // namespace tpurpc
