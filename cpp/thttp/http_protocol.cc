#include "thttp/http_protocol.h"

#include <memory>

#include "tbase/errno.h"
#include "tbase/time.h"
#include "tbase/logging.h"
#include "thttp/http_message.h"
#include "thttp/progressive_attachment.h"
#include "tnet/input_messenger.h"
#include "tnet/protocol.h"
#include "tnet/socket.h"
#include "tfiber/fiber_sync.h"
#include "trpc/controller.h"
#include "trpc/auth.h"
#include "trpc/json2pb.h"
#include "trpc/server.h"

namespace tpurpc {

namespace {

struct HttpInputMessage : public InputMessageBase {
    HttpRequest req;
    Server* server = nullptr;
};

ParseResult ParseHttp(IOBuf* source, Socket* s, bool read_eof, const void*) {
    (void)read_eof;
    HttpRequest req;
    switch (ParseHttpRequest(source, &req)) {
        case HttpParseStatus::kNotHttp:
            return ParseResult::make(ParseError::TRY_OTHERS);
        case HttpParseStatus::kNeedMore:
            return ParseResult::make(ParseError::NOT_ENOUGH_DATA);
        case HttpParseStatus::kError:
            return ParseResult::make(ParseError::ERROR);
        case HttpParseStatus::kOk:
            break;
    }
    auto* msg = new HttpInputMessage;
    msg->req = std::move(req);
    InputMessenger* m = (InputMessenger*)s->user();
    msg->server = m != nullptr ? (Server*)m->context : nullptr;
    return ParseResult::make_ok(msg);
}

}  // namespace

// Error strings embedded in json bodies: strip characters that would
// break the syntax (quotes, backslashes, control bytes).
static std::string json_safe_text(std::string s) {
    for (char& ch : s) {
        if (ch == '"' || ch == '\\' || (unsigned char)ch < 0x20) {
            ch = ' ';
        }
    }
    return s;
}

// HTTP-as-RPC: POST /Service/Method with an application/json body is
// transcoded to the pb service and answered as json (reference
// policy/http_rpc_protocol.cpp:1790 + src/json2pb). Runs synchronously on
// this (in-order) connection fiber: the done-closure is awaited, so async
// handlers work too. Returns false if the path maps to no method.
bool DispatchHttpRpc(Server* server, const HttpRequest& req,
                     HttpResponse* res, const EndPoint& remote_side) {
    Server::MethodProperty* mp = server->FindMethodByHttpPath(req.path);
    if (mp == nullptr) return false;
    res->set_content_type("application/json");
    // ServerOptions::auth covers the json transcoding door too (the RPC
    // methods it guards on tpu_std/gRPC/redis must not be callable bare
    // over HTTP): the `authorization` header carries the credential,
    // like the gRPC path. Portal pages stay open — they don't run user
    // service code.
    if (server->options().auth != nullptr) {
        const std::string* authz = req.FindHeader("authorization");
        AuthContext actx;
        if (authz == nullptr ||
            server->options().auth->VerifyCredential(
                *authz, remote_side, &actx) != 0) {
            res->status = 401;
            res->body.clear();
            res->Append("{\"error\":\"authentication failed\"}\n");
            return true;
        }
    }
    if (req.method != "POST" && req.method != "GET") {
        res->status = 405;
        res->body.clear();
        res->Append("{\"error\":\"use POST (json body) or GET\"}\n");
        return true;
    }
    // QoS identity + rate quota (ISSUE 8): the x-tpu-tenant /
    // x-tpu-priority headers class json-door traffic exactly like the
    // native protocols; quota sheds answer 429 with Retry-After.
    QosDispatcher* qos = server->qos();
    const std::string* xt = req.FindHeader("x-tpu-tenant");
    const int priority =
        PriorityFromHeader(req.FindHeader("x-tpu-priority"));
    QosDispatcher::TenantState* tstate = nullptr;
    const int64_t arrival_us = monotonic_time_us();
    // Work-priced admission (ISSUE 15): the json door charges the same
    // per-(tenant, method) cost estimate as the native protocols.
    const std::string method_key =
        mp->method->service()->full_name() + "." + mp->method->name();
    int64_t cost_milli = kCostUnitMilli;
    if (qos->enabled()) {
        tstate = qos->Acquire(xt != nullptr ? *xt : "");
        cost_milli = qos->EstimateCostMilli(tstate, method_key);
        int64_t backoff_ms = 0;
        if (!qos->AdmitCost(tstate, arrival_us, cost_milli, &backoff_ms)) {
            res->status = 429;
            res->headers["Retry-After"] =
                std::to_string((backoff_ms + 999) / 1000);
            res->Append("{\"error\":\"tenant over its cost quota\","
                        "\"backoff_ms\":" +
                        std::to_string(backoff_ms) + "}\n");
            return true;
        }
    }
    // Admission + stats + Join accounting shared with the native protocol.
    Server::MethodCallGuard guard(server, mp, -1, priority);
    if (guard.rejected()) {
        if (tstate != nullptr) qos->CountShed(tstate, cost_milli);
        res->status = qos->enabled() ? 429 : 503;
        res->Append("{\"error\":\"concurrency limit\"}\n");
        return true;
    }
    if (tstate != nullptr) qos->BeginServed(tstate, cost_milli);

    std::unique_ptr<google::protobuf::Message> pb_req(
        mp->service->GetRequestPrototype(mp->method).New());
    std::unique_ptr<google::protobuf::Message> pb_res(
        mp->service->GetResponsePrototype(mp->method).New());
    Controller cntl;
    cntl.InitServerSide(server, remote_side);
    if (xt != nullptr) cntl.set_tenant(*xt);
    cntl.set_priority(priority);
    // Sticky-session identity (ISSUE 16): the json door carries it on
    // the same x-tpu-session header as the h2 door.
    const std::string* xs = req.FindHeader("x-tpu-session");
    if (xs != nullptr) cntl.set_session(*xs);
    if (server->options().interceptor != nullptr) {
        int ierr = 0;
        std::string ietext;
        if (!server->options().interceptor->Accept(&cntl, &ierr, &ietext)) {
            res->status = 403;
            res->Append("{\"error\":\"" +
                        (ietext.empty() ? std::string("rejected")
                                        : json_safe_text(ietext)) +
                        "\"}\n");
            if (tstate != nullptr) {
                qos->OnDone(tstate, monotonic_time_us() - arrival_us);
            }
            guard.Finish(ierr != 0 ? ierr : 403);
            return true;
        }
    }
    std::string err;
    const std::string body = req.body.to_string();
    if (!body.empty() && !JsonToPb(body, pb_req.get(), &err)) {
        res->status = 400;
        res->Append("{\"error\":\"bad request json: " + json_safe_text(err) +
                    "\"}\n");
    } else {
        // Await the done-closure (handlers may complete asynchronously).
        CountdownEvent done_ev(1);
        struct SignalClosure : google::protobuf::Closure {
            CountdownEvent* ev;
            void Run() override { ev->signal(); }  // NOT self-deleting
        } done;
        done.ev = &done_ev;
        mp->service->CallMethod(mp->method, &cntl, pb_req.get(),
                                pb_res.get(), &done);
        done_ev.wait();
        if (cntl.Failed()) {
            res->status = 500;
            res->Append("{\"error\":\"" + json_safe_text(cntl.ErrorText()) +
                        "\"}\n");
        } else {
            std::string json;
            if (!PbToJson(*pb_res, &json, &err)) {
                res->status = 500;
                res->Append("{\"error\":\"serialize response\"}\n");
            } else {
                res->Append(json);
                res->Append("\n");
            }
        }
    }
    // Per-tenant completion, then feed the limiter/stats the RPC error
    // (the same signal the native protocol uses), not the HTTP status.
    // The completion teaches the cost model (body bytes = the logical
    // payload of a json call) and the tenant's gradient limiter.
    if (tstate != nullptr) {
        QosDispatcher::CompletionInfo ci;
        ci.error_code = cntl.Failed() ? cntl.ErrorCode() : 0;
        ci.method = &method_key;
        ci.logical_bytes = (int64_t)body.size();
        ci.peer = remote_side;
        qos->OnDone(tstate, monotonic_time_us() - arrival_us, ci);
    }
    guard.Finish(cntl.Failed() ? cntl.ErrorCode()
                               : (res->status == 200 ? 0 : res->status));
    return true;
}


namespace {

void ProcessHttp(InputMessageBase* msg_base) {
    std::unique_ptr<HttpInputMessage> msg((HttpInputMessage*)msg_base);
    SocketUniquePtr s = SocketUniquePtr::FromId(msg->socket_id);
    if (!s) return;
    HttpResponse res;
    const bool close_conn = [&] {
        // Draining server (graceful shutdown): HTTP/1 has no unsolicited
        // server frame, so the drain announcement rides the next
        // response as `Connection: close` — the client re-connects
        // elsewhere (or gets refused once the listener stops).
        if (msg->server != nullptr && msg->server->draining()) return true;
        const std::string* conn = msg->req.FindHeader("Connection");
        if (conn != nullptr) {
            return conn->find("close") != std::string::npos;
        }
        return msg->req.version_minor == 0;  // HTTP/1.0 default
    }();
    if (msg->server == nullptr) {
        res.status = 503;
        res.Append("no server bound to this port\n");
    } else {
        const HttpHandler* h = msg->server->FindHttpHandler(msg->req.path);
        if (h != nullptr) {
            (*h)(msg->server, msg->req, &res);
        } else if (!DispatchHttpRpc(msg->server, msg->req, &res,
                                    s->remote_side())) {
            res.status = 404;
            res.set_content_type("text/plain");
            res.Append("404 not found: " + msg->req.path + "\n");
        }
    }
    if (close_conn) res.SetHeader("Connection", "close");
    // Progressive body (thttp/progressive_attachment.h): chunked header
    // block now; the handler's callback owns the writer from here and
    // streams until Close. Requires a chunked-capable peer.
    const bool can_chunk =
        msg->req.version_minor >= 1 && msg->req.method != "HEAD";
    if (res.start_progressive && !can_chunk) {
        // HTTP/1.0 or HEAD can't carry the stream — but the handler
        // already committed to one. Hand it an already-dead writer
        // (every Write returns -1) instead of silently sending an empty
        // 200 it never learns about; the plain response below still
        // answers the request.
        auto cb = std::move(res.start_progressive);
        res.start_progressive = nullptr;
        cb(std::make_shared<ProgressiveAttachment>(INVALID_VREF_ID));
    }
    if (res.start_progressive && can_chunk) {
        res.SetHeader("Transfer-Encoding", "chunked");
        res.headers.erase("Content-Length");
        res.body.clear();
        IOBuf out;
        SerializeHttpResponse(&res, &out);
        s->Write(&out);
        auto pa = std::make_shared<ProgressiveAttachment>(s->id());
        // The chunked body outlives this handler: count it as in-flight
        // work so Server::Join / GracefulStop drain waits for Close()
        // instead of truncating the stream mid-chunk (Stop fails the
        // connection, dropping queued chunks).
        if (msg->server != nullptr) {
            msg->server->BeginRequest();
            pa->set_on_close(
                [](void* arg) { ((Server*)arg)->EndRequest(); },
                msg->server);
        }
        // The headers just sent advertised Connection: close (draining
        // server or client request): the stream's Close() must actually
        // close, or a read-until-EOF client blocks on the open socket.
        if (close_conn) pa->set_close_connection_on_close();
        res.start_progressive(std::move(pa));
        return;  // without close_conn, keep-alive continues after the
                 // terminating chunk
    }
    // HEAD: headers (incl. the Content-Length the body WOULD have), no
    // body bytes (RFC 9110 §9.3.2 — sending them desyncs keep-alive).
    if (msg->req.method == "HEAD") {
        char cl[32];
        snprintf(cl, sizeof(cl), "%zu", res.body.size());
        res.SetHeader("Content-Length", cl);
        res.body.clear();
    }
    IOBuf out;
    SerializeHttpResponse(&res, &out);
    s->Write(&out);
    if (close_conn) {
        // Honor the advertised close ourselves: read-until-EOF clients
        // (HTTP/1.0, simple scripts) block forever otherwise. Wait for
        // the write queue to drain (bounded), then fail the socket —
        // which closes the fd.
        for (int i = 0; i < 200 && s->unwritten_bytes() > 0; ++i) {
            fiber_usleep(1000);
        }
        s->SetFailedWithError(TERR_EOF);
    }
}

int g_http_index = -1;

}  // namespace

void RegisterHttpProtocol() {
    if (g_http_index >= 0) return;
    Protocol p;
    p.parse = ParseHttp;
    p.process = ProcessHttp;
    p.name = "http";
    p.process_in_order = true;  // no correlation ids: FIFO responses
    g_http_index = RegisterProtocol(p);
}

int HttpProtocolIndex() { return g_http_index; }

}  // namespace tpurpc
