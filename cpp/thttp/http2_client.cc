#include "thttp/http2_client.h"

#include <arpa/inet.h>

#include <algorithm>
#include <cstring>
#include <map>
#include <mutex>
#include <vector>

#include "tbase/errno.h"
#include "tbase/logging.h"
#include "tbase/time.h"
#include "tfiber/butex.h"
#include "tfiber/fiber.h"
#include "thttp/h2_frames.h"
#include "thttp/hpack.h"
#include "tnet/input_messenger.h"
#include "tnet/protocol.h"
#include "trpc/controller.h"

namespace tpurpc {

using namespace h2;

namespace {

constexpr size_t kMaxRespBody = 64u << 20;
constexpr size_t kMaxHeaderBlock = 64u << 10;

int g_h2_client_index = -1;

// Per-connection client session, installed as the socket's conn_data
// BEFORE the first write, so response parsing can claim the bytes.
struct H2ClientSession {
    std::mutex mu;
    HpackDecoder decoder;           // response header blocks
    uint32_t next_stream_id = 1;    // odd, increasing (RFC 7540 §5.1.1)
    bool preface_sent = false;
    int64_t conn_send_window = kDefaultWindow;
    int64_t peer_initial_window = kDefaultWindow;
    void* window_butex = butex_create();

    struct RespStream {
        uint64_t cid;
        std::vector<HpackHeader> headers;   // response HEADERS
        std::vector<HpackHeader> trailers;  // trailing HEADERS
        IOBuf body;
        bool has_headers = false;
        int64_t send_window = kDefaultWindow;
    };
    std::map<uint32_t, RespStream> streams;

    uint32_t cont_stream = 0;  // CONTINUATION expected for this stream
    uint8_t cont_flags = 0;
    std::string header_block;

    ~H2ClientSession() { butex_destroy(window_butex); }

    void WakeWindowWaiters() {
        butex_word(window_butex)->fetch_add(1, std::memory_order_release);
        butex_wake_all(window_butex);
    }
};

void FailAllStreams(H2ClientSession* sess, int error);

// Runs at socket recycle (last ref dropped — no fiber can still touch
// the connection): pending calls learn their connection died here; until
// then their RPC timeouts cover the gap, like tpu_std responses on a
// dead socket.
void DeleteClientSession(void* s) {
    auto* sess = (H2ClientSession*)s;
    FailAllStreams(sess, TERR_FAILED_SOCKET);
    delete sess;
}

H2ClientSession* client_session_of(Socket* s) {
    // Only sockets we marked at send time carry a client session; the
    // preferred-protocol check makes the conn_data cast safe (a server
    // h2 socket stores an H2Session under a different protocol index).
    if (s->preferred_protocol_index != g_h2_client_index) return nullptr;
    return (H2ClientSession*)s->conn_data();
}

const std::string* FindHeader(const std::vector<HpackHeader>& hs,
                              const char* name) {
    for (const auto& h : hs) {
        if (h.name == name) return &h.value;
    }
    return nullptr;
}

// Fail every pending stream of the session (connection died / GOAWAY).
// Errors go through id_error, which QUEUES when the id is locked: this
// can run at socket recycle on the stack of whoever dropped the last
// ref — including the RPC's own IssueRPC, which HOLDS the id lock
// (blocking on it here deadlocked: IssueRPC -> Dereference -> OnRecycle
// -> DeleteClientSession -> this -> id_lock_range on the same id).
void FailAllStreams(H2ClientSession* sess, int error) {
    std::vector<uint64_t> cids;
    {
        std::lock_guard<std::mutex> g(sess->mu);
        for (auto& kv : sess->streams) cids.push_back(kv.second.cid);
        sess->streams.clear();
    }
    for (uint64_t cid : cids) {
        id_error(cid, error);
    }
}

// ---------------- response completion ----------------

// Map grpc-status (trailers) / :status to the RPC result and finish.
void CompleteStream(H2ClientSession::RespStream&& st) {
    const std::string* status = FindHeader(st.headers, ":status");
    // Trailers-only responses put grpc-status in the first (only) block.
    const std::string* grpc_status = FindHeader(st.trailers, "grpc-status");
    if (grpc_status == nullptr) {
        grpc_status = FindHeader(st.headers, "grpc-status");
    }
    const std::string* grpc_msg = FindHeader(st.trailers, "grpc-message");
    if (grpc_msg == nullptr) {
        grpc_msg = FindHeader(st.headers, "grpc-message");
    }
    if (status != nullptr && *status != "200") {
        CompleteClientUnaryResponse(st.cid, TERR_RESPONSE,
                                    "h2 :status " + *status, nullptr);
        return;
    }
    if (grpc_status != nullptr && *grpc_status != "0") {
        CompleteClientUnaryResponse(
            st.cid, TERR_RESPONSE,
            "grpc-status " + *grpc_status +
                (grpc_msg != nullptr ? ": " + *grpc_msg : std::string()),
            nullptr);
        return;
    }
    // gRPC unary body: 1-byte compressed flag + u32be length + pb.
    if (st.body.size() < 5) {
        CompleteClientUnaryResponse(st.cid, TERR_RESPONSE,
                                    "short grpc response body", nullptr);
        return;
    }
    char prefix[5];
    st.body.cutn(prefix, 5);
    if (prefix[0] != 0) {
        CompleteClientUnaryResponse(st.cid, TERR_RESPONSE,
                                    "compressed grpc response unsupported",
                                    nullptr);
        return;
    }
    uint32_t len;
    memcpy(&len, prefix + 1, 4);
    len = ntohl(len);
    if ((size_t)len != st.body.size()) {
        CompleteClientUnaryResponse(st.cid, TERR_RESPONSE,
                                    "grpc length prefix mismatch", nullptr);
        return;
    }
    CompleteClientUnaryResponse(st.cid, 0, "", &st.body);
}

void* CompleteStreamThunk(void* arg) {
    auto* st = (H2ClientSession::RespStream*)arg;
    CompleteStream(std::move(*st));
    delete st;
    return nullptr;
}

// Hand the completion to a background fiber — NEVER complete inline from
// the in-order input fiber. CompleteClientUnaryResponse blocks in
// id_lock_range; the lock may be held by this very stream's SENDER parked
// on h2 flow control (H2ClientSendUnary waits for WINDOW_UPDATEs that
// only this input fiber can deliver). Observed deadlock: early
// trailers-only response to a >64KB request — the response completes
// while the sender still holds the CallId lock waiting for window that
// never comes (the server already finished the stream). Same discipline
// as Socket::CloseFdAndDropQueued's id_error fiber hand-off.
void CompleteStreamInBackground(H2ClientSession::RespStream&& st) {
    auto* heap = new H2ClientSession::RespStream(std::move(st));
    fiber_t tid;
    if (fiber_start_background(&tid, nullptr, CompleteStreamThunk, heap) !=
        0) {
        // Out of fibers: inline is the lesser evil (the deadlock needs a
        // concurrently parked sender; a fiber-exhausted process has
        // bigger problems and the RPC deadline still bounds it).
        CompleteStream(std::move(*heap));
        delete heap;
    }
}

// ---------------- frame processing (input fiber, in order) ----------------

class H2ClientFrame : public InputMessageBase {
public:
    uint8_t type = 0;
    uint8_t flags = 0;
    uint32_t stream_id = 0;
    IOBuf payload;
};

void HandleHeaderBlockDone(Socket* s, H2ClientSession* sess,
                           uint32_t stream_id, uint8_t flags) {
    std::vector<HpackHeader> headers;
    if (!sess->decoder.Decode((const uint8_t*)sess->header_block.data(),
                              sess->header_block.size(), &headers)) {
        s->SetFailedWithError(TERR_RESPONSE);  // COMPRESSION_ERROR
        return;
    }
    sess->header_block.clear();
    if (stream_id == 0) return;
    const bool complete = (flags & kFlagEndStream) != 0;
    H2ClientSession::RespStream done;
    bool finish = false;
    {
        std::lock_guard<std::mutex> g(sess->mu);
        auto it = sess->streams.find(stream_id);
        if (it == sess->streams.end()) return;  // canceled/unknown
        H2ClientSession::RespStream& st = it->second;
        if (!st.has_headers) {
            st.headers = std::move(headers);
            st.has_headers = true;
        } else {
            st.trailers = std::move(headers);
        }
        if (complete) {
            done = std::move(st);
            sess->streams.erase(it);
            finish = true;
        }
    }
    if (finish) CompleteStreamInBackground(std::move(done));
}

void ProcessH2ClientFrame(InputMessageBase* raw) {
    std::unique_ptr<H2ClientFrame> msg((H2ClientFrame*)raw);
    SocketUniquePtr s = SocketUniquePtr::FromId(msg->socket_id);
    if (!s) return;
    H2ClientSession* sess = client_session_of(s.get());
    if (sess == nullptr) return;

    // CONTINUATION discipline (same as the server side).
    if (sess->cont_stream != 0 && (msg->type != H2_CONTINUATION ||
                                   msg->stream_id != sess->cont_stream)) {
        s->SetFailedWithError(TERR_RESPONSE);
        return;
    }

    switch (msg->type) {
        case H2_SETTINGS: {
            if (msg->flags & kFlagAck) break;
            const std::string p = msg->payload.to_string();
            for (size_t off = 0; off + 6 <= p.size(); off += 6) {
                uint16_t id;
                uint32_t value;
                memcpy(&id, p.data() + off, 2);
                memcpy(&value, p.data() + off + 2, 4);
                id = ntohs(id);
                value = ntohl(value);
                if (id == 0x4) {  // SETTINGS_INITIAL_WINDOW_SIZE
                    std::lock_guard<std::mutex> g(sess->mu);
                    const int64_t delta =
                        (int64_t)value - sess->peer_initial_window;
                    sess->peer_initial_window = value;
                    for (auto& kv : sess->streams) {
                        kv.second.send_window += delta;
                    }
                    sess->WakeWindowWaiters();
                }
            }
            IOBuf ack;
            ack.append(BuildFrame(H2_SETTINGS, kFlagAck, 0, ""));
            s->Write(&ack);
            break;
        }
        case H2_PING: {
            if (msg->flags & kFlagAck) break;
            IOBuf ack;
            ack.append(BuildFrame(H2_PING, kFlagAck, 0,
                                  msg->payload.to_string()));
            s->Write(&ack);
            break;
        }
        case H2_WINDOW_UPDATE: {
            if (msg->payload.size() != 4) break;
            uint32_t inc;
            msg->payload.copy_to(&inc, 4);
            inc = ntohl(inc) & 0x7fffffffu;
            std::lock_guard<std::mutex> g(sess->mu);
            if (msg->stream_id == 0) {
                sess->conn_send_window += inc;
            } else {
                auto it = sess->streams.find(msg->stream_id);
                if (it != sess->streams.end()) {
                    it->second.send_window += inc;
                }
            }
            sess->WakeWindowWaiters();
            break;
        }
        case H2_HEADERS: {
            IOBuf frag = std::move(msg->payload);
            if (msg->flags & kFlagPadded) {
                uint8_t pad;
                if (frag.size() < 1 || ((void)frag.cutn(&pad, 1),
                                        (size_t)pad > frag.size())) {
                    s->SetFailedWithError(TERR_RESPONSE);
                    return;
                }
                IOBuf tmp;
                frag.cutn(&tmp, frag.size() - pad);
                frag.swap(tmp);
            }
            if (msg->flags & kFlagPriority) {
                if (frag.size() < 5) {
                    s->SetFailedWithError(TERR_RESPONSE);
                    return;
                }
                IOBuf drop;
                frag.cutn(&drop, 5);
            }
            sess->header_block += frag.to_string();
            if (sess->header_block.size() > kMaxHeaderBlock) {
                s->SetFailedWithError(TERR_RESPONSE);
                return;
            }
            if (msg->flags & kFlagEndHeaders) {
                HandleHeaderBlockDone(s.get(), sess, msg->stream_id,
                                      msg->flags);
            } else {
                sess->cont_stream = msg->stream_id;
                sess->cont_flags = msg->flags;
            }
            break;
        }
        case H2_CONTINUATION: {
            if (sess->cont_stream == 0) {
                s->SetFailedWithError(TERR_RESPONSE);
                return;
            }
            sess->header_block += msg->payload.to_string();
            if (sess->header_block.size() > kMaxHeaderBlock) {
                s->SetFailedWithError(TERR_RESPONSE);
                return;
            }
            if (msg->flags & kFlagEndHeaders) {
                const uint8_t hf = sess->cont_flags;
                sess->cont_stream = 0;
                HandleHeaderBlockDone(s.get(), sess, msg->stream_id, hf);
            }
            break;
        }
        case H2_DATA: {
            const size_t sz = msg->payload.size();
            IOBuf frag = std::move(msg->payload);
            if (msg->flags & kFlagPadded) {
                uint8_t pad;
                if (frag.size() < 1 || ((void)frag.cutn(&pad, 1),
                                        (size_t)pad > frag.size())) {
                    s->SetFailedWithError(TERR_RESPONSE);
                    return;
                }
                IOBuf tmp;
                frag.cutn(&tmp, frag.size() - pad);
                frag.swap(tmp);
            }
            H2ClientSession::RespStream done;
            bool finish = false;
            bool known = false;
            {
                std::lock_guard<std::mutex> g(sess->mu);
                auto it = sess->streams.find(msg->stream_id);
                if (it != sess->streams.end()) {
                    known = true;
                    it->second.body.append(frag);
                    if (it->second.body.size() > kMaxRespBody) {
                        s->SetFailedWithError(TERR_RESPONSE);
                        return;
                    }
                    if (msg->flags & kFlagEndStream) {
                        done = std::move(it->second);
                        sess->streams.erase(it);
                        finish = true;
                    }
                }
            }
            // Replenish receive windows (conn always; stream while open).
            if (sz > 0) {
                uint32_t inc = htonl((uint32_t)sz);
                std::string p((const char*)&inc, 4);
                std::string out = BuildFrame(H2_WINDOW_UPDATE, 0, 0, p);
                if (known && !finish) {
                    out += BuildFrame(H2_WINDOW_UPDATE, 0, msg->stream_id,
                                      p);
                }
                IOBuf buf;
                buf.append(out);
                s->Write(&buf);
            }
            if (finish) CompleteStreamInBackground(std::move(done));
            break;
        }
        case H2_RST_STREAM: {
            uint64_t cid = 0;
            {
                std::lock_guard<std::mutex> g(sess->mu);
                auto it = sess->streams.find(msg->stream_id);
                if (it == sess->streams.end()) break;
                cid = it->second.cid;
                sess->streams.erase(it);
            }
            // REFUSED_STREAM (RFC 9113 §8.7) guarantees the server did
            // no processing: retriable on another connection without
            // spending retry budget (a draining server refuses streams
            // that raced its GOAWAY). Every other code means unknown
            // progress — plain TERR_RESPONSE, budget applies.
            uint32_t rst_code = 0;
            if (msg->payload.size() >= 4) {
                msg->payload.copy_to(&rst_code, 4);
                rst_code = ntohl(rst_code);
            }
            // id_error (queues under a held lock): the id may be locked
            // by its sender parked mid-send on flow control; blocking
            // this in-order input fiber on it would stall the whole
            // connection's frame processing.
            id_error(cid, rst_code == 0x7 ? TERR_DRAINING : TERR_RESPONSE);
            break;
        }
        case H2_GOAWAY: {
            // Planned drain, not death — but ONLY for NO_ERROR. An error
            // GOAWAY (ENHANCE_YOUR_CALM, PROTOCOL_ERROR, ...) is the
            // server rejecting us: treat it like connection death so the
            // retries it causes DO consume budget (a shedding server
            // must not receive a budget-free re-issue storm).
            uint32_t last_id = 0;
            uint32_t error_code = 0;
            if (msg->payload.size() >= 8) {
                uint32_t words[2];
                msg->payload.copy_to(words, 8);
                last_id = ntohl(words[0]) & 0x7fffffffu;
                error_code = ntohl(words[1]);
            } else if (msg->payload.size() >= 4) {
                msg->payload.copy_to(&last_id, 4);
                last_id = ntohl(last_id) & 0x7fffffffu;
            }
            if (error_code != 0) {
                FailAllStreams(sess, TERR_FAILED_SOCKET);
                s->SetFailedWithError(TERR_FAILED_SOCKET);
                break;
            }
            // NO_ERROR: the server promises to answer every stream at or
            // below last-stream-id — those stay pending and complete
            // normally. Streams above it were provably NOT processed:
            // fail them as TERR_DRAINING, which is retriable on another
            // connection WITHOUT consuming retry budget (re-issuing
            // cannot load a server that is leaving). The socket is
            // marked draining (not failed) so the channel re-creates its
            // pinned connection for new calls while the old one
            // finishes; the server's eventual close fails whatever is
            // left through DeleteClientSession.
            std::vector<uint64_t> unprocessed;
            {
                std::lock_guard<std::mutex> g(sess->mu);
                for (auto it = sess->streams.begin();
                     it != sess->streams.end();) {
                    if (it->first > last_id) {
                        unprocessed.push_back(it->second.cid);
                        it = sess->streams.erase(it);
                    } else {
                        ++it;
                    }
                }
            }
            s->SetDraining();
            // id_error queues under a held id lock (same discipline as
            // RST_STREAM above): never block this in-order input fiber.
            for (uint64_t cid : unprocessed) {
                id_error(cid, TERR_DRAINING);
            }
            break;
        }
        default:
            break;
    }
}

ParseResult ParseH2ClientFrames(IOBuf* source, Socket* socket,
                                bool read_eof, const void* arg) {
    if (client_session_of(socket) == nullptr) {
        return ParseResult::make(ParseError::TRY_OTHERS);
    }
    if (source->size() < kFrameHeaderLen) {
        return ParseResult::make(ParseError::NOT_ENOUGH_DATA);
    }
    char header[kFrameHeaderLen];
    source->copy_to(header, kFrameHeaderLen);
    const uint32_t len = ((uint32_t)(uint8_t)header[0] << 16) |
                         ((uint32_t)(uint8_t)header[1] << 8) |
                         (uint32_t)(uint8_t)header[2];
    if (len > kMaxFrameSize + 255) {
        return ParseResult::make(ParseError::ERROR);
    }
    if (source->size() < kFrameHeaderLen + len) {
        return ParseResult::make(ParseError::NOT_ENOUGH_DATA);
    }
    source->pop_front(kFrameHeaderLen);
    auto* msg = new H2ClientFrame;
    msg->type = (uint8_t)header[3];
    msg->flags = (uint8_t)header[4];
    uint32_t sid;
    memcpy(&sid, header + 5, 4);
    msg->stream_id = ntohl(sid) & 0x7fffffffu;
    source->cutn(&msg->payload, len);
    return ParseResult::make_ok(msg);
}

}  // namespace

void H2ClientCancel(SocketId sid, uint64_t cid) {
    SocketUniquePtr s;
    if (Socket::AddressSocket(sid, &s) != 0) return;
    H2ClientSession* sess = client_session_of(s.get());
    if (sess == nullptr) return;
    uint32_t stream_id = 0;
    {
        std::lock_guard<std::mutex> g(sess->mu);
        for (auto it = sess->streams.begin(); it != sess->streams.end();
             ++it) {
            if (it->second.cid == cid) {
                stream_id = it->first;
                sess->streams.erase(it);
                break;
            }
        }
    }
    if (stream_id == 0) return;  // already completed / never sent
    uint32_t code = htonl(0x8);  // CANCEL
    IOBuf rst;
    rst.append(BuildFrame(H2_RST_STREAM, 0, stream_id,
                          std::string((const char*)&code, 4)));
    s->Write(&rst);
}

// ---------------- send path ----------------

int H2ClientSendUnary(Socket* s, uint64_t cid, const std::string& grpc_path,
                      const std::string& authority, const IOBuf& request_pb,
                      int64_t deadline_us, const std::string& authorization,
                      const std::string& tenant, int priority,
                      const std::string& session) {
    if (g_h2_client_index < 0) return -1;
    H2ClientSession* sess = client_session_of(s);
    std::string out;
    if (sess == nullptr) {
        // First RPC on this connection: install the session + preface.
        // IssueRPC serializes per-socket via the CallId lock only for one
        // call; two fibers may race here, so install under a plain
        // compare: set_conn_data is not atomic — but both racers run on
        // the SAME channel's first calls, which the SocketMap serializes
        // through connect-on-first-write ordering. Guard anyway with a
        // session-level mutex via double-checked conn_data.
        static std::mutex install_mu;
        std::lock_guard<std::mutex> g(install_mu);
        sess = client_session_of(s);
        if (sess == nullptr) {
            sess = new H2ClientSession;
            s->set_conn_data(sess, DeleteClientSession);
            s->preferred_protocol_index = g_h2_client_index;
        }
    }
    // HEADERS: gRPC request pseudo-headers + metadata (built before the
    // lock; the block itself doesn't depend on the stream id).
    std::vector<std::pair<std::string, std::string>> headers = {
        {":method", "POST"},
        {":scheme", "http"},
        {":path", grpc_path},
        {":authority", authority.empty() ? "tpurpc" : authority},
        {"content-type", "application/grpc"},
        {"te", "trailers"},
    };
    if (!authorization.empty()) {
        headers.emplace_back("authorization", authorization);
    }
    // QoS identity (ISSUE 8): the h2 spelling of the tpu_std meta's
    // tenant/priority pair.
    if (!tenant.empty()) {
        headers.emplace_back("x-tpu-tenant", tenant);
    }
    if (priority >= 0) {
        headers.emplace_back("x-tpu-priority", std::to_string(priority));
    }
    // Sticky-session identity (ISSUE 16).
    if (!session.empty()) {
        headers.emplace_back("x-tpu-session", session);
    }
    if (deadline_us > 0) {
        const int64_t remain_us = deadline_us - monotonic_time_us();
        if (remain_us > 0) {
            // Floor at 1ms while budget remains (see the tpu_std stamp
            // in IssueRPC: 0 means "already expired"). The gRPC spec
            // caps the value at 8 digits — upscale the unit for huge
            // deadlines (truncation only SHRINKS the budget: safe).
            const int64_t remain_ms =
                remain_us < 1000 ? 1 : remain_us / 1000;
            std::string gt;
            if (remain_ms <= 99999999) {
                gt = std::to_string(remain_ms) + "m";
            } else if (remain_ms / 1000 <= 99999999) {
                gt = std::to_string(remain_ms / 1000) + "S";
            } else if (remain_ms / 60000 <= 99999999) {
                gt = std::to_string(remain_ms / 60000) + "M";
            } else {
                gt = std::to_string(std::min<int64_t>(
                         99999999, remain_ms / 3600000)) +
                     "H";
            }
            headers.emplace_back("grpc-timeout", gt);
        } else {
            // Budget already spent: say so explicitly ("1n" parses to 0)
            // so the server sheds instead of executing for nobody.
            headers.emplace_back("grpc-timeout", "1n");
        }
    }

    uint32_t stream_id;
    {
        // Allocate the stream id AND queue preface+HEADERS under ONE mu
        // hold: ids must hit the wire in increasing order (RFC 7540
        // §5.1.1 — a reordered HEADERS is a connection error) and the
        // preface must precede everything. Socket::Write never blocks,
        // so holding mu across it is safe; DATA goes out separately
        // below (inter-stream DATA interleaving is legal).
        std::lock_guard<std::mutex> g(sess->mu);
        if (!sess->preface_sent) {
            out.append(kPreface, kPrefaceLen);
            out += BuildFrame(H2_SETTINGS, 0, 0, "");
            sess->preface_sent = true;
        }
        stream_id = sess->next_stream_id;
        sess->next_stream_id += 2;
        auto& st = sess->streams[stream_id];
        st.cid = cid;
        st.send_window = sess->peer_initial_window;
        AppendHeadersFrames(&out, kFlagEndHeaders, stream_id,
                            EncodeHeaderBlock(headers));
        IOBuf hb;
        hb.append(out);
        out.clear();
        if (s->Write(&hb, cid) != 0) {
            sess->streams.erase(stream_id);
            return -1;
        }
    }

    // Cleanup for send-side failures below: drop our stream entry and
    // RST it so the server releases its half-open state too.
    auto abort_stream = [&]() {
        {
            std::lock_guard<std::mutex> g(sess->mu);
            sess->streams.erase(stream_id);
        }
        uint32_t code = htonl(0x8);  // CANCEL
        IOBuf rst;
        rst.append(BuildFrame(H2_RST_STREAM, 0, stream_id,
                              std::string((const char*)&code, 4)));
        s->Write(&rst);
    };

    // DATA: 5-byte gRPC prefix + pb, chunked to the frame cap. Unary
    // requests are bounded by the peer's default 64KB window in practice;
    // larger bodies park on WINDOW_UPDATE below.
    std::string body;
    body.push_back('\0');
    const uint32_t len = htonl((uint32_t)request_pb.size());
    body.append((const char*)&len, 4);
    body += request_pb.to_string();

    size_t sent = 0;
    const int64_t stall_deadline =
        deadline_us > 0 ? deadline_us
                        : monotonic_time_us() + 60 * 1000 * 1000;
    while (sent < body.size()) {
        // Snapshot before the window check (lost-wakeup guard — see the
        // server's WriteResponse loop).
        std::atomic<int>* word = butex_word(sess->window_butex);
        const int expected = word->load(std::memory_order_acquire);
        size_t n = 0;
        {
            std::lock_guard<std::mutex> g(sess->mu);
            auto it = sess->streams.find(stream_id);
            if (it == sess->streams.end()) return -1;  // already failed
            const int64_t avail = std::min<int64_t>(
                sess->conn_send_window, it->second.send_window);
            n = (size_t)std::max<int64_t>(
                0, std::min<int64_t>(
                       avail, (int64_t)std::min<size_t>(
                                  kMaxFrameSize, body.size() - sent)));
            if (n > 0) {
                sess->conn_send_window -= (int64_t)n;
                it->second.send_window -= (int64_t)n;
            }
        }
        if (n == 0) {
            if (!out.empty()) {
                IOBuf buf;
                buf.append(out);
                out.clear();
                if (s->Write(&buf) != 0) {
                    abort_stream();
                    return -1;
                }
            }
            if (s->Failed() || monotonic_time_us() >= stall_deadline) {
                abort_stream();
                return -1;
            }
            const int64_t abst = monotonic_time_us() + 1000 * 1000;
            butex_wait(sess->window_butex, expected, &abst);
            continue;
        }
        const bool last = sent + n >= body.size();
        AppendFrame(&out, H2_DATA, last ? kFlagEndStream : 0, stream_id,
                    body.data() + sent, n);
        sent += n;
    }
    IOBuf buf;
    buf.append(out);
    if (s->Write(&buf, cid) != 0) {
        abort_stream();
        return -1;
    }
    return 0;
}

void RegisterHttp2ClientProtocol() {
    if (g_h2_client_index >= 0) return;
    Protocol p;
    p.parse = ParseH2ClientFrames;
    p.process = ProcessH2ClientFrame;
    p.name = "h2c-client";
    p.process_in_order = true;  // shared HPACK decoder + session state
    g_h2_client_index = RegisterProtocol(p);
}

int Http2ClientProtocolIndex() { return g_h2_client_index; }

}  // namespace tpurpc
