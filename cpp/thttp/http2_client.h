// Client half of HTTP/2: outbound h2c sessions + the gRPC unary client.
//
// The framework can CALL gRPC servers (grpcio et al.), not just serve
// them: Channel{options.protocol="grpc"} routes Controller::IssueRPC
// here, which multiplexes unary calls as h2 streams over the channel's
// connection. Reference parity: the client half of
// /root/reference/src/brpc/policy/http2_rpc_protocol.cpp
// (PackH2Request/H2UnsentRequest, stream id allocation, SETTINGS/flow
// control) + grpc.{h,cpp} status mapping.
#pragma once

#include <cstdint>
#include <string>

#include "tbase/iobuf.h"
#include "tnet/socket.h"

namespace tpurpc {

// Send one gRPC unary request on `s` as a new h2 stream (client preface
// + SETTINGS on first use of the connection). The response completes the
// RPC via CompleteClientUnaryResponse(cid, ...). `grpc_path` is
// "/package.Service/Method". QoS identity rides as x-tpu-tenant /
// x-tpu-priority headers; the sticky-session id as x-tpu-session
// (empty/negative = omitted). Returns 0 on success (frames queued).
int H2ClientSendUnary(Socket* s, uint64_t cid, const std::string& grpc_path,
                      const std::string& authority, const IOBuf& request_pb,
                      int64_t deadline_us,
                      const std::string& authorization = "",
                      const std::string& tenant = "", int priority = -1,
                      const std::string& session = "");

// Cancel the in-flight unary call `cid` on the h2 client session of
// `sid`: RST_STREAM(CANCEL) the matching stream and drop its response
// state. No-op when the call already completed or the socket is gone.
void H2ClientCancel(SocketId sid, uint64_t cid);

// Registered at GlobalInitializeOrDie: parses/processes server->client h2
// frames on sockets carrying an h2 client session.
void RegisterHttp2ClientProtocol();
int Http2ClientProtocolIndex();

}  // namespace tpurpc
