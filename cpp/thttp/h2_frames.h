// HTTP/2 frame constants + builders shared by the server protocol
// (http2_protocol.cc) and the client session (http2_client.cc).
// RFC 7540 §4/§6; reference: the framing half of
// /root/reference/src/brpc/policy/http2_rpc_protocol.cpp and http2.h.
#pragma once

#include <cstddef>
#include <cstdint>
#include <string>
#include <utility>
#include <vector>

namespace tpurpc {
namespace h2 {

constexpr char kPreface[] = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
constexpr size_t kPrefaceLen = 24;
constexpr size_t kFrameHeaderLen = 9;

enum FrameType : uint8_t {
    H2_DATA = 0x0,
    H2_HEADERS = 0x1,
    H2_PRIORITY = 0x2,
    H2_RST_STREAM = 0x3,
    H2_SETTINGS = 0x4,
    H2_PUSH_PROMISE = 0x5,
    H2_PING = 0x6,
    H2_GOAWAY = 0x7,
    H2_WINDOW_UPDATE = 0x8,
    H2_CONTINUATION = 0x9,
};

constexpr uint8_t kFlagEndStream = 0x1;
constexpr uint8_t kFlagEndHeaders = 0x4;
constexpr uint8_t kFlagPadded = 0x8;
constexpr uint8_t kFlagPriority = 0x20;
constexpr uint8_t kFlagAck = 0x1;

constexpr int64_t kDefaultWindow = 65535;
constexpr uint32_t kMaxFrameSize = 16384;

// Append one frame (header + payload) onto *out.
void AppendFrame(std::string* out, uint8_t type, uint8_t flags,
                 uint32_t stream, const char* payload, size_t len);

std::string BuildFrame(uint8_t type, uint8_t flags, uint32_t stream,
                       const std::string& payload);

// HEADERS split into CONTINUATION frames when the block exceeds the max
// frame size (an oversize frame is a connection error).
void AppendHeadersFrames(std::string* out, uint8_t flags, uint32_t stream,
                         const std::string& block);

// HPACK-encode a header list (literal-without-indexing; both sides).
std::string EncodeHeaderBlock(
    const std::vector<std::pair<std::string, std::string>>& headers);

}  // namespace h2
}  // namespace tpurpc
