// ProgressiveAttachment: stream an HTTP/1.1 response body in chunks
// AFTER the handler returned — server push, SSE, long downloads.
//
// Reference parity: src/brpc/progressive_attachment.{h,cpp} (+
// docs/en/server_push.md): the handler detaches a progressive writer
// from the response; the framework sends the header block with
// Transfer-Encoding: chunked, and every Write() becomes one chunk on
// the wire (the socket's ordered write queue keeps framing intact under
// concurrent writers). Close() sends the terminating chunk; the
// connection then continues keep-alive as usual.
//
// Usage (inside an HTTP handler):
//   res->start_progressive = [](ProgressiveAttachmentPtr pa) {
//       fiber... { pa->Write("chunk"); ...; pa->Close(); }
//   };
#pragma once

#include <atomic>
#include <memory>
#include <string>

#include "tbase/iobuf.h"
#include "tnet/socket.h"

namespace tpurpc {

class ProgressiveAttachment {
public:
    explicit ProgressiveAttachment(SocketId sid) : sid_(sid) {}
    ~ProgressiveAttachment() { Close(); }

    // Send one chunk now. Returns 0, or -1 (connection dead / closed).
    int Write(const IOBuf& data);
    int Write(const std::string& data) {
        IOBuf buf;
        buf.append(data);
        return Write(buf);
    }

    // Terminating 0-chunk; idempotent. The connection stays keep-alive.
    void Close();

    SocketId socket_id() const { return sid_; }

private:
    SocketId sid_;
    std::atomic<bool> closed_{false};
};

using ProgressiveAttachmentPtr = std::shared_ptr<ProgressiveAttachment>;

}  // namespace tpurpc
