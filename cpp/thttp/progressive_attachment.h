// ProgressiveAttachment: stream an HTTP/1.1 response body in chunks
// AFTER the handler returned — server push, SSE, long downloads.
//
// Reference parity: src/brpc/progressive_attachment.{h,cpp} (+
// docs/en/server_push.md): the handler detaches a progressive writer
// from the response; the framework sends the header block with
// Transfer-Encoding: chunked, and every Write() becomes one chunk on
// the wire (the socket's ordered write queue keeps framing intact under
// concurrent writers). Close() sends the terminating chunk; the
// connection then continues keep-alive as usual.
//
// Usage (inside an HTTP handler):
//   res->start_progressive = [](ProgressiveAttachmentPtr pa) {
//       fiber... { pa->Write("chunk"); ...; pa->Close(); }
//   };
#pragma once

#include <atomic>
#include <memory>
#include <string>

#include "tbase/iobuf.h"
#include "tnet/socket.h"

namespace tpurpc {

class ProgressiveAttachment {
public:
    explicit ProgressiveAttachment(SocketId sid) : sid_(sid) {}
    ~ProgressiveAttachment() { Close(); }

    // Send one chunk now. Returns 0, or -1 (connection dead / closed).
    int Write(const IOBuf& data);
    int Write(const std::string& data) {
        IOBuf buf;
        buf.append(data);
        return Write(buf);
    }

    // Terminating 0-chunk; idempotent. The connection stays keep-alive
    // unless set_close_connection_on_close was requested.
    void Close();

    // The response that started this stream advertised Connection:
    // close (e.g. the server is draining): after the terminating chunk
    // is flushed, Close() fails the socket so read-until-EOF clients
    // see the promised EOF instead of blocking on a keep-alive that
    // will never speak again. Set before the handler's callback runs.
    void set_close_connection_on_close() { close_conn_ = true; }

    SocketId socket_id() const { return sid_; }

    // Lifecycle accounting hook, fired exactly once from the closing
    // Close() (the destructor closes too). The HTTP layer registers
    // Server::EndRequest here so a chunked body still streaming AFTER
    // its handler returned counts against Server::Join / GracefulStop
    // draining — without it, a graceful restart would truncate the
    // stream mid-chunk. Set before the handler's callback runs.
    void set_on_close(void (*cb)(void*), void* arg) {
        on_close_ = cb;
        on_close_arg_ = arg;
    }

private:
    SocketId sid_;
    std::atomic<bool> closed_{false};
    bool close_conn_ = false;
    void (*on_close_)(void*) = nullptr;
    void* on_close_arg_ = nullptr;
};

using ProgressiveAttachmentPtr = std::shared_ptr<ProgressiveAttachment>;

}  // namespace tpurpc
